"""Mesh context + the collective (ICI) data plane for the exec-layer shuffle.

This is the framework integration of the UCX-mode shuffle (SURVEY.md §2.7:
shuffle-plugin/ UCXShuffleTransport.scala, RapidsShuffleInternalManagerBase.
scala:238): when a jax.sharding.Mesh is configured, `TpuShuffleExchangeExec`
routes its exchange through ONE jitted `shard_map` program whose
`lax.all_to_all` moves every column's rows between shards over the
interconnect — XLA schedules the ICI transfers that the reference hand-codes
as UCX transactions. The exchange is collective: all map inputs are sharded
row-wise over the mesh, re-bucketed by murmur3(key) % n_shards on-device
(hash partitioning) or funneled to shard 0 (single partitioning — the
partial→final aggregation / global-limit merge funnel), and each shard
receives exactly its reduce partition.

Static-shape strategy (XLA cannot size buffers data-dependently):
  1. partition ids are computed per shard-group batch with the normal
     expression path (shuffle/partitioner.py);
  2. ONE audited host sync reads the per-(shard, dest) counts and picks a
     bucketed slot capacity — the analogue of the reference sizing
     contiguousSplit slices before handing them to the transport. The SAME
     counts are the exchange's device-side partition statistics: exact
     per-reduce row/byte sizes are known at exchange time, so AQE planning
     (`partition_sizes`) never re-fetches blocks, and the received batches
     compact under HOST-KNOWN counts (zero per-partition count syncs);
  3. the jitted exchange scatters rows into [n_shards, slot_cap] send
     buffers and `all_to_all`s them; receive-validity rides along.
Compiled programs are cached by (mesh, capacity, slot_cap, column dtypes) so
steady-state queries reuse one executable. Every launch lands in the
process-wide dispatch accounting as kind "mesh_collective"
(`opjit.record_external_dispatch`) and — when the query tracer is armed —
inside a `mesh.exchange` span carrying the per-chip send-row breakdown and
the stage/launch/wait timing split (docs/observability.md).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar.batch import TpuColumnarBatch, _compact_plan, _repad, gather
from ..columnar.vector import (TpuColumnVector, audited_device_get,
                               bucket_capacity, row_mask)
from ..config import MESH_ENABLED, MESH_SIZE, SHUFFLE_MODE
from ..obs import tracer as obs

_AXIS = "data"


class MeshContext:
    """Process-wide mesh handle (the TPU analogue of the executor's device
    topology discovered via the shuffle heartbeat, Plugin.scala:436-447)."""

    _lock = threading.Lock()
    _meshes: Dict[int, Mesh] = {}

    @classmethod
    def get(cls, conf, n: Optional[int] = None) -> Optional[Mesh]:
        """Mesh of exactly `n` devices (default: the configured/maximum
        size); None when disabled or the topology is too small."""
        if not conf.get(MESH_ENABLED):
            return None
        limit = conf.get(MESH_SIZE)
        devs = jax.devices()
        avail = min(limit, len(devs)) if limit else len(devs)
        n = n if n is not None else avail
        if n > avail or n < 2:
            return None
        with cls._lock:
            if n not in cls._meshes:
                cls._meshes[n] = Mesh(np.array(devs[:n]), (_AXIS,))
            return cls._meshes[n]

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._meshes = {}


def mesh_session_active(conf) -> Optional[Mesh]:
    """The mesh this session's PLANNER should target, or None. A mesh
    session is active when the mesh is enabled, the shuffle mode is ICI
    (the collective commits device-resident blocks to the ICI catalog) and
    the topology offers >= 2 devices — the condition under which
    plan/overrides.py selects the collective exchange and aligns hash
    partition counts to the mesh."""
    if str(conf.get(SHUFFLE_MODE)).upper() != "ICI":
        return None
    return MeshContext.get(conf)


def collective_payload(output, conf) -> Optional[str]:
    """Payload classification for the collective data plane (shared by the
    planner's exchange selection and the runtime eligibility check):

    * ``"fixed"`` — every column has a fixed-width device layout; the
      all_to_all carries the raw buffers;
    * ``"dict"`` — the variable-width columns are all strings/binary
      (offsets+bytes device layout): they ride as int32 dictionary codes
      plus one broadcast dictionary per exchange
      (``spark.rapids.tpu.exchange.dictionaryEncode.enabled``), the TPU
      analogue of the reference's compressed shuffle batches;
    * ``None`` — nested or host-only payloads: per-map path.
    """
    from ..columnar.vector import device_layout_ok
    from ..config import EXCHANGE_DICT_ENCODE_ENABLED
    from ..types import BinaryType, StringType, is_fixed_width
    has_var = False
    for a in output:
        if is_fixed_width(a.dtype) and device_layout_ok(a.dtype):
            continue
        if isinstance(a.dtype, (StringType, BinaryType)):
            has_var = True
            continue
        return None
    if not has_var:
        return "fixed"
    return "dict" if conf.get(EXCHANGE_DICT_ENCODE_ENABLED) else None


# compiled exchange cache: (mesh, cap, slot_cap, col sig) -> jitted fn.
# Guarded: collective exchanges can materialize from concurrent query
# threads (TL010 — same discipline as the opjit executable cache).
_CACHE_LOCK = threading.Lock()
_EXCHANGE_CACHE: Dict[Tuple, "jax.stages.Wrapped"] = {}

# collective-launch statistics (bench MULTICHIP stage + the O(exchanges)
# assertion read these next to opjit calls_by_kind["mesh_collective"]).
_STATS_LOCK = threading.Lock()
_STATS = {"launches": 0, "rows_sent": 0, "stage_ns": 0, "launch_ns": 0,
          "wait_ns": 0, "compact_ns": 0,
          # dictionary-encoded string exchanges (the MULTICHIP summary's
          # multichip_string_collectives / dict_encode_ms keys)
          "dict_exchanges": 0, "dict_encode_ns": 0}


def collective_stats() -> Dict[str, int]:
    with _STATS_LOCK:
        return dict(_STATS)


def reset_collective_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


def record_dict_encode(ns: int) -> None:
    """One exchange's map-side dictionary-encode pass completed (every
    value is host-known: a perf_counter wall — zero device syncs)."""
    with _STATS_LOCK:
        _STATS["dict_exchanges"] += 1
        _STATS["dict_encode_ns"] += ns


def _record_launch(rows: int, stage_ns: int, launch_ns: int,
                   wait_ns: int, compact_ns: int) -> None:
    with _STATS_LOCK:
        _STATS["launches"] += 1
        _STATS["rows_sent"] += rows
        _STATS["stage_ns"] += stage_ns
        _STATS["launch_ns"] += launch_ns
        _STATS["wait_ns"] += wait_ns
        _STATS["compact_ns"] += compact_ns
    # always-on registry (docs/observability.md): the collective's blocking
    # wait is the fabric's user-visible latency — histogram it per launch
    # (rare: one per exchange) so a serving dashboard sees the tail;
    # the running totals above fold into metrics_snapshot() as-is
    from ..obs import metrics as _metrics
    _metrics.histogram_observe("mesh.collective_wait_ms", wait_ns / 1e6)


class MeshExchangeResult(NamedTuple):
    """One collective exchange's outputs + its device-side statistics."""
    batches: List[TpuColumnarBatch]  # one compacted batch per reduce part
    rows: List[int]                  # exact received rows per reduce part
    bytes: List[int]                 # device bytes per reduce part
    profile: Optional[Dict] = None   # obs/mesh_profile.py record


def _build_exchange(mesh: Mesh, n_dev: int, slot_cap: int,
                    sig: Tuple[Tuple[str, bool], ...]):
    """One jitted shard_map program moving `len(sig)` columns + validity via
    all_to_all. `sig` is ((dtype_str, has_validity), ...)."""
    key = (mesh, n_dev, slot_cap, sig)
    with _CACHE_LOCK:
        fn = _EXCHANGE_CACHE.get(key)
    if fn is not None:
        return fn

    n_cols = len(sig)

    def exchange(dest, *flat):
        # per-shard local views: dest [cap], columns/validities [cap]
        cap = dest.shape[0]
        order = jnp.argsort(dest, stable=True)
        sorted_dest = jnp.take(dest, order)
        idx = jnp.arange(cap, dtype=jnp.int32)
        one = jnp.ones((cap,), jnp.int32)
        run_start = jnp.zeros((n_dev + 2,), jnp.int32).at[
            sorted_dest + 1].add(one, mode="drop")
        starts = jnp.cumsum(run_start)[:-1]
        pos_in_bucket = idx - jnp.take(starts, sorted_dest)
        live = sorted_dest < n_dev
        keep = live & (pos_in_bucket < slot_cap)
        send_slot = jnp.where(keep, sorted_dest * slot_cap + pos_in_bucket,
                              n_dev * slot_cap)

        def a2a(x):
            x = x.reshape(n_dev, slot_cap)
            return jax.lax.all_to_all(x, _AXIS, split_axis=0, concat_axis=0,
                                      tiled=False).reshape(-1)

        def scatter_send(x, fill, dt):
            buf = jnp.full((n_dev * slot_cap,), fill, dt).at[send_slot].set(
                jnp.take(x, order), mode="drop")
            return a2a(buf)

        rowok = a2a(jnp.zeros((n_dev * slot_cap,), jnp.bool_).at[
            send_slot].set(keep, mode="drop"))
        outs = [rowok]
        datas = flat[:n_cols]
        valids = flat[n_cols:]
        for (dt, has_v), d, v in zip(sig, datas, valids):
            outs.append(scatter_send(d, 0, d.dtype))
            if has_v:
                outs.append(scatter_send(v, False, jnp.bool_))
        return tuple(outs)

    from .distributed import shard_map
    spec = P(_AXIS)
    n_valid = sum(1 for _, has_v in sig if has_v)
    in_specs = tuple([spec] * (1 + 2 * n_cols))
    out_specs = tuple([spec] * (1 + n_cols + n_valid))
    fn = jax.jit(shard_map(exchange, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False))
    with _CACHE_LOCK:
        _EXCHANGE_CACHE[key] = fn
    return fn


def _fixed_row_bytes(ref: TpuColumnarBatch, has_valid: List[bool]) -> int:
    """Device bytes per row of a fixed-width batch (carrier itemsize +
    1 byte per validity lane) — the row→byte scale for the device-side
    partition statistics."""
    total = 0
    for i, c in enumerate(ref.columns):
        total += int(np.dtype(c.data.dtype).itemsize)
        if has_valid[i]:
            total += 1
    return total


def mesh_hash_exchange(mesh: Mesh,
                       group_batches: List[Optional[TpuColumnarBatch]],
                       pids_list: List[Optional[jnp.ndarray]],
                       names: Sequence[str],
                       shuffle_id: int = -1,
                       partitioning: str = "hash") -> MeshExchangeResult:
    """Collective hash exchange: `group_batches[d]` is the (possibly empty)
    concatenated map input assigned to shard d, `pids_list[d]` its
    destination-partition ids. Returns one compacted device batch per reduce
    partition (= per shard) plus the exact per-reduce row/byte counts
    derived from the sizing counts (the device-side statistics AQE plans
    against — no block fetch, no extra sync) and the exchange's
    efficiency profile (obs/mesh_profile.py: phase walls + per-chip skew,
    all from host values this function already holds)."""
    from ..chaos import inject
    from ..execs import opjit
    from ..obs import mesh_profile as mprof
    from ..serving.query_context import checkpoint as _cancel_checkpoint
    # collective-launch cancellation boundary: last stop before the
    # staging sync + fabric program — a cancelled/timed-out query never
    # launches the collective (docs/robustness.md "Query lifecycle")
    _cancel_checkpoint(f"mesh.collective s{shuffle_id}")
    n_dev = mesh.devices.size
    assert len(group_batches) == n_dev
    t_stage0 = time.perf_counter_ns()
    ref = next(b for b in group_batches if b is not None)
    dtypes = [c.dtype for c in ref.columns]
    cap = bucket_capacity(max([b.capacity for b in group_batches
                               if b is not None] + [1]))

    # per-(shard, dest) counts -> slot capacity AND the exchange's partition
    # statistics (ONE audited host sync for all shards' pid arrays; a
    # per-shard np.asarray loop would pay one round trip each on
    # high-latency links)
    live = [(d, b, p) for d, (b, p) in enumerate(zip(group_batches,
                                                     pids_list))
            if b is not None and b.num_rows]
    fetched = audited_device_get([p for _d, _b, p in live], "mesh_counts") \
        if live else []
    max_count = 1
    recv_rows = np.zeros(n_dev, np.int64)
    send_rows = np.zeros(n_dev, np.int64)
    for (shard, b, _p), pids_np in zip(live, fetched):
        counts = np.bincount(np.asarray(pids_np)[: b.num_rows],
                             minlength=n_dev)
        max_count = max(max_count, int(counts.max()))
        recv_rows += counts
        send_rows[shard] += int(counts.sum())
    slot_cap = bucket_capacity(max_count)

    # stack per-shard arrays into globally sharded [n_dev * cap] inputs
    sharding = NamedSharding(mesh, P(_AXIS))
    sig = []
    col_data: List[List[jnp.ndarray]] = []
    col_valid: List[List[jnp.ndarray]] = []
    has_valid = [any(b is not None and b.columns[i].validity is not None
                     for b in group_batches)
                 for i in range(len(dtypes))]
    for i, dt in enumerate(dtypes):
        carrier = ref.columns[i].data.dtype
        sig.append((str(carrier), has_valid[i]))
        datas, valids = [], []
        for b in group_batches:
            if b is None:
                datas.append(jnp.zeros((cap,), carrier))
                valids.append(jnp.zeros((cap,), jnp.bool_))
            else:
                c = _repad(b.columns[i], cap)
                datas.append(c.data)
                valids.append(c.validity if c.validity is not None
                              else row_mask(b.num_rows, cap))
        col_data.append(datas)
        col_valid.append(valids)
    dests = []
    for b, pids in zip(group_batches, pids_list):
        if b is None or not b.num_rows:
            dests.append(jnp.full((cap,), n_dev, jnp.int32))
        else:
            p = jnp.asarray(pids)[:cap].astype(jnp.int32)
            if p.shape[0] < cap:
                p = jnp.concatenate(
                    [p, jnp.full((cap - p.shape[0],), n_dev, jnp.int32)])
            dests.append(jnp.where(row_mask(b.num_rows, cap), p, n_dev))

    def shard(arrs):
        return jax.device_put(jnp.concatenate(arrs), sharding)

    dest_g = shard(dests)
    flat = [shard(col_data[i]) for i in range(len(dtypes))] + \
           [shard(col_valid[i]) for i in range(len(dtypes))]
    fn = _build_exchange(mesh, n_dev, slot_cap, tuple(sig))
    t_launch0 = time.perf_counter_ns()
    # pre-allocated profile seq: the span args and the consumer read's
    # flow events reference the profile before it is recorded
    seq = mprof.alloc_seq()
    # the span covers launch → wait → compact (staging_ms rides as an arg:
    # the per-chip send counts it reports only exist after the sizing
    # sync). The watchdog arms around ONLY the fabric window — inject +
    # launch + wait — and disarms before the host-side compact: chaos
    # `mesh.link` (a slow or flapping ICI link) injects inside it, so a
    # stalled transfer trips the watchdog exactly like a hung chip would,
    # while a long (pure-CPU) compact never raises a false "hung chip".
    # Latency sleeps here; a transient error propagates to the caller's
    # with_device_retry, which re-runs the whole (idempotent) staging.
    with obs.span(f"mesh.exchange s{shuffle_id}",
                  cat="shuffle.collective", shuffle=shuffle_id,
                  n_dev=n_dev, slot_cap=slot_cap, exchange_seq=seq,
                  staging_ms=round((t_launch0 - t_stage0) / 1e6, 3),
                  per_chip_rows=[int(x) for x in send_rows]):
        with mprof.collective_watchdog(shuffle_id, n_dev) as wd:
            inject("mesh.link", detail=f"s{shuffle_id}")
            outs = fn(dest_g, *flat)
            t_wait0 = time.perf_counter_ns()
            # the collective is the stage boundary: waiting for it here is
            # the exchange's one blocking device sync (no data moves to
            # host — the ledger records the wait so per-query sync
            # accounting stays exact)
            from ..profiling import record_sync
            record_sync("collective_wait")
            jax.block_until_ready(outs)
            t_end = time.perf_counter_ns()
        opjit.record_external_dispatch("mesh_collective")
        rowok = outs[0]
        pos = 1
        recv_data: List[jnp.ndarray] = []
        recv_valid: List[Optional[jnp.ndarray]] = []
        for i in range(len(dtypes)):
            recv_data.append(outs[pos])
            pos += 1
            if has_valid[i]:
                recv_valid.append(outs[pos])
                pos += 1
            else:
                recv_valid.append(None)

        # slice per shard, compact out the slot gaps. The kept-row count
        # per shard is KNOWN host-side from the sizing counts (slot_cap >=
        # the largest bucket, so nothing was dropped): compact under the
        # known count instead of paying one scalar sync per reduce
        # partition.
        local = n_dev * slot_cap
        row_bytes = _fixed_row_bytes(ref, has_valid)
        results: List[TpuColumnarBatch] = []
        sizes: List[int] = []
        for r in range(n_dev):
            sl = slice(r * local, (r + 1) * local)
            ok = rowok[sl]
            cols = []
            for i, dt in enumerate(dtypes):
                v = recv_valid[i][sl] if recv_valid[i] is not None else None
                cols.append(TpuColumnVector(dt, recv_data[i][sl], v, local))
            batch = TpuColumnarBatch(cols, local, list(names))
            idx, _n_dev_count = _compact_plan(jnp.asarray(ok),
                                              batch.rows_arg)
            results.append(gather(batch, idx, int(recv_rows[r]),
                                  out_capacity=local))
            sizes.append(int(recv_rows[r]) * row_bytes)
        t_compact_end = time.perf_counter_ns()
        profile = mprof.record_exchange(
            seq, shuffle_id, partitioning, n_dev,
            send_rows=[int(x) for x in send_rows],
            recv_rows=[int(x) for x in recv_rows], recv_bytes=sizes,
            stage_ns=t_launch0 - t_stage0, launch_ns=t_wait0 - t_launch0,
            wait_ns=t_end - t_wait0, compact_ns=t_compact_end - t_end,
            watchdog_fired=wd.fired)
        if profile is not None:
            # the full attribution record as an instant event: the Chrome
            # export derives the per-device tracks + producer→consumer
            # flows from it (all values already host-side)
            obs.event("mesh.profile", cat="mesh", exchange_seq=seq,
                      shuffle=shuffle_id, n_dev=n_dev,
                      phases_ms=dict(profile["phases_ms"]),
                      recv_rows=list(profile["recv_rows"]),
                      skew=dict(profile["skew"]))
    _record_launch(int(send_rows.sum()), t_launch0 - t_stage0,
                   t_wait0 - t_launch0, t_end - t_wait0,
                   t_compact_end - t_end)
    return MeshExchangeResult(results, [int(x) for x in recv_rows], sizes,
                              profile)


def mesh_single_exchange(mesh: Mesh,
                         group_batches: List[Optional[TpuColumnarBatch]],
                         names: Sequence[str],
                         shuffle_id: int = -1) -> MeshExchangeResult:
    """Collective SINGLE-partition funnel: every shard's rows move to shard
    0 in one all_to_all — the fabric path for partial→final aggregation and
    global limit/top-N merges (the reduce-scatter analogue: per-shard
    partial states were already reduced locally by the partial stage; the
    collective carries only the states). Returns mesh-size results where
    only reduce partition 0 is non-empty.

    Cost note: this reuses the hash-exchange program with all-zero
    destinations, so each shard still ships a full [n_dev, slot_cap] send
    buffer — slot groups 1..n-1 are padding the receivers discard,
    ~n_dev× the payload in fabric traffic. Acceptable for the state-merge
    funnels this serves (payloads are per-shard partial STATES, already
    reduced); a ragged gather / all_gather layout is the follow-up if a
    row-heavy single exchange ever rides it (ROADMAP item 2)."""
    pids = [None if b is None
            else jnp.zeros((b.capacity,), jnp.int32)
            for b in group_batches]
    return mesh_hash_exchange(mesh, group_batches, pids, names,
                              shuffle_id=shuffle_id, partitioning="single")
