"""Multi-process executors: worker processes own shards of map tasks and
shuffle through the file block store; the driver monitors liveness via
heartbeats and re-runs lost work.

Reference analogues:
  - executor processes + shuffle files: RapidsShuffleInternalManagerBase.scala
    (MULTITHREADED writer :238 / reader :569 run inside separate executor
    JVMs; here each executor is a spawned Python process)
  - heartbeat/lost-peer detection: RapidsShuffleHeartbeatManager.scala (driver
    tracks executor liveness; a dead peer invalidates its blocks)
  - FetchFailed -> re-materialization: Spark's lineage recovery; the reduce
    side raises FetchFailedError for a missing block and the driver re-runs
    the producing map task on a surviving worker.

Workers execute REAL physical-plan partitions (the plan pickles: host-side
exec trees hold Arrow data / file paths, never device arrays), hash-partition
the rows with a process-stable hash, and write blocks under a shared
directory. The TPU chip belongs to the driver process; workers run the host
(CPU) plan path — matching the reference topology where map-side executors
do host shuffle IO while device work stays on the owning executor's device.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import queue as pyqueue
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

HB_INTERVAL_S = 0.25
HB_TIMEOUT_S = 3.0


def _atomic_write(path: str, data: bytes) -> None:
    """Write-then-rename so a killed worker never leaves a partial block
    (the reduce side either sees a complete block or FetchFailed)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


class FetchFailedError(RuntimeError):
    """A reduce task could not read a map output block (lost worker)."""

    def __init__(self, shuffle_id: int, map_id: int, reduce_id: int):
        super().__init__(
            f"fetch failed: shuffle={shuffle_id} map={map_id} "
            f"reduce={reduce_id}")
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.reduce_id = reduce_id


_INV31 = np.uint32(pow(31, -1, 1 << 32))  # 31 is odd => invertible mod 2^32


def _string_hash_u32(arr) -> np.ndarray:
    """Vectorized per-row polynomial hash over the Arrow string buffers:
    h(row) = sum(byte_i * 31^i) mod 2^32, computed for all rows at once with
    global position weights 31^gpos and a modular-inverse shift (divide by
    31^row_start) — no per-row Python loop. Only determinism matters here
    (bucket assignment), not hash quality."""
    import pyarrow as pa

    from ..columnar.vector import rebase_string_offsets
    arr = arr.cast(pa.string())
    if arr.null_count:
        arr = arr.fill_null("")
    # zero-based offsets + exactly the addressed bytes (the shared
    # offsets-rebase the device decode staging uses too); copy=False —
    # the buffers are only read within this call
    offsets, chars = rebase_string_offsets(arr.buffers(), len(arr),
                                           arr.offset, copy=False)
    if not len(chars):
        return np.zeros(len(arr), np.uint32)
    b = chars.astype(np.uint32)
    with np.errstate(over="ignore"):
        pow31 = np.empty(len(b), np.uint32)
        pow31[0] = 1
        np.cumprod(np.full(len(b) - 1, 31, np.uint32), out=pow31[1:])
        weighted = b * pow31
        csum = np.concatenate([[np.uint32(0)],
                               np.cumsum(weighted, dtype=np.uint32)])
        starts = offsets.astype(np.int64)
        seg = csum[starts[1:]] - csum[starts[:-1]]
        # shift each row's weights back to 31^0: multiply by inv31^row_start
        # (rows starting at data_end are empty; the clipped index is unused
        # because their seg is already 0)
        invpow = np.empty(len(b), np.uint32)
        invpow[0] = 1
        np.cumprod(np.full(len(b) - 1, _INV31, np.uint32), out=invpow[1:])
        inv = invpow[starts[:-1].clip(0, len(invpow) - 1)]
        return (seg * inv).astype(np.uint32)


def _stable_bucket(table, key_ordinals: Sequence[int],
                   num_reduces: int) -> np.ndarray:
    """Process-stable row bucket assignment (numpy for fixed-width, crc32 for
    strings — python's builtin hash is salted per process and must not be
    used here)."""
    n = table.num_rows
    h = np.full(n, 0x9E3779B9, np.uint32)
    for o in key_ordinals:
        col = table.column(o)
        arr = col.combine_chunks() if hasattr(col, "combine_chunks") else col
        import pyarrow as pa
        if pa.types.is_string(arr.type) or pa.types.is_large_string(arr.type):
            vals = _string_hash_u32(arr)
        elif pa.types.is_floating(arr.type):
            f = np.asarray(arr.fill_null(0.0).to_numpy(
                zero_copy_only=False), np.float64)
            f = np.where(f == 0.0, 0.0, f)  # -0.0 == 0.0
            f = np.where(np.isnan(f), np.float64("nan"), f)  # one NaN bits
            vals = f.view(np.uint64).astype(np.uint32) \
                ^ (f.view(np.uint64) >> np.uint64(32)).astype(np.uint32)
        else:
            # pyarrow has no direct date32/time32→int64 cast; hop through
            # int32 (timestamp/date64/time64 cast to int64 directly below)
            if pa.types.is_date32(arr.type) or pa.types.is_time32(arr.type):
                arr = arr.cast(pa.int32())
            iv = np.asarray(arr.cast(pa.int64()).fill_null(0).to_numpy(
                zero_copy_only=False), np.int64)
            u = iv.view(np.uint64)
            vals = u.astype(np.uint32) ^ (u >> np.uint64(32)).astype(
                np.uint32)
        h = (h ^ vals) * np.uint32(0x85EBCA6B)
        h ^= h >> np.uint32(13)
    return (h % np.uint32(num_reduces)).astype(np.int64)


def _block_path(root: str, shuffle_id: int, map_id: int,
                reduce_id: int) -> str:
    return os.path.join(root, f"s{shuffle_id}",
                        f"m{map_id}_r{reduce_id}.blk")


def _run_map_task(payload: dict) -> dict:
    """Executes one map task inside a worker: run the plan partition,
    hash-partition rows, write one block file per reduce."""
    import pyarrow as pa

    from ..execs.base import TaskContext
    from ..shuffle.serializer import get_codec, serialize_table

    plan = pickle.loads(payload["plan"])
    map_id = payload["map_id"]
    tables = list(plan.execute_partition(map_id, TaskContext(map_id)))
    table = (pa.concat_tables(tables) if tables
             else pa.schema([]).empty_table())
    num_reduces = payload["num_reduces"]
    buckets = (_stable_bucket(table, payload["key_ordinals"], num_reduces)
               if table.num_rows else np.zeros(0, np.int64))
    codec = get_codec(payload["codec"])
    sizes = []
    os.makedirs(os.path.join(payload["root"], f"s{payload['shuffle_id']}"),
                exist_ok=True)
    for rid in range(num_reduces):
        part = table.filter(buckets == rid) if table.num_rows else table
        blob = serialize_table(part, codec,
                               checksum=payload.get("checksum", True))
        _atomic_write(
            _block_path(payload["root"], payload["shuffle_id"], map_id, rid),
            blob)
        sizes.append(len(blob))
    return {"map_id": map_id, "sizes": sizes}


_TASK_FNS = {"map": _run_map_task}


def _worker_main(worker_id: int, task_q, result_q) -> None:
    """Worker process entry: heartbeat thread + task loop. Workers run the
    host plan path on CPU — the accelerator belongs to the driver process
    (v1; per-worker device ownership is the multi-host mode's job)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    stop = threading.Event()

    def beat():
        while not stop.is_set():
            try:
                result_q.put(("hb", worker_id, time.time()))
            except Exception:  # noqa: BLE001 — queue torn down at shutdown
                return
            stop.wait(HB_INTERVAL_S)

    threading.Thread(target=beat, daemon=True).start()
    try:
        while True:
            item = task_q.get()
            if item is None:
                return
            kind, task_id, payload = item
            try:
                out = _TASK_FNS[kind](payload)
                result_q.put(("done", worker_id, task_id, out))
            except Exception as e:  # noqa: BLE001 — report, don't die
                result_q.put(("error", worker_id, task_id, repr(e)))
    finally:
        stop.set()


class ExecutorPool:
    """N spawned worker processes + a shared-file shuffle root.

    The driver submits map tasks, tracks which worker holds which unfinished
    task, and treats a worker as lost when its process dies OR its heartbeat
    goes stale — lost workers' unfinished tasks are reassigned to survivors
    (reference: RapidsShuffleHeartbeatManager + Spark task rescheduling)."""

    def __init__(self, num_workers: int = 2, shuffle_root: Optional[str] = None,
                 codec: str = "zstd", hb_timeout_s: Optional[float] = None,
                 checksum: bool = True):
        if hb_timeout_s is None:
            from ..config import (EXECUTOR_HEARTBEAT_TIMEOUT_SECONDS,
                                  default_conf)
            hb_timeout_s = default_conf().get(
                EXECUTOR_HEARTBEAT_TIMEOUT_SECONDS)
        self.hb_timeout_s = float(hb_timeout_s)
        self._ctx = mp.get_context("spawn")
        self.shuffle_root = shuffle_root or tempfile.mkdtemp(
            prefix="tpu_mp_shuffle_")
        self.codec = codec
        self.checksum = bool(checksum)
        # one result queue PER worker: SIGKILLing a worker mid-put can
        # corrupt a shared queue's pipe for every producer; per-worker
        # queues confine the damage to the dead worker
        self._result_qs: Dict[int, object] = {}
        self._task_qs: Dict[int, object] = {}
        self._procs: Dict[int, object] = {}
        self._last_hb: Dict[int, float] = {}
        self._assigned: Dict[int, Dict[int, tuple]] = {}  # wid -> {tid: task}
        self._next_shuffle = 0
        self._next_task = 0
        for wid in range(num_workers):
            self._spawn(wid)

    def _spawn(self, wid: int) -> None:
        q = self._ctx.Queue()
        rq = self._ctx.Queue()
        self._result_qs[wid] = rq
        p = self._ctx.Process(target=_worker_main,
                              args=(wid, q, rq), daemon=True)
        p.start()
        self._task_qs[wid] = q
        self._procs[wid] = p
        # no heartbeat yet: startup (interpreter + jax import) can exceed the
        # heartbeat timeout, so liveness falls back to is_alive() until the
        # first beat arrives
        self._last_hb[wid] = None
        self._assigned[wid] = {}

    # -- liveness ----------------------------------------------------------
    def _alive(self, wid: int) -> bool:
        p = self._procs.get(wid)
        if p is None or not p.is_alive():
            return False
        hb = self._last_hb[wid]
        return hb is None or (time.time() - hb) < self.hb_timeout_s

    def live_workers(self) -> List[int]:
        return [w for w in self._procs if self._alive(w)]

    def kill_worker(self, wid: int) -> None:
        """Test hook: hard-kill one worker (SIGKILL)."""
        self._procs[wid].kill()

    def heal(self) -> None:
        """Replace dead workers with fresh processes (Spark's executor
        replacement: the cluster manager restarts lost executors)."""
        for wid in list(self._procs):
            if not self._procs[wid].is_alive():
                self._procs[wid].join(timeout=1)
                lost = list(self._assigned[wid].values())
                new_wid = max(self._procs) + 1
                del self._procs[wid], self._task_qs[wid]
                del self._last_hb[wid], self._assigned[wid]
                del self._result_qs[wid]
                self._spawn(new_wid)
                for task in lost:  # in-flight work moves to the replacement
                    self._dispatch(task)

    # -- task scheduling ---------------------------------------------------
    def _dispatch(self, task: tuple, exclude=()) -> int:
        live = [w for w in self.live_workers() if w not in exclude]
        if not live:
            raise RuntimeError("no live workers")
        wid = min(live, key=lambda w: len(self._assigned[w]))
        kind, tid, payload = task
        self._assigned[wid][tid] = task
        self._task_qs[wid].put(task)
        return wid

    def _drain_results(self, timeout: float):
        """Poll every live worker's result queue; heartbeats update liveness
        in passing, the first task result found is returned."""
        deadline = time.time() + timeout
        while True:
            for wid in list(self._result_qs):
                if not self._procs[wid].is_alive() \
                        and self._result_qs[wid].empty():
                    continue
                try:
                    while True:
                        msg = self._result_qs[wid].get_nowait()
                        if msg[0] == "hb":
                            self._last_hb[msg[1]] = msg[2]
                        else:
                            return msg
                except (pyqueue.Empty, OSError, EOFError):
                    continue
            if time.time() >= deadline:
                return None
            time.sleep(0.01)

    def run_map_stage(self, shuffle_id: int, plan_blob: bytes,
                      map_ids: Sequence[int], key_ordinals: Sequence[int],
                      num_reduces: int, deadline_s: float = 120.0) -> None:
        """Run map tasks across workers, reassigning work from lost workers
        until every map output is written (or deadline)."""
        pending: Dict[int, tuple] = {}
        for mid in map_ids:
            tid = self._next_task
            self._next_task += 1
            task = ("map", tid, {
                "plan": plan_blob, "map_id": mid,
                "key_ordinals": list(key_ordinals),
                "num_reduces": num_reduces, "root": self.shuffle_root,
                "shuffle_id": shuffle_id, "codec": self.codec,
                "checksum": self.checksum,
            })
            pending[tid] = task
            self._dispatch(task)
        deadline = time.time() + deadline_s
        while pending:
            if time.time() > deadline:
                raise TimeoutError(f"map stage timed out; pending={pending}")
            msg = self._drain_results(timeout=0.1)
            if msg is not None:
                kind, wid, tid, out = msg
                self._assigned.get(wid, {}).pop(tid, None)
                if kind == "done":
                    pending.pop(tid, None)
                elif kind == "error":
                    raise RuntimeError(f"map task failed on worker {wid}: "
                                       f"{out}")
            # reassign work held by dead workers
            for wid in list(self._procs):
                if not self._alive(wid) and self._assigned[wid]:
                    lost = list(self._assigned[wid].values())
                    self._assigned[wid] = {}
                    for task in lost:
                        if task[1] in pending:
                            self._dispatch(task, exclude=(wid,))

    # -- reduce side -------------------------------------------------------
    def read_reduce(self, shuffle_id: int, reduce_id: int,
                    map_ids: Sequence[int]):
        """Read one reduce partition's blocks; a missing block raises
        FetchFailedError naming the lost map (lineage recovery trigger)."""
        from ..shuffle.serializer import deserialize_table
        out = []
        for mid in map_ids:
            path = _block_path(self.shuffle_root, shuffle_id, mid, reduce_id)
            if not os.path.exists(path):
                raise FetchFailedError(shuffle_id, mid, reduce_id)
            with open(path, "rb") as f:
                out.append(deserialize_table(f.read()))
        return out

    def shuffled_collect(self, plan, key_ordinals: Sequence[int],
                         num_reduces: int):
        """Full shuffle round: map stage in workers (with loss recovery),
        reduce reads in the driver (FetchFailed -> re-run the lost map)."""
        import pyarrow as pa
        sid = self._next_shuffle
        self._next_shuffle += 1
        blob = pickle.dumps(plan)
        map_ids = list(range(plan.num_partitions()))
        self.run_map_stage(sid, blob, map_ids, key_ordinals, num_reduces)
        results = []
        max_heals = len(map_ids) + 1
        for rid in range(num_reduces):
            tables = None
            for _attempt in range(max_heals):
                try:
                    tables = self.read_reduce(sid, rid, map_ids)
                    break
                except FetchFailedError as e:
                    # re-materialize the lost map output then retry the
                    # read; each attempt can surface a DIFFERENT lost map,
                    # so allow one heal per map before giving up
                    self.run_map_stage(sid, blob, [e.map_id], key_ordinals,
                                       num_reduces)
            if tables is None:
                raise RuntimeError(f"reduce {rid} unrecoverable")
            results.append(pa.concat_tables(
                [t for t in tables if t.num_rows]
                or [tables[0]]))
        return results

    def shutdown(self) -> None:
        for wid, q in self._task_qs.items():
            try:
                q.put(None)
            except Exception:  # noqa: BLE001
                pass
        for p in self._procs.values():
            p.join(timeout=2)
            if p.is_alive():
                p.kill()
