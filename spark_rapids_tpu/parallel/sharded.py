"""Plan-driven sharded multi-chip query execution over the mesh data plane.

This module is the driver side of ROADMAP item 2 ("make the ICI mesh the
production data plane"): given any session query, it runs the SAME plan two
ways —

  * **mesh**: a mesh session (`spark.rapids.tpu.mesh.enabled`, ICI shuffle
    mode) where the planner aligns hash exchanges to the mesh, eligible
    exchanges materialize as ONE fabric collective each
    (`parallel/mesh.py`), AQE consumes the exchange-time device-side size
    counters, and the session's root pull drives all partitions through the
    grouped multi-partition dispatch;
  * **single-device baseline**: the identical plan with the mesh disabled
    (per-map device-resident ICI path on the default device) — the
    bit-identity oracle and the 1-chip denominator for scaling efficiency.

and returns per-query statistics: wall times, per-chip rows/s, the
collective launch count against the plan's exchange count (the
O(exchanges) assertion — launches must NOT scale with partitions), the
staging/launch/wait/compact phase breakdown of collective time
(`parallel.mesh.collective_stats` + the per-exchange profiles and skew
tables from `obs/mesh_profile.py`), the per-map "why not collective"
reasons, and the named-phase `efficiency_attribution` of the profiled
mesh wall (docs/distributed.md "Diagnosing poor scaling").

Unlike the hand-written q1 step this replaces (`distributed.py`, kept for
the kernel-level dryrun), nothing here is query-specific: the planner —
not this runner — decides which exchanges ride the fabric, so any
session query (TPC-H, TPC-DS, ad-hoc DataFrames) shards the same way.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .mesh import collective_stats


def mesh_settings(n_devices: int, extra: Optional[Dict[str, str]] = None
                  ) -> Dict[str, str]:
    """Session settings for a mesh data-plane session of `n_devices`
    chips. Compiled whole-stage shortcuts are disabled so every stage
    boundary is a REAL exchange (the thing this data plane accelerates);
    the partition batch matches the mesh so whole-stage segments launch
    once per group."""
    s = {
        "spark.rapids.shuffle.mode": "ICI",
        "spark.rapids.tpu.mesh.enabled": "true",
        "spark.rapids.tpu.mesh.size": str(n_devices),
        "spark.sql.shuffle.partitions": str(n_devices),
        "spark.rapids.tpu.dispatch.partitionBatch": str(n_devices),
        "spark.sql.autoBroadcastJoinThreshold": "0",
        "spark.rapids.tpu.agg.compiledStage.enabled": "false",
        "spark.rapids.tpu.join.compiledStage.enabled": "false",
    }
    s.update(extra or {})
    return s


def baseline_settings(n_devices: int,
                      extra: Optional[Dict[str, str]] = None
                      ) -> Dict[str, str]:
    """The single-device baseline: identical plan shape (same partition
    count, same device-resident ICI shuffle, same disabled shortcuts) with
    the mesh off — per-map materialization on the default device."""
    s = mesh_settings(n_devices, extra)
    s["spark.rapids.tpu.mesh.enabled"] = "false"
    return s


def compare_tables(a, b) -> Tuple[bool, float]:
    """(bit_identical, max_abs_err) between two Arrow tables after a
    canonical whole-row sort. Identity is EXACT (float bit patterns, null
    masks); max_abs_err reports the largest float divergence when not."""
    import pyarrow as pa
    if a.num_rows != b.num_rows or a.column_names != b.column_names:
        return False, float("inf")
    if a.num_rows:
        keys = [(n, "ascending") for n in a.column_names]
        a = a.sort_by(keys)
        b = b.sort_by(keys)
    worst = 0.0
    same = True
    for name in a.column_names:
        ca = a.column(name).combine_chunks()
        cb = b.column(name).combine_chunks()
        # host Arrow values throughout (the query already collected):
        # .to_numpy on the pyarrow arrays, never np.asarray on anything the
        # taint walk could grade device (TL011 covers parallel/)
        na = ca.is_null().to_numpy(zero_copy_only=False)
        nb = cb.is_null().to_numpy(zero_copy_only=False)
        if not np.array_equal(na, nb):
            return False, float("inf")
        if pa.types.is_floating(ca.type):
            va = ca.to_numpy(zero_copy_only=False)
            vb = cb.to_numpy(zero_copy_only=False)
            va = np.where(na, 0.0, va)
            vb = np.where(nb, 0.0, vb)
            if not np.array_equal(va, vb, equal_nan=True):
                same = False
                both = np.isfinite(va) & np.isfinite(vb)
                if both.any():
                    worst = max(worst,
                                float(np.abs(va[both] - vb[both]).max()))
                else:
                    worst = float("inf")
        else:
            if ca.drop_null().to_pylist() != cb.drop_null().to_pylist():
                return False, float("inf")
    return same, worst


def _count_exchanges(session) -> int:
    """Exchange nodes in the last executed plan (the session snapshots the
    tree for every query — works untraced)."""
    tree = getattr(session, "_last_plan_tree", None) or []
    return sum(1 for n in tree if "ShuffleExchange" in str(n.get("name", "")))


def _dispatch_kind(kind: str) -> int:
    from ..execs import opjit
    return opjit.cache_stats()["calls_by_kind"].get(kind, 0)


def run_mesh_query(name: str, build: Callable, *, n_devices: int,
                   iters: int = 2,
                   extra_conf: Optional[Dict[str, str]] = None) -> Dict:
    """Run `build(session) -> DataFrame` on the mesh data plane and on the
    single-device baseline; return the comparison record (see module
    docstring). `build` is called once per session — its DataFrame is
    collected `iters` times on each (first collect warms the executable
    caches; the best of the rest is the wall time)."""
    from ..session import TpuSession

    def timed_run(settings, measure: bool) -> Tuple[object, float, Dict]:
        from ..obs import mesh_profile
        s = TpuSession(dict(settings))
        q = build(s)
        out = q.to_arrow()  # warm: traces/compiles every program
        best = float("inf")
        for _ in range(max(1, iters)):
            t0 = time.perf_counter()
            out = q.to_arrow()
            best = min(best, time.perf_counter() - t0)
        if not measure:
            # the baseline contributes only results + wall time — skip the
            # counter-bracketed extra collect (a whole wasted execution)
            return out, best, {}
        # one more collect bracketed by the collective counters: exchanges
        # re-materialize per collect, so this measures launches PER QUERY.
        # The SAME collect's wall anchors the phase attribution (the phase
        # walls and the wall must come from one execution or the
        # percentages lie).
        before_launches = collective_stats()
        before_kind = _dispatch_kind("mesh_collective")
        seq0 = mesh_profile.current_seq()
        t0 = time.perf_counter()
        out = q.to_arrow()
        wall_profiled = time.perf_counter() - t0
        stats = collective_stats()
        delta = {k: stats[k] - before_launches[k] for k in stats}
        delta["dispatch_kind"] = _dispatch_kind("mesh_collective") \
            - before_kind
        profiles = mesh_profile.profiles_since(seq0)
        reasons: Dict[str, int] = {}
        for f in mesh_profile.fallbacks_since(seq0):
            reasons[f["reason"]] = reasons.get(f["reason"], 0) + 1
        return out, best, {"collective": delta,
                           "exchanges": _count_exchanges(s),
                           "wall_profiled_s": wall_profiled,
                           "profiles": profiles,
                           "per_map_reasons": reasons}

    out_mesh, wall_mesh, info = timed_run(
        mesh_settings(n_devices, extra_conf), measure=True)
    out_one, wall_one, _ = timed_run(
        baseline_settings(n_devices, extra_conf), measure=False)
    identical, max_err = compare_tables(out_mesh, out_one)
    col = info["collective"]
    launches = col["launches"]
    # O(exchanges): each exchange materializes at most ONE collective per
    # query — never one per partition. The dispatch-accounting kind must
    # agree with the mesh module's own launch counter.
    launches_ok = (launches <= info["exchanges"]
                   and launches == col["dispatch_kind"])
    # worst-skew exchange of the profiled collect (the per-exchange skew
    # tables ride the full record; this is the one-line summary)
    profiles = info.get("profiles") or []
    worst = max(profiles, key=lambda p: p["skew"]["imbalance"],
                default=None)
    return {
        "query": name,
        "rows_out": out_mesh.num_rows,
        "n_devices": n_devices,
        "wall_ms_mesh": round(wall_mesh * 1e3, 1),
        "wall_ms_single": round(wall_one * 1e3, 1),
        "wall_ms_profiled": round(info["wall_profiled_s"] * 1e3, 1),
        "scaling_vs_single": round(wall_one / wall_mesh, 3)
        if wall_mesh > 0 else None,
        "bit_identical": identical,
        "max_abs_err": max_err,
        "exchanges": info["exchanges"],
        "collective_launches": launches,
        "collective_launches_O_exchanges": launches_ok,
        # dictionary-encoded string exchanges (codes + one broadcast
        # dictionary over the fabric) and their map-side encode wall
        "string_collectives": col.get("dict_exchanges", 0),
        "dict_encode_ms": round(col.get("dict_encode_ns", 0) / 1e6, 2),
        "collective_rows": col["rows_sent"],
        # r07 fused dataplane keys: compact fused into the collective
        # dispatch on EVERY profiled exchange, staged pad pieces served
        # from the staging pool, and segments launched by the overlapped
        # path (0 = the correctness-first unsegmented default)
        "compact_fused": all(p.get("compact_fused") for p in profiles)
        if profiles else True,
        "staging_reuse_hits": col.get("staging_reuse_hits", 0),
        "overlap_segments": col.get("overlap_segments", 0),
        "collective_stage_ms": round(col["stage_ns"] / 1e6, 2),
        "collective_launch_ms": round(col["launch_ns"] / 1e6, 2),
        "collective_wait_ms": round(col["wait_ns"] / 1e6, 2),
        "collective_compact_ms": round(col["compact_ns"] / 1e6, 2),
        "exchange_profiles": profiles,
        "per_map_reasons": info.get("per_map_reasons") or {},
        "skew_worst": None if worst is None else {
            "exchange": worst["exchange"], **worst["skew"]},
        "watchdog_fired": any(p.get("watchdog_fired") for p in profiles),
    }


def attribute_efficiency(record: Dict) -> Dict[str, float]:
    """Named-phase attribution of one query's PROFILED mesh wall
    (staging / launch / collective-wait / compact from the collective
    counters, compute = the residual outside the exchange path) as
    percentages — the `efficiency_attribution` the MULTICHIP compact line
    carries so each round explains its own efficiency number. The phase
    walls and the wall come from the SAME collect (run_mesh_query's
    bracketed execution), so the split is exact."""
    wall_ms = record.get("wall_ms_profiled") or record.get("wall_ms_mesh")
    if not wall_ms:
        return {}
    phases = {
        "staging": record.get("collective_stage_ms", 0.0),
        "launch": record.get("collective_launch_ms", 0.0),
        "collective_wait": record.get("collective_wait_ms", 0.0),
        "compact": record.get("collective_compact_ms", 0.0),
    }
    out = {k: round(100.0 * v / wall_ms, 1) for k, v in phases.items()}
    named = sum(out.values())
    out["compute"] = round(max(0.0, 100.0 - named), 1)
    # NOT clamped to 100: a value above 100 means the summed phase walls
    # exceeded the wall they were measured against (a phase/wall mismatch
    # bug) — clamping would mask exactly the overcount this key exists to
    # surface
    out["attributed_pct"] = round(named + out["compute"], 1)
    return out


def summarize(records: List[Dict], n_devices: int,
              input_rows: Dict[str, int]) -> Dict:
    """The MULTICHIP stage's compact summary (ONE parseable line — the
    r05 lesson: the driver keeps only the stdout tail). Per-chip rows/s is
    the mesh run's input-row throughput divided by the chip count; scaling
    efficiency is speedup-over-1-chip / n_chips. The single collective_ms
    scalar of r06 is replaced by the per-phase walls + skew summary +
    efficiency_attribution (obs/mesh_profile.py); the full per-exchange
    profiles ride the detail records."""
    per_query = {}
    total_launches = 0
    total_collective_ms = 0.0
    total_string_collectives = 0
    total_dict_encode_ms = 0.0
    all_identical = True
    all_o_exchanges = True
    for r in records:
        rows = input_rows.get(r["query"], 0)
        mesh_s = r["wall_ms_mesh"] / 1e3
        phases = {
            "staging": round(r["collective_stage_ms"], 1),
            "launch": round(r["collective_launch_ms"], 1),
            "collective_wait": round(r["collective_wait_ms"], 1),
            "compact": round(r.get("collective_compact_ms", 0.0), 1),
        }
        # compact-line discipline (the r05 lesson: the driver keeps ~2000
        # chars of stdout): no key whose value is derivable from another —
        # rows/bit_identical/wall_ms_single ride the detail records, the
        # worst-skew summary keeps only the verdict fields
        sk = r.get("skew_worst")
        ea = attribute_efficiency(r)
        ea = {k: v for k, v in ea.items()
              if v or k in ("compute", "attributed_pct")}
        per_query[r["query"]] = {
            "per_chip_rows_per_s": round(rows / mesh_s / n_devices, 1)
            if mesh_s > 0 else None,
            "wall_ms": r["wall_ms_mesh"],
            "scaling_efficiency": round(
                (r["scaling_vs_single"] or 0) / n_devices, 3),
            "exchanges": r["exchanges"],
            "collective_launches": r["collective_launches"],
            "string_collectives": r.get("string_collectives", 0),
            "dict_encode_ms": r.get("dict_encode_ms", 0.0),
            # r07 fused dataplane keys (ISSUE 16): compact_fused is the
            # headline invariant (never elided — a False here means a
            # regression back to host compact); the counters elide at zero
            "compact_fused": bool(r.get("compact_fused", False)),
            "staging_reuse_hits": r.get("staging_reuse_hits", 0),
            "overlap_segments": r.get("overlap_segments", 0),
            "phases_ms": phases,
            "efficiency_attribution": ea,
            "skew": None if sk is None else {
                "exchange": sk["exchange"],
                "imbalance": sk["imbalance"],
                "straggler_chip": sk["straggler_chip"]},
            "per_map_exchanges": r.get("per_map_reasons") or {},
        }
        if not per_query[r["query"]]["string_collectives"]:
            # compact-line discipline: zero-valued dictionary keys elide
            del per_query[r["query"]]["string_collectives"]
            del per_query[r["query"]]["dict_encode_ms"]
        for zk in ("staging_reuse_hits", "overlap_segments"):
            if not per_query[r["query"]][zk]:
                del per_query[r["query"]][zk]
        total_launches += r["collective_launches"]
        total_collective_ms += sum(phases.values())
        total_string_collectives += r.get("string_collectives", 0)
        total_dict_encode_ms += r.get("dict_encode_ms", 0.0)
        all_identical = all_identical and r["bit_identical"]
        all_o_exchanges = all_o_exchanges \
            and r["collective_launches_O_exchanges"]
    return {
        "metric": "multichip_sharded_execution",
        "n_devices": n_devices,
        "queries": per_query,
        "collective_launches_total": total_launches,
        # string exchanges riding the fabric as dictionary codes + one
        # broadcast dictionary each (the r06 burndown: q1's agg exchange
        # and q18's c_name final agg were per_map=string_or_nested_payload)
        "string_collectives_total": total_string_collectives,
        "dict_encode_ms_total": round(total_dict_encode_ms, 2),
        # RENAMED from r06's collective_ms_total: the total now includes
        # the compact phase, and bench_diff gates collective totals
        # lower-is-better — reusing the old key with a wider composition
        # would read as a spurious 4–5× regression against r06
        "collective_phases_ms_total": round(total_collective_ms, 2),
        "bit_identical_all": all_identical,
        # the fused-compact invariant over the whole round: False means
        # some exchange fell back to a host-side compact (the r06 wall)
        "compact_fused_all": all(bool(r.get("compact_fused", False))
                                 for r in records),
        "collective_launches_O_exchanges": all_o_exchanges,
        "watchdog_fired_any": any(r.get("watchdog_fired")
                                  for r in records),
    }
