"""Distributed execution over a jax.sharding.Mesh — kernel-level layer.

The TPU re-design of the reference's distributed layer (SURVEY.md §2.7):
  * Spark executor data-parallelism       → mesh "data" axis, row-sharded batches
  * partial→shuffle→final aggregation     → per-shard partial agg + psum (tree
    aggregate over ICI — cheaper than materializing a shuffle for aggregates)
  * hash-partition exchange (UCX mode)    → murmur3 bucketing + lax.all_to_all
    over ICI ("ICI shuffle mode", config spark.rapids.shuffle.mode=ICI)
The reference's parallelism inventory (SURVEY.md §2.7 note) maps exactly: no
tensor/pipeline/expert axes exist in a SQL engine; the mesh is 1-D data-parallel
with collectives carrying exchange traffic.

This module holds the KERNEL-level pieces (the q1 sharded step and the raw
all-to-all used as collective smoke checks); the plan-driven sharded
executor that runs ARBITRARY session queries on the mesh data plane lives
in `parallel/sharded.py` + `parallel/mesh.py`, selected by the planner
(`plan/overrides.py`) whenever a mesh session is active.  `dryrun_multichip`
below validates both layers and emits the MULTICHIP bench summary
(benchmarks/multichip.py) as its LAST stdout line.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar.vector import audited_sync
from ..kernels.q1 import Q1Inputs, Q1State, q1_final, q1_partial

import warnings

with warnings.catch_warnings():
    # the experimental path keeps the check_rep kwarg this jax version needs
    warnings.simplefilter("ignore", DeprecationWarning)
    from jax.experimental.shard_map import shard_map  # noqa: F401


def make_mesh(n_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def shard_batch(mesh: Mesh, batch, axis: str = "data"):
    """Place a batch's arrays row-sharded across the mesh."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


def distributed_q1_step(mesh: Mesh, axis: str = "data"):
    """Build the jitted multi-chip query step: row-sharded scan → per-shard
    partial agg → psum over ICI → identical final results on every shard.
    This is the aggregate analogue of partial/final around an exchange
    (GpuShuffleExchangeExecBase between GpuHashAggregateExec modes)."""

    def step(batch: Q1Inputs, cutoff):
        state = q1_partial(batch, cutoff)
        merged = jax.tree.map(lambda x: jax.lax.psum(x, axis), state)
        return q1_final(Q1State(*merged))

    spec = P(axis)
    in_specs = (Q1Inputs(*([spec] * 8)), P())
    out_spec = P()  # replicated results
    sharded = shard_map(step, mesh=mesh, in_specs=in_specs,
                        out_specs=out_spec, check_rep=False)
    return jax.jit(sharded)


def ici_all_to_all_exchange(mesh: Mesh, axis: str = "data"):
    """Jitted hash-partition exchange over ICI: each shard buckets its rows by
    murmur3(key) % n_shards into fixed-size slots, then lax.all_to_all moves
    bucket i of every shard to shard i (the UCX-mode data plane,
    reference shuffle-plugin/ UCXShuffleTransport, re-expressed as an XLA
    collective so XLA schedules it on the interconnect).

    Returns fn(keys, values, slot_capacity) -> (recv_keys, recv_values,
    recv_valid) with shapes [n_shards * slot_capacity] per shard; overflowing
    rows are dropped into the valid mask (callers size slots via sub-partition
    retry, mirroring GpuSubPartitionHashJoin's approach to skew)."""
    n_shards = mesh.devices.size

    def exchange(keys, values, valid):
        from ..expressions.hashexprs import murmur3_int
        cap = keys.shape[0]
        slot_cap = cap // n_shards
        h = murmur3_int(keys.astype(jnp.int32).view(jnp.uint32),
                        jnp.uint32(42)).view(jnp.int32)
        dest = jnp.where(valid, jnp.abs(h) % n_shards, n_shards)  # invalid → drop
        # slot position within destination bucket
        one = jnp.ones((cap,), jnp.int32)
        # rank of each row within its destination (stable): sort by dest
        order = jnp.argsort(dest, stable=True)
        sorted_dest = jnp.take(dest, order)
        # position within run of equal dest
        idx = jnp.arange(cap, dtype=jnp.int32)
        run_start = jnp.zeros((n_shards + 2,), jnp.int32).at[sorted_dest + 1].add(one, mode="drop")
        starts = jnp.cumsum(run_start)[:-1]  # start offset of each dest bucket
        pos_in_bucket = idx - jnp.take(starts, sorted_dest)
        keep = pos_in_bucket < slot_cap
        # scatter into [n_shards, slot_cap] send buffers
        send_slot = jnp.where(keep, sorted_dest * slot_cap + pos_in_bucket,
                              n_shards * slot_cap)
        src_rows = order
        buf_k = jnp.zeros((n_shards * slot_cap,), keys.dtype).at[send_slot].set(
            jnp.take(keys, src_rows), mode="drop")
        buf_v = jnp.zeros((n_shards * slot_cap,), values.dtype).at[send_slot].set(
            jnp.take(values, src_rows), mode="drop")
        buf_ok = jnp.zeros((n_shards * slot_cap,), jnp.bool_).at[send_slot].set(
            (sorted_dest < n_shards) & keep, mode="drop")
        # all-to-all: axis-split into n_shards blocks, transpose across shards
        def a2a(x):
            x = x.reshape(n_shards, slot_cap)
            return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                      tiled=False).reshape(-1)
        return a2a(buf_k), a2a(buf_v), a2a(buf_ok)

    spec = P(axis)
    return jax.jit(shard_map(exchange, mesh=mesh,
                             in_specs=(spec, spec, spec),
                             out_specs=(spec, spec, spec), check_rep=False))


def dryrun_multichip(n_devices: int) -> None:
    """Multi-chip validation + MULTICHIP bench over an n_devices mesh:
    (a) kernel layer: row-sharded partial agg + psum final, and the raw
        ICI all-to-all exchange — both collective shapes of the shuffle
        design compile and route correctly;
    (b) data plane: the plan-driven sharded executor runs TPC-H q1/q3/q18
        and a TPC-DS sample through session → planner → collective
        exchanges, bit-identical to the single-device baseline, with the
        O(exchanges) collective-launch assertion — and prints the compact
        parseable MULTICHIP summary as the LAST stdout line (per-chip
        rows/s, collective-time breakdown, scaling efficiency)."""
    import json
    import os
    import sys

    from ..kernels.q1 import make_example_batch
    mesh = make_mesh(n_devices)
    n = 128 * n_devices
    batch, cutoff = make_example_batch(n)
    batch = shard_batch(mesh, batch)
    step = distributed_q1_step(mesh)
    out = step(batch, jnp.int32(cutoff))
    jax.block_until_ready(out)
    assert int(audited_sync(out["count_order"], "fetch").sum()) > 0

    exchange = ici_all_to_all_exchange(mesh)
    keys = jnp.arange(n, dtype=jnp.int64)
    vals = jnp.ones((n,), jnp.float32)
    valid = jnp.ones((n,), jnp.bool_)
    sharding = NamedSharding(mesh, P("data"))
    keys, vals, valid = (jax.device_put(x, sharding) for x in (keys, vals, valid))
    rk, rv, rok = exchange(keys, vals, valid)
    jax.block_until_ready((rk, rv, rok))
    # every received-valid key must hash-route to its receiving shard
    from ..expressions.hashexprs import np_murmur3_int
    rk_np = audited_sync(rk, "fetch")
    rok_np = audited_sync(rok, "fetch")
    n_local = rk_np.shape[0] // n_devices
    dest = np.abs(np_murmur3_int(rk_np.astype(np.int32).view(np.uint32),
                                 np.uint32(42)).view(np.int32).astype(np.int64)) % n_devices
    owner = np.repeat(np.arange(n_devices), n_local)
    assert (dest[rok_np] == owner[rok_np]).all(), "exchange misrouted rows"

    # (b) the framework data plane: plan-driven sharded execution of real
    # queries (benchmarks/multichip.py). The summary prints LAST so the
    # driver's stdout tail is the parseable MULTICHIP record.
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if root not in sys.path:
        sys.path.insert(0, root)
    import benchmarks.multichip as mc
    rows = int(os.environ.get("MULTICHIP_ROWS", str(1 << 16)))
    summary = mc.run(n_devices, rows)
    records = summary.pop("records", [])
    print(json.dumps({"detail": records}), flush=True)
    assert not summary.get("errors"), \
        f"multichip query stages failed: {summary['errors']}"
    assert summary.get("bit_identical_all"), \
        "mesh execution diverged from single-device results"
    assert summary.get("collective_launches_O_exchanges"), \
        "collective launches not O(exchanges)"
    # coverage, not just scaling: the pruned q3/q18/tpcds_q3 shapes are
    # fully fixed-width, so EVERY one of their exchanges must have ridden
    # the fabric (q1's string-keyed aggregation exchange is per-map by
    # design and is exempt) — a silent eligibility regression fails here
    for qname in ("tpch_q3", "tpch_q18", "tpcds_q3"):
        q = summary["queries"].get(qname, {})
        assert q.get("collective_launches", 0) == q.get("exchanges", -1), \
            f"{qname}: only {q.get('collective_launches')} of " \
            f"{q.get('exchanges')} exchanges took the collective"
    print(json.dumps(summary, separators=(",", ":")), flush=True)
