"""Distributed execution over a jax.sharding.Mesh.

The TPU re-design of the reference's distributed layer (SURVEY.md §2.7):
  * Spark executor data-parallelism       → mesh "data" axis, row-sharded batches
  * partial→shuffle→final aggregation     → per-shard partial agg + psum (tree
    aggregate over ICI — cheaper than materializing a shuffle for aggregates)
  * hash-partition exchange (UCX mode)    → murmur3 bucketing + lax.all_to_all
    over ICI ("ICI shuffle mode", config spark.rapids.shuffle.mode=ICI)
The reference's parallelism inventory (SURVEY.md §2.7 note) maps exactly: no
tensor/pipeline/expert axes exist in a SQL engine; the mesh is 1-D data-parallel
with collectives carrying exchange traffic.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..kernels.q1 import Q1Inputs, Q1State, q1_final, q1_partial

import warnings

with warnings.catch_warnings():
    # the experimental path keeps the check_rep kwarg this jax version needs
    warnings.simplefilter("ignore", DeprecationWarning)
    from jax.experimental.shard_map import shard_map  # noqa: F401


def make_mesh(n_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def shard_batch(mesh: Mesh, batch, axis: str = "data"):
    """Place a batch's arrays row-sharded across the mesh."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


def distributed_q1_step(mesh: Mesh, axis: str = "data"):
    """Build the jitted multi-chip query step: row-sharded scan → per-shard
    partial agg → psum over ICI → identical final results on every shard.
    This is the aggregate analogue of partial/final around an exchange
    (GpuShuffleExchangeExecBase between GpuHashAggregateExec modes)."""

    def step(batch: Q1Inputs, cutoff):
        state = q1_partial(batch, cutoff)
        merged = jax.tree.map(lambda x: jax.lax.psum(x, axis), state)
        return q1_final(Q1State(*merged))

    spec = P(axis)
    in_specs = (Q1Inputs(*([spec] * 8)), P())
    out_spec = P()  # replicated results
    sharded = shard_map(step, mesh=mesh, in_specs=in_specs,
                        out_specs=out_spec, check_rep=False)
    return jax.jit(sharded)


def ici_all_to_all_exchange(mesh: Mesh, axis: str = "data"):
    """Jitted hash-partition exchange over ICI: each shard buckets its rows by
    murmur3(key) % n_shards into fixed-size slots, then lax.all_to_all moves
    bucket i of every shard to shard i (the UCX-mode data plane,
    reference shuffle-plugin/ UCXShuffleTransport, re-expressed as an XLA
    collective so XLA schedules it on the interconnect).

    Returns fn(keys, values, slot_capacity) -> (recv_keys, recv_values,
    recv_valid) with shapes [n_shards * slot_capacity] per shard; overflowing
    rows are dropped into the valid mask (callers size slots via sub-partition
    retry, mirroring GpuSubPartitionHashJoin's approach to skew)."""
    n_shards = mesh.devices.size

    def exchange(keys, values, valid):
        from ..expressions.hashexprs import murmur3_int
        cap = keys.shape[0]
        slot_cap = cap // n_shards
        h = murmur3_int(keys.astype(jnp.int32).view(jnp.uint32),
                        jnp.uint32(42)).view(jnp.int32)
        dest = jnp.where(valid, jnp.abs(h) % n_shards, n_shards)  # invalid → drop
        # slot position within destination bucket
        one = jnp.ones((cap,), jnp.int32)
        # rank of each row within its destination (stable): sort by dest
        order = jnp.argsort(dest, stable=True)
        sorted_dest = jnp.take(dest, order)
        # position within run of equal dest
        idx = jnp.arange(cap, dtype=jnp.int32)
        run_start = jnp.zeros((n_shards + 2,), jnp.int32).at[sorted_dest + 1].add(one, mode="drop")
        starts = jnp.cumsum(run_start)[:-1]  # start offset of each dest bucket
        pos_in_bucket = idx - jnp.take(starts, sorted_dest)
        keep = pos_in_bucket < slot_cap
        # scatter into [n_shards, slot_cap] send buffers
        send_slot = jnp.where(keep, sorted_dest * slot_cap + pos_in_bucket,
                              n_shards * slot_cap)
        src_rows = order
        buf_k = jnp.zeros((n_shards * slot_cap,), keys.dtype).at[send_slot].set(
            jnp.take(keys, src_rows), mode="drop")
        buf_v = jnp.zeros((n_shards * slot_cap,), values.dtype).at[send_slot].set(
            jnp.take(values, src_rows), mode="drop")
        buf_ok = jnp.zeros((n_shards * slot_cap,), jnp.bool_).at[send_slot].set(
            (sorted_dest < n_shards) & keep, mode="drop")
        # all-to-all: axis-split into n_shards blocks, transpose across shards
        def a2a(x):
            x = x.reshape(n_shards, slot_cap)
            return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                      tiled=False).reshape(-1)
        return a2a(buf_k), a2a(buf_v), a2a(buf_ok)

    spec = P(axis)
    return jax.jit(shard_map(exchange, mesh=mesh,
                             in_specs=(spec, spec, spec),
                             out_specs=(spec, spec, spec), check_rep=False))


def dryrun_multichip(n_devices: int) -> None:
    """Compile + execute one full distributed query step on tiny shapes:
    (a) row-sharded partial agg + psum final; (b) ICI all-to-all exchange,
    validating both collective paths of the shuffle design."""
    from ..kernels.q1 import make_example_batch
    mesh = make_mesh(n_devices)
    n = 128 * n_devices
    batch, cutoff = make_example_batch(n)
    batch = shard_batch(mesh, batch)
    step = distributed_q1_step(mesh)
    out = step(batch, jnp.int32(cutoff))
    jax.block_until_ready(out)
    assert int(np.asarray(out["count_order"]).sum()) > 0

    exchange = ici_all_to_all_exchange(mesh)
    keys = jnp.arange(n, dtype=jnp.int64)
    vals = jnp.ones((n,), jnp.float32)
    valid = jnp.ones((n,), jnp.bool_)
    sharding = NamedSharding(mesh, P("data"))
    keys, vals, valid = (jax.device_put(x, sharding) for x in (keys, vals, valid))
    rk, rv, rok = exchange(keys, vals, valid)
    jax.block_until_ready((rk, rv, rok))
    # every received-valid key must hash-route to its receiving shard
    from ..expressions.hashexprs import np_murmur3_int
    rk_np, rok_np = np.asarray(rk), np.asarray(rok)
    n_local = rk_np.shape[0] // n_devices
    dest = np.abs(np_murmur3_int(rk_np.astype(np.int32).view(np.uint32),
                                 np.uint32(42)).view(np.int32).astype(np.int64)) % n_devices
    owner = np.repeat(np.arange(n_devices), n_local)
    assert (dest[rok_np] == owner[rok_np]).all(), "exchange misrouted rows"

    # (c) FRAMEWORK query over the mesh: session -> plan -> collective
    # all_to_all exchange -> per-shard aggregation/join, vs the CPU oracle
    # (the exec-layer integration of the UCX-mode shuffle, VERDICT.md #2)
    import pyarrow as pa

    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.shuffle.exchange import TpuShuffleExchangeExec

    rng = np.random.default_rng(3)
    t = pa.table({"k": rng.integers(0, 40, 4096),
                  "v": rng.normal(size=4096),
                  "w": rng.integers(-50, 50, 4096)})
    t2 = pa.table({"k": rng.integers(0, 40, 512),
                   "r": rng.integers(0, 9, 512)})
    mesh_conf = {"spark.rapids.shuffle.mode": "ICI",
                 "spark.rapids.tpu.mesh.enabled": "true",
                 "spark.sql.shuffle.partitions": str(n_devices),
                 "spark.sql.autoBroadcastJoinThreshold": "0"}
    tpu_s = TpuSession(dict(mesh_conf))
    cpu_s = TpuSession({"spark.rapids.sql.enabled": "false"})

    collective_runs = []
    orig = TpuShuffleExchangeExec._try_materialize_collective

    def spy(self, sid, ctx):
        used = orig(self, sid, ctx)
        collective_runs.append(used)
        return used

    TpuShuffleExchangeExec._try_materialize_collective = spy
    try:
        def query(sess):
            df = sess.createDataFrame(t, num_partitions=min(4, n_devices))
            d2 = sess.createDataFrame(t2, num_partitions=2)
            return (df.join(d2, on="k", how="inner")
                    .groupBy("k").agg(F.sum(F.col("v")),
                                      F.count(F.col("w")),
                                      F.max(F.col("r"))))
        got = {r["k"]: list(r.values()) for r in query(tpu_s).collect()}
        want = {r["k"]: list(r.values()) for r in query(cpu_s).collect()}
    finally:
        TpuShuffleExchangeExec._try_materialize_collective = orig
    assert set(got) == set(want), "framework mesh query lost groups"
    for k in got:
        for x, y in zip(got[k], want[k]):
            assert (x == y) or abs(x - y) < 1e-6, (k, x, y)
    assert any(collective_runs), \
        "framework query never used the mesh collective exchange"
