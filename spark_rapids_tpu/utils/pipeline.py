"""Producer/consumer pipelining helpers for the shuffle and exec layers.

Reference idiom: RapidsShuffleThreadedReaderBase's prefetching block fetcher —
the next block's deserialize+upload runs on a pool thread while downstream
consumes the current one, so the tunnel's fixed per-dispatch latency overlaps
host I/O instead of adding to it.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, TypeVar

T = TypeVar("T")

_DONE = object()


class _Err:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def prefetch_iterator(it: Iterator[T], depth: int) -> Iterator[T]:
    """Drive `it` from a worker thread, keeping up to `depth` items ready
    ahead of the consumer. Order is preserved exactly; an exception raised by
    the producer re-raises at the consumer's corresponding `next()`; closing
    the returned generator early stops the worker without leaking it (the
    worker re-checks the stop flag on every bounded put). depth <= 0 is a
    passthrough."""
    if depth <= 0:
        yield from it
        return
    q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
    stop = threading.Event()
    # the producer runs `it`'s frames on the worker thread: inherit the
    # consumer's query tracer (per-query tracing routes by thread — an
    # unbound worker's spans/syncs would vanish from the owning query's
    # record and break bundle reconciliation); a no-op when untraced
    from ..obs import tracer as _obs
    from ..serving import query_context as _qlc
    obs_parent = _obs.current_span()
    # same for the query lifecycle binding: checkpoints inside the
    # producer's frames (reduce fetch, nested operator pulls) must see
    # the consumer's query so a cancel/deadline stops the prefetch too
    qctx = _qlc.current()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def work() -> None:
        try:
            with _obs.inherit(obs_parent), _qlc.bind(qctx):
                for item in it:
                    if not _put(item):
                        return
        except BaseException as e:  # noqa: BLE001 — delivered to consumer
            _put(_Err(e))
            return
        _put(_DONE)

    t = threading.Thread(target=work, name="srt-prefetch", daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _DONE:
                break
            if isinstance(item, _Err):
                raise item.exc
            yield item
    finally:
        stop.set()
        t.join()
