"""Hardware capability probes for the active JAX backend.

The real TPU path (axon) compiles with an X64-removal pass: f64/i64 are
demoted to 32-bit and programs containing ops that cannot be rewritten
(notably bitcast-convert on 64-bit types) are rejected at compile time.
Device code that relies on 64-bit bit views (sortable float encodings, the
join/shuffle hash plane) must therefore pick its width per backend.

Reference analogue: GpuDeviceManager.scala validates device capabilities at
startup (validateGpuArchitecture); here the probe is a one-time AOT compile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.lru_cache(maxsize=None)
def _x64_native_for(backend: str) -> bool:
    try:
        fn = jax.jit(
            lambda x: jax.lax.bitcast_convert_type(x, jnp.int64) ^ 1)
        x = jnp.ones((8,), jnp.float64)
        fn.lower(x).compile()
        return True
    except Exception:  # noqa: BLE001 — any rejection means "not native"
        return False


def x64_native() -> bool:
    """True when the active backend compiles 64-bit bitcasts natively (CPU
    does; the tunneled TPU demotes X64 and rejects them). Cached per
    backend name actually in use."""
    return _x64_native_for(jax.default_backend())


def sortable_float_dtype(dtype):
    """The float dtype whose bit-encoding is safe on this backend: f64 stays
    f64 where 64-bit bitcasts work, else f32 (the demoting backend computes
    every f64 op in f32 anyway, so the narrowing is semantics-preserving
    on-device)."""
    if dtype == jnp.float64 and not x64_native():
        return jnp.float32
    return dtype


def hash_plane():
    """(uint dtype, mix constant, init value, sentinel) for the join/shuffle
    composite-hash plane. 64-bit splitmix on native backends; 32-bit variant
    (same structure) on demoting backends — hash collisions only add
    verified-equality candidates, never wrong results."""
    import numpy as np
    if x64_native():
        return (jnp.uint64, jnp.uint64(np.uint64(0x9E3779B97F4A7C15)),
                jnp.uint64(np.uint64(0x243F6A8885A308D3)),
                jnp.uint64(np.uint64(0xFFFFFFFFFFFFFFFF)))
    return (jnp.uint32, jnp.uint32(0x9E3779B9), jnp.uint32(0x85A308D3),
            jnp.uint32(0xFFFFFFFF))
