"""Spark-compatible data-type system + per-operator type-support signatures.

TPU re-design of the reference's type layer:
  * DataType hierarchy mirrors Spark SQL types (the surface `TypeChecks.scala` gates).
  * `TypeSig` is the reference's static type-support matrix
    (/root/reference/sql-plugin/.../TypeChecks.scala:543) — a set of types an
    operator/expression supports on the accelerator, with notes for partial support.
On TPU the physical carriers differ from cuDF: fixed-width types map to jax dtypes,
strings/binary to Arrow offset+data buffers, decimals <=18 digits to scaled int64
(decimal128 falls back to host), dates to int32 days, timestamps to int64 micros.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np


class DataType:
    """Base of the Spark-mirroring logical type hierarchy."""

    #: numpy dtype of the device carrier, or None when not fixed-width
    np_dtype: Optional[np.dtype] = None

    def simple_string(self) -> str:
        return type(self).__name__.replace("Type", "").lower()

    def __repr__(self) -> str:
        return self.simple_string()

    def __eq__(self, other: object) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self).__name__)

    @property
    def is_numeric(self) -> bool:
        return isinstance(self, NumericType)

    @property
    def default_size(self) -> int:
        return self.np_dtype.itemsize if self.np_dtype is not None else 8


class NullType(DataType):
    np_dtype = np.dtype(np.bool_)  # carrier irrelevant; all rows null

    def simple_string(self) -> str:
        return "void"


class BooleanType(DataType):
    np_dtype = np.dtype(np.bool_)


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class ByteType(IntegralType):
    np_dtype = np.dtype(np.int8)

    def simple_string(self) -> str:
        return "tinyint"


class ShortType(IntegralType):
    np_dtype = np.dtype(np.int16)

    def simple_string(self) -> str:
        return "smallint"


class IntegerType(IntegralType):
    np_dtype = np.dtype(np.int32)

    def simple_string(self) -> str:
        return "int"


class LongType(IntegralType):
    np_dtype = np.dtype(np.int64)

    def simple_string(self) -> str:
        return "bigint"


class FractionalType(NumericType):
    pass


class FloatType(FractionalType):
    np_dtype = np.dtype(np.float32)


class DoubleType(FractionalType):
    np_dtype = np.dtype(np.float64)


@dataclass(frozen=True, eq=False)
class DecimalType(FractionalType):
    """Decimal(precision, scale). Precision<=18 carried as scaled int64 on device
    (reference carries <=38 via cuDF 128-bit, spark-rapids-jni DecimalUtils)."""
    precision: int = 10
    scale: int = 0

    MAX_PRECISION = 38
    MAX_DEVICE_PRECISION = 18  # int64-scaled carrier

    @property
    def np_dtype(self):  # type: ignore[override]
        return np.dtype(np.int64) if self.precision <= self.MAX_DEVICE_PRECISION else None

    def simple_string(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, DecimalType) and other.precision == self.precision
                and other.scale == self.scale)

    def __hash__(self) -> int:
        return hash(("decimal", self.precision, self.scale))


class StringType(DataType):
    np_dtype = None  # Arrow offsets(int32/int64) + uint8 data on device


class BinaryType(DataType):
    np_dtype = None


class DateType(DataType):
    np_dtype = np.dtype(np.int32)  # days since epoch (Spark internal repr)


class TimestampType(DataType):
    np_dtype = np.dtype(np.int64)  # microseconds since epoch UTC

    def simple_string(self) -> str:
        return "timestamp"


class CalendarIntervalType(DataType):
    np_dtype = None


@dataclass(eq=False)
class ArrayType(DataType):
    element_type: DataType = field(default_factory=lambda: NullType())
    contains_null: bool = True
    np_dtype = None

    def simple_string(self) -> str:
        return f"array<{self.element_type.simple_string()}>"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ArrayType) and other.element_type == self.element_type

    def __hash__(self) -> int:
        return hash(("array", self.element_type))


@dataclass(eq=False)
class MapType(DataType):
    key_type: DataType = field(default_factory=lambda: NullType())
    value_type: DataType = field(default_factory=lambda: NullType())
    value_contains_null: bool = True
    np_dtype = None

    def simple_string(self) -> str:
        return f"map<{self.key_type.simple_string()},{self.value_type.simple_string()}>"

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, MapType) and other.key_type == self.key_type
                and other.value_type == self.value_type)

    def __hash__(self) -> int:
        return hash(("map", self.key_type, self.value_type))


@dataclass(frozen=True)
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True


@dataclass(eq=False)
class StructType(DataType):
    fields: Tuple[StructField, ...] = ()
    np_dtype = None

    def __init__(self, fields: Iterable[StructField] = ()):
        object.__setattr__(self, "fields", tuple(fields))

    @property
    def field_names(self) -> List[str]:
        return [f.name for f in self.fields]

    def simple_string(self) -> str:
        inner = ",".join(f"{f.name}:{f.data_type.simple_string()}" for f in self.fields)
        return f"struct<{inner}>"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, StructType) and other.fields == self.fields

    def __hash__(self) -> int:
        return hash(("struct", self.fields))


# Singletons (Spark convention)
NullT = NullType()
BooleanT = BooleanType()
ByteT = ByteType()
ShortT = ShortType()
IntegerT = IntegerType()
LongT = LongType()
FloatT = FloatType()
DoubleT = DoubleType()
StringT = StringType()
BinaryT = BinaryType()
DateT = DateType()
TimestampT = TimestampType()


def is_fixed_width(dt: DataType) -> bool:
    return dt.np_dtype is not None and not isinstance(dt, NullType)


INTEGRAL_TYPES: Tuple[DataType, ...] = (ByteT, ShortT, IntegerT, LongT)
FRACTIONAL_TYPES: Tuple[DataType, ...] = (FloatT, DoubleT)
NUMERIC_TYPES: Tuple[DataType, ...] = INTEGRAL_TYPES + FRACTIONAL_TYPES


def numeric_promote(a: DataType, b: DataType) -> DataType:
    """Spark's binary-arithmetic common type for non-decimal numerics."""
    order = [ByteT, ShortT, IntegerT, LongT, FloatT, DoubleT]
    if a == b:
        return a
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        raise ValueError("decimal promotion handled by decimal rules")
    ia, ib = order.index(a), order.index(b)
    hi = order[max(ia, ib)]
    # long (op) float => double in Spark? Spark: long+float -> float. Keep simple widening.
    return hi


# ---------------------------------------------------------------------------
# TypeSig: the can-this-run-on-TPU matrix (reference TypeChecks.scala:543)
# ---------------------------------------------------------------------------

class TypeEnum:
    BOOLEAN = "BOOLEAN"
    BYTE = "BYTE"
    SHORT = "SHORT"
    INT = "INT"
    LONG = "LONG"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    DATE = "DATE"
    TIMESTAMP = "TIMESTAMP"
    STRING = "STRING"
    BINARY = "BINARY"
    DECIMAL_64 = "DECIMAL_64"
    DECIMAL_128 = "DECIMAL_128"
    NULL = "NULL"
    ARRAY = "ARRAY"
    MAP = "MAP"
    STRUCT = "STRUCT"
    CALENDAR = "CALENDAR"
    UDT = "UDT"

    ALL = (BOOLEAN, BYTE, SHORT, INT, LONG, FLOAT, DOUBLE, DATE, TIMESTAMP, STRING,
           BINARY, DECIMAL_64, DECIMAL_128, NULL, ARRAY, MAP, STRUCT, CALENDAR, UDT)


def _type_enum_of(dt: DataType) -> str:
    if isinstance(dt, BooleanType):
        return TypeEnum.BOOLEAN
    if isinstance(dt, ByteType):
        return TypeEnum.BYTE
    if isinstance(dt, ShortType):
        return TypeEnum.SHORT
    if isinstance(dt, IntegerType):
        return TypeEnum.INT
    if isinstance(dt, LongType):
        return TypeEnum.LONG
    if isinstance(dt, FloatType):
        return TypeEnum.FLOAT
    if isinstance(dt, DoubleType):
        return TypeEnum.DOUBLE
    if isinstance(dt, DateType):
        return TypeEnum.DATE
    if isinstance(dt, TimestampType):
        return TypeEnum.TIMESTAMP
    if isinstance(dt, StringType):
        return TypeEnum.STRING
    if isinstance(dt, BinaryType):
        return TypeEnum.BINARY
    if isinstance(dt, DecimalType):
        return (TypeEnum.DECIMAL_64 if dt.precision <= DecimalType.MAX_DEVICE_PRECISION
                else TypeEnum.DECIMAL_128)
    if isinstance(dt, NullType):
        return TypeEnum.NULL
    if isinstance(dt, ArrayType):
        return TypeEnum.ARRAY
    if isinstance(dt, MapType):
        return TypeEnum.MAP
    if isinstance(dt, StructType):
        return TypeEnum.STRUCT
    if isinstance(dt, CalendarIntervalType):
        return TypeEnum.CALENDAR
    return TypeEnum.UDT


class TypeSig:
    """A set of supported `TypeEnum`s, with per-type notes and nested-type scoping.

    Reference: TypeSig (TypeChecks.scala:543) with combinators `+`, `withPsNote`,
    `nested`. `check(dt)` returns None when supported or a human-readable reason.
    """

    def __init__(self, initial: Iterable[str] = (), child: Optional["TypeSig"] = None,
                 notes: Optional[Dict[str, str]] = None):
        self.types = frozenset(initial)
        self.child = child
        self.notes = dict(notes or {})

    def __add__(self, other: "TypeSig") -> "TypeSig":
        notes = dict(self.notes)
        notes.update(other.notes)
        child = self.child or other.child
        return TypeSig(self.types | other.types, child, notes)

    def with_ps_note(self, type_enum: str, note: str) -> "TypeSig":
        notes = dict(self.notes)
        notes[type_enum] = note
        return TypeSig(self.types, self.child, notes)

    def nested(self, child: Optional["TypeSig"] = None) -> "TypeSig":
        return TypeSig(self.types, child if child is not None else self, self.notes)

    def supports(self, dt: DataType) -> bool:
        return self.check(dt) is None

    def check(self, dt: DataType) -> Optional[str]:
        te = _type_enum_of(dt)
        if te not in self.types:
            return f"{dt.simple_string()} is not supported"
        inner = self.child or self
        if isinstance(dt, ArrayType):
            r = inner.check(dt.element_type)
            if r:
                return f"array element: {r}"
        elif isinstance(dt, MapType):
            r = inner.check(dt.key_type) or inner.check(dt.value_type)
            if r:
                return f"map entry: {r}"
        elif isinstance(dt, StructType):
            for f in dt.fields:
                r = inner.check(f.data_type)
                if r:
                    return f"struct field {f.name}: {r}"
        return None


def _sig(*types: str) -> TypeSig:
    return TypeSig(types)


class TypeSigs:
    """Standard signatures, mirroring reference TypeSig companion object."""
    none = _sig()
    BOOLEAN = _sig(TypeEnum.BOOLEAN)
    integral = _sig(TypeEnum.BYTE, TypeEnum.SHORT, TypeEnum.INT, TypeEnum.LONG)
    fp = _sig(TypeEnum.FLOAT, TypeEnum.DOUBLE)
    DECIMAL_64 = _sig(TypeEnum.DECIMAL_64)
    DECIMAL_128 = _sig(TypeEnum.DECIMAL_64, TypeEnum.DECIMAL_128)
    numeric = integral + fp + DECIMAL_64
    STRING = _sig(TypeEnum.STRING)
    BINARY = _sig(TypeEnum.BINARY)
    DATE = _sig(TypeEnum.DATE)
    TIMESTAMP = _sig(TypeEnum.TIMESTAMP)
    NULL = _sig(TypeEnum.NULL)
    datetime = DATE + TIMESTAMP
    comparable = integral + fp + DECIMAL_64 + BOOLEAN + STRING + datetime + NULL
    common_scalar = comparable
    orderable = comparable
    all_basic = comparable + BINARY
    ARRAY = _sig(TypeEnum.ARRAY)
    MAP = _sig(TypeEnum.MAP)
    STRUCT = _sig(TypeEnum.STRUCT)
    nested_common = (all_basic + ARRAY + STRUCT + MAP).nested()
    all = TypeSig(TypeEnum.ALL).nested()


def from_arrow(at) -> DataType:
    """Arrow → Spark type (host interop boundary)."""
    import pyarrow as pa
    if pa.types.is_boolean(at):
        return BooleanT
    if pa.types.is_int8(at):
        return ByteT
    if pa.types.is_int16(at):
        return ShortT
    if pa.types.is_int32(at):
        return IntegerT
    if pa.types.is_int64(at):
        return LongT
    if pa.types.is_float32(at):
        return FloatT
    if pa.types.is_float64(at):
        return DoubleT
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return StringT
    if pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return BinaryT
    if pa.types.is_date32(at):
        return DateT
    if pa.types.is_timestamp(at):
        return TimestampT
    if pa.types.is_decimal(at):
        return DecimalType(at.precision, at.scale)
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ArrayType(from_arrow(at.value_type))
    if pa.types.is_map(at):
        return MapType(from_arrow(at.key_type), from_arrow(at.item_type))
    if pa.types.is_struct(at):
        return StructType([StructField(f.name, from_arrow(f.type), f.nullable) for f in at])
    if pa.types.is_null(at):
        return NullT
    raise TypeError(f"unsupported arrow type {at}")


def to_arrow(dt: DataType):
    """Spark → Arrow type."""
    import pyarrow as pa
    if isinstance(dt, BooleanType):
        return pa.bool_()
    if isinstance(dt, ByteType):
        return pa.int8()
    if isinstance(dt, ShortType):
        return pa.int16()
    if isinstance(dt, IntegerType):
        return pa.int32()
    if isinstance(dt, LongType):
        return pa.int64()
    if isinstance(dt, FloatType):
        return pa.float32()
    if isinstance(dt, DoubleType):
        return pa.float64()
    if isinstance(dt, StringType):
        return pa.string()
    if isinstance(dt, BinaryType):
        return pa.binary()
    if isinstance(dt, DateType):
        return pa.date32()
    if isinstance(dt, TimestampType):
        return pa.timestamp("us", tz="UTC")
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, ArrayType):
        return pa.list_(to_arrow(dt.element_type))
    if isinstance(dt, MapType):
        return pa.map_(to_arrow(dt.key_type), to_arrow(dt.value_type))
    if isinstance(dt, StructType):
        return pa.struct([(f.name, to_arrow(f.data_type)) for f in dt.fields])
    if isinstance(dt, NullType):
        return pa.null()
    raise TypeError(f"unsupported type {dt}")


# ---------------------------------------------------------------------------
# DDL schema strings ("a INT, b STRUCT<x: BIGINT, y: STRING>") — the schema
# syntax Spark accepts in from_json / createDataFrame (StructType.fromDDL)
# ---------------------------------------------------------------------------

def parse_ddl(ddl: str) -> StructType:
    ddl = ddl.strip()
    # Spark also accepts the full 'struct<a: int, ...>' form at top level
    if ddl.lower().startswith("struct<") and ddl.endswith(">"):
        ddl = ddl[7:-1]
    fields = []
    for part in _split_top_level(ddl):
        part = part.strip()
        if not part:
            continue
        # "name type" or "name: type" (struct-field style)
        if ":" in part.split("<")[0]:
            name, typ = part.split(":", 1)
        else:
            bits = part.split(None, 1)
            if len(bits) != 2:
                raise ValueError(f"cannot parse DDL field {part!r}")
            name, typ = bits
        fields.append(StructField(name.strip().strip("`"),
                                  parse_ddl_type(typ.strip()), True))
    return StructType(tuple(fields))


def _split_top_level(s: str, sep: str = ",") -> list:
    out, depth, cur = [], 0, []
    for ch in s:
        if ch in "<(":
            depth += 1
        elif ch in ">)":
            depth -= 1
        if ch == sep and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


_DDL_SIMPLE = {
    "boolean": BooleanType, "tinyint": ByteType, "byte": ByteType,
    "smallint": ShortType, "short": ShortType, "int": IntegerType,
    "integer": IntegerType, "bigint": LongType, "long": LongType,
    "float": FloatType, "real": FloatType, "double": DoubleType,
    "string": StringType, "binary": BinaryType, "date": DateType,
    "timestamp": TimestampType, "void": NullType, "null": NullType,
}


def parse_ddl_type(s: str) -> DataType:
    s = s.strip()
    low = s.lower()
    if low in _DDL_SIMPLE:
        return _DDL_SIMPLE[low]()
    if low.startswith("decimal"):
        m = s[s.index("(") + 1: s.rindex(")")] if "(" in s else "10,0"
        p, sc = (m.split(",") + ["0"])[:2]
        return DecimalType(int(p), int(sc))
    if low.startswith("array<") and s.endswith(">"):
        return ArrayType(parse_ddl_type(s[6:-1]))
    if low.startswith("map<") and s.endswith(">"):
        k, v = _split_top_level(s[4:-1])
        return MapType(parse_ddl_type(k), parse_ddl_type(v))
    if low.startswith("struct<") and s.endswith(">"):
        return parse_ddl(s[7:-1])
    raise ValueError(f"cannot parse DDL type {s!r}")
