"""explain("metrics"): the executed physical plan annotated per node with
its actual metrics, dispatch counts, and blocking-sync counts.

The Spark SQL UI analogue (reference GpuExec SQLMetrics rendered on the
plan graph, PAPER.md §5): after a query runs, every plan node shows what it
actually did. Works without the tracer — the inputs are the session's
always-captured snapshots (plan tree, metric snapshot, sync-ledger delta);
with tracing on, ``session.last_query_profile()`` carries the same numbers
plus the timeline.

Sync counts attribute by OPERATOR NAME (the SyncLedger's thread-local scope
granularity): two nodes of the same class share one ledger bucket, and the
annotation says so (``syncs[class]``) instead of pretending per-instance
precision.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

_LEVELS = {"ESSENTIAL": 0, "MODERATE": 1, "DEBUG": 2}

#: metric names rendered as durations (the engine records ns)
_TIME_SUFFIXES = ("Time", "TimeNs", "WaitNs", "Ns")


def _fmt_val(name: str, v: int) -> str:
    if any(name.endswith(s) for s in _TIME_SUFFIXES) and isinstance(
            v, (int, float)) and v >= 10_000:
        return f"{v / 1e6:.1f}ms"
    if isinstance(v, int) and v >= 10_000:
        return f"{v:,}"
    return str(v)


def render_explain_metrics(plan_tree: List[Dict[str, Any]],
                           metrics: Dict[str, Dict[str, tuple]],
                           sync_ledger: Optional[Dict[str, Dict[str, int]]]
                           = None,
                           level: str = "MODERATE") -> str:
    """Render the annotated tree. ``plan_tree`` is the session's per-node
    snapshot ({"i","depth","name","desc","tpu"} in collect_nodes preorder);
    ``metrics`` is the snapshot_plan_metrics form ({"i:Name": {metric:
    (value, level)}})."""
    if not plan_tree:
        return "<no executed query: run a collect() first>"
    want = _LEVELS.get(str(level).upper(), 1)
    sync_ledger = sync_ledger or {}
    # class-name collision detection for the honest "[class]" marker
    name_counts: Dict[str, int] = {}
    for n in plan_tree:
        name_counts[n["name"]] = name_counts.get(n["name"], 0) + 1
    lines: List[str] = []
    for n in plan_tree:
        key = f"{n['i']}:{n['name']}"
        vals = metrics.get(key, {})
        shown = {m: v for m, (v, lvl) in vals.items()
                 if _LEVELS.get(lvl, 1) <= want and v}
        parts = ["  " * n["depth"] + ("*" if n.get("tpu") else " ") + " "
                 + n["desc"]]
        ann = []
        # dispatch accounting rides the per-exec opjit metrics
        hits = vals.get("opJitCacheHits", (0, None))[0]
        misses = vals.get("opJitCacheMisses", (0, None))[0]
        core = {m: v for m, v in shown.items()
                if not m.startswith("opJit")}
        if core:
            ann.append("metrics: " + ", ".join(
                f"{m}={_fmt_val(m, v)}" for m, v in sorted(core.items())))
        if hits or misses:
            ann.append(f"dispatches: {hits + misses} "
                       f"(hits={hits} misses={misses})")
        syncs = sync_ledger.get(n["name"])
        if syncs:
            tag = "[class]" if name_counts[n["name"]] > 1 else ""
            ann.append(f"syncs{tag}: " + ", ".join(
                f"{k}={v}" for k, v in sorted(syncs.items())))
        if ann:
            parts.append("  " * n["depth"] + "     | " + " | ".join(ann))
        lines.extend(parts)
    return "\n".join(lines)
