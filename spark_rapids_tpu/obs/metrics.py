"""Always-on process-wide metrics registry: counters, gauges, and bounded
log2-bucket histograms with per-session/per-query labels.

Reference: the plugin accumulates per-operator ``GpuMetric``s into Spark's
executor-wide metrics system and history server (SURVEY L2 /
``GpuExec.scala``) — an aggregate, always-on layer that exists whether or
not anyone is profiling, so serving dashboards (rows/s, p95 latency, HBM
pressure, spill volume) read from running totals instead of per-query
artifacts. This module is that layer for the TPU engine; the per-query
tracer (obs/tracer.py) remains the deep-dive tool.

Design:

* **Always on, near-zero cost when idle**: nothing increments when no
  query runs. The hot path is one dict lookup plus one in-place add on a
  pre-resolved cell — no lock is taken on the increment path (CPython's
  GIL keeps cell reads untorn; a rare lost update under extreme thread
  contention is the standard monitoring-counter tradeoff and is
  documented here rather than hidden). Locks guard only registry/label
  STRUCTURE (first sight of a metric or label set) and snapshots.
* **Emission discipline** (tracelint TL012, analysis/obslint.py): engine
  code emits through the module-level helpers (:func:`counter_inc`,
  :func:`gauge_set`, :func:`gauge_max`, :func:`histogram_observe`) and a
  label/value argument must never embed a blocking device→host sync —
  metric values are numbers the caller already holds on host.
* **Histograms** use log2 buckets: bucket ``i`` counts observations in
  ``[2^(i-1), 2^i)`` (bucket 0: values < 1), 64 buckets total — bounded
  memory per label set, and p50/p95/p99 read out as the upper edge of the
  bucket where the cumulative count crosses the rank (factor-of-two
  resolution, which is what a serving dashboard needs).
* **Query lifecycle** (:func:`query_begin` / :func:`query_end`) feeds the
  ``queries.active`` gauge, the ``query.latency_ms`` / ``query.rows_per_s``
  histograms and the process-wide query epoch the tracer uses to decide
  whether process-wide counter deltas are attributable to one query
  (``exclusive``) — it runs for EVERY query, traced or not.
* :func:`full_snapshot` is the one readout
  (``session.metrics_snapshot()``, ``python -m tools.obs_report``): the
  registry's own metrics plus the pre-existing process-wide counters
  folded in at snapshot time (opjit ``cache_stats``, mesh
  ``collective_stats``, the SyncLedger, ``TaskMetricsRegistry``, chaos
  injection counts, shuffle/HBM/spill state) — folding at read time keeps
  their hot paths untouched.

Schema: docs/observability.md "Metrics registry".
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: global off-switch (spark.rapids.tpu.obs.metrics.enabled; session init
#: applies it) — read unlocked on every emission
_ENABLED = True

_N_BUCKETS = 64

_REG_LOCK = threading.Lock()


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Hist:
    """One label set's log2 histogram cell."""

    __slots__ = ("buckets", "count", "total")

    def __init__(self):
        self.buckets = [0] * _N_BUCKETS
        self.count = 0
        self.total = 0.0

    def observe(self, value) -> None:
        v = int(value)
        idx = v.bit_length() if v > 0 else 0
        if idx >= _N_BUCKETS:
            idx = _N_BUCKETS - 1
        self.buckets[idx] += 1
        self.count += 1
        self.total += float(value)

    def quantile(self, q: float) -> float:
        """Upper bucket edge where the cumulative count crosses rank
        ``q * count`` (factor-of-two resolution)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        cum = 0
        for i, n in enumerate(self.buckets):
            cum += n
            if cum >= rank:
                return float(1 << i)
        return float(1 << (_N_BUCKETS - 1))

    def snapshot(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "sum": self.total,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
            "buckets": {f"<{1 << i}": n
                        for i, n in enumerate(self.buckets) if n},
        }


class MetricsRegistry:
    """Process-wide metric store. Engine code uses the module helpers;
    this class is the storage + snapshot."""

    _instance: Optional["MetricsRegistry"] = None

    def __init__(self):
        # name -> {label_key: cell}; counter/gauge cells are one-element
        # lists (in-place adds stay lock-free), histogram cells are _Hist
        self._counters: Dict[str, Dict[Tuple, list]] = {}
        self._gauges: Dict[str, Dict[Tuple, list]] = {}
        self._hists: Dict[str, Dict[Tuple, _Hist]] = {}

    @classmethod
    def get(cls) -> "MetricsRegistry":
        reg = cls._instance
        if reg is None:
            with _REG_LOCK:
                reg = cls._instance
                if reg is None:
                    reg = cls._instance = cls()
        return reg

    @classmethod
    def reset_for_tests(cls) -> "MetricsRegistry":
        global _ENABLED
        with _REG_LOCK:
            cls._instance = cls()
            _ENABLED = True
            return cls._instance

    def _cell(self, table: Dict[str, Dict], name: str, labels, ctor):
        cells = table.get(name)
        key = _label_key(labels)
        if cells is not None:
            cell = cells.get(key)
            if cell is not None:
                return cell
        with _REG_LOCK:
            cells = table.setdefault(name, {})
            cell = cells.get(key)
            if cell is None:
                cell = cells[key] = ctor()
            return cell

    # --- snapshot ----------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with _REG_LOCK:
            counters = {n: {self._fmt(k): c[0] for k, c in cells.items()}
                        for n, cells in self._counters.items()}
            gauges = {n: {self._fmt(k): c[0] for k, c in cells.items()}
                      for n, cells in self._gauges.items()}
            hists = {n: {self._fmt(k): h.snapshot()
                         for k, h in cells.items()}
                     for n, cells in self._hists.items()}
        return {"counters": counters, "gauges": gauges,
                "histograms": hists}

    @staticmethod
    def _fmt(key: Tuple) -> str:
        return ",".join(f"{k}={v}" for k, v in key) if key else ""


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def counter_inc(name: str, value: int = 1, **labels) -> None:
    """Add ``value`` to a monotonic counter (one cell per label set)."""
    if not _ENABLED:
        return
    cell = MetricsRegistry.get()._cell(
        MetricsRegistry.get()._counters, name, labels, lambda: [0])
    cell[0] += value


def gauge_set(name: str, value, **labels) -> None:
    """Set a gauge to the latest value."""
    if not _ENABLED:
        return
    cell = MetricsRegistry.get()._cell(
        MetricsRegistry.get()._gauges, name, labels, lambda: [0])
    cell[0] = value


def gauge_max(name: str, value, **labels) -> None:
    """Raise a high-water gauge to ``value`` if it exceeds the current."""
    if not _ENABLED:
        return
    cell = MetricsRegistry.get()._cell(
        MetricsRegistry.get()._gauges, name, labels, lambda: [0])
    if value > cell[0]:
        cell[0] = value


def histogram_observe(name: str, value, **labels) -> None:
    """Record one observation into a log2-bucket histogram."""
    if not _ENABLED:
        return
    MetricsRegistry.get()._cell(
        MetricsRegistry.get()._hists, name, labels, _Hist).observe(value)


# ---------------------------------------------------------------------------
# query lifecycle: every query (traced or not) registers here — the active-
# query gauge/list, the latency and rows/s histograms, and the epoch the
# tracer's exclusivity check reads all come from this one place.

_QL_LOCK = threading.Lock()
# token -> (name, t0_ns, priority class or None)
_ACTIVE_QUERIES: Dict[int, Tuple[str, int, Optional[str]]] = {}
_EPOCH = 0
_NEXT_TOKEN = 1


def _set_active_gauges_locked() -> None:
    """queries.active total plus one labelled cell per SLO class with an
    active query (docs/serving.md): a dashboard watching
    queries.active{cls=interactive} sees exactly the class the shed
    policy protects. Committed under the lifecycle lock — an interleaved
    begin/end pair must not overwrite a gauge with a stale count."""
    gauge_set("queries.active", len(_ACTIVE_QUERIES))
    by_cls: Dict[str, int] = {}
    for _name, _t0, cls in _ACTIVE_QUERIES.values():
        if cls is not None:
            by_cls[cls] = by_cls.get(cls, 0) + 1
    from ..serving.query_context import PRIORITIES
    for cls in PRIORITIES:
        gauge_set("queries.active", by_cls.get(cls, 0), cls=cls)


def query_begin(name: str, session: str = "default",
                cls: Optional[str] = None) -> int:
    """Register a query start; returns the token for :func:`query_end`.
    `cls` is the SLO priority class (None for lifecycle paths that
    predate classes — counted in the total, not any per-class cell)."""
    global _EPOCH, _NEXT_TOKEN
    with _QL_LOCK:
        _EPOCH += 1
        token = _NEXT_TOKEN
        _NEXT_TOKEN += 1
        _ACTIVE_QUERIES[token] = (name, time.perf_counter_ns(), cls)
        _set_active_gauges_locked()
    from . import flight as _flight
    _flight.note("query.begin", query=name, session=session)
    return token


def query_end(token: int, rows: Optional[int] = None,
              failed: bool = False, session: str = "default") -> None:
    """Close a query: latency/rows-per-s histograms + completion counters.
    Idempotent on an unknown token."""
    with _QL_LOCK:
        entry = _ACTIVE_QUERIES.pop(token, None)
        _set_active_gauges_locked()
    if entry is None:
        return
    name, t0, _cls = entry
    latency_ms = (time.perf_counter_ns() - t0) / 1e6
    counter_inc("queries.failed" if failed else "queries.completed",
                session=session)
    histogram_observe("query.latency_ms", latency_ms, session=session)
    if rows is not None and not failed and latency_ms > 0:
        histogram_observe("query.rows_per_s", rows / (latency_ms / 1e3),
                          session=session)
    from . import flight as _flight
    _flight.note("query.end", query=name, session=session,
                 latency_ms=round(latency_ms, 3), rows=rows, failed=failed)


def active_queries() -> List[str]:
    with _QL_LOCK:
        return [name for name, _t0, _cls in _ACTIVE_QUERIES.values()]


def active_query_count() -> int:
    with _QL_LOCK:
        return len(_ACTIVE_QUERIES)


def query_epoch() -> int:
    """Monotone count of query begins (any session, traced or not) — the
    tracer compares begin/end epochs to decide exclusivity."""
    with _QL_LOCK:
        return _EPOCH


def reset_query_state_for_tests() -> None:
    global _EPOCH, _NEXT_TOKEN
    with _QL_LOCK:
        _ACTIVE_QUERIES.clear()
        _EPOCH = 0
        _NEXT_TOKEN = 1


# ---------------------------------------------------------------------------
# the one readout: registry + pre-existing process-wide counters folded in
# at snapshot time (their hot paths stay untouched)


def hbm_state() -> Dict[str, Any]:
    """HBM budget state without side-effect instantiation (shared by the
    metrics snapshot and the flight recorder's postmortem bundle)."""
    from ..memory.hbm import HbmBudget
    b = HbmBudget._instance
    if b is None:
        return {}
    return {"budget": b.budget, "used": b.used,
            "peak_used": b.peak_used, "alloc_count": b.alloc_count}


def full_snapshot() -> Dict[str, Any]:
    """The registry snapshot plus the engine's other process-wide counters
    (opjit cache stats incl. hit rate, mesh collective_stats, SyncLedger
    totals, task metrics, chaos injections, shuffle bytes, HBM state) —
    ``session.metrics_snapshot()`` and ``tools/obs_report.py`` both serve
    this. Folding never raises: a source that cannot be read reports an
    error string instead."""
    out = MetricsRegistry.get().snapshot()
    out["schema"] = "spark-rapids-tpu/metrics/1"
    out["queries"] = {"active": active_queries(), "epoch": query_epoch()}
    ext: Dict[str, Any] = {}

    def fold(key, fn):
        try:
            ext[key] = fn()
        except Exception as e:  # noqa: BLE001 — a readout must never fail
            ext[key] = {"error": f"{type(e).__name__}: {e}"[:120]}

    def _opjit():
        from ..execs import opjit
        st = opjit.cache_stats()
        calls = st.get("hits", 0) + st.get("misses", 0)
        st["hit_rate"] = round(st.get("hits", 0) / calls, 4) if calls \
            else None
        st["entries"] = opjit.cache_len()
        return st

    def _collective():
        from ..parallel.mesh import collective_stats
        return collective_stats()

    def _mesh_profiles():
        # the mesh efficiency profiler's recent per-exchange records
        # (phase walls + skew tables) and the per-map fallback reasons —
        # the metrics_snapshot() "mesh" readout next to the registry's
        # mesh.* histograms
        from . import mesh_profile
        return {"recent_exchanges": mesh_profile.recent(16),
                "per_map_reasons": mesh_profile.fallback_counts()}

    def _syncs():
        from ..profiling import SyncLedger
        led = SyncLedger.get()
        return {"total": led.total(), "by_op": led.totals_by_op()}

    def _task_metrics():
        from ..profiling import TaskMetricsRegistry
        return TaskMetricsRegistry.get().snapshot()

    def _chaos():
        from ..chaos import FaultInjector
        inj = FaultInjector.get()
        return {"injections": inj.injection_count(),
                "enabled": inj.enabled}

    def _shuffle():
        from ..shuffle.manager import TpuShuffleManager
        mgr = TpuShuffleManager._instance  # no side-effect instantiation
        if mgr is None:
            return {}
        return {"bytes_written": mgr.bytes_written,
                "bytes_read": mgr.bytes_read}

    def _scheduler():
        # the query scheduler's admission state (queued/running names,
        # limits) — docs/robustness.md "Query lifecycle"
        from ..serving.scheduler import QueryScheduler
        s = QueryScheduler._instance  # no side-effect instantiation
        if s is None:
            return {}
        return s.snapshot()

    fold("opjit", _opjit)
    fold("collective", _collective)
    fold("mesh_profiles", _mesh_profiles)
    fold("sync_ledger", _syncs)
    fold("task_metrics", _task_metrics)
    fold("chaos", _chaos)
    fold("shuffle", _shuffle)
    fold("scheduler", _scheduler)
    fold("hbm", hbm_state)
    out["external"] = ext
    return out
