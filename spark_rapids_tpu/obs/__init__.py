"""obs: the query-scoped observability layer (docs/observability.md).

One correlated record per query over dispatch, sync, memory, shuffle,
retry and chaos — a ring-buffered, thread-aware span/event tracer
(:mod:`.tracer`, near-zero-cost when ``spark.rapids.tpu.trace.enabled`` is
off) with three exports from the same record (:mod:`.export`):

* Chrome trace-event JSON (perfetto / ``chrome://tracing``),
* ``session.explain("metrics")`` — the executed plan annotated per node
  (:mod:`.explain`; works with tracing off, from the session snapshots),
* the machine-readable diagnostics bundle
  (``session.last_query_profile()``), whose per-operator dispatch+sync
  counts reconcile against opjit ``calls_by_kind`` and the SyncLedger.

Instrumentation sites in execs//shuffle//memory/ must emit through this
package's :func:`span` / :func:`event` helpers (tracelint rule TL012) and
must never put a blocking device→host sync in a span/event argument.
"""

from .explain import render_explain_metrics
from .export import build_bundle, chrome_trace, span_tree, write_artifacts
from .tracer import (QueryTracer, begin_query, current_span, end_query,
                     event, is_active, span)

__all__ = [
    "QueryTracer", "begin_query", "build_bundle", "chrome_trace",
    "current_span", "end_query", "event", "is_active",
    "render_explain_metrics", "span", "span_tree", "write_artifacts",
]
