"""obs: the production observability plane (docs/observability.md).

Three connected layers over dispatch, sync, memory, shuffle, retry and
chaos:

* **Concurrent per-query tracing** (:mod:`.tracer`, near-zero-cost when
  ``spark.rapids.tpu.trace.enabled`` is off): each query gets its own
  ring-buffered, thread-aware span/event tracer routed by thread-local
  scopes — N sessions trace N queries simultaneously — with three exports
  from the same record (:mod:`.export`): Chrome trace-event JSON
  (perfetto / ``chrome://tracing``), ``session.explain("metrics")``
  (:mod:`.explain`; works with tracing off, from the session snapshots),
  and the diagnostics bundle (``session.last_query_profile()``) whose
  per-operator dispatch+sync counts reconcile against its OWN query's
  ``calls_by_kind``/SyncLedger deltas.
* **Always-on metrics registry** (:mod:`.metrics`): process-wide
  counters, gauges and log2-bucket histograms (query latency p50/p95/p99,
  rows/s, HBM high-water, spill bytes, cache hit rates, retry/chaos
  counts) — ``session.metrics_snapshot()`` / ``python -m
  tools.obs_report``.
* **Crash flight recorder** (:mod:`.flight`): a small always-on ring of
  notable events that dumps a postmortem bundle (last-K events, registry
  snapshot, HBM/semaphore/spill state, active queries) under
  ``spark.rapids.tpu.obs.postmortemDir`` on a fatal device error, an
  exhausted retry, or an HBM OOM.
* **Mesh efficiency profiler** (:mod:`.mesh_profile`): per-collective-
  exchange wall attribution (staging/launch/wait/compact), per-chip skew
  and straggler reporting, "why not collective" fallback reasons, and
  the collective watchdog — the distributed layer over the three above
  (``last_query_profile()['mesh']``, the MULTICHIP bench's
  ``efficiency_attribution``, ``mesh.watchdog_fired``).

Instrumentation sites in execs//shuffle//memory//parallel/ must emit
through this package's :func:`span` / :func:`event` / metric helpers
(tracelint rule TL012) and must never put a blocking device→host sync in
an emission argument.
"""

from .explain import render_explain_metrics
from .export import build_bundle, chrome_trace, span_tree, write_artifacts
from .tracer import (QueryTracer, SpanRef, begin_query, current_span,
                     end_query, event, inherit, is_active, span,
                     thread_traced)
from . import flight, mesh_profile, metrics

__all__ = [
    "QueryTracer", "SpanRef", "begin_query", "build_bundle", "chrome_trace",
    "current_span", "end_query", "event", "flight", "inherit", "is_active",
    "mesh_profile", "metrics", "render_explain_metrics", "span",
    "span_tree", "thread_traced", "write_artifacts",
]
