"""Exports over one raw tracer profile: Chrome trace JSON, the span tree,
and the per-query diagnostics bundle.

Three views of the SAME record (the reference ships these as separate
artifacts — the xprof/NVTX timeline, the Spark SQL UI plan graph, and the
profiler's file dumps; here they are projections of one ring buffer):

* :func:`chrome_trace` — trace-event JSON loadable in perfetto or
  ``chrome://tracing`` (complementing profiling.trace_scope's xprof
  timeline, which sees XLA internals but not engine semantics);
* :func:`span_tree` — the nested query → task → operator → shuffle-map
  structure with per-span instant events;
* :func:`build_bundle` — the machine-readable diagnostics bundle
  (``session.last_query_profile()``), including per-operator dispatch and
  sync counts RECONCILED against the opjit ``calls_by_kind`` delta and the
  SyncLedger delta for the same query — the two pre-existing counters are
  the ground truth, and a mismatch (other than ring-buffer overflow) marks
  the bundle unreconciled rather than silently disagreeing.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from .tracer import (REC_ARGS, REC_CAT, REC_NAME, REC_OP, REC_PARENT,
                     REC_PHASE, REC_SPAN, REC_TID, REC_TS)


def span_tree(profile: Dict[str, Any]) -> Dict[str, Any]:
    """Reconstruct the span tree from the raw ring. Spans whose begin
    record was overwritten (ring overflow) are dropped; spans recorded on
    threads with no open parent attach to the query root."""
    root_id = profile["root"]
    nodes: Dict[int, Dict[str, Any]] = {}
    order: List[int] = []
    for rec in profile["events"]:
        ph = rec[REC_PHASE]
        if ph == "B":
            nodes[rec[REC_SPAN]] = {
                "id": rec[REC_SPAN], "name": rec[REC_NAME],
                "cat": rec[REC_CAT], "op": rec[REC_OP],
                "tid": rec[REC_TID], "t_start_ns": rec[REC_TS],
                "dur_ns": None, "parent": rec[REC_PARENT],
                "args": rec[REC_ARGS] or {}, "children": [], "events": []}
            order.append(rec[REC_SPAN])
        elif ph == "E":
            n = nodes.get(rec[REC_SPAN])
            if n is not None:
                n["dur_ns"] = rec[REC_TS] - n["t_start_ns"]
        else:  # instant
            n = nodes.get(rec[REC_SPAN]) if rec[REC_SPAN] else None
            target = n if n is not None else nodes.get(root_id)
            if target is not None:
                target["events"].append({
                    "name": rec[REC_NAME], "cat": rec[REC_CAT],
                    "op": rec[REC_OP], "t_ns": rec[REC_TS],
                    "args": rec[REC_ARGS] or {}})
    root = nodes.get(root_id)
    if root is None:  # root begin overwritten: synthesize
        root = {"id": root_id, "name": profile.get("name", "query"),
                "cat": "query", "op": None, "tid": None, "t_start_ns": 0,
                "dur_ns": profile.get("duration_ns"), "parent": None,
                "args": {}, "children": [], "events": []}
        nodes[root_id] = root
    for sid in order:
        if sid == root_id:
            continue
        n = nodes[sid]
        parent = nodes.get(n["parent"]) if n["parent"] is not None else None
        (parent if parent is not None else root)["children"].append(n)
    for n in nodes.values():
        n.pop("parent", None)
    return root


#: pid of the synthesized per-device track group in the Chrome trace (the
#: engine's real threads render under pid 1)
MESH_DEVICE_PID = 2


def _mesh_tracks(profile: Dict[str, Any]) -> tuple:
    """Synthesize the multi-chip view from the SAME ring record: one track
    per device (pid ``MESH_DEVICE_PID``, tid = device index) with the
    collective wait of every exchange as an "X" complete event ALIGNED
    across tracks (the wait is the fabric barrier: every chip is in it
    together), plus flow events ("s"/"f", id = the exchange's profile seq)
    tying each producer ``mesh.profile`` record to its consumer
    ``mesh.read`` events. Emitted through the existing tracer records, so
    concurrent-query routing needs no new machinery — a query's trace
    only ever contains its own exchanges. Returns (events, device_ids)."""
    evs: List[Dict[str, Any]] = []
    devices: set = set()
    reads: Dict[int, List[Tuple[float, int]]] = {}  # seq -> [(ts_us, tid)]
    for rec in profile["events"]:
        if rec[REC_PHASE] != "i" or rec[REC_NAME] != "mesh.read":
            continue
        args = rec[REC_ARGS] or {}
        seq = args.get("exchange_seq")
        if seq is not None:
            reads.setdefault(int(seq), []).append(
                (rec[REC_TS] / 1e3, rec[REC_TID]))
    for rec in profile["events"]:
        if rec[REC_PHASE] != "i" or rec[REC_NAME] != "mesh.profile":
            continue
        args = rec[REC_ARGS] or {}
        phases = args.get("phases_ms") or {}
        n_dev = int(args.get("n_dev", 0))
        seq = args.get("exchange_seq")
        if not n_dev or seq is None:
            continue
        # the profile event is recorded at the end of compact: walk back
        # through the phase walls to place the aligned wait window
        end_us = rec[REC_TS] / 1e3
        compact_us = float(phases.get("compact", 0.0)) * 1e3
        wait_us = float(phases.get("collective_wait", 0.0)) * 1e3
        wait_end = end_us - compact_us
        wait_start = max(0.0, wait_end - wait_us)
        recv = args.get("recv_rows") or []
        skew = args.get("skew") or {}
        name = f"collective s{args.get('shuffle', '?')}"
        for d in range(n_dev):
            devices.add(d)
            dev_args = {"exchange_seq": seq,
                        "rows_recv": recv[d] if d < len(recv) else None}
            if skew.get("straggler_chip") == d:
                dev_args["straggler"] = True
            evs.append({"ph": "X", "name": name, "cat": "mesh",
                        "ts": wait_start, "dur": max(wait_us, 1.0),
                        "pid": MESH_DEVICE_PID, "tid": d,
                        "args": dev_args})
        # producer→consumer flows: anchor the start on the producing
        # thread inside the exchange span, finish at each consumer read
        consumers = reads.get(int(seq), [])
        if consumers:
            evs.append({"ph": "s", "id": int(seq), "name": "mesh.flow",
                        "cat": "mesh", "ts": end_us, "pid": 1,
                        "tid": rec[REC_TID]})
            for ts_us, tid in consumers:
                evs.append({"ph": "f", "bp": "e", "id": int(seq),
                            "name": "mesh.flow", "cat": "mesh",
                            "ts": max(ts_us, end_us), "pid": 1,
                            "tid": tid})
    return evs, sorted(devices)


def chrome_trace(profile: Dict[str, Any],
                 process_name: str = "spark-rapids-tpu") -> Dict[str, Any]:
    """Chrome trace-event JSON (the "JSON object format"): open in perfetto
    (ui.perfetto.dev → Open trace) or chrome://tracing. B/E pairs are
    emitted per thread in record order, which our per-thread span stacks
    guarantee to be properly nested. Queries that rode the mesh data plane
    additionally render one track per DEVICE (process "mesh devices") with
    the collective wait of every exchange aligned across tracks and flow
    arrows from producer exchange to consumer read
    (docs/observability.md "Mesh profiling")."""
    evs: List[Dict[str, Any]] = []
    tids = set()
    opened = set()
    for rec in profile["events"]:
        ph = rec[REC_PHASE]
        ts_us = rec[REC_TS] / 1e3
        tids.add(rec[REC_TID])
        if ph == "B":
            opened.add(rec[REC_SPAN])
            args = dict(rec[REC_ARGS] or {})
            if rec[REC_OP]:
                args.setdefault("op", rec[REC_OP])
            evs.append({"ph": "B", "name": rec[REC_NAME],
                        "cat": rec[REC_CAT], "ts": ts_us, "pid": 1,
                        "tid": rec[REC_TID], "args": args})
        elif ph == "E":
            # ring overflow can evict a long-lived span's B while its E
            # survives; a stray E would pop the wrong slice in the viewer
            # (same orphan handling as span_tree)
            if rec[REC_SPAN] not in opened:
                continue
            evs.append({"ph": "E", "ts": ts_us, "pid": 1,
                        "tid": rec[REC_TID]})
        else:
            args = dict(rec[REC_ARGS] or {})
            if rec[REC_OP]:
                args.setdefault("op", rec[REC_OP])
            evs.append({"ph": "i", "s": "t", "name": rec[REC_NAME],
                        "cat": rec[REC_CAT], "ts": ts_us, "pid": 1,
                        "tid": rec[REC_TID], "args": args})
    mesh_evs, device_ids = _mesh_tracks(profile)
    meta = [{"ph": "M", "name": "process_name", "pid": 1,
             "args": {"name": process_name}}]
    meta += [{"ph": "M", "name": "thread_name", "pid": 1, "tid": t,
              "args": {"name": f"thread-{t}"}} for t in sorted(tids)]
    if device_ids:
        meta.append({"ph": "M", "name": "process_name",
                     "pid": MESH_DEVICE_PID,
                     "args": {"name": "mesh devices"}})
        meta += [{"ph": "M", "name": "thread_name",
                  "pid": MESH_DEVICE_PID, "tid": d,
                  "args": {"name": f"device-{d}"}} for d in device_ids]
    return {"traceEvents": meta + evs + mesh_evs,
            "displayTimeUnit": "ms",
            "otherData": {"query": profile.get("name"),
                          "dropped_events": profile.get("dropped", 0)}}


def _counts(profile: Dict[str, Any]):
    """Aggregate instant events: (by_operator, dispatch_by_kind, sync_total,
    event_counts_by_cat, chaos_events, retry_events)."""
    by_op: Dict[str, Dict[str, Dict[str, int]]] = {}
    disp_by_kind: Dict[str, int] = {}
    by_cat: Dict[str, int] = {}
    chaos: List[Dict[str, Any]] = []
    retries: List[Dict[str, Any]] = []
    sync_total = 0
    for rec in profile["events"]:
        if rec[REC_PHASE] != "i":
            continue
        cat = rec[REC_CAT]
        by_cat[cat] = by_cat.get(cat, 0) + 1
        args = rec[REC_ARGS] or {}
        op = rec[REC_OP] or "<unattributed>"
        slot = by_op.setdefault(op, {})
        if cat == "dispatch":
            kind = str(args.get("kind", "?"))
            d = slot.setdefault("dispatches", {})
            d[kind] = d.get(kind, 0) + 1
            c = slot.setdefault("dispatch_cache", {})
            hit = str(args.get("cache", "?"))
            c[hit] = c.get(hit, 0) + 1
            if args.get("source") == "opjit" or args.get("cache") == "extern":
                # "extern" = launches recorded into calls_by_kind from
                # outside the opjit cache (opjit.record_external_dispatch,
                # e.g. the parquet device-decode programs) — they must
                # count here too or reconciliation would always fail
                disp_by_kind[kind] = disp_by_kind.get(kind, 0) + 1
        elif cat == "sync":
            kind = str(args.get("kind", "?"))
            s = slot.setdefault("syncs", {})
            s[kind] = s.get(kind, 0) + 1
            sync_total += 1
        elif cat == "chaos":
            chaos.append({"span": rec[REC_SPAN], "op": op,
                          "t_ns": rec[REC_TS], **args})
        elif cat == "retry":
            retries.append({"span": rec[REC_SPAN], "op": op,
                            "t_ns": rec[REC_TS], **args})
        else:
            e = slot.setdefault("events", {})
            e[rec[REC_NAME]] = e.get(rec[REC_NAME], 0) + 1
    return by_op, disp_by_kind, sync_total, by_cat, chaos, retries


def build_bundle(profile: Dict[str, Any],
                 plan_tree: Optional[List[Dict[str, Any]]] = None,
                 metrics: Optional[Dict[str, Dict[str, int]]] = None,
                 sync_ledger: Optional[Dict[str, Dict[str, int]]] = None,
                 dispatch_delta: Optional[Dict[str, int]] = None,
                 task_metrics: Optional[Dict[str, int]] = None,
                 mesh_profiles: Optional[List[Dict[str, Any]]] = None,
                 mesh_fallbacks: Optional[List[Dict[str, Any]]] = None,
                 mesh_dropped: int = 0) -> Dict[str, Any]:
    """The machine-readable per-query diagnostics bundle
    (docs/observability.md "Bundle schema"). `sync_ledger` and
    `dispatch_delta` are the SAME-query deltas of the SyncLedger and of
    opjit ``cache_stats()["calls_by_kind"]`` — the bundle's own event
    counts must reconcile with them exactly unless the ring overflowed.
    `mesh_profiles` / `mesh_fallbacks` are this query's collective-
    exchange records (obs/mesh_profile.py) — present only for queries
    that ran on a mesh session."""
    by_op, disp_by_kind, sync_total, by_cat, chaos, retries = \
        _counts(profile)
    dropped = int(profile.get("dropped", 0))
    # exclusive: no other query (traced or not) overlapped this one, so
    # process-wide counter deltas were attributable; when False the caller
    # passed the tracer's own per-query counters instead (obs/tracer.py)
    reconcile: Dict[str, Any] = {
        "overflow": dropped > 0,
        "exclusive": bool(profile.get("exclusive", True))}
    if dispatch_delta is not None:
        want = {k: v for k, v in dispatch_delta.items() if v}
        reconcile["dispatch_ok"] = dropped > 0 or disp_by_kind == want
        reconcile["dispatch_expected"] = want
    if sync_ledger is not None:
        want_syncs = {op: dict(kinds) for op, kinds in sync_ledger.items()}
        got_syncs = {op: slot["syncs"] for op, slot in by_op.items()
                     if slot.get("syncs")}
        reconcile["sync_ok"] = dropped > 0 or got_syncs == want_syncs
        reconcile["sync_total_expected"] = sum(
            sum(k.values()) for k in want_syncs.values())
    bundle = {
        "schema": "spark-rapids-tpu/query-profile/1",
        "query": profile.get("name"),
        "duration_ms": round(profile.get("duration_ns", 0) / 1e6, 3),
        "dropped_events": dropped,
        "event_counts": by_cat,
        "spans": span_tree(profile),
        "plan": plan_tree or [],
        "metrics": metrics or {},
        "task_metrics": task_metrics or {},
        "by_operator": by_op,
        "dispatches_by_kind": disp_by_kind,
        "sync_events_total": sync_total,
        "chaos_events": chaos,
        "retry_events": retries,
        "reconcile": reconcile,
    }
    if mesh_profiles or mesh_fallbacks or mesh_dropped:
        # mesh section (docs/observability.md "Mesh profiling"): the
        # per-exchange phase breakdown + skew table, the worst-imbalance
        # exchange, the per-map fallback reason counts, and the count of
        # records the bounded profiler rings evicted inside this query's
        # window (never presented as a complete set when it is not)
        reasons: Dict[str, int] = {}
        for f in mesh_fallbacks or []:
            reasons[f["reason"]] = reasons.get(f["reason"], 0) + 1
        worst = max((p for p in mesh_profiles or []),
                    key=lambda p: p["skew"]["imbalance"], default=None)
        bundle["mesh"] = {
            "exchanges": list(mesh_profiles or []),
            "per_map_reasons": reasons,
            "skew_worst": None if worst is None else {
                "exchange": worst["exchange"], "seq": worst["seq"],
                **worst["skew"]},
            "watchdog_fired": any(p.get("watchdog_fired")
                                  for p in mesh_profiles or []),
            "dropped_records": int(mesh_dropped),
        }
    return bundle


def write_artifacts(bundle: Dict[str, Any], profile: Dict[str, Any],
                    out_dir: str, stem: str) -> Dict[str, str]:
    """Write the Chrome trace and the bundle JSON under ``out_dir``;
    returns {"chrome_trace": path, "bundle": path} (also recorded inside
    the bundle as ``artifacts``)."""
    import os
    os.makedirs(out_dir, exist_ok=True)
    trace_path = os.path.join(out_dir, f"{stem}.trace.json")
    bundle_path = os.path.join(out_dir, f"{stem}.profile.json")
    with open(trace_path, "w") as f:
        json.dump(chrome_trace(profile), f)
    paths = {"chrome_trace": trace_path, "bundle": bundle_path}
    bundle["artifacts"] = paths
    with open(bundle_path, "w") as f:
        json.dump(bundle, f, default=str)
    return paths
