"""Crash flight recorder: a small always-on ring of recent notable events
plus the postmortem bundle written when a query dies hard.

Reference: the plugin's GpuCoreDumpHandler captures a device core dump to
distributed storage before the executor exits (GpuCoreDumpHandler.scala) —
the incident artifact exists even though nobody was profiling. Today a
fatal device error, an exhausted transient retry, or an HBM OOM here
leaves only a stack trace; this module turns those into actionable
artifacts:

* :func:`note` — an always-on, bounded ring (``deque(maxlen=...)``,
  conf ``spark.rapids.tpu.obs.flightRecorderEvents``) of RARE, notable
  events: query begin/end, chaos injections, device retries, HBM
  pressure/OOM, disk spills, shuffle fetch retries, fatal failures. It is
  independent of any traced query (the per-query tracer may be off or may
  belong to a different query); each note self-tags with the calling
  thread's traced query name when one is bound. The per-batch hot path
  never notes — idle cost is zero, and a note is one lock-guarded append.
* :func:`postmortem` — on a fatal device error
  (``failure.handle_task_failure``), an exhausted transient retry
  (``failure.with_device_retry``) or a genuine HBM budget OOM
  (``memory/hbm.py``), dump one JSON bundle under
  ``spark.rapids.tpu.obs.postmortemDir``: the last-K flight events, the
  full metrics-registry snapshot, HBM / semaphore / spill-store state, the
  active query names, and the failure itself. Writing never raises and
  never masks the original error.

Schema: docs/observability.md "Postmortem bundle".
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

_DEFAULT_RING = 512

_LOCK = threading.Lock()
_RING: deque = deque(maxlen=_DEFAULT_RING)
_SEQ = 0
#: process-wide postmortem output dir (session init applies the conf, the
#: same arm-once pattern as chaos.FaultInjector.maybe_configure); failure
#: sites have no session handle
_POSTMORTEM_DIR: Optional[str] = None


def maybe_configure(conf) -> None:
    """Apply ``spark.rapids.tpu.obs.*`` flight-recorder settings from a
    session's conf (ring size, postmortem dir) — called at session init."""
    global _RING, _POSTMORTEM_DIR
    from ..config import OBS_FLIGHT_EVENTS, OBS_POSTMORTEM_DIR
    size = max(16, int(conf.get(OBS_FLIGHT_EVENTS)))
    pdir = conf.get(OBS_POSTMORTEM_DIR)
    with _LOCK:
        if size != _RING.maxlen:
            _RING = deque(_RING, maxlen=size)
        if pdir and str(pdir) != "None":
            _POSTMORTEM_DIR = str(pdir)


def reset_for_tests() -> None:
    global _RING, _SEQ, _POSTMORTEM_DIR
    with _LOCK:
        _RING = deque(maxlen=_DEFAULT_RING)
        _SEQ = 0
        _POSTMORTEM_DIR = None


def note(event: str, **fields) -> None:
    """Append one notable event to the always-on ring. Call only at RARE
    sites (faults, retries, pressure, spill-to-disk, query lifecycle) —
    never per batch. Field values must already be host scalars (the same
    no-blocking-sync rule as tracer events, tracelint TL012)."""
    global _SEQ
    from .tracer import current_query_name
    q = current_query_name()
    if q is not None:
        fields.setdefault("query", q)
    rec = {"seq": 0, "ts": time.time(),
           "thread": threading.current_thread().name, "event": event,
           **fields}
    with _LOCK:
        _SEQ += 1
        rec["seq"] = _SEQ
        _RING.append(rec)


def snapshot(last_k: Optional[int] = None) -> List[Dict[str, Any]]:
    with _LOCK:
        recs = list(_RING)
    return recs[-last_k:] if last_k else recs


def _engine_state() -> Dict[str, Any]:
    """HBM / semaphore / spill-store state for the bundle; each source
    folds independently and never raises (the process may be dying)."""
    state: Dict[str, Any] = {}

    def fold(key, fn):
        try:
            state[key] = fn()
        except Exception as e:  # noqa: BLE001 — a dump must never fail
            state[key] = {"error": f"{type(e).__name__}: {e}"[:120]}

    def _sem():
        from ..memory.semaphore import TpuSemaphore
        s = TpuSemaphore._instance
        if s is None:
            return {}
        with s._state_lock:
            holders, shared = len(s._holders), len(s._shared)
        return {"permits": s.permits, "holders": holders,
                "shared_riders": shared,
                "total_waits_ns": s.total_waits_ns}

    def _spill():
        from ..memory.spill import TpuBufferCatalog
        c = TpuBufferCatalog._instance
        if c is None:
            return {}
        return {"host_used": c.host_used,
                "spilled_to_host": c.spilled_to_host,
                "spilled_to_disk": c.spilled_to_disk}

    def _sched():
        from ..serving.scheduler import QueryScheduler
        s = QueryScheduler._instance
        if s is None:
            return {}
        # a postmortem must NAME the queries that were queued, running
        # or cancelling when the process died (docs/robustness.md
        # "Query lifecycle")
        return s.snapshot()

    from . import metrics as _metrics
    fold("hbm", _metrics.hbm_state)
    fold("semaphore", _sem)
    fold("spill", _spill)
    fold("scheduler", _sched)
    return state


def build_postmortem(reason: str, exc: Optional[BaseException] = None,
                     last_k: int = 256) -> Dict[str, Any]:
    """Assemble the postmortem bundle as plain data (the write path and
    tests share this)."""
    from . import metrics as _metrics
    bundle: Dict[str, Any] = {
        "schema": "spark-rapids-tpu/postmortem/1",
        "reason": reason,
        "timestamp": time.time(),
        "active_queries": _metrics.active_queries(),
        "flight_events": snapshot(last_k),
        "engine_state": _engine_state(),
    }
    if exc is not None:
        bundle["error_type"] = type(exc).__name__
        bundle["error"] = str(exc)
        bundle["traceback"] = traceback.format_exception(
            type(exc), exc, exc.__traceback__)
    try:
        bundle["metrics"] = _metrics.full_snapshot()
    except Exception as e:  # noqa: BLE001 — a dump must never fail
        bundle["metrics"] = {"error": f"{type(e).__name__}: {e}"[:120]}
    return bundle


def postmortem(reason: str, exc: Optional[BaseException] = None,
               conf=None) -> Optional[str]:
    """Write the postmortem bundle under the configured dir (conf argument
    wins over the session-armed process-wide dir). Returns the written
    path, or None when no dir is configured. Never raises — the caller is
    already handling a failure and this must not mask it."""
    try:
        out_dir = None
        if conf is not None:
            from ..config import OBS_POSTMORTEM_DIR
            d = conf.get(OBS_POSTMORTEM_DIR)
            if d and str(d) != "None":
                out_dir = str(d)
        if out_dir is None:
            out_dir = _POSTMORTEM_DIR
        if not out_dir:
            return None
        bundle = build_postmortem(reason, exc)
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"postmortem-{reason}-{int(time.time() * 1000)}.json")
        with open(path, "w") as f:
            json.dump(bundle, f, indent=2, default=str)
        note("postmortem.written", reason=reason, path=path)
        return path
    except Exception:  # noqa: BLE001 — never mask the original failure
        return None
