"""Mesh efficiency profiler: per-exchange wall attribution, skew and
straggler reporting, and the collective watchdog.

MULTICHIP_r06 measured scaling efficiency 0.05–0.11 on the 8-device mesh
with collectives only 10–28% of wall — meaning most of the wall was
UNATTRIBUTED (host staging? launch overhead? compact? partition skew?
idle chips?). The reference stack treats shuffle-transport visibility as
a first-class subsystem (per-peer/per-block accounting around
``RapidsShuffleHeartbeatManager``, SURVEY §2.7); this module is that
layer for the collective data plane:

* **Per-exchange profiles** — every collective exchange records a
  :data:`MeshExchangeProfile`-shaped dict (exchange id, per-chip send /
  recv rows and bytes from the already-synced sizing counters — ZERO new
  device syncs — plus the phase walls: host staging, program launch,
  collective wait, per-shard compact) into a bounded process-wide ring.
  The session folds the profiles recorded during one query into the
  diagnostics bundle's ``mesh`` section (``last_query_profile()``), the
  always-on registry folds the recent ring into
  ``session.metrics_snapshot()``, and ``parallel/sharded.py`` /
  ``benchmarks/multichip.py`` turn them into the MULTICHIP round's
  ``efficiency_attribution`` breakdown.
* **Skew metrics** — per profile: max / median per-chip received rows,
  the imbalance factor (max/median), and the straggler chip id when one
  chip's share exceeds ``spark.rapids.tpu.obs.meshStragglerFactor`` × the
  median (per-chip rows are the exact host-known proxy for that chip's
  downstream work — the wait of everyone else). Registry histograms
  ``mesh.skew_imbalance`` (imbalance × 100, log2 buckets) and
  ``mesh.straggler_wait_ms`` (the collective wait of exchanges where a
  straggler was detected) feed serving dashboards.
* **"Why not collective" reasons** — when the planner or the exchange
  routes a mesh-session exchange per-map (string payload, misaligned
  partitions, conf off, staging OOM), :func:`record_fallback` counts the
  reason (``mesh.per_map_exchange{reason=…}``) and keeps it for the
  multichip summary and ``explain("metrics")``.
* **Collective watchdog** — on real hardware a hung chip manifests
  exactly as an unbounded collective wait, indistinguishable from a slow
  one. :func:`collective_watchdog` arms a timer around the launch+wait
  window: past ``spark.rapids.tpu.obs.collectiveWatchdogMs`` it emits a
  flight-recorder event + the ``mesh.watchdog_fired`` counter WHILE the
  wait is still blocked; past ``…collectiveWatchdogFatalMs`` (when set)
  it dumps a postmortem bundle so the incident artifact exists even if
  the process never returns from the wait.

Emission discipline is the same TL012 contract as the rest of the plane:
every value recorded here is a host scalar the collective already holds
(the sizing counters and ``perf_counter`` walls) — the profiler adds no
device round trip to the hot path, asserted by
``tests/test_mesh_profile.py``.

Schema: docs/observability.md "Mesh profiling".
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_RING_SIZE = 256

_LOCK = threading.Lock()
#: recording switch (tests toggle it to prove zero hot-path impact); the
#: watchdog is configured independently via the conf thresholds
_ENABLED = True
_SEQ = 0
_PROFILES: deque = deque(maxlen=_RING_SIZE)
_FALLBACKS: deque = deque(maxlen=_RING_SIZE)

#: watchdog / skew thresholds — armed once at session init
#: (maybe_configure, the flight-recorder pattern: the exchange hot path
#: has no session handle)
_WATCHDOG_MS = 30000.0
_WATCHDOG_FATAL_MS = 0.0
_STRAGGLER_FACTOR = 2.0


def maybe_configure(conf) -> None:
    """Apply the collective-watchdog thresholds and the straggler factor
    from a session's conf — called at session init (same arm-once pattern
    as ``flight.maybe_configure``: only EXPLICITLY SET keys overwrite the
    process state, so constructing a default-conf session never silently
    resets another live session's thresholds)."""
    global _WATCHDOG_MS, _WATCHDOG_FATAL_MS, _STRAGGLER_FACTOR
    from ..config import (OBS_COLLECTIVE_WATCHDOG_FATAL_MS,
                          OBS_COLLECTIVE_WATCHDOG_MS,
                          OBS_MESH_STRAGGLER_FACTOR)
    with _LOCK:
        if conf.get_raw(OBS_COLLECTIVE_WATCHDOG_MS.key) is not None:
            _WATCHDOG_MS = float(conf.get(OBS_COLLECTIVE_WATCHDOG_MS))
        if conf.get_raw(OBS_COLLECTIVE_WATCHDOG_FATAL_MS.key) is not None:
            _WATCHDOG_FATAL_MS = float(
                conf.get(OBS_COLLECTIVE_WATCHDOG_FATAL_MS))
        if conf.get_raw(OBS_MESH_STRAGGLER_FACTOR.key) is not None:
            _STRAGGLER_FACTOR = max(1.0, float(
                conf.get(OBS_MESH_STRAGGLER_FACTOR)))


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def reset_for_tests() -> None:
    global _ENABLED, _SEQ, _WATCHDOG_MS, _WATCHDOG_FATAL_MS, \
        _STRAGGLER_FACTOR
    with _LOCK:
        _ENABLED = True
        _SEQ = 0
        _PROFILES.clear()
        _FALLBACKS.clear()
        _WATCHDOG_MS = 30000.0
        _WATCHDOG_FATAL_MS = 0.0
        _STRAGGLER_FACTOR = 2.0


def current_seq() -> int:
    """Monotone count of recorded exchange profiles — snapshot before a
    query, pass to :func:`profiles_since` after (the same windowing idiom
    as the session's counter deltas)."""
    with _LOCK:
        return _SEQ


def alloc_seq() -> int:
    """Pre-allocate the next profile's sequence id so the ``mesh.exchange``
    span and the consumer-read flow events can reference it before the
    profile itself is recorded (the Chrome-trace pairing key)."""
    global _SEQ
    with _LOCK:
        _SEQ += 1
        return _SEQ


def profiles_since(seq: int, query: Optional[str] = None
                   ) -> List[Dict[str, Any]]:
    """Profiles recorded after sequence ``seq``; when ``query`` is given,
    keep ONLY profiles tagged with that traced query name. The filter is
    strict: a traced query's exchanges always materialize on a tracer-
    bound thread so its own profiles are tagged, and accepting untagged
    (query=None) records would absorb a concurrent UNTRACED query's
    exchanges into this query's bundle (cross-query bleed — the exact
    failure the PR 12 routing exists to prevent)."""
    with _LOCK:
        out = [p for p in _PROFILES if p["seq"] > seq]
    if query is not None:
        out = [p for p in out if p.get("query") == query]
    return out


def fallbacks_since(seq: int, query: Optional[str] = None
                    ) -> List[Dict[str, Any]]:
    with _LOCK:
        out = [f for f in _FALLBACKS if f["seq"] > seq]
    if query is not None:
        out = [f for f in out if f.get("query") == query]
    return out


def window_dropped(seq: int) -> int:
    """How many records (profiles + fallbacks) sequenced after ``seq``
    have already been evicted from the bounded rings — callers report the
    count instead of presenting a silently truncated window as complete.
    (Sequence ids are allocated across both rings, so the count is exact
    while recording is enabled.)"""
    with _LOCK:
        have = sum(1 for p in _PROFILES if p["seq"] > seq) \
            + sum(1 for f in _FALLBACKS if f["seq"] > seq)
        return max(0, _SEQ - seq - have)


def recent(last_k: int = 16) -> List[Dict[str, Any]]:
    """The most recent profiles (``metrics_snapshot()`` /
    ``tools/obs_report.py --mesh`` readout)."""
    with _LOCK:
        recs = list(_PROFILES)
    return recs[-last_k:]


def fallback_counts() -> Dict[str, int]:
    """{reason: count} over the fallback ring."""
    with _LOCK:
        recs = list(_FALLBACKS)
    out: Dict[str, int] = {}
    for f in recs:
        out[f["reason"]] = out.get(f["reason"], 0) + 1
    return out


def skew_stats(recv_rows: List[int], factor: Optional[float] = None
               ) -> Dict[str, Any]:
    """Skew metrics over one exchange's per-chip received-row counts (all
    host-known from the sizing sync): max, median, the imbalance factor
    (max/median — 1.0 is perfectly balanced) and the straggler chip id
    when the heaviest chip exceeds ``factor`` × the median."""
    if factor is None:
        factor = _STRAGGLER_FACTOR
    n = len(recv_rows)
    if n == 0 or not any(recv_rows):
        return {"max_rows": 0, "median_rows": 0, "imbalance": 1.0,
                "straggler_chip": None}
    ordered = sorted(recv_rows)
    mid = n // 2
    median = (ordered[mid] if n % 2
              else (ordered[mid - 1] + ordered[mid]) / 2.0)
    mx = max(recv_rows)
    # a zero median with a non-zero max is the worst skew there is: the
    # imbalance reports max vs the next-best denominator (1 row)
    imbalance = mx / max(float(median), 1.0)
    straggler = recv_rows.index(mx) \
        if mx > factor * max(float(median), 1.0) else None
    return {"max_rows": int(mx), "median_rows": float(median),
            "imbalance": round(float(imbalance), 3),
            "straggler_chip": straggler}


def record_exchange(seq: int, shuffle_id: int, partitioning: str,
                    n_dev: int, send_rows: List[int], recv_rows: List[int],
                    recv_bytes: List[int], stage_ns: int, launch_ns: int,
                    wait_ns: int, compact_ns: int,
                    watchdog_fired: bool = False,
                    compact_fused: bool = False,
                    staging_reuse_hits: int = 0,
                    overlap_segments: int = 0
                    ) -> Optional[Dict[str, Any]]:
    """Record one collective exchange's profile. Every argument is a host
    value the collective already computed (the sizing counters and the
    ``perf_counter`` walls) — recording adds zero device syncs. Returns
    the profile dict (also appended to the ring), or None when recording
    is disabled."""
    if not _ENABLED:
        return None
    from . import metrics as _metrics
    from .tracer import current_query_name
    wait_ms = wait_ns / 1e6
    skew = skew_stats(list(recv_rows))
    profile: Dict[str, Any] = {
        "seq": seq,
        "exchange": shuffle_id,
        "partitioning": partitioning,
        "n_dev": n_dev,
        "query": current_query_name(),
        "ts": time.time(),
        "send_rows": [int(x) for x in send_rows],
        "recv_rows": [int(x) for x in recv_rows],
        "recv_bytes": [int(x) for x in recv_bytes],
        "phases_ms": {
            "staging": round(stage_ns / 1e6, 3),
            "launch": round(launch_ns / 1e6, 3),
            "collective_wait": round(wait_ms, 3),
            "compact": round(compact_ns / 1e6, 3),
        },
        "skew": skew,
        "watchdog_fired": bool(watchdog_fired),
        # r07 fused dataplane keys (docs/distributed.md "Fused compact &
        # overlap"): whether the post-collective compact ran inside the
        # collective dispatch, how many staged pad pieces came from the
        # staging pool, and the segment count when the exchange rode the
        # overlapped path (0 = unsegmented)
        "compact_fused": bool(compact_fused),
        "staging_reuse_hits": int(staging_reuse_hits),
        "overlap_segments": int(overlap_segments),
    }
    # registry histograms (docs/observability.md "Mesh profiling"):
    # imbalance ×100 so the log2 buckets resolve 1.28x from 2.56x from
    # 5.12x; straggler_wait_ms only for exchanges where a straggler was
    # actually detected — its p95 is the "how much wall does skew cost"
    # dashboard number
    _metrics.histogram_observe("mesh.skew_imbalance",
                               skew["imbalance"] * 100.0)
    if skew["straggler_chip"] is not None:
        _metrics.histogram_observe("mesh.straggler_wait_ms", wait_ms)
    with _LOCK:
        _PROFILES.append(profile)
    return profile


def record_fallback(shuffle_id: int, reason: str) -> None:
    """One mesh-session exchange routed per-map instead of riding the
    collective: count the reason (``mesh.per_map_exchange{reason=…}``)
    and keep it for the multichip summary / diagnostics bundle."""
    global _SEQ
    if not _ENABLED:
        return
    from . import metrics as _metrics
    from .tracer import current_query_name
    _metrics.counter_inc("mesh.per_map_exchange", reason=reason)
    with _LOCK:
        _SEQ += 1
        _FALLBACKS.append({"seq": _SEQ, "exchange": shuffle_id,
                           "reason": str(reason),
                           "query": current_query_name(),
                           "ts": time.time()})


class collective_watchdog:
    """Context manager arming the collective watchdog around one
    launch+wait window. Timers fire on daemon threads WHILE the wait is
    still blocked — the only vantage point that can tell a hung chip
    (unbounded wait) from a slow one:

    * at ``collectiveWatchdogMs``: flight-recorder event
      (``mesh.watchdog``) + ``mesh.watchdog_fired`` registry counter, and
      the profile records ``watchdog_fired`` when the exchange eventually
      completes;
    * at ``collectiveWatchdogFatalMs`` (when > 0): a postmortem bundle
      under ``spark.rapids.tpu.obs.postmortemDir`` — the incident
      artifact exists even if the process never returns from the wait.

    Both timers cancel on a timely exit; a watchdog with threshold 0 is
    disabled and arms nothing."""

    __slots__ = ("_shuffle", "_n_dev", "_query", "_t0", "_timer",
                 "_fatal_timer", "fired", "fatal_fired")

    def __init__(self, shuffle_id: int, n_dev: int):
        self._shuffle = shuffle_id
        self._n_dev = n_dev
        self._query = None
        self._t0 = 0.0
        self._timer: Optional[threading.Timer] = None
        self._fatal_timer: Optional[threading.Timer] = None
        self.fired = False
        self.fatal_fired = False

    def __enter__(self) -> "collective_watchdog":
        from .tracer import current_query_name
        # captured on the exchange thread: the timer threads have no
        # tracer binding, so the flight note tags the query explicitly
        self._query = current_query_name()
        self._t0 = time.perf_counter()
        if _WATCHDOG_MS > 0:
            self._timer = threading.Timer(_WATCHDOG_MS / 1e3, self._trip)
            self._timer.daemon = True
            self._timer.start()
        if _WATCHDOG_FATAL_MS > 0:
            self._fatal_timer = threading.Timer(_WATCHDOG_FATAL_MS / 1e3,
                                                self._fatal)
            self._fatal_timer.daemon = True
            self._fatal_timer.start()
        return self

    def _waited_ms(self) -> float:
        return round((time.perf_counter() - self._t0) * 1e3, 1)

    def _trip(self) -> None:
        from . import flight as _flight
        from . import metrics as _metrics
        self.fired = True
        _metrics.counter_inc("mesh.watchdog_fired")
        _flight.note("mesh.watchdog", shuffle=self._shuffle,
                     n_dev=self._n_dev, waited_ms=self._waited_ms(),
                     threshold_ms=_WATCHDOG_MS,
                     query=self._query or "<untraced>")

    def _fatal(self) -> None:
        from . import flight as _flight
        from . import metrics as _metrics
        self.fatal_fired = True
        _metrics.counter_inc("mesh.watchdog_fatal")
        _flight.note("mesh.watchdog_fatal", shuffle=self._shuffle,
                     waited_ms=self._waited_ms(),
                     threshold_ms=_WATCHDOG_FATAL_MS,
                     query=self._query or "<untraced>")
        _flight.postmortem("collective_watchdog")

    def __exit__(self, *exc) -> bool:
        if self._timer is not None:
            self._timer.cancel()
        if self._fatal_timer is not None:
            self._fatal_timer.cancel()
        return False
