"""Concurrent per-query span/event tracers: the correlated record of each
query, N queries at a time.

Reference (PAPER.md §5): the plugin wraps every operator in NVTX ranges
(NvtxWithMetrics.scala), ships a built-in sampled profiler
(profiler.scala:37) and surfaces leveled SQLMetrics in the Spark SQL UI
(GpuExec.scala:41) — and it does so for every concurrently running query,
because the metrics sinks are per-execution, not a process singleton. This
module is that layer for the TPU engine:

* a **per-query tracer object** — each ``begin_query`` creates its own
  ring buffer, span-id space and counters; the serving tier's N sessions
  each trace their own query simultaneously with zero interleaving;
* **thread-local routing** — the same mechanism the SyncLedger's operator
  scopes use: the session thread that arms a tracer owns it via a
  thread-local binding, and every emission helper routes to the calling
  thread's bound tracer. Worker threads (pipelined exchange map tasks,
  prefetch uploaders, the join side-collector) inherit the owning query's
  tracer through the explicit-parent capture: :func:`current_span` returns
  a :class:`SpanRef` carrying BOTH the span id and the tracer, and a
  ``span(..., parent=ref)`` or ``inherit(ref)`` on the worker thread binds
  that tracer there for the duration;
* a **span tree** per query — query → partition task → operator → shuffle
  map task — built from begin/end records pushed on thread-local stacks;
* **instant events** inside those spans — opjit/compiled dispatches,
  audited D→H syncs (piggybacking the SyncLedger's thread-local operator
  scopes, so attribution is IDENTICAL to the ledger), HBM alloc/pressure,
  spill, semaphore waits, shuffle reads/fetch retries, device retries and
  chaos injections;
* **per-query ground-truth counters** — :func:`dispatch_event` and
  :func:`sync_event` increment the bound tracer's own dispatch/sync
  counters (never dropped, unlike ring records) at exactly the sites where
  the process-wide ``calls_by_kind`` / SyncLedger counters increment, so a
  bundle reconciles against ITS OWN query's deltas even when other queries
  run concurrently (no cross-query bleed).

Design constraints:

* **Near-zero cost when off**: every public entry point first reads the
  module-level ``_ACTIVE`` armed-tracer count (a plain int, no lock);
  ``span()`` returns a shared null context manager. Sites in the per-batch
  hot path additionally branch on ``_ACTIVE`` themselves (execs/base.py
  keeps its untraced fast loop, and checks :func:`thread_traced` so a
  query that is NOT being traced stays on the fast loop even while a
  concurrent query is).
* **Ring-buffered**: records land in a ``deque(maxlen=bufferEvents)`` —
  a runaway query overwrites its oldest records instead of growing without
  bound; the export layer reports the drop count and downgrades
  reconciliation to "overflow" instead of lying.
* **No silent drops**: a query that cannot be traced (the
  ``trace.maxConcurrentQueries`` capacity cap, or a nested begin on an
  already-tracing thread) increments the always-on
  ``trace.dropped_queries`` registry counter (obs/metrics.py) — the old
  one-query-at-a-time singleton returned ``None`` silently; that behavior
  is gone (tests/test_obs.py locks this in).

Exports (obs/export.py): Chrome trace-event JSON (perfetto /
``chrome://tracing``), the span tree, and the per-query diagnostics bundle.
See docs/observability.md.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from ..profiling import current_sync_scope

#: record layout (tuples, not objects: the tracer may absorb hundreds of
#: thousands of records per query):
#:   (phase, ts_ns, tid, span_id, parent_id, name, cat, op, args)
#: phase: "B" span begin / "E" span end / "i" instant event
REC_PHASE, REC_TS, REC_TID, REC_SPAN, REC_PARENT, REC_NAME, REC_CAT, \
    REC_OP, REC_ARGS = range(9)

#: hot-path gate — the COUNT of armed tracers, read unlocked everywhere
#: (truthy exactly when any query is being traced); mutated only under
#: _REG_LOCK by begin_query/end_query
_ACTIVE = 0

_REG_LOCK = threading.Lock()
#: armed tracers (begin_query registered, end_query not yet) — the
#: capacity cap and reset_for_tests operate on this set
_TRACERS: "set[QueryTracer]" = set()

#: default cap on simultaneously traced queries (conf
#: spark.rapids.tpu.trace.maxConcurrentQueries overrides via begin_query)
DEFAULT_MAX_CONCURRENT = 16


class _ObsTls(threading.local):
    """Per-thread tracer binding + stack of open span ids (same idiom as
    the profiling sync-scope stack). ``stack`` always belongs to
    ``tracer``; rebinding replaces both together."""
    tracer: Optional["QueryTracer"] = None
    stack: Tuple[int, ...] = ()


_tls = _ObsTls()


class SpanRef:
    """Opaque cross-thread handoff token: a span id PLUS the tracer that
    owns it. Capture on the submitting thread (``current_span()`` or a
    ``span()`` ``__enter__`` value), pass to the worker thread — a
    ``span(..., parent=ref)`` or ``inherit(ref)`` there routes the
    worker's records into the owning query's tracer."""

    __slots__ = ("tracer", "sid")

    def __init__(self, tracer: "QueryTracer", sid: int):
        self.tracer = tracer
        self.sid = sid


class _NullSpan:
    """Shared no-op context manager returned when tracing is off."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class QueryTracer:
    """One query's ring-buffered recorder. Use the module-level helpers
    (``span`` / ``event`` / ``begin_query`` / ``end_query``) — they carry
    the off-fast-path and the thread-local routing; this class is the
    storage."""

    def __init__(self, name: str, buffer_events: int, categories=()):
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(buffer_events), 1024))
        self._appended = 0
        self._next_span = 1
        self._t0_ns = time.perf_counter_ns()
        self._cats: Optional[frozenset] = frozenset(categories) or None
        self._closed = False
        self.name = name
        self.root = 0
        # per-query ground-truth counters (never ring-dropped): the bundle
        # reconciles its ring-derived counts against THESE when other
        # queries ran concurrently (process-wide deltas would cross-bleed)
        self._disp_counts: Dict[str, int] = {}
        self._sync_counts: Dict[str, Dict[str, int]] = {}
        # exclusivity: snapshot the process-wide query epoch/active count
        # at begin; end() compares — TRUE means no other query (traced or
        # not) overlapped, so process-wide counter deltas are attributable
        from . import metrics as _metrics
        self._epoch0 = _metrics.query_epoch()
        self._solo0 = _metrics.active_query_count() <= 1

    # --- lifecycle ---------------------------------------------------------
    def _begin(self) -> None:
        """Open the root span on the CALLING thread (so partition spans
        nest) and bind this tracer there."""
        with self._mu:
            self.root = self._next_span
            self._next_span += 1
            self._ring.append(("B", 0, threading.get_ident(), self.root,
                               None, self.name, "query", None, None))
            self._appended += 1
        _tls.tracer = self
        _tls.stack = (self.root,)

    def end(self) -> Dict[str, Any]:
        """Close the query record; returns the raw profile dict consumed by
        obs/export.py."""
        from . import metrics as _metrics
        exclusive = self._solo0 and _metrics.query_epoch() == self._epoch0
        self._append(("E", self.now_ns(), threading.get_ident(), self.root,
                      None, None, "query", None, None))
        with self._mu:
            self._closed = True
            events = list(self._ring)
            dropped = self._appended - len(self._ring)
            disp = dict(self._disp_counts)
            syncs = {op: dict(kinds)
                     for op, kinds in self._sync_counts.items()}
            # drop the ring storage: SpanRefs parked on plan nodes (e.g.
            # an exchange's captured parent) may pin this tracer past the
            # query — they must not pin bufferEvents of records with it
            self._ring.clear()
        if _tls.tracer is self:
            _tls.tracer = None
            _tls.stack = ()
        return {"name": self.name, "root": self.root, "events": events,
                "dropped": dropped,
                "duration_ns": events[-1][REC_TS] if events else 0,
                "dispatch_counts": disp, "sync_counts": syncs,
                "exclusive": exclusive}

    # --- recording ---------------------------------------------------------
    def _append(self, rec: Tuple) -> None:
        with self._mu:
            self._ring.append(rec)
            self._appended += 1

    def begin_span(self, ts: int, tid: int, parent: Optional[int],
                   name: str, cat: str, op: str,
                   args: Optional[Dict[str, Any]]) -> int:
        """Allocate a span id and append its begin record under ONE lock
        acquisition (pool threads hammer this during traced shuffles)."""
        with self._mu:
            sid = self._next_span
            self._next_span += 1
            self._ring.append(("B", ts, tid, sid, parent, name, cat, op,
                               args))
            self._appended += 1
        return sid

    def record_dispatch(self, kind: str, cache: str, source: str, op: str,
                        sid: Optional[int], ts: int, tid: int) -> None:
        """One program dispatch: per-query counter + ring event under ONE
        lock acquisition (called exactly where ``calls_by_kind``
        increments — execs/opjit.py)."""
        with self._mu:
            self._disp_counts[kind] = self._disp_counts.get(kind, 0) + 1
            if self._cats is None or "dispatch" in self._cats:
                self._ring.append(("i", ts, tid, sid, None, "dispatch",
                                   "dispatch", op,
                                   {"kind": kind, "cache": cache,
                                    "source": source}))
                self._appended += 1

    def record_sync(self, op: str, kind: str, sid: Optional[int], ts: int,
                    tid: int) -> None:
        """One audited blocking D→H sync: per-query counter + ring event
        (called by ``profiling.SyncLedger.record`` itself, with the SAME
        operator attribution the ledger used)."""
        with self._mu:
            ops = self._sync_counts.setdefault(op, {})
            ops[kind] = ops.get(kind, 0) + 1
            if self._cats is None or "sync" in self._cats:
                self._ring.append(("i", ts, tid, sid, None, "sync", "sync",
                                   op, {"kind": kind}))
                self._appended += 1

    def now_ns(self) -> int:
        return time.perf_counter_ns() - self._t0_ns

    # --- test hooks --------------------------------------------------------
    @classmethod
    def reset_for_tests(cls) -> None:
        global _ACTIVE
        with _REG_LOCK:
            for tr in _TRACERS:
                tr._closed = True
            _TRACERS.clear()
            _ACTIVE = 0
        _tls.tracer = None
        _tls.stack = ()


class _Span:
    """Open span context manager (only constructed when tracing is on).
    ``__enter__`` returns a :class:`SpanRef` — pass it to worker threads as
    ``span(..., parent=ref)`` for cross-thread nesting."""

    __slots__ = ("_tracer", "_name", "_cat", "_parent", "_args", "_sid",
                 "_saved")

    def __init__(self, tracer: QueryTracer, name: str, cat: str, parent,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._parent = parent
        self._args = args
        self._sid = 0
        self._saved = None

    def _parent_sid(self) -> Optional[int]:
        p = self._parent
        if type(p) is SpanRef:
            return p.sid
        return p if isinstance(p, int) else None

    def __enter__(self) -> SpanRef:
        tr = self._tracer
        if _tls.tracer is tr:
            st = _tls.stack
            # natural nesting wins; the explicit parent serves worker
            # threads whose stacks start empty
            parent = st[-1] if st else self._parent_sid()
        else:
            # cross-thread adoption: bind the owning query's tracer to
            # this worker thread for the span's duration (restored on
            # exit, so a pool thread serving query A then query B never
            # leaks A's binding into B's span)
            self._saved = (_tls.tracer, _tls.stack)
            _tls.tracer = tr
            _tls.stack = ()
            parent = self._parent_sid()
        sid = tr.begin_span(tr.now_ns(), threading.get_ident(), parent,
                            self._name, self._cat, current_sync_scope(),
                            self._args)
        self._sid = sid
        _tls.stack = _tls.stack + (sid,)
        return SpanRef(tr, sid)

    def __exit__(self, *exc) -> bool:
        tr = self._tracer
        st = _tls.stack
        if st and st[-1] == self._sid:
            _tls.stack = st[:-1]
        tr._append(("E", tr.now_ns(), threading.get_ident(), self._sid,
                    None, None, self._cat, None, None))
        if self._saved is not None:
            _tls.tracer, _tls.stack = self._saved
            self._saved = None
        return False


class _Inherit:
    """Bind a captured SpanRef's tracer (and its span as the ambient
    parent) to this thread WITHOUT opening a new span — the handoff for
    worker threads whose nested operator pulls open their own spans
    (prefetch uploaders, the join side-collector)."""

    __slots__ = ("_ref", "_saved")

    def __init__(self, ref: SpanRef):
        self._ref = ref
        self._saved = None

    def __enter__(self):
        self._saved = (_tls.tracer, _tls.stack)
        _tls.tracer = self._ref.tracer
        # seed the stack with the captured span id: nested spans/events on
        # this thread nest under the capture point; span __exit__ only pops
        # its OWN sid, so the seed survives until restore
        _tls.stack = (self._ref.sid,)
        return self._ref

    def __exit__(self, *exc):
        _tls.tracer, _tls.stack = self._saved
        return False


def _thread_tracer() -> Optional[QueryTracer]:
    tr = _tls.tracer
    return None if tr is None or tr._closed else tr


def span(name: str, cat: str = "op", parent=None, **args):
    """Context manager for one timed span. Near-free when tracing is off.
    ``parent`` (a :class:`SpanRef`) is honored when the current thread has
    no bound tracer — the cross-thread handoff — and as the nesting parent
    when the thread has no open span."""
    if not _ACTIVE:
        return _NULL_SPAN
    tr = _thread_tracer()
    if tr is None:
        if type(parent) is SpanRef and not parent.tracer._closed:
            tr = parent.tracer
        else:
            return _NULL_SPAN
    if tr._cats is not None and cat not in tr._cats and cat != "query":
        return _NULL_SPAN
    return _Span(tr, name, cat, parent, args or None)


def inherit(ref):
    """Context manager binding ``ref``'s tracer to this thread (no new
    span). No-op (shared null CM) when ``ref`` is None or tracing is off —
    callers can pass ``current_span()``'s result unconditionally."""
    if not _ACTIVE or type(ref) is not SpanRef or ref.tracer._closed:
        return _NULL_SPAN
    return _Inherit(ref)


def event(name: str, cat: str = "event", op: Optional[str] = None,
          **args) -> None:
    """One instant event inside the current thread's innermost span. ``op``
    defaults to the profiling sync-scope operator (so sync/dispatch events
    reconcile exactly with the SyncLedger's attribution)."""
    if not _ACTIVE:
        return
    tr = _thread_tracer()
    if tr is None:
        return
    if tr._cats is not None and cat not in tr._cats:
        return
    st = _tls.stack
    tr._append(("i", tr.now_ns(), threading.get_ident(),
                st[-1] if st else None, None, name, cat,
                op if op is not None else current_sync_scope(),
                args or None))


def dispatch_event(kind: str, cache: str, source: str) -> None:
    """One opjit-accounted program dispatch: increments the bound tracer's
    per-query dispatch counter AND appends the ring event — call exactly
    where ``calls_by_kind`` increments (execs/opjit.py) so both the
    per-query and the process-wide ground truth see every launch."""
    if not _ACTIVE:
        return
    tr = _thread_tracer()
    if tr is None:
        return
    st = _tls.stack
    tr.record_dispatch(kind, cache, source, current_sync_scope(),
                       st[-1] if st else None, tr.now_ns(),
                       threading.get_ident())


def sync_event(op: str, kind: str) -> None:
    """One audited blocking D→H sync (called by SyncLedger.record with the
    ledger's own operator attribution)."""
    if not _ACTIVE:
        return
    tr = _thread_tracer()
    if tr is None:
        return
    st = _tls.stack
    tr.record_sync(op, kind, st[-1] if st else None, tr.now_ns(),
                   threading.get_ident())


def current_span() -> Optional[SpanRef]:
    """Handoff token for the innermost open span on this thread (the query
    root when no narrower span is open; None when this thread's query is
    not being traced) — capture before handing work to a pool thread, pass
    as ``span(..., parent=...)`` or ``inherit(...)`` there."""
    if not _ACTIVE:
        return None
    tr = _thread_tracer()
    if tr is None:
        return None
    st = _tls.stack
    return SpanRef(tr, st[-1] if st else tr.root)


def is_active() -> bool:
    """True when ANY query in the process is being traced."""
    return _ACTIVE > 0


def thread_traced() -> bool:
    """True when THIS thread's query is being traced (the per-batch slow-
    path gate in execs/base.py: a concurrent untraced query must stay on
    the fast loop while another query traces)."""
    return _ACTIVE > 0 and _thread_tracer() is not None


def current_query_name() -> Optional[str]:
    """Name of the traced query bound to this thread, if any (flight-
    recorder notes tag themselves with it)."""
    tr = _thread_tracer() if _ACTIVE else None
    return tr.name if tr is not None else None


def begin_query(name: str, buffer_events: int = 262144, categories=(),
                max_concurrent: int = DEFAULT_MAX_CONCURRENT
                ) -> Optional[QueryTracer]:
    """Arm a NEW tracer for one query on the calling thread; returns the
    tracer handle (pass to :func:`end_query`). Returns None — and counts a
    ``trace.dropped_queries`` registry drop, never silently — when the
    ``max_concurrent`` capacity cap is reached or this thread is already
    tracing a query (a nested collect inside a traced query)."""
    global _ACTIVE
    from . import metrics as _metrics
    if _thread_tracer() is not None:
        _metrics.counter_inc("trace.dropped_queries",
                             reason="nested_thread")
        return None
    tracer = QueryTracer(name, buffer_events, categories)
    with _REG_LOCK:
        if len(_TRACERS) >= max(1, int(max_concurrent)):
            dropped = True
        else:
            dropped = False
            _TRACERS.add(tracer)
            _ACTIVE += 1
    if dropped:
        _metrics.counter_inc("trace.dropped_queries", reason="capacity")
        return None
    tracer._begin()
    return tracer


def end_query(tracer: QueryTracer) -> Dict[str, Any]:
    """Close a tracer armed by :func:`begin_query`; returns the raw profile
    dict (obs/export.py builds the bundle/Chrome trace from it)."""
    global _ACTIVE
    with _REG_LOCK:
        if tracer in _TRACERS:
            _TRACERS.discard(tracer)
            _ACTIVE -= 1
    return tracer.end()
