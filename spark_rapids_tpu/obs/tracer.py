"""Query-scoped span/event tracer: the one correlated record of a query.

Reference (PAPER.md §5): the plugin wraps every operator in NVTX ranges
(NvtxWithMetrics.scala), ships a built-in sampled profiler
(profiler.scala:37) and surfaces leveled SQLMetrics in the Spark SQL UI
(GpuExec.scala:41) — one artifact diagnoses a regression. Our pre-existing
equivalents (TpuMetric levels, SyncLedger, opjit `calls_by_kind`,
TaskMetricsRegistry, chaos `trace_text()`) were islands; this module is the
record that ties them together per query:

* a **span tree** — query → partition task → operator → shuffle map task —
  built from begin/end records pushed on thread-local stacks (thread-aware:
  pipelined exchange map tasks and prefetch workers carry their own stacks,
  and a worker-thread span nests under the submitting span via an explicit
  ``parent``);
* **instant events** inside those spans — opjit/compiled dispatches
  (kind + cache hit/miss), audited D→H syncs (piggybacking the SyncLedger's
  thread-local operator scopes, so attribution is IDENTICAL to the ledger),
  HBM alloc/pressure, spill to host/disk/read-back, semaphore waits,
  shuffle map/reduce/fetch-retry, transient device-error retries, and chaos
  injections.

Design constraints:

* **Near-zero cost when off**: every public entry point first reads the
  module-level ``_ACTIVE`` flag (a plain bool, no lock); ``span()`` returns
  a shared null context manager. Sites in the per-batch hot path
  additionally branch on ``_ACTIVE`` themselves (execs/base.py keeps its
  untraced fast loop).
* **Ring-buffered**: records land in a ``deque(maxlen=bufferEvents)`` —
  a runaway query overwrites its oldest records instead of growing without
  bound; the export layer reports the drop count and downgrades
  reconciliation to "overflow" instead of lying.
* **One query at a time**: the tracer is process-wide (instrumentation
  sites have no session handle, exactly like the SyncLedger); a second
  concurrent ``begin_query`` simply gets ``None`` and runs untraced.

Exports (obs/export.py): Chrome trace-event JSON (perfetto /
``chrome://tracing``), the span tree, and the per-query diagnostics bundle.
See docs/observability.md.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..profiling import current_sync_scope

#: record layout (tuples, not objects: the tracer may absorb hundreds of
#: thousands of records per query):
#:   (phase, ts_ns, tid, span_id, parent_id, name, cat, op, args)
#: phase: "B" span begin / "E" span end / "i" instant event
REC_PHASE, REC_TS, REC_TID, REC_SPAN, REC_PARENT, REC_NAME, REC_CAT, \
    REC_OP, REC_ARGS = range(9)

#: hot-path gate — read unlocked everywhere; flipped only under the
#: tracer lock by begin_query/end_query
_ACTIVE = False

#: category filter (frozenset or None == all); set at begin_query
_CATS: Optional[frozenset] = None


class _SpanStack(threading.local):
    """Per-thread stack of open span ids (tuple; same idiom as the
    profiling sync-scope stack)."""
    stack: Tuple[int, ...] = ()


_tls = _SpanStack()


class _NullSpan:
    """Shared no-op context manager returned when tracing is off."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class QueryTracer:
    """Process-wide ring-buffered recorder. Use the module-level helpers
    (``span`` / ``event`` / ``begin_query`` / ``end_query``) — they carry
    the off-fast-path; this class is the storage."""

    _instance: Optional["QueryTracer"] = None
    _cls_lock = threading.Lock()

    def __init__(self):
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=65536)
        self._appended = 0
        self._next_span = 1
        self._query: Optional[Dict[str, Any]] = None
        self._t0_ns = 0

    @classmethod
    def get(cls) -> "QueryTracer":
        with cls._cls_lock:
            if cls._instance is None:
                cls._instance = QueryTracer()
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> "QueryTracer":
        global _ACTIVE, _CATS
        with cls._cls_lock:
            _ACTIVE = False
            _CATS = None
            _tls.stack = ()
            cls._instance = QueryTracer()
            return cls._instance

    # --- lifecycle ---------------------------------------------------------
    def begin(self, name: str, buffer_events: int,
              categories=()) -> Optional[int]:
        """Open a query record and its root span; returns the root span id,
        or None when another query already owns the tracer."""
        global _ACTIVE, _CATS
        with self._mu:
            if self._query is not None:
                return None
            self._ring = deque(maxlen=max(int(buffer_events), 1024))
            self._appended = 0
            self._next_span = 1
            self._t0_ns = time.perf_counter_ns()
            root = self._alloc_span()
            self._query = {"name": name, "root": root}
            _CATS = frozenset(categories) or None
            _ACTIVE = True
        # root span rides the CALLING thread's stack so partition spans nest
        self._push(root)
        self._append(("B", 0, threading.get_ident(), root, None,
                      name, "query", None, None))
        return root

    def end(self, root: int) -> Dict[str, Any]:
        """Close the query record; returns the raw profile dict consumed by
        obs/export.py."""
        global _ACTIVE, _CATS
        self._append(("E", time.perf_counter_ns() - self._t0_ns,
                      threading.get_ident(), root, None, None, "query",
                      None, None))
        self._pop(root)
        with self._mu:
            q = self._query or {"name": "?", "root": root}
            events = list(self._ring)
            dropped = self._appended - len(self._ring)
            self._query = None
            _ACTIVE = False
            _CATS = None
            return {"name": q["name"], "root": q["root"], "events": events,
                    "dropped": dropped, "duration_ns": events[-1][REC_TS]
                    if events else 0}

    # --- recording ---------------------------------------------------------
    def _alloc_span(self) -> int:
        sid = self._next_span
        self._next_span += 1
        return sid

    def _append(self, rec: Tuple) -> None:
        with self._mu:
            self._ring.append(rec)
            self._appended += 1

    def begin_span(self, ts: int, tid: int, parent: Optional[int],
                   name: str, cat: str, op: str,
                   args: Optional[Dict[str, Any]]) -> int:
        """Allocate a span id and append its begin record under ONE lock
        acquisition (pool threads hammer this during traced shuffles)."""
        with self._mu:
            sid = self._alloc_span()
            self._ring.append(("B", ts, tid, sid, parent, name, cat, op,
                               args))
            self._appended += 1
        return sid

    @staticmethod
    def _push(sid: int) -> None:
        _tls.stack = _tls.stack + (sid,)

    @staticmethod
    def _pop(sid: int) -> None:
        st = _tls.stack
        if st and st[-1] == sid:
            _tls.stack = st[:-1]

    def now_ns(self) -> int:
        return time.perf_counter_ns() - self._t0_ns


class _Span:
    """Open span context manager (only constructed when tracing is on)."""

    __slots__ = ("_name", "_cat", "_parent", "_args", "_sid", "_tracer")

    def __init__(self, name: str, cat: str, parent: Optional[int],
                 args: Optional[Dict[str, Any]]):
        self._name = name
        self._cat = cat
        self._parent = parent
        self._args = args or None
        self._sid = 0
        # lock-free singleton read: _instance is always set while _ACTIVE
        # (begin_query goes through get())
        self._tracer = QueryTracer._instance or QueryTracer.get()

    def __enter__(self) -> int:
        tr = self._tracer
        st = _tls.stack
        # natural nesting wins; the explicit parent serves worker threads
        # whose stacks start empty (pipelined shuffle map tasks)
        parent = st[-1] if st else self._parent
        sid = tr.begin_span(tr.now_ns(), threading.get_ident(), parent,
                            self._name, self._cat, current_sync_scope(),
                            self._args)
        self._sid = sid
        tr._push(sid)
        return sid

    def __exit__(self, *exc) -> bool:
        tr = self._tracer
        tr._pop(self._sid)
        tr._append(("E", tr.now_ns(), threading.get_ident(), self._sid,
                    None, None, self._cat, None, None))
        return False


def span(name: str, cat: str = "op", parent: Optional[int] = None, **args):
    """Context manager for one timed span. Near-free when tracing is off.
    ``parent`` is only honored when the current thread has no open span
    (cross-thread nesting: capture ``current_span()`` on the submitting
    thread, pass it to the worker)."""
    if not _ACTIVE:
        return _NULL_SPAN
    if _CATS is not None and cat not in _CATS and cat != "query":
        return _NULL_SPAN
    return _Span(name, cat, parent, args or None)


def event(name: str, cat: str = "event", op: Optional[str] = None,
          **args) -> None:
    """One instant event inside the current span. ``op`` defaults to the
    profiling sync-scope operator (so sync/dispatch events reconcile
    exactly with the SyncLedger's attribution)."""
    if not _ACTIVE:
        return
    if _CATS is not None and cat not in _CATS:
        return
    tr = QueryTracer._instance
    if tr is None:  # racing a reset; nothing to record into
        return
    st = _tls.stack
    tr._append(("i", tr.now_ns(), threading.get_ident(),
                st[-1] if st else None, None, name, cat,
                op if op is not None else current_sync_scope(),
                args or None))


def current_span() -> Optional[int]:
    """Id of the innermost open span on this thread (None when tracing is
    off or the thread has no span) — capture before handing work to a pool
    thread, pass as ``span(..., parent=...)`` there."""
    if not _ACTIVE:
        return None
    st = _tls.stack
    return st[-1] if st else None


def is_active() -> bool:
    return _ACTIVE


def begin_query(name: str, buffer_events: int = 262144,
                categories=()) -> Optional[int]:
    """Arm the tracer for one query; None when another query is tracing."""
    return QueryTracer.get().begin(name, buffer_events, categories)


def end_query(root: int) -> Dict[str, Any]:
    return QueryTracer.get().end(root)
