"""Seeded chaos fault injection for the robustness stack.

Reference: the spark-rapids project ships a dedicated fault-injection tool
to prove its retry/spill/lineage machinery survives randomized failure
(RmmSpark.forceRetryOOM and the cuDF fault injector used by the retry
suites, SURVEY §7). This package is our process-wide analogue: a
deterministic, site-based `FaultInjector` with named injection points woven
through the stack, each drawing from an independent per-(seed, site) PRNG
stream so a run's injection trace is replayable.

The module-level `inject`/`corrupt_bytes` helpers are the fast path the
woven sites call: when no injector is armed they cost one attribute read.
"""

from .injector import (ALL_KINDS, ALL_SITES, SITE_KINDS, FaultInjector,
                       corrupt_bytes, in_retry_scope, inject, retry_scope)

__all__ = [
    "ALL_KINDS", "ALL_SITES", "SITE_KINDS", "FaultInjector",
    "corrupt_bytes", "in_retry_scope", "inject", "retry_scope",
]
