"""Deterministic, seeded, site-based fault injector.

Design (ISSUE 4 tentpole):

* **Sites** are stable names woven through the stack (`hbm.alloc`,
  `spill.to_host`, `spill.to_disk`, `device.dispatch`, `shuffle.serialize`,
  `shuffle.write`, `shuffle.read`, `ici.fetch`, `pipeline.task`,
  `scan.read`). A site either *checks* (`inject(site)` — may raise a fault
  or sleep) or *mangles* a byte stream (`corrupt_bytes(site, data)`).

* **Determinism**: each site owns an independent PRNG seeded from
  (seed, site) via sha256, so the per-site sequence of draws — and therefore
  the per-site injection trace — is identical run to run even though thread
  interleaving may hand a given draw to a different caller. `trace_text()`
  serializes the trace sorted by (site, seq) for byte-identical comparison.

* **Healability gating**: the OOM kinds (`retry_oom`, `split_oom`) only
  fire inside a retry-framework scope (`retry_scope`, entered by
  memory/retry.py around each attempt), mirroring the reference's rule that
  RmmSpark.forceRetryOOM targets threads inside a retry block — an OOM
  injected outside the framework would just kill the query, proving
  nothing. `split_oom` degrades to `retry_oom` when the scope says the
  input cannot be split (fewer than 2 rows, or a no-split retry).
  Scope gating is applied AFTER the PRNG draw so the draw sequence stays
  independent of scope state.

* **Forced counters**: the deterministic `HbmBudget.force_retry_oom`-style
  test hooks route through `force(site, kind, n)` — they fire ahead of any
  randomized draw, bypass scope gating, and work with the injector
  otherwise disabled (preserving the pre-existing test-hook semantics).
"""

from __future__ import annotations

import contextlib
import hashlib
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

ALL_SITES = (
    "hbm.alloc", "spill.to_host", "spill.to_disk", "device.dispatch",
    "shuffle.serialize", "shuffle.write", "shuffle.read", "ici.fetch",
    "pipeline.task", "scan.read", "mesh.shard", "mesh.link",
    "sched.admit", "query.cancel", "sched.shed",
)

ALL_KINDS = (
    "retry_oom", "split_oom", "transient", "fatal", "corrupt", "truncate",
    "io_error", "latency", "cancel",
)

#: which fault kinds make sense at each site. `inject` draws from the
#: configured kinds ∩ this set; `corrupt_bytes` additionally restricts to
#: the byte-stream kinds (corrupt/truncate). Raise-kinds at byte sites fire
#: through the adjacent `inject` call the site also makes.
SITE_KINDS: Dict[str, Tuple[str, ...]] = {
    "hbm.alloc": ("retry_oom", "split_oom", "latency"),
    "spill.to_host": ("retry_oom", "latency", "io_error"),
    "spill.to_disk": ("latency", "io_error", "corrupt", "truncate"),
    "device.dispatch": ("transient", "fatal", "latency"),
    "shuffle.serialize": ("latency", "io_error"),
    "shuffle.write": ("corrupt", "truncate", "io_error", "latency"),
    "shuffle.read": ("corrupt", "truncate", "io_error", "latency"),
    "ici.fetch": ("transient", "latency"),
    "pipeline.task": ("transient", "latency", "io_error"),
    "scan.read": ("corrupt", "truncate", "io_error", "latency"),
    # mesh data plane (docs/distributed.md): a LOST SHARD (io_error at the
    # collective read — the exchange converts it into catalog invalidation
    # so FetchFailed lineage recovery re-runs the collective) and a SLOW
    # SHARD (latency); a SLOW or FLAPPING ICI LINK fires inside the
    # collective launch (latency stalls the transfer; transient heals via
    # with_device_retry re-running the idempotent staging)
    "mesh.shard": ("io_error", "latency"),
    "mesh.link": ("transient", "latency"),
    # query lifecycle (docs/robustness.md "Query lifecycle"): the
    # scheduler's admission point (latency = queue delay; io_error = a
    # failed admission — the query dies QUEUED, before any resource is
    # acquired) and the cooperative cancellation checkpoints (cancel =
    # the bound query's cancel token arms AT this exact boundary, racing
    # a user cancel against every task boundary in the stack; latency =
    # a slow checkpoint)
    "sched.admit": ("latency", "io_error"),
    "query.cancel": ("cancel", "latency"),
    # the load-shed decision point (docs/serving.md): fires BEFORE the
    # victim's cancel token arms — latency delays the shed, io_error
    # fails the shed attempt itself (the victim survives the pass; a
    # queue-full submission degrades to typed QueryQueueFull
    # backpressure, the overload path re-decides next tick)
    "sched.shed": ("latency", "io_error"),
}

_BYTE_KINDS = ("corrupt", "truncate")

# --- retry-scope tracking (memory/retry.py enters; OOM kinds gate on it) ---

_TL = threading.local()


@contextlib.contextmanager
def retry_scope(splittable: bool = True):
    """Mark the current thread as inside a retry-framework attempt: injected
    TpuRetryOOM/TpuSplitAndRetryOOM here is healable by design."""
    prev = getattr(_TL, "scope", None)
    _TL.scope = {"splittable": bool(splittable)}
    try:
        yield
    finally:
        _TL.scope = prev


def in_retry_scope() -> bool:
    return getattr(_TL, "scope", None) is not None


def _scope_splittable() -> bool:
    s = getattr(_TL, "scope", None)
    return bool(s and s["splittable"])


# --- the injector -----------------------------------------------------------


class _Record:
    __slots__ = ("site", "seq", "kind", "detail", "forced")

    def __init__(self, site: str, seq: int, kind: str, detail: str = "",
                 forced: bool = False):
        self.site = site
        self.seq = seq
        self.kind = kind
        self.detail = detail
        self.forced = forced

    def render(self) -> str:
        tag = "forced " if self.forced else ""
        extra = f" {self.detail}" if self.detail else ""
        return f"{self.site}#{self.seq} {tag}{self.kind}{extra}"


def _site_seed(seed: int, site: str) -> int:
    # sha256, not hash(): str hashing is randomized per process, and the
    # trace must replay across processes for the same conf
    h = hashlib.sha256(f"{seed}:{site}".encode()).digest()
    return int.from_bytes(h[:8], "little")


class FaultInjector:
    """Process-wide seeded fault injector (see module docstring)."""

    _instance: Optional["FaultInjector"] = None
    _cls_lock = threading.Lock()

    def __init__(self, enabled: bool = False, seed: int = 0,
                 sites: Sequence[str] = (), kinds: Sequence[str] = (),
                 probability: float = 0.0, max_injections: int = 0,
                 latency_ms: float = 2.0):
        for s in sites:
            if s not in ALL_SITES:
                raise ValueError(f"unknown chaos site {s!r}; known: "
                                 f"{', '.join(ALL_SITES)}")
        for k in kinds:
            if k not in ALL_KINDS:
                raise ValueError(f"unknown chaos fault kind {k!r}; known: "
                                 f"{', '.join(ALL_KINDS)}")
        self.enabled = bool(enabled)
        self.seed = int(seed)
        self.sites = tuple(sites) or ALL_SITES
        self.kinds = tuple(kinds) or ALL_KINDS
        self.probability = float(probability)
        self.max_injections = int(max_injections)
        self.latency_ms = float(latency_ms)
        self._mu = threading.Lock()
        self._rngs: Dict[str, random.Random] = {}
        self._seqs: Dict[str, int] = {}
        self._trace: List[_Record] = []
        self._injected = 0
        self._forced: Dict[Tuple[str, str], int] = {}
        # checks to SKIP before a forced counter starts firing: lets a
        # test land a fault at exactly the k-th visit of a site (the
        # cancel-at-every-boundary sweep in test_resource_lifecycle.py)
        self._forced_skip: Dict[Tuple[str, str], int] = {}
        # read un-locked on the hot path; flipped under the lock
        self._armed = self.enabled

    # --- lifecycle ---------------------------------------------------------
    @classmethod
    def get(cls) -> "FaultInjector":
        with cls._cls_lock:
            if cls._instance is None:
                cls._instance = FaultInjector()
            return cls._instance

    @classmethod
    def configure(cls, conf) -> "FaultInjector":
        """Build an injector from `spark.rapids.tpu.test.chaos.*`; forced
        counters survive reconfiguration (they are independent test hooks)."""
        from ..config import (CHAOS_ENABLED, CHAOS_KINDS, CHAOS_LATENCY_MS,
                              CHAOS_MAX_INJECTIONS, CHAOS_PROBABILITY,
                              CHAOS_SEED, CHAOS_SITES)
        inj = FaultInjector(
            enabled=conf.get(CHAOS_ENABLED), seed=conf.get(CHAOS_SEED),
            sites=conf.get(CHAOS_SITES), kinds=conf.get(CHAOS_KINDS),
            probability=conf.get(CHAOS_PROBABILITY),
            max_injections=conf.get(CHAOS_MAX_INJECTIONS),
            latency_ms=conf.get(CHAOS_LATENCY_MS))
        with cls._cls_lock:
            old = cls._instance
            if old is not None:
                with old._mu:
                    pending = {k: n for k, n in old._forced.items() if n > 0}
                inj._forced.update(pending)
                inj._armed = inj.enabled or bool(pending)
            cls._instance = inj
            return inj

    @classmethod
    def maybe_configure(cls, conf) -> None:
        """Session hook: (re)configure only when the conf mentions chaos —
        ordinary sessions must not clear another test's armed injector."""
        from ..config import CHAOS_ENABLED
        cur = cls._instance
        if conf.get(CHAOS_ENABLED) or (cur is not None and cur.enabled):
            cls.configure(conf)

    @classmethod
    def reset_for_tests(cls) -> "FaultInjector":
        with cls._cls_lock:
            cls._instance = FaultInjector()
            return cls._instance

    # --- test hooks (reference RmmSpark.forceRetryOOM) ---------------------
    def force(self, site: str, kind: str, n: int = 1,
              skip: int = 0) -> None:
        """Arm `n` deterministic one-shot faults at `site` (SET, not add —
        the RmmSpark.forceRetryOOM counter semantics). `skip` lets the
        first `skip` checks of the site pass clean first, so a test can
        land the fault at exactly the k-th boundary visit."""
        if site not in ALL_SITES or kind not in ALL_KINDS:
            raise ValueError(f"unknown chaos site/kind {site!r}/{kind!r}")
        with self._mu:
            self._forced[(site, kind)] = int(n)
            self._forced_skip[(site, kind)] = int(skip)
            self._armed = self.enabled or any(
                v > 0 for v in self._forced.values())

    def clear_forced(self, site: Optional[str] = None) -> None:
        """Drop pending forced counters (all sites, or one) — called by the
        singletons' reset_for_tests so a partially-consumed force cannot
        leak OOMs into a later test."""
        with self._mu:
            for key in list(self._forced):
                if site is None or key[0] == site:
                    del self._forced[key]
                    self._forced_skip.pop(key, None)
            self._armed = self.enabled or any(
                v > 0 for v in self._forced.values())

    # --- trace -------------------------------------------------------------
    def trace(self) -> List[Dict]:
        with self._mu:
            recs = list(self._trace)
        recs.sort(key=lambda r: (r.site, r.seq))
        return [{"site": r.site, "seq": r.seq, "kind": r.kind,
                 "detail": r.detail, "forced": r.forced} for r in recs]

    def trace_text(self) -> str:
        with self._mu:
            recs = list(self._trace)
        recs.sort(key=lambda r: (r.site, r.seq))
        return "\n".join(r.render() for r in recs)

    def injection_count(self) -> int:
        with self._mu:
            return len(self._trace)

    # --- the check ---------------------------------------------------------
    def _rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            rng = self._rngs[site] = random.Random(_site_seed(self.seed,
                                                              site))
        return rng

    def _pop_forced(self, site: str, wanted: Tuple[str, ...]
                    ) -> Optional[str]:
        # split before retry mirrors the old HbmBudget counter precedence
        order = ("cancel", "split_oom", "retry_oom", "transient", "fatal",
                 "corrupt", "truncate", "io_error", "latency")
        for kind in order:
            if kind not in wanted:
                continue
            n = self._forced.get((site, kind), 0)
            if n > 0:
                sk = self._forced_skip.get((site, kind), 0)
                if sk > 0:  # this kind passes the visit clean; other
                    # forced kinds at the site still get their turn
                    self._forced_skip[(site, kind)] = sk - 1
                    continue
                self._forced[(site, kind)] = n - 1
                self._armed = self.enabled or any(
                    v > 0 for v in self._forced.values())
                return kind
        return None

    def _draw(self, site: str, applicable: Tuple[str, ...]
              ) -> Tuple[Optional[str], float, int]:
        """One randomized decision for `site` under the lock. Returns
        (kind-or-None, latency_seconds, seq). The draw sequence per site is
        fixed by (seed, site) alone — gating never skips a draw."""
        rng = self._rng(site)
        seq = self._seqs.get(site, 0)
        self._seqs[site] = seq + 1
        r = rng.random()
        if r >= self.probability:
            return None, 0.0, seq
        kinds = tuple(k for k in self.kinds if k in applicable)
        if not kinds:
            return None, 0.0, seq
        kind = kinds[rng.randrange(len(kinds))]
        delay = 0.0
        if kind == "latency":
            delay = (self.latency_ms / 1000.0) * (0.25 + 0.75 * rng.random())
        # scope gating AFTER the draws: an un-healable OOM is suppressed,
        # not re-rolled, so the stream stays deterministic
        if kind in ("retry_oom", "split_oom"):
            if not in_retry_scope():
                return None, 0.0, seq
            if kind == "split_oom" and not _scope_splittable():
                kind = "retry_oom"
        if self.max_injections and self._injected >= self.max_injections:
            return None, 0.0, seq
        self._injected += 1
        return kind, delay, seq

    def check(self, site: str, detail: str = "") -> None:
        """Maybe raise a fault (or sleep) at `site`."""
        delay = 0.0
        with self._mu:
            kind = self._pop_forced(
                site, tuple(k for k in ALL_KINDS if k not in _BYTE_KINDS))
            forced = kind is not None
            if forced:
                seq = self._seqs.get(site, 0)  # forced: no draw consumed
            elif (self.enabled and site in self.sites):
                kind, delay, seq = self._draw(
                    site, tuple(k for k in SITE_KINDS[site]
                                if k not in _BYTE_KINDS))
            if kind is None:
                return
            self._trace.append(_Record(site, seq, kind,
                                       detail=detail, forced=forced))
        self._obs_event(site, seq, kind, detail, forced)
        self._raise(site, kind, delay)

    def mangle(self, site: str, data: bytes) -> bytes:
        """Maybe corrupt or truncate a byte stream at `site`."""
        if not data:
            return data
        with self._mu:
            kind = self._pop_forced(site, _BYTE_KINDS)
            forced = kind is not None
            offset = 0
            if forced:
                seq = self._seqs.get(site, 0)
                rng = self._rng(site)
            elif (self.enabled and site in self.sites):
                kind, _, seq = self._draw(
                    site, tuple(k for k in SITE_KINDS[site]
                                if k in _BYTE_KINDS))
                rng = self._rng(site)
            if kind is None:
                return data
            offset = rng.randrange(len(data))
            self._trace.append(_Record(
                site, seq, kind, detail=f"@{offset}/{len(data)}",
                forced=forced))
        self._obs_event(site, seq, kind, f"@{offset}/{len(data)}", forced)
        if kind == "truncate":
            return data[:offset]
        return data[:offset] + bytes([data[offset] ^ 0x5A]) \
            + data[offset + 1:]

    @staticmethod
    def _obs_event(site: str, seq: int, kind: str, detail: str,
                   forced: bool) -> None:
        """Mirror one injection into the query timeline: the event fires
        inside whatever span the injection interrupted (the failing map
        task / operator pull), so the trace shows the fault exactly where
        it struck — next to the device.retry event that healed it. Every
        injection also lands in the always-on registry (per-site counter)
        and the crash flight recorder, so a postmortem bundle shows the
        fault that preceded the death even when nothing was traced."""
        from ..obs import flight as _flight
        from ..obs import metrics as _metrics
        from ..obs import tracer as _obs
        _metrics.counter_inc("chaos.injections", site=site, kind=kind)
        _flight.note("chaos.inject", site=site, seq=seq, kind=kind,
                     detail=detail, forced=forced)
        if _obs._ACTIVE:
            _obs.event("chaos", cat="chaos", site=site, seq=seq, kind=kind,
                       detail=detail, forced=forced)

    def _raise(self, site: str, kind: str, delay: float) -> None:
        if kind == "latency":
            time.sleep(delay)
            return
        if kind in ("retry_oom", "split_oom"):
            from ..memory.hbm import TpuRetryOOM, TpuSplitAndRetryOOM
            exc = (TpuSplitAndRetryOOM if kind == "split_oom"
                   else TpuRetryOOM)(f"chaos-injected {kind} at {site}")
            raise exc
        if kind == "transient":
            raise RuntimeError(
                f"UNAVAILABLE: chaos-injected transient device error "
                f"at {site}")
        if kind == "fatal":
            raise RuntimeError(
                f"INTERNAL: chaos-injected fatal device error at {site}")
        if kind == "io_error":
            raise OSError(f"chaos-injected io error at {site}")
        if kind == "cancel":
            # query-lifecycle chaos (docs/robustness.md "Query
            # lifecycle"): arm the bound query's cancel token — so every
            # OTHER thread serving the query trips at its next checkpoint
            # too, exactly like a user cancel — then raise here, at this
            # boundary
            from ..serving.query_context import (QueryCancelledError,
                                                 current)
            q = current()
            if q is not None:
                q.cancel(reason=f"chaos at {site}")
            raise QueryCancelledError(
                f"chaos-injected cancel at {site}")
        raise AssertionError(f"unhandled chaos kind {kind}")


# --- module-level fast path (sites call these) ------------------------------


def inject(site: str, detail: str = "") -> None:
    inj = FaultInjector._instance
    if inj is None or not inj._armed:
        return
    inj.check(site, detail)


def corrupt_bytes(site: str, data: bytes) -> bytes:
    inj = FaultInjector._instance
    if inj is None or not inj._armed:
        return data
    return inj.mangle(site, data)
