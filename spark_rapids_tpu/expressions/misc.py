"""Nondeterministic / task-context expressions.

Reference: GpuMonotonicallyIncreasingID, GpuSparkPartitionID, GpuRand
(catalyst/expressions/GpuRandomExpressions.scala), GpuInputFileName /
GpuInputFileBlockStart / GpuInputFileBlockLength (InputFileBlockRule).
These read task-scoped state from EvalContext (partition id, input-file info,
running row counters) instead of JVM TaskContext thread-locals.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..types import DataType, DoubleT, IntegerT, LongT, StringT
from ..columnar.vector import TpuColumnVector, TpuScalar, row_mask
from .base import Expression, _DEFAULT_CTX, make_column


class _LeafExpression(Expression):
    children = ()

    @property
    def foldable(self) -> bool:
        return False

    @property
    def nullable(self) -> bool:
        return False


class SparkPartitionID(_LeafExpression):
    """spark_partition_id(): the task's partition index."""

    @property
    def dtype(self) -> DataType:
        return IntegerT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        data = jnp.full((cap,), ctx.partition_id, jnp.int32)
        return make_column(IntegerT, data, row_mask(batch.num_rows, cap),
                           batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        return pa.array([ctx.partition_id] * table.num_rows, pa.int32())

    def pretty(self) -> str:
        return "spark_partition_id()"


class MonotonicallyIncreasingID(_LeafExpression):
    """monotonically_increasing_id(): (partition_id << 33) + row index within
    the partition, accumulated across batches via the ctx row counter — the
    same layout Spark documents (33 bits of per-partition record number)."""

    @property
    def dtype(self) -> DataType:
        return LongT

    def _offset(self, ctx, n: int) -> int:
        off = ctx.row_counters.get(id(self), 0)
        ctx.row_counters[id(self)] = off + n
        return off

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        off = self._offset(ctx, batch.num_rows)
        base = (ctx.partition_id << 33) + off
        data = base + jnp.arange(cap, dtype=jnp.int64)
        return make_column(LongT, data, row_mask(batch.num_rows, cap),
                           batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        n = table.num_rows
        off = self._offset(ctx, n)
        base = (ctx.partition_id << 33) + off
        return pa.array(range(base, base + n), pa.int64())

    def pretty(self) -> str:
        return "monotonically_increasing_id()"


class Rand(_LeafExpression):
    """rand(seed): uniform [0,1) doubles, deterministic per
    (seed, partition, row). Uses jax's threefry counter PRNG keyed by
    (seed, partition) and indexed by absolute row position — reproducible
    under re-execution like Spark's XORShiftRandom, though the sequence
    itself differs (priced as incompat)."""

    def __init__(self, seed: Expression = None):
        from .base import Literal
        self.children = (seed if seed is not None else Literal(0),)

    @property
    def dtype(self) -> DataType:
        return DoubleT

    def _seed(self):
        from .base import Literal
        s = self.children[0]
        return int(s.value) if isinstance(s, Literal) and s.value is not None else 0

    def _offset(self, ctx, n: int) -> int:
        off = ctx.row_counters.get(id(self), 0)
        ctx.row_counters[id(self)] = off + n
        return off

    def _values(self, ctx, off: int, n: int):
        key = jax.random.fold_in(jax.random.PRNGKey(self._seed()),
                                 ctx.partition_id)
        # counter-mode: one fold per batch start keeps draws independent of
        # batch boundaries without materializing per-row keys
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
            jnp.arange(off, off + n, dtype=jnp.uint32))
        return jax.vmap(lambda k: jax.random.uniform(k, dtype=jnp.float64))(keys)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        vals = self._values(ctx, self._offset(ctx, batch.num_rows), cap)
        return make_column(DoubleT, vals, row_mask(batch.num_rows, cap),
                           batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import numpy as np
        import pyarrow as pa
        n = table.num_rows
        vals = self._values(ctx, self._offset(ctx, n), n)
        return pa.array(np.asarray(vals, dtype=np.float64), pa.float64())

    def pretty(self) -> str:
        return f"rand({self.children[0].pretty()})"


class InputFileName(_LeafExpression):
    """input_file_name(): current scan file, '' outside a file scan
    (Spark semantics; set by the multi-file readers via EvalContext)."""

    @property
    def dtype(self) -> DataType:
        return StringT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        name = ctx.input_file or ""
        return TpuColumnVector.from_scalar(name, StringT, batch.num_rows,
                                           capacity=batch.capacity)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        return pa.array([ctx.input_file or ""] * table.num_rows, pa.string())

    def pretty(self) -> str:
        return "input_file_name()"


class _InputFileLong(_LeafExpression):
    _field = "input_block_start"

    @property
    def dtype(self) -> DataType:
        return LongT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        data = jnp.full((cap,), getattr(ctx, self._field), jnp.int64)
        return make_column(LongT, data, row_mask(batch.num_rows, cap),
                           batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        return pa.array([getattr(ctx, self._field)] * table.num_rows,
                        pa.int64())


class InputFileBlockStart(_InputFileLong):
    _field = "input_block_start"

    def pretty(self) -> str:
        return "input_file_block_start()"


class InputFileBlockLength(_InputFileLong):
    _field = "input_block_length"

    def pretty(self) -> str:
        return "input_file_block_length()"
