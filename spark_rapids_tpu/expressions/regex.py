"""Regex support with the reference's validate/rewrite/reject architecture.

Reference: RegexParser.scala (RegexParser:44, CudfRegexTranspiler:687,
rewrite optimizations :2030) + RegexComplexityEstimator. The reference parses
Java regex, transpiles to the cuDF dialect, and *rejects* untranspilable
patterns so tagging falls back to CPU. Here the target engines are:
  1. cheap device ops for rewritable patterns (^lit → startswith, lit$ →
     endswith, plain literal → contains) — same rewrites as RegexParser:2030
  2. Python `re` on host for everything else that parses (host-assisted)
  3. reject → expression tagged unsupported → operator falls back
Java-vs-Python dialect differences that change semantics (possessive
quantifiers, \\p{...} variants) are rejected rather than silently wrong.
"""

from __future__ import annotations

import re as _re
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..types import BooleanT, DataType, IntegerT, StringT
from ..columnar.vector import TpuColumnVector, TpuScalar, row_mask
from .base import Expression, _DEFAULT_CTX, combine_validity, make_column
from .strings import (Contains, EndsWith, StartsWith, _bool_result_from_arrow,
                      _dev_str, _string_result_from_arrow, _to_arrow_side)

_META = set(".^$*+?()[]{}|\\")

# constructs Java supports but python re does not (or differs) → reject
_REJECT_PATTERNS = [
    _re.compile(r"\*\+|\+\+|\?\+"),           # possessive quantifiers
    _re.compile(r"\\[pP]\{"),                  # unicode property classes
    _re.compile(r"\(\?<[=!]"),                 # lookbehind (py supports but
                                               # fixed-width only; differs)
    _re.compile(r"\\[GZ]"),                    # Java-only anchors
]


def transpile(pattern: str) -> Optional[str]:
    """Java regex → python-re pattern, or None if rejected
    (reference CudfRegexTranspiler.transpile)."""
    for rej in _REJECT_PATTERNS:
        if rej.search(pattern):
            return None
    try:
        _re.compile(pattern)
    except _re.error:
        return None
    return pattern


def literal_prefix_rewrite(pattern: str) -> Optional[Tuple[str, str]]:
    """Recognize trivially-rewritable patterns (reference RegexParser
    optimizations :2030): returns (kind, literal) with kind in
    startswith/endswith/contains/equals."""

    def is_literal(s: str) -> bool:
        i = 0
        while i < len(s):
            if s[i] == "\\" and i + 1 < len(s) and s[i + 1] in _META:
                i += 2
                continue
            if s[i] in _META:
                return False
            i += 1
        return True

    def unescape(s: str) -> str:
        out = []
        i = 0
        while i < len(s):
            if s[i] == "\\" and i + 1 < len(s):
                out.append(s[i + 1])
                i += 2
            else:
                out.append(s[i])
                i += 1
        return "".join(out)

    body = pattern
    anchored_start = body.startswith("^")
    anchored_end = body.endswith("$") and not body.endswith("\\$")
    core = body[1 if anchored_start else 0:
                len(body) - 1 if anchored_end else len(body)]
    if not is_literal(core):
        return None
    lit = unescape(core)
    if anchored_start and anchored_end:
        return ("equals", lit)
    if anchored_start:
        return ("startswith", lit)
    if anchored_end:
        return ("endswith", lit)
    # bare literal: Java regex `find` semantics for RLike = contains
    return ("contains", lit)



def _dfa_cap(ctx):
    """Per-session DFA state cap (spark.rapids.tpu.regex.maxDfaStates)."""
    if ctx is None:
        return None
    from ..config import REGEX_MAX_DFA_STATES
    try:
        return ctx.conf.get(REGEX_MAX_DFA_STATES)
    except Exception:  # noqa: BLE001 — eval ctx without conf
        return None

class RLike(Expression):
    """rlike / regexp: Java `find` semantics (reference GpuRLike)."""

    def __init__(self, child: Expression, pattern: str):
        self.children = (child,)
        self.pattern = pattern
        self._transpiled = transpile(pattern)
        self._rewrite = (literal_prefix_rewrite(pattern)
                         if self._transpiled is not None else None)

    tpu_supported = property(lambda self: self._transpiled is not None)  # type: ignore

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def pretty(self) -> str:
        return f"{self.children[0].pretty()} RLIKE {self.pattern!r}"

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .base import Literal
        c = self.children[0]
        if self._rewrite is not None:
            kind, lit = self._rewrite
            if kind == "startswith":
                return StartsWith(c, Literal(lit)).eval_tpu(batch, ctx)
            if kind == "endswith":
                return EndsWith(c, Literal(lit)).eval_tpu(batch, ctx)
            if kind == "contains":
                return Contains(c, Literal(lit)).eval_tpu(batch, ctx)
            # equals
            from .predicates import EqualTo
            return EqualTo(c, Literal(lit)).eval_tpu(batch, ctx)
        col = c.eval_tpu(batch, ctx)
        out = self._device_dfa_match(col, batch, ctx)
        if out is not None:
            return out
        import pyarrow.compute as pc
        arr = _to_arrow_side(col, batch)
        out = pc.match_substring_regex(arr, pattern=self._transpiled)
        return _bool_result_from_arrow(out, batch)

    def _device_dfa_match(self, col, batch, ctx=None):
        """Compiled byte-DFA table walk on device (kernels/regex_dfa.py), or
        None when the pattern/column is outside the device subset."""
        import jax.numpy as jnp

        from ..kernels import strings as SK
        from ..kernels.regex_dfa import (MAX_DEVICE_ROW_BYTES, compile_dfa,
                                         rlike_device)
        from .base import combine_validity, make_column, row_mask
        from .strings import _dev_str
        dfa = compile_dfa(self.pattern, _dfa_cap(ctx))
        if dfa is None or not _dev_str(col):
            return None
        if not dfa.ascii_atoms and not SK.is_ascii(col.data):
            return None  # byte/char mismatch possible: host engine decides
        cap_bytes = MAX_DEVICE_ROW_BYTES
        if ctx is not None:
            from ..config import REGEX_MAX_DEVICE_ROW_BYTES
            cap_bytes = ctx.conf.get(REGEX_MAX_DEVICE_ROW_BYTES)
        lens = col.offsets[1:] - col.offsets[:-1]
        max_len = int(jnp.max(lens)) if int(lens.shape[0]) else 0
        if max_len > cap_bytes:
            return None  # pathological rows: lock-step walk too deep
        data = rlike_device(col.data, col.offsets, batch.num_rows, dfa,
                            max_len)
        valid = combine_validity(batch.capacity, col.validity,
                                 row_mask(batch.num_rows, batch.capacity))
        return make_column(BooleanT, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.children[0].eval_cpu(table, ctx).to_pylist()
        prog = _re.compile(self.pattern)
        return pa.array([None if v is None else prog.search(v) is not None
                         for v in vals], pa.bool_())


class RegexpReplace(Expression):
    def __init__(self, child: Expression, pattern: str, replacement: str):
        self.children = (child,)
        self.pattern = pattern
        self.replacement = replacement
        self._transpiled = transpile(pattern)

    tpu_supported = property(lambda self: self._transpiled is not None)  # type: ignore

    @property
    def dtype(self) -> DataType:
        return StringT

    def pretty(self) -> str:
        return (f"regexp_replace({self.children[0].pretty()}, "
                f"{self.pattern!r}, {self.replacement!r})")

    def _java_to_py_repl(self) -> str:
        # Java uses $1; python re uses \1
        return _re.sub(r"\$(\d+)", r"\\\1", self.replacement)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        col = self.children[0].eval_tpu(batch, ctx)
        out = self._device_replace(col, batch, ctx)
        if out is not None:
            return out
        arr = _to_arrow_side(col, batch)
        prog = _re.compile(self._transpiled)
        if prog.match(""):
            # empty-matchable patterns: arrow's RE2 global replace advances
            # differently from Java after a non-empty match ('c?' on "xcx":
            # re2 → yxyxy, Java/python → yxyyxy — found by the regex fuzzer);
            # keep those on the python engine that matches Java
            repl = self._java_to_py_repl()
            out = pa.array([None if v is None else prog.sub(repl, v)
                            for v in arr.to_pylist()], pa.string())
        else:
            out = pc.replace_substring_regex(
                arr, pattern=self._transpiled,
                replacement=self._java_to_py_repl())
        return _string_result_from_arrow(out, batch)

    def _device_replace(self, col, batch, ctx=None):
        """DFA span matching + device byte assembly over HBM buffers, or
        None when pattern/replacement/column are outside the device subset
        (reference: cuDF regex replace kernels behind
        CudfRegexTranspiler/RegexParser.scala:687)."""
        from ..columnar.vector import bucket_capacity
        from ..kernels import strings as SK
        from ..kernels.regex_dfa import (MAX_DEVICE_SPAN_ROW_BYTES,
                                         compile_exact_dfa,
                                         match_lengths_device,
                                         select_leftmost_nonoverlapping)
        if "$" in self.replacement or "\\" in self.replacement:
            return None  # group refs / escapes: host engine
        dfa = compile_exact_dfa(self.pattern, _dfa_cap(ctx))
        if dfa is None or not _dev_str(col):
            return None
        if not dfa.ascii_atoms and not SK.is_ascii(col.data):
            return None
        span_cap = MAX_DEVICE_SPAN_ROW_BYTES
        if ctx is not None:
            from ..config import REGEX_MAX_SPAN_ROW_BYTES
            span_cap = ctx.conf.get(REGEX_MAX_SPAN_ROW_BYTES)
        lens = col.offsets[1:] - col.offsets[:-1]
        max_len = int(jnp.max(lens)) if int(lens.shape[0]) else 0
        if max_len > span_cap:
            return None
        data, offsets = col.data, col.offsets
        nbytes = int(data.shape[0])
        repl = np.frombuffer(self.replacement.encode(), np.uint8)
        rlen = int(repl.shape[0])
        mlen = match_lengths_device(data, offsets, dfa, max_len)
        taken = select_leftmost_nonoverlapping(mlen, offsets, max_len)
        # covered bytes: +1 at taken starts, -1 at their (exclusive) ends
        pos = jnp.arange(nbytes, dtype=jnp.int32)
        delta = jnp.zeros((nbytes + 1,), jnp.int32)
        delta = delta.at[jnp.where(taken, pos, nbytes)].add(1, mode="drop")
        delta = delta.at[jnp.where(taken, pos + mlen, nbytes)].add(
            -1, mode="drop")
        covered = jnp.cumsum(delta[:-1]) > 0
        if rlen <= dfa.min_len:
            out_cap = max(nbytes, 1)
        else:
            out_cap = bucket_capacity(
                (nbytes // dfa.min_len) * rlen + nbytes)
        out, offs = SK.build_from_contributions(
            data, ~covered, offsets, out_cap,
            replace_at=taken, replacement=repl)
        from .strings import _str_col
        return _str_col(batch, out, offs, col.validity, col)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.children[0].eval_cpu(table, ctx).to_pylist()
        prog = _re.compile(self.pattern)
        repl = self._java_to_py_repl()
        return pa.array([None if v is None else prog.sub(repl, v)
                         for v in vals], pa.string())


class RegexpExtract(Expression):
    def __init__(self, child: Expression, pattern: str, group: int = 1):
        self.children = (child,)
        self.pattern = pattern
        self.group = group
        self._transpiled = transpile(pattern)

    tpu_supported = property(lambda self: self._transpiled is not None)  # type: ignore

    @property
    def dtype(self) -> DataType:
        return StringT

    def pretty(self) -> str:
        return (f"regexp_extract({self.children[0].pretty()}, "
                f"{self.pattern!r}, {self.group})")

    def _extract(self, vals):
        prog = _re.compile(self.pattern)
        out = []
        for v in vals:
            if v is None:
                out.append(None)
                continue
            m = prog.search(v)
            if m is None:
                out.append("")  # Spark: no match → empty string
            else:
                g = m.group(self.group)
                out.append(g if g is not None else "")
        return out

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        col = self.children[0].eval_tpu(batch, ctx)
        out = self._device_extract(col, batch, ctx)
        if out is not None:
            return out
        arr = _to_arrow_side(col, batch)
        out = pa.array(self._extract(arr.to_pylist()), pa.string())
        return _string_result_from_arrow(out, batch)

    def _device_extract(self, col, batch, ctx=None):
        """Whole-match (group 0) extraction on device: first match span via
        the exact DFA, then a ranged gather. Capture groups (>0) stay on the
        host engine."""
        from ..columnar.vector import bucket_capacity
        from ..kernels import strings as SK
        from ..kernels.regex_dfa import (MAX_DEVICE_SPAN_ROW_BYTES,
                                         compile_exact_dfa,
                                         match_lengths_device)
        if self.group != 0:
            return None
        dfa = compile_exact_dfa(self.pattern, _dfa_cap(ctx))
        if dfa is None or not _dev_str(col):
            return None
        if not dfa.ascii_atoms and not SK.is_ascii(col.data):
            return None
        span_cap = MAX_DEVICE_SPAN_ROW_BYTES
        if ctx is not None:
            from ..config import REGEX_MAX_SPAN_ROW_BYTES
            span_cap = ctx.conf.get(REGEX_MAX_SPAN_ROW_BYTES)
        lens = col.offsets[1:] - col.offsets[:-1]
        max_len = int(jnp.max(lens)) if int(lens.shape[0]) else 0
        if max_len > span_cap:
            return None
        data, offsets = col.data, col.offsets
        nbytes = int(data.shape[0])
        n = int(offsets.shape[0]) - 1
        if nbytes == 0 or n == 0:
            from .strings import _str_col
            return _str_col(batch, data, offsets, col.validity, col)
        mlen = match_lengths_device(data, offsets, dfa, max_len)
        rows = SK.byte_rows(offsets, nbytes)
        pos = jnp.arange(nbytes, dtype=jnp.int32)
        big = jnp.int32(nbytes)
        first = SK.segment_min(jnp.where(mlen > 0, pos, big), rows, n,
                               init=jnp.int32(nbytes))
        found = first < big
        start = jnp.where(found, first, 0)
        length = jnp.where(found,
                           mlen[jnp.clip(start, 0, nbytes - 1)],
                           0)  # Spark: no match → empty string
        out_cap = bucket_capacity(nbytes)
        out, offs = SK.build_ranges(data, start.astype(jnp.int32),
                                    length.astype(jnp.int32), out_cap)
        from .strings import _str_col
        return _str_col(batch, out, offs, col.validity, col)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.children[0].eval_cpu(table, ctx).to_pylist()
        return pa.array(self._extract(vals), pa.string())


class Like(Expression):
    """SQL LIKE: % and _ wildcards with escape (reference GpuLike)."""

    def __init__(self, child: Expression, pattern: str, escape: str = "\\"):
        self.children = (child,)
        self.pattern = pattern
        self.escape = escape

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def pretty(self) -> str:
        return f"{self.children[0].pretty()} LIKE {self.pattern!r}"

    def _to_regex(self) -> str:
        out = ["^"]
        i = 0
        p = self.pattern
        while i < len(p):
            ch = p[i]
            if ch == self.escape and i + 1 < len(p):
                out.append(_re.escape(p[i + 1]))
                i += 2
                continue
            if ch == "%":
                out.append(".*")
            elif ch == "_":
                out.append(".")
            else:
                out.append(_re.escape(ch))
            i += 1
        out.append("$")
        return "".join(out)

    def _segments(self):
        """Parse the LIKE pattern into %-separated segments of
        (bytes, wildcard-mask) — `_` positions match any single char."""
        segs = [[]]
        p, esc = self.pattern, self.escape
        i = 0
        while i < len(p):
            ch = p[i]
            if ch == esc and i + 1 < len(p):
                segs[-1].append((p[i + 1], False))
                i += 2
                continue
            if ch == "%":
                segs.append([])
            elif ch == "_":
                segs[-1].append(("\0", True))
            else:
                segs[-1].append((ch, False))
            i += 1
        out = []
        for seg in segs:
            b = np.array([ord(c) for c, _ in seg], dtype=np.uint8)
            w = np.array([wild for _, wild in seg], dtype=bool)
            out.append((b, w))
        return out

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        from ..kernels import strings as SK
        c = self.children[0].eval_tpu(batch, ctx)
        if _dev_str(c) and self.pattern.isascii() and SK.is_ascii(c.data):
            cap = c.capacity
            starts = c.offsets[:-1]
            lens = c.offsets[1:] - starts
            nbytes = int(c.data.shape[0])
            segs = self._segments()
            valid = combine_validity(cap, c.validity,
                                     row_mask(batch.num_rows, cap))
            if nbytes == 0:
                ok = jnp.full((cap,), all(len(b) == 0 for b, _ in segs),
                              jnp.bool_) & (lens == 0) if len(segs) == 1 \
                    else jnp.full((cap,), all(len(b) == 0 for b, _ in segs),
                                  jnp.bool_)
                return make_column(BooleanT, ok, valid, batch.num_rows)

            def hit_at(hit, pos_in_row, seg_len):
                """hit gathered at per-row byte position (row-relative)."""
                idx = jnp.clip(starts + pos_in_row, 0, nbytes - 1)
                ok_pos = (pos_in_row >= 0) & (pos_in_row + seg_len <= lens)
                return jnp.where(ok_pos, hit[idx], False)

            if len(segs) == 1:
                b, w = segs[0]
                if len(b) == 0:
                    ok = lens == 0
                else:
                    hit = SK.match_windows(c.data, c.offsets, b, w)
                    ok = (lens == len(b)) & hit_at(hit, jnp.zeros_like(lens),
                                                   len(b))
                return make_column(BooleanT, ok, valid, batch.num_rows)
            ok = jnp.ones((cap,), jnp.bool_)
            cur = jnp.zeros((cap,), jnp.int32)
            first_b, first_w = segs[0]
            if len(first_b):
                hit = SK.match_windows(c.data, c.offsets, first_b, first_w)
                ok = ok & hit_at(hit, jnp.zeros_like(lens), len(first_b))
                cur = jnp.full((cap,), len(first_b), jnp.int32)
            for b, w in segs[1:-1]:
                if len(b) == 0:
                    continue
                pos = SK.first_match(c.data, c.offsets, b, from_pos=cur,
                                     wildcard=w)
                ok = ok & (pos >= 0)
                cur = jnp.where(pos >= 0, pos + len(b), cur)
            last_b, last_w = segs[-1]
            if len(last_b):
                hit = SK.match_windows(c.data, c.offsets, last_b, last_w)
                tail = lens - len(last_b)
                ok = ok & (tail >= cur) & hit_at(hit, tail, len(last_b))
            return make_column(BooleanT, ok, valid, batch.num_rows)
        arr = _to_arrow_side(c, batch)
        out = self._match_host(arr)
        return _bool_result_from_arrow(out, batch)

    def _match_host(self, arr):
        """Host LIKE via the regex translation — arrow's match_like treats a
        backslash before a NON-wildcard as a literal backslash, unlike
        Spark/Java where \\x is always the literal x (found by the LIKE
        fuzzer: 'c\\b%' vs 'cb...')."""
        import pyarrow as pa
        prog = _re.compile(self._to_regex(), _re.DOTALL)
        # fullmatch: '$' alone would accept a trailing newline (python quirk)
        return pa.array([None if v is None else bool(prog.fullmatch(v))
                         for v in arr.to_pylist()], pa.bool_())

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        return self._match_host(self.children[0].eval_cpu(table, ctx))


class RegexpExtractAll(Expression):
    """regexp_extract_all(str, pattern, idx) → array<string>
    (reference GpuRegExpExtractAll)."""

    def __init__(self, child: Expression, pattern: str, group: int = 1):
        self.children = (child,)
        self.pattern = pattern
        self.group = group
        self._transpiled = transpile(pattern)

    tpu_supported = property(lambda self: self._transpiled is not None)  # type: ignore

    @property
    def dtype(self) -> DataType:
        from ..types import ArrayType
        return ArrayType(StringT, contains_null=False)

    def pretty(self) -> str:
        return (f"regexp_extract_all({self.children[0].pretty()}, "
                f"{self.pattern!r}, {self.group})")

    def _extract(self, vals):
        prog = _re.compile(self.pattern)
        out = []
        for v in vals:
            if v is None:
                out.append(None)
                continue
            row = []
            for m in prog.finditer(v):
                g = m.group(self.group)
                row.append(g if g is not None else "")
            out.append(row)
        return out

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        arr = _to_arrow_side(self.children[0].eval_tpu(batch, ctx), batch)
        out = pa.array(self._extract(arr.to_pylist()),
                       pa.list_(pa.string()))
        col = TpuColumnVector.from_arrow(out)
        if col.capacity < batch.capacity:
            from ..columnar.batch import _repad
            col = _repad(col, batch.capacity)
        return col

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.children[0].eval_cpu(table, ctx).to_pylist()
        return pa.array(self._extract(vals), pa.list_(pa.string()))
