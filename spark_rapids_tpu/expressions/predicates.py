"""Comparison and boolean predicates with Spark semantics.

Reference: /root/reference/sql-plugin/src/main/scala/org/apache/spark/sql/rapids/
predicates.scala. Spark quirks preserved:
  * NaN is equal to NaN and sorts greater than any other double (unlike IEEE);
    the reference normalizes NaN via cuDF NaNEquality — here we branch in XLA.
  * AND/OR use Kleene three-valued logic (false AND null = false, true OR null = true).
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..types import BooleanT, DataType, StringType
from ..columnar.vector import TpuColumnVector, TpuScalar, row_mask
from .base import (BinaryExpression, EvalContext, Expression, UnaryExpression,
                   _DEFAULT_CTX, combine_validity, device_parts, make_column)


def _is_float(d) -> bool:
    return jnp.issubdtype(d.dtype, jnp.floating)


def nan_aware_eq(l, r):
    out = l == r
    if _is_float(l):
        out = out | (jnp.isnan(l) & jnp.isnan(r))
    return out


def nan_aware_lt(l, r):
    if _is_float(l):
        # NaN is greatest: l < r iff (l not nan and r nan) or plain l < r
        return (~jnp.isnan(l) & jnp.isnan(r)) | (l < r)
    return l < r


def nan_aware_le(l, r):
    if _is_float(l):
        return jnp.isnan(r) | ((~jnp.isnan(l)) & (l <= r))
    return l <= r


class BinaryComparison(BinaryExpression):
    symbol = "?"

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def pretty(self) -> str:
        return f"({self.children[0].pretty()} {self.symbol} {self.children[1].pretty()})"

    def _device_cmp(self, l, r):
        raise NotImplementedError

    def _np_cmp(self, l, r):
        raise NotImplementedError

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from . import strings as _s
        l = self.left.eval_tpu(batch, ctx)
        r = self.right.eval_tpu(batch, ctx)
        if isinstance(self.left.dtype, StringType):
            return _s.string_compare(self, l, r, batch)
        cap = batch.capacity
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        valid = combine_validity(cap, lv, rv, row_mask(batch.num_rows, cap))
        data = self._device_cmp(ld, rd)
        return make_column(BooleanT, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        l = self.left.eval_cpu(table, ctx)
        r = self.right.eval_cpu(table, ctx)
        # date32 vs integer: compare as day numbers, mirroring the device
        # plane (which stores date32 as int32 days)
        l, r = _align_date_int(pa, l, r)
        lt = l.type if isinstance(l, (pa.Array, pa.ChunkedArray)) else None
        if lt is not None and (pa.types.is_floating(lt)) and _has_nan(l, r):
            return self._cpu_nan_path(l, r)
        return self._arrow_cmp(pc, l, r)

    def _cpu_nan_path(self, l, r):
        import pyarrow as pa
        ln, lm = _to_np(l)
        rn, rm = _to_np(r)
        with np.errstate(invalid="ignore"):
            out = self._np_cmp(ln, rn)
        return pa.array(out, mask=lm | rm)


def _align_date_int(pa, l, r):
    """Cast date32 to int32 day numbers when the comparison's other side is
    an integer (scalar or array); no-op otherwise."""
    def is_date(x):
        return (isinstance(x, (pa.Array, pa.ChunkedArray))
                and pa.types.is_date32(x.type))

    def is_int(x):
        if isinstance(x, (pa.Array, pa.ChunkedArray)):
            return pa.types.is_integer(x.type)
        return isinstance(x, (int, np.integer)) and not isinstance(x, bool)

    if is_date(l) and is_int(r):
        l = l.cast(pa.int32())
    elif is_date(r) and is_int(l):
        r = r.cast(pa.int32())
    return l, r


def _has_nan(l, r) -> bool:
    import pyarrow as pa
    import pyarrow.compute as pc
    for x in (l, r):
        if isinstance(x, (pa.Array, pa.ChunkedArray)):
            if bool(pc.any(pc.fill_null(pc.is_nan(x), False)).as_py()):
                return True
        elif isinstance(x, float) and np.isnan(x):
            return True
    return False


def _to_np(x):
    import pyarrow as pa
    import pyarrow.compute as pc
    if isinstance(x, (pa.Array, pa.ChunkedArray)):
        arr = x.combine_chunks() if isinstance(x, pa.ChunkedArray) else x
        mask = np.asarray(pc.is_null(arr).to_numpy(zero_copy_only=False)).astype(bool)
        vals = np.asarray(arr.fill_null(0).to_numpy(zero_copy_only=False))
        # restore NaNs that fill_null(0) left intact (only nulls were replaced)
        return vals, mask
    return np.asarray(x), np.zeros(1, dtype=bool)


class EqualTo(BinaryComparison):
    symbol = "="

    def _device_cmp(self, l, r):
        return nan_aware_eq(l, r)

    def _np_cmp(self, l, r):
        return (l == r) | (np.isnan(l) & np.isnan(r))

    def _arrow_cmp(self, pc, l, r):
        return pc.equal(l, r)


class LessThan(BinaryComparison):
    symbol = "<"

    def _device_cmp(self, l, r):
        return nan_aware_lt(l, r)

    def _np_cmp(self, l, r):
        return (~np.isnan(l) & np.isnan(r)) | (l < r)

    def _arrow_cmp(self, pc, l, r):
        return pc.less(l, r)


class LessThanOrEqual(BinaryComparison):
    symbol = "<="

    def _device_cmp(self, l, r):
        return nan_aware_le(l, r)

    def _np_cmp(self, l, r):
        return np.isnan(r) | (~np.isnan(l) & (l <= r))

    def _arrow_cmp(self, pc, l, r):
        return pc.less_equal(l, r)


class GreaterThan(BinaryComparison):
    symbol = ">"

    def _device_cmp(self, l, r):
        return nan_aware_lt(r, l)

    def _np_cmp(self, l, r):
        return (~np.isnan(r) & np.isnan(l)) | (l > r)

    def _arrow_cmp(self, pc, l, r):
        return pc.greater(l, r)


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="

    def _device_cmp(self, l, r):
        return nan_aware_le(r, l)

    def _np_cmp(self, l, r):
        return np.isnan(l) | (~np.isnan(r) & (l >= r))

    def _arrow_cmp(self, pc, l, r):
        return pc.greater_equal(l, r)


class EqualNullSafe(BinaryComparison):
    """`<=>`: null-safe equality — never returns null."""
    symbol = "<=>"

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from . import strings as _s
        l = self.left.eval_tpu(batch, ctx)
        r = self.right.eval_tpu(batch, ctx)
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        if isinstance(self.left.dtype, StringType):
            eq = _s.string_compare(EqualTo(self.left, self.right), l, r, batch)
            lv = l.validity_or_true() if isinstance(l, TpuColumnVector) else (
                jnp.zeros((cap,), jnp.bool_) if l.is_null else mask)
            rv = r.validity_or_true() if isinstance(r, TpuColumnVector) else (
                jnp.zeros((cap,), jnp.bool_) if r.is_null else mask)
            data = jnp.where(lv & rv, eq.data, lv == rv)
            return make_column(BooleanT, data & mask | (~mask & False), None, batch.num_rows)
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        lv = lv if lv is not None else mask
        rv = rv if rv is not None else mask
        both = lv & rv
        data = jnp.where(both, nan_aware_eq(ld, rd), lv == rv) & mask
        return make_column(BooleanT, data, None, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        l = self.left.eval_cpu(table, ctx)
        r = self.right.eval_cpu(table, ctx)
        lt = l.type if isinstance(l, (pa.Array, pa.ChunkedArray)) else None
        if lt is not None and pa.types.is_floating(lt) and _has_nan(l, r):
            ln, lm = _to_np(l)
            rn, rm = _to_np(r)
            with np.errstate(invalid="ignore"):
                eq = (ln == rn) | (np.isnan(ln) & np.isnan(rn))
            out = np.where(~lm & ~rm, eq, lm == rm)
            return pa.array(out)
        eq = pc.equal(l, r)
        lnull = pc.is_null(l) if isinstance(l, (pa.Array, pa.ChunkedArray)) else pa.scalar(l is None)
        rnull = pc.is_null(r) if isinstance(r, (pa.Array, pa.ChunkedArray)) else pa.scalar(r is None)
        both_null = pc.and_(lnull, rnull)
        return pc.if_else(pc.is_null(eq), both_null, eq)


class And(BinaryExpression):
    """Kleene AND (reference GpuAnd)."""

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        l = self.left.eval_tpu(batch, ctx)
        r = self.right.eval_tpu(batch, ctx)
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        lv = lv if lv is not None else mask
        rv = rv if rv is not None else mask
        lfalse = lv & ~ld.astype(jnp.bool_)
        rfalse = rv & ~rd.astype(jnp.bool_)
        valid = (lv & rv) | lfalse | rfalse
        data = ld.astype(jnp.bool_) & rd.astype(jnp.bool_)
        return make_column(BooleanT, data & valid, valid & mask, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.and_kleene(self.left.eval_cpu(table, ctx),
                             self.right.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"({self.children[0].pretty()} AND {self.children[1].pretty()})"


class Or(BinaryExpression):
    """Kleene OR."""

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        l = self.left.eval_tpu(batch, ctx)
        r = self.right.eval_tpu(batch, ctx)
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        lv = lv if lv is not None else mask
        rv = rv if rv is not None else mask
        ltrue = lv & ld.astype(jnp.bool_)
        rtrue = rv & rd.astype(jnp.bool_)
        valid = (lv & rv) | ltrue | rtrue
        data = ltrue | rtrue
        return make_column(BooleanT, data, valid & mask, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.or_kleene(self.left.eval_cpu(table, ctx),
                            self.right.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"({self.children[0].pretty()} OR {self.children[1].pretty()})"


class Not(UnaryExpression):
    @property
    def dtype(self) -> DataType:
        return BooleanT

    def _compute(self, d, ctx, valid):
        return ~d.astype(jnp.bool_)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.invert(self.child.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"NOT {self.child.pretty()}"


class In(Expression):
    """`value IN (literals…)` with Spark null semantics: null value → null;
    no match with a null in the list → null (reference GpuInSet)."""

    def __init__(self, value: Expression, items: List[Expression]):
        self.children = (value, *items)

    @property
    def value(self) -> Expression:
        return self.children[0]

    @property
    def items(self):
        return self.children[1:]

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .base import Literal
        from ..types import StringType
        if isinstance(self.value.dtype, StringType):
            # strings have no dense device scalar form; lower to an OR of
            # equalities (exactly Spark's IN null semantics: any-true → true,
            # else any-null → null, else false), served by the device string
            # equality kernel
            import functools
            legs = [EqualTo(self.value, item) for item in self.items]
            if not legs:
                return Literal(False, BooleanT).eval_tpu(batch, ctx)
            return functools.reduce(Or, legs).eval_tpu(batch, ctx)
        v = self.value.eval_tpu(batch, ctx)
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        vd, vv = device_parts(v, cap)
        vv = vv if vv is not None else mask
        has_null_item = any(isinstance(i, Literal) and i.value is None for i in self.items)
        found = jnp.zeros((cap,), jnp.bool_)
        for item in self.items:
            iv = item.eval_tpu(batch, ctx)
            if isinstance(iv, TpuScalar) and iv.is_null:
                continue
            idata, ivalid = device_parts(iv, cap)
            hit = nan_aware_eq(vd, idata)
            if ivalid is not None:
                hit = hit & ivalid
            found = found | hit
        if has_null_item:
            valid = vv & (found | jnp.zeros((cap,), jnp.bool_)) & mask
            valid = vv & found & mask  # unmatched rows become null
        else:
            valid = vv & mask
        return make_column(BooleanT, found & vv, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        from .base import Literal
        v = self.value.eval_cpu(table, ctx)
        vals = [i.value for i in self.items if isinstance(i, Literal)]
        has_null = any(x is None for x in vals)
        non_null = [x for x in vals if x is not None]
        vset = pa.array(non_null, type=v.type if isinstance(v, (pa.Array, pa.ChunkedArray)) else None)
        found = pc.is_in(v, value_set=vset)
        if has_null:
            found = pc.if_else(found, True, pa.scalar(None, pa.bool_()))
        return pc.if_else(pc.is_null(v), pa.scalar(None, pa.bool_()), found)

    def pretty(self) -> str:
        return f"{self.value.pretty()} IN ({', '.join(i.pretty() for i in self.items)})"


class InSet(Expression):
    """`value IN <set>` for a pre-materialized literal set — the optimizer's
    large-list form of IN (reference GpuInSet). Device: one jnp.isin over a
    constant device array (no per-item loop)."""

    def __init__(self, value: Expression, items):
        self.children = (value,)
        self.items = list(items)
        self._has_null = any(i is None for i in self.items)
        self._non_null = [i for i in self.items if i is not None]

    @property
    def value(self) -> Expression:
        return self.children[0]

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import numpy as np
        from ..types import StringType
        v = self.value.eval_tpu(batch, ctx)
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        if isinstance(self.value.dtype, StringType):
            # strings: reuse the In item-loop via literals (host hop avoided
            # only for fixed-width carriers)
            from .base import Literal
            return In(self.value,
                      [Literal(i) for i in self.items]).eval_tpu(batch, ctx)
        vd, vv = device_parts(v, cap)
        vv = vv if vv is not None else mask
        if self._non_null:
            items = jnp.asarray(np.array(self._non_null, dtype=vd.dtype))
            found = jnp.isin(jnp.broadcast_to(vd, (cap,)), items)
            if jnp.issubdtype(vd.dtype, jnp.floating) and \
                    any(isinstance(i, float) and i != i for i in self._non_null):
                found = found | jnp.isnan(vd)
        else:
            found = jnp.zeros((cap,), jnp.bool_)
        if self._has_null:
            valid = vv & found & mask  # unmatched rows become null
        else:
            valid = vv & mask
        return make_column(BooleanT, found & vv, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import math
        import pyarrow as pa
        vals = self.value.eval_cpu(table, ctx).to_pylist()
        non_null = self._non_null
        has_nan = any(isinstance(i, float) and math.isnan(i) for i in non_null)
        out = []
        for v in vals:
            if v is None:
                out.append(None)
            elif isinstance(v, float) and math.isnan(v):
                out.append(True if has_nan else (None if self._has_null else False))
            elif any(v == i for i in non_null
                     if not (isinstance(i, float) and math.isnan(i))):
                out.append(True)
            else:
                out.append(None if self._has_null else False)
        return pa.array(out, pa.bool_())

    def pretty(self) -> str:
        return f"{self.value.pretty()} INSET ({len(self.items)} values)"
