"""Hash functions — Spark-exact Murmur3 (seed 42) and xxhash64.

Reference: HashFunctions.scala + spark-rapids-jni `Hash` CUDA kernels. Spark's
hash() is Murmur3 x86_32 applied per column with the running hash as seed, nulls
skipped. It is THE shuffle-partitioning hash (HashPartitioning), so bit-exactness
here is what makes TPU↔CPU shuffles agree (reference GpuHashPartitioningBase).

Device implementation uses uint32 arithmetic (wrapping multiplies) which XLA
lowers to the VPU; strings hash on device when the column fits a padded byte
matrix, host otherwise.
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from ..types import (BooleanType, ByteType, DataType, DateType, DoubleType,
                     FloatType, IntegerT, IntegerType, LongType, ShortType,
                     StringType, TimestampType)
from ..columnar.vector import TpuColumnVector, TpuScalar, row_mask
from .base import (Expression, UnaryExpression, _DEFAULT_CTX, device_parts,
                   make_column)

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl(x, r):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    k1 = (k1 * _C1).astype(jnp.uint32)
    k1 = _rotl(k1, 15)
    return (k1 * _C2).astype(jnp.uint32)


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13)
    return (h1 * np.uint32(5) + np.uint32(0xE6546B64)).astype(jnp.uint32)


def _fmix(h1, length):
    h1 = h1 ^ jnp.uint32(length)
    h1 = h1 ^ (h1 >> 16)
    h1 = (h1 * np.uint32(0x85EBCA6B)).astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 13)
    h1 = (h1 * np.uint32(0xC2B2AE35)).astype(jnp.uint32)
    return h1 ^ (h1 >> 16)


def murmur3_int(values_u32, seed_u32):
    """hashInt: one 4-byte block."""
    h1 = _mix_h1(seed_u32, _mix_k1(values_u32))
    return _fmix(h1, 4)


def murmur3_long(values_i64, seed_u32):
    """hashLong: low word then high word."""
    lo = (values_i64 & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = ((values_i64 >> 32) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    h1 = _mix_h1(seed_u32, _mix_k1(lo))
    h1 = _mix_h1(h1, _mix_k1(hi))
    return _fmix(h1, 8)


def _normalize_double(d):
    """Spark: -0.0 → 0.0 and NaN → canonical NaN bits before hashing."""
    d = jnp.where(d == 0.0, jnp.zeros((), d.dtype), d)
    canon = jnp.asarray(np.float64(np.nan), d.dtype)
    return jnp.where(jnp.isnan(d), canon, d)


def murmur3_col(col: TpuColumnVector, seed, capacity: int):
    """Hash one device column, returning updated per-row seeds (uint32).
    Null rows keep their incoming seed (Spark skips nulls)."""
    dt = col.dtype
    d = col.data
    if isinstance(dt, (BooleanType,)):
        h = murmur3_int(d.astype(jnp.uint32), seed)
    elif isinstance(dt, (ByteType, ShortType, IntegerType, DateType)):
        h = murmur3_int(d.astype(jnp.int32).view(jnp.uint32), seed)
    elif isinstance(dt, (LongType, TimestampType)):
        h = murmur3_long(d.astype(jnp.int64), seed)
    elif isinstance(dt, FloatType):
        f = _normalize_float(d)
        h = murmur3_int(f.view(jnp.uint32), seed)
    elif isinstance(dt, DoubleType):
        f = _normalize_double(d)
        h = murmur3_long(f.view(jnp.int64), seed)
    elif isinstance(dt, StringType):
        h = _murmur3_string_device(col, seed, capacity)
    else:
        raise NotImplementedError(f"murmur3 of {dt}")
    if col.validity is not None:
        h = jnp.where(col.validity, h, seed)
    return h


def _normalize_float(d):
    d = jnp.where(d == 0.0, jnp.zeros((), d.dtype), d)
    canon = jnp.asarray(np.float32(np.nan), d.dtype)
    return jnp.where(jnp.isnan(d), canon, d)


def _murmur3_string_device(col: TpuColumnVector, seed, capacity: int):
    """Spark hashUnsafeBytes: 4-byte little-endian blocks, then a *signed-byte*
    tail loop (each remaining byte hashed via hashInt of its signed value).
    Implemented as a padded gather: rows are processed in max_len/4 block steps.
    Cost is O(cap * max_len) — fine for typical key strings; long-tail columns
    should be hashed host-side (tagging prices this)."""
    starts = col.offsets[:-1]
    lens = (col.offsets[1:] - starts).astype(jnp.int32)
    max_len = int(jnp.max(lens)) if col.num_rows else 0
    nblocks = max_len // 4
    h1 = jnp.broadcast_to(seed, (capacity,)).astype(jnp.uint32)
    data = col.data
    ncap = max(int(data.shape[0]) - 1, 0)
    for b in range(nblocks):
        base = starts + 4 * b
        idx = jnp.clip(base[:, None] + jnp.arange(4)[None, :], 0, ncap)
        bytes4 = jnp.take(data, idx).astype(jnp.uint32)
        word = (bytes4[:, 0] | (bytes4[:, 1] << 8) | (bytes4[:, 2] << 16)
                | (bytes4[:, 3] << 24))
        active = lens >= 4 * (b + 1)
        new_h1 = _mix_h1(h1, _mix_k1(word))
        h1 = jnp.where(active, new_h1, h1)
    max_tail = max_len % 4 if max_len else 0
    # tail bytes: Spark treats each as SIGNED int, one mix per byte
    for t in range(3):
        pos = (lens // 4) * 4 + t
        idx = jnp.clip(starts + pos, 0, ncap)
        byte = jnp.take(data, idx).astype(jnp.int8)
        signed = byte.astype(jnp.int32).view(jnp.uint32)
        active = pos < lens
        new_h1 = _mix_h1(h1, _mix_k1(signed))
        h1 = jnp.where(active, new_h1, h1)
    return _fmix_lengths(h1, lens)


def _fmix_lengths(h1, lens):
    h1 = h1 ^ lens.view(jnp.uint32) if lens.dtype == jnp.int32 else h1 ^ lens.astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 16)
    h1 = (h1 * np.uint32(0x85EBCA6B)).astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 13)
    h1 = (h1 * np.uint32(0xC2B2AE35)).astype(jnp.uint32)
    return h1 ^ (h1 >> 16)


def murmur3_batch(cols: Sequence[TpuColumnVector], num_rows: int, capacity: int,
                  seed: int = 42):
    """Row hash over several columns (Spark HashExpression fold)."""
    h = jnp.full((capacity,), np.uint32(seed), jnp.uint32)
    for c in cols:
        h = murmur3_col(c, h, capacity)
    return h.view(jnp.int32)


# ---- CPU (numpy) mirror, used by the CPU plan path and tests -----------------

def _np_u32(x):
    return np.asarray(x).astype(np.uint32)


def np_murmur3_int(v_u32, seed_u32):
    k1 = (v_u32 * np.uint32(0xCC9E2D51)).astype(np.uint32)
    k1 = ((k1 << np.uint32(15)) | (k1 >> np.uint32(17))).astype(np.uint32)
    k1 = (k1 * np.uint32(0x1B873593)).astype(np.uint32)
    h1 = (seed_u32 ^ k1).astype(np.uint32)
    h1 = ((h1 << np.uint32(13)) | (h1 >> np.uint32(19))).astype(np.uint32)
    h1 = (h1 * np.uint32(5) + np.uint32(0xE6546B64)).astype(np.uint32)
    h1 ^= np.uint32(4)
    h1 ^= h1 >> np.uint32(16)
    h1 = (h1 * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h1 ^= h1 >> np.uint32(13)
    h1 = (h1 * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h1 ^= h1 >> np.uint32(16)
    return h1


class Murmur3Hash(Expression):
    """hash(...) expression returning int (reference GpuMurmur3Hash)."""

    def __init__(self, *children: Expression, seed: int = 42):
        self.children = tuple(children)
        self.seed = seed

    @property
    def dtype(self) -> DataType:
        return IntegerT

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .base import to_column
        cap = batch.capacity
        cols = [to_column(c.eval_tpu(batch, ctx), batch, c.dtype)
                for c in self.children]
        h = murmur3_batch(cols, batch.num_rows, cap, self.seed)
        return make_column(IntegerT, h, None, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = [c.eval_cpu(table, ctx) for c in self.children]
        n = len(vals[0]) if isinstance(vals[0], (pa.Array, pa.ChunkedArray)) else 1
        out = np.full(n, np.uint32(self.seed), np.uint32)
        for c, v in zip(self.children, vals):
            out = _np_hash_col(c.dtype, v, out)
        return pa.array(out.view(np.int32))

    def pretty(self) -> str:
        return f"hash({', '.join(c.pretty() for c in self.children)})"


def _np_hash_col(dt: DataType, arr, seeds: np.ndarray) -> np.ndarray:
    import pyarrow as pa
    import pyarrow.compute as pc
    from .. import native_bridge
    a = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    nulls = np.asarray(pc.is_null(a).to_numpy(zero_copy_only=False)).astype(bool)
    validity = (~nulls).astype(np.uint8) if nulls.any() else None
    if isinstance(dt, StringType):
        if native_bridge.available():
            s = a.cast(pa.string())
            bufs = s.buffers()
            offsets = np.frombuffer(bufs[1], np.int32, count=len(s) + 1,
                                    offset=s.offset * 4)
            base = offsets[0]
            offsets = (offsets - base).astype(np.int32)
            chars = np.frombuffer(bufs[2], np.uint8,
                                  count=int(offsets[-1]), offset=int(base)) \
                if offsets[-1] else np.zeros(0, np.uint8)
            out = seeds.copy()
            if native_bridge.murmur3_column("str", np.zeros(0), validity, out,
                                            offsets=offsets, chars=chars):
                return out
        out = seeds.copy()
        for i, s in enumerate(a.to_pylist()):
            if s is None:
                continue
            out[i] = _np_murmur3_bytes(s.encode(), seeds[i])
        return out
    if native_bridge.available() and isinstance(
            dt, (ByteType, ShortType, IntegerType, DateType, LongType,
                 TimestampType, FloatType, DoubleType)):
        fill = 0
        vals = np.asarray(a.fill_null(fill).to_numpy(zero_copy_only=False))
        out = seeds.copy()
        kind = {np.dtype(np.float32): "f32", np.dtype(np.float64): "f64"}.get(
            vals.dtype)
        if kind is None:
            kind = "i64" if isinstance(dt, (LongType, TimestampType)) else "i32"
            vals = vals.astype(np.int64 if kind == "i64" else np.int32)
        if native_bridge.murmur3_column(kind, vals, validity, out):
            return out
    fill = False if isinstance(dt, BooleanType) else 0
    vals = np.asarray(a.fill_null(fill).to_numpy(zero_copy_only=False))
    if isinstance(dt, (BooleanType,)):
        h = np_murmur3_int(vals.astype(np.uint32), seeds)
    elif isinstance(dt, (ByteType, ShortType, IntegerType, DateType)):
        h = np_murmur3_int(vals.astype(np.int32).view(np.uint32), seeds)
    elif isinstance(dt, (LongType, TimestampType)):
        h = _np_murmur3_long(vals.astype(np.int64), seeds)
    elif isinstance(dt, FloatType):
        v = vals.astype(np.float32)
        v = np.where(v == 0.0, np.float32(0.0), v)
        v = np.where(np.isnan(v), np.float32(np.nan), v)
        h = np_murmur3_int(v.view(np.uint32), seeds)
    elif isinstance(dt, DoubleType):
        v = vals.astype(np.float64)
        v = np.where(v == 0.0, 0.0, v)
        v = np.where(np.isnan(v), np.nan, v)
        h = _np_murmur3_long(v.view(np.int64), seeds)
    else:
        raise NotImplementedError(f"cpu murmur3 of {dt}")
    return np.where(nulls, seeds, h)


def _np_murmur3_long(v_i64: np.ndarray, seeds: np.ndarray) -> np.ndarray:
    lo = (v_i64 & 0xFFFFFFFF).astype(np.uint32)
    hi = ((v_i64 >> 32) & 0xFFFFFFFF).astype(np.uint32)
    h1 = _np_mix_h1(seeds, _np_mix_k1(lo))
    h1 = _np_mix_h1(h1, _np_mix_k1(hi))
    return _np_fmix(h1, np.uint32(8))


def _np_mix_k1(k1):
    k1 = (k1 * np.uint32(0xCC9E2D51)).astype(np.uint32)
    k1 = ((k1 << np.uint32(15)) | (k1 >> np.uint32(17))).astype(np.uint32)
    return (k1 * np.uint32(0x1B873593)).astype(np.uint32)


def _np_mix_h1(h1, k1):
    h1 = (h1 ^ k1).astype(np.uint32)
    h1 = ((h1 << np.uint32(13)) | (h1 >> np.uint32(19))).astype(np.uint32)
    return (h1 * np.uint32(5) + np.uint32(0xE6546B64)).astype(np.uint32)


def _np_fmix(h1, length):
    h1 = (h1 ^ length).astype(np.uint32)
    h1 ^= h1 >> np.uint32(16)
    h1 = (h1 * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h1 ^= h1 >> np.uint32(13)
    h1 = (h1 * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h1 ^= h1 >> np.uint32(16)
    return h1


def _np_murmur3_bytes(data: bytes, seed: np.uint32) -> np.uint32:
    """Spark hashUnsafeBytes: word blocks then per-byte signed tail.
    uint32 wraparound is intended; numpy's overflow warnings are suppressed."""
    with np.errstate(over="ignore"):
        h1 = np.uint32(seed)
        n = len(data)
        nblocks = n // 4
        for b in range(nblocks):
            word = np.uint32(int.from_bytes(data[4 * b:4 * b + 4], "little"))
            h1 = _np_mix_h1(h1, _np_mix_k1(word))
        for t in range(nblocks * 4, n):
            signed = np.int8(data[t] if data[t] < 128 else data[t] - 256)
            h1 = _np_mix_h1(h1, _np_mix_k1(np.int32(signed).view(np.uint32)))
        return _np_fmix(h1, np.uint32(n))


# ---------------------------------------------------------------------------
# XxHash64 + HiveHash (reference HashFunctions.scala: GpuXxHash64, GpuHiveHash,
# backed by the JNI Hash kernel). Host numpy implementations with Spark-exact
# bit math; per-column seed chaining like murmur3 (null rows keep the seed).
# ---------------------------------------------------------------------------

_XP1 = np.uint64(0x9E3779B185EBCA87)
_XP2 = np.uint64(0xC2B2AE3D27D4EB4F)
_XP3 = np.uint64(0x165667B19E3779F9)
_XP4 = np.uint64(0x85EBCA77C2B2AE63)
_XP5 = np.uint64(0x27D4EB2F165667C5)


def _xrotl(x, r):
    r = np.uint64(r)
    return (x << r) | (x >> (np.uint64(64) - r))


def _xfmix(h):
    h = h ^ (h >> np.uint64(33))
    h = h * _XP2
    h = h ^ (h >> np.uint64(29))
    h = h * _XP3
    return h ^ (h >> np.uint64(32))


def np_xxhash64_int(v_i32, seed_u64):
    """Spark XXH64.hashInt."""
    h = seed_u64 + _XP5 + np.uint64(4)
    u = (np.asarray(v_i32).astype(np.int64) & np.int64(0xFFFFFFFF)).astype(np.uint64)
    h = h ^ (u * _XP1)
    h = _xrotl(h, 23) * _XP2 + _XP3
    return _xfmix(h)


def np_xxhash64_long(v_i64, seed_u64):
    """Spark XXH64.hashLong."""
    h = seed_u64 + _XP5 + np.uint64(8)
    u = np.asarray(v_i64).astype(np.uint64)
    h = h ^ (_xrotl(u * _XP2, 31) * _XP1)
    h = _xrotl(h, 27) * _XP1 + _XP4
    return _xfmix(h)


def _xx_round(acc, val):
    acc = acc + val * _XP2
    return _xrotl(acc, 31) * _XP1


def np_xxhash64_bytes(data: bytes, seed: int) -> int:
    """Spark XXH64.hashUnsafeBytes (standard XXH64)."""
    with np.errstate(over="ignore"):
        seed = np.uint64(seed)
        n = len(data)
        i = 0
        if n >= 32:
            v1 = seed + _XP1 + _XP2
            v2 = seed + _XP2
            v3 = seed + np.uint64(0)
            v4 = seed - _XP1
            while i <= n - 32:
                v1 = _xx_round(v1, np.frombuffer(data, np.uint64, 1, i)[0])
                v2 = _xx_round(v2, np.frombuffer(data, np.uint64, 1, i + 8)[0])
                v3 = _xx_round(v3, np.frombuffer(data, np.uint64, 1, i + 16)[0])
                v4 = _xx_round(v4, np.frombuffer(data, np.uint64, 1, i + 24)[0])
                i += 32
            h = (_xrotl(v1, 1) + _xrotl(v2, 7) + _xrotl(v3, 12)
                 + _xrotl(v4, 18))
            for v in (v1, v2, v3, v4):
                h = (h ^ _xx_round(np.uint64(0), v)) * _XP1 + _XP4
        else:
            h = seed + _XP5
        h = h + np.uint64(n)
        while i <= n - 8:
            h = h ^ (_xrotl(np.frombuffer(data, np.uint64, 1, i)[0] * _XP2, 31)
                     * _XP1)
            h = _xrotl(h, 27) * _XP1 + _XP4
            i += 8
        if i <= n - 4:
            w = np.uint64(np.frombuffer(data, np.uint32, 1, i)[0])
            h = h ^ (w * _XP1)
            h = _xrotl(h, 23) * _XP2 + _XP3
            i += 4
        while i < n:
            h = h ^ (np.uint64(data[i]) * _XP5)
            h = _xrotl(h, 11) * _XP1
            i += 1
        return int(_xfmix(h))


def _np_xxhash_col(dt: DataType, arr, seeds: np.ndarray) -> np.ndarray:
    """One column pass: per-row updated uint64 seeds (nulls unchanged)."""
    import pyarrow as pa
    import pyarrow.compute as pc
    from ..types import (BooleanType, ByteType, DateType, DoubleType,
                         FloatType, IntegerType, LongType, ShortType,
                         StringType, TimestampType)
    a = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    nulls = np.asarray(pc.is_null(a).to_numpy(zero_copy_only=False)).astype(bool)
    with np.errstate(over="ignore"):
        if isinstance(dt, StringType):
            out = seeds.copy()
            for i, s in enumerate(a.to_pylist()):
                if s is not None:
                    out[i] = np_xxhash64_bytes(s.encode(), seeds[i])
            return out
        vals = np.asarray(a.fill_null(0).to_numpy(zero_copy_only=False))
        if isinstance(dt, (LongType, TimestampType)):
            h = np_xxhash64_long(vals.astype(np.int64), seeds)
        elif isinstance(dt, DoubleType):
            v = np.where(vals == 0.0, 0.0, vals)  # -0.0 → 0.0
            h = np_xxhash64_long(v.astype(np.float64).view(np.int64), seeds)
        elif isinstance(dt, FloatType):
            v = np.where(vals == 0.0, np.float32(0.0), vals.astype(np.float32))
            h = np_xxhash64_int(v.view(np.int32), seeds)
        elif isinstance(dt, BooleanType):
            h = np_xxhash64_int(vals.astype(np.int32), seeds)
        elif isinstance(dt, (ByteType, ShortType, IntegerType, DateType)):
            h = np_xxhash64_int(vals.astype(np.int32), seeds)
        else:
            raise ExpressionError(f"xxhash64 of {dt} is not supported")
    return np.where(nulls, seeds, h)


# ---- device xxhash64 (Spark XXH64) ------------------------------------------
# Same padded-gather design as the murmur3 device path: per-row masked stride
# loops over the HBM byte buffer, all arithmetic in wrapping uint64 on the VPU.


def _xx_rotl_dev(x, r):
    r = jnp.uint64(r)
    return (x << r) | (x >> (jnp.uint64(64) - r))


def _xx_fmix_dev(h):
    h = h ^ (h >> jnp.uint64(33))
    h = (h * _XP2).astype(jnp.uint64)
    h = h ^ (h >> jnp.uint64(29))
    h = (h * _XP3).astype(jnp.uint64)
    return h ^ (h >> jnp.uint64(32))


def _xx_round_dev(acc, val):
    acc = (acc + val * _XP2).astype(jnp.uint64)
    return (_xx_rotl_dev(acc, 31) * _XP1).astype(jnp.uint64)


def xxhash64_int_dev(v_i32, seed_u64):
    """Spark XXH64.hashInt on device."""
    h = seed_u64 + _XP5 + jnp.uint64(4)
    u = (v_i32.astype(jnp.int64) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint64)
    h = h ^ (u * _XP1)
    h = (_xx_rotl_dev(h, 23) * _XP2 + _XP3).astype(jnp.uint64)
    return _xx_fmix_dev(h)


def xxhash64_long_dev(v_i64, seed_u64):
    """Spark XXH64.hashLong on device."""
    h = seed_u64 + _XP5 + jnp.uint64(8)
    u = v_i64.astype(jnp.uint64)
    h = h ^ ((_xx_rotl_dev((u * _XP2).astype(jnp.uint64), 31) * _XP1)
             .astype(jnp.uint64))
    h = (_xx_rotl_dev(h, 27) * _XP1 + _XP4).astype(jnp.uint64)
    return _xx_fmix_dev(h)


def _xxhash64_string_device(col: TpuColumnVector, seed, capacity: int):
    """Spark XXH64.hashUnsafeBytes on device: the 4-accumulator 32-byte
    stride loop, then 8-/4-/1-byte tails, each as a per-row masked loop
    over max_len like the murmur3 string path. O(cap * max_len)."""
    starts = col.offsets[:-1].astype(jnp.int64)
    lens = (col.offsets[1:].astype(jnp.int64) - starts)
    max_len = int(jnp.max(lens)) if col.num_rows else 0
    data = col.data
    ncap = max(int(data.shape[0]) - 1, 0)

    def read_u64(base):
        idx = jnp.clip(base[:, None] + jnp.arange(8)[None, :], 0, ncap)
        b = jnp.take(data, idx).astype(jnp.uint64)
        out = b[:, 0]
        for k in range(1, 8):
            out = out | (b[:, k] << jnp.uint64(8 * k))
        return out

    def read_u32(base):
        idx = jnp.clip(base[:, None] + jnp.arange(4)[None, :], 0, ncap)
        b = jnp.take(data, idx).astype(jnp.uint64)
        return b[:, 0] | (b[:, 1] << jnp.uint64(8)) \
            | (b[:, 2] << jnp.uint64(16)) | (b[:, 3] << jnp.uint64(24))

    seed = jnp.broadcast_to(seed, (capacity,)).astype(jnp.uint64)
    v1 = seed + _XP1 + _XP2
    v2 = seed + _XP2
    v3 = seed
    v4 = seed - _XP1
    for sidx in range(max_len // 32):
        base = starts + 32 * sidx
        active = lens >= 32 * (sidx + 1)
        v1 = jnp.where(active, _xx_round_dev(v1, read_u64(base)), v1)
        v2 = jnp.where(active, _xx_round_dev(v2, read_u64(base + 8)), v2)
        v3 = jnp.where(active, _xx_round_dev(v3, read_u64(base + 16)), v3)
        v4 = jnp.where(active, _xx_round_dev(v4, read_u64(base + 24)), v4)
    h_big = (_xx_rotl_dev(v1, 1) + _xx_rotl_dev(v2, 7)
             + _xx_rotl_dev(v3, 12) + _xx_rotl_dev(v4, 18))
    for v in (v1, v2, v3, v4):
        h_big = ((h_big ^ _xx_round_dev(jnp.uint64(0), v)) * _XP1 + _XP4) \
            .astype(jnp.uint64)
    h = jnp.where(lens >= 32, h_big, seed + _XP5)
    h = h + lens.astype(jnp.uint64)
    # 8-byte words of the tail (tail < 32 bytes → at most 3)
    i0 = (lens // 32) * 32
    for tidx in range(3):
        pos = i0 + 8 * tidx
        active = (pos + 8) <= lens
        w = read_u64(starts + pos)
        nh = (_xx_rotl_dev(
            h ^ (_xx_rotl_dev((w * _XP2).astype(jnp.uint64), 31) * _XP1)
            .astype(jnp.uint64), 27) * _XP1 + _XP4).astype(jnp.uint64)
        h = jnp.where(active, nh, h)
    i1 = i0 + ((lens - i0) // 8) * 8
    # one 4-byte word
    active4 = (i1 + 4) <= lens
    w32 = read_u32(starts + i1)
    nh = (_xx_rotl_dev(h ^ (w32 * _XP1), 23) * _XP2 + _XP3) \
        .astype(jnp.uint64)
    h = jnp.where(active4, nh, h)
    i2 = i1 + jnp.where(active4, 4, 0)
    # remaining bytes (at most 3)
    for bidx in range(3):
        pos = i2 + bidx
        active = pos < lens
        byte = jnp.take(data, jnp.clip(starts + pos, 0, ncap)) \
            .astype(jnp.uint64)
        nh = (_xx_rotl_dev(h ^ (byte * _XP5), 11) * _XP1).astype(jnp.uint64)
        h = jnp.where(active, nh, h)
    return _xx_fmix_dev(h)


def xxhash64_col(col: TpuColumnVector, seed, capacity: int):
    """One device column pass: per-row updated uint64 seeds (nulls keep
    their incoming seed, like Spark)."""
    dt = col.dtype
    d = col.data
    if isinstance(dt, (BooleanType, ByteType, ShortType, IntegerType,
                       DateType)):
        h = xxhash64_int_dev(d.astype(jnp.int32), seed)
    elif isinstance(dt, (LongType, TimestampType)):
        h = xxhash64_long_dev(d.astype(jnp.int64), seed)
    elif isinstance(dt, FloatType):
        # -0.0 AND NaN normalization (Java floatToIntBits canonicalizes NaN;
        # the host oracle does too — shared with the murmur3 path)
        f = _normalize_float(d)
        h = xxhash64_int_dev(f.view(jnp.int32), seed)
    elif isinstance(dt, DoubleType):
        f = _normalize_double(d)
        h = xxhash64_long_dev(f.view(jnp.int64), seed)
    elif isinstance(dt, StringType):
        h = _xxhash64_string_device(col, seed, capacity)
    else:
        raise NotImplementedError(f"xxhash64 of {dt}")
    if col.validity is not None:
        h = jnp.where(col.validity, h, seed)
    return h


def xxhash64_batch(cols: Sequence[TpuColumnVector], capacity: int,
                   seed: int = 42):
    h = jnp.full((capacity,), np.uint64(seed), jnp.uint64)
    for c in cols:
        h = xxhash64_col(c, h, capacity)
    return h.view(jnp.int64)


def _device_hashable(cols, children, ctx=None) -> bool:
    """All hash inputs are device-resident flat columns (strings must carry
    offsets, and their longest row must fit the configured device cap —
    the padded byte-matrix loop costs O(rows x max_len)); shared gate for
    the xxhash64/hive-hash device paths."""
    max_bytes = None
    if ctx is not None:
        from ..config import HASH_DEVICE_MAX_STRING_BYTES
        try:
            max_bytes = ctx.conf.get(HASH_DEVICE_MAX_STRING_BYTES)
        except Exception:  # noqa: BLE001 — eval ctx without conf
            max_bytes = None
    for c, ch in zip(cols, children):
        if c.host_data is not None or c.children is not None:
            return False
        if isinstance(ch.dtype, StringType):
            if c.offsets is None:
                return False
            if max_bytes is not None and c.num_rows:
                ml = int(jnp.max(c.offsets[1:] - c.offsets[:-1]))
                if ml > max_bytes:
                    return False
    return True


class XxHash64(Expression):
    """xxhash64(...) → long (reference GpuXxHash64, HashFunctions.scala)."""

    def __init__(self, *children: Expression, seed: int = 42):
        self.children = tuple(children)
        self.seed = seed

    @property
    def dtype(self) -> DataType:
        from ..types import LongT
        return LongT

    @property
    def nullable(self) -> bool:
        return False

    def _hash_arrays(self, vals, n):
        out = np.full(n, np.uint64(self.seed), np.uint64)
        for c, v in zip(self.children, vals):
            out = _np_xxhash_col(c.dtype, v, out)
        return out.view(np.int64)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = [c.eval_cpu(table, ctx) for c in self.children]
        n = table.num_rows
        vals = [v if isinstance(v, (pa.Array, pa.ChunkedArray))
                else pa.array([v] * n) for v in vals]
        return pa.array(self._hash_arrays(vals, n))

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .base import to_column
        from ..types import LongT
        cols = [to_column(c.eval_tpu(batch, ctx), batch, c.dtype)
                for c in self.children]
        if _device_hashable(cols, self.children, ctx):
            try:
                h = xxhash64_batch(cols, batch.capacity, self.seed)
                return make_column(LongT, h, None, batch.num_rows)
            except NotImplementedError:
                pass  # dtype outside the device set: host mirror below
        vals = [c.to_arrow() for c in cols]
        h = self._hash_arrays(vals, batch.num_rows)
        return TpuColumnVector.from_numpy(LongT, h,
                                          capacity=batch.capacity)

    def pretty(self) -> str:
        return f"xxhash64({', '.join(c.pretty() for c in self.children)})"


def _hive_hash_value(dt: DataType, v) -> int:
    from ..types import (ArrayType, BooleanType, ByteType, DateType,
                         DoubleType, FloatType, IntegerType, LongType,
                         ShortType, StringType)
    import struct as _struct
    if v is None:
        return 0
    if isinstance(dt, BooleanType):
        return 1 if v else 0
    if isinstance(dt, (ByteType, ShortType, IntegerType, DateType)):
        return int(v) if not hasattr(v, "toordinal") else \
            (v - __import__("datetime").date(1970, 1, 1)).days
    if isinstance(dt, LongType):
        l = int(v) & 0xFFFFFFFFFFFFFFFF
        return ((l >> 32) ^ l) & 0xFFFFFFFF
    if isinstance(dt, FloatType):
        f = np.float32(0.0) if v == 0.0 else np.float32(v)
        return int(np.asarray(f).view(np.int32)) & 0xFFFFFFFF
    if isinstance(dt, DoubleType):
        d = 0.0 if v == 0.0 else float(v)
        l = int(np.asarray(np.float64(d)).view(np.int64)) & 0xFFFFFFFFFFFFFFFF
        return ((l >> 32) ^ l) & 0xFFFFFFFF
    if isinstance(dt, StringType):
        h = 0
        for ch in v.encode("utf-8"):
            h = (31 * h + (ch if ch < 128 else ch - 256)) & 0xFFFFFFFF
        return h
    if isinstance(dt, ArrayType):
        h = 0
        for x in v:
            h = (31 * h + _hive_hash_value(dt.element_type, x)) & 0xFFFFFFFF
        return h
    raise ExpressionError(f"hive hash of {dt} is not supported")


class HiveHash(Expression):
    """hive-hash(...) → int (reference GpuHiveHash; Hive bucketing hash:
    h = 31*h + fieldHash, Java int overflow)."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        return IntegerT

    @property
    def nullable(self) -> bool:
        return False

    def _hash_rows(self, cols_py, n):
        out = np.zeros(n, np.int64)
        for ri in range(n):
            h = 0
            for c, vals in zip(self.children, cols_py):
                h = (31 * h + _hive_hash_value(c.dtype, vals[ri])) & 0xFFFFFFFF
            out[ri] = h
        return out.astype(np.uint32).view(np.int32).astype(np.int32)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        n = table.num_rows
        cols_py = []
        for c in self.children:
            v = c.eval_cpu(table, ctx)
            cols_py.append(v.to_pylist() if isinstance(v, (pa.Array, pa.ChunkedArray))
                           else [v] * n)
        return pa.array(self._hash_rows(cols_py, n), type=pa.int32())

    @staticmethod
    def _field_hash_dev(col: TpuColumnVector, dt: DataType, capacity: int):
        """Per-row Hive field hash on device (uint32); None → 0."""
        d = col.data
        if isinstance(dt, BooleanType):
            h = d.astype(jnp.uint32)
        elif isinstance(dt, (ByteType, ShortType, IntegerType, DateType)):
            h = d.astype(jnp.int32).view(jnp.uint32)
        elif isinstance(dt, LongType):
            u = d.astype(jnp.int64).view(jnp.uint64)
            h = ((u >> jnp.uint64(32)) ^ u).astype(jnp.uint32)
        elif isinstance(dt, FloatType):
            # Java Float.floatToIntBits canonicalizes NaN as well as -0.0
            f = _normalize_float(d)
            h = f.view(jnp.int32).view(jnp.uint32)
        elif isinstance(dt, DoubleType):
            f = _normalize_double(d)
            u = f.view(jnp.int64).view(jnp.uint64)
            h = ((u >> jnp.uint64(32)) ^ u).astype(jnp.uint32)
        elif isinstance(dt, StringType):
            # Java String.hashCode over utf-8 SIGNED bytes: h = 31h + b
            starts = col.offsets[:-1].astype(jnp.int64)
            lens = col.offsets[1:].astype(jnp.int64) - starts
            max_len = int(jnp.max(lens)) if col.num_rows else 0
            data = col.data
            ncap = max(int(data.shape[0]) - 1, 0)
            h = jnp.zeros((capacity,), jnp.uint32)
            for b in range(max_len):
                idx = jnp.clip(starts + b, 0, ncap)
                byte = jnp.take(data, idx).astype(jnp.int8) \
                    .astype(jnp.int32).view(jnp.uint32)
                nh = (h * jnp.uint32(31) + byte).astype(jnp.uint32)
                h = jnp.where(b < lens, nh, h)
        else:
            raise NotImplementedError(f"hive hash of {dt}")
        if col.validity is not None:
            h = jnp.where(col.validity, h, jnp.uint32(0))
        return h

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .base import to_column
        cols = [to_column(c.eval_tpu(batch, ctx), batch, c.dtype)
                for c in self.children]
        if _device_hashable(cols, self.children, ctx):
            try:
                h = jnp.zeros((batch.capacity,), jnp.uint32)
                for c, ch in zip(cols, self.children):
                    fh = self._field_hash_dev(c, ch.dtype, batch.capacity)
                    h = (h * jnp.uint32(31) + fh).astype(jnp.uint32)
                return make_column(IntegerT, h.view(jnp.int32), None,
                                   batch.num_rows)
            except NotImplementedError:
                pass  # nested dtype: host mirror below
        cols_py = [c.to_arrow().to_pylist() for c in cols]
        h = self._hash_rows(cols_py, batch.num_rows)
        return TpuColumnVector.from_numpy(IntegerT, h.astype(np.int32),
                                          capacity=batch.capacity)

    def pretty(self) -> str:
        return f"hive_hash({', '.join(c.pretty() for c in self.children)})"


class Md5(UnaryExpression):
    """md5(binary|string) → 32-char hex string (reference GpuMd5, JNI).
    Host-assisted: hashlib per row — MD5 is a sequential byte algorithm with
    no vectorizable structure worth a device port."""

    @property
    def dtype(self) -> DataType:
        from ..types import StringT
        return StringT

    @staticmethod
    def _hex(v):
        import hashlib
        if v is None:
            return None
        data = v.encode("utf-8") if isinstance(v, str) else bytes(v)
        return hashlib.md5(data).hexdigest()

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .collections import _result_from_pylist
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            return TpuScalar(self.dtype, self._hex(c.value))
        return _result_from_pylist([self._hex(v) for v in c.to_pylist()],
                                   self.dtype, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.child.eval_cpu(table, ctx).to_pylist()
        return pa.array([self._hex(v) for v in vals], pa.string())

    def pretty(self) -> str:
        return f"md5({self.child.pretty()})"
