"""Math expressions (reference mathExpressions.scala). Spark quirks preserved:
log of non-positive → null (non-ANSI), floor/ceil of fp return bigint, round is
HALF_UP (not banker's)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..types import (DataType, DoubleT, DoubleType, FloatType, FractionalType,
                     IntegralType, LongT)
from ..columnar.vector import row_mask
from .base import (Expression, UnaryExpression, _DEFAULT_CTX, combine_validity,
                   device_parts, make_column)


class _DoubleUnary(UnaryExpression):
    """Unary math fn returning double."""
    _np_fn = None
    _jnp_fn = None

    @property
    def dtype(self) -> DataType:
        return DoubleT

    def _compute(self, d, ctx, valid):
        return type(self)._jnp_fn(d.astype(jnp.float64))

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        c = self.child.eval_cpu(table, ctx)
        vals = np.asarray(pc.cast(_chunk(c), pa.float64()).fill_null(0.0)
                          .to_numpy(zero_copy_only=False))
        mask = np.asarray(pc.is_null(c).to_numpy(zero_copy_only=False)).astype(bool)
        with np.errstate(all="ignore"):
            out = type(self)._np_fn(vals)
        return pa.array(out, mask=mask)

    def pretty(self) -> str:
        return f"{type(self).__name__.lower()}({self.child.pretty()})"


def _chunk(c):
    import pyarrow as pa
    return c.combine_chunks() if isinstance(c, pa.ChunkedArray) else c


class Sqrt(_DoubleUnary):
    _np_fn = staticmethod(np.sqrt)   # sqrt(-x) = NaN, matching Spark
    _jnp_fn = staticmethod(jnp.sqrt)


class Cbrt(_DoubleUnary):
    _np_fn = staticmethod(np.cbrt)
    _jnp_fn = staticmethod(jnp.cbrt)


class Exp(_DoubleUnary):
    _np_fn = staticmethod(np.exp)
    _jnp_fn = staticmethod(jnp.exp)


class Expm1(_DoubleUnary):
    _np_fn = staticmethod(np.expm1)
    _jnp_fn = staticmethod(jnp.expm1)


class Sin(_DoubleUnary):
    _np_fn = staticmethod(np.sin)
    _jnp_fn = staticmethod(jnp.sin)


class Cos(_DoubleUnary):
    _np_fn = staticmethod(np.cos)
    _jnp_fn = staticmethod(jnp.cos)


class Tan(_DoubleUnary):
    _np_fn = staticmethod(np.tan)
    _jnp_fn = staticmethod(jnp.tan)


class Asin(_DoubleUnary):
    _np_fn = staticmethod(np.arcsin)
    _jnp_fn = staticmethod(jnp.arcsin)


class Acos(_DoubleUnary):
    _np_fn = staticmethod(np.arccos)
    _jnp_fn = staticmethod(jnp.arccos)


class Atan(_DoubleUnary):
    _np_fn = staticmethod(np.arctan)
    _jnp_fn = staticmethod(jnp.arctan)


class Sinh(_DoubleUnary):
    _np_fn = staticmethod(np.sinh)
    _jnp_fn = staticmethod(jnp.sinh)


class Cosh(_DoubleUnary):
    _np_fn = staticmethod(np.cosh)
    _jnp_fn = staticmethod(jnp.cosh)


class Tanh(_DoubleUnary):
    _np_fn = staticmethod(np.tanh)
    _jnp_fn = staticmethod(jnp.tanh)


class _LogBase(UnaryExpression):
    """Spark log family: non-positive input → null (non-ANSI)."""

    @property
    def dtype(self) -> DataType:
        return DoubleT

    _jnp_fn = None
    _np_fn = None

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        cap = batch.capacity
        d, v = device_parts(c, cap)
        d = jnp.broadcast_to(d, (cap,)).astype(jnp.float64)
        bad = d <= 0
        data = type(self)._jnp_fn(jnp.where(bad, 1.0, d))
        valid = combine_validity(cap, v, ~bad, row_mask(batch.num_rows, cap))
        return make_column(DoubleT, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        c = self.child.eval_cpu(table, ctx)
        vals = np.asarray(pc.cast(_chunk(c), pa.float64()).fill_null(1.0)
                          .to_numpy(zero_copy_only=False))
        mask = np.asarray(pc.is_null(c).to_numpy(zero_copy_only=False)).astype(bool)
        bad = ~(vals > 0)
        with np.errstate(all="ignore"):
            out = type(self)._np_fn(np.where(bad, 1.0, vals))
        return pa.array(out, mask=mask | bad)


class Log(_LogBase):
    _jnp_fn = staticmethod(jnp.log)
    _np_fn = staticmethod(np.log)


class Log10(_LogBase):
    _jnp_fn = staticmethod(jnp.log10)
    _np_fn = staticmethod(np.log10)


class Log2(_LogBase):
    _jnp_fn = staticmethod(jnp.log2)
    _np_fn = staticmethod(np.log2)


class Log1p(_LogBase):
    # valid domain: x > -1
    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        cap = batch.capacity
        d, v = device_parts(c, cap)
        d = jnp.broadcast_to(d, (cap,)).astype(jnp.float64)
        bad = d <= -1
        data = jnp.log1p(jnp.where(bad, 0.0, d))
        valid = combine_validity(cap, v, ~bad, row_mask(batch.num_rows, cap))
        return make_column(DoubleT, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        c = self.child.eval_cpu(table, ctx)
        vals = np.asarray(pc.cast(_chunk(c), pa.float64()).fill_null(0.0)
                          .to_numpy(zero_copy_only=False))
        mask = np.asarray(pc.is_null(c).to_numpy(zero_copy_only=False)).astype(bool)
        bad = ~(vals > -1)
        with np.errstate(all="ignore"):
            out = np.log1p(np.where(bad, 0.0, vals))
        return pa.array(out, mask=mask | bad)


class Pow(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def dtype(self) -> DataType:
        return DoubleT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        l = self.children[0].eval_tpu(batch, ctx)
        r = self.children[1].eval_tpu(batch, ctx)
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        data = jnp.power(jnp.broadcast_to(ld, (cap,)).astype(jnp.float64),
                         jnp.broadcast_to(rd, (cap,)).astype(jnp.float64))
        valid = combine_validity(cap, lv, rv, row_mask(batch.num_rows, cap))
        return make_column(DoubleT, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.power(self.children[0].eval_cpu(table, ctx),
                        self.children[1].eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"pow({self.children[0].pretty()}, {self.children[1].pretty()})"


class Atan2(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def dtype(self) -> DataType:
        return DoubleT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        l = self.children[0].eval_tpu(batch, ctx)
        r = self.children[1].eval_tpu(batch, ctx)
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        data = jnp.arctan2(jnp.broadcast_to(ld, (cap,)).astype(jnp.float64),
                           jnp.broadcast_to(rd, (cap,)).astype(jnp.float64))
        valid = combine_validity(cap, lv, rv, row_mask(batch.num_rows, cap))
        return make_column(DoubleT, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        l = self.children[0].eval_cpu(table, ctx)
        r = self.children[1].eval_cpu(table, ctx)
        return pc.atan2(l, r)


class Signum(UnaryExpression):
    @property
    def dtype(self) -> DataType:
        return DoubleT

    def _compute(self, d, ctx, valid):
        return jnp.sign(d.astype(jnp.float64))

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        c = self.child.eval_cpu(table, ctx)
        return pc.cast(pc.sign(c), pa.float64())


_I64_MIN = np.int64(-2**63)
_I64_MAX = np.int64(2**63 - 1)
_TWO63 = np.float64(2.0**63)  # exactly representable; 2**63-1 is not


def _java_double_to_long(d):
    """(long) cast semantics: NaN→0, out-of-range clamps to MIN/MAX."""
    v = jnp.where(jnp.isnan(d), 0.0, d)
    in_range = (v > -_TWO63) & (v < _TWO63)
    safe = jnp.where(in_range, v, 0.0).astype(jnp.int64)
    return jnp.where(v >= _TWO63, _I64_MAX,
                     jnp.where(v <= -_TWO63, _I64_MIN, safe))


def _np_java_double_to_long(v):
    v = np.where(np.isnan(v), 0.0, v)
    in_range = (v > -_TWO63) & (v < _TWO63)
    safe = np.where(in_range, v, 0.0).astype(np.int64)
    return np.where(v >= _TWO63, _I64_MAX,
                    np.where(v <= -_TWO63, _I64_MIN, safe))


class Floor(UnaryExpression):
    """floor(double) → bigint (Spark return type; java (long) conversion)."""

    @property
    def dtype(self) -> DataType:
        return LongT if isinstance(self.child.dtype, FractionalType) else self.child.dtype

    def _compute(self, d, ctx, valid):
        if jnp.issubdtype(d.dtype, jnp.floating):
            return _java_double_to_long(jnp.floor(d))
        return d

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        c = self.child.eval_cpu(table, ctx)
        if pa.types.is_floating(c.type):
            vals = np.asarray(_chunk(c).fill_null(0.0).to_numpy(zero_copy_only=False))
            mask = np.asarray(pc.is_null(c).to_numpy(zero_copy_only=False)).astype(bool)
            return pa.array(_np_java_double_to_long(np.floor(vals)), mask=mask)
        return c


class Ceil(UnaryExpression):
    @property
    def dtype(self) -> DataType:
        return LongT if isinstance(self.child.dtype, FractionalType) else self.child.dtype

    def _compute(self, d, ctx, valid):
        if jnp.issubdtype(d.dtype, jnp.floating):
            return _java_double_to_long(jnp.ceil(d))
        return d

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        c = self.child.eval_cpu(table, ctx)
        if pa.types.is_floating(c.type):
            vals = np.asarray(_chunk(c).fill_null(0.0).to_numpy(zero_copy_only=False))
            mask = np.asarray(pc.is_null(c).to_numpy(zero_copy_only=False)).astype(bool)
            return pa.array(_np_java_double_to_long(np.ceil(vals)), mask=mask)
        return c


class Round(Expression):
    """round(x, scale) HALF_UP (Spark), not banker's rounding."""

    def __init__(self, child: Expression, scale: Expression):
        self.children = (child, scale)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .base import Literal
        cap = batch.capacity
        c = self.children[0].eval_tpu(batch, ctx)
        scale = self.children[1].value if isinstance(self.children[1], Literal) else 0
        d, v = device_parts(c, cap)
        d = jnp.broadcast_to(d, (cap,))
        if jnp.issubdtype(d.dtype, jnp.floating):
            m = 10.0 ** scale
            scaled = d.astype(jnp.float64) * m
            # HALF_UP: add 0.5 away from zero then truncate
            rounded = jnp.trunc(scaled + jnp.where(scaled >= 0, 0.5, -0.5)) / m
            data = rounded.astype(d.dtype)
        elif scale >= 0:
            data = d
        else:
            m = np.int64(10 ** (-scale))
            half = m // 2
            adj = jnp.where(d >= 0, d + half, d - half)
            data = (adj // m) * m
        valid = combine_validity(cap, v, row_mask(batch.num_rows, cap))
        return make_column(self.dtype, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        from .base import Literal
        c = self.children[0].eval_cpu(table, ctx)
        scale = self.children[1].value if isinstance(self.children[1], Literal) else 0
        # arrow ≥25 renamed HALF_UP: half_towards_infinity == Spark's
        # ROUND_HALF_UP (away from zero for both signs)
        return pc.round(c, ndigits=scale, round_mode="half_towards_infinity")

    def pretty(self) -> str:
        return f"round({self.children[0].pretty()}, {self.children[1].pretty()})"


# ---------------------------------------------------------------------------
# Math breadth 2 (reference mathExpressions.scala: GpuAsinh/GpuAcosh/GpuAtanh,
# GpuCot, GpuHypot, GpuLogarithm, GpuRint, GpuBRound, GpuToDegrees/ToRadians)
# ---------------------------------------------------------------------------

def _as_f64_array(x, n):
    """eval_cpu result (array or scalar) → (float64 values[n], null mask[n])."""
    import pyarrow as pa
    import pyarrow.compute as pc
    if isinstance(x, (pa.Array, pa.ChunkedArray)):
        arr = _chunk(pc.cast(x, pa.float64()))
        vals = np.asarray(arr.fill_null(0.0).to_numpy(zero_copy_only=False))
        mask = np.asarray(pc.is_null(arr).to_numpy(zero_copy_only=False)).astype(bool)
        return vals, mask
    v = x.as_py() if hasattr(x, "as_py") else x
    if v is None:
        return np.zeros(n), np.ones(n, dtype=bool)
    return np.full(n, float(v)), np.zeros(n, dtype=bool)


class Asinh(_DoubleUnary):
    _np_fn = staticmethod(np.arcsinh)
    _jnp_fn = staticmethod(jnp.arcsinh)


class Acosh(_DoubleUnary):
    _np_fn = staticmethod(np.arccosh)   # x < 1 → NaN, matching Spark StrictMath
    _jnp_fn = staticmethod(jnp.arccosh)


class Atanh(_DoubleUnary):
    _np_fn = staticmethod(np.arctanh)
    _jnp_fn = staticmethod(jnp.arctanh)


class Cot(_DoubleUnary):
    _np_fn = staticmethod(lambda x: 1.0 / np.tan(x))
    _jnp_fn = staticmethod(lambda x: 1.0 / jnp.tan(x))


class ToDegrees(_DoubleUnary):
    _np_fn = staticmethod(np.degrees)
    _jnp_fn = staticmethod(jnp.degrees)


class ToRadians(_DoubleUnary):
    _np_fn = staticmethod(np.radians)
    _jnp_fn = staticmethod(jnp.radians)


class Rint(_DoubleUnary):
    """rint: round to nearest even, result stays double (Spark GpuRint)."""
    _np_fn = staticmethod(np.rint)
    _jnp_fn = staticmethod(jnp.round)


class Hypot(Expression):
    """hypot(a, b) = sqrt(a² + b²) without intermediate overflow."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def dtype(self) -> DataType:
        return DoubleT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        l = self.children[0].eval_tpu(batch, ctx)
        r = self.children[1].eval_tpu(batch, ctx)
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        data = jnp.hypot(jnp.broadcast_to(ld, (cap,)).astype(jnp.float64),
                         jnp.broadcast_to(rd, (cap,)).astype(jnp.float64))
        valid = combine_validity(cap, lv, rv, row_mask(batch.num_rows, cap))
        return make_column(DoubleT, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        l = _as_f64_array(self.children[0].eval_cpu(table, ctx), table.num_rows)
        r = _as_f64_array(self.children[1].eval_cpu(table, ctx), table.num_rows)
        lv, lm = l
        rv, rm = r
        return pa.array(np.hypot(lv, rv), mask=(lm | rm))

    def pretty(self) -> str:
        return f"hypot({self.children[0].pretty()}, {self.children[1].pretty()})"


class Logarithm(Expression):
    """log(base, x): null when x <= 0 (Spark non-ANSI null-on-domain-error)."""

    def __init__(self, base: Expression, child: Expression):
        self.children = (base, child)

    @property
    def dtype(self) -> DataType:
        return DoubleT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        b = self.children[0].eval_tpu(batch, ctx)
        c = self.children[1].eval_tpu(batch, ctx)
        bd, bv = device_parts(b, cap)
        cd, cv = device_parts(c, cap)
        bd = jnp.broadcast_to(bd, (cap,)).astype(jnp.float64)
        cd = jnp.broadcast_to(cd, (cap,)).astype(jnp.float64)
        bad = (cd <= 0) | (bd <= 0)
        data = jnp.log(jnp.where(bad, 1.0, cd)) / jnp.log(jnp.where(bad, 2.0, bd))
        valid = combine_validity(cap, bv, cv, ~bad,
                                 row_mask(batch.num_rows, cap))
        return make_column(DoubleT, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        bv, bm = _as_f64_array(self.children[0].eval_cpu(table, ctx),
                               table.num_rows)
        cv, cm = _as_f64_array(self.children[1].eval_cpu(table, ctx),
                               table.num_rows)
        mask = bm | cm | (cv <= 0) | (bv <= 0)
        with np.errstate(all="ignore"):
            out = np.log(np.where(cv <= 0, 1.0, cv)) / \
                np.log(np.where(bv <= 0, 2.0, bv))
        return pa.array(out, mask=mask)

    def pretty(self) -> str:
        return f"log({self.children[0].pretty()}, {self.children[1].pretty()})"


class BRound(Round):
    """bround(x, scale): HALF_EVEN (banker's) rounding — Spark GpuBRound."""

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .base import Literal
        cap = batch.capacity
        c = self.children[0].eval_tpu(batch, ctx)
        scale = self.children[1].value if isinstance(self.children[1], Literal) else 0
        d, v = device_parts(c, cap)
        d = jnp.broadcast_to(d, (cap,))
        if jnp.issubdtype(d.dtype, jnp.floating):
            m = 10.0 ** scale
            data = (jnp.round(d.astype(jnp.float64) * m) / m).astype(d.dtype)
        elif scale >= 0:
            data = d
        else:
            m = np.int64(10 ** (-scale))
            q = d // m          # floor quotient; remainder below is in [0, m)
            rem = d - q * m
            half = m // 2
            up = (rem > half) | ((rem == half) & (q % 2 != 0))
            data = (q + up.astype(q.dtype)) * m
        valid = combine_validity(cap, v, row_mask(batch.num_rows, cap))
        return make_column(self.dtype, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        from .base import Literal
        c = self.children[0].eval_cpu(table, ctx)
        scale = self.children[1].value if isinstance(self.children[1], Literal) else 0
        return pc.round(c, ndigits=scale, round_mode="half_to_even")

    def pretty(self) -> str:
        return f"bround({self.children[0].pretty()}, {self.children[1].pretty()})"
