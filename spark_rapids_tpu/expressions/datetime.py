"""Date/time expressions.

Reference: org/apache/spark/sql/rapids/datetimeExpressions.scala (1266) +
spark-rapids-jni DateTimeRebase/GpuTimeZoneDB. Carriers: DateType = int32 days
since epoch, TimestampType = int64 micros since epoch UTC (Spark internal
representation). Device field extraction uses Howard Hinnant's civil-calendar
integer algorithms — pure elementwise integer math, ideal for the VPU (the
reference calls cuDF datetime kernels). Session-timezone math runs on device
for any zone with a TZif table: tzdb.TimeZoneDB loads transition tables and
the conversion is a searchsorted + gather before the civil-calendar math
(reference GpuTimeZoneDB); zones without a table fall back to the host arrow
path inside the op.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..types import DataType, DateT, DateType, IntegerT, LongT, TimestampT, TimestampType
from ..columnar.vector import row_mask
from .base import (EvalContext, Expression, UnaryExpression, _DEFAULT_CTX,
                   combine_validity, device_parts, make_column)

MICROS_PER_DAY = 86_400_000_000
MICROS_PER_SECOND = 1_000_000


def _floor_div(a, b):
    return a // b  # python/jax floor semantics match Spark's floorDiv here


def civil_from_days(z):
    """days-since-epoch → (year, month, day); Hinnant's algorithm."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y, m, d):
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = (m.astype(jnp.int64) + jnp.where(m > 2, -3, 9))
    doy = (153 * mp + 2) // 5 + d.astype(jnp.int64) - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def _days_of(d, dtype):
    if isinstance(dtype, TimestampType):
        return _floor_div(d.astype(jnp.int64), MICROS_PER_DAY).astype(jnp.int32)
    return d.astype(jnp.int32)


def _localize_micros(d, dtype, ctx):
    """Timestamp micros → session-timezone wall-clock micros (device TZ DB
    binary search; reference GpuTimeZoneDB). Non-timestamp inputs and UTC
    sessions pass through. Returns None when the zone has no TZif table —
    callers fall back to the host arrow path."""
    from ..tzdb import TimeZoneDB, is_utc
    if not isinstance(dtype, TimestampType) or is_utc(getattr(ctx, "tz", None)):
        return d
    db = TimeZoneDB.get(ctx.tz)
    if db is None:
        return None
    return db.utc_to_local(d.astype(jnp.int64))


def _cpu_session_ts(arr, ctx):
    """Arrow timestamp column re-flagged to the session timezone so arrow's
    temporal kernels extract LOCAL fields (instant unchanged)."""
    import pyarrow as pa
    if pa.types.is_timestamp(arr.type):
        tz = getattr(ctx, "tz", None) or "UTC"
        return arr.cast(pa.timestamp(arr.type.unit, tz=tz))
    return arr


class _DateField(UnaryExpression):
    """Extract an integer field from date/timestamp (session-timezone aware
    for timestamps)."""

    @property
    def dtype(self) -> DataType:
        return IntegerT

    _arrow_fn = ""

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        cap = batch.capacity
        d, v = device_parts(c, cap)
        d = jnp.broadcast_to(d, (cap,))
        local = _localize_micros(d, self.child.dtype, ctx)
        if local is None:  # zone has no TZif table → host oracle path
            from .base import to_column
            from .collections import _result_from_pylist
            col = to_column(c, batch, self.child.dtype)
            arr = _cpu_session_ts(col.to_arrow(), ctx)
            return _result_from_pylist(self._arrow_field(arr).to_pylist(),
                                       IntegerT, batch)
        days = _days_of(local, self.child.dtype)
        data = self._field(days, local)
        valid = combine_validity(cap, v, row_mask(batch.num_rows, cap))
        return make_column(IntegerT, data, valid, batch.num_rows)

    def _arrow_field(self, arr):
        import pyarrow as pa
        import pyarrow.compute as pc
        return pc.cast(getattr(pc, self._arrow_fn)(arr), pa.int32())

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        c = _cpu_session_ts(self.child.eval_cpu(table, ctx), ctx)
        return self._arrow_field(c)

    def pretty(self) -> str:
        return f"{type(self).__name__.lower()}({self.child.pretty()})"


class Year(_DateField):
    _arrow_fn = "year"

    def _field(self, days, raw):
        y, m, d = civil_from_days(days)
        return y


class Month(_DateField):
    _arrow_fn = "month"

    def _field(self, days, raw):
        y, m, d = civil_from_days(days)
        return m


class DayOfMonth(_DateField):
    _arrow_fn = "day"

    def _field(self, days, raw):
        y, m, d = civil_from_days(days)
        return d


class Quarter(_DateField):
    _arrow_fn = "quarter"

    def _field(self, days, raw):
        y, m, d = civil_from_days(days)
        return (m - 1) // 3 + 1


class DayOfWeek(_DateField):
    """Spark: 1 = Sunday … 7 = Saturday. 1970-01-01 was a Thursday."""

    def _field(self, days, raw):
        return ((days.astype(jnp.int64) + 4) % 7 + 1).astype(jnp.int32)

    def _arrow_field(self, arr):
        import pyarrow as pa
        import pyarrow.compute as pc
        # Spark: 1=Sunday..7=Saturday == arrow week_start=7, count_from_zero=False
        dow = pc.day_of_week(arr, week_start=7, count_from_zero=False)
        return pc.cast(dow, pa.int32())


class WeekDay(_DateField):
    """Spark weekday(): 0 = Monday … 6 = Sunday."""

    def _field(self, days, raw):
        return ((days.astype(jnp.int64) + 3) % 7).astype(jnp.int32)

    def _arrow_field(self, arr):
        import pyarrow as pa
        import pyarrow.compute as pc
        return pc.cast(pc.day_of_week(arr), pa.int32())


class DayOfYear(_DateField):
    _arrow_fn = "day_of_year"

    def _field(self, days, raw):
        y, m, d = civil_from_days(days)
        jan1 = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        return (days - jan1 + 1).astype(jnp.int32)


class WeekOfYear(_DateField):
    """ISO 8601 week number (Spark weekofyear)."""

    def _field(self, days, raw):
        d64 = days.astype(jnp.int64)
        # ISO: week of the Thursday of this week
        dow_mon0 = (d64 + 3) % 7  # 0=Monday
        thursday = d64 + (3 - dow_mon0)
        y, m, d = civil_from_days(thursday.astype(jnp.int32))
        jan1 = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d)).astype(jnp.int64)
        return ((thursday - jan1) // 7 + 1).astype(jnp.int32)

    def _arrow_field(self, arr):
        import pyarrow as pa
        import pyarrow.compute as pc
        return pc.cast(pc.iso_week(arr), pa.int32())


class _TimeField(_DateField):
    def _tod_micros(self, raw):
        micros = raw.astype(jnp.int64)
        days = _floor_div(micros, MICROS_PER_DAY)
        return micros - days * MICROS_PER_DAY


class Hour(_TimeField):
    _arrow_fn = "hour"

    def _field(self, days, raw):
        return (self._tod_micros(raw) // 3_600_000_000).astype(jnp.int32)


class Minute(_TimeField):
    _arrow_fn = "minute"

    def _field(self, days, raw):
        return ((self._tod_micros(raw) // 60_000_000) % 60).astype(jnp.int32)


class Second(_TimeField):
    _arrow_fn = "second"

    def _field(self, days, raw):
        return ((self._tod_micros(raw) // MICROS_PER_SECOND) % 60).astype(jnp.int32)


class LastDay(UnaryExpression):
    """Last day of the month of the given date."""

    @property
    def dtype(self) -> DataType:
        return DateT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        cap = batch.capacity
        d, v = device_parts(c, cap)
        days = _days_of(jnp.broadcast_to(d, (cap,)), self.child.dtype)
        y, m, _ = civil_from_days(days)
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        first_next = days_from_civil(ny, nm, jnp.ones_like(nm))
        valid = combine_validity(cap, v, row_mask(batch.num_rows, cap))
        return make_column(DateT, first_next - 1, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import datetime
        import pyarrow as pa
        vals = self.child.eval_cpu(table, ctx).to_pylist()
        out = []
        for v in vals:
            if v is None:
                out.append(None)
            else:
                nxt = datetime.date(v.year + (v.month == 12),
                                    1 if v.month == 12 else v.month + 1, 1)
                out.append(nxt - datetime.timedelta(days=1))
        return pa.array(out, pa.date32())


class DateAdd(Expression):
    """date_add(date, days)."""

    def __init__(self, date: Expression, days: Expression, negate: bool = False):
        self.children = (date, days)
        self.negate = negate

    @property
    def dtype(self) -> DataType:
        return DateT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        l = self.children[0].eval_tpu(batch, ctx)
        r = self.children[1].eval_tpu(batch, ctx)
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        delta = jnp.broadcast_to(rd, (cap,)).astype(jnp.int32)
        if self.negate:
            delta = -delta
        data = jnp.broadcast_to(ld, (cap,)).astype(jnp.int32) + delta
        valid = combine_validity(cap, lv, rv, row_mask(batch.num_rows, cap))
        return make_column(DateT, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        l = self.children[0].eval_cpu(table, ctx)
        r = self.children[1].eval_cpu(table, ctx)
        days32 = pc.cast(l, pa.int32())
        delta = pc.cast(r, pa.int32())
        if self.negate:
            delta = pc.negate(delta)
        return pc.cast(pc.add(days32, delta), pa.date32())

    def pretty(self) -> str:
        op = "date_sub" if self.negate else "date_add"
        return f"{op}({self.children[0].pretty()}, {self.children[1].pretty()})"


class DateDiff(Expression):
    """datediff(end, start) in days."""

    def __init__(self, end: Expression, start: Expression):
        self.children = (end, start)

    @property
    def dtype(self) -> DataType:
        return IntegerT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        l = self.children[0].eval_tpu(batch, ctx)
        r = self.children[1].eval_tpu(batch, ctx)
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        data = (jnp.broadcast_to(ld, (cap,)).astype(jnp.int32)
                - jnp.broadcast_to(rd, (cap,)).astype(jnp.int32))
        valid = combine_validity(cap, lv, rv, row_mask(batch.num_rows, cap))
        return make_column(IntegerT, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        l = pc.cast(self.children[0].eval_cpu(table, ctx), pa.int32())
        r = pc.cast(self.children[1].eval_cpu(table, ctx), pa.int32())
        return pc.subtract(l, r)


class AddMonths(Expression):
    def __init__(self, date: Expression, months: Expression):
        self.children = (date, months)

    @property
    def dtype(self) -> DataType:
        return DateT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        l = self.children[0].eval_tpu(batch, ctx)
        r = self.children[1].eval_tpu(batch, ctx)
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        days = jnp.broadcast_to(ld, (cap,)).astype(jnp.int32)
        y, m, d = civil_from_days(days)
        total = (y.astype(jnp.int64) * 12 + (m - 1)
                 + jnp.broadcast_to(rd, (cap,)).astype(jnp.int64))
        ny = (total // 12).astype(jnp.int32)
        nm = (total % 12 + 1).astype(jnp.int32)
        # clamp day to last day of target month (Spark semantics)
        nny = jnp.where(nm == 12, ny + 1, ny)
        nnm = jnp.where(nm == 12, 1, nm + 1)
        last = days_from_civil(nny, nnm, jnp.ones_like(nnm)) - 1
        _, _, last_d = civil_from_days(last)
        nd = jnp.minimum(d, last_d)
        data = days_from_civil(ny, nm, nd)
        valid = combine_validity(cap, lv, rv, row_mask(batch.num_rows, cap))
        return make_column(DateT, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import calendar
        import datetime
        import pyarrow as pa
        dates = self.children[0].eval_cpu(table, ctx).to_pylist()
        months = self.children[1].eval_cpu(table, ctx)
        months = months.to_pylist() if hasattr(months, "to_pylist") \
            else [months] * len(dates)
        out = []
        for v, mo in zip(dates, months):
            if v is None or mo is None:
                out.append(None)
                continue
            total = v.year * 12 + (v.month - 1) + int(mo)
            y, m = total // 12, total % 12 + 1
            d = min(v.day, calendar.monthrange(y, m)[1])
            out.append(datetime.date(y, m, d))
        return pa.array(out, pa.date32())


class UnixTimestampFromTs(UnaryExpression):
    """unix_timestamp(ts): seconds since epoch (floor)."""

    @property
    def dtype(self) -> DataType:
        return LongT

    def _compute(self, d, ctx, valid):
        return _floor_div(d.astype(jnp.int64), MICROS_PER_SECOND)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        c = self.child.eval_cpu(table, ctx)
        micros = pc.cast(c, pa.int64())
        # floor division for negative timestamps
        import numpy as np
        vals, mask = _np_mask(micros)
        return pa.array(np.floor_divide(vals, MICROS_PER_SECOND), mask=mask)


class ToUnixMicros(UnaryExpression):
    @property
    def dtype(self) -> DataType:
        return LongT

    def _compute(self, d, ctx, valid):
        return d.astype(jnp.int64)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        return pc.cast(self.child.eval_cpu(table, ctx), pa.int64())


def _np_mask(arr):
    import pyarrow as pa
    import pyarrow.compute as pc
    a = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    mask = np.asarray(pc.is_null(a).to_numpy(zero_copy_only=False)).astype(bool)
    vals = np.asarray(a.fill_null(0).to_numpy(zero_copy_only=False))
    return vals, mask


class DateSub(DateAdd):
    """date_sub(date, days) (reference GpuDateSub)."""

    def __init__(self, date: Expression, days: Expression):
        super().__init__(date, days, negate=True)

    def pretty(self) -> str:
        return f"date_sub({self.children[0].pretty()}, {self.children[1].pretty()})"


class _EpochToTimestamp(UnaryExpression):
    """seconds/millis/micros → timestamp (reference GpuSecondsToTimestamp
    family): integer scaling on device."""

    _scale = MICROS_PER_SECOND  # micros per input unit

    @property
    def dtype(self) -> DataType:
        return TimestampT

    def _compute(self, d, ctx, valid):
        return (d.astype(jnp.int64) * self._scale).astype(jnp.int64)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        c = self.child.eval_cpu(table, ctx)
        micros = pc.multiply(pc.cast(c, pa.int64()), self._scale)
        return pc.cast(micros, pa.timestamp("us", tz="UTC"))

    def pretty(self) -> str:
        return f"{type(self).__name__.lower()}({self.child.pretty()})"


class SecondsToTimestamp(_EpochToTimestamp):
    _scale = MICROS_PER_SECOND


class MillisToTimestamp(_EpochToTimestamp):
    _scale = 1000


class MicrosToTimestamp(_EpochToTimestamp):
    _scale = 1


def _java_to_strftime(pattern: str) -> str:
    """Java SimpleDateFormat subset → strftime. Quoted literals ('T', '')
    copy through; unknown directives (incl. SSS/DD, which have no exact
    strftime width) raise ValueError — callers set tpu_supported=False at
    construction so tagging rejects the expression instead of crashing
    mid-query (mirroring GpuToTimestamp.COMPATIBLE_FORMATS)."""
    out = []
    i = 0
    mapping = {"yyyy": "%Y", "yy": "%y", "MMM": "%b", "MM": "%m", "dd": "%d",
               "HH": "%H", "mm": "%M", "ss": "%S", "EEEE": "%A", "EEE": "%a",
               "a": "%p"}
    toks = ("yyyy", "EEEE", "MMM", "EEE", "yy", "MM", "dd", "HH", "mm", "ss",
            "a")
    while i < len(pattern):
        if pattern[i] == "'":
            # Java quoted literal; '' inside quotes is a literal quote
            if pattern.startswith("''", i):
                out.append("'")
                i += 2
                continue
            j = pattern.find("'", i + 1)
            if j < 0:
                raise ValueError("unterminated quote in datetime pattern")
            lit = pattern[i + 1: j]
            out.append(lit.replace("%", "%%") if lit else "'")
            i = j + 1
            continue
        matched = False
        for tok in toks:
            if pattern.startswith(tok, i):
                out.append(mapping[tok])
                i += len(tok)
                matched = True
                break
        if matched:
            continue
        ch = pattern[i]
        if ch.isalpha():
            raise ValueError(f"unsupported datetime pattern token: {ch}")
        out.append("%%" if ch == "%" else ch)
        i += 1
    return "".join(out)


def _session_zone(ctx):
    """tzinfo of the session timezone (UTC default; unknown zones fall back
    to UTC rather than crashing the host formatting path)."""
    import datetime as _dt
    from ..tzdb import is_utc
    tz = getattr(ctx, "tz", None)
    if is_utc(tz):
        return _dt.timezone.utc
    try:
        from zoneinfo import ZoneInfo
        return ZoneInfo(tz)
    except Exception:  # noqa: BLE001 — unknown zone name
        return _dt.timezone.utc


_SF_CACHE: dict = {}


def _strftime_cached(fmt):
    """fmt → strftime string (memoized); None for null/unsupported fmt."""
    if fmt is None:
        return None
    if fmt not in _SF_CACHE:
        try:
            _SF_CACHE[fmt] = _java_to_strftime(fmt)
        except ValueError:
            _SF_CACHE[fmt] = None
    return _SF_CACHE[fmt]


def _fmt_supported(fmt) -> bool:
    """Constructor-time pattern validation (the tagging gate)."""
    if fmt is None:
        return True
    try:
        _java_to_strftime(fmt)
        return True
    except ValueError:
        return False


def _tz_local_micros(micros, ctx):
    """Epoch micros → session-local wall-clock micros regardless of the
    input dtype (device TZ table binary search; None = no TZif table)."""
    from ..tzdb import TimeZoneDB, is_utc
    if is_utc(getattr(ctx, "tz", None)):
        return micros
    db = TimeZoneDB.get(ctx.tz)
    if db is None:
        return None
    return db.utc_to_local(micros.astype(jnp.int64))


def _device_fmt_plan(fmt):
    """Tokenize a Java datetime pattern into [(kind, value)] when every
    token is fixed-width numeric (yyyy/MM/dd/HH/mm/ss/SSS) or a literal
    byte — the set a device byte-assembly can format. None otherwise."""
    if fmt is None:
        return None
    toks = []
    i = 0
    letters = "GyYMLdHhmsSaEuwWDFkKzZXQqecV'"
    while i < len(fmt):
        ch = fmt[i]
        if ch in letters:
            j = i
            while j < len(fmt) and fmt[j] == ch:
                j += 1
            run = fmt[i:j]
            # SSS deliberately absent: the construction-time gate
            # (_java_to_strftime) rejects it, and strftime's %f (micros)
            # cannot mirror Java millis on the host-fallback path
            if run not in ("yyyy", "MM", "dd", "HH", "mm", "ss"):
                return None
            toks.append(("f", run))
            i = j
        else:
            b = ch.encode("utf-8")
            if len(b) != 1:
                return None
            toks.append(("l", b[0]))
            i += 1
    return toks or None


_FMT_WIDTH = {"yyyy": 4, "MM": 2, "dd": 2, "HH": 2, "mm": 2, "ss": 2}


def _format_micros_device(micros, valid, n, cap, toks):
    """Local-wall-clock micros → formatted string column, fully on device:
    civil fields + per-token zero-padded digit bytes assembled into a
    (cap, W) byte matrix. Returns None when a year falls outside 1..9999
    (Java widens yyyy there — variable width, host path)."""
    from ..columnar.vector import TpuColumnVector
    from ..types import StringT
    micros = micros.astype(jnp.int64)
    days = _floor_div(micros, MICROS_PER_DAY)
    intra = micros - days * MICROS_PER_DAY
    y, mo, d = civil_from_days(days)
    if n:
        sel = valid[:n] if valid is not None else None
        ys = jnp.where(sel, y[:n], 2000) if sel is not None else y[:n]
        # one transfer for both bounds (each eager D→H sync is a full
        # tunnel round trip)
        ymin, ymax = map(int, jax.device_get(
            jnp.stack([jnp.min(ys), jnp.max(ys)])))
        if ymin < 1 or ymax > 9999:
            return None
    secs = intra // 1_000_000
    fields = {"yyyy": y, "MM": mo, "dd": d,
              "HH": (secs // 3600).astype(jnp.int32),
              "mm": ((secs // 60) % 60).astype(jnp.int32),
              "ss": (secs % 60).astype(jnp.int32),
              "SSS": ((intra // 1000) % 1000).astype(jnp.int32)}
    cols = []
    for kind, v in toks:
        if kind == "l":
            cols.append(jnp.full((cap,), np.uint8(v), jnp.uint8))
        else:
            val = fields[v].astype(jnp.int32)
            w = _FMT_WIDTH[v]
            for k in range(w):
                digit = (val // (10 ** (w - 1 - k))) % 10
                cols.append((digit + 48).astype(jnp.uint8))
    chars = jnp.stack(cols, axis=1).reshape(-1)
    width = len(cols)
    lens = jnp.where(jnp.arange(cap) < n, width, 0).astype(jnp.int32)
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(lens, dtype=jnp.int32)])
    return TpuColumnVector(StringT, chars, valid, n, offsets=offs)


class FromUnixTime(Expression):
    """from_unixtime(seconds, fmt) → string, UTC session timezone
    (reference GpuFromUnixTime). Host-assisted formatting."""

    def __init__(self, sec: Expression, fmt: Expression = None):
        from .base import Literal
        self.children = (sec, fmt if fmt is not None
                         else Literal("yyyy-MM-dd HH:mm:ss"))
        f = self.children[1]
        self.tpu_supported = _fmt_supported(
            f.value if isinstance(f, Literal) else None)

    @property
    def dtype(self) -> DataType:
        from ..types import StringT
        return StringT

    def _fmt(self):
        from .base import Literal
        f = self.children[1]
        return f.value if isinstance(f, Literal) else None

    def _format_list(self, secs, ctx, fmts=None):
        import datetime as _dt
        tz = _session_zone(ctx)
        out = []
        for i, s in enumerate(secs):
            fmt = fmts[i] if fmts is not None else self._fmt()
            sf = _strftime_cached(fmt)
            if s is None or sf is None:
                out.append(None)
            else:
                t = _dt.datetime.fromtimestamp(int(s), tz)
                out.append(t.strftime(sf))
        return out

    def _fmts_of(self, batch_or_table, ctx, n, is_tpu):
        """Per-row formats when the fmt child is not a literal."""
        from .base import Literal
        f = self.children[1]
        if isinstance(f, Literal):
            return None
        v = f.eval_tpu(batch_or_table, ctx) if is_tpu \
            else f.eval_cpu(batch_or_table, ctx)
        from ..columnar.vector import TpuScalar
        if isinstance(v, TpuScalar):
            return [v.value] * n
        return v.to_pylist()[:n] if hasattr(v, "to_pylist") else [v] * n

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from ..columnar.vector import TpuScalar
        from .collections import _result_from_pylist
        c = self.children[0].eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            v = self._format_list([c.value], ctx,
                                  self._fmts_of(batch, ctx, 1, True))[0]
            return TpuScalar(self.dtype, v)
        toks = _device_fmt_plan(self._fmt())
        if toks is not None and not isinstance(c, TpuScalar) \
                and getattr(c, "host_data", None) is None:
            micros = c.data.astype(jnp.int64) * 1_000_000
            local = _tz_local_micros(micros, ctx)
            if local is not None:
                out = _format_micros_device(
                    local, combine_validity(batch.capacity, c.validity,
                                            row_mask(batch.num_rows,
                                                     batch.capacity)),
                    batch.num_rows, batch.capacity, toks)
                if out is not None:
                    return out
        vals = c.to_pylist()
        fmts = self._fmts_of(batch, ctx, len(vals), True)
        return _result_from_pylist(self._format_list(vals, ctx, fmts),
                                   self.dtype, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.children[0].eval_cpu(table, ctx).to_pylist()
        fmts = self._fmts_of(table, ctx, len(vals), False)
        return pa.array(self._format_list(vals, ctx, fmts), pa.string())

    def pretty(self) -> str:
        return f"from_unixtime({self.children[0].pretty()}, {self.children[1].pretty()})"


class DateFormatClass(Expression):
    """date_format(ts, fmt) → string (reference GpuDateFormatClass). UTC only;
    host-assisted formatting over the civil fields."""

    def __init__(self, ts: Expression, fmt: Expression):
        from .base import Literal
        self.children = (ts, fmt)
        self.tpu_supported = _fmt_supported(
            fmt.value if isinstance(fmt, Literal) else None)

    @property
    def dtype(self) -> DataType:
        from ..types import StringT
        return StringT

    def _format_list(self, vals, ctx, fmts=None):
        from .base import Literal
        import datetime as _dt
        f = self.children[1]
        lit_fmt = f.value if isinstance(f, Literal) else None
        tz = _session_zone(ctx)
        out = []
        for i, v in enumerate(vals):
            fmt = fmts[i] if fmts is not None else lit_fmt
            sf = _strftime_cached(fmt)
            if v is None or sf is None:
                out.append(None)
                continue
            if isinstance(v, _dt.datetime):
                # naive values are UTC instants (the _DateField convention:
                # stored micros are instants, fields display session-local)
                t = (v if v.tzinfo is not None
                     else v.replace(tzinfo=_dt.timezone.utc)).astimezone(tz)
            elif isinstance(v, _dt.date):
                t = _dt.datetime(v.year, v.month, v.day)
            else:
                t = _dt.datetime.fromtimestamp(int(v) / 1e6, tz)
            out.append(t.strftime(sf))
        return out

    def _fmts_of(self, batch_or_table, ctx, n, is_tpu):
        from .base import Literal
        f = self.children[1]
        if isinstance(f, Literal):
            return None
        v = f.eval_tpu(batch_or_table, ctx) if is_tpu \
            else f.eval_cpu(batch_or_table, ctx)
        from ..columnar.vector import TpuScalar
        if isinstance(v, TpuScalar):
            return [v.value] * n
        return v.to_pylist()[:n] if hasattr(v, "to_pylist") else [v] * n

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .base import Literal
        from ..columnar.vector import TpuScalar
        from .collections import _result_from_pylist
        c = self.children[0].eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            return TpuScalar(self.dtype, self._format_list([c.value], ctx)[0])
        f = self.children[1]
        toks = _device_fmt_plan(f.value if isinstance(f, Literal) else None)
        if toks is not None and getattr(c, "host_data", None) is None:
            dt = self.children[0].dtype
            if isinstance(dt, TimestampType):
                local = _tz_local_micros(c.data.astype(jnp.int64), ctx)
            elif isinstance(dt, DateType):
                local = c.data.astype(jnp.int64) * MICROS_PER_DAY
            else:
                local = None
            if local is not None:
                out = _format_micros_device(
                    local, combine_validity(batch.capacity, c.validity,
                                            row_mask(batch.num_rows,
                                                     batch.capacity)),
                    batch.num_rows, batch.capacity, toks)
                if out is not None:
                    return out
        return _result_from_pylist(self._format_list(c.to_pylist(), ctx),
                                   self.dtype, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.children[0].eval_cpu(table, ctx).to_pylist()
        return pa.array(self._format_list(vals, ctx), pa.string())

    def pretty(self) -> str:
        return f"date_format({self.children[0].pretty()}, {self.children[1].pretty()})"


class ToUnixTimestamp(Expression):
    """to_unix_timestamp(str|ts|date, fmt) → bigint seconds (reference
    GpuToUnixTimestamp). String inputs parse host-side (UTC); timestamp/date
    inputs scale on device."""

    def __init__(self, child: Expression, fmt: Expression = None):
        from .base import Literal
        self.children = (child, fmt if fmt is not None
                         else Literal("yyyy-MM-dd HH:mm:ss"))
        f = self.children[1]
        self.tpu_supported = _fmt_supported(
            f.value if isinstance(f, Literal) else None)

    @property
    def dtype(self) -> DataType:
        return LongT

    def _fmt(self):
        from .base import Literal
        f = self.children[1]
        return f.value if isinstance(f, Literal) else None

    def _parse_list(self, vals, ctx, fmts=None):
        import datetime as _dt
        tz = _session_zone(ctx)
        out = []
        for i, v in enumerate(vals):
            fmt = fmts[i] if fmts is not None else self._fmt()
            sf = _strftime_cached(fmt)
            if v is None or sf is None:
                out.append(None)
                continue
            try:
                # fold=0: ambiguous wall times take the earlier offset,
                # matching java.time (and the device TZ-DB kernel)
                t = _dt.datetime.strptime(v, sf).replace(tzinfo=tz, fold=0)
                out.append(int(t.timestamp()))
            except ValueError:
                out.append(None)  # Spark: unparseable → null
        return out

    def _fmts_of(self, batch_or_table, ctx, n, is_tpu):
        from .base import Literal
        f = self.children[1]
        if isinstance(f, Literal):
            return None
        v = f.eval_tpu(batch_or_table, ctx) if is_tpu \
            else f.eval_cpu(batch_or_table, ctx)
        from ..columnar.vector import TpuScalar
        if isinstance(v, TpuScalar):
            return [v.value] * n
        return v.to_pylist()[:n] if hasattr(v, "to_pylist") else [v] * n

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        from ..columnar.batch import _repad
        from ..columnar.vector import TpuColumnVector, TpuScalar
        from ..types import DateType, StringType, TimestampType
        src = self.children[0]
        c = src.eval_tpu(batch, ctx)
        if isinstance(src.dtype, TimestampType) and isinstance(c, TpuColumnVector):
            data = _floor_div(c.data.astype(jnp.int64), MICROS_PER_SECOND)
            valid = combine_validity(batch.capacity, c.validity,
                                     row_mask(batch.num_rows, batch.capacity))
            return make_column(LongT, data, valid, batch.num_rows)
        if isinstance(src.dtype, DateType) and isinstance(c, TpuColumnVector):
            from ..tzdb import TimeZoneDB, is_utc
            local_midnight = c.data.astype(jnp.int64) * MICROS_PER_DAY
            if is_utc(getattr(ctx, "tz", None)):
                utc = local_midnight
            else:
                db = TimeZoneDB.get(ctx.tz)
                if db is None:
                    raise ValueError(f"unknown session timezone {ctx.tz}")
                utc = db.local_to_utc(local_midnight)
            data = _floor_div(utc, MICROS_PER_SECOND)
            valid = combine_validity(batch.capacity, c.validity,
                                     row_mask(batch.num_rows, batch.capacity))
            return make_column(LongT, data, valid, batch.num_rows)
        from .collections import _result_from_pylist
        vals = [c.value] * batch.num_rows if isinstance(c, TpuScalar) \
            else c.to_pylist()
        fmts = self._fmts_of(batch, ctx, len(vals), True)
        return _result_from_pylist(self._parse_list(vals, ctx, fmts),
                                   LongT, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import datetime as _dt
        import pyarrow as pa
        from ..types import DateType, StringType, TimestampType
        src = self.children[0]
        vals = src.eval_cpu(table, ctx).to_pylist()
        if isinstance(src.dtype, TimestampType):
            out = [None if v is None else
                   int(v.timestamp() // 1) if isinstance(v, _dt.datetime)
                   else int(v) // 1000000 for v in vals]
            return pa.array(out, pa.int64())
        if isinstance(src.dtype, DateType):
            tz = _session_zone(ctx)
            out = [None if v is None else
                   int(_dt.datetime(v.year, v.month, v.day,
                                    tzinfo=tz, fold=0).timestamp())
                   for v in vals]
            return pa.array(out, pa.int64())
        fmts = self._fmts_of(table, ctx, len(vals), False)
        return pa.array(self._parse_list(vals, ctx, fmts), pa.int64())

    def pretty(self) -> str:
        return f"to_unix_timestamp({self.children[0].pretty()}, {self.children[1].pretty()})"


class UnixTimestamp(ToUnixTimestamp):
    """unix_timestamp(...) — same semantics as to_unix_timestamp
    (reference GpuUnixTimestamp)."""

    def pretty(self) -> str:
        return f"unix_timestamp({self.children[0].pretty()}, {self.children[1].pretty()})"
