"""Aggregate function declarations (reference org/apache/spark/sql/rapids/aggregate/
aggregateFunctions.scala, 8314 LoC incl. shims).

Each aggregate declares: result dtype, the partial-state columns it produces
(update), and how partial states merge — the same update/merge decomposition the
reference uses (GpuAggregateFunction update/merge aggregates), which is what makes
partial-before-shuffle / final-after-shuffle work.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..types import (BooleanT, DataType, DecimalType, DoubleT, FractionalType,
                     IntegralType, LongT, NumericType)
from .base import Expression, _DEFAULT_CTX


class AggregateFunction(Expression):
    """Declarative aggregate; evaluated by the aggregate execs, not columnar_eval."""

    unevaluable = True  # driven by the aggregate execs (reference Unevaluable)

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def child(self) -> Expression:
        return self.children[0]

    #: name of the device reduction for update ("sum"|"count"|"min"|"max"|...)
    update_op: str = ""

    @property
    def dtype(self) -> DataType:
        raise NotImplementedError

    def pretty(self) -> str:
        return f"{type(self).__name__.lower()}({', '.join(c.pretty() for c in self.children)})"

    # partial-state schema: list of (suffix, dtype, reduce_op_for_merge)
    def state_fields(self) -> List[Tuple[str, DataType, str]]:
        raise NotImplementedError


class Sum(AggregateFunction):
    update_op = "sum"

    @property
    def dtype(self) -> DataType:
        ct = self.child.dtype
        if isinstance(ct, IntegralType):
            return LongT
        if isinstance(ct, DecimalType):
            return DecimalType(min(ct.precision + 10, 38), ct.scale)
        return DoubleT

    @property
    def nullable(self) -> bool:
        return True

    def state_fields(self):
        return [("sum", self.dtype, "sum"), ("nonnull", LongT, "sum")]


class Count(AggregateFunction):
    update_op = "count"

    @property
    def dtype(self) -> DataType:
        return LongT

    @property
    def nullable(self) -> bool:
        return False

    def state_fields(self):
        return [("count", LongT, "sum")]


class Min(AggregateFunction):
    update_op = "min"

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def state_fields(self):
        return [("min", self.dtype, "min"), ("nonnull", LongT, "sum")]


class Max(AggregateFunction):
    update_op = "max"

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def state_fields(self):
        return [("max", self.dtype, "max"), ("nonnull", LongT, "sum")]


class Average(AggregateFunction):
    update_op = "avg"

    @property
    def dtype(self) -> DataType:
        ct = self.child.dtype
        if isinstance(ct, DecimalType):
            return DecimalType(min(ct.precision + 4, 38), min(ct.scale + 4, 38))
        return DoubleT

    @property
    def nullable(self) -> bool:
        return True

    def state_fields(self):
        return [("sum", DoubleT, "sum"), ("count", LongT, "sum")]


class First(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    update_op = "first"

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def state_fields(self):
        return [("first", self.dtype, "first"), ("has", BooleanT, "max")]


class Last(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    update_op = "last"

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def state_fields(self):
        return [("last", self.dtype, "last"), ("has", BooleanT, "max")]


class StddevBase(AggregateFunction):
    """Welford-style via (n, sum, m2) partial state (reference M2/stddev/variance)."""

    @property
    def dtype(self) -> DataType:
        return DoubleT

    @property
    def nullable(self) -> bool:
        return True

    def state_fields(self):
        return [("n", LongT, "sum"), ("sum", DoubleT, "sum"),
                ("sumsq", DoubleT, "sum")]


class StddevSamp(StddevBase):
    update_op = "stddev_samp"


class StddevPop(StddevBase):
    update_op = "stddev_pop"


class VarianceSamp(StddevBase):
    update_op = "var_samp"


class VariancePop(StddevBase):
    update_op = "var_pop"


class CountDistinct(AggregateFunction):
    update_op = "count_distinct"

    @property
    def dtype(self) -> DataType:
        return LongT

    @property
    def nullable(self) -> bool:
        return False

    def state_fields(self):
        raise NotImplementedError("count distinct expands via grouped dedup")


class CollectList(AggregateFunction):
    """collect_list (reference GpuCollectList, aggregateFunctions.scala).
    Returns [] (never null) for empty/all-null groups, like Spark."""

    update_op = "collect_list"

    @property
    def dtype(self) -> DataType:
        from ..types import ArrayType
        return ArrayType(self.child.dtype, contains_null=False)

    @property
    def nullable(self) -> bool:
        return False

    def state_fields(self):
        return [("list", self.dtype, "concat")]


class CollectSet(AggregateFunction):
    """collect_set (reference GpuCollectSet). Element order is unspecified —
    the device impl yields value-sorted sets; wrap in sort_array for stable
    comparisons (the reference's tests do the same)."""

    update_op = "collect_set"

    @property
    def dtype(self) -> DataType:
        from ..types import ArrayType
        return ArrayType(self.child.dtype, contains_null=False)

    @property
    def nullable(self) -> bool:
        return False

    def state_fields(self):
        return [("set", self.dtype, "union")]


class Percentile(AggregateFunction):
    """Exact percentile with linear interpolation (reference GpuPercentile.scala).
    percentage is a literal double or list of doubles."""

    update_op = "percentile"

    def __init__(self, child: Expression, percentage):
        super().__init__(child)
        self.percentages = list(percentage) if isinstance(percentage, (list, tuple)) \
            else [float(percentage)]
        self.is_array = isinstance(percentage, (list, tuple))
        for p in self.percentages:
            if not (0.0 <= p <= 1.0):
                raise ValueError("percentile must be in [0, 1]")

    @property
    def dtype(self) -> DataType:
        from ..types import ArrayType
        return ArrayType(DoubleT, contains_null=False) if self.is_array else DoubleT

    @property
    def nullable(self) -> bool:
        return True

    def pretty(self) -> str:
        return f"percentile({self.child.pretty()}, {self.percentages})"


class ApproximatePercentile(Percentile):
    """approx_percentile (reference GpuApproximatePercentile.scala): a
    mergeable t-digest sketch (kernels/tdigest.py) built with device-side
    bucketing — the k1 scale function maps sorted ranks straight to
    centroids, so every group's digest falls out of one segment reduction.
    Partial digests merge through exchanges (merge_digests); quantiles
    interpolate on centroid midpoints and cast back to the input type."""

    update_op = "approx_percentile"

    def __init__(self, child: Expression, percentage, accuracy: int = 10000):
        super().__init__(child, percentage)
        self.accuracy = accuracy

    @property
    def dtype(self) -> DataType:
        from ..types import ArrayType
        base = self.child.dtype
        return ArrayType(base, contains_null=False) if self.is_array else base


class _CovarianceBase(AggregateFunction):
    """Two-input aggregates over (x, y); rows with any null are skipped
    (reference GpuCovPopulation/GpuCovSample, aggregateFunctions.scala)."""

    def __init__(self, x: Expression, y: Expression):
        super().__init__(x, y)

    @property
    def dtype(self) -> DataType:
        return DoubleT

    @property
    def nullable(self) -> bool:
        return True

    def state_fields(self):
        return [("n", LongT, "sum"), ("sx", DoubleT, "sum"),
                ("sy", DoubleT, "sum"), ("sxy", DoubleT, "sum"),
                ("sx2", DoubleT, "sum"), ("sy2", DoubleT, "sum")]


class CovSample(_CovarianceBase):
    update_op = "covar_samp"


class CovPopulation(_CovarianceBase):
    update_op = "covar_pop"


class Corr(_CovarianceBase):
    """Pearson correlation (reference GpuPearsonCorrelation)."""
    update_op = "corr"
