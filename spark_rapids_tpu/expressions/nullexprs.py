"""Null-handling expressions (reference org/apache/spark/sql/rapids/nullExpressions.scala)."""

from __future__ import annotations

import jax.numpy as jnp

from ..types import BooleanT, DataType
from ..columnar.vector import TpuColumnVector, TpuScalar, row_mask
from .base import (Expression, UnaryExpression, _DEFAULT_CTX, combine_validity,
                   device_parts, make_column)


class IsNull(UnaryExpression):
    @property
    def dtype(self) -> DataType:
        return BooleanT

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        if isinstance(c, TpuScalar):
            data = jnp.broadcast_to(jnp.asarray(c.is_null), (cap,)) & mask
        else:
            data = (~c.validity if c.validity is not None
                    else jnp.zeros((cap,), jnp.bool_)) & mask
        return make_column(BooleanT, data, None, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.is_null(self.child.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"{self.child.pretty()} IS NULL"


class IsNotNull(UnaryExpression):
    @property
    def dtype(self) -> DataType:
        return BooleanT

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        if isinstance(c, TpuScalar):
            data = jnp.broadcast_to(jnp.asarray(not c.is_null), (cap,)) & mask
        else:
            data = (c.validity if c.validity is not None else mask) & mask
        return make_column(BooleanT, data, None, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.is_valid(self.child.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"{self.child.pretty()} IS NOT NULL"


class IsNaN(UnaryExpression):
    @property
    def dtype(self) -> DataType:
        return BooleanT

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        cap = batch.capacity
        d, v = device_parts(c, cap)
        data = jnp.isnan(jnp.broadcast_to(d, (cap,)))
        if v is not None:
            data = data & v
        return make_column(BooleanT, data & row_mask(batch.num_rows, cap),
                           None, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.fill_null(pc.is_nan(self.child.eval_cpu(table, ctx)), False)


class Coalesce(Expression):
    """First non-null argument (reference GpuCoalesce)."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    @property
    def nullable(self) -> bool:
        return all(c.nullable for c in self.children)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        data = None
        valid = jnp.zeros((cap,), jnp.bool_)
        for c in self.children:
            r = c.eval_tpu(batch, ctx)
            rd, rv = device_parts(r, cap)
            rd = jnp.broadcast_to(rd, (cap,))
            rv = rv if rv is not None else mask
            if data is None:
                data, valid = rd, rv
            else:
                take = ~valid & rv
                data = jnp.where(take, rd, data)
                valid = valid | rv
        return make_column(self.dtype, data, valid & mask, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.coalesce(*[c.eval_cpu(table, ctx) for c in self.children])

    def pretty(self) -> str:
        return f"coalesce({', '.join(c.pretty() for c in self.children)})"


class NaNvl(Expression):
    """nanvl(a, b): b where a is NaN (reference GpuNaNvl)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        a = self.children[0].eval_tpu(batch, ctx)
        b = self.children[1].eval_tpu(batch, ctx)
        ad, av = device_parts(a, cap)
        bd, bv = device_parts(b, cap)
        ad = jnp.broadcast_to(ad, (cap,))
        isnan = jnp.isnan(ad)
        data = jnp.where(isnan, jnp.broadcast_to(bd, (cap,)).astype(ad.dtype), ad)
        av = av if av is not None else mask
        bv = bv if bv is not None else mask
        valid = jnp.where(isnan, bv, av)
        return make_column(self.dtype, data, valid & mask, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        a = self.children[0].eval_cpu(table, ctx)
        b = self.children[1].eval_cpu(table, ctx)
        return pc.if_else(pc.fill_null(pc.is_nan(a), False), b, a)


class AtLeastNNonNulls(Expression):
    """Filter helper used by df.na.drop (reference GpuAtLeastNNonNulls):
    true when at least n of the children evaluate non-null (NaN counts as
    null for float children, matching Spark)."""

    def __init__(self, n: int, *children: Expression):
        self.n = int(n)
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        return BooleanT

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        count = jnp.zeros((cap,), jnp.int32)
        for c in self.children:
            v = c.eval_tpu(batch, ctx)
            if isinstance(v, TpuScalar):
                import math
                nn = v.value is not None and not (
                    isinstance(v.value, float) and math.isnan(v.value))
                nonnull = jnp.full((cap,), nn, jnp.bool_)
            else:
                nonnull = v.validity if v.validity is not None \
                    else jnp.ones((cap,), jnp.bool_)
                if jnp.issubdtype(v.data.dtype, jnp.floating):
                    nonnull = nonnull & ~jnp.isnan(v.data)
            count = count + nonnull.astype(jnp.int32)
        data = (count >= self.n) & row_mask(batch.num_rows, cap)
        return make_column(BooleanT, data, None, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import math
        import pyarrow as pa
        n = table.num_rows
        cols = []
        for c in self.children:
            r = c.eval_cpu(table, ctx)
            cols.append(r.to_pylist() if isinstance(r, (pa.Array, pa.ChunkedArray))
                        else [r] * n)
        out = []
        for row in zip(*cols) if cols else []:
            nn = sum(1 for v in row
                     if v is not None and not (isinstance(v, float) and math.isnan(v)))
            out.append(nn >= self.n)
        if not cols:
            out = [0 >= self.n] * table.num_rows
        return pa.array(out, pa.bool_())

    def pretty(self) -> str:
        return f"atleastnnonnulls({self.n}, {', '.join(c.pretty() for c in self.children)})"


class KnownNotNull(UnaryExpression):
    """Optimizer marker: child is known non-null (reference GpuKnownNotNull).
    Evaluation is a passthrough that drops the validity mask."""

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        # pure passthrough: the marker is a planner assertion, not a cast —
        # stripping validity here would turn erroneously-null rows into zeros
        return self.child.eval_tpu(batch, ctx)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        return self.child.eval_cpu(table, ctx)

    def pretty(self) -> str:
        return f"knownnotnull({self.child.pretty()})"


class KnownFloatingPointNormalized(UnaryExpression):
    """Optimizer marker: NaN/-0.0 already normalized — pure passthrough
    (reference GpuKnownFloatingPointNormalized)."""

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        return self.child.eval_tpu(batch, ctx)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        return self.child.eval_cpu(table, ctx)


class NormalizeNaNAndZero(UnaryExpression):
    """Canonicalize NaN bit patterns and -0.0 → 0.0 so float grouping/join
    keys compare by equality (reference GpuNormalizeNaNAndZero)."""

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            import math
            v = c.value
            if isinstance(v, float):
                if math.isnan(v):
                    v = float("nan")
                elif v == 0.0:
                    v = 0.0
            return TpuScalar(c.dtype, v)
        d = c.data
        if jnp.issubdtype(d.dtype, jnp.floating):
            d = jnp.where(d == 0, jnp.zeros((), d.dtype), d)
            d = jnp.where(jnp.isnan(d), jnp.full((), jnp.nan, d.dtype), d)
        return TpuColumnVector(c.dtype, d, c.validity, c.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        arr = self.child.eval_cpu(table, ctx)
        if not (pa.types.is_floating(arr.type)):
            return arr
        import numpy as np
        import pyarrow.compute as pc
        vals = np.asarray(arr.fill_null(0).to_numpy(zero_copy_only=False)).copy()
        vals[vals == 0] = 0.0
        vals[np.isnan(vals)] = float("nan")
        mask = np.asarray(pc.is_null(arr).to_numpy(zero_copy_only=False)).astype(bool)
        return pa.array(vals, mask=mask)
