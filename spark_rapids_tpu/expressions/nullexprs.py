"""Null-handling expressions (reference org/apache/spark/sql/rapids/nullExpressions.scala)."""

from __future__ import annotations

import jax.numpy as jnp

from ..types import BooleanT, DataType
from ..columnar.vector import TpuColumnVector, TpuScalar, row_mask
from .base import (Expression, UnaryExpression, _DEFAULT_CTX, combine_validity,
                   device_parts, make_column)


class IsNull(UnaryExpression):
    @property
    def dtype(self) -> DataType:
        return BooleanT

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        if isinstance(c, TpuScalar):
            data = jnp.broadcast_to(jnp.asarray(c.is_null), (cap,)) & mask
        else:
            data = (~c.validity if c.validity is not None
                    else jnp.zeros((cap,), jnp.bool_)) & mask
        return make_column(BooleanT, data, None, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.is_null(self.child.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"{self.child.pretty()} IS NULL"


class IsNotNull(UnaryExpression):
    @property
    def dtype(self) -> DataType:
        return BooleanT

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        if isinstance(c, TpuScalar):
            data = jnp.broadcast_to(jnp.asarray(not c.is_null), (cap,)) & mask
        else:
            data = (c.validity if c.validity is not None else mask) & mask
        return make_column(BooleanT, data, None, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.is_valid(self.child.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"{self.child.pretty()} IS NOT NULL"


class IsNaN(UnaryExpression):
    @property
    def dtype(self) -> DataType:
        return BooleanT

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        cap = batch.capacity
        d, v = device_parts(c, cap)
        data = jnp.isnan(jnp.broadcast_to(d, (cap,)))
        if v is not None:
            data = data & v
        return make_column(BooleanT, data & row_mask(batch.num_rows, cap),
                           None, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.fill_null(pc.is_nan(self.child.eval_cpu(table, ctx)), False)


class Coalesce(Expression):
    """First non-null argument (reference GpuCoalesce)."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    @property
    def nullable(self) -> bool:
        return all(c.nullable for c in self.children)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        data = None
        valid = jnp.zeros((cap,), jnp.bool_)
        for c in self.children:
            r = c.eval_tpu(batch, ctx)
            rd, rv = device_parts(r, cap)
            rd = jnp.broadcast_to(rd, (cap,))
            rv = rv if rv is not None else mask
            if data is None:
                data, valid = rd, rv
            else:
                take = ~valid & rv
                data = jnp.where(take, rd, data)
                valid = valid | rv
        return make_column(self.dtype, data, valid & mask, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.coalesce(*[c.eval_cpu(table, ctx) for c in self.children])

    def pretty(self) -> str:
        return f"coalesce({', '.join(c.pretty() for c in self.children)})"


class NaNvl(Expression):
    """nanvl(a, b): b where a is NaN (reference GpuNaNvl)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        a = self.children[0].eval_tpu(batch, ctx)
        b = self.children[1].eval_tpu(batch, ctx)
        ad, av = device_parts(a, cap)
        bd, bv = device_parts(b, cap)
        ad = jnp.broadcast_to(ad, (cap,))
        isnan = jnp.isnan(ad)
        data = jnp.where(isnan, jnp.broadcast_to(bd, (cap,)).astype(ad.dtype), ad)
        av = av if av is not None else mask
        bv = bv if bv is not None else mask
        valid = jnp.where(isnan, bv, av)
        return make_column(self.dtype, data, valid & mask, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        a = self.children[0].eval_cpu(table, ctx)
        b = self.children[1].eval_cpu(table, ctx)
        return pc.if_else(pc.fill_null(pc.is_nan(a), False), b, a)
