"""parse_url expression (reference GpuParseUrl.scala, JNI ParseURI kernel).

Host-assisted via urllib.parse (the reference's kernel mirrors java.net.URI;
urllib is slightly more lenient on malformed URLs — priced as incompat)."""

from __future__ import annotations

from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..types import DataType, StringT
from .base import Expression, _DEFAULT_CTX
from .strings import _HostRowOp


_PARTS = {"HOST", "PATH", "QUERY", "REF", "PROTOCOL", "FILE", "AUTHORITY",
          "USERINFO"}


def parse_url_part(url: Optional[str], part: Optional[str],
                   key: Optional[str] = None) -> Optional[str]:
    if url is None or part is None:
        return None
    if part not in _PARTS:
        return None
    try:
        u = urlparse(url.strip())
    except ValueError:
        return None
    if not u.scheme:
        return None
    if part == "PROTOCOL":
        return u.scheme or None
    if part == "HOST":
        try:
            return u.hostname
        except ValueError:
            return None
    if part == "PATH":
        return u.path
    if part == "QUERY":
        if not u.query:
            return None
        if key is None:
            return u.query
        vals = parse_qs(u.query, keep_blank_values=True).get(key)
        return vals[0] if vals else None
    if part == "REF":
        return u.fragment or None
    if part == "FILE":
        return u.path + (f"?{u.query}" if u.query else "")
    if part == "AUTHORITY":
        return u.netloc or None
    if part == "USERINFO":
        if "@" not in u.netloc:
            return None
        return u.netloc.rsplit("@", 1)[0]
    return None


class ParseUrl(_HostRowOp):
    """parse_url(url, part[, key]) → string."""

    def __init__(self, url: Expression, part: Expression,
                 key: Expression = None):
        self.children = (url, part) + ((key,) if key is not None else ())

    @property
    def dtype(self) -> DataType:
        return StringT

    def _row(self, *vals, ctx):
        url, part = vals[0], vals[1]
        key = vals[2] if len(vals) > 2 else None
        return parse_url_part(url, part, key)

    def pretty(self) -> str:
        return f"parse_url({', '.join(c.pretty() for c in self.children)})"
