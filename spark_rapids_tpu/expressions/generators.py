"""Generator expressions: explode / posexplode / stack (+ _outer variants).

TPU re-design of the reference's generator support
(/root/reference/sql-plugin/src/main/scala/com/nvidia/spark/rapids/GpuGenerateExec.scala:
GpuExplode, GpuPosExplode, GpuStack and the GpuGenerator trait). A generator maps
one input row to zero or more output rows; the exec layer (execs/generate.py)
gathers the required child columns by a parent-row index map produced here.

Device strategy (vs the reference's cudf `explode`/`explode_position` kernels):
the list column already holds offsets + flattened child on device, so explode is
  counts  = offsets[1:] - offsets[:-1]
  parent  = repeat(arange(n), counts)          # gather map for child columns
  element = child[offsets[parent] + pos]       # contiguous, so a slice when !outer
computed entirely in XLA ops; the only host sync is the output row count (the
same data-dependent-size sync a filter pays).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from ..types import ArrayType, DataType, IntegerT, MapType
from .base import Expression, UnaryExpression


class Generator(Expression):
    """Base generator: produces `element_schema()` columns and a variable number
    of rows per input row. Not evaluable via columnar_eval — the Generate exec
    drives it (reference GpuGenerator, GpuGenerateExec.scala)."""

    outer: bool = False
    unevaluable = True  # driven by GenerateExec (reference GpuUnevaluable)

    def element_schema(self) -> List[Tuple[str, DataType, bool]]:
        """(name, dtype, nullable) for each generated column."""
        raise NotImplementedError

    @property
    def dtype(self) -> DataType:
        # a generator has no single result type; exposed for error messages only
        raise TypeError(f"{type(self).__name__} is a generator, not a value expression")


class MultiAlias(Expression):
    """Names for a multi-column generator, e.g.
    posexplode(m).alias("p", "k", "v") (Spark MultiAlias)."""

    unevaluable = True  # naming wrapper resolved by GenerateExec

    def __init__(self, child: Generator, names: Sequence[str]):
        self.children = (child,)
        self.names = list(names)

    @property
    def child(self) -> Generator:
        return self.children[0]

    def pretty(self) -> str:
        return f"{self.child.pretty()} AS ({', '.join(self.names)})"


class Explode(Generator):
    """explode(array) / explode(map) → one row per element (per entry).
    Reference: GpuExplode (GpuGenerateExec.scala)."""

    def __init__(self, child: Expression, outer: bool = False,
                 with_position: bool = False):
        self.children = (child,)
        self.outer = outer
        self.with_position = with_position

    @property
    def child(self) -> Expression:
        return self.children[0]

    def element_schema(self):
        ct = self.child.dtype
        cols: List[Tuple[str, DataType, bool]] = []
        if self.with_position:
            # outer: the filler row for a null/empty input has pos NULL (Spark
            # GenerateExec nulls ALL generator outputs on outer filler rows)
            cols.append(("pos", IntegerT, self.outer))
        if isinstance(ct, ArrayType):
            cols.append(("col", ct.element_type,
                         ct.contains_null or self.outer))
        elif isinstance(ct, MapType):
            cols.append(("key", ct.key_type, self.outer))
            cols.append(("value", ct.value_type,
                         ct.value_contains_null or self.outer))
        else:
            raise TypeError(f"explode expects array or map, got {ct}")
        return cols

    def pretty(self) -> str:
        name = "posexplode" if self.with_position else "explode"
        return f"{name}{'_outer' if self.outer else ''}({self.child.pretty()})"


class Stack(Generator):
    """stack(n, e1, ..., ek): n rows of k/n columns per input row.
    Reference: GpuStack (GpuGenerateExec.scala)."""

    def __init__(self, n: int, exprs: Sequence[Expression]):
        if n <= 0:
            raise ValueError("stack row count must be positive")
        if not exprs:
            raise ValueError("stack requires at least one value expression")
        self.children = tuple(exprs)
        self.n = n
        self.num_cols = -(-len(exprs) // n)  # ceil

    def element_schema(self):
        from ..types import NullT
        cols = []
        for c in range(self.num_cols):
            # column type = common type of exprs at positions r*num_cols + c
            dts = []
            nullable = False
            for r in range(self.n):
                i = r * self.num_cols + c
                if i < len(self.children):
                    dts.append(self.children[i].dtype)
                    nullable = nullable or self.children[i].nullable
                else:
                    nullable = True
            first = next((d for d in dts if d != NullT), dts[0] if dts else NullT)
            for d in dts:
                if d != first and d != NullT:
                    raise TypeError(
                        f"stack column {c}: incompatible types {first} vs {d}")
            cols.append((f"col{c}", first, nullable))
        return cols

    def pretty(self) -> str:
        return f"stack({self.n}, {', '.join(c.pretty() for c in self.children)})"


class ReplicateRows(Generator):
    """replicate_rows(n, cols...): repeats the row n times (reference
    GpuReplicateRows, GpuGenerateExec.scala — used by some Delta paths)."""

    def __init__(self, exprs: Sequence[Expression]):
        self.children = tuple(exprs)

    def element_schema(self):
        return [(f"col{i}", e.dtype, e.nullable)
                for i, e in enumerate(self.children[1:])]

    def pretty(self) -> str:
        return f"replicate_rows({', '.join(c.pretty() for c in self.children)})"


# ---------------------------------------------------------------------------
# Grouping-set markers (Spark grouping.scala: Grouping / GroupingID / Cube /
# Rollup; resolved away by the grouping-analytics rewrite in session.py)
# ---------------------------------------------------------------------------

class GroupingID(Expression):
    """grouping_id(): bitmask of nulled-out grouping columns; replaced by a
    reference to the Expand gid column during grouping-sets lowering."""

    children = ()
    unevaluable = True  # rewritten away before evaluation

    @property
    def dtype(self) -> DataType:
        from ..types import LongT
        return LongT

    @property
    def nullable(self) -> bool:
        return False

    def pretty(self) -> str:
        return "grouping_id()"


class GroupingExpr(UnaryExpression):
    """grouping(col): 1 if col is nulled-out in this grouping set else 0;
    lowered to (gid >> bit) & 1 during grouping-sets rewrite."""

    @property
    def dtype(self) -> DataType:
        from ..types import ByteT
        return ByteT

    @property
    def nullable(self) -> bool:
        return False

    def pretty(self) -> str:
        return f"grouping({self.child.pretty()})"
