"""Cast expression — Spark's cast matrix subset with ANSI support.

Reference: /root/reference/sql-plugin/.../GpuCast.scala (1903 LoC) + CastChecks in
TypeChecks.scala. Implemented pairs (grown over rounds, gated by CastChecks in
plan/typechecks.py): numeric↔numeric (with Spark's overflow wrap / ANSI raise),
bool↔numeric, numeric↔string, string→numeric (host-assisted), date/timestamp↔long,
anything→string per Spark formatting for fixed-width types.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..types import (BooleanType, BooleanT, ByteType, DataType, DateType,
                     DecimalType, DoubleType, FloatType, FractionalType, IntegerType,
                     IntegralType, LongType, NumericType, ShortType, StringType,
                     StringT, TimestampType)
from ..columnar.vector import TpuColumnVector, TpuScalar, row_mask
from .base import (EvalContext, Expression, ExpressionError, UnaryExpression,
                   _DEFAULT_CTX, combine_validity, device_parts, make_column)

_INT_BOUNDS = {np.dtype(np.int8): (-128, 127),
               np.dtype(np.int16): (-32768, 32767),
               np.dtype(np.int32): (-2**31, 2**31 - 1),
               np.dtype(np.int64): (-2**63, 2**63 - 1)}


class Cast(UnaryExpression):
    def __init__(self, child: Expression, to_type: DataType, ansi: Optional[bool] = None):
        super().__init__(child)
        self._to = to_type
        self._ansi = ansi

    @property
    def dtype(self) -> DataType:
        return self._to

    @property
    def nullable(self) -> bool:
        return True

    def pretty(self) -> str:
        return f"cast({self.child.pretty()} AS {self._to.simple_string()})"

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        src = self.child.dtype
        dst = self._to
        c = self.child.eval_tpu(batch, ctx)
        ansi = self._ansi if self._ansi is not None else ctx.ansi
        if isinstance(c, TpuScalar):
            return TpuScalar(dst, _cast_scalar(c.value, src, dst, ansi))
        if src == dst:
            return c
        if isinstance(src, StringType) or isinstance(dst, StringType):
            return _cast_via_host(c, src, dst, batch, ansi)
        cap = batch.capacity
        d, v = device_parts(c, cap)
        valid = combine_validity(cap, v, row_mask(batch.num_rows, cap))
        data, extra_null = _device_numeric_cast(d, src, dst, ansi, valid)
        if extra_null is not None:
            valid = combine_validity(cap, valid, ~extra_null)
        return make_column(dst, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        from ..types import to_arrow
        c = self.child.eval_cpu(table, ctx)
        src, dst = self.child.dtype, self._to
        ansi = self._ansi if self._ansi is not None else ctx.ansi
        if not isinstance(c, (pa.Array, pa.ChunkedArray)):
            return _cast_scalar(c, src, dst, ansi)
        if isinstance(dst, StringType):
            return _format_to_string_arrow(c, src)
        if isinstance(src, StringType):
            return _parse_string_arrow(c, dst, ansi)
        at = to_arrow(dst)
        if isinstance(src, FractionalType) and isinstance(dst, IntegralType):
            # Spark float→int truncates toward zero, out-of-range wraps (non-ANSI)
            ln, lm = _np_of(c)
            return pa.array(_float_to_int_np(ln, at.to_pandas_dtype(), ansi, ~lm),
                            mask=lm)
        if isinstance(src, TimestampType) and isinstance(dst, IntegralType):
            # Spark timestampToLong = floorDiv(micros, 1e6), not raw micros;
            # narrower targets wrap like java narrowing (ANSI raises)
            micros, lm = _np_of(pc.cast(c, pa.int64()))
            secs = np.floor_divide(micros, 1_000_000)
            np_t = np.dtype(dst.np_dtype)
            if np_t.itemsize < 8:
                lo, hi = _INT_BOUNDS[np_t]
                if ansi and bool((((secs < lo) | (secs > hi)) & ~lm).any()):
                    raise ExpressionError("cast overflow")
                secs = secs.astype(np_t)  # two's-complement wrap
            return pa.array(secs, mask=lm).cast(at, safe=False)
        if isinstance(src, IntegralType) and isinstance(dst, TimestampType):
            secs, lm = _np_of(c)
            return pa.array(secs.astype(np.int64) * 1_000_000,
                            mask=lm).cast(at)
        try:
            return pc.cast(c, at, safe=ansi)
        except pa.ArrowInvalid as e:
            if ansi:
                raise ExpressionError(str(e)) from e
            return pc.cast(c, at, safe=False)


def _np_of(arr):
    import pyarrow as pa
    import pyarrow.compute as pc
    a = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    mask = np.asarray(pc.is_null(a).to_numpy(zero_copy_only=False)).astype(bool)
    vals = np.asarray(a.fill_null(0).to_numpy(zero_copy_only=False))
    return vals, mask


def _float_to_int_np(vals, np_int, ansi, valid):
    lo, hi = _INT_BOUNDS[np.dtype(np_int)]
    finite = np.isfinite(vals)
    if ansi and bool(((~finite | (vals < lo) | (vals > hi)) & valid).any()):
        raise ExpressionError("cast overflow")
    v = np.trunc(np.where(np.isnan(vals), 0.0, vals))
    # 2**63-1 is not float-representable: use exact power-of-two range tests
    hi_f = np.float64(float(hi) if np.dtype(np_int).itemsize < 8 else 2.0**63)
    lo_f = np.float64(lo)
    in_range = (v >= lo_f) & (v < hi_f) if np.dtype(np_int).itemsize == 8 \
        else (v >= lo_f) & (v <= hi_f)
    safe = np.where(in_range, v, 0.0).astype(np_int)
    return np.where(v >= hi_f, np_int(hi), np.where(v < lo_f, np_int(lo), safe))


def _device_numeric_cast(d, src: DataType, dst: DataType, ansi: bool, valid):
    """Fixed-width device cast. Returns (data, extra_null_mask_or_None)."""
    carrier = dst.np_dtype
    if isinstance(src, BooleanType) and isinstance(dst, NumericType):
        return d.astype(carrier), None
    if isinstance(dst, BooleanType):
        return (d != 0), None
    if isinstance(src, FractionalType) and isinstance(dst, IntegralType):
        lo, hi = _INT_BOUNDS[np.dtype(carrier)]
        nan = jnp.isnan(d)
        if ansi:
            bad = nan | (d < lo) | (d > hi)
            if valid is not None:
                bad = bad & valid
            if bool(jnp.any(bad)):
                raise ExpressionError("cast overflow")
        # Java (int)/(long) conversion: NaN→0, out-of-range clamps to MIN/MAX.
        # For int64 the upper bound 2**63-1 is not float-representable; use exact
        # power-of-two range tests instead of clip.
        v = jnp.trunc(jnp.where(nan, 0.0, d))
        hi_f = 2.0 ** 63 if np.dtype(carrier).itemsize == 8 else float(hi)
        in_range = (v >= float(lo)) & (v < hi_f) if np.dtype(carrier).itemsize == 8 \
            else (v >= float(lo)) & (v <= hi_f)
        safe = jnp.where(in_range, v, 0.0).astype(carrier)
        data = jnp.where(v >= hi_f, jnp.asarray(hi, carrier),
                         jnp.where(v < float(lo), jnp.asarray(lo, carrier), safe))
        return data, None
    if isinstance(src, IntegralType) and isinstance(dst, IntegralType):
        if np.dtype(carrier).itemsize < np.dtype(src.np_dtype).itemsize and ansi:
            lo, hi = _INT_BOUNDS[np.dtype(carrier)]
            bad = (d < lo) | (d > hi)
            if valid is not None:
                bad = bad & valid
            if bool(jnp.any(bad)):
                raise ExpressionError("cast overflow")
        return d.astype(carrier), None  # wraps like java narrowing (non-ANSI)
    if isinstance(src, (DateType,)) and isinstance(dst, IntegralType):
        return d.astype(carrier), None
    if isinstance(src, TimestampType) and isinstance(dst, LongType):
        return _trunc_div_seconds(d), None
    if isinstance(src, IntegralType) and isinstance(dst, TimestampType):
        return (d.astype(jnp.int64) * 1_000_000), None
    if isinstance(src, TimestampType) and isinstance(dst, DoubleType):
        return d.astype(jnp.float64) / 1e6, None
    if isinstance(src, NumericType) and isinstance(dst, NumericType):
        return d.astype(carrier), None
    raise NotImplementedError(f"device cast {src} -> {dst}")


def _trunc_div_seconds(d):
    # Spark timestampToLong = Math.floorDiv(micros, 1e6): -0.5s -> -1
    # (jnp integer // is floor division already)
    return d // 1_000_000


def _cast_via_host(col: TpuColumnVector, src, dst, batch, ansi):
    import pyarrow as pa
    arr = col.to_arrow()
    if isinstance(dst, StringType):
        out = _format_to_string_arrow(arr, src)
    else:
        out = _parse_string_arrow(arr, dst, ansi)
    res = TpuColumnVector.from_arrow(out)
    if res.capacity != batch.capacity:
        from ..columnar.batch import _repad
        res = _repad(res, batch.capacity)
    return res


def _format_to_string_arrow(arr, src: DataType):
    """Spark-exact value formatting (Ryu-style shortest repr for floats, 'true'/'false',
    decimal trailing-zero rules) — reference GpuCast castToString."""
    import pyarrow as pa
    vals = arr.to_pylist()
    out = []
    for v in vals:
        if v is None:
            out.append(None)
        elif isinstance(src, BooleanType):
            out.append("true" if v else "false")
        elif isinstance(src, (FloatType, DoubleType)):
            out.append(_spark_float_str(v, isinstance(src, FloatType)))
        elif isinstance(src, TimestampType):
            out.append(v.strftime("%Y-%m-%d %H:%M:%S") +
                       (f".{v.microsecond:06d}".rstrip("0") if v.microsecond else ""))
        elif isinstance(src, DateType):
            out.append(v.isoformat())
        else:
            out.append(str(v))
    return pa.array(out, type=pa.string())


def _spark_float_str(v: float, is_float32: bool) -> str:
    """Java Double.toString / Float.toString semantics exactly: shortest
    round-trip digits; plain decimal form when 1e-3 <= |v| < 1e7, otherwise
    scientific `d.dddEexp` with one digit before the point (reference
    GpuCast castToString float path / castFloatingTypesToString; the 'Ryu
    quirks' of VERDICT r2 — python repr switches notation at different
    thresholds, so the digits are re-laid-out here)."""
    if np.isnan(v):
        return "NaN"
    if np.isinf(v):
        return "Infinity" if v > 0 else "-Infinity"
    f = float(np.float32(v)) if is_float32 else float(v)
    if f == 0.0:
        return "-0.0" if np.signbit(f) else "0.0"
    # shortest round-trip digits (str() is shortest for the type; known
    # divergence: ties between equally-short reprs can pick a different
    # digit than Java's Ryu, e.g. Double.MIN_VALUE 5e-324 vs Java 4.9E-324)
    s = str(np.float32(v)) if is_float32 else repr(f)
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    if "e" in s or "E" in s:
        mant, _, exp = s.replace("E", "e").partition("e")
        exp10 = int(exp)
    else:
        mant, exp10 = s, 0
    # normalize mantissa to pure digit string + exponent of leading digit
    if "." in mant:
        int_part, frac = mant.split(".")
    else:
        int_part, frac = mant, ""
    digits = (int_part + frac).lstrip("0")
    lead_exp = exp10 + len(int_part.lstrip("0")) - 1 if int_part.strip("0") \
        else exp10 - (len(frac) - len(frac.lstrip("0"))) - 1
    digits = digits.rstrip("0") or "0"
    sign = "-" if neg else ""
    if -3 <= lead_exp < 7:
        if lead_exp >= 0:
            ip = digits[:lead_exp + 1].ljust(lead_exp + 1, "0")
            fp = digits[lead_exp + 1:] or "0"
        else:
            ip = "0"
            fp = "0" * (-lead_exp - 1) + digits
        return f"{sign}{ip}.{fp}"
    fp = digits[1:] or "0"
    return f"{sign}{digits[0]}.{fp}E{lead_exp}"


def _parse_string_arrow(arr, dst: DataType, ansi: bool):
    import pyarrow as pa
    import pyarrow.compute as pc
    from ..types import to_arrow
    trimmed = pc.utf8_trim_whitespace(arr)
    at = to_arrow(dst)
    if isinstance(dst, BooleanType):
        lowered = pc.utf8_lower(trimmed)
        true_set = pa.array(["t", "true", "y", "yes", "1"])
        false_set = pa.array(["f", "false", "n", "no", "0"])
        is_t = pc.is_in(lowered, value_set=true_set)
        is_f = pc.is_in(lowered, value_set=false_set)
        bad = pc.and_(pc.invert(is_t), pc.invert(is_f))
        if ansi and bool(pc.any(pc.fill_null(bad, False)).as_py()):
            raise ExpressionError("invalid input for cast to boolean")
        return pc.if_else(bad, pa.scalar(None, pa.bool_()), is_t)
    if isinstance(dst, IntegralType):
        # Spark accepts trailing .xxx for int casts? Only via decimal path; keep strict
        vals = trimmed.to_pylist() if isinstance(trimmed, pa.Array) else trimmed.combine_chunks().to_pylist()
        out = []
        lo, hi = _INT_BOUNDS[np.dtype(dst.np_dtype)]
        for s in vals:
            if s is None:
                out.append(None)
                continue
            try:
                v = int(s)
                if v < lo or v > hi:
                    raise ValueError("overflow")
                out.append(v)
            except ValueError:
                if ansi:
                    raise ExpressionError(f"invalid input for cast to {dst}: {s!r}")
                out.append(None)
        return pa.array(out, type=at)
    if isinstance(dst, (FloatType, DoubleType)):
        vals = trimmed.to_pylist() if isinstance(trimmed, pa.Array) else trimmed.combine_chunks().to_pylist()
        out = []
        for s in vals:
            if s is None:
                out.append(None)
                continue
            try:
                sl = s.lower()
                if sl in ("nan",):
                    out.append(float("nan"))
                elif sl in ("inf", "infinity", "+inf", "+infinity"):
                    out.append(float("inf"))
                elif sl in ("-inf", "-infinity"):
                    out.append(float("-inf"))
                else:
                    # Java Double.parseDouble accepts a trailing d/D/f/F
                    # type suffix ("1d" == 1.0); Spark inherits it
                    if sl and sl[-1] in "df" and len(sl) > 1 \
                            and (sl[-2].isdigit() or sl[-2] == "."):
                        s = s[:-1]
                    out.append(float(s))
            except ValueError:
                if ansi:
                    raise ExpressionError(f"invalid input for cast to {dst}: {s!r}")
                out.append(None)
        return pa.array(out, type=at)
    if isinstance(dst, DateType):
        vals = trimmed.to_pylist() if isinstance(trimmed, pa.Array) \
            else trimmed.combine_chunks().to_pylist()
        out = []
        for s in vals:
            d = None if s is None else _parse_spark_date(s)
            if s is not None and d is None and ansi:
                raise ExpressionError(f"invalid input for cast to date: {s!r}")
            out.append(d)
        return pa.array(out, type=pa.date32())
    if isinstance(dst, TimestampType):
        vals = trimmed.to_pylist() if isinstance(trimmed, pa.Array) \
            else trimmed.combine_chunks().to_pylist()
        out = []
        for s in vals:
            us = None if s is None else _parse_spark_timestamp(s)
            if s is not None and us is None and ansi:
                raise ExpressionError(
                    f"invalid input for cast to timestamp: {s!r}")
            out.append(us)
        return pa.array(out, type=pa.timestamp("us")).cast(at)
    if isinstance(dst, DecimalType):
        vals = trimmed.to_pylist() if isinstance(trimmed, pa.Array) \
            else trimmed.combine_chunks().to_pylist()
        out = []
        for s in vals:
            d = None if s is None else _parse_spark_decimal(
                s, dst.precision, dst.scale)
            if s is not None and d is None and ansi:
                raise ExpressionError(
                    f"invalid input for cast to {dst.simple_string()}: {s!r}")
            out.append(d)
        return pa.array(out, type=pa.decimal128(dst.precision, dst.scale))
    raise NotImplementedError(f"string cast to {dst}")


_DATE_RE = None
_TIME_RE = None


def _parse_spark_date(s: str):
    """Spark stringToDate: `[+-]y{1,7}[-m[-d]]`, anything after the day
    allowed when separated by ' ' or 'T' (reference GpuCast castStringToDate;
    org.apache.spark.sql.catalyst.util.DateTimeUtils.stringToDate).
    Returns datetime.date or None."""
    import datetime
    import re as _re2
    global _DATE_RE
    if _DATE_RE is None:
        _DATE_RE = _re2.compile(
            r"^([+-]?\d{1,7})(?:-(\d{1,2})(?:-(\d{1,2})(?:[ T].*)?)?)?$")
    m = _DATE_RE.match(s.strip())
    if not m:
        return None
    y = int(m.group(1))
    mo = int(m.group(2)) if m.group(2) else 1
    d = int(m.group(3)) if m.group(3) else 1
    try:
        return datetime.date(y, mo, d)  # proleptic Gregorian, 1..9999
    except ValueError:
        return None


def _parse_spark_timestamp(s: str):
    """Spark stringToTimestamp (UTC session zone): date part as in
    stringToDate, optional `[h]h[:[m]m[:[s]s[.f{1,9}]]]` after ' ' or 'T',
    optional zone `Z` / `UTC` / `GMT` / `[+-]h[h][:mm]`. Returns epoch
    microseconds (int) or None. 'epoch' special literal supported."""
    import datetime
    import re as _re2
    s = s.strip()
    if s.lower() == "epoch":
        return 0
    global _TIME_RE
    if _TIME_RE is None:
        _TIME_RE = _re2.compile(
            r"^([+-]?\d{1,7})(?:-(\d{1,2})(?:-(\d{1,2})"
            r"(?:[ T](\d{1,2})(?::(\d{1,2})(?::(\d{1,2})"
            r"(?:\.(\d{1,9}))?)?)?\s*(.*))?)?)?$")
    m = _TIME_RE.match(s)
    if not m:
        return None
    y = int(m.group(1))
    mo = int(m.group(2)) if m.group(2) else 1
    d = int(m.group(3)) if m.group(3) else 1
    hh = int(m.group(4)) if m.group(4) else 0
    mi = int(m.group(5)) if m.group(5) else 0
    ss = int(m.group(6)) if m.group(6) else 0
    frac = m.group(7) or ""
    us = int(frac[:6].ljust(6, "0")) if frac else 0
    zone = (m.group(8) or "").strip()
    off_us = 0
    if zone:
        zm = _re2.match(r"^(?:Z|z|UTC|GMT)$", zone)
        if zm:
            off_us = 0
        else:
            zm = _re2.match(r"^([+-])(\d{1,2})(?::(\d{1,2}))?$", zone)
            if not zm:
                return None
            sign = 1 if zm.group(1) == "+" else -1
            off_us = sign * ((int(zm.group(2)) * 60
                              + int(zm.group(3) or 0)) * 60 * 1_000_000)
    if hh > 23 or mi > 59 or ss > 59:
        return None
    try:
        day = datetime.date(y, mo, d)
    except ValueError:
        return None
    epoch_days = (day - datetime.date(1970, 1, 1)).days
    local = (epoch_days * 86_400_000_000
             + (hh * 3600 + mi * 60 + ss) * 1_000_000 + us)
    return local - off_us


def _parse_spark_decimal(s: str, precision: int, scale: int):
    """Spark string→decimal: parse, HALF_UP round to scale, null on
    overflow/garbage (reference GpuCast castStringToDecimal)."""
    import decimal
    try:
        d = decimal.Decimal(s.strip())
    except decimal.InvalidOperation:
        return None
    if not d.is_finite():
        return None
    # default context precision (28) would raise on wide-but-valid
    # decimal(38) inputs; Spark's Decimal holds 38 digits + rounding room
    with decimal.localcontext() as dctx:
        dctx.prec = 60
        q = d.quantize(decimal.Decimal(1).scaleb(-scale),
                       rounding=decimal.ROUND_HALF_UP)
    if len(q.as_tuple().digits) - scale > precision - scale and q != 0:
        return None  # integral part too wide
    return q


def _cast_scalar(v, src, dst, ansi):
    if v is None:
        return None
    import pyarrow as pa
    arr = pa.array([v], type=None if not isinstance(src, DataType) else None)
    # simple python-level conversion mirroring the array paths
    if isinstance(dst, StringType):
        return _format_to_string_arrow(pa.array([v]), src)[0].as_py()
    if isinstance(dst, BooleanType):
        return bool(v)
    if isinstance(dst, IntegralType):
        lo, hi = _INT_BOUNDS[np.dtype(dst.np_dtype)]
        if isinstance(v, str):
            v = int(v.strip())
        iv = int(v)
        if iv < lo or iv > hi:
            if ansi:
                raise ExpressionError("cast overflow")
            iv = ((iv - lo) % (hi - lo + 1)) + lo  # java wrap
        return iv
    if isinstance(dst, (FloatType, DoubleType)):
        return float(v)
    raise NotImplementedError(f"scalar cast {src} -> {dst}")
