"""Conditional expressions (reference conditionalExpressions.scala: GpuIf, GpuCaseWhen,
GpuGreatest, GpuLeast). On TPU both branches evaluate eagerly and are blended with
jnp.where — the vectorized-engine norm (the reference does the same: cuDF computes
both sides then copy_if_else; lazy side evaluation is a CPU-row-engine concept)."""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

from ..types import DataType
from ..columnar.vector import TpuScalar, row_mask
from .base import (Expression, _DEFAULT_CTX, combine_validity, device_parts,
                   make_column)
from .predicates import nan_aware_lt


class If(Expression):
    def __init__(self, predicate: Expression, true_value: Expression,
                 false_value: Expression):
        self.children = (predicate, true_value, false_value)

    @property
    def dtype(self) -> DataType:
        return self.children[1].dtype

    @property
    def nullable(self) -> bool:
        return self.children[1].nullable or self.children[2].nullable

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        p = self.children[0].eval_tpu(batch, ctx)
        t = self.children[1].eval_tpu(batch, ctx)
        f = self.children[2].eval_tpu(batch, ctx)
        pd, pv = device_parts(p, cap)
        cond = jnp.broadcast_to(pd, (cap,)).astype(jnp.bool_)
        if pv is not None:
            cond = cond & pv  # null predicate → false branch (Spark semantics)
        td, tv = device_parts(t, cap)
        fd, fv = device_parts(f, cap)
        td = jnp.broadcast_to(td, (cap,))
        fd = jnp.broadcast_to(fd, (cap,)).astype(td.dtype)
        data = jnp.where(cond, td, fd)
        tv = tv if tv is not None else mask
        fv = fv if fv is not None else mask
        valid = jnp.where(cond, tv, fv)
        return make_column(self.dtype, data, valid & mask, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        p = self.children[0].eval_cpu(table, ctx)
        t = self.children[1].eval_cpu(table, ctx)
        f = self.children[2].eval_cpu(table, ctx)
        return pc.if_else(pc.fill_null(p, False), t, f)

    def pretty(self) -> str:
        c = self.children
        return f"if({c[0].pretty()}, {c[1].pretty()}, {c[2].pretty()})"


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 … ELSE ve END; branches stored as flat children:
    (p1, v1, p2, v2, …[, else])."""

    def __init__(self, branches: List[Tuple[Expression, Expression]],
                 else_value: Optional[Expression] = None):
        flat: List[Expression] = []
        for p, v in branches:
            flat.extend((p, v))
        if else_value is not None:
            flat.append(else_value)
        self.children = tuple(flat)
        self._n_branches = len(branches)
        self._has_else = else_value is not None

    @property
    def branches(self):
        return [(self.children[2 * i], self.children[2 * i + 1])
                for i in range(self._n_branches)]

    @property
    def else_value(self) -> Optional[Expression]:
        return self.children[-1] if self._has_else else None

    @property
    def dtype(self) -> DataType:
        return self.children[1].dtype

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        mask = row_mask(batch.num_rows, cap)
        decided = jnp.zeros((cap,), jnp.bool_)
        data = None
        valid = jnp.zeros((cap,), jnp.bool_)
        for pred, value in self.branches:
            pd, pv = device_parts(pred.eval_tpu(batch, ctx), cap)
            cond = jnp.broadcast_to(pd, (cap,)).astype(jnp.bool_)
            if pv is not None:
                cond = cond & pv
            take = cond & ~decided
            vd, vv = device_parts(value.eval_tpu(batch, ctx), cap)
            vd = jnp.broadcast_to(vd, (cap,))
            vv = vv if vv is not None else mask
            if data is None:
                data = jnp.where(take, vd, jnp.zeros((), vd.dtype))
            else:
                data = jnp.where(take, vd.astype(data.dtype), data)
            valid = jnp.where(take, vv, valid)
            decided = decided | cond
        if self.else_value is not None:
            ed, ev = device_parts(self.else_value.eval_tpu(batch, ctx), cap)
            ed = jnp.broadcast_to(ed, (cap,))
            ev = ev if ev is not None else mask
            data = jnp.where(~decided, ed.astype(data.dtype), data)
            valid = jnp.where(~decided, ev, valid)
        # no else: undecided rows are null (valid stays False)
        return make_column(self.dtype, data, valid & mask, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        result = (self.else_value.eval_cpu(table, ctx) if self.else_value is not None
                  else pa.scalar(None, type=_arrow_type_of(self.dtype)))
        for pred, value in reversed(self.branches):
            p = pc.fill_null(pred.eval_cpu(table, ctx), False)
            result = pc.if_else(p, value.eval_cpu(table, ctx), result)
        return result

    def pretty(self) -> str:
        parts = [f"WHEN {p.pretty()} THEN {v.pretty()}" for p, v in self.branches]
        if self.else_value is not None:
            parts.append(f"ELSE {self.else_value.pretty()}")
        return "CASE " + " ".join(parts) + " END"


def _arrow_type_of(dt: DataType):
    from ..types import to_arrow
    return to_arrow(dt)


class Greatest(Expression):
    """greatest(...): max ignoring nulls; NaN greater than everything
    (reference GpuGreatest)."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _pick(self, cur, cur_v, cand, cand_v):
        better = cand_v & (~cur_v | nan_aware_lt(cur, cand))
        return jnp.where(better, cand, cur), cur_v | cand_v

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        return _fold_minmax(self, batch, ctx, self._pick)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.max_element_wise(*[c.eval_cpu(table, ctx) for c in self.children])


class Least(Expression):
    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _pick(self, cur, cur_v, cand, cand_v):
        better = cand_v & (~cur_v | nan_aware_lt(cand, cur))
        return jnp.where(better, cand, cur), cur_v | cand_v

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        return _fold_minmax(self, batch, ctx, self._pick)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.min_element_wise(*[c.eval_cpu(table, ctx) for c in self.children])


def _fold_minmax(expr, batch, ctx, pick):
    cap = batch.capacity
    mask = row_mask(batch.num_rows, cap)
    cur = None
    cur_v = jnp.zeros((cap,), jnp.bool_)
    for c in expr.children:
        d, v = device_parts(c.eval_tpu(batch, ctx), cap)
        d = jnp.broadcast_to(d, (cap,))
        v = v if v is not None else mask
        if cur is None:
            cur, cur_v = jnp.where(v, d, jnp.zeros((), d.dtype)), v
        else:
            cur, cur_v = pick(cur, cur_v, d.astype(cur.dtype), v)
    return make_column(expr.dtype, cur, cur_v & mask, batch.num_rows)
