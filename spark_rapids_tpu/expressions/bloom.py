"""Bloom-filter aggregate + might_contain (runtime join-filter support).

Reference: GpuBloomFilterAggregate / GpuBloomFilterMightContain (shimmed under
spark330/, backed by the spark-rapids-jni BloomFilter CUDA kernel). Spark uses
these as dynamic join filters on long join keys (spark 3.3+).

Blob format here is framework-internal (built and probed only by this pair):
  b"TPBF" | int32 k (hash count) | int64 m (bits) | packbits(bitset, little)
Hashing: two Spark-exact murmur3 passes over the long value (h1 = m3(v, 0),
h2 = m3(v, h1)), probes at (h1 + i*h2) mod m — the standard Kirsch-
Mitzenmacher double hashing the reference's BloomFilterImpl uses.
"""

from __future__ import annotations

import math
import struct
from typing import Optional

import numpy as np

from ..types import BinaryT, BooleanT, DataType, LongT
from .base import Expression, UnaryExpression, _DEFAULT_CTX
from .aggregates import AggregateFunction

_MAGIC = b"TPBF"


def _np_murmur3_long(v_i64: np.ndarray, seed_u32) -> np.ndarray:
    """Spark hashLong (numpy; identical math to hashexprs.murmur3_long)."""
    def mix_k1(k1):
        k1 = (k1 * np.uint32(0xCC9E2D51)).astype(np.uint32)
        k1 = ((k1 << np.uint32(15)) | (k1 >> np.uint32(17))).astype(np.uint32)
        return (k1 * np.uint32(0x1B873593)).astype(np.uint32)

    def mix_h1(h1, k1):
        h1 = (h1 ^ k1).astype(np.uint32)
        h1 = ((h1 << np.uint32(13)) | (h1 >> np.uint32(19))).astype(np.uint32)
        return (h1 * np.uint32(5) + np.uint32(0xE6546B64)).astype(np.uint32)

    v = np.asarray(v_i64, dtype=np.int64)
    lo = (v & np.int64(0xFFFFFFFF)).astype(np.uint32)
    hi = ((v >> np.int64(32)) & np.int64(0xFFFFFFFF)).astype(np.uint32)
    h1 = mix_h1(np.uint32(seed_u32) if np.isscalar(seed_u32) else seed_u32,
                mix_k1(lo))
    h1 = mix_h1(h1, mix_k1(hi))
    h1 ^= np.uint32(8)
    h1 ^= h1 >> np.uint32(16)
    h1 = (h1 * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h1 ^= h1 >> np.uint32(13)
    h1 = (h1 * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h1 ^= h1 >> np.uint32(16)
    return h1


def optimal_k(m_bits: int, n_items: int) -> int:
    if n_items <= 0:
        return 1
    return max(1, int(round(m_bits / n_items * math.log(2))))


def _probe_positions(values: np.ndarray, m: int, k: int) -> np.ndarray:
    """(k, n) bit positions via double hashing."""
    h1 = _np_murmur3_long(values, 0).astype(np.int64)
    h2 = _np_murmur3_long(values, h1.astype(np.uint32)).astype(np.int64)
    i = np.arange(k, dtype=np.int64)[:, None]
    combined = h1[None, :] + i * h2[None, :]
    return np.abs(combined) % np.int64(m)


def bloom_build(values: np.ndarray, m: int, k: int) -> bytes:
    bits = np.zeros(m, dtype=bool)
    if values.size:
        pos = _probe_positions(values.astype(np.int64), m, k)
        bits[pos.ravel()] = True
    return (_MAGIC + struct.pack("<iq", k, m)
            + np.packbits(bits, bitorder="little").tobytes())


def bloom_might_contain(blob: Optional[bytes], values: np.ndarray) -> np.ndarray:
    if blob is None:
        return np.zeros(len(values), dtype=bool)
    if blob[:4] != _MAGIC:
        raise ValueError("not a TPBF bloom filter blob")
    k, m = struct.unpack("<iq", blob[4:16])
    bits = np.unpackbits(np.frombuffer(blob[16:], dtype=np.uint8),
                         bitorder="little")[:m].astype(bool)
    if not len(values):
        return np.zeros(0, dtype=bool)
    pos = _probe_positions(values.astype(np.int64), m, k)
    return bits[pos].all(axis=0)


class BloomFilterAggregate(AggregateFunction):
    """bloom_filter_agg(longCol[, estimatedItems[, numBits]]) → binary blob."""

    update_op = "bloom_filter"

    def __init__(self, child: Expression, estimated_items: int = 1_000_000,
                 num_bits: int = 8_388_608):
        super().__init__(child)
        self.estimated_items = int(estimated_items)
        self.num_bits = max(64, int(num_bits))
        self.k = optimal_k(self.num_bits, self.estimated_items)

    @property
    def dtype(self) -> DataType:
        return BinaryT

    @property
    def nullable(self) -> bool:
        return True

    def build(self, values: np.ndarray) -> bytes:
        return bloom_build(values, self.num_bits, self.k)

    def pretty(self) -> str:
        return f"bloom_filter_agg({self.child.pretty()})"


class BloomFilterMightContain(Expression):
    """might_contain(bloomBlob, longValue) → boolean (null blob → null;
    null value → null, matching the reference)."""

    def __init__(self, bloom: Expression, value: Expression):
        self.children = (bloom, value)

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def _blob(self, side, ctx) -> Optional[bytes]:
        from .base import Literal
        b = self.children[0]
        if isinstance(b, Literal):
            return b.value
        raise ValueError("might_contain bloom side must be a literal blob "
                         "(collect the aggregate first)")

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        blob = self._blob(table, ctx)
        arr = self.children[1].eval_cpu(table, ctx)
        if not isinstance(arr, (pa.Array, pa.ChunkedArray)):
            if arr is None or blob is None:
                return None
            return bool(bloom_might_contain(
                blob, np.asarray([arr], np.int64))[0])
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if blob is None:
            return pa.nulls(len(arr), pa.bool_())
        nulls = np.asarray(arr.is_null())
        vals = np.asarray(arr.fill_null(0).to_numpy(zero_copy_only=False),
                          dtype=np.int64)
        out = bloom_might_contain(blob, vals)
        return pa.array(out, mask=nulls)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from ..columnar.vector import TpuColumnVector, TpuScalar, row_mask
        from .base import combine_validity, make_column
        import jax.numpy as jnp
        blob = self._blob(batch, ctx)
        v = self.children[1].eval_tpu(batch, ctx)
        cap = batch.capacity
        if isinstance(v, TpuScalar):
            if v.value is None or blob is None:
                return TpuScalar(BooleanT, None)
            return TpuScalar(BooleanT, bool(bloom_might_contain(
                blob, np.asarray([v.value], np.int64))[0]))
        valid = combine_validity(cap, v.validity, row_mask(batch.num_rows, cap))
        if blob is None:
            return make_column(BooleanT, jnp.zeros((cap,), jnp.bool_),
                               jnp.zeros((cap,), jnp.bool_), batch.num_rows)
        # host probe (bit math is cheap; the reference runs this in a JNI
        # kernel — a Pallas probe kernel is the upgrade path)
        vals = np.asarray(v.data).astype(np.int64)
        out = bloom_might_contain(blob, vals)
        return make_column(BooleanT, jnp.asarray(out), valid, batch.num_rows)

    def pretty(self) -> str:
        return (f"might_contain({self.children[0].pretty()}, "
                f"{self.children[1].pretty()})")
