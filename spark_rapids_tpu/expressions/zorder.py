"""Z-order expressions: bit interleaving and Hilbert-curve indexing.

Reference: /root/reference/sql-plugin/src/main/scala/org/apache/spark/sql/rapids/
zorder/ (GpuInterleaveBits.scala, GpuHilbertLongIndex.scala, ZOrderRules.scala)
backed by the spark-rapids-jni `ZOrder` CUDA kernels. Used by Delta Lake
`OPTIMIZE ... ZORDER BY (...)` to compute a clustering key.

Semantics (matching Delta's open-source InterleaveBits operator, which the
reference extends to BYTE/SHORT/LONG):
  * InterleaveBits(c1..cN): all children share one integral type of W bytes;
    output is BINARY of N*W bytes per row. Bits are taken MSB-first, cycling
    over columns per bit position (bit 31 of c1, bit 31 of c2, ..., bit 30 of
    c1, ...), packed MSB-first into output bytes. Nulls are read as 0 (the
    reference notes nulls never occur in practice because the input is the
    non-nullable GpuPartitionerExpr).
  * HilbertLongIndex(numBits, c1..cN): N int columns, `numBits` significant
    bits each (N*numBits <= 64); output LONG Hilbert-curve distance. Uses
    Skilling's axes-to-transpose transform then bit interleaving.

TPU design: both are pure bit arithmetic — shifts, masks, XOR — which XLA maps
straight onto the VPU. The per-bit loops run over *static* bit counts, so they
unroll at trace time into a fixed op DAG; there is no data-dependent control
flow. Output bytes are packed via a (rows, bytes, 8) reshape + weighted sum.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..types import (BinaryType, ByteType, DataType, IntegerType, IntegralType,
                     LongType, ShortType)
from ..columnar.vector import TpuColumnVector
from .base import Expression, EvalContext, _DEFAULT_CTX, device_parts


_UNSIGNED = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def _child_width(dt: DataType) -> int:
    if isinstance(dt, ByteType):
        return 1
    if isinstance(dt, ShortType):
        return 2
    if isinstance(dt, LongType):
        return 8
    return 4  # int (and the degenerate/non-integral default, like the reference)


def _eval_unsigned_columns(children, batch, ctx, width: int):
    """Evaluate children to (N, capacity) unsigned arrays with nulls as 0."""
    cols = []
    for ch in children:
        v = ch.eval_tpu(batch, ctx)
        data, valid = device_parts(v, batch.capacity)
        data = jnp.broadcast_to(data, (batch.capacity,))
        if valid is not None:
            data = jnp.where(valid, data, jnp.zeros((), data.dtype))
        cols.append(data.astype(_UNSIGNED[width]))
    return jnp.stack(cols)  # (N, capacity)


def _pack_bits_msb(bits: jax.Array) -> jax.Array:
    """(rows, total_bits) 0/1 → (rows, total_bits//8) uint8, MSB-first."""
    rows, total = bits.shape
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint32)
    grouped = bits.reshape(rows, total // 8, 8).astype(jnp.uint32)
    return jnp.sum(grouped * weights, axis=-1).astype(jnp.uint8)


class InterleaveBits(Expression):
    """interleave_bits(c1..cN) -> BINARY(N*W). Reference GpuInterleaveBits."""

    def __init__(self, children: Sequence[Expression]):
        self.children = tuple(children)

    @property
    def _width(self) -> int:
        self._validate()
        head = self.children[0].dtype if self.children else IntegerType()
        return _child_width(head)

    def _validate(self) -> None:
        # Reference GpuInterleaveBits uses ExpectsInputTypes: every child must
        # share one integral type; anything else is an analysis error, never a
        # silently truncated key.
        for ch in self.children:
            if not isinstance(ch.dtype, IntegralType):
                raise TypeError(
                    f"interleave_bits requires integral columns, got "
                    f"{ch.dtype} in {ch.pretty()}")
        widths = {_child_width(ch.dtype) for ch in self.children}
        if len(widths) > 1:
            raise TypeError(
                "interleave_bits requires all columns to share one integral "
                f"type, got {[str(ch.dtype) for ch in self.children]}")

    @property
    def dtype(self) -> DataType:
        return BinaryType()

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx: EvalContext = _DEFAULT_CTX):
        n = len(self.children)
        width = self._width
        nbits = 8 * width
        vals = _eval_unsigned_columns(self.children, batch, ctx, width)  # (N, cap)
        cap = batch.capacity
        shifts = jnp.arange(nbits - 1, -1, -1, dtype=vals.dtype)  # MSB first
        # (N, cap, nbits) -> transpose to (cap, nbits, N): per output bit
        # position, columns cycle fastest — delta's interleave order.
        bits = ((vals[:, :, None] >> shifts[None, None, :]) & 1).astype(jnp.uint8)
        stream = jnp.transpose(bits, (1, 2, 0)).reshape(cap, nbits * n)
        packed = _pack_bits_msb(stream)  # (cap, N*W) uint8
        row_bytes = n * width
        offsets = (jnp.arange(cap + 1, dtype=jnp.int32) * row_bytes)
        return TpuColumnVector(BinaryType(), packed.reshape(-1), None,
                               batch.num_rows, offsets=offsets)

    def eval_cpu(self, table, ctx: EvalContext = _DEFAULT_CTX):
        import pyarrow as pa
        n = len(self.children)
        width = self._width
        nbits = 8 * width
        arrs = []
        for ch in self.children:
            a = ch.eval_cpu(table, ctx)
            np_a = np.asarray(a.fill_null(0) if hasattr(a, "fill_null") else a)
            arrs.append(np_a.astype(f"u{width}"))
        rows = len(arrs[0]) if arrs else 0
        out = np.zeros((rows, nbits * n), dtype=np.uint8)
        for b in range(nbits):
            for j in range(n):
                out[:, b * n + j] = (arrs[j] >> (nbits - 1 - b)) & 1
        packed = np.packbits(out, axis=1)  # MSB-first per byte
        return pa.array([row.tobytes() for row in packed], type=pa.binary())

    def pretty(self) -> str:
        return f"interleave_bits({', '.join(c.pretty() for c in self.children)})"


def _hilbert_transpose(axes, num_bits: int):
    """Skilling's AxestoTranspose, vectorized over rows.

    axes: list of N uint32 arrays (coordinates, `num_bits` significant bits).
    Returns the transposed Hilbert code (list of N uint32 arrays) whose
    bit-interleave is the Hilbert distance. The loops run over static bit
    positions/column indices and unroll at trace time.
    """
    x = list(axes)
    n = len(x)
    m = np.uint32(1) << np.uint32(num_bits - 1)
    # Inverse undo of excess work
    q = int(m)
    while q > 1:
        p = jnp.uint32(q - 1)
        qq = jnp.uint32(q)
        for i in range(n):
            cond = (x[i] & qq) != 0
            # if bit set: invert low bits of x[0]; else swap low bits x[0]<->x[i]
            t = jnp.where(cond, jnp.zeros_like(x[0]), (x[0] ^ x[i]) & p)
            x0_new = jnp.where(cond, x[0] ^ p, x[0] ^ t)
            x[i] = jnp.where(cond, x[i], x[i] ^ t)
            x[0] = x0_new
        q >>= 1
    # Gray encode
    for i in range(1, n):
        x[i] = x[i] ^ x[i - 1]
    t = jnp.zeros_like(x[0])
    q = int(m)
    while q > 1:
        cond = (x[n - 1] & jnp.uint32(q)) != 0
        t = jnp.where(cond, t ^ jnp.uint32(q - 1), t)
        q >>= 1
    for i in range(n):
        x[i] = x[i] ^ t
    return x


class HilbertLongIndex(Expression):
    """hilbert_index(numBits, c1..cN) -> LONG. Reference GpuHilbertLongIndex."""

    def __init__(self, num_bits: int, children: Sequence[Expression]):
        if not 1 <= num_bits <= 32:
            raise ValueError("numBits must be in [1, 32] (int coordinates)")
        if num_bits * len(children) > 64:
            raise ValueError("numBits * num_columns must be <= 64")
        self.num_bits = int(num_bits)
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        return LongType()

    @property
    def nullable(self) -> bool:
        return False

    def _index_from_axes(self, axes):
        """Interleave transposed-code bits (MSB-first, column-major cycle) into
        one int64 distance."""
        x = _hilbert_transpose(axes, self.num_bits)
        n = len(x)
        out = jnp.zeros_like(x[0], dtype=jnp.uint64)
        pos = n * self.num_bits - 1
        for b in range(self.num_bits - 1, -1, -1):
            for i in range(n):
                bit = ((x[i] >> jnp.uint32(b)) & 1).astype(jnp.uint64)
                out = out | (bit << jnp.uint64(pos))
                pos -= 1
        return out.astype(jnp.int64)

    def eval_tpu(self, batch, ctx: EvalContext = _DEFAULT_CTX):
        mask = jnp.uint32((1 << self.num_bits) - 1) if self.num_bits < 32 \
            else jnp.uint32(0xFFFFFFFF)
        axes = [a & mask for a in
                _eval_unsigned_columns(self.children, batch, ctx, 4)]
        out = self._index_from_axes(axes)
        return TpuColumnVector(LongType(), out, None, batch.num_rows)

    def eval_cpu(self, table, ctx: EvalContext = _DEFAULT_CTX):
        import pyarrow as pa
        # Reuse the device math on host arrays via numpy->jax (cpu backend is
        # the parity oracle; the transform is identical).
        arrs = []
        for ch in self.children:
            a = ch.eval_cpu(table, ctx)
            np_a = np.asarray(a.fill_null(0) if hasattr(a, "fill_null") else a)
            arrs.append(jnp.asarray(np_a.astype(np.uint32)
                                    & np.uint32((1 << self.num_bits) - 1)))
        out = np.asarray(self._index_from_axes(arrs))
        return pa.array(out, type=pa.int64())

    def pretty(self) -> str:
        cols = ", ".join(c.pretty() for c in self.children)
        return f"hilbert_long_index({self.num_bits}, {cols})"
