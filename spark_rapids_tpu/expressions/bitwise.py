"""Bitwise and shift expressions.

Reference: /root/reference/sql-plugin/src/main/scala/org/apache/spark/sql/rapids/
bitwise.scala (GpuBitwiseAnd/Or/Xor/Not, GpuShiftLeft/Right/RightUnsigned).
Device path is a single XLA elementwise op; Spark semantics notes:
  * shift distance is taken modulo the bit width (Java <</>>/>>> behavior);
  * >>> (unsigned shift) reinterprets the value as unsigned for the shift.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..types import DataType, IntegralType
from .base import (BinaryExpression, EvalContext, UnaryExpression, _DEFAULT_CTX,
                   combine_validity, device_parts, make_column)


class _BitwiseBinary(BinaryExpression):
    symbol = "?"

    @property
    def dtype(self) -> DataType:
        return self.left.dtype

    def pretty(self) -> str:
        return f"({self.left.pretty()} {self.symbol} {self.right.pretty()})"

    def _compute(self, ld, rd, ctx, valid):
        raise NotImplementedError

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        l = self.left.eval_cpu(table, ctx)
        r = self.right.eval_cpu(table, ctx)
        return self._cpu_compute(l, r, ctx)


class BitwiseAnd(_BitwiseBinary):
    symbol = "&"

    def _compute(self, ld, rd, ctx, valid):
        return ld & rd

    def _cpu_compute(self, l, r, ctx):
        import pyarrow.compute as pc
        return pc.bit_wise_and(l, r)


class BitwiseOr(_BitwiseBinary):
    symbol = "|"

    def _compute(self, ld, rd, ctx, valid):
        return ld | rd

    def _cpu_compute(self, l, r, ctx):
        import pyarrow.compute as pc
        return pc.bit_wise_or(l, r)


class BitwiseXor(_BitwiseBinary):
    symbol = "^"

    def _compute(self, ld, rd, ctx, valid):
        return ld ^ rd

    def _cpu_compute(self, l, r, ctx):
        import pyarrow.compute as pc
        return pc.bit_wise_xor(l, r)


class BitwiseNot(UnaryExpression):
    def _compute(self, data, ctx, valid):
        return ~data

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.bit_wise_not(self.child.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"~{self.child.pretty()}"


class BitwiseCount(UnaryExpression):
    """bit_count: number of set bits (negative inputs counted in
    two's-complement, per Spark)."""

    @property
    def dtype(self) -> DataType:
        from ..types import IntegerT
        return IntegerT

    def _compute(self, data, ctx, valid):
        w = data.dtype.itemsize * 8
        u = data.astype({8: jnp.uint8, 16: jnp.uint16,
                         32: jnp.uint32, 64: jnp.uint64}[w])
        return jax.lax.population_count(u).astype(jnp.int32)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import numpy as np
        v = self.child.eval_cpu(table, ctx)
        if isinstance(v, pa.ChunkedArray):
            v = v.combine_chunks()
        if not isinstance(v, pa.Array):
            if v is None:
                return None
            return int(bin(v & (2 ** 64 - 1) if v < 0 else v).count("1"))
        width = v.type.bit_width
        npv = v.fill_null(0).to_numpy(zero_copy_only=False)
        u = np.asarray(npv).astype(f"int{width}").astype(f"uint{width}")
        counts = np.array([bin(int(x)).count("1") for x in u], dtype=np.int32)
        mask = np.asarray(v.is_null())
        return pa.array(counts, mask=mask)


class _ShiftBase(BinaryExpression):
    symbol = "?"

    @property
    def dtype(self) -> DataType:
        return self.left.dtype

    def pretty(self) -> str:
        return f"({self.left.pretty()} {self.symbol} {self.right.pretty()})"

    def _shift(self, ld, dist):
        raise NotImplementedError

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from ..columnar.vector import row_mask
        l = self.left.eval_tpu(batch, ctx)
        r = self.right.eval_tpu(batch, ctx)
        cap = batch.capacity
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        valid = combine_validity(cap, lv, rv, row_mask(batch.num_rows, cap))
        width = jnp.asarray(ld).dtype.itemsize * 8
        dist = (rd.astype(jnp.int32) & (width - 1))  # Java shift-mod semantics
        data = self._shift(ld, dist)
        return make_column(self.dtype, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import numpy as np
        import pyarrow as pa
        l = self.left.eval_cpu(table, ctx)
        r = self.right.eval_cpu(table, ctx)
        l_arr = isinstance(l, (pa.Array, pa.ChunkedArray))
        r_arr = isinstance(r, (pa.Array, pa.ChunkedArray))
        if not l_arr and not r_arr:
            if l is None or r is None:
                return None
            ln = np.array([l])
            rn = np.array([r])
            out = self._np_shift(ln, rn)
            return out[0].item()
        if isinstance(l, pa.ChunkedArray):
            l = l.combine_chunks()
        if isinstance(r, pa.ChunkedArray):
            r = r.combine_chunks()
        n = len(l) if l_arr else len(r)
        lm = np.asarray(l.is_null()) if l_arr else np.zeros(n, bool)
        rm = np.asarray(r.is_null()) if r_arr else np.zeros(n, bool)
        lt = np.dtype(l.type.to_pandas_dtype()) if l_arr else np.int64
        ln = l.fill_null(0).to_numpy(zero_copy_only=False).astype(lt) \
            if l_arr else np.full(n, l, dtype=np.int64)
        rn = r.fill_null(0).to_numpy(zero_copy_only=False).astype(np.int64) \
            if r_arr else np.full(n, r, dtype=np.int64)
        mask = lm | rm
        out = self._np_shift(np.asarray(ln), np.asarray(rn))
        return pa.array(out, mask=mask)

    def _np_shift(self, ln, rn):
        raise NotImplementedError


class ShiftLeft(_ShiftBase):
    symbol = "<<"

    def _shift(self, ld, dist):
        return ld << dist.astype(ld.dtype)

    def _np_shift(self, ln, rn):
        import numpy as np
        width = ln.dtype.itemsize * 8
        return ln << (rn.astype(np.int64) & (width - 1)).astype(ln.dtype)


class ShiftRight(_ShiftBase):
    """Arithmetic (sign-extending) right shift."""
    symbol = ">>"

    def _shift(self, ld, dist):
        return ld >> dist.astype(ld.dtype)

    def _np_shift(self, ln, rn):
        import numpy as np
        width = ln.dtype.itemsize * 8
        return ln >> (rn.astype(np.int64) & (width - 1)).astype(ln.dtype)


class ShiftRightUnsigned(_ShiftBase):
    """Logical (zero-filling) right shift (Java >>>)."""
    symbol = ">>>"

    def _shift(self, ld, dist):
        width = ld.dtype.itemsize * 8
        u = ld.astype({8: jnp.uint8, 16: jnp.uint16,
                       32: jnp.uint32, 64: jnp.uint64}[width])
        return (u >> dist.astype(u.dtype)).astype(ld.dtype)

    def _np_shift(self, ln, rn):
        import numpy as np
        width = ln.dtype.itemsize * 8
        u = ln.astype(f"uint{width}")
        d = (rn.astype(np.int64) & (width - 1)).astype(u.dtype)
        return (u >> d).astype(ln.dtype)
