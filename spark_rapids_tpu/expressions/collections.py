"""Collection (array/map) expressions + higher-order functions.

TPU re-design of the reference collection layer
(/root/reference/sql-plugin/src/main/scala/org/apache/spark/sql/rapids/
collectionOperations.scala and higherOrderFunctions.scala). cuDF has native LIST
kernels; here a list column is an int32 offsets vector plus a flattened child
vector (columnar/vector.py), and the device kernels are XLA *segment ops* over
the flat child:

  * per-row reductions (array_min/max/contains/exists/forall) use
    jax.ops.segment_{min,max,sum} with segment ids computed by a searchsorted
    over the offsets — one fused XLA program per op, no per-list loops.
  * element lookups (a[i], element_at) are flat gathers at offsets[:-1]+i.
  * lambdas (transform/filter/exists/forall) evaluate the lambda body over a
    pseudo-batch wrapping the FLAT child column, so the lambda runs as ordinary
    vectorized expression code over all elements of all rows at once; outer-row
    references are expanded by gathering the row value per element segment.

Set-like ops (sort_array, array_distinct/union/intersect/except, maps) are
host-assisted (arrow/python hop inside eval_tpu), the same status as the
ragged string kernels; the tagging layer prices this via host_assisted rules.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..types import (ArrayType, BooleanT, BooleanType, DataType, DoubleType,
                     FloatType, IntegerT, LongT, MapType, StringType, StructField,
                     StructType, is_fixed_width, to_arrow as type_to_arrow)
from ..columnar.vector import TpuColumnVector, TpuScalar, bucket_capacity, row_mask
from .base import (AttributeReference, BinaryExpression, Expression, Literal,
                   UnaryExpression, _DEFAULT_CTX, ExpressionError, combine_validity,
                   make_column)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _is_float(dt: DataType) -> bool:
    return isinstance(dt, (FloatType, DoubleType))


def _result_from_pylist(values, dtype, batch):
    """pylist → device column padded to the batch capacity."""
    import pyarrow as pa
    col = TpuColumnVector.from_arrow(pa.array(values, type=type_to_arrow(dtype)))
    if col.capacity < batch.capacity:
        from ..columnar.batch import _repad
        col = _repad(col, batch.capacity)
    return col


def _pylist_of(x, batch, ctx, expr, n):
    """Evaluate and materialize as a python list of length n (host hop)."""
    r = expr.eval_tpu(batch, ctx)
    if isinstance(r, TpuScalar):
        return [r.value] * n
    return r.to_pylist()


def _segments(col: TpuColumnVector):
    """Per-element segment (row) ids for a list column.

    Returns (seg_ids, in_data) where seg_ids[e] is the owning row of flat
    element e (clipped into range) and in_data marks real (non-padding)
    element slots. Pure XLA — searchsorted lowers to a vectorized binary
    search on TPU."""
    child = col.child
    elem_cap = child.capacity
    offsets = col.offsets
    pos = jnp.arange(elem_cap, dtype=jnp.int32)
    seg = jnp.searchsorted(offsets, pos, side="right").astype(jnp.int32) - 1
    in_data = pos < offsets[-1]
    return jnp.clip(seg, 0, col.capacity - 1), in_data


def _lengths(col: TpuColumnVector):
    return col.offsets[1:] - col.offsets[:-1]


def _segment_reduce(vals, seg, drop_mask, row_cap: int, kind: str):
    """Segment reduction dropping masked elements (drop_mask True == drop)."""
    seg_ids = jnp.where(drop_mask, row_cap, seg)
    fn = {"max": jax.ops.segment_max, "min": jax.ops.segment_min,
          "sum": jax.ops.segment_sum}[kind]
    out = fn(vals, seg_ids, num_segments=row_cap + 1)
    return out[:row_cap]


def _list_validity(col: TpuColumnVector, batch):
    v = col.validity
    return combine_validity(batch.capacity, v, row_mask(col.num_rows, batch.capacity))


def _eval_list(expr: Expression, batch, ctx):
    """Evaluate a child producing a list column; scalars are expanded."""
    r = expr.eval_tpu(batch, ctx)
    if isinstance(r, TpuScalar):
        return TpuColumnVector.from_scalar(r.value, r.dtype, batch.num_rows,
                                           capacity=batch.capacity)
    return r


# ---------------------------------------------------------------------------
# size / element access
# ---------------------------------------------------------------------------

class Size(UnaryExpression):
    """size(array|map). Reference GpuSize (collectionOperations.scala); Spark
    legacy semantics: size(null) == -1 unless spark.sql.legacy.sizeOfNull=false."""

    def __init__(self, child: Expression, legacy_size_of_null: bool = True):
        super().__init__(child)
        self.legacy = legacy_size_of_null

    @property
    def dtype(self) -> DataType:
        return IntegerT

    @property
    def nullable(self) -> bool:
        return not self.legacy

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        col = _eval_list(self.child, batch, ctx)
        if isinstance(self.child.dtype, MapType) or col.child is None:
            # maps live host-side
            vals = [None if v is None else len(v)
                    for v in col.to_pylist()]
            if self.legacy:
                vals = [-1 if v is None else v for v in vals]
            return _result_from_pylist(vals, IntegerT, batch)
        lens = _lengths(col).astype(jnp.int32)
        valid = _list_validity(col, batch)
        if self.legacy:
            data = jnp.where(valid if valid is not None else True, lens, -1)
            return make_column(IntegerT, data,
                               row_mask(col.num_rows, batch.capacity)
                               if col.num_rows < batch.capacity else None,
                               col.num_rows)
        return make_column(IntegerT, lens, valid, col.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        arr = self.child.eval_cpu(table, ctx)
        out = [(-1 if self.legacy else None) if v is None else len(v)
               for v in arr.to_pylist()]
        return pa.array(out, type=pa.int32())

    def pretty(self) -> str:
        return f"size({self.child.pretty()})"


class GetArrayItem(BinaryExpression):
    """a[i] — 0-based; out-of-bounds → null (ANSI: error). Also dispatches
    map[key] (Column.getItem can't know the type pre-resolution).
    Reference GpuGetArrayItem / GpuGetMapValue (complexTypeExtractors)."""

    @property
    def dtype(self) -> DataType:
        lt = self.left.dtype
        return lt.value_type if isinstance(lt, MapType) else lt.element_type

    def _as_map_value(self) -> "GetMapValue":
        return GetMapValue(self.left, self.right)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        if isinstance(self.left.dtype, MapType):
            return self._as_map_value().eval_tpu(batch, ctx)
        col = _eval_list(self.left, batch, ctx)
        idx = self.right.eval_tpu(batch, ctx)
        cap = batch.capacity
        if isinstance(idx, TpuScalar):
            if idx.value is None:
                return TpuScalar(self.dtype, None)
            idx_d = jnp.full((cap,), int(idx.value), jnp.int32)
            idx_v = None
        else:
            idx_d = idx.data.astype(jnp.int32)
            idx_v = idx.validity
        if not is_fixed_width(self.dtype) or col.child is None:
            lists = col.to_pylist()
            h_idx = np.asarray(idx_d)[:col.num_rows]
            h_iv = np.asarray(idx_v)[:col.num_rows] if idx_v is not None else None
            out = []
            for k, lst in enumerate(lists):
                if lst is None or (h_iv is not None and not h_iv[k]):
                    out.append(None)
                    continue
                i = int(h_idx[k])
                out.append(lst[i] if 0 <= i < len(lst) else None)
            return _result_from_pylist(out, self.dtype, batch)
        lens = _lengths(col)
        in_range = (idx_d >= 0) & (idx_d < lens)
        valid = combine_validity(cap, _list_validity(col, batch), idx_v, in_range)
        abs_idx = jnp.clip(col.offsets[:-1] + jnp.maximum(idx_d, 0), 0,
                           max(col.child.capacity - 1, 0))
        data = jnp.take(col.child.data, abs_idx)
        cv = col.child.validity
        if cv is not None:
            valid = combine_validity(cap, valid, jnp.take(cv, abs_idx))
        return make_column(self.dtype, data, valid, col.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        if isinstance(self.left.dtype, MapType):
            return self._as_map_value().eval_cpu(table, ctx)
        arr = self.left.eval_cpu(table, ctx)
        idx = self.right.eval_cpu(table, ctx)
        lists = arr.to_pylist()
        idxs = idx.to_pylist() if isinstance(idx, (pa.Array, pa.ChunkedArray)) \
            else [idx] * len(lists)
        out = []
        for lst, i in zip(lists, idxs):
            out.append(None if lst is None or i is None or not (0 <= i < len(lst))
                       else lst[i])
        return pa.array(out, type=type_to_arrow(self.dtype))

    def pretty(self) -> str:
        return f"{self.left.pretty()}[{self.right.pretty()}]"


class ElementAt(BinaryExpression):
    """element_at(array, i) 1-based (negative from end; 0 errors) or
    element_at(map, key). Reference GpuElementAt."""

    @property
    def dtype(self) -> DataType:
        lt = self.left.dtype
        return lt.value_type if isinstance(lt, MapType) else lt.element_type

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        lt = self.left.dtype
        if isinstance(lt, MapType):
            return self._map_eval(batch, ctx)
        col = _eval_list(self.left, batch, ctx)
        idx = self.right.eval_tpu(batch, ctx)
        cap = batch.capacity
        # the 0-index error only fires for rows where the array itself is
        # non-null (Spark nullSafeEval short-circuits null inputs)
        arr_valid = _list_validity(col, batch)
        if isinstance(idx, TpuScalar):
            if idx.value is None:
                return TpuScalar(self.dtype, None)
            if int(idx.value) == 0:
                any_valid = bool(jnp.any(arr_valid)) if arr_valid is not None \
                    else col.num_rows > 0
                if any_valid:
                    raise ExpressionError("SQL array indices start at 1")
            idx_d = jnp.full((cap,), int(idx.value), jnp.int64)
            idx_v = None
        else:
            idx_d = idx.data.astype(jnp.int64)
            idx_v = idx.validity
            rowv = combine_validity(cap, idx_v, arr_valid,
                                    row_mask(col.num_rows, cap))
            zero = (idx_d == 0) & (rowv if rowv is not None else True)
            if bool(jnp.any(zero)):  # host sync: error semantics need a decision
                raise ExpressionError("SQL array indices start at 1")
        if not is_fixed_width(self.dtype) or col.child is None:
            lists = col.to_pylist()
            h_idx = np.asarray(idx_d)[:col.num_rows]
            h_iv = np.asarray(idx_v)[:col.num_rows] if idx_v is not None else None
            out = []
            for k, lst in enumerate(lists):
                if lst is None or (h_iv is not None and not h_iv[k]):
                    out.append(None)
                    continue
                i = int(h_idx[k])
                if i > 0:
                    out.append(lst[i - 1] if i <= len(lst) else None)
                else:
                    out.append(lst[i] if -i <= len(lst) else None)
            return _result_from_pylist(out, self.dtype, batch)
        lens = _lengths(col).astype(jnp.int64)
        pos0 = jnp.where(idx_d > 0, idx_d - 1, lens + idx_d)
        in_range = (pos0 >= 0) & (pos0 < lens)
        valid = combine_validity(cap, _list_validity(col, batch), idx_v, in_range)
        abs_idx = jnp.clip(col.offsets[:-1] + jnp.maximum(pos0, 0).astype(jnp.int32),
                           0, max(col.child.capacity - 1, 0))
        data = jnp.take(col.child.data, abs_idx)
        cv = col.child.validity
        if cv is not None:
            valid = combine_validity(cap, valid, jnp.take(cv, abs_idx))
        return make_column(self.dtype, data, valid, col.num_rows)

    def _map_eval(self, batch, ctx):
        maps = _pylist_of(None, batch, ctx, self.left, batch.num_rows)
        keys = _pylist_of(None, batch, ctx, self.right, batch.num_rows)
        out = []
        for m, k in zip(maps, keys):
            if m is None or k is None:
                out.append(None)
            else:
                d = dict(m) if not isinstance(m, dict) else m
                out.append(d.get(k))
        return _result_from_pylist(out, self.dtype, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        arr = self.left.eval_cpu(table, ctx)
        idx = self.right.eval_cpu(table, ctx)
        lists = arr.to_pylist()
        idxs = idx.to_pylist() if isinstance(idx, (pa.Array, pa.ChunkedArray)) \
            else [idx] * len(lists)
        out = []
        is_map = isinstance(self.left.dtype, MapType)
        for lst, i in zip(lists, idxs):
            if lst is None or i is None:
                out.append(None)
            elif is_map:
                d = dict(lst) if not isinstance(lst, dict) else lst
                out.append(d.get(i))
            elif i == 0:
                raise ExpressionError("SQL array indices start at 1")
            elif i > 0:
                out.append(lst[i - 1] if i <= len(lst) else None)
            else:
                out.append(lst[i] if -i <= len(lst) else None)
        return pa.array(out, type=type_to_arrow(self.dtype))

    def pretty(self) -> str:
        return f"element_at({self.left.pretty()}, {self.right.pretty()})"


# ---------------------------------------------------------------------------
# membership / reductions
# ---------------------------------------------------------------------------

class ArrayContains(BinaryExpression):
    """array_contains(arr, value). Null semantics: null arr or null value → null;
    no match but null element present → null (reference GpuArrayContains)."""

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        col = _eval_list(self.left, batch, ctx)
        val = self.right.eval_tpu(batch, ctx)
        elem_t = self.left.dtype.element_type
        cap = batch.capacity
        if (not is_fixed_width(elem_t) or col.child is None
                or not isinstance(val, TpuScalar)):
            return self._host(batch, ctx, col, val)
        if val.value is None:
            return TpuScalar(BooleanT, None)
        seg, in_data = _segments(col)
        elem = col.child.data
        target = jnp.asarray(val.value, elem.dtype)
        if _is_float(elem_t) and isinstance(val.value, float) and math.isnan(val.value):
            match = jnp.isnan(elem)
        else:
            match = elem == target
        ev = col.child.validity
        evalid = in_data if ev is None else (in_data & ev)
        row_cap = col.capacity
        any_match = _segment_reduce(
            (match & evalid).astype(jnp.int32), seg, ~in_data, row_cap, "max") > 0
        any_null = _segment_reduce(
            ((~evalid) & in_data).astype(jnp.int32), seg, ~in_data, row_cap, "max") > 0
        valid = combine_validity(cap, _list_validity(col, batch),
                                 ~((~any_match) & any_null))
        return make_column(BooleanT, any_match, valid, col.num_rows)

    def _host(self, batch, ctx, col, val):
        lists = col.to_pylist()
        vals = [val.value] * len(lists) if isinstance(val, TpuScalar) \
            else val.to_pylist()
        out = [_contains_one(l, v) for l, v in zip(lists, vals)]
        return _result_from_pylist(out, BooleanT, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        arr = self.left.eval_cpu(table, ctx)
        v = self.right.eval_cpu(table, ctx)
        lists = arr.to_pylist()
        vals = v.to_pylist() if isinstance(v, (pa.Array, pa.ChunkedArray)) \
            else [v] * len(lists)
        return pa.array([_contains_one(l, x) for l, x in zip(lists, vals)],
                        type=pa.bool_())

    def pretty(self) -> str:
        return f"array_contains({self.left.pretty()}, {self.right.pretty()})"


def _eq_value(a, b):
    if isinstance(a, float) and isinstance(b, float) \
            and math.isnan(a) and math.isnan(b):
        return True
    return a == b


def _contains_one(lst, v):
    if lst is None or v is None:
        return None
    found = any(e is not None and _eq_value(e, v) for e in lst)
    if found:
        return True
    return None if any(e is None for e in lst) else False


class ArrayPosition(BinaryExpression):
    """array_position(arr, val): 1-based first match, 0 when absent."""

    @property
    def dtype(self) -> DataType:
        return LongT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        col = _eval_list(self.left, batch, ctx)
        val = self.right.eval_tpu(batch, ctx)
        elem_t = self.left.dtype.element_type
        cap = batch.capacity
        if (not is_fixed_width(elem_t) or col.child is None
                or col.host_data is not None or not isinstance(val, TpuScalar)):
            lists = col.to_pylist()
            vals = [val.value] * len(lists) if isinstance(val, TpuScalar) \
                else val.to_pylist()
            return _result_from_pylist(
                [_position_one(l, v) for l, v in zip(lists, vals)], LongT, batch)
        if val.value is None:
            return TpuScalar(LongT, None)
        seg, in_data = _segments(col)
        elem = col.child.data
        if _is_float(elem_t) and isinstance(val.value, float) \
                and math.isnan(val.value):
            match = jnp.isnan(elem)
        else:
            match = elem == jnp.asarray(val.value, elem.dtype)
        ev = col.child.validity
        hit = match & in_data & (ev if ev is not None else True)
        pos_in_row = (jnp.arange(col.child.capacity, dtype=jnp.int32)
                      - col.offsets[seg])
        big = jnp.int32(2**31 - 1)
        first = jnp.full((col.capacity,), big, jnp.int32).at[
            jnp.where(in_data, seg, col.capacity)].min(
            jnp.where(hit, pos_in_row, big), mode="drop")
        data = jnp.where(first == big, 0, first + 1).astype(jnp.int64)
        valid = _list_validity(col, batch)
        return make_column(LongT, data, valid, col.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        lists = self.left.eval_cpu(table, ctx).to_pylist()
        v = self.right.eval_cpu(table, ctx)
        vals = v.to_pylist() if isinstance(v, (pa.Array, pa.ChunkedArray)) \
            else [v] * len(lists)
        return pa.array([_position_one(l, x) for l, x in zip(lists, vals)],
                        type=pa.int64())

    def pretty(self) -> str:
        return f"array_position({self.left.pretty()}, {self.right.pretty()})"


def _position_one(lst, v):
    if lst is None or v is None:
        return None
    for i, e in enumerate(lst):
        if e is not None and _eq_value(e, v):
            return i + 1
    return 0


class _ArrayMinMax(UnaryExpression):
    _kind = "min"

    @property
    def dtype(self) -> DataType:
        return self.child.dtype.element_type

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        col = _eval_list(self.child, batch, ctx)
        elem_t = self.dtype
        if not is_fixed_width(elem_t) or col.child is None:
            lists = col.to_pylist()
            return _result_from_pylist([_minmax_one(l, self._kind) for l in lists],
                                       elem_t, batch)
        seg, in_data = _segments(col)
        ev = col.child.validity
        evalid = in_data if ev is None else (in_data & ev)
        vals = col.child.data
        row_cap = col.capacity
        cap = batch.capacity
        if _is_float(elem_t):
            nan = jnp.isnan(vals)
            sent = jnp.inf if self._kind == "min" else -jnp.inf
            clean = jnp.where(nan, sent, vals)
            red = _segment_reduce(clean, seg, ~evalid, row_cap, self._kind)
            nonnan = _segment_reduce(((~nan) & evalid).astype(jnp.int32), seg,
                                     ~in_data, row_cap, "sum")
            has_nan = _segment_reduce((nan & evalid).astype(jnp.int32), seg,
                                      ~in_data, row_cap, "sum") > 0
            count = _segment_reduce(evalid.astype(jnp.int32), seg, ~in_data,
                                    row_cap, "sum")
            if self._kind == "max":
                data = jnp.where(has_nan, jnp.nan, red)
            else:
                data = jnp.where(nonnan > 0, red, jnp.nan)
            valid = combine_validity(cap, _list_validity(col, batch), count > 0)
            return make_column(elem_t, data, valid, col.num_rows)
        red = _segment_reduce(vals, seg, ~evalid, row_cap, self._kind)
        count = _segment_reduce(evalid.astype(jnp.int32), seg, ~in_data,
                                row_cap, "sum")
        valid = combine_validity(cap, _list_validity(col, batch), count > 0)
        return make_column(elem_t, red, valid, col.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        lists = self.child.eval_cpu(table, ctx).to_pylist()
        return pa.array([_minmax_one(l, self._kind) for l in lists],
                        type=type_to_arrow(self.dtype))

    def pretty(self) -> str:
        return f"array_{self._kind}({self.child.pretty()})"


def _minmax_one(lst, kind):
    if lst is None:
        return None
    vals = [e for e in lst if e is not None]
    if not vals:
        return None
    floats = [v for v in vals if isinstance(v, float)]
    nans = [v for v in floats if math.isnan(v)]
    if nans:
        clean = [v for v in vals if not (isinstance(v, float) and math.isnan(v))]
        if kind == "max":
            return float("nan")
        return min(clean) if clean else float("nan")
    return min(vals) if kind == "min" else max(vals)


class ArrayMin(_ArrayMinMax):
    _kind = "min"


class ArrayMax(_ArrayMinMax):
    _kind = "max"


# ---------------------------------------------------------------------------
# constructors / shape ops
# ---------------------------------------------------------------------------

def _common_elem_type(types: Sequence[DataType]) -> DataType:
    """Least-common type over array() arguments (Spark's coerceArrayType:
    numeric widening; otherwise the first non-null type)."""
    from ..types import NullType, NumericType, numeric_promote
    cur = types[0]
    for t in types[1:]:
        if t == cur:
            continue
        if isinstance(cur, NullType):
            cur = t
            continue
        if isinstance(t, NullType):
            continue
        if isinstance(cur, NumericType) and isinstance(t, NumericType):
            cur = numeric_promote(cur, t)
            continue
        raise ExpressionError(
            f"cannot resolve array() due to data type mismatch: {cur} vs {t}")
    return cur


class CreateArray(Expression):
    """array(e1, e2, ...). Device path interleaves the evaluated child columns
    into the flat element vector (reference GpuCreateArray)."""

    def __init__(self, children: Sequence[Expression]):
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        if not self.children:
            from ..types import NullT
            return ArrayType(NullT, True)
        elem = _common_elem_type([c.dtype for c in self.children])
        return ArrayType(elem, any(c.nullable for c in self.children))

    @property
    def nullable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        elem_t = self.dtype.element_type
        k = len(self.children)
        cap = batch.capacity
        n = batch.num_rows
        if not is_fixed_width(elem_t) or k == 0:
            cols = [_pylist_of(None, batch, ctx, c, n) for c in self.children]
            out = [[col[i] for col in cols] for i in range(n)]
            return _result_from_pylist(out, self.dtype, batch)
        datas, valids = [], []
        for c in self.children:
            r = c.eval_tpu(batch, ctx)
            if isinstance(r, TpuScalar):
                if r.value is None:
                    datas.append(jnp.zeros((cap,), elem_t.np_dtype))
                    valids.append(jnp.zeros((cap,), jnp.bool_))
                else:
                    datas.append(jnp.full((cap,), r.value, elem_t.np_dtype))
                    valids.append(jnp.ones((cap,), jnp.bool_))
            else:
                datas.append(r.data.astype(elem_t.np_dtype))
                valids.append(r.validity if r.validity is not None
                              else jnp.ones((cap,), jnp.bool_))
        flat = jnp.stack(datas, axis=1).reshape(-1)       # (cap*k,)
        flat_v = jnp.stack(valids, axis=1).reshape(-1)
        elem_mask = jnp.repeat(row_mask(n, cap), k)
        flat_v = flat_v & elem_mask
        offsets = (jnp.minimum(jnp.arange(cap + 1, dtype=jnp.int32), n) * k)
        child = TpuColumnVector(elem_t, flat, flat_v, n * k)
        return TpuColumnVector(self.dtype, flat, None, n, offsets=offsets,
                               child=child)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        n = table.num_rows
        cols = []
        for c in self.children:
            r = c.eval_cpu(table, ctx)
            cols.append(r.to_pylist() if isinstance(r, (pa.Array, pa.ChunkedArray))
                        else [r] * n)
        out = [[col[i] for col in cols] for i in range(n)]
        return pa.array(out, type=type_to_arrow(self.dtype))

    def pretty(self) -> str:
        return f"array({', '.join(c.pretty() for c in self.children)})"


class _HostListOp(Expression):
    """Base for host-assisted list ops: children evaluated, pylists combined."""

    def _combine(self, *lists_per_child):
        raise NotImplementedError

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        vals = [c.eval_tpu(batch, ctx) for c in self.children]
        return self._host_from_vals(vals, batch)

    def _host_from_vals(self, vals, batch):
        """Host combine over ALREADY-evaluated child values — device-path
        guards fall back here so child expressions never run twice."""
        n = batch.num_rows
        cols = [[v.value] * n if isinstance(v, TpuScalar) else v.to_pylist()[:n]
                for v in vals]
        out = [self._combine(*[col[i] for col in cols]) for i in range(n)]
        return _result_from_pylist(out, self.dtype, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        n = table.num_rows
        cols = []
        for c in self.children:
            r = c.eval_cpu(table, ctx)
            cols.append(r.to_pylist() if isinstance(r, (pa.Array, pa.ChunkedArray))
                        else [r] * n)
        out = [self._combine(*[col[i] for col in cols]) for i in range(n)]
        return pa.array(out, type=type_to_arrow(self.dtype))

    def pretty(self) -> str:
        name = type(self).__name__
        return f"{name}({', '.join(c.pretty() for c in self.children)})"


# ---------------------------------------------------------------------------
# device list machinery (shares the ragged gather_plan with kernels/strings:
# a list column is offsets + a flat fixed-width child, exactly a string column
# with wider "bytes" — reference cuDF LIST kernels, collectionOperations.scala)
# ---------------------------------------------------------------------------

def _fixed_list(col) -> bool:
    """List column whose flat child is fixed-width device-resident data."""
    return (isinstance(col, TpuColumnVector) and col.child is not None
            and col.host_data is None and col.child.host_data is None
            and col.child.child is None and is_fixed_width(col.child.dtype))


def _list_from_plan(col, starts, lengths, out_cap, validity, num_rows,
                    stride=None, dtype=None):
    """Ragged gather over a list column's flat child → new list column.
    One scalar D→H sync fixes the new child's logical element count."""
    from ..kernels.strings import gather_plan
    child = col.child
    src, in_range, new_offs = gather_plan(starts, lengths, out_cap,
                                          stride=stride)
    ecap = max(int(child.capacity), 1)
    src_c = jnp.clip(src, 0, ecap - 1)
    data = jnp.where(in_range, child.data[src_c],
                     jnp.zeros((), child.data.dtype))
    cv = None
    if child.validity is not None:
        cv = jnp.where(in_range, child.validity[src_c], False)
    n_elems = int(new_offs[num_rows])
    new_child = TpuColumnVector(child.dtype, data, cv, n_elems)
    return TpuColumnVector(dtype or col.dtype, data, validity, num_rows,
                           offsets=new_offs, child=new_child)


def _int_operand(x, cap, dtype=jnp.int32):
    """Evaluated int operand → (int array over capacity, validity) or
    (None, None) when it is a null scalar."""
    if isinstance(x, TpuScalar):
        if x.value is None:
            return None, None
        return jnp.full((cap,), int(x.value), dtype), None
    return x.data.astype(dtype), x.validity


def _all_null_list(dtype, batch):
    return TpuColumnVector.from_scalar(None, dtype, batch.num_rows,
                                       capacity=batch.capacity)


def _expand_list(v, batch):
    """Already-evaluated list value → column (scalars expand, no re-eval)."""
    if isinstance(v, TpuScalar):
        return TpuColumnVector.from_scalar(v.value, v.dtype, batch.num_rows,
                                           capacity=batch.capacity)
    return v


def _elem_sort_keys(child: TpuColumnVector):
    """Total-order integer sort keys for fixed-width element data. Floats use
    the IEEE bit trick with -0.0→0.0 and canonical-NaN normalization, giving
    Spark's ordering (NaN greatest) AND SQL equality (NaN==NaN, -0.0==0.0) as
    plain integer comparison — one key serves sort, dedup, and membership."""
    v = child.data
    if _is_float(child.dtype):
        v = jnp.where(v == 0, jnp.zeros((), v.dtype), v)
        v = jnp.where(jnp.isnan(v), jnp.full((), jnp.nan, v.dtype), v)
        ity = jnp.int32 if v.dtype == jnp.float32 else jnp.int64
        bits = jax.lax.bitcast_convert_type(v, ity)
        imin = jnp.iinfo(ity).min
        key = jnp.where(bits >= 0, bits, ~bits + imin)
        return key
    if isinstance(child.dtype, BooleanType):
        return v.astype(jnp.int32)
    return v


def _ragged_sort_perm(col, ascending: bool):
    """Permutation that sorts each row's elements in place (rows keep their
    offset ranges; ascending puts nulls first, descending last — Spark
    sort_array). Works because the flat layout is already segment-contiguous:
    a stable sort with segment as primary key leaves row boundaries fixed."""
    child = col.child
    seg, in_data = _segments(col)
    cap = col.capacity
    key = _elem_sort_keys(child)
    cv = child.validity
    valid_e = cv if cv is not None else jnp.ones((child.capacity,), jnp.bool_)
    key = jnp.where(valid_e, key, 0)
    if ascending:
        nrank = jnp.where(valid_e, 0, -1)
    else:
        nrank = jnp.where(valid_e, 0, 1)
        key = ~key
    seg_key = jnp.where(in_data, seg, cap)
    return jnp.lexsort((key, nrank, seg_key))


def _distinct_keep(col):
    """bool[elem_cap]: element is the first occurrence of its value within its
    row (nulls form one group; key normalization makes NaN/-0.0 collapse).
    Original order is preserved by ranking candidates by position."""
    child = col.child
    seg, in_data = _segments(col)
    cap = col.capacity
    ecap = int(child.capacity)
    key = _elem_sort_keys(child)
    cv = child.validity
    valid_e = cv if cv is not None else jnp.ones((ecap,), jnp.bool_)
    key = jnp.where(valid_e, key, 0)
    nullg = (~valid_e).astype(jnp.int32)
    seg_key = jnp.where(in_data, seg, cap)
    pos = jnp.arange(ecap, dtype=jnp.int32)
    perm = jnp.lexsort((pos, key, nullg, seg_key))
    s_seg, s_key, s_null = seg_key[perm], key[perm], nullg[perm]
    prev_ne = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                               (s_seg[1:] != s_seg[:-1])
                               | (s_key[1:] != s_key[:-1])
                               | (s_null[1:] != s_null[:-1])])
    keep = jnp.zeros((ecap,), jnp.bool_).at[perm].set(prev_ne)
    return keep & in_data


def _compact_list(col, keep, validity, num_rows, dtype):
    """Rebuild a list column keeping flagged elements in original order."""
    child = col.child
    ecap = int(child.capacity)
    seg, in_data = _segments(col)
    cap = col.capacity
    keep_i = keep.astype(jnp.int32)
    new_lens = jnp.zeros((cap,), jnp.int32).at[
        jnp.where(in_data, seg, cap)].add(keep_i, mode="drop")
    new_offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(new_lens, dtype=jnp.int32)])
    out_pos = jnp.cumsum(keep_i) - keep_i
    idx = jnp.where(keep, out_pos, ecap)
    data = jnp.zeros((ecap,), child.data.dtype).at[idx].set(
        child.data, mode="drop")
    cv = None
    if child.validity is not None:
        cv = jnp.zeros((ecap,), jnp.bool_).at[idx].set(
            child.validity, mode="drop")
    n_elems = int(new_offs[num_rows])
    new_child = TpuColumnVector(child.dtype, data, cv, n_elems)
    return TpuColumnVector(dtype, data, validity, num_rows,
                           offsets=new_offs, child=new_child)


def _member_in(a_col, b_col):
    """bool[a_elem_cap]: a's element value appears among b's NON-NULL elements
    of the same row. Vectorized per-row binary search over b sorted in place
    (nulls ranked last so each row's search range is its non-null prefix)."""
    a_child, b_child = a_col.child, b_col.child
    cap = a_col.capacity
    # sort b ascending with nulls ranked last, so each row's search range is
    # its non-null prefix
    b_valid = b_child.validity if b_child.validity is not None else \
        jnp.ones((b_child.capacity,), jnp.bool_)
    b_key = jnp.where(b_valid, _elem_sort_keys(b_child), 0)
    b_seg, b_in = _segments(b_col)
    nrank = jnp.where(b_valid, 0, 1)  # nulls last within each row
    perm = jnp.lexsort((b_key, nrank, jnp.where(b_in, b_seg, b_col.capacity)))
    sorted_bkey = b_key[perm]
    b_nulls = jnp.zeros((cap,), jnp.int32).at[
        jnp.where(b_in, b_seg, cap)].add((~b_valid).astype(jnp.int32),
                                         mode="drop")
    a_key = _elem_sort_keys(a_child)
    a_seg, a_in = _segments(a_col)
    a_seg_c = jnp.clip(a_seg, 0, cap - 1)
    lo = b_col.offsets[:-1][a_seg_c].astype(jnp.int32)
    hi = (b_col.offsets[1:][a_seg_c] - b_nulls[a_seg_c]).astype(jnp.int32)
    hi0 = hi
    ecap_b = max(int(b_child.capacity), 1)
    steps = max(int(ecap_b).bit_length(), 1)
    for _ in range(steps):
        mid = (lo + hi) // 2
        active = lo < hi
        go = sorted_bkey[jnp.clip(mid, 0, ecap_b - 1)] < a_key
        lo, hi = (jnp.where(active & go, mid + 1, lo),
                  jnp.where(active & ~go, mid, hi))
    found = (lo < hi0) & (sorted_bkey[jnp.clip(lo, 0, ecap_b - 1)] == a_key)
    return found & a_in


def _seg_any(flags, col):
    """Per-row OR of an element-level bool vector."""
    seg, in_data = _segments(col)
    cap = col.capacity
    cnt = jnp.zeros((cap,), jnp.int32).at[
        jnp.where(in_data, seg, cap)].add(flags.astype(jnp.int32), mode="drop")
    return cnt > 0


def _intersect_except_eval(op, batch, ctx, invert: bool):
    """Shared device body of array_intersect (invert=False: keep a-elements
    present in b) and array_except (invert=True: keep those absent). Null
    element kept when b's null-presence matches the same polarity."""
    vals = [c.eval_tpu(batch, ctx) for c in op.children]
    a = _expand_list(vals[0], batch)
    b = _expand_list(vals[1], batch)
    if not (_fixed_list(a) and _fixed_list(b)
            and a.child.data.dtype == b.child.data.dtype):
        return op._host_from_vals(vals, batch)
    cap = batch.capacity
    a_valid_e = a.child.validity if a.child.validity is not None else \
        jnp.ones((a.child.capacity,), jnp.bool_)
    b_valid_e = b.child.validity if b.child.validity is not None else \
        jnp.ones((b.child.capacity,), jnp.bool_)
    member = _member_in(a, b)
    b_has_null = _seg_any(~b_valid_e, b)
    a_seg, _ = _segments(a)
    a_seg_c = jnp.clip(a_seg, 0, cap - 1)
    keep = _distinct_keep(a) & jnp.where(
        a_valid_e, member ^ invert, b_has_null[a_seg_c] ^ invert)
    valid = combine_validity(cap, _list_validity(a, batch),
                             _list_validity(b, batch))
    return _compact_list(a, keep, valid, batch.num_rows, op.dtype)


def _concat_list_cols(cols, batch, dtype):
    """Device row-wise concatenation of K list columns, or None when any
    column lacks the fixed-width device layout."""
    if not cols or not all(_fixed_list(c) for c in cols) or \
            len({c.child.data.dtype for c in cols}) != 1:
        return None
    cap = batch.capacity
    part_lens = [jnp.maximum(_lengths(c), 0) for c in cols]
    total = sum(part_lens)
    new_offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(total, dtype=jnp.int32)])
    out_cap = bucket_capacity(sum(max(int(c.child.capacity), 1)
                                  for c in cols))
    j = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offs[1:], j, side="right").astype(jnp.int32)
    row_c = jnp.clip(row, 0, cap - 1)
    pos = j - new_offs[row_c]
    in_range = j < new_offs[cap]
    dt = cols[0].child.data.dtype
    data = jnp.zeros((out_cap,), dt)
    eval_out = jnp.ones((out_cap,), jnp.bool_)
    has_ev = any(c.child.validity is not None for c in cols)
    cum = jnp.zeros((cap,), jnp.int32)
    validity = None
    for c, ln in zip(cols, part_lens):
        sel = in_range & (pos >= cum[row_c]) & (pos < cum[row_c] + ln[row_c])
        src = jnp.clip(c.offsets[:-1][row_c] + pos - cum[row_c], 0,
                       max(int(c.child.capacity), 1) - 1)
        data = jnp.where(sel, c.child.data[src], data)
        if has_ev:
            cv = c.child.validity if c.child.validity is not None else \
                jnp.ones((int(c.child.capacity),), jnp.bool_)
            eval_out = jnp.where(sel, cv[src], eval_out)
        cum = cum + ln
        validity = combine_validity(cap, validity, c.validity)
    valid = combine_validity(cap, validity, row_mask(batch.num_rows, cap))
    n_elems = int(new_offs[batch.num_rows])
    new_child = TpuColumnVector(cols[0].child.dtype, data,
                                eval_out if has_ev else None, n_elems)
    return TpuColumnVector(dtype, data, valid, batch.num_rows,
                           offsets=new_offs, child=new_child)


class SortArray(_HostListOp):
    """sort_array(arr, asc): nulls first when ascending, last when descending
    (Spark semantics; reference GpuSortArray)."""

    def __init__(self, child: Expression, ascending: Expression = None):
        asc = ascending if ascending is not None else Literal(True)
        self.children = (child, asc)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _combine(self, lst, asc):
        if lst is None or asc is None:
            return None
        non_null = sorted([e for e in lst if e is not None],
                          key=_sort_key, reverse=not asc)
        nulls = [None] * (len(lst) - len(non_null))
        return nulls + non_null if asc else non_null + nulls

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        asc_e = self.children[1]
        asc = asc_e.value if isinstance(asc_e, Literal) else None
        vals = [c.eval_tpu(batch, ctx) for c in self.children]
        col = _expand_list(vals[0], batch)
        if asc is None or not _fixed_list(col):
            return self._host_from_vals(vals, batch)
        child = col.child
        perm = _ragged_sort_perm(col, bool(asc))
        data = child.data[perm]
        cv = child.validity[perm] if child.validity is not None else None
        new_child = TpuColumnVector(child.dtype, data, cv, child.num_rows)
        return TpuColumnVector(self.dtype, data, col.validity, col.num_rows,
                               offsets=col.offsets, child=new_child)


def _sort_key(v):
    # NaN sorts greatest (Spark ordering)
    if isinstance(v, float) and math.isnan(v):
        return (1, 0.0)
    if isinstance(v, (int, float)):
        return (0, v)
    return (0, v)


class ArrayDistinct(_HostListOp):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _combine(self, lst):
        if lst is None:
            return None
        return _dedupe(lst, keep_null=True)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        vals = [c.eval_tpu(batch, ctx) for c in self.children]
        col = _expand_list(vals[0], batch)
        if not _fixed_list(col):
            return self._host_from_vals(vals, batch)
        keep = _distinct_keep(col)
        return _compact_list(col, keep, col.validity, col.num_rows, self.dtype)


def _canon(e):
    if isinstance(e, float) and math.isnan(e):
        return "__nan__"
    return e


def _dedupe(lst, keep_null=True):
    seen, out, saw_null = set(), [], False
    for e in lst:
        if e is None:
            if keep_null and not saw_null:
                saw_null = True
                out.append(None)
            continue
        k = _canon(e)
        if k not in seen:
            seen.add(k)
            out.append(e)
    return out


class ArrayUnion(_HostListOp):
    def __init__(self, l: Expression, r: Expression):
        self.children = (l, r)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _combine(self, a, b):
        if a is None or b is None:
            return None
        return _dedupe(list(a) + list(b), keep_null=True)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        vals = [c.eval_tpu(batch, ctx) for c in self.children]
        cols = [_expand_list(v, batch) for v in vals]
        cat = _concat_list_cols(cols, batch, self.dtype)
        if cat is None:
            return self._host_from_vals(vals, batch)
        keep = _distinct_keep(cat)
        return _compact_list(cat, keep, cat.validity, batch.num_rows,
                             self.dtype)


class ArrayIntersect(_HostListOp):
    def __init__(self, l: Expression, r: Expression):
        self.children = (l, r)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _combine(self, a, b):
        if a is None or b is None:
            return None
        bset = {_canon(e) for e in b if e is not None}
        b_null = any(e is None for e in b)
        out = []
        for e in _dedupe(a, keep_null=True):
            if e is None:
                if b_null:
                    out.append(None)
            elif _canon(e) in bset:
                out.append(e)
        return out

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        return _intersect_except_eval(self, batch, ctx, invert=False)


class ArrayExcept(_HostListOp):
    def __init__(self, l: Expression, r: Expression):
        self.children = (l, r)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _combine(self, a, b):
        if a is None or b is None:
            return None
        bset = {_canon(e) for e in b if e is not None}
        b_null = any(e is None for e in b)
        out = []
        for e in _dedupe(a, keep_null=True):
            if e is None:
                if not b_null:
                    out.append(None)
            elif _canon(e) not in bset:
                out.append(e)
        return out

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        return _intersect_except_eval(self, batch, ctx, invert=True)


class ArraysOverlap(_HostListOp):
    def __init__(self, l: Expression, r: Expression):
        self.children = (l, r)

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def _combine(self, a, b):
        if a is None or b is None:
            return None
        aset = {_canon(e) for e in a if e is not None}
        bset = {_canon(e) for e in b if e is not None}
        if aset & bset:
            return True
        if (any(e is None for e in a) and len(b) > 0) or \
                (any(e is None for e in b) and len(a) > 0):
            return None
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        vals = [c.eval_tpu(batch, ctx) for c in self.children]
        a = _expand_list(vals[0], batch)
        b = _expand_list(vals[1], batch)
        if not (_fixed_list(a) and _fixed_list(b)
                and a.child.data.dtype == b.child.data.dtype):
            return self._host_from_vals(vals, batch)
        cap = batch.capacity
        a_valid_e = a.child.validity if a.child.validity is not None else \
            jnp.ones((a.child.capacity,), jnp.bool_)
        b_valid_e = b.child.validity if b.child.validity is not None else \
            jnp.ones((b.child.capacity,), jnp.bool_)
        member = _member_in(a, b) & a_valid_e
        overlap = _seg_any(member, a)
        a_has_null = _seg_any(~a_valid_e, a)
        b_has_null = _seg_any(~b_valid_e, b)
        a_len = _lengths(a)
        b_len = _lengths(b)
        unknown = (~overlap) & ((a_has_null & (b_len > 0))
                                | (b_has_null & (a_len > 0)))
        valid = combine_validity(cap, _list_validity(a, batch),
                                 _list_validity(b, batch), ~unknown)
        return make_column(BooleanT, overlap, valid, batch.num_rows)


class ArrayRepeat(_HostListOp):
    def __init__(self, elem: Expression, count: Expression):
        self.children = (elem, count)

    @property
    def dtype(self) -> DataType:
        return ArrayType(self.children[0].dtype, self.children[0].nullable)

    def _combine(self, e, cnt):
        if cnt is None:
            return None
        return [e] * max(0, int(cnt))

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        elem_t = self.children[0].dtype
        if not is_fixed_width(elem_t):
            return super().eval_tpu(batch, ctx)
        cap = batch.capacity
        ev = self.children[0].eval_tpu(batch, ctx)
        if isinstance(ev, TpuScalar):
            from .base import to_column
            ev = to_column(ev, batch, elem_t)
        cnt_arr, cnt_val = _int_operand(self.children[1].eval_tpu(batch, ctx),
                                        cap)
        if cnt_arr is None:
            return _all_null_list(self.dtype, batch)
        valid = combine_validity(cap, cnt_val, row_mask(batch.num_rows, cap))
        act = valid if valid is not None else row_mask(batch.num_rows, cap)
        lens = jnp.where(act, jnp.maximum(cnt_arr, 0), 0)
        out_cap = bucket_capacity(max(int(jnp.sum(lens)), 1))
        new_offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                    jnp.cumsum(lens, dtype=jnp.int32)])
        j = jnp.arange(out_cap, dtype=jnp.int32)
        row = jnp.searchsorted(new_offs[1:], j, side="right").astype(jnp.int32)
        row_c = jnp.clip(row, 0, cap - 1)
        in_range = j < new_offs[cap]
        data = jnp.where(in_range, ev.data[row_c], jnp.zeros((), ev.data.dtype))
        ev_valid = None
        if ev.validity is not None:
            ev_valid = jnp.where(in_range, ev.validity[row_c], False)
        n_elems = int(new_offs[batch.num_rows])
        child = TpuColumnVector(elem_t, data, ev_valid, n_elems)
        return TpuColumnVector(self.dtype, data, valid, batch.num_rows,
                               offsets=new_offs, child=child)


class Slice(_HostListOp):
    """slice(arr, start, length): 1-based; negative start counts from end."""

    def __init__(self, arr: Expression, start: Expression, length: Expression):
        self.children = (arr, start, length)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _combine(self, lst, start, length):
        if lst is None or start is None or length is None:
            return None
        if start == 0:
            raise ExpressionError("Unexpected value for start in slice: 0")
        if length < 0:
            raise ExpressionError(f"Unexpected value for length in slice: {length}")
        i = start - 1 if start > 0 else len(lst) + start
        if i < 0:
            return []
        return lst[i:i + length]

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        vals = [c.eval_tpu(batch, ctx) for c in self.children]
        col = _expand_list(vals[0], batch)
        if not _fixed_list(col):
            return self._host_from_vals(vals, batch)
        cap = batch.capacity
        s_arr, s_val = _int_operand(vals[1], cap)
        l_arr, l_val = _int_operand(vals[2], cap)
        if s_arr is None or l_arr is None:
            return _all_null_list(self.dtype, batch)
        lens = _lengths(col)
        valid = combine_validity(cap, _list_validity(col, batch), s_val, l_val)
        act = valid if valid is not None else row_mask(col.num_rows, cap)
        if bool(jnp.any(act & (s_arr == 0))):
            raise ExpressionError("Unexpected value for start in slice: 0")
        bad_len = act & (l_arr < 0)
        if bool(jnp.any(bad_len)):
            v = int(jnp.min(jnp.where(bad_len, l_arr, 0)))
            raise ExpressionError(f"Unexpected value for length in slice: {v}")
        i = jnp.where(s_arr > 0, s_arr - 1, lens + s_arr)
        i_c = jnp.clip(i, 0, lens)
        new_len = jnp.where(i < 0, 0,
                            jnp.minimum(jnp.maximum(l_arr, 0), lens - i_c))
        return _list_from_plan(col, col.offsets[:-1] + i_c, new_len,
                               max(int(col.child.capacity), 1), valid,
                               col.num_rows)


class ConcatArrays(_HostListOp):
    """concat(a1, a2, ...) for array inputs (strings use ConcatStr)."""

    def __init__(self, children: Sequence[Expression]):
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _combine(self, *lists):
        out = []
        for l in lists:
            if l is None:
                return None
            out.extend(l)
        return out

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cols = [_eval_list(c, batch, ctx) for c in self.children]
        out = _concat_list_cols(cols, batch, self.dtype)
        if out is None:
            return super().eval_tpu(batch, ctx)
        return out


class Flatten(_HostListOp):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype.element_type

    def _combine(self, lst):
        if lst is None:
            return None
        out = []
        for inner in lst:
            if inner is None:
                return None
            out.extend(inner)
        return out

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        vals = [c.eval_tpu(batch, ctx) for c in self.children]
        col = _expand_list(vals[0], batch)
        inner = col.child if isinstance(col, TpuColumnVector) else None
        if (inner is None or inner.child is None or col.host_data is not None
                or inner.host_data is not None):
            return self._host_from_vals(vals, batch)
        cap = batch.capacity
        # offset composition: new row i spans inner rows [O[i], O[i+1]) whose
        # elements are [I[O[i]], I[O[i+1]]) — one gather, child shared as-is
        m = int(inner.offsets.shape[0]) - 1
        new_offs = inner.offsets[jnp.clip(col.offsets, 0, m)]
        valid = _list_validity(col, batch)
        if inner.validity is not None:
            # Spark: any null inner array → whole row null
            icap = inner.capacity
            irows = jnp.searchsorted(col.offsets[1:],
                                     jnp.arange(icap, dtype=jnp.int32),
                                     side="right").astype(jnp.int32)
            in_data = jnp.arange(icap) < col.offsets[cap]
            nulls = jnp.zeros((cap,), jnp.int32).at[
                jnp.where(in_data, irows, cap)].add(
                (~inner.validity).astype(jnp.int32), mode="drop")
            valid = combine_validity(cap, valid, nulls == 0)
        return TpuColumnVector(self.dtype, inner.child.data, valid,
                               col.num_rows, offsets=new_offs,
                               child=inner.child)


class ArrayJoin(_HostListOp):
    def __init__(self, arr: Expression, delim: Expression,
                 null_replacement: Optional[Expression] = None):
        self.children = (arr, delim) + \
            ((null_replacement,) if null_replacement is not None else ())

    @property
    def dtype(self) -> DataType:
        from ..types import StringT
        return StringT

    def _combine(self, lst, delim, *rep):
        if lst is None or delim is None:
            return None
        repl = rep[0] if rep else None
        parts = []
        for e in lst:
            if e is None:
                if repl is not None:
                    parts.append(str(repl))
            else:
                parts.append(str(e))
        return delim.join(parts)


class Sequence(_HostListOp):
    """sequence(start, stop[, step]) — inclusive. Reference GpuSequence."""

    def __init__(self, start: Expression, stop: Expression,
                 step: Optional[Expression] = None):
        self.children = (start, stop) + ((step,) if step is not None else ())

    @property
    def dtype(self) -> DataType:
        return ArrayType(self.children[0].dtype, False)

    def _combine(self, start, stop, *step):
        if start is None or stop is None or (step and step[0] is None):
            return None
        s = step[0] if step else (1 if stop >= start else -1)
        if s == 0:
            raise ExpressionError("sequence step must not be zero")
        if (stop - start) * s < 0:
            return []
        out = list(range(int(start), int(stop) + (1 if s > 0 else -1), int(s)))
        return out

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from ..types import IntegerType, LongType, ShortType, ByteType
        elem = self.children[0].dtype
        if not isinstance(elem, (IntegerType, LongType, ShortType, ByteType)):
            return super().eval_tpu(batch, ctx)
        cap = batch.capacity
        raw = [c.eval_tpu(batch, ctx) for c in self.children]
        # arithmetic runs in the element carrier (int64 for bigint — an int32
        # intermediate would truncate values and wrap the range computation)
        wide = jnp.int64 if np.dtype(elem.np_dtype).itemsize >= 8 else jnp.int32
        vals = [_int_operand(v, cap, dtype=wide) for v in raw]
        if any(a is None for a, _ in vals):
            return _all_null_list(self.dtype, batch)
        s_arr, s_val = vals[0]
        e_arr, e_val = vals[1]
        if len(vals) > 2:
            st_arr, st_val = vals[2]
        else:
            st_arr = jnp.where(e_arr >= s_arr, 1, -1).astype(wide)
            st_val = None
        valid = combine_validity(cap, s_val, e_val, st_val,
                                 row_mask(batch.num_rows, cap))
        act = valid if valid is not None else row_mask(batch.num_rows, cap)
        if bool(jnp.any(act & (st_arr == 0))):
            raise ExpressionError("sequence step must not be zero")
        st_safe = jnp.where(st_arr == 0, 1, st_arr)
        diff = e_arr - s_arr
        empty = jnp.sign(diff) * jnp.sign(st_safe) < 0
        lens = jnp.where(act & ~empty, diff // st_safe + 1, 0).astype(jnp.int32)
        out_cap = bucket_capacity(max(int(jnp.sum(lens)), 1))
        new_offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                    jnp.cumsum(lens, dtype=jnp.int32)])
        j = jnp.arange(out_cap, dtype=jnp.int32)
        row = jnp.searchsorted(new_offs[1:], j, side="right").astype(jnp.int32)
        row_c = jnp.clip(row, 0, cap - 1)
        pos = j - new_offs[row_c]
        in_range = j < new_offs[cap]
        carrier = elem.np_dtype
        data = jnp.where(in_range,
                         (s_arr[row_c] + pos.astype(wide) * st_arr[row_c]),
                         0).astype(carrier)
        n_elems = int(new_offs[batch.num_rows])
        child = TpuColumnVector(elem, data, None, n_elems)
        return TpuColumnVector(self.dtype, data, valid, batch.num_rows,
                               offsets=new_offs, child=child)


class ArrayReverse(_HostListOp):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _combine(self, lst):
        return None if lst is None else list(reversed(lst))

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        vals = [c.eval_tpu(batch, ctx) for c in self.children]
        col = _expand_list(vals[0], batch)
        if not _fixed_list(col):
            return self._host_from_vals(vals, batch)
        lens = _lengths(col)
        stride = jnp.full((col.capacity,), -1, jnp.int32)
        return _list_from_plan(col, col.offsets[:-1] + lens - 1, lens,
                               max(int(col.child.capacity), 1),
                               col.validity, col.num_rows, stride=stride)


class ArraysZip(_HostListOp):
    def __init__(self, children: Sequence[Expression], names: Optional[List[str]] = None):
        self.children = tuple(children)
        self._names = names or [str(i) for i in range(len(self.children))]

    @property
    def dtype(self) -> DataType:
        fields = [StructField(n, c.dtype.element_type, True)
                  for n, c in zip(self._names, self.children)]
        return ArrayType(StructType(fields), True)

    def _combine(self, *lists):
        if any(l is None for l in lists):
            return None
        m = max((len(l) for l in lists), default=0)
        return [{n: (l[i] if i < len(l) else None)
                 for n, l in zip(self._names, lists)} for i in range(m)]


# ---------------------------------------------------------------------------
# map expressions (host-side; map columns have no device layout yet)
# ---------------------------------------------------------------------------

def _as_pairs(m):
    if m is None:
        return None
    if isinstance(m, dict):
        return list(m.items())
    return list(m)


def _dedupe_pairs(pairs):
    """Last-win key dedup (spark.sql.mapKeyDedupPolicy=LAST_WIN), preserving
    first-insertion order and NaN-key equality consistent with GetMapValue."""
    out = {}
    for k, v in pairs:
        out[_canon(k)] = (k, v)
    return list(out.values())


class CreateMap(_HostListOp):
    def __init__(self, children: Sequence[Expression]):
        assert len(children) % 2 == 0
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        k = self.children[0].dtype
        v = self.children[1].dtype
        return MapType(k, v, any(c.nullable for c in self.children[1::2]))

    def _combine(self, *vals):
        keys = vals[0::2]
        vs = vals[1::2]
        if any(k is None for k in keys):
            raise ExpressionError("Cannot use null as map key")
        return _dedupe_pairs(zip(keys, vs))


def _device_map(col) -> bool:
    """Device-resident map column: offsets + struct<key,value> child."""
    return (isinstance(col, TpuColumnVector) and col.host_data is None
            and col.offsets is not None and col.child is not None
            and col.child.children is not None)


class MapKeys(_HostListOp):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self) -> DataType:
        return ArrayType(self.children[0].dtype.key_type, False)

    def _combine(self, m):
        p = _as_pairs(m)
        return None if p is None else [k for k, _ in p]

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        v = self.children[0].eval_tpu(batch, ctx)
        if _device_map(v):
            # zero-copy: the map's offsets over its keys child column
            kid = v.child.children[0]
            return TpuColumnVector(self.dtype, kid.data, v.validity,
                                   v.num_rows, offsets=v.offsets, child=kid)
        return self._host_from_vals([v], batch)


class MapValues(_HostListOp):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self) -> DataType:
        mt = self.children[0].dtype
        return ArrayType(mt.value_type, mt.value_contains_null)

    def _combine(self, m):
        p = _as_pairs(m)
        return None if p is None else [v for _, v in p]

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        v = self.children[0].eval_tpu(batch, ctx)
        if _device_map(v):
            kid = v.child.children[1]
            return TpuColumnVector(self.dtype, kid.data, v.validity,
                                   v.num_rows, offsets=v.offsets, child=kid)
        return self._host_from_vals([v], batch)


class GetMapValue(_HostListOp):
    def __init__(self, child: Expression, key: Expression):
        self.children = (child, key)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype.value_type

    def _combine(self, m, k):
        p = _as_pairs(m)
        if p is None or k is None:
            return None
        for ek, ev in p:
            if _eq_value(ek, k):
                return ev
        return None

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from jax.ops import segment_min
        from ..types import is_fixed_width
        vals = [c.eval_tpu(batch, ctx) for c in self.children]
        m, k = vals
        mt = self.children[0].dtype
        if (_device_map(m) and is_fixed_width(mt.key_type)
                and is_fixed_width(mt.value_type)
                and not (isinstance(k, TpuScalar) and k.value is None)):
            keys = m.child.children[0]
            values = m.child.children[1]
            cap, n = batch.capacity, batch.num_rows
            offs = m.offsets
            ecap = int(keys.data.shape[0])
            e = jnp.arange(ecap, dtype=jnp.int32)
            elem_row = jnp.clip(
                jnp.searchsorted(offs[1:cap + 1], e, side="right"),
                0, max(cap - 1, 0)).astype(jnp.int32)
            if isinstance(k, TpuScalar):
                kv = jnp.asarray(k.value, keys.data.dtype)
                k_valid_row = None
            else:
                kv = k.data[elem_row]
                k_valid_row = k.validity
            in_elems = e < offs[n]
            match = (keys.data == kv) & in_elems
            big = jnp.int32(2**31 - 1)
            sel = segment_min(jnp.where(match, e, big), elem_row,
                              num_segments=cap)
            found = sel < big
            sel_c = jnp.clip(sel, 0, max(ecap - 1, 0))
            data = values.data[sel_c]
            valid = found
            if values.validity is not None:
                valid = valid & values.validity[sel_c]
            valid = combine_validity(cap, valid, m.validity, k_valid_row,
                                     row_mask(n, cap))
            return make_column(mt.value_type, data, valid, n)
        return self._host_from_vals(vals, batch)


class MapConcat(_HostListOp):
    def __init__(self, children: Sequence[Expression]):
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _combine(self, *maps):
        pairs = []
        for m in maps:
            p = _as_pairs(m)
            if p is None:
                return None
            pairs.extend(p)
        return _dedupe_pairs(pairs)


class MapFromArrays(_HostListOp):
    def __init__(self, keys: Expression, values: Expression):
        self.children = (keys, values)

    @property
    def dtype(self) -> DataType:
        kt = self.children[0].dtype.element_type
        vt = self.children[1].dtype.element_type
        return MapType(kt, vt, True)

    def _combine(self, ks, vs):
        if ks is None or vs is None:
            return None
        if len(ks) != len(vs):
            raise ExpressionError("map_from_arrays: key/value lengths differ")
        if any(k is None for k in ks):
            raise ExpressionError("Cannot use null as map key")
        return _dedupe_pairs(zip(ks, vs))


# ---------------------------------------------------------------------------
# higher-order functions
# ---------------------------------------------------------------------------

_NEXT_LAMBDA_ID = [0]


class NamedLambdaVariable(Expression):
    """A lambda argument (reference NamedLambdaVariable). Identity by object."""

    unevaluable = True  # bound by the enclosing higher-order function

    def __init__(self, name: str, dtype: DataType, nullable: bool = True):
        self.children = ()
        self.name = name
        self._dtype = dtype
        self._nullable = nullable
        _NEXT_LAMBDA_ID[0] += 1
        self.var_id = _NEXT_LAMBDA_ID[0]

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    def pretty(self) -> str:
        return self.name


class _BoundLambdaVar(Expression):
    """Lambda variable bound to an ordinal of the element pseudo-batch."""

    def __init__(self, ordinal: int, dtype: DataType, nullable: bool = True):
        self.children = ()
        self.ordinal = ordinal
        self._dtype = dtype
        self._nullable = nullable

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        return batch.column(self.ordinal)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        return table.column(self.ordinal).combine_chunks()

    def pretty(self) -> str:
        return f"lambda#{self.ordinal}"


class LambdaFunction(Expression):
    """(x[, i]) -> body. children = (body,); arguments kept separately."""

    unevaluable = True  # evaluated by the enclosing higher-order function

    def __init__(self, body: Expression, arguments: Sequence[NamedLambdaVariable]):
        self.children = (body,)
        self.arguments = list(arguments)

    @property
    def body(self) -> Expression:
        return self.children[0]

    @property
    def dtype(self) -> DataType:
        return self.body.dtype

    @property
    def nullable(self) -> bool:
        return self.body.nullable

    def pretty(self) -> str:
        args = ", ".join(a.name for a in self.arguments)
        return f"({args}) -> {self.body.pretty()}"


class HigherOrderFunction(Expression):
    """Base: evaluates the lambda body over the FLAT element vector.

    Both eval paths share the structure: flatten → vectorized body eval over a
    pseudo input (elements, [position], [outer cols expanded per element]) →
    segment-level recombination. This turns a per-list lambda into one
    batch-wide XLA program — no per-row interpretation (the reference instead
    relies on cuDF per-list kernels)."""

    def __init__(self, argument: Expression, function: LambdaFunction):
        self.children = (argument, function)

    @property
    def argument(self) -> Expression:
        return self.children[0]

    @property
    def function(self) -> LambdaFunction:
        return self.children[1]

    def _sync_vars(self) -> None:
        """Fill lambda-variable types from the (now resolved) argument type.
        Lambda vars are shared object identities across tree copies, so this
        mutation is visible wherever the body is evaluated (the analogue of
        Spark's ResolveLambdaVariables rule)."""
        at = self.argument.dtype
        if isinstance(at, ArrayType):
            args = self.function.arguments
            if args:
                args[0]._dtype = at.element_type
                args[0]._nullable = at.contains_null
            if len(args) > 1:
                args[1]._dtype = IntegerT
                args[1]._nullable = False

    @property
    def resolved(self) -> bool:
        ok = all(c.resolved for c in self.children)
        if ok:
            self._sync_vars()
        return ok

    # -- binding -----------------------------------------------------------
    def _bound_body(self, with_index: bool):
        """Replace lambda vars with pseudo-batch ordinals; collect outer refs.
        Pseudo layout: [0]=element, [1]=position (if used), [2+]=outer refs."""
        fn = self.function
        var_ids = {v.var_id: i for i, v in enumerate(fn.arguments)}
        outer: List[AttributeReference] = []
        base = 2 if with_index else 1

        def rule(e: Expression):
            if isinstance(e, NamedLambdaVariable):
                return _BoundLambdaVar(var_ids[e.var_id], e.dtype, e.nullable)
            if isinstance(e, AttributeReference):
                for j, o in enumerate(outer):
                    if o.expr_id == e.expr_id:
                        return _BoundLambdaVar(base + j, e.dtype, e.nullable)
                outer.append(e)
                return _BoundLambdaVar(base + len(outer) - 1, e.dtype, e.nullable)
            return None

        body = fn.body.transform(rule)
        return body, outer

    @property
    def _uses_index(self) -> bool:
        return len(self.function.arguments) > 1

    # -- device ------------------------------------------------------------
    def _device_pseudo(self, col: TpuColumnVector, batch, ctx, outer):
        """Build the element pseudo-batch on device."""
        from ..columnar.batch import TpuColumnarBatch
        child = col.child
        seg, in_data = _segments(col)
        cols = [child]
        if self._uses_index:
            pos = jnp.arange(child.capacity, dtype=jnp.int32)
            idx = pos - jnp.take(col.offsets, seg)
            cols.append(TpuColumnVector(IntegerT, idx, None, child.num_rows))
        for o in outer:
            oc = o.eval_tpu(batch, ctx)
            od = jnp.take(oc.data, seg)
            ov = jnp.take(oc.validity, seg) if oc.validity is not None else None
            cols.append(TpuColumnVector(oc.dtype, od, ov, child.num_rows))
        return TpuColumnarBatch(cols, child.num_rows), seg, in_data

    def _device_ok(self, col: TpuColumnVector, outer) -> bool:
        if col.child is None or not is_fixed_width(col.child.dtype):
            return False
        return all(is_fixed_width(o.dtype) for o in outer)

    # -- host --------------------------------------------------------------
    def _host_pseudo(self, lists, batch_or_table, ctx, outer, is_tpu: bool):
        """Flatten python lists into a pyarrow pseudo-table for eval_cpu."""
        import pyarrow as pa
        elem_t = self.argument.dtype.element_type
        flat, pos, seg = [], [], []
        for i, lst in enumerate(lists):
            if lst is None:
                continue
            for j, e in enumerate(lst):
                flat.append(e)
                pos.append(j)
                seg.append(i)
        cols = {"elem": pa.array(flat, type=type_to_arrow(elem_t))}
        if self._uses_index:
            cols["pos"] = pa.array(pos, type=pa.int32())
        for k, o in enumerate(outer):
            if is_tpu:
                ovals = o.eval_tpu(batch_or_table, ctx).to_pylist()
            else:
                r = o.eval_cpu(batch_or_table, ctx)
                ovals = r.to_pylist()
            cols[f"outer{k}"] = pa.array([ovals[s] for s in seg],
                                         type=type_to_arrow(o.dtype))
        return pa.table(cols)


class ArrayTransform(HigherOrderFunction):
    """transform(arr, x -> f(x)). Reference GpuArrayTransform."""

    @property
    def dtype(self) -> DataType:
        self._sync_vars()
        return ArrayType(self.function.dtype, True)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        self._sync_vars()
        col = _eval_list(self.argument, batch, ctx)
        body, outer = self._bound_body(self._uses_index)
        if self._device_ok(col, outer) and is_fixed_width(self.function.dtype):
            pseudo, seg, in_data = self._device_pseudo(col, batch, ctx, outer)
            res = body.eval_tpu(pseudo, ctx)
            from .base import to_column
            res_col = to_column(res, pseudo, self.function.dtype)
            new_child = TpuColumnVector(self.function.dtype, res_col.data,
                                        res_col.validity, col.child.num_rows)
            return TpuColumnVector(self.dtype, new_child.data, col.validity,
                                   col.num_rows, offsets=col.offsets,
                                   child=new_child)
        # host path
        lists = col.to_pylist()
        pseudo = self._host_pseudo(lists, batch, ctx, outer, is_tpu=True)
        out_flat = body.eval_cpu(pseudo, ctx)
        return _result_from_pylist(
            _regroup(lists, out_flat.to_pylist() if hasattr(out_flat, "to_pylist")
                     else [out_flat] * pseudo.num_rows),
            self.dtype, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        self._sync_vars()
        lists = self.argument.eval_cpu(table, ctx).to_pylist()
        body, outer = self._bound_body(self._uses_index)
        pseudo = self._host_pseudo(lists, table, ctx, outer, is_tpu=False)
        out_flat = body.eval_cpu(pseudo, ctx)
        vals = out_flat.to_pylist() if isinstance(out_flat, (pa.Array, pa.ChunkedArray)) \
            else [out_flat] * pseudo.num_rows
        return pa.array(_regroup(lists, vals), type=type_to_arrow(self.dtype))

    def pretty(self) -> str:
        return f"transform({self.argument.pretty()}, {self.function.pretty()})"


def _regroup(lists, flat_vals):
    out, p = [], 0
    for lst in lists:
        if lst is None:
            out.append(None)
        else:
            out.append(flat_vals[p:p + len(lst)])
            p += len(lst)
    return out


class _ArrayPredicateHOF(HigherOrderFunction):
    """exists / forall: three-valued segment reduction of the predicate."""

    _kind = "exists"  # or "forall"

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        self._sync_vars()
        col = _eval_list(self.argument, batch, ctx)
        body, outer = self._bound_body(self._uses_index)
        cap = batch.capacity
        if self._device_ok(col, outer):
            pseudo, seg, in_data = self._device_pseudo(col, batch, ctx, outer)
            from .base import to_column
            res = to_column(body.eval_tpu(pseudo, ctx), pseudo, BooleanT)
            pred = res.data.astype(jnp.bool_)
            pv = res.validity
            known = in_data if pv is None else (in_data & pv)
            row_cap = col.capacity
            any_true = _segment_reduce((pred & known).astype(jnp.int32), seg,
                                       ~in_data, row_cap, "max") > 0
            any_false = _segment_reduce(((~pred) & known).astype(jnp.int32), seg,
                                        ~in_data, row_cap, "max") > 0
            any_unknown = _segment_reduce(((~known) & in_data).astype(jnp.int32),
                                          seg, ~in_data, row_cap, "max") > 0
            if self._kind == "exists":
                data = any_true
                unknown = (~any_true) & any_unknown
            else:
                data = ~any_false
                unknown = (~any_false) & any_unknown
            valid = combine_validity(cap, _list_validity(col, batch), ~unknown)
            return make_column(BooleanT, data, valid, col.num_rows)
        lists = col.to_pylist()
        pseudo = self._host_pseudo(lists, batch, ctx, outer, is_tpu=True)
        flat = body.eval_cpu(pseudo, ctx)
        vals = flat.to_pylist() if hasattr(flat, "to_pylist") \
            else [flat] * pseudo.num_rows
        return _result_from_pylist(
            [_pred_one(g, self._kind) for g in _regroup(lists, vals)],
            BooleanT, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        self._sync_vars()
        lists = self.argument.eval_cpu(table, ctx).to_pylist()
        body, outer = self._bound_body(self._uses_index)
        pseudo = self._host_pseudo(lists, table, ctx, outer, is_tpu=False)
        flat = body.eval_cpu(pseudo, ctx)
        vals = flat.to_pylist() if isinstance(flat, (pa.Array, pa.ChunkedArray)) \
            else [flat] * pseudo.num_rows
        return pa.array([_pred_one(g, self._kind) for g in _regroup(lists, vals)],
                        type=pa.bool_())

    def pretty(self) -> str:
        return f"{self._kind}({self.argument.pretty()}, {self.function.pretty()})"


def _pred_one(group, kind):
    if group is None:
        return None
    if kind == "exists":
        if any(v is True for v in group):
            return True
        return None if any(v is None for v in group) else False
    if any(v is False for v in group):
        return False
    return None if any(v is None for v in group) else True


class ArrayExists(_ArrayPredicateHOF):
    _kind = "exists"


class ArrayForAll(_ArrayPredicateHOF):
    _kind = "forall"


class ArrayFilter(HigherOrderFunction):
    """filter(arr, x -> pred): keeps elements where pred is true (null → drop)."""

    @property
    def dtype(self) -> DataType:
        return self.argument.dtype

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from ..columnar.batch import TpuColumnarBatch, compact
        self._sync_vars()
        col = _eval_list(self.argument, batch, ctx)
        body, outer = self._bound_body(self._uses_index)
        if self._device_ok(col, outer):
            pseudo, seg, in_data = self._device_pseudo(col, batch, ctx, outer)
            from .base import to_column
            res = to_column(body.eval_tpu(pseudo, ctx), pseudo, BooleanT)
            keep = res.data.astype(jnp.bool_)
            if res.validity is not None:
                keep = keep & res.validity
            keep = keep & in_data
            row_cap = col.capacity
            new_lens = _segment_reduce(keep.astype(jnp.int32), seg, ~in_data,
                                       row_cap, "sum")
            new_offsets = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32),
                 jnp.cumsum(new_lens).astype(jnp.int32)])
            kept = compact(TpuColumnarBatch([col.child], col.child.num_rows), keep)
            new_child = kept.columns[0]
            return TpuColumnVector(self.dtype, new_child.data, col.validity,
                                   col.num_rows, offsets=new_offsets,
                                   child=new_child)
        lists = col.to_pylist()
        pseudo = self._host_pseudo(lists, batch, ctx, outer, is_tpu=True)
        flat = body.eval_cpu(pseudo, ctx)
        vals = flat.to_pylist() if hasattr(flat, "to_pylist") \
            else [flat] * pseudo.num_rows
        return _result_from_pylist(_filter_groups(lists, vals), self.dtype, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        self._sync_vars()
        lists = self.argument.eval_cpu(table, ctx).to_pylist()
        body, outer = self._bound_body(self._uses_index)
        pseudo = self._host_pseudo(lists, table, ctx, outer, is_tpu=False)
        flat = body.eval_cpu(pseudo, ctx)
        vals = flat.to_pylist() if isinstance(flat, (pa.Array, pa.ChunkedArray)) \
            else [flat] * pseudo.num_rows
        return pa.array(_filter_groups(lists, vals), type=type_to_arrow(self.dtype))

    def pretty(self) -> str:
        return f"filter({self.argument.pretty()}, {self.function.pretty()})"


def _filter_groups(lists, flat_preds):
    out, p = [], 0
    for lst in lists:
        if lst is None:
            out.append(None)
        else:
            preds = flat_preds[p:p + len(lst)]
            p += len(lst)
            out.append([e for e, keep in zip(lst, preds) if keep is True])
    return out


class ArrayAggregate(Expression):
    """aggregate(arr, zero, (acc, x) -> merge[, acc -> finish]).

    Vectorized fold: iterate element POSITIONS (max list length times), each
    step evaluating the merge body over full row-width columns — device when
    types are fixed-width, arrow otherwise. children = (argument, zero,
    merge_lambda[, finish_lambda])."""

    def __init__(self, argument: Expression, zero: Expression,
                 merge: LambdaFunction, finish: Optional[LambdaFunction] = None):
        self.children = (argument, zero, merge) + \
            ((finish,) if finish is not None else ())

    @property
    def argument(self) -> Expression:
        return self.children[0]

    @property
    def zero(self) -> Expression:
        return self.children[1]

    @property
    def merge(self) -> LambdaFunction:
        return self.children[2]

    @property
    def finish(self) -> Optional[LambdaFunction]:
        return self.children[3] if len(self.children) > 3 else None

    def _sync_vars(self) -> None:
        at = self.argument.dtype
        margs = self.merge.arguments
        margs[0]._dtype = self.zero.dtype
        if isinstance(at, ArrayType):
            margs[1]._dtype = at.element_type
            margs[1]._nullable = at.contains_null
        if self.finish is not None:
            self.finish.arguments[0]._dtype = self.merge.dtype

    @property
    def dtype(self) -> DataType:
        self._sync_vars()
        return self.finish.dtype if self.finish is not None else self.merge.dtype

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        self._sync_vars()
        col = _eval_list(self.argument, batch, ctx)
        lists = col.to_pylist()
        return _result_from_pylist(self._fold(lists, batch, ctx, is_tpu=True),
                                   self.dtype, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        self._sync_vars()
        lists = self.argument.eval_cpu(table, ctx).to_pylist()
        return pa.array(self._fold(lists, table, ctx, is_tpu=False),
                        type=type_to_arrow(self.dtype))

    def _fold(self, lists, batch_or_table, ctx, is_tpu: bool):
        """Per-position vectorized fold over arrow arrays (host)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        n = len(lists)
        acc_t = self.merge.dtype
        # zero
        if is_tpu:
            z = self.zero.eval_tpu(batch_or_table, ctx)
            zvals = [z.value] * n if isinstance(z, TpuScalar) else z.to_pylist()
        else:
            z = self.zero.eval_cpu(batch_or_table, ctx)
            zvals = z.to_pylist() if isinstance(z, (pa.Array, pa.ChunkedArray)) \
                else [z] * n
        acc = list(zvals)
        max_len = max((len(l) for l in lists if l is not None), default=0)
        acc_var, elem_var = self.merge.arguments[0], self.merge.arguments[1]

        # bind lambda vars to pseudo ordinals 0/1 and outer column refs to 2+
        # (the fold pseudo table is row-aligned, so outer columns pass through)
        outer: List[AttributeReference] = []

        def bind(body):
            def rule(e):
                if isinstance(e, NamedLambdaVariable):
                    if e.var_id == acc_var.var_id:
                        return _BoundLambdaVar(0, acc_var.dtype)
                    return _BoundLambdaVar(1, elem_var.dtype)
                if isinstance(e, AttributeReference):
                    for j, o in enumerate(outer):
                        if o.expr_id == e.expr_id:
                            return _BoundLambdaVar(2 + j, e.dtype, e.nullable)
                    outer.append(e)
                    return _BoundLambdaVar(2 + len(outer) - 1, e.dtype, e.nullable)
                return None
            return body.transform(rule)

        merge_body = bind(self.merge.body)
        outer_cols = {}
        for j, o in enumerate(outer):
            if is_tpu:
                ov = o.eval_tpu(batch_or_table, ctx).to_pylist()
            else:
                ov = o.eval_cpu(batch_or_table, ctx).to_pylist()
            outer_cols[f"outer{j}"] = pa.array(ov, type=type_to_arrow(o.dtype))
        for k in range(max_len):
            elems = [l[k] if l is not None and k < len(l) else None for l in lists]
            in_range = [l is not None and k < len(l) for l in lists]
            pseudo = pa.table({
                "acc": pa.array(acc, type=type_to_arrow(acc_t)),
                "elem": pa.array(elems,
                                 type=type_to_arrow(self.argument.dtype.element_type)),
                **outer_cols,
            })
            merged = merge_body.eval_cpu(pseudo, ctx)
            mvals = merged.to_pylist() if isinstance(merged, (pa.Array, pa.ChunkedArray)) \
                else [merged] * n
            acc = [mv if ir else a for mv, ir, a in zip(mvals, in_range, acc)]
        out = [a if l is not None else None for a, l in zip(acc, lists)]
        if self.finish is not None:
            fv = self.finish.arguments[0]
            fouter: List[AttributeReference] = []

            def frule(e):
                if isinstance(e, NamedLambdaVariable) and e.var_id == fv.var_id:
                    return _BoundLambdaVar(0, fv.dtype)
                if isinstance(e, AttributeReference):
                    for j, o in enumerate(fouter):
                        if o.expr_id == e.expr_id:
                            return _BoundLambdaVar(1 + j, e.dtype, e.nullable)
                    fouter.append(e)
                    return _BoundLambdaVar(len(fouter), e.dtype, e.nullable)
                return None
            fbody = self.finish.body.transform(frule)
            fcols = {"acc": pa.array(out, type=type_to_arrow(acc_t))}
            for j, o in enumerate(fouter):
                ov = o.eval_tpu(batch_or_table, ctx).to_pylist() if is_tpu \
                    else o.eval_cpu(batch_or_table, ctx).to_pylist()
                fcols[f"fouter{j}"] = pa.array(ov, type=type_to_arrow(o.dtype))
            pseudo = pa.table(fcols)
            fin = fbody.eval_cpu(pseudo, ctx)
            fvals = fin.to_pylist() if isinstance(fin, (pa.Array, pa.ChunkedArray)) \
                else [fin] * n
            out = [f if l is not None else None for f, l in zip(fvals, lists)]
        return out

    def pretty(self) -> str:
        return (f"aggregate({self.argument.pretty()}, {self.zero.pretty()}, "
                f"{self.merge.pretty()})")


class ZipWith(_HostListOp):
    """zip_with(a, b, (x, y) -> f): pads the shorter with nulls."""

    def __init__(self, left: Expression, right: Expression, function: LambdaFunction):
        self.children = (left, right, function)

    @property
    def function(self) -> LambdaFunction:
        return self.children[2]

    def _sync_vars(self) -> None:
        lt, rt = self.children[0].dtype, self.children[1].dtype
        args = self.function.arguments
        if isinstance(lt, ArrayType):
            args[0]._dtype = lt.element_type
        if isinstance(rt, ArrayType):
            args[1]._dtype = rt.element_type
        args[0]._nullable = True  # shorter side padded with nulls
        args[1]._nullable = True

    @property
    def dtype(self) -> DataType:
        self._sync_vars()
        return ArrayType(self.function.dtype, True)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        self._sync_vars()
        n = batch.num_rows
        a = _pylist_of(None, batch, ctx, self.children[0], n)
        b = _pylist_of(None, batch, ctx, self.children[1], n)
        return _result_from_pylist(self._zip(a, b, ctx, batch, True),
                                   self.dtype, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        self._sync_vars()
        a = self.children[0].eval_cpu(table, ctx).to_pylist()
        b = self.children[1].eval_cpu(table, ctx).to_pylist()
        return pa.array(self._zip(a, b, ctx, table, False),
                        type=type_to_arrow(self.dtype))

    def _zip(self, a_lists, b_lists, ctx, batch_or_table, is_tpu: bool):
        import pyarrow as pa
        fn = self.function
        xv, yv = fn.arguments[0], fn.arguments[1]
        outer: List[AttributeReference] = []

        def rule(e):
            if isinstance(e, NamedLambdaVariable):
                if e.var_id == xv.var_id:
                    return _BoundLambdaVar(0, xv.dtype)
                return _BoundLambdaVar(1, yv.dtype)
            if isinstance(e, AttributeReference):
                for j, o in enumerate(outer):
                    if o.expr_id == e.expr_id:
                        return _BoundLambdaVar(2 + j, e.dtype, e.nullable)
                outer.append(e)
                return _BoundLambdaVar(2 + len(outer) - 1, e.dtype, e.nullable)
            return None
        body = fn.body.transform(rule)
        flat_a, flat_b, shape, seg = [], [], [], []
        for ri, (a, b) in enumerate(zip(a_lists, b_lists)):
            if a is None or b is None:
                shape.append(None)
                continue
            m = max(len(a), len(b))
            shape.append(m)
            for i in range(m):
                flat_a.append(a[i] if i < len(a) else None)
                flat_b.append(b[i] if i < len(b) else None)
                seg.append(ri)
        cols = {
            "x": pa.array(flat_a, type=type_to_arrow(xv.dtype)),
            "y": pa.array(flat_b, type=type_to_arrow(yv.dtype))}
        for j, o in enumerate(outer):
            ov = o.eval_tpu(batch_or_table, ctx).to_pylist() if is_tpu \
                else o.eval_cpu(batch_or_table, ctx).to_pylist()
            cols[f"outer{j}"] = pa.array([ov[s] for s in seg],
                                         type=type_to_arrow(o.dtype))
        pseudo = pa.table(cols)
        res = body.eval_cpu(pseudo, ctx)
        vals = res.to_pylist() if isinstance(res, (pa.Array, pa.ChunkedArray)) \
            else [res] * pseudo.num_rows
        out, p = [], 0
        for m in shape:
            if m is None:
                out.append(None)
            else:
                out.append(vals[p:p + m])
                p += m
        return out


# ---------------------------------------------------------------------------
# breadth 2: array_remove, map entry/lambda ops, struct field access
# (reference collectionOperations.scala GpuArrayRemove/GpuMapEntries,
# higherOrderFunctions.scala GpuMapFilter/GpuTransformKeys/GpuTransformValues,
# complexTypeExtractors.scala GpuGetStructField/GpuGetArrayStructFields,
# complexTypeCreator.scala GpuCreateNamedStruct)
# ---------------------------------------------------------------------------

class ArrayRemove(_HostListOp):
    """array_remove(arr, elem): drops elements equal to elem (NaN equals NaN,
    like array ops' ordering equivalence); nulls are kept."""

    def __init__(self, arr: Expression, elem: Expression):
        self.children = (arr, elem)

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _combine(self, lst, v):
        if lst is None or v is None:
            return None
        return [e for e in lst if e is None or not _eq_value(e, v)]

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        vals = [c.eval_tpu(batch, ctx) for c in self.children]
        col = _expand_list(vals[0], batch)
        elem = vals[1]
        if not _fixed_list(col) or not isinstance(elem, TpuScalar):
            return self._host_from_vals(vals, batch)
        if elem.value is None:
            return _all_null_list(self.dtype, batch)
        child = col.child
        ev = child.validity if child.validity is not None else \
            jnp.ones((child.capacity,), jnp.bool_)
        if _is_float(child.dtype) and isinstance(elem.value, float) \
                and math.isnan(elem.value):
            match = jnp.isnan(child.data)
        else:
            match = child.data == jnp.asarray(elem.value, child.data.dtype)
        _, in_data = _segments(col)
        keep = in_data & ~(match & ev)
        valid = _list_validity(col, batch)
        return _compact_list(col, keep, valid, col.num_rows, self.dtype)


class MapEntries(_HostListOp):
    """map_entries(m) → array<struct<key,value>>."""

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self) -> DataType:
        mt = self.children[0].dtype
        return ArrayType(StructType([StructField("key", mt.key_type, False),
                                     StructField("value", mt.value_type)]),
                         contains_null=False)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        v = self.children[0].eval_tpu(batch, ctx)
        if _device_map(v):
            # the map child IS the entries struct column — dtype change only
            kid = v.child
            entry_t = self.dtype.element_type
            new_kid = TpuColumnVector(entry_t, kid.data, kid.validity,
                                      kid.num_rows, children=kid.children)
            return TpuColumnVector(self.dtype, kid.data, v.validity,
                                   v.num_rows, offsets=v.offsets,
                                   child=new_kid)
        return self._host_from_vals([v], batch)

    def _combine(self, m):
        if m is None:
            return None
        return [{"key": k, "value": v} for k, v in m]


class _MapLambdaOp(_HostListOp):
    """Host lambda-over-map-entries base (pattern: ZipWith — bind (k, v) to
    flat pseudo-table columns, evaluate the body once over all entries)."""

    def __init__(self, child: Expression, function):
        self.children = (child, function)

    @property
    def function(self):
        return self.children[1]

    def _sync_vars(self) -> None:
        mt = self.children[0].dtype
        args = self.function.arguments
        if isinstance(mt, MapType):
            args[0]._dtype = mt.key_type
            if len(args) > 1:
                args[1]._dtype = mt.value_type

    def _apply(self, maps, ctx, batch_or_table, is_tpu: bool):
        import pyarrow as pa
        fn = self.function
        args = fn.arguments
        outer: List[AttributeReference] = []

        def rule(e):
            if isinstance(e, NamedLambdaVariable):
                for ai, a in enumerate(args):
                    if e.var_id == a.var_id:
                        return _BoundLambdaVar(ai, a.dtype)
                return None
            if isinstance(e, AttributeReference):
                for j, o in enumerate(outer):
                    if o.expr_id == e.expr_id:
                        return _BoundLambdaVar(2 + j, e.dtype, e.nullable)
                outer.append(e)
                return _BoundLambdaVar(2 + len(outer) - 1, e.dtype, e.nullable)
            return None

        body = fn.body.transform(rule)
        flat_k, flat_v, shape, seg = [], [], [], []
        for ri, m in enumerate(maps):
            if m is None:
                shape.append(None)
                continue
            shape.append(len(m))
            for k, v in m:
                flat_k.append(k)
                flat_v.append(v)
                seg.append(ri)
        mt = self.children[0].dtype
        cols = {"k": pa.array(flat_k, type=type_to_arrow(mt.key_type)),
                "v": pa.array(flat_v, type=type_to_arrow(mt.value_type))}
        for j, o in enumerate(outer):
            ov = o.eval_tpu(batch_or_table, ctx).to_pylist() if is_tpu \
                else o.eval_cpu(batch_or_table, ctx).to_pylist()
            cols[f"outer{j}"] = pa.array([ov[s] for s in seg],
                                         type=type_to_arrow(o.dtype))
        pseudo = pa.table(cols)
        res = body.eval_cpu(pseudo, ctx)
        vals = res.to_pylist() if isinstance(res, (pa.Array, pa.ChunkedArray)) \
            else [res] * pseudo.num_rows
        out, p = [], 0
        for ri, m in enumerate(shape):
            if m is None:
                out.append(None)
            else:
                out.append(self._regroup(maps[ri], vals[p:p + m]))
                p += m
        return out

    def _regroup(self, entries, lambda_vals):
        raise NotImplementedError

    # -- device ------------------------------------------------------------
    def _device_body_eval(self, m, batch, ctx):
        """Bound (k, v) body over the flat device entry columns. Returns
        (res_col, keys, values, seg, in_data) or None when host-bound."""
        from .base import to_column
        from ..columnar.batch import TpuColumnarBatch
        if not _device_map(m):
            return None
        keys, values = m.child.children
        if not (is_fixed_width(keys.dtype) and is_fixed_width(values.dtype)
                and keys.host_data is None and values.host_data is None):
            return None
        fn = self.function
        args = fn.arguments
        outer: List[AttributeReference] = []

        def rule(e):
            if isinstance(e, NamedLambdaVariable):
                for ai, a in enumerate(args):
                    if e.var_id == a.var_id:
                        return _BoundLambdaVar(ai, a.dtype)
                return None
            if isinstance(e, AttributeReference):
                for j, o in enumerate(outer):
                    if o.expr_id == e.expr_id:
                        return _BoundLambdaVar(2 + j, e.dtype, e.nullable)
                outer.append(e)
                return _BoundLambdaVar(2 + len(outer) - 1, e.dtype,
                                       e.nullable)
            return None

        body = fn.body.transform(rule)
        seg, in_data = _segments(m)
        cols = [keys, values]
        for o in outer:
            oc = o.eval_tpu(batch, ctx)
            if not is_fixed_width(oc.dtype) or oc.host_data is not None:
                return None
            od = jnp.take(oc.data, seg)
            ov = jnp.take(oc.validity, seg) if oc.validity is not None \
                else None
            cols.append(TpuColumnVector(oc.dtype, od, ov, keys.num_rows))
        pseudo = TpuColumnarBatch(cols, keys.num_rows)
        res = body.eval_tpu(pseudo, ctx)
        res_col = to_column(res, pseudo, self.function.dtype)
        return res_col, keys, values, seg, in_data

    def _device_assemble(self, m, res_col, keys, values, seg, in_data,
                         batch):
        return None  # subclass hook; None = fall back to host

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        self._sync_vars()
        mcol = self.children[0].eval_tpu(batch, ctx)
        if isinstance(mcol, TpuColumnVector):
            dev = self._device_body_eval(mcol, batch, ctx)
            if dev is not None:
                out = self._device_assemble(mcol, *dev, batch)
                if out is not None:
                    return out
        maps = (mcol.to_pylist()[:batch.num_rows]
                if isinstance(mcol, TpuColumnVector)
                else [mcol.value] * batch.num_rows)
        return _result_from_pylist(self._apply(maps, ctx, batch, True),
                                   self.dtype, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        self._sync_vars()
        maps = self.children[0].eval_cpu(table, ctx).to_pylist()
        return pa.array(self._apply(maps, ctx, table, False),
                        type=type_to_arrow(self.dtype))


class MapFilter(_MapLambdaOp):
    """map_filter(m, (k, v) -> pred)."""

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    def _regroup(self, entries, lambda_vals):
        return [(k, v) for (k, v), keep in zip(entries, lambda_vals)
                if keep is True]

    def _device_assemble(self, m, res_col, keys, values, seg, in_data,
                         batch):
        # keep = predicate strictly True (null drops), entries only
        keep = res_col.data.astype(jnp.bool_) & in_data
        if res_col.validity is not None:
            keep = keep & res_col.validity
        cap = m.capacity
        ecap = int(keys.capacity)
        keep_i = keep.astype(jnp.int32)
        new_lens = jnp.zeros((cap,), jnp.int32).at[
            jnp.where(in_data, seg, cap)].add(keep_i, mode="drop")
        new_offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                    jnp.cumsum(new_lens, dtype=jnp.int32)])
        out_pos = jnp.cumsum(keep_i) - keep_i
        idx = jnp.where(keep, out_pos, ecap)

        def compact(c):
            data = jnp.zeros((ecap,), c.data.dtype).at[idx].set(
                c.data, mode="drop")
            cv = None
            if c.validity is not None:
                cv = jnp.zeros((ecap,), jnp.bool_).at[idx].set(
                    c.validity, mode="drop")
            return data, cv

        n_elems = int(new_offs[m.num_rows])
        kd, kv = compact(keys)
        vd, vv = compact(values)
        new_keys = TpuColumnVector(keys.dtype, kd, kv, n_elems)
        new_vals = TpuColumnVector(values.dtype, vd, vv, n_elems)
        entry = TpuColumnVector(m.child.dtype, kd, None, n_elems,
                                children=[new_keys, new_vals])
        return TpuColumnVector(self.dtype, kd, m.validity, m.num_rows,
                               offsets=new_offs, child=entry)


class TransformValues(_MapLambdaOp):
    """transform_values(m, (k, v) -> newv)."""

    @property
    def dtype(self) -> DataType:
        mt = self.children[0].dtype
        self._sync_vars()
        return MapType(mt.key_type, self.function.dtype, True)

    def _regroup(self, entries, lambda_vals):
        return [(k, nv) for (k, _), nv in zip(entries, lambda_vals)]

    def _device_assemble(self, m, res_col, keys, values, seg, in_data,
                         batch):
        if not is_fixed_width(self.function.dtype):
            return None
        # zero-copy keys + offsets; only the values child is rebuilt
        new_vals = TpuColumnVector(self.function.dtype, res_col.data,
                                   res_col.validity, values.num_rows)
        from ..types import StructField as _Sf, StructType as _St2
        entry_t = _St2([_Sf("key", keys.dtype, False),
                        _Sf("value", self.function.dtype, True)])
        entry = TpuColumnVector(entry_t, keys.data, None, keys.num_rows,
                                children=[keys, new_vals])
        return TpuColumnVector(self.dtype, keys.data, m.validity,
                               m.num_rows, offsets=m.offsets, child=entry)


class TransformKeys(_MapLambdaOp):
    """transform_keys(m, (k, v) -> newk). Duplicate result keys follow
    LAST_WIN dedup (Spark's non-exception mapKeyDedupPolicy); a null result
    key is a runtime error, as in Spark."""

    @property
    def dtype(self) -> DataType:
        mt = self.children[0].dtype
        self._sync_vars()
        return MapType(self.function.dtype, mt.value_type,
                       getattr(mt, "value_contains_null", True))

    def _regroup(self, entries, lambda_vals):
        out = {}
        for (_, v), nk in zip(entries, lambda_vals):
            if nk is None:
                raise ExpressionError("Cannot use null as map key")
            out[nk] = v
        return list(out.items())


class GetStructField(UnaryExpression):
    """struct.field access (reference GpuGetStructField). Device structs are
    child-column tuples (cuDF STRUCT ColumnView), so field access is a
    zero-copy child selection + validity AND — no host hop."""

    def __init__(self, child: Expression, name: str):
        super().__init__(child)
        self.name = name

    @property
    def dtype(self) -> DataType:
        st = self.child.dtype
        for f in st.fields:
            if f.name == self.name:
                return f.data_type
        raise KeyError(self.name)

    def _ordinal(self) -> int:
        for i, f in enumerate(self.child.dtype.fields):
            if f.name == self.name:
                return i
        raise KeyError(self.name)

    def _gather(self, vals):
        return [None if v is None else v.get(self.name) for v in vals]

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            v = c.value
            return TpuScalar(self.dtype,
                             None if v is None else v.get(self.name))
        if getattr(c, "children", None) is not None:
            kid = c.children[self._ordinal()]
            if c.validity is None:
                return kid
            v = kid.validity & c.validity if kid.validity is not None \
                else c.validity
            return TpuColumnVector(kid.dtype, kid.data, v, c.num_rows,
                                   offsets=kid.offsets, child=kid.child,
                                   host_data=kid.host_data,
                                   host_capacity=kid.host_capacity,
                                   children=kid.children)
        return _result_from_pylist(self._gather(c.to_pylist()), self.dtype,
                                   batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.child.eval_cpu(table, ctx).to_pylist()
        return pa.array(self._gather(vals), type=type_to_arrow(self.dtype))

    def pretty(self) -> str:
        return f"{self.child.pretty()}.{self.name}"


class GetArrayStructFields(UnaryExpression):
    """arr_of_struct.field → array of the field (reference
    GpuGetArrayStructFields)."""

    def __init__(self, child: Expression, name: str):
        super().__init__(child)
        self.name = name

    @property
    def dtype(self) -> DataType:
        st = self.child.dtype.element_type
        for f in st.fields:
            if f.name == self.name:
                return ArrayType(f.data_type, True)
        raise KeyError(self.name)

    def _gather(self, lists):
        out = []
        for lst in lists:
            if lst is None:
                out.append(None)
            else:
                out.append([None if e is None else e.get(self.name)
                            for e in lst])
        return out

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        kid = getattr(c, "child", None)
        if kid is not None and getattr(kid, "children", None) is not None:
            # array<struct>: keep the array shell (offsets + validity), swap
            # the struct child for the selected field's column — zero-copy
            st = self.child.dtype.element_type
            ordinal = next(i for i, f in enumerate(st.fields)
                           if f.name == self.name)
            elem = kid.children[ordinal]
            if kid.validity is not None:
                ev = elem.validity & kid.validity \
                    if elem.validity is not None else kid.validity
                elem = TpuColumnVector(elem.dtype, elem.data, ev,
                                       kid.num_rows, offsets=elem.offsets,
                                       child=elem.child,
                                       children=elem.children)
            return TpuColumnVector(self.dtype, elem.data, c.validity,
                                   c.num_rows, offsets=c.offsets,
                                   child=elem)
        return _result_from_pylist(self._gather(c.to_pylist()), self.dtype,
                                   batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        lists = self.child.eval_cpu(table, ctx).to_pylist()
        return pa.array(self._gather(lists), type=type_to_arrow(self.dtype))

    def pretty(self) -> str:
        return f"{self.child.pretty()}.{self.name}"


class CreateNamedStruct(Expression):
    """named_struct(name1, val1, ...) (reference GpuCreateNamedStruct).
    Builds a device struct directly from the evaluated child columns when
    they are device-resident — no host materialization."""

    def __init__(self, names: Sequence[str], values: Sequence[Expression]):
        self.names = list(names)
        self.children = tuple(values)

    @property
    def dtype(self) -> DataType:
        return StructType([StructField(n, c.dtype, c.nullable)
                           for n, c in zip(self.names, self.children)])

    def _rows(self, cols, n):
        return [{nm: col[i] for nm, col in zip(self.names, cols)}
                for i in range(n)]

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import jax.numpy as jnp
        n = batch.num_rows
        evaled = [c.eval_tpu(batch, ctx) for c in self.children]
        kids = []
        device_ok = True
        for e, c in zip(evaled, self.children):
            if isinstance(e, TpuScalar):
                e = TpuColumnVector.from_scalar(e.value, c.dtype, n,
                                                capacity=batch.capacity)
            if getattr(e, "host_data", None) is not None:
                device_ok = False
                break
            kids.append(e)
        if device_ok and kids:
            cap = max(k.capacity for k in kids)
            from ..columnar.batch import _repad
            kids = [_repad(k, cap) if k.capacity < cap else k for k in kids]
            return TpuColumnVector(self.dtype, jnp.zeros((0,), jnp.int8),
                                   None, n, children=kids)
        cols = [_pylist_of(None, batch, ctx, c, n) for c in self.children]
        return _result_from_pylist(self._rows(cols, n), self.dtype, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        n = table.num_rows
        cols = []
        for c in self.children:
            r = c.eval_cpu(table, ctx)
            cols.append(r.to_pylist() if isinstance(r, (pa.Array, pa.ChunkedArray))
                        else [r] * n)
        return pa.array(self._rows(cols, n), type=type_to_arrow(self.dtype))

    def pretty(self) -> str:
        parts = [f"{n}={c.pretty()}" for n, c in zip(self.names, self.children)]
        return f"named_struct({', '.join(parts)})"
