"""String expressions (reference stringFunctions.scala, 2433 LoC).

TPU strategy (SURVEY.md §7 "Variable-width strings in XLA"): columns live on
device as Arrow offset+byte arrays, and the hot ops run there as compositions
of the ragged kernels in kernels/strings.py — byte→row maps, segment
reductions, and static-capacity ragged gathers. Byte-oriented ops (concat,
replace, repeat, substring_index, contains/starts/ends) are UTF-8 safe and run
on device unconditionally; character-oriented ops (substring, pad, locate,
initcap, reverse, trim, like, case mapping) take the device path when the
column is pure ASCII (one scalar device reduction gates this — chars == bytes)
and fall back to the host Arrow path for non-ASCII, the same pricing the
reference applies to locale-sensitive ops via incompat tags.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..types import BooleanT, DataType, IntegerT, StringT
from ..columnar.vector import (TpuColumnVector, TpuScalar, bucket_capacity,
                               row_mask)
from ..kernels import strings as SK
from .base import (Expression, UnaryExpression, _DEFAULT_CTX, combine_validity,
                   make_column)


def _to_arrow_side(x, batch):
    """Column-or-scalar device value → arrow array/py value (host hop)."""
    import pyarrow as pa
    if isinstance(x, TpuScalar):
        return x.value
    return x.to_arrow()


def _bool_result_from_arrow(arr, batch):
    import pyarrow.compute as pc
    import pyarrow as pa
    n = batch.num_rows
    vals = np.asarray(pc.fill_null(arr, False).to_numpy(zero_copy_only=False)).astype(bool)
    nulls = np.asarray(pc.is_null(arr).to_numpy(zero_copy_only=False)).astype(bool)
    return TpuColumnVector.from_numpy(BooleanT, vals, ~nulls if nulls.any() else None,
                                      capacity=batch.capacity)


def _string_result_from_arrow(arr, batch):
    col = TpuColumnVector.from_arrow(arr)
    # align row capacity with the batch
    if col.capacity != batch.capacity:
        from ..columnar.batch import _repad
        col = _repad(col, batch.capacity)
    return col


# ---------------------------------------------------------------------------
# device-path helpers
# ---------------------------------------------------------------------------

def _dev_str(x) -> bool:
    """Value has a device string layout the kernels can consume."""
    return (isinstance(x, TpuColumnVector) and x.offsets is not None
            and x.host_data is None)


def _ascii_dev(x) -> bool:
    """Device layout AND pure-ASCII bytes (char ops can use byte positions)."""
    return _dev_str(x) and SK.is_ascii(x.data)


def _sl(c: TpuColumnVector):
    """(starts, byte lengths) over the column's full capacity."""
    return SK.starts_lengths(c.offsets)


def _str_col(batch, data, offsets, validity, template: TpuColumnVector
             ) -> TpuColumnVector:
    return TpuColumnVector(StringT, data, validity, batch.num_rows,
                           offsets=offsets)


def _scalar_to_col(x, batch) -> TpuColumnVector:
    """Materialize a string scalar as a device column at batch capacity."""
    return TpuColumnVector.from_scalar(x.value, StringT, batch.num_rows,
                                       capacity=batch.capacity)


def _pat_bytes(s: str) -> np.ndarray:
    return np.frombuffer(s.encode("utf-8"), dtype=np.uint8)


def string_compare(cmp_expr, l, r, batch):
    """Lexicographic (UTF-8 byte order, matching Spark) comparison. Host-assisted."""
    import pyarrow.compute as pc
    la = _to_arrow_side(l, batch)
    ra = _to_arrow_side(r, batch)
    out = cmp_expr._arrow_cmp(pc, la, ra)
    return _bool_result_from_arrow(out, batch)


class Length(UnaryExpression):
    """char_length: number of UTF-8 *characters* (not bytes), like Spark.
    Device: count non-continuation bytes ((b & 0xC0) != 0x80) per row via a
    segment reduction over the byte array."""

    @property
    def dtype(self) -> DataType:
        return IntegerT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            v = None if c.value is None else len(c.value)
            return TpuScalar(IntegerT, v)
        cap = batch.capacity
        # char counts: map each byte to its row via searchsorted on offsets, then
        # segment-sum of "is not continuation byte"
        nbytes = c.data.shape[0]
        is_start = ((c.data & 0xC0) != 0x80).astype(jnp.int32)
        byte_row = jnp.searchsorted(c.offsets[1:], jnp.arange(nbytes), side="right")
        counts = jnp.zeros((cap,), jnp.int32).at[byte_row].add(
            is_start, mode="drop")
        # rows past the last offset contribute to out-of-range (dropped)
        valid = combine_validity(cap, c.validity, row_mask(batch.num_rows, cap))
        return make_column(IntegerT, counts, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.utf8_length(self.child.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"length({self.child.pretty()})"


class Upper(UnaryExpression):
    """ASCII uppercase on device; full-unicode via host when non-ASCII present
    (Spark is locale-independent unicode; reference marks case ops incompat for
    some locales too)."""

    @property
    def dtype(self) -> DataType:
        return StringT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            return TpuScalar(StringT, None if c.value is None else c.value.upper())
        if _ascii_dev(c):
            lower = (c.data >= ord('a')) & (c.data <= ord('z'))
            data = jnp.where(lower, c.data - 32, c.data)
            return TpuColumnVector(StringT, data, c.validity, c.num_rows,
                                   offsets=c.offsets)
        import pyarrow.compute as pc
        return _string_result_from_arrow(pc.utf8_upper(c.to_arrow()), batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.utf8_upper(self.child.eval_cpu(table, ctx))


class Lower(UnaryExpression):
    @property
    def dtype(self) -> DataType:
        return StringT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            return TpuScalar(StringT, None if c.value is None else c.value.lower())
        if _ascii_dev(c):
            upper = (c.data >= ord('A')) & (c.data <= ord('Z'))
            data = jnp.where(upper, c.data + 32, c.data)
            return TpuColumnVector(StringT, data, c.validity, c.num_rows,
                                   offsets=c.offsets)
        import pyarrow.compute as pc
        return _string_result_from_arrow(pc.utf8_lower(c.to_arrow()), batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.utf8_lower(self.child.eval_cpu(table, ctx))


class _ScalarPatternPredicate(Expression):
    """Base for startswith/endswith/contains against a literal pattern."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def dtype(self) -> DataType:
        return BooleanT

    def _pattern(self, ctx):
        from .base import Literal
        r = self.children[1]
        if isinstance(r, Literal):
            return r.value
        return None


class StartsWith(_ScalarPatternPredicate):
    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.children[0].eval_tpu(batch, ctx)
        pat = self._pattern(ctx)
        cap = batch.capacity
        if _dev_str(c) and pat is not None:
            pb = _pat_bytes(pat)
            plen = len(pb)
            starts = c.offsets[:-1]
            lens = c.offsets[1:] - starts
            if plen == 0:
                data = jnp.ones((cap,), jnp.bool_)
            else:
                # gather a plen-wide window at each row start (clamped), compare
                idx = jnp.clip(starts[:, None] + jnp.arange(plen)[None, :],
                               0, max(int(c.data.shape[0]) - 1, 0))
                window = jnp.take(c.data, idx)
                match = jnp.all(window == jnp.asarray(pb)[None, :], axis=1)
                data = match & (lens >= plen)
            valid = combine_validity(cap, c.validity, row_mask(batch.num_rows, cap))
            return make_column(BooleanT, data, valid, batch.num_rows)
        import pyarrow.compute as pc
        la = _to_arrow_side(c, batch)
        ra = _to_arrow_side(self.children[1].eval_tpu(batch, ctx), batch)
        return _bool_result_from_arrow(pc.starts_with(la, pattern=ra), batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        from .base import Literal
        l = self.children[0].eval_cpu(table, ctx)
        pat = self._pattern(ctx)
        if pat is None:
            raise NotImplementedError("startswith with non-literal pattern")
        return pc.starts_with(l, pattern=pat)

    def pretty(self) -> str:
        return f"startswith({self.children[0].pretty()}, {self.children[1].pretty()})"


class EndsWith(_ScalarPatternPredicate):
    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.children[0].eval_tpu(batch, ctx)
        pat = self._pattern(ctx)
        cap = batch.capacity
        if _dev_str(c) and pat is not None:
            pb = _pat_bytes(pat)
            plen = len(pb)
            ends = c.offsets[1:]
            lens = ends - c.offsets[:-1]
            if plen == 0:
                data = jnp.ones((cap,), jnp.bool_)
            else:
                idx = jnp.clip(ends[:, None] - plen + jnp.arange(plen)[None, :],
                               0, max(int(c.data.shape[0]) - 1, 0))
                window = jnp.take(c.data, idx)
                match = jnp.all(window == jnp.asarray(pb)[None, :], axis=1)
                data = match & (lens >= plen)
            valid = combine_validity(cap, c.validity, row_mask(batch.num_rows, cap))
            return make_column(BooleanT, data, valid, batch.num_rows)
        import pyarrow.compute as pc
        la = _to_arrow_side(c, batch)
        ra = _to_arrow_side(self.children[1].eval_tpu(batch, ctx), batch)
        return _bool_result_from_arrow(pc.ends_with(la, pattern=ra), batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        pat = self._pattern(ctx)
        if pat is None:
            raise NotImplementedError("endswith with non-literal pattern")
        return pc.ends_with(self.children[0].eval_cpu(table, ctx), pattern=pat)


class Contains(_ScalarPatternPredicate):
    """contains(str, literal): device sliding-window match + per-row any
    (byte matching of well-formed UTF-8 substrings is char-safe)."""

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.children[0].eval_tpu(batch, ctx)
        pat = self._pattern(ctx)
        cap = batch.capacity
        if _dev_str(c) and pat is not None:
            pb = _pat_bytes(pat)
            if len(pb) == 0:
                data = jnp.ones((cap,), jnp.bool_)
            else:
                first = SK.first_match(c.data, c.offsets, pb)
                data = first >= 0
            valid = combine_validity(cap, c.validity, row_mask(batch.num_rows, cap))
            return make_column(BooleanT, data, valid, batch.num_rows)
        import pyarrow.compute as pc
        la = _to_arrow_side(c, batch)
        return _bool_result_from_arrow(pc.match_substring(la, pattern=pat), batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        pat = self._pattern(ctx)
        if pat is None:
            raise NotImplementedError("contains with non-literal pattern")
        return pc.match_substring(self.children[0].eval_cpu(table, ctx), pattern=pat)


class Substring(Expression):
    """substring(str, pos, len) with Spark 1-based/negative-pos semantics.
    Device for ASCII columns (chars == bytes): clamp per-row ranges + one
    ragged gather. Non-ASCII falls back to the host Arrow slice."""

    def __init__(self, child: Expression, pos: Expression, length: Expression):
        self.children = (child, pos, length)

    @property
    def dtype(self) -> DataType:
        return StringT

    def _literals(self):
        from .base import Literal
        pos = self.children[1].value if isinstance(self.children[1], Literal) else None
        ln = self.children[2].value if isinstance(self.children[2], Literal) else None
        return pos, ln

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        s = self.children[0].eval_cpu(table, ctx)
        pos, ln = self._literals()
        if pos is None or ln is None:
            raise NotImplementedError("substring with non-literal pos/len")
        return self._cpu_on_arrow(s, ctx)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.children[0].eval_tpu(batch, ctx)
        pos, ln = self._literals()
        if _ascii_dev(c) and pos is not None and ln is not None:
            starts, lens = _sl(c)
            pos_i, ln_i = int(pos), int(ln)
            if pos_i > 0:
                s0 = jnp.full_like(lens, pos_i - 1)
            elif pos_i == 0:
                s0 = jnp.zeros_like(lens)
            else:
                s0 = lens + pos_i
            e0 = s0 + max(ln_i, 0)
            s_c = jnp.clip(s0, 0, lens)
            e_c = jnp.clip(e0, 0, lens)
            out, offs = SK.build_ranges(c.data, starts + s_c, e_c - s_c,
                                        int(c.data.shape[0]) or 1)
            return _str_col(batch, out, offs, c.validity, c)
        arr = _to_arrow_side(c, batch)
        out = self._cpu_on_arrow(arr, ctx)
        return _string_result_from_arrow(out, batch)

    def _cpu_on_arrow(self, arr, ctx):
        import pyarrow.compute as pc
        pos = self.children[1].value
        ln = self.children[2].value
        start = pos - 1 if pos > 0 else (0 if pos == 0 else pos)
        if start >= 0:
            return pc.utf8_slice_codeunits(arr, start=start, stop=start + max(ln, 0))
        stop = start + ln if start + ln < 0 else np.iinfo(np.int32).max
        return pc.utf8_slice_codeunits(arr, start=start, stop=stop)

    def pretty(self) -> str:
        c = self.children
        return f"substring({c[0].pretty()}, {c[1].pretty()}, {c[2].pretty()})"


class ConcatStr(Expression):
    """concat(...) for strings: null if any input null (Spark concat
    semantics). Device: one multi-source ragged gather (UTF-8 safe)."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def dtype(self) -> DataType:
        return StringT

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        args = [c.eval_cpu(table, ctx) for c in self.children]
        return pc.binary_join_element_wise(*args, "",
                                           null_handling="emit_null")

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        vals = [c.eval_tpu(batch, ctx) for c in self.children]
        cols = []
        for v in vals:
            if isinstance(v, TpuScalar):
                cols.append(_scalar_to_col(v, batch))
            else:
                cols.append(v)
        if all(_dev_str(c) for c in cols):
            cap = batch.capacity
            parts, validity = [], None
            out_cap = 0
            for c in cols:
                starts, lens = _sl(c)
                parts.append((c.data, starts, lens))
                out_cap += int(c.data.shape[0])
                validity = combine_validity(cap, validity, c.validity)
            out, offs = SK.concat_columns(parts, bucket_capacity(out_cap))
            valid = combine_validity(cap, validity,
                                     row_mask(batch.num_rows, cap))
            return _str_col(batch, out, offs, valid, cols[0])
        import pyarrow.compute as pc
        args = [_to_arrow_side(v, batch) for v in vals]
        out = pc.binary_join_element_wise(*args, "", null_handling="emit_null")
        return _string_result_from_arrow(out, batch)

    def pretty(self) -> str:
        return f"concat({', '.join(c.pretty() for c in self.children)})"


class _TrimBase(UnaryExpression):
    """trim family: per-row first/last non-whitespace via segment min/max,
    then one ragged gather. ASCII device path; unicode whitespace via host."""

    trim_left = True
    trim_right = True
    _pc_fn = ""
    # ASCII whitespace, matching Arrow's trim_whitespace on ASCII input
    _WS = np.array([9, 10, 11, 12, 13, 32], dtype=np.uint8)

    @property
    def dtype(self) -> DataType:
        return StringT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            import pyarrow as pa
            v = getattr(pc, self._pc_fn)(pa.array([c.value]))[0].as_py() \
                if c.value is not None else None
            return TpuScalar(StringT, v)
        if _ascii_dev(c):
            starts, lens = _sl(c)
            nbytes = int(c.data.shape[0])
            if nbytes == 0:
                return c
            is_ws = jnp.isin(c.data, jnp.asarray(self._WS))
            rows = SK.byte_rows(c.offsets, nbytes)
            pos_in_row = jnp.arange(nbytes, dtype=jnp.int32) - c.offsets[rows]
            n = int(starts.shape[0])
            nonws_pos = jnp.where(~is_ws, pos_in_row, SK._BIG)
            first = SK.segment_min(nonws_pos, rows, n)
            last = SK.segment_max(jnp.where(~is_ws, pos_in_row, -1), rows, n)
            has = last >= 0
            if self.trim_left:
                lead = jnp.where(has, first, lens)  # all-ws → empty
            else:
                lead = jnp.zeros_like(lens)
            if self.trim_right:
                end = jnp.where(has, last + 1, lead)
            else:
                end = lens
            out, offs = SK.build_ranges(c.data, starts + lead, end - lead,
                                        nbytes)
            return _str_col(batch, out, offs, c.validity, c)
        return _string_result_from_arrow(getattr(pc, self._pc_fn)(c.to_arrow()),
                                         batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return getattr(pc, self._pc_fn)(self.child.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"{type(self).__name__.lower()}({self.child.pretty()})"


class Trim(_TrimBase):
    _pc_fn = "utf8_trim_whitespace"


class LTrim(_TrimBase):
    trim_right = False
    _pc_fn = "utf8_ltrim_whitespace"


class RTrim(_TrimBase):
    trim_left = False
    _pc_fn = "utf8_rtrim_whitespace"


class Reverse(UnaryExpression):
    """reverse(str): ASCII device via a stride(-1) ragged gather; unicode
    (char-level reversal) via host."""

    @property
    def dtype(self) -> DataType:
        return StringT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            return TpuScalar(StringT, None if c.value is None else c.value[::-1])
        if _ascii_dev(c):
            starts, lens = _sl(c)
            stride = jnp.full_like(starts, -1)
            out, offs = SK.build_ranges(c.data, starts + lens - 1, lens,
                                        int(c.data.shape[0]) or 1,
                                        stride=stride)
            return _str_col(batch, out, offs, c.validity, c)
        return _string_result_from_arrow(pc.utf8_reverse(c.to_arrow()), batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.utf8_reverse(self.child.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"reverse({self.child.pretty()})"


class InitCap(UnaryExpression):
    """Spark initcap: capitalize first letter of each space-separated word,
    lowercase the rest. ASCII device: word-start mask + case map."""

    @property
    def dtype(self) -> DataType:
        return StringT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            v = None if c.value is None else self._initcap_list([c.value])[0]
            return TpuScalar(StringT, v)
        if _ascii_dev(c):
            nbytes = int(c.data.shape[0])
            if nbytes == 0:
                return c
            # offsets == nbytes (empty/padding rows) fall out of range and drop
            row_start = jnp.zeros((nbytes,), jnp.bool_).at[
                c.offsets[:-1]].set(True, mode="drop")
            prev = jnp.concatenate([jnp.zeros((1,), c.data.dtype), c.data[:-1]])
            word_start = row_start | (prev == 32)
            b = c.data
            is_lower = (b >= ord('a')) & (b <= ord('z'))
            is_upper = (b >= ord('A')) & (b <= ord('Z'))
            out = jnp.where(word_start & is_lower, b - 32,
                            jnp.where(~word_start & is_upper, b + 32, b))
            return TpuColumnVector(StringT, out, c.validity, c.num_rows,
                                   offsets=c.offsets)
        arr = _to_arrow_side(c, batch)
        out = pa.array(self._initcap_list(arr.to_pylist()), pa.string())
        return _string_result_from_arrow(out, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.child.eval_cpu(table, ctx).to_pylist()
        return pa.array(self._initcap_list(vals), pa.string())

    @staticmethod
    def _initcap_list(vals):
        out = []
        for v in vals:
            if v is None:
                out.append(None)
                continue
            out.append(" ".join(w[:1].upper() + w[1:].lower() if w else w
                                for w in v.split(" ")))
        return out

    def pretty(self) -> str:
        return f"initcap({self.child.pretty()})"


class StringRepeat(Expression):
    """repeat(str, n): device byte tiling (UTF-8 safe)."""

    def __init__(self, child: Expression, times: Expression):
        self.children = (child, times)

    @property
    def dtype(self) -> DataType:
        return StringT

    def _times(self):
        from .base import Literal
        t = self.children[1]
        return t.value if isinstance(t, Literal) else None

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.children[0].eval_cpu(table, ctx).to_pylist()
        n = self._times()
        n = 1 if n is None else n
        return pa.array([None if v is None else v * max(int(n), 0)
                         for v in vals], pa.string())

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        c = self.children[0].eval_tpu(batch, ctx)
        n = self._times()
        if _dev_str(c) and n is not None:
            n = max(int(n), 0)
            starts, lens = _sl(c)
            out_cap = bucket_capacity(int(c.data.shape[0]) * max(n, 1))
            out, offs = SK.build_repeat(c.data, starts, lens, n, out_cap)
            return _str_col(batch, out, offs, c.validity, c)
        arr = _to_arrow_side(c, batch)
        n = 1 if n is None else n
        out = pa.array([None if v is None else v * max(int(n), 0)
                        for v in arr.to_pylist()], pa.string())
        return _string_result_from_arrow(out, batch)

    def pretty(self) -> str:
        return f"repeat({self.children[0].pretty()}, {self.children[1].pretty()})"


class StringReplace(Expression):
    """replace(str, search, replace) — literal replacement. Device: greedy
    non-overlapping window matches + contribution-scatter rebuild (UTF-8 safe:
    byte matching of well-formed UTF-8 is char-aligned)."""

    def __init__(self, child: Expression, search: Expression, replace: Expression):
        self.children = (child, search, replace)

    @property
    def dtype(self) -> DataType:
        return StringT

    def _args(self):
        from .base import Literal
        s = self.children[1].value if isinstance(self.children[1], Literal) else None
        r = self.children[2].value if isinstance(self.children[2], Literal) else ""
        return s, r

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        c = self.children[0].eval_tpu(batch, ctx)
        s, r = self._args()
        if _dev_str(c) and s is not None:
            if s == "":
                return c  # Spark: empty search leaves the string unchanged
            sb, rb = _pat_bytes(s), _pat_bytes(r)
            nbytes = int(c.data.shape[0])
            if nbytes == 0:
                return c
            taken = SK.greedy_matches(c.data, c.offsets, sb)
            # bytes covered by a taken match window
            delta = jnp.zeros((nbytes + 1,), jnp.int32)
            pos = jnp.arange(nbytes, dtype=jnp.int32)
            delta = delta.at[jnp.where(taken, pos, nbytes)].add(1, mode="drop")
            delta = delta.at[jnp.where(taken, pos + len(sb),
                                       nbytes)].add(-1, mode="drop")
            covered = jnp.cumsum(delta[:-1]) > 0
            if len(rb) <= len(sb):
                out_cap = nbytes
            else:
                out_cap = bucket_capacity(
                    (nbytes // len(sb)) * len(rb) + nbytes)
            out, offs = SK.build_from_contributions(
                c.data, ~covered, c.offsets, out_cap,
                replace_at=taken, replacement=rb)
            return _str_col(batch, out, offs, c.validity, c)
        arr = _to_arrow_side(c, batch)
        out = pc.replace_substring(arr, pattern=s, replacement=r)
        return _string_result_from_arrow(out, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        s, r = self._args()
        if s == "":
            return self.children[0].eval_cpu(table, ctx)
        return pc.replace_substring(self.children[0].eval_cpu(table, ctx),
                                    pattern=s, replacement=r)

    def pretty(self) -> str:
        c = self.children
        return f"replace({c[0].pretty()}, {c[1].pretty()}, {c[2].pretty()})"


class StringLocate(Expression):
    """locate(substr, str[, pos]) — 1-based, 0 when absent (instr = pos 1).
    ASCII device via first_match; non-ASCII host (char positions)."""

    def __init__(self, substr: Expression, child: Expression,
                 pos: Optional[Expression] = None):
        from .base import Literal
        self.children = (substr, child, pos if pos is not None else Literal(1))

    @property
    def dtype(self) -> DataType:
        from ..types import IntegerT
        return IntegerT

    def _compute_list(self, subs, vals, start):
        out = []
        for v in vals:
            if v is None or subs is None:
                out.append(None)
            elif start < 1:
                out.append(0)
            else:
                out.append(v.find(subs, start - 1) + 1)
        return out

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        from .base import Literal
        subs = self.children[0].value if isinstance(self.children[0], Literal) else None
        vals = self.children[1].eval_cpu(table, ctx).to_pylist()
        start = self.children[2].value if isinstance(self.children[2], Literal) else 1
        return pa.array(self._compute_list(subs, vals, start), pa.int32())

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        from .base import Literal
        from ..columnar.batch import _repad
        subs = self.children[0].value if isinstance(self.children[0], Literal) else None
        c = self.children[1].eval_tpu(batch, ctx)
        start = self.children[2].value if isinstance(self.children[2], Literal) else 1
        cap = batch.capacity
        if _ascii_dev(c) and subs is not None and subs.isascii():
            valid = combine_validity(cap, c.validity,
                                     row_mask(batch.num_rows, cap))
            if start < 1:
                data = jnp.zeros((cap,), jnp.int32)
            elif subs == "":
                # python find("", k): k when k <= len else -1
                _, lens = _sl(c)
                data = jnp.where(start - 1 <= lens, start, 0).astype(jnp.int32)
            else:
                from_pos = jnp.full((c.capacity,), start - 1, jnp.int32)
                first = SK.first_match(c.data, c.offsets, _pat_bytes(subs),
                                       from_pos=from_pos)
                data = first + 1
            return make_column(IntegerT, data, valid, batch.num_rows)
        arr = _to_arrow_side(c, batch)
        out = pa.array(self._compute_list(subs, arr.to_pylist(), start), pa.int32())
        col = TpuColumnVector.from_arrow(out)
        if col.capacity != batch.capacity:
            col = _repad(col, batch.capacity)
        return col

    def pretty(self) -> str:
        return f"locate({self.children[0].pretty()}, {self.children[1].pretty()})"


class _PadBase(Expression):
    left_side = True

    def __init__(self, child: Expression, length: Expression, pad: Expression):
        self.children = (child, length, pad)

    @property
    def dtype(self) -> DataType:
        return StringT

    def _literals(self):
        from .base import Literal
        n = self.children[1].value if isinstance(self.children[1], Literal) else None
        pad = self.children[2].value if isinstance(self.children[2], Literal) else None
        return n, pad

    def _compute_list(self, vals, n, pad):
        out = []
        for v in vals:
            if v is None:
                out.append(None)
            elif len(v) >= n:
                out.append(v[:n])  # Spark truncates to length
            elif not pad:
                out.append(v)
            else:
                fill = (pad * n)[: n - len(v)]
                out.append(fill + v if self.left_side else v + fill)
        return out

    def _eval(self, arr, ctx):
        import pyarrow as pa
        n, pad = self._literals()
        n = 0 if n is None else int(n)
        pad = " " if pad is None else pad
        return pa.array(self._compute_list(arr.to_pylist(), n, pad),
                        pa.string())

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        return self._eval(self.children[0].eval_cpu(table, ctx), ctx)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.children[0].eval_tpu(batch, ctx)
        n, pad = self._literals()
        if (_ascii_dev(c) and n is not None and pad is not None
                and pad.isascii()):
            n = max(int(n), 0)
            starts, lens = _sl(c)
            out_cap = bucket_capacity(max(int(c.data.shape[0]),
                                          int(c.capacity) * n))
            out, offs = SK.build_pad(c.data, starts, lens, n,
                                     _pat_bytes(pad), self.left_side, out_cap,
                                     active=row_mask(batch.num_rows,
                                                     c.capacity))
            # Spark: null rows stay null; pad fills even empty non-null rows
            return _str_col(batch, out, offs, c.validity, c)
        arr = _to_arrow_side(c, batch)
        return _string_result_from_arrow(self._eval(arr, ctx), batch)


class LPad(_PadBase):
    left_side = True


class RPad(_PadBase):
    left_side = False


class StringTranslate(Expression):
    """translate(str, from, to) — per-char mapping (reference GpuTranslate).
    ASCII device: a 256-entry LUT + contribution rebuild (deletions shrink)."""

    def __init__(self, child: Expression, from_str: Expression, to_str: Expression):
        self.children = (child, from_str, to_str)

    @property
    def dtype(self) -> DataType:
        return StringT

    def _table(self):
        from .base import Literal
        f = self.children[1].value if isinstance(self.children[1], Literal) else ""
        t = self.children[2].value if isinstance(self.children[2], Literal) else ""
        m = {}
        for i, ch in enumerate(f):
            if ch not in m:
                m[ch] = t[i] if i < len(t) else None  # None = delete
        return m

    def _compute_list(self, vals):
        m = self._table()
        out = []
        for v in vals:
            if v is None:
                out.append(None)
            else:
                out.append("".join(m.get(ch, ch) for ch in v
                                   if m.get(ch, ch) is not None))
        return out

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.children[0].eval_cpu(table, ctx).to_pylist()
        return pa.array(self._compute_list(vals), pa.string())

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        c = self.children[0].eval_tpu(batch, ctx)
        m = self._table()
        table_ascii = all(ord(k) < 128 and (v is None or (len(v) == 1 and ord(v) < 128))
                          for k, v in m.items())
        if _ascii_dev(c) and table_ascii:
            nbytes = int(c.data.shape[0])
            if nbytes == 0:
                return c
            lut = np.arange(256, dtype=np.uint8)
            drop = np.zeros(256, dtype=bool)
            for k, v in m.items():
                if v is None:
                    drop[ord(k)] = True
                else:
                    lut[ord(k)] = ord(v)
            mapped = jnp.asarray(lut)[c.data]
            keep = ~jnp.asarray(drop)[c.data]
            out, offs = SK.build_from_contributions(c.data, keep, c.offsets,
                                                    nbytes, mapped=mapped)
            return _str_col(batch, out, offs, c.validity, c)
        arr = _to_arrow_side(c, batch)
        out = pa.array(self._compute_list(arr.to_pylist()), pa.string())
        return _string_result_from_arrow(out, batch)


# ---------------------------------------------------------------------------
# String breadth 2 (reference stringFunctions.scala: GpuConcatWs,
# GpuStringSplit, GpuSubstringIndex, GpuOctetLength, GpuBitLength,
# GpuFormatNumber, GpuConv, GpuStringToMap)
# ---------------------------------------------------------------------------

def _rows_of(x, n):
    """Arrow array / scalar → python list of length n."""
    import pyarrow as pa
    if isinstance(x, pa.ChunkedArray):
        x = x.combine_chunks()
    if isinstance(x, pa.Array):
        return x.to_pylist()
    return [x] * n


class _HostRowOp(Expression):
    """Host-assisted op computed row-wise over python values (the pattern the
    reference prices as incompat/host; Pallas ragged kernels are the upgrade
    path). Subclasses define _row(vals...) and _out_arrow_type()."""

    def _out_arrow_type(self):
        from ..types import to_arrow
        return to_arrow(self.dtype)

    def _num_rows_cpu(self, table):
        return table.num_rows

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        n = self._num_rows_cpu(table)
        ins = [_rows_of(c.eval_cpu(table, ctx), n) for c in self.children]
        return pa.array([self._row(*vals, ctx=ctx) for vals in zip(*ins)],
                        type=self._out_arrow_type())

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        from ..columnar.vector import TpuScalar
        n = batch.num_rows
        ins = []
        for c in self.children:
            v = c.eval_tpu(batch, ctx)
            ins.append([v.value] * n if isinstance(v, TpuScalar)
                       else v.to_arrow().to_pylist())
        out = pa.array([self._row(*vals, ctx=ctx) for vals in zip(*ins)],
                       type=self._out_arrow_type())
        col = TpuColumnVector.from_arrow(out)
        if col.capacity < batch.capacity:
            from ..columnar.batch import _repad
            col = _repad(col, batch.capacity)
        return col

    def _row(self, *vals, ctx):
        raise NotImplementedError


class ConcatWs(Expression):
    """concat_ws(sep, cols...): skips nulls; array<string> args are flattened;
    null only when sep is null (reference GpuConcatWs). Device when sep is a
    literal and all args are plain string columns."""

    def __init__(self, sep: Expression, *cols: Expression):
        self.children = (sep,) + tuple(cols)

    @property
    def dtype(self) -> DataType:
        return StringT

    @property
    def nullable(self) -> bool:
        return self.children[0].nullable

    def _join(self, sep, parts):
        if sep is None:
            return None
        flat = []
        for p in parts:
            if p is None:
                continue
            if isinstance(p, list):
                flat.extend(x for x in p if x is not None)
            else:
                flat.append(p)
        return sep.join(flat)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        n = table.num_rows
        ins = [_rows_of(c.eval_cpu(table, ctx), n) for c in self.children]
        return pa.array([self._join(vals[0], vals[1:]) for vals in zip(*ins)],
                        type=pa.string())

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        from .base import Literal
        from ..columnar.vector import TpuScalar
        from ..types import StringType
        sep_e = self.children[0]
        sep = sep_e.value if isinstance(sep_e, Literal) else None
        args = self.children[1:]
        vals = None
        if (sep is not None and args
                and all(isinstance(a.dtype, StringType) for a in args)):
            vals = [a.eval_tpu(batch, ctx) for a in args]
            cols = [(_scalar_to_col(v, batch) if isinstance(v, TpuScalar)
                     else v) for v in vals]
            if all(_dev_str(c) for c in cols):
                cap = batch.capacity
                sep_b = _pat_bytes(sep)
                parts, emits, seps = [], [], []
                any_before = jnp.zeros((cap,), jnp.bool_)
                out_cap = 0
                logical = row_mask(batch.num_rows, cap)
                for i, c in enumerate(cols):
                    starts, lens = _sl(c)
                    parts.append((c.data, starts, lens))
                    nonnull = (c.validity if c.validity is not None else
                               jnp.ones((cap,), jnp.bool_)) & logical
                    emits.append(nonnull)
                    if i == 0:
                        seps.append(None)
                    else:
                        seps.append((sep_b, nonnull & any_before))
                    any_before = any_before | nonnull
                    out_cap += int(c.data.shape[0]) + len(sep_b) * int(cap)
                out, offs = SK.concat_columns(parts, bucket_capacity(out_cap),
                                              part_emit=emits, seps=seps)
                valid = combine_validity(cap, None,
                                         row_mask(batch.num_rows, cap))
                return _str_col(batch, out, offs, valid, cols[0])
        n = batch.num_rows
        sep_v = sep_e.eval_tpu(batch, ctx)
        ins = [[sep_v.value] * n if isinstance(sep_v, TpuScalar)
               else sep_v.to_arrow().to_pylist()]
        if vals is None:  # device gate failed before evaluating the args
            vals = [a.eval_tpu(batch, ctx) for a in args]
        for v in vals:
            ins.append([v.value] * n if isinstance(v, TpuScalar)
                       else v.to_arrow().to_pylist())
        out = pa.array([self._join(r[0], r[1:]) for r in zip(*ins)],
                       type=pa.string())
        return _string_result_from_arrow(out, batch)

    def pretty(self) -> str:
        return f"concat_ws({', '.join(c.pretty() for c in self.children)})"


class StringSplit(_HostRowOp):
    """split(str, javaRegex, limit) → array<string> (reference GpuStringSplit;
    Java split semantics: limit=-1 keeps trailing empties, limit>0 caps parts)."""

    def __init__(self, child: Expression, pattern: Expression,
                 limit: Expression = None):
        from .base import Literal
        if limit is None:
            limit = Literal(-1)
        self.children = (child, pattern, limit)
        pat = pattern.value if isinstance(pattern, Literal) else None
        from .regex import transpile
        self._pat = None if pat is None else transpile(pat)

    tpu_supported = True

    @property
    def dtype(self) -> DataType:
        from ..types import ArrayType
        return ArrayType(StringT, contains_null=False)

    def _row(self, s, pat, limit, ctx):
        import re as _re2
        if s is None or pat is None:
            return None
        p = self._pat if self._pat is not None else pat
        if limit is None:
            limit = -1
        if limit == 1:
            # Java split(re, 1) = the whole string; python maxsplit=0
            # means UNLIMITED, so it cannot express this case
            return [s]
        if limit > 0:
            return _re2.split(p, s, maxsplit=limit - 1)
        parts = _re2.split(p, s)
        if limit == 0:  # Java: drop trailing empty strings
            while parts and parts[-1] == "":
                parts.pop()
        return parts

    @staticmethod
    def _literal_delim(pat):
        """The single utf-8 byte a trivial Java regex denotes, or None."""
        meta = set("\\^$.|?*+()[]{}")
        if pat is None:
            return None
        if len(pat) == 1 and pat not in meta:
            lit = pat
        elif len(pat) == 2 and pat[0] == "\\" and pat[1] in meta:
            lit = pat[1]
        else:
            return None
        b = lit.encode("utf-8")
        return b[0] if len(b) == 1 else None

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        """Device split for single-byte literal delimiters: delimiter scan +
        two ragged gathers over the HBM byte buffer; the parts column is a
        string child sharing one materialized chars buffer (reference
        GpuStringSplit on cuDF's split_record). Regex patterns, multi-byte
        delimiters, and limit=0 (trailing-empty trim) take the host path."""
        from .base import Literal, to_column
        from ..columnar.vector import bucket_capacity, row_mask
        from ..kernels.strings import gather_plan
        from ..types import ArrayType, StringT
        child, pattern, limit = self.children
        lit = pattern.value if isinstance(pattern, Literal) else None
        lim = limit.value if isinstance(limit, Literal) else None
        delim = self._literal_delim(lit)
        if delim is None or lim is None or lim == 0:
            return super().eval_tpu(batch, ctx)
        col = to_column(child.eval_tpu(batch, ctx), batch, child.dtype)
        if col.host_data is not None or col.offsets is None:
            return super().eval_tpu(batch, ctx)
        n, cap = batch.num_rows, batch.capacity
        offs = col.offsets.astype(jnp.int32)
        starts, ends = offs[:-1], offs[1:]
        data = col.data
        n_chars = int(offs[n]) if n else 0
        char_cap = max(int(data.shape[0]), 1)
        valid = col.validity if col.validity is not None \
            else row_mask(n, cap)
        is_delim = (data == jnp.uint8(delim)) \
            & (jnp.arange(char_cap) < n_chars)
        prefix = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                  jnp.cumsum(is_delim.astype(jnp.int32))])
        cnt = prefix[ends] - prefix[starts]
        if lim > 0:
            cnt = jnp.minimum(cnt, lim - 1)
        parts = jnp.where(valid, cnt + 1, 0)
        list_offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                     jnp.cumsum(parts, dtype=jnp.int32)])
        total = int(list_offs[n]) if n else 0
        pcap = bucket_capacity(total)
        P = jnp.where(is_delim, size=char_cap,
                      fill_value=char_cap - 1)[0].astype(jnp.int32)
        d0 = prefix[starts]
        j = jnp.arange(pcap, dtype=jnp.int32)
        row_j = jnp.clip(jnp.searchsorted(list_offs[1:cap + 1], j,
                                          side="right"),
                         0, max(cap - 1, 0)).astype(jnp.int32)
        k = j - list_offs[row_j]
        pmax = max(char_cap - 1, 0)
        pstart = jnp.where(k == 0, starts[row_j],
                           P[jnp.clip(d0[row_j] + k - 1, 0, pmax)] + 1)
        pend = jnp.where(k == cnt[row_j], ends[row_j],
                         P[jnp.clip(d0[row_j] + k, 0, pmax)])
        in_part = j < total
        plen = jnp.where(in_part, jnp.maximum(pend - pstart, 0), 0)
        src, in_range, child_offs = gather_plan(pstart, plen, char_cap)
        chars = jnp.where(in_range,
                          data[jnp.clip(src, 0, char_cap - 1)],
                          jnp.zeros((), data.dtype))
        part_col = TpuColumnVector(StringT, chars, None, total,
                                   offsets=child_offs)
        return TpuColumnVector(ArrayType(StringT, contains_null=False),
                               chars, valid, n, offsets=list_offs,
                               child=part_col)

    def pretty(self) -> str:
        return f"split({self.children[0].pretty()}, {self.children[1].pretty()})"


class SubstringIndex(Expression):
    """substring_index(str, delim, count) (reference GpuSubstringIndex).
    Device via nth-match ranking (UTF-8 safe byte matching)."""

    def __init__(self, child: Expression, delim: Expression, count: Expression):
        self.children = (child, delim, count)

    @property
    def dtype(self) -> DataType:
        return StringT

    def _literals(self):
        from .base import Literal
        d = self.children[1].value if isinstance(self.children[1], Literal) else None
        cnt = self.children[2].value if isinstance(self.children[2], Literal) else None
        return d, cnt

    def _row(self, s, delim, count):
        if s is None or delim is None or count is None:
            return None
        if delim == "" or count == 0:
            return ""
        parts = s.split(delim)
        if count > 0:
            return delim.join(parts[:count])
        return delim.join(parts[count:])

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.children[0].eval_cpu(table, ctx).to_pylist()
        d, cnt = self._literals()
        return pa.array([self._row(v, d, cnt) for v in vals], pa.string())

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        c = self.children[0].eval_tpu(batch, ctx)
        d, cnt = self._literals()
        if _dev_str(c) and d is not None and cnt is not None:
            starts, lens = _sl(c)
            nbytes = int(c.data.shape[0]) or 1
            if d == "" or cnt == 0:
                out, offs = SK.build_ranges(c.data, starts,
                                            jnp.zeros_like(lens), nbytes)
                return _str_col(batch, out, offs, c.validity, c)
            db = _pat_bytes(d)
            cnt = int(cnt)
            # Spark splits on non-overlapping occurrences; split() semantics
            # and greedy left-to-right agree for counting here
            if cnt > 0:
                pos = SK.nth_match(c.data, c.offsets, db, cnt)
                new_start = starts
                new_len = jnp.where(pos >= 0, pos, lens)
            else:
                pos = SK.nth_match(c.data, c.offsets, db, cnt)
                s0 = jnp.where(pos >= 0, pos + len(db), 0)
                new_start = starts + s0
                new_len = lens - s0
            out, offs = SK.build_ranges(c.data, new_start, new_len, nbytes)
            return _str_col(batch, out, offs, c.validity, c)
        arr = _to_arrow_side(c, batch)
        out = pa.array([self._row(v, d, cnt) for v in arr.to_pylist()],
                       pa.string())
        return _string_result_from_arrow(out, batch)

    def pretty(self) -> str:
        cs = self.children
        return f"substring_index({cs[0].pretty()}, {cs[1].pretty()}, {cs[2].pretty()})"


class OctetLength(UnaryExpression):
    """octet_length: UTF-8 byte count — pure device op on the offsets buffer."""

    @property
    def dtype(self) -> DataType:
        return IntegerT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        from ..columnar.vector import TpuScalar
        if isinstance(c, TpuScalar):
            v = None if c.value is None else len(c.value.encode("utf-8"))
            return TpuScalar(IntegerT, v)
        lens = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int32)
        valid = combine_validity(c.capacity, c.validity,
                                 row_mask(batch.num_rows, c.capacity))
        return make_column(IntegerT, lens, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.binary_length(self.child.eval_cpu(table, ctx))

    def pretty(self) -> str:
        return f"octet_length({self.child.pretty()})"


class BitLength(OctetLength):
    """bit_length = octet_length * 8."""

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        r = super().eval_tpu(batch, ctx)
        from ..columnar.vector import TpuScalar
        if isinstance(r, TpuScalar):
            return TpuScalar(IntegerT, None if r.value is None else r.value * 8)
        return TpuColumnVector(IntegerT, r.data * 8, r.validity, r.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        return pc.multiply(super().eval_cpu(table, ctx), 8)

    def pretty(self) -> str:
        return f"bit_length({self.child.pretty()})"


class FormatNumber(_HostRowOp):
    """format_number(x, d): thousands separators + d decimals, HALF_EVEN like
    Java DecimalFormat (reference GpuFormatNumber)."""

    def __init__(self, child: Expression, d: Expression):
        self.children = (child, d)

    @property
    def dtype(self) -> DataType:
        return StringT

    def _row(self, x, d, ctx):
        if x is None or d is None or d < 0:
            return None
        import decimal as _dec
        if isinstance(x, float):
            if x != x or x in (float("inf"), float("-inf")):
                return None
            q = _dec.Decimal(repr(x)).quantize(
                _dec.Decimal(1).scaleb(-d), rounding=_dec.ROUND_HALF_EVEN)
        else:
            q = _dec.Decimal(x).quantize(
                _dec.Decimal(1).scaleb(-d), rounding=_dec.ROUND_HALF_EVEN)
        return f"{q:,.{d}f}"


class Conv(_HostRowOp):
    """conv(numStr, fromBase, toBase): Java NumberConverter semantics —
    unsigned 64-bit wraparound, negative toBase → signed output, leading
    digits parsed until the first invalid character (reference GpuConv)."""

    def __init__(self, child: Expression, from_base: Expression,
                 to_base: Expression):
        self.children = (child, from_base, to_base)

    @property
    def dtype(self) -> DataType:
        return StringT

    _DIGITS = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"

    def _row(self, s, fb, tb, ctx):
        if s is None or fb is None or tb is None:
            return None
        if not (2 <= abs(fb) <= 36 and 2 <= abs(tb) <= 36):
            return None
        s = s.strip()
        if not s:
            return None
        neg = s.startswith("-")
        if neg:
            s = s[1:]
        val = 0
        seen = False
        for ch in s.upper():
            d = self._DIGITS.find(ch)
            if d < 0 or d >= abs(fb):
                break
            val = (val * abs(fb) + d) & 0xFFFFFFFFFFFFFFFF
            seen = True
        if not seen:
            return "0"
        if neg:
            val = (-val) & 0xFFFFFFFFFFFFFFFF
        if tb < 0:  # signed output
            sval = val - (1 << 64) if val >= (1 << 63) else val
            sign = "-" if sval < 0 else ""
            sval = abs(sval)
            base = abs(tb)
        else:
            sign = ""
            sval = val
            base = tb
        if sval == 0:
            return "0"
        out = []
        while sval:
            out.append(self._DIGITS[sval % base])
            sval //= base
        return sign + "".join(reversed(out))


class StringToMap(_HostRowOp):
    """str_to_map(str, pairDelim=',', keyValueDelim=':')
    (reference GpuStringToMap)."""

    def __init__(self, child: Expression, pair_delim: Expression = None,
                 kv_delim: Expression = None):
        from .base import Literal
        self.children = (child,
                         pair_delim if pair_delim is not None else Literal(","),
                         kv_delim if kv_delim is not None else Literal(":"))

    @property
    def dtype(self) -> DataType:
        from ..types import MapType
        return MapType(StringT, StringT)

    def _row(self, s, pd, kd, ctx):
        import re as _re2
        if s is None or pd is None or kd is None:
            return None
        out = {}
        for pair in _re2.split(pd, s):
            kv = _re2.split(kd, pair, maxsplit=1)
            # duplicate keys: LAST_WIN (Spark's non-exception dedup policy)
            out[kv[0]] = kv[1] if len(kv) > 1 else None
        return list(out.items())


class Ascii(UnaryExpression):
    """ascii(str): code point of the first character, 0 for empty, null for
    null (reference GpuAscii). Device: gather the first byte per row (exact
    for ASCII; non-ASCII falls back to host for the full code point)."""

    @property
    def dtype(self) -> DataType:
        return IntegerT

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            v = c.value
            return TpuScalar(IntegerT, None if v is None
                             else (ord(v[0]) if v else 0))
        if _ascii_dev(c):
            starts, lens = _sl(c)
            nbytes = int(c.data.shape[0])
            if nbytes == 0:
                data = jnp.zeros((c.capacity,), jnp.int32)
            else:
                first = c.data[jnp.clip(starts, 0, nbytes - 1)].astype(jnp.int32)
                data = jnp.where(lens > 0, first, 0)
            valid = combine_validity(c.capacity, c.validity,
                                     row_mask(batch.num_rows, c.capacity))
            return make_column(IntegerT, data, valid, batch.num_rows)
        from .collections import _result_from_pylist
        arr = _to_arrow_side(c, batch)
        return _result_from_pylist([None if v is None else (ord(v[0]) if v else 0)
                                    for v in arr.to_pylist()], IntegerT, batch)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        vals = self.child.eval_cpu(table, ctx).to_pylist()
        return pa.array([None if v is None else (ord(v[0]) if v else 0)
                         for v in vals], pa.int32())

    def pretty(self) -> str:
        return f"ascii({self.child.pretty()})"


class StringInstr(StringLocate):
    """instr(str, substr) == locate(substr, str, 1) (reference GpuStringInstr)."""

    def __init__(self, child: Expression, substr: Expression):
        super().__init__(substr, child)

    def pretty(self) -> str:
        return f"instr({self.children[1].pretty()}, {self.children[0].pretty()})"
