"""Expression-layer core: the `columnar_eval` contract, binding, null helpers.

TPU re-design of the reference expression layer
(/root/reference/sql-plugin/.../GpuExpressions.scala — trait GpuExpression:113,
columnarEval:155; binding GpuBoundAttribute.scala). Each expression implements
  * eval_tpu(batch, ctx)  -> TpuColumnVector | TpuScalar   (device, jax/XLA)
  * eval_cpu(table, ctx)  -> pyarrow Array | python scalar (host fallback + parity oracle)
The planner's tagging layer (plan/meta.py) decides per-expression which path runs,
mirroring the reference's per-expression CPU fallback.

Unlike the reference (JVM objects wrapping JNI handles), evaluation here is pure:
expressions build jax computations over the batch's arrays; XLA fuses the whole
projection into one program (the reference pays one kernel launch per op).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..config import RapidsConf, default_conf
from ..types import (BooleanT, BooleanType, DataType, DecimalType, DoubleT, LongT,
                     NullT, NullType, StringType, numeric_promote)
from ..columnar.vector import TpuColumnVector, TpuScalar, row_mask


class EvalContext:
    """Per-task evaluation context: conf snapshot + ANSI flag + task-scoped
    fields nondeterministic expressions read (partition id, current input
    file, running row counters — reference TaskContext + InputFileUtils)."""

    def __init__(self, conf: Optional[RapidsConf] = None,
                 partition_id: int = 0):
        from ..config import SESSION_TZ
        self.conf = conf or default_conf()
        self.ansi = self.conf.ansi_enabled
        self.tz = self.conf.get(SESSION_TZ) or "UTC"
        self.partition_id = partition_id
        self.input_file: Optional[str] = None
        self.input_block_start: int = -1
        self.input_block_length: int = -1
        #: per-expression running row offsets (monotonically_increasing_id,
        #: rand) keyed by id(expr)
        self.row_counters: dict = {}


_DEFAULT_CTX = EvalContext()


class ExpressionError(Exception):
    """Runtime error raised by ANSI-mode expression failures."""


class Expression:
    """Base logical expression; doubles as the evaluable node (no separate
    Catalyst-vs-Gpu split — tagging chooses the eval path instead)."""

    children: Tuple["Expression", ...] = ()

    @property
    def dtype(self) -> DataType:
        raise NotImplementedError(type(self).__name__)

    @property
    def nullable(self) -> bool:
        return True

    @property
    def foldable(self) -> bool:
        return bool(self.children) and all(c.foldable for c in self.children)

    @property
    def resolved(self) -> bool:
        return all(c.resolved for c in self.children)

    #: Whether a device kernel exists (tagging gate; reference: expr rule present
    #: in GpuOverrides.commonExpressions)
    tpu_supported = True

    def with_children(self, children: Sequence["Expression"]) -> "Expression":
        import copy
        new = copy.copy(self)
        new.children = tuple(children)
        # memoized structural fingerprints (execs/opjit.py) describe the OLD
        # children; a copy with new children must not inherit them
        for memo in ("_ojfp", "_ojgate"):
            new.__dict__.pop(memo, None)
        return new

    # --- evaluation -------------------------------------------------------
    def eval_tpu(self, batch, ctx: EvalContext = _DEFAULT_CTX):
        raise NotImplementedError(f"no TPU kernel for {type(self).__name__}")

    def eval_cpu(self, table, ctx: EvalContext = _DEFAULT_CTX):
        raise NotImplementedError(f"no CPU fallback for {type(self).__name__}")

    # --- utils ------------------------------------------------------------
    def pretty(self) -> str:
        name = type(self).__name__
        if self.children:
            return f"{name}({', '.join(c.pretty() for c in self.children)})"
        return name

    def transform(self, fn: Callable[["Expression"], Optional["Expression"]]) -> "Expression":
        """Bottom-up transform (Catalyst transformUp)."""
        new_children = [c.transform(fn) for c in self.children]
        node = self if all(a is b for a, b in zip(new_children, self.children)) \
            else self.with_children(new_children)
        replaced = fn(node)
        return replaced if replaced is not None else node

    def collect(self, pred: Callable[["Expression"], bool]) -> List["Expression"]:
        out = [self] if pred(self) else []
        for c in self.children:
            out.extend(c.collect(pred))
        return out


@dataclass(init=False)
class Literal(Expression):
    value: Any
    _dtype: DataType

    def __init__(self, value: Any, dtype: Optional[DataType] = None):
        self.children = ()
        if dtype is None:
            dtype = infer_literal_type(value)
        self.value = value
        self._dtype = dtype

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self.value is None

    @property
    def foldable(self) -> bool:
        return True

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        return TpuScalar(self._dtype, self.value)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        return self.value

    def pretty(self) -> str:
        return repr(self.value)


def infer_literal_type(value: Any) -> DataType:
    import datetime as _dt
    import decimal as _decimal
    from ..types import (DateT, IntegerT, StringT, TimestampT)
    if value is None:
        return NullT
    if isinstance(value, bool):
        return BooleanT
    if isinstance(value, (int, np.integer)):
        return IntegerT if -(2**31) <= int(value) < 2**31 else LongT
    if isinstance(value, (float, np.floating)):
        return DoubleT
    if isinstance(value, str):
        return StringT
    if isinstance(value, (bytes, bytearray)):
        from ..types import BinaryT
        return BinaryT
    if isinstance(value, _decimal.Decimal):
        sign, digits, exp = value.as_tuple()
        scale = max(0, -exp)
        return DecimalType(max(len(digits), scale), scale)
    if isinstance(value, _dt.datetime):
        return TimestampT
    if isinstance(value, _dt.date):
        return DateT
    raise TypeError(f"cannot infer literal type of {value!r}")


@dataclass(init=False)
class UnresolvedAttribute(Expression):
    name: str

    def __init__(self, name: str):
        self.children = ()
        self.name = name

    @property
    def resolved(self) -> bool:
        return False

    @property
    def dtype(self) -> DataType:
        raise ValueError(f"unresolved attribute {self.name}")

    def pretty(self) -> str:
        return f"'{self.name}"


_NEXT_EXPR_ID = [0]


def _new_expr_id() -> int:
    _NEXT_EXPR_ID[0] += 1
    return _NEXT_EXPR_ID[0]


@dataclass(init=False)
class AttributeReference(Expression):
    """Resolved column reference. Carries a Catalyst-style unique expr_id (so
    self-joins disambiguate) and, after binding, the ordinal of its slot in the
    input batch (reference GpuBoundReference, GpuBoundAttribute.scala)."""
    name: str
    _dtype: DataType
    _nullable: bool
    ordinal: int
    expr_id: int

    def __init__(self, name: str, dtype: DataType, nullable: bool = True,
                 ordinal: int = -1, expr_id: Optional[int] = None):
        self.children = ()
        self.name = name
        self._dtype = dtype
        self._nullable = nullable
        self.ordinal = ordinal
        self.expr_id = expr_id if expr_id is not None else _new_expr_id()

    def renewed(self) -> "AttributeReference":
        """Copy with a fresh expr_id (used when a relation is re-instantiated)."""
        return AttributeReference(self.name, self._dtype, self._nullable)

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    @property
    def foldable(self) -> bool:
        return False

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        return batch.column(self.ordinal)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        return table.column(self.ordinal).combine_chunks()

    def pretty(self) -> str:
        return self.name


@dataclass(init=False)
class Alias(Expression):
    name: str

    def __init__(self, child: Expression, name: str):
        self.children = (child,)
        self.name = name

    @property
    def child(self) -> Expression:
        return self.children[0]

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        return self.child.eval_tpu(batch, ctx)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        return self.child.eval_cpu(table, ctx)

    def pretty(self) -> str:
        return f"{self.child.pretty()} AS {self.name}"


def output_name(expr: Expression, default: Optional[str] = None) -> str:
    if isinstance(expr, Alias):
        return expr.name
    if isinstance(expr, (AttributeReference, UnresolvedAttribute)):
        return expr.name
    return default if default is not None else expr.pretty()


# ---------------------------------------------------------------------------
# Device-eval helpers: broadcasting + null propagation
# ---------------------------------------------------------------------------

ColOrScalar = Union[TpuColumnVector, TpuScalar]


def is_null_scalar(x: ColOrScalar) -> bool:
    return isinstance(x, TpuScalar) and x.is_null


def device_parts(x: ColOrScalar, capacity: int):
    """Return (data, validity_or_None) with data broadcastable to (capacity,).
    Fixed-width only; strings use expressions/strings.py helpers."""
    if isinstance(x, TpuScalar):
        dec128 = (isinstance(x.dtype, DecimalType)
                  and x.dtype.precision > DecimalType.MAX_DEVICE_PRECISION)
        if x.value is None:
            if dec128:
                # (1, 2): row axis present so a 2-row unbucketed column can
                # never be mistaken for a scalar limb pair
                return jnp.zeros((1, 2), jnp.int64), jnp.zeros((capacity,), jnp.bool_)
            dt = x.dtype.np_dtype or np.bool_
            return jnp.zeros((), dt), jnp.zeros((capacity,), jnp.bool_)
        val = x.value
        if isinstance(x.dtype, DecimalType):
            from ..kernels.decimal128 import unscaled_int
            val = unscaled_int(val, x.dtype.scale)
            if dec128:
                from ..kernels.decimal128 import int_to_limbs
                return jnp.asarray([int_to_limbs(val)], jnp.int64), None
        return jnp.asarray(val, x.dtype.np_dtype), None
    return x.data, x.validity


def combine_validity(capacity: int, *vs) -> Optional[jax.Array]:
    acc = None
    for v in vs:
        if v is None:
            continue
        acc = v if acc is None else (acc & v)
    return acc


def make_column(dtype: DataType, data: jax.Array, validity, num_rows: int,
                offsets=None) -> TpuColumnVector:
    if validity is not None:
        # zero out null slots so downstream kernels never see garbage
        if offsets is None:
            vb = validity[:, None] if getattr(data, "ndim", 1) == 2 else validity
            data = jnp.where(vb, data, jnp.zeros((), data.dtype))
    return TpuColumnVector(dtype, data, validity, num_rows, offsets=offsets)


def to_column(x: ColOrScalar, batch, dtype: Optional[DataType] = None) -> TpuColumnVector:
    """Materialize a scalar result as a full column (used by execs)."""
    if isinstance(x, TpuColumnVector):
        return x
    dt = dtype or x.dtype
    return TpuColumnVector.from_scalar(x.value, dt, batch.num_rows,
                                       capacity=batch.capacity)


class BinaryExpression(Expression):
    """Binary op with standard null propagation (null if either side null)."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def left(self) -> Expression:
        return self.children[0]

    @property
    def right(self) -> Expression:
        return self.children[1]

    @property
    def nullable(self) -> bool:
        return self.left.nullable or self.right.nullable

    def _compute(self, ldata, rdata, ctx: EvalContext, valid):
        raise NotImplementedError

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        l = self.left.eval_tpu(batch, ctx)
        r = self.right.eval_tpu(batch, ctx)
        if isinstance(l, TpuScalar) and isinstance(r, TpuScalar):
            # fold on host via cpu path
            import pyarrow as pa
            res = self.eval_cpu(None, ctx)
            return TpuScalar(self.dtype, res)
        cap = batch.capacity
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        valid = combine_validity(cap, lv, rv,
                                 row_mask(batch.num_rows, cap))
        data = self._compute(ld, rd, ctx, valid)
        return make_column(self.dtype, data, valid, batch.num_rows)


class UnaryExpression(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self) -> Expression:
        return self.children[0]

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def _compute(self, data, ctx: EvalContext, valid):
        raise NotImplementedError

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        c = self.child.eval_tpu(batch, ctx)
        cap = batch.capacity
        d, v = device_parts(c, cap)
        if isinstance(c, TpuScalar):
            d = jnp.broadcast_to(d, (cap,))
        valid = combine_validity(cap, v, row_mask(batch.num_rows, cap))
        data = self._compute(d, ctx, valid)
        return make_column(self.dtype, data, valid, batch.num_rows)


def arrow_value(x, i=None):
    """pyarrow scalar/array → python value helpers for CPU eval."""
    import pyarrow as pa
    if isinstance(x, (pa.Array, pa.ChunkedArray)):
        return x
    return x
