"""JSON expressions: get_json_object, from_json, to_json, json_tuple.

Reference: GpuGetJsonObject.scala, GpuJsonToStructs.scala + GpuJsonReadCommon.scala,
GpuStructsToJson.scala, GpuJsonTuple.scala (backed by JNI JSONUtils + the cuDF
JSON reader). TPU strategy: JSON text has no device layout, so these are
host-assisted expressions — parse with Python's json (Spark parity caveats are
handled explicitly below), then rebuild an Arrow column; the tagging layer
prices them as host_assisted, the same way the reference prices JSON ops as
incompat/off-by-default (spark.rapids.sql.expression.GetJsonObject defaults
false, GpuOverrides.scala).

Spark-parity notes implemented here:
  * get_json_object path grammar: $, .field, ['field'], [index], [*]; invalid
    path or malformed document → NULL; string results are unquoted; object /
    array results re-serialized compactly.
  * from_json PERMISSIVE mode: malformed row → NULL struct; field type
    mismatches null out the single field (Spark's partial-result behavior).
  * to_json omits null fields (spark.sql.jsonGenerator.ignoreNullFields=true
    default).
"""

from __future__ import annotations

import json as _json
import re
from typing import Any, List, Optional, Tuple

import numpy as np

from ..types import (ArrayType, BooleanType, ByteType, DataType, DateType,
                     DecimalType, DoubleType, FloatType, IntegerType, IntegralType,
                     LongType, MapType, ShortType, StringT, StringType,
                     StructField, StructType, TimestampType)
from .base import Expression, UnaryExpression, _DEFAULT_CTX
from .generators import Generator


# ---------------------------------------------------------------------------
# JSONPath subset (Spark's JsonPathParser: root, named field, array index, *)
# ---------------------------------------------------------------------------

_PATH_TOKEN = re.compile(
    r"\.(?P<dot>[^.\[\]]+)"        # .field
    r"|\[\'(?P<quoted>[^']*)\'\]"  # ['field']
    r"|\[(?P<index>\d+)\]"         # [0]
    r"|\[\*\]"                     # [*]
    r"|(?P<star>\.\*)"             # .*
)


def parse_json_path(path: str) -> Optional[List[Any]]:
    """'$.a[0].b' → ['a', 0, 'b']; '[*]' → WILDCARD marker. None if invalid."""
    if not path or not path.startswith("$"):
        return None
    out: List[Any] = []
    pos = 1
    while pos < len(path):
        m = _PATH_TOKEN.match(path, pos)
        if m is None:
            return None
        if m.group("dot") is not None:
            name = m.group("dot")
            if name == "*":
                out.append(WILDCARD)
            else:
                out.append(name)
        elif m.group("quoted") is not None:
            out.append(m.group("quoted"))
        elif m.group("index") is not None:
            out.append(int(m.group("index")))
        else:  # [*] or .*
            out.append(WILDCARD)
        pos = m.end()
    return out


class _Wildcard:
    def __repr__(self):
        return "*"


WILDCARD = _Wildcard()


def _walk(value: Any, steps: List[Any], i: int = 0):
    """Evaluate path steps; returns list of matches (wildcards fan out)."""
    if i == len(steps):
        return [value]
    step = steps[i]
    if step is WILDCARD:
        if isinstance(value, list):
            out = []
            for v in value:
                out.extend(_walk(v, steps, i + 1))
            return out
        if isinstance(value, dict):
            out = []
            for v in value.values():
                out.extend(_walk(v, steps, i + 1))
            return out
        return []
    if isinstance(step, int):
        if isinstance(value, list) and 0 <= step < len(value):
            return _walk(value[step], steps, i + 1)
        return []
    # named field
    if isinstance(value, dict) and step in value:
        return _walk(value[step], steps, i + 1)
    # Spark: name step on an ARRAY maps over the elements (e.g. $.a.b where a
    # is an array of objects)
    if isinstance(value, list):
        out = []
        for v in value:
            if isinstance(v, dict) and step in v:
                out.extend(_walk(v[step], steps, i + 1))
        return out
    return []


def _render(matches: List[Any], had_wildcard: bool) -> Optional[str]:
    if not matches:
        return None
    if len(matches) == 1 and not had_wildcard:
        v = matches[0]
        if v is None:
            return None
        if isinstance(v, str):
            return v
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, (dict, list)):
            return _json.dumps(v, separators=(",", ":"))
        return _json.dumps(v)
    if len(matches) == 1:
        v = matches[0]
        if isinstance(v, (dict, list)):
            return _json.dumps(v, separators=(",", ":"))
        return _json.dumps(v) if not isinstance(v, str) else v
    return _json.dumps(matches, separators=(",", ":"))


def get_json_object_impl(doc: Optional[str], path_steps) -> Optional[str]:
    if doc is None or path_steps is None:
        return None
    try:
        value = _json.loads(doc)
    except (ValueError, RecursionError):
        return None
    had_wildcard = any(s is WILDCARD for s in path_steps)
    return _render(_walk(value, path_steps), had_wildcard)


def device_json_get(col, batch, steps, ctx=None, host_render=None):
    """Device JSON path extraction (kernels/json_scan.py) for single-name
    paths ('$.key'), or None when outside the device subset. Per-ROW hybrid:
    rows the validating scan cannot certify (escapes, float canonicalization,
    duplicate keys, deep nesting, top-level arrays) are re-run on the host
    engine and spliced back — one odd row no longer drags the batch to host.
    `host_render(text) -> Optional[str]` overrides the host engine for the
    patched rows (json_tuple renders floats canonically, unlike the raw
    get_json_object span).

    Reference: GpuGetJsonObject.scala via JNI JSONUtils (device kernel)."""
    import jax.numpy as jnp
    import numpy as np

    from ..kernels import strings as SK
    from ..kernels.json_scan import (K_PRIMITIVE, K_STRING, scan_key_spans)
    from ..columnar.vector import bucket_capacity
    from .strings import _dev_str, _str_col
    if (steps is None or len(steps) != 1
            or not isinstance(steps[0], str)):
        return None
    if not _dev_str(col):
        return None
    if not SK.is_ascii(col.data):
        return None  # multi-byte keys/content: host handles encoding corners
    data, offsets = col.data, col.offsets
    nbytes = int(data.shape[0])
    n = int(offsets.shape[0]) - 1
    if n == 0:
        return None
    cap_bytes = 4096
    if ctx is not None:
        from ..config import JSON_DEVICE_SCAN_MAX_ROW_BYTES
        cap_bytes = ctx.conf.get(JSON_DEVICE_SCAN_MAX_ROW_BYTES)
    lens = offsets[1:] - offsets[:-1]
    max_len = int(jnp.max(lens)) if n else 0
    if max_len > cap_bytes:
        return None
    spans = scan_key_spans(data, offsets, steps[0].encode(), max_len)
    # servable on device: certified rows whose value renders byte-identically
    # to the host (raw string without escapes; canonical int; true/false) —
    # or a null result (invalid doc / missing key / JSON null)
    is_null_out = (~spans.valid_doc | ~spans.found
                   | ((spans.kind == K_PRIMITIVE) & (spans.tok == 21)))
    raw_ok = ((spans.kind == K_STRING)
              | ((spans.kind == K_PRIMITIVE)
                 & ((spans.tok == 2) | (spans.tok == 3)
                    | (spans.tok == 12) | (spans.tok == 17))))
    serve = spans.confident & (is_null_out | raw_ok)
    serve_np = np.asarray(serve)
    row_valid = col.validity
    out_len = jnp.where(serve & ~is_null_out, spans.length, 0)
    out_start = jnp.where(serve & ~is_null_out, spans.start, 0)
    out, offs = SK.build_ranges(data, out_start.astype(jnp.int32),
                                out_len.astype(jnp.int32),
                                bucket_capacity(max(nbytes, 1)))
    validity = ~jnp.asarray(np.asarray(is_null_out))
    if row_valid is not None:
        nv = int(validity.shape[0])
        validity = validity & row_valid[:nv]
    if bool(np.all(serve_np)):
        v = jnp.zeros((batch.capacity,), bool).at[
            :validity.shape[0]].set(validity)
        return _str_col(batch, out, offs, v, col)
    # host patch for the unserved minority, spliced row-wise on device
    import pyarrow as pa

    from ..columnar.vector import TpuColumnVector
    arr = col.to_arrow()
    texts = arr.to_pylist()
    if host_render is None:
        host_render = lambda t: get_json_object_impl(t, steps)  # noqa: E731
    patched = [None] * n
    for i in np.nonzero(~serve_np)[0]:
        patched[int(i)] = host_render(texts[int(i)])
    patch_col = TpuColumnVector.from_arrow(pa.array(patched, pa.string()))
    serve_j = jnp.asarray(serve_np)
    dev_emit = serve_j & validity
    patch_valid = (patch_col.validity if patch_col.validity is not None
                   else jnp.ones((int(patch_col.offsets.shape[0]) - 1,),
                                 bool))
    patch_emit = (~serve_j) & patch_valid[:n]
    p_starts = patch_col.offsets[:-1][:n]
    p_lens = (patch_col.offsets[1:] - patch_col.offsets[:-1])[:n]
    out2, offs2 = SK.concat_columns(
        [(out, offs[:-1], offs[1:] - offs[:-1]),
         (patch_col.data, p_starts, p_lens)],
        bucket_capacity(max(nbytes + int(patch_col.data.shape[0]), 1)),
        part_emit=[dev_emit, patch_emit])
    final_valid = jnp.where(serve_j, validity, patch_valid[:n])
    v = jnp.zeros((batch.capacity,), bool).at[:n].set(final_valid)
    return _str_col(batch, out2, offs2, v, col)


class GetJsonObject(Expression):
    """get_json_object(json, path) → string (reference GpuGetJsonObject.scala,
    JNI JSONUtils.getJsonObject)."""

    def __init__(self, child: Expression, path: Expression):
        self.children = (child, path)

    @property
    def dtype(self) -> DataType:
        return StringT

    def _path_steps(self, ctx):
        from .base import Literal
        p = self.children[1]
        if not isinstance(p, Literal):
            raise ValueError("get_json_object path must be a literal")
        return parse_json_path(p.value) if p.value is not None else None

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        steps = self._path_steps(ctx)
        arr = self.children[0].eval_cpu(table, ctx)
        if not isinstance(arr, (pa.Array, pa.ChunkedArray)):
            return get_json_object_impl(arr, steps)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        return pa.array([get_json_object_impl(v, steps)
                         for v in arr.to_pylist()], type=pa.string())

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from ..columnar.vector import TpuScalar
        from .strings import _string_result_from_arrow
        import pyarrow as pa
        steps = self._path_steps(ctx)
        c = self.children[0].eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            return TpuScalar(StringT, get_json_object_impl(c.value, steps))
        out = device_json_get(c, batch, steps, ctx)
        if out is not None:
            return out
        out = pa.array([get_json_object_impl(v, steps)
                        for v in c.to_arrow().to_pylist()], type=pa.string())
        return _string_result_from_arrow(out, batch)

    def pretty(self) -> str:
        return f"get_json_object({self.children[0].pretty()}, {self.children[1].pretty()})"


# ---------------------------------------------------------------------------
# from_json
# ---------------------------------------------------------------------------

def _coerce_json_value(v: Any, dt: DataType) -> Any:
    """Spark JacksonParser-style coercion; mismatch → None (partial results)."""
    if v is None:
        return None
    try:
        if isinstance(dt, StringType):
            if isinstance(v, (dict, list)):
                return _json.dumps(v, separators=(",", ":"))
            if isinstance(v, bool):
                return "true" if v else "false"
            return v if isinstance(v, str) else _json.dumps(v)
        if isinstance(dt, BooleanType):
            return v if isinstance(v, bool) else None
        if isinstance(dt, IntegralType):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                if isinstance(v, str):
                    return None  # Spark: quoted numbers don't parse as ints
                return None
            if isinstance(v, float):
                return None  # Spark: JSON float tokens don't parse as ints
            iv = int(v)
            bits = {ByteType: 8, ShortType: 16, IntegerType: 32,
                    LongType: 64}[type(dt)]
            lo, hi = -(1 << (bits - 1)), (1 << (bits - 1)) - 1
            return iv if lo <= iv <= hi else None
        if isinstance(dt, (DoubleType, FloatType)):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return None
            return float(v)
        if isinstance(dt, DecimalType):
            import decimal
            if isinstance(v, bool) or not isinstance(v, (int, float, str)):
                return None
            d = decimal.Decimal(str(v)).quantize(
                decimal.Decimal(1).scaleb(-dt.scale),
                rounding=decimal.ROUND_HALF_UP)
            # overflow vs declared precision → null (PERMISSIVE)
            if len(d.as_tuple().digits) - max(0, -d.as_tuple().exponent) \
                    > dt.precision - dt.scale:
                return None
            return d
        if isinstance(dt, DateType):
            import datetime as _dt
            if not isinstance(v, str):
                return None
            return _dt.date.fromisoformat(v.strip()[:10])
        if isinstance(dt, TimestampType):
            import datetime as _dt
            if not isinstance(v, str):
                return None
            ts = _dt.datetime.fromisoformat(v.strip().replace("Z", "+00:00"))
            if ts.tzinfo is None:
                ts = ts.replace(tzinfo=_dt.timezone.utc)
            return ts
        if isinstance(dt, StructType):
            if not isinstance(v, dict):
                return None
            return {f.name: _coerce_json_value(v.get(f.name), f.data_type)
                    for f in dt.fields}
        if isinstance(dt, ArrayType):
            if not isinstance(v, list):
                return None
            return [_coerce_json_value(x, dt.element_type) for x in v]
        if isinstance(dt, MapType):
            if not isinstance(v, dict):
                return None
            return [( k, _coerce_json_value(x, dt.value_type))
                    for k, x in v.items()]
    except (ValueError, TypeError, OverflowError):
        return None
    return None


def from_json_impl(doc: Optional[str], schema: StructType) -> Optional[dict]:
    if doc is None:
        return None
    try:
        v = _json.loads(doc)
    except (ValueError, RecursionError):
        return None
    if not isinstance(v, dict):
        return None
    return _coerce_json_value(v, schema)


def device_json_to_structs(col, batch, schema, ctx=None):
    """Schema-driven multi-field device from_json: ONE validating scan per
    target key over the same byte buffer, device coercion for
    int/bool/string fields, per-ROW host patch for everything the scan
    cannot certify (escapes, float-typed string renders, >19-digit ints,
    non-object or whitespace-prefixed docs). None = schema/layout outside
    the device subset entirely (reference GpuJsonToStructs.scala; JNI
    JSONUtils runs the same one-pass-per-key design).
    """
    import jax.numpy as jnp
    import numpy as np

    from ..kernels import strings as SK
    from ..kernels.json_scan import (K_STRING, K_PRIMITIVE, scan_key_spans)
    from ..columnar.vector import TpuColumnVector, bucket_capacity
    from ..types import (BooleanType, ByteType, IntegerType, IntegralType,
                         LongType, ShortType)
    from .strings import _dev_str
    ok_types = (IntegralType, BooleanType, StringType)
    if not schema.fields:
        return None  # no per-field scans: host fallback decides dict-ness
    if not all(isinstance(f.data_type, ok_types) for f in schema.fields):
        return None
    if not _dev_str(col) or not SK.is_ascii(col.data):
        return None
    data, offsets = col.data, col.offsets
    n = int(offsets.shape[0]) - 1
    nbytes = int(data.shape[0])
    if n == 0 or not nbytes:
        return None
    cap_bytes = 4096
    if ctx is not None:
        from ..config import JSON_DEVICE_SCAN_MAX_ROW_BYTES
        cap_bytes = ctx.conf.get(JSON_DEVICE_SCAN_MAX_ROW_BYTES)
    starts = offsets[:-1].astype(jnp.int32)
    lens = (offsets[1:] - offsets[:-1]).astype(jnp.int32)
    max_len = int(jnp.max(lens)) if n else 0
    if max_len > cap_bytes:
        return None
    first = data[jnp.clip(starts, 0, nbytes - 1)]
    is_obj = (first == np.uint8(ord("{"))) & (lens > 0)

    _INT_TOKS = (2, 3, 22)

    def parse_int_span(sp):
        """Device parse of a canonical JSON integer span (≤19 chars)."""
        neg = data[jnp.clip(sp.start, 0, nbytes - 1)] == np.uint8(ord("-"))
        val = jnp.zeros((n,), jnp.int64)
        for k in range(min(max_len, 20)):
            pos = jnp.clip(sp.start + k, 0, nbytes - 1)
            b = data[pos].astype(jnp.int64)
            is_digit = (b >= 48) & (b <= 57) & (k < sp.length)
            val = jnp.where(is_digit, val * 10 + (b - 48), val)
        return jnp.where(neg, -val, val)

    serve = jnp.ones((n,), bool)
    children_plan = []  # (field, kind, device arrays)
    for f in schema.fields:
        sp = scan_key_spans(data, offsets, f.name.encode(), max_len)
        serve = serve & sp.confident
        is_null_tok = (sp.kind == K_PRIMITIVE) & (sp.tok == 21)
        absent = ~sp.found | is_null_tok
        if isinstance(f.data_type, StringType):
            raw_ok = ((sp.kind == K_STRING)
                      | ((sp.kind == K_PRIMITIVE)
                         & jnp.isin(sp.tok, jnp.asarray(
                             list(_INT_TOKS) + [12, 17]))))
            serve = serve & (absent | raw_ok | ~sp.valid_doc)
            fvalid = sp.found & ~is_null_tok & raw_ok
            children_plan.append((f, "str", (sp, fvalid)))
        elif isinstance(f.data_type, BooleanType):
            is_bool = (sp.kind == K_PRIMITIVE) & jnp.isin(
                sp.tok, jnp.asarray([12, 17]))
            fvalid = sp.found & is_bool
            bval = (sp.tok == 12)
            children_plan.append((f, "fixed", (bval, fvalid)))
        else:  # integral
            is_int = ((sp.kind == K_PRIMITIVE)
                      & jnp.isin(sp.tok, jnp.asarray(list(_INT_TOKS))))
            # 18 digits is the widest span the int64 accumulator parses
            # without wrapping; 19-digit values can exceed int64 max and
            # wrap back in-range, so they route to the host patch
            neg = data[jnp.clip(sp.start, 0, nbytes - 1)] \
                == np.uint8(ord("-"))
            digits = sp.length - jnp.where(neg, 1, 0)
            too_long = is_int & (digits > 18)
            serve = serve & ~too_long
            ival = parse_int_span(sp)
            bits = {ByteType: 8, ShortType: 16, IntegerType: 32,
                    LongType: 64}[type(f.data_type)]
            lo = -(1 << (bits - 1))
            hi = (1 << (bits - 1)) - 1
            in_range = (ival >= lo) & (ival <= hi)
            fvalid = sp.found & is_int & in_range
            children_plan.append((f, "fixed",
                                  (ival.astype(f.data_type.np_dtype
                                               or np.int64), fvalid)))
        valid_doc = sp.valid_doc  # identical across fields
    # rows that are not objects need json.loads to decide dict-ness unless
    # clearly invalid; whitespace-prefixed docs are ambiguous on device
    serve = serve & (is_obj | ~valid_doc)
    struct_valid = valid_doc & is_obj
    rm = jnp.arange(n) < batch.num_rows
    serve = serve | ~rm  # padding rows have nothing to patch
    struct_valid = struct_valid & rm
    if col.validity is not None:
        struct_valid = struct_valid & col.validity[:n]
        serve = serve | ~col.validity[:n]  # null input rows: null struct
    serve_np = np.asarray(serve)
    all_served = bool(np.all(serve_np))
    patch_rows = None
    if not all_served:
        texts = col.to_arrow().to_pylist()
        patch_rows = {int(i): from_json_impl(texts[int(i)], schema)
                      for i in np.nonzero(~serve_np)[0]}
        patched_idx = np.nonzero(~serve_np)[0]
        p_struct_valid = np.array(np.asarray(struct_valid))
        p_struct_valid[patched_idx] = [patch_rows[int(i)] is not None
                                       for i in patched_idx]
        struct_valid = jnp.asarray(p_struct_valid)
    cap = batch.capacity
    kids = []
    for f, kind, payload in children_plan:
        if kind == "fixed":
            vals, fvalid = payload
            fvalid = fvalid & struct_valid[:n]
            buf = jnp.zeros((cap,), vals.dtype).at[:n].set(vals[:n])
            vb = jnp.zeros((cap,), bool).at[:n].set(fvalid[:n])
            if not all_served:
                idx = np.nonzero(~serve_np)[0]
                pv = []
                pm = []
                for i in idx:
                    r = patch_rows[int(i)]
                    v = None if r is None else r.get(f.name)
                    pv.append(0 if v is None else
                              (1 if v is True else (0 if v is False else v)))
                    pm.append(v is not None)
                if len(idx):
                    buf = buf.at[jnp.asarray(idx)].set(
                        jnp.asarray(np.asarray(pv, dtype=buf.dtype)))
                    vb = vb.at[jnp.asarray(idx)].set(
                        jnp.asarray(np.asarray(pm, dtype=bool)))
            kids.append(TpuColumnVector(f.data_type, buf, vb,
                                        batch.num_rows))
        else:
            sp, fvalid = payload
            fvalid = fvalid & struct_valid[:n]
            out_len = jnp.where(fvalid, sp.length, 0)
            out_start = jnp.where(fvalid, sp.start, 0)
            sdata, soffs = SK.build_ranges(
                data, out_start.astype(jnp.int32),
                out_len.astype(jnp.int32), bucket_capacity(max(nbytes, 1)))
            svalid = fvalid
            if not all_served:
                import pyarrow as pa
                patched = [None] * n
                for i in np.nonzero(~serve_np)[0]:
                    r = patch_rows[int(i)]
                    v = None if r is None else r.get(f.name)
                    patched[int(i)] = v
                pcol = TpuColumnVector.from_arrow(
                    pa.array(patched, pa.string()))
                serve_j = jnp.asarray(serve_np)
                pvalid = (pcol.validity if pcol.validity is not None
                          else jnp.ones((int(pcol.offsets.shape[0]) - 1,),
                                        bool))
                sdata, soffs = SK.concat_columns(
                    [(sdata, soffs[:-1], soffs[1:] - soffs[:-1]),
                     (pcol.data, pcol.offsets[:-1][:n],
                      (pcol.offsets[1:] - pcol.offsets[:-1])[:n])],
                    bucket_capacity(max(
                        nbytes + int(pcol.data.shape[0]), 1)),
                    part_emit=[serve_j & svalid,
                               (~serve_j) & pvalid[:n]])
                svalid = jnp.where(serve_j, svalid, pvalid[:n])
            sv = jnp.zeros((cap,), bool).at[:n].set(svalid[:n])
            # offsets at capacity: pad with the final offset
            pad = cap + 1 - int(soffs.shape[0])
            if pad > 0:
                soffs = jnp.concatenate(
                    [soffs, jnp.full((pad,), soffs[-1], soffs.dtype)])
            kids.append(TpuColumnVector(StringType(), sdata, sv,
                                        batch.num_rows, offsets=soffs))
    from ..columnar.batch import _repad
    kids = [k if k.capacity == cap else _repad(k, cap) for k in kids]
    sv = jnp.zeros((cap,), bool).at[:n].set(struct_valid[:n])
    return TpuColumnVector(schema, jnp.zeros((0,), jnp.int8), sv,
                           batch.num_rows, children=kids)


class JsonToStructs(UnaryExpression):
    """from_json(json, schema) (reference GpuJsonToStructs.scala; cuDF JSON
    reader per batch there, row-wise host parse here)."""

    def __init__(self, child: Expression, schema: StructType):
        super().__init__(child)
        if not isinstance(schema, StructType):
            raise TypeError("from_json schema must be a StructType")
        self.schema_type = schema

    @property
    def dtype(self) -> DataType:
        return self.schema_type

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        from ..types import to_arrow
        arr = self.child.eval_cpu(table, ctx)
        at = to_arrow(self.schema_type)
        if not isinstance(arr, (pa.Array, pa.ChunkedArray)):
            one = from_json_impl(arr, self.schema_type)
            return pa.array([one], type=at)[0]
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        rows = [from_json_impl(v, self.schema_type) for v in arr.to_pylist()]
        return pa.array(rows, type=at)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from ..columnar.vector import TpuColumnVector, TpuScalar
        import pyarrow as pa
        from ..types import to_arrow
        c = self.child.eval_tpu(batch, ctx)
        at = to_arrow(self.schema_type)
        if isinstance(c, TpuScalar):
            rows = [from_json_impl(c.value, self.schema_type)] * batch.num_rows
        else:
            out = device_json_to_structs(c, batch, self.schema_type, ctx)
            if out is not None:
                return out
            rows = [from_json_impl(v, self.schema_type)
                    for v in c.to_arrow().to_pylist()]
        col = TpuColumnVector.from_arrow(pa.array(rows, type=at))
        if col.capacity < batch.capacity:
            from ..columnar.batch import _repad
            col = _repad(col, batch.capacity)
        return col

    def pretty(self) -> str:
        return f"from_json({self.child.pretty()})"


def device_structs_to_json(col, batch, st, ctx=None):
    """Device to_json for structs of int/bool/string fields: one
    concat_columns assembly — constant braces/keys/quotes ride the
    separator mechanism, bools gather from a shared 'truefalse' buffer,
    ints render into fixed-width digit cells, strings reuse their child
    byte buffer. Rows whose strings need escaping (or non-ASCII) are
    host-patched row-wise. None = outside the device subset (reference
    GpuStructsToJson.scala)."""
    import jax.numpy as jnp
    import numpy as np

    from ..kernels import strings as SK
    from ..columnar.vector import TpuColumnVector, bucket_capacity
    from ..types import BooleanType, IntegralType
    from .strings import _str_col
    ok_types = (IntegralType, BooleanType, StringType)
    if not isinstance(st, StructType) \
            or not all(isinstance(f.data_type, ok_types) for f in st.fields):
        return None
    if not (isinstance(col, TpuColumnVector) and col.children is not None
            and col.host_data is None):
        return None
    kids = col.children
    if any(k.host_data is not None for k in kids):
        return None
    cap = batch.capacity
    n = batch.num_rows
    row_ok = jnp.ones((cap,), bool)  # device-confident rows
    struct_valid = col.validity if col.validity is not None \
        else jnp.ones((cap,), bool)
    parts, part_emit, seps = [], [], []
    zero_starts = jnp.zeros((cap,), jnp.int32)
    empty = (jnp.zeros((1,), jnp.uint8), zero_starts, zero_starts)
    all_rows = jnp.ones((cap,), bool)

    def add_const(bts, emit):
        parts.append(empty)
        part_emit.append(jnp.zeros((cap,), bool))
        seps.append((np.frombuffer(bts, np.uint8), emit))

    add_const(b"{", struct_valid)
    prev_any = jnp.zeros((cap,), bool)
    bool_buf = jnp.asarray(np.frombuffer(b"truefalse", np.uint8))
    total_bytes = 2
    for f, kid in zip(st.fields, kids):
        fvalid = kid.validity if kid.validity is not None else all_rows
        emit = fvalid & struct_valid
        add_const(b",", emit & prev_any)
        add_const(b'"%s":' % f.name.encode(), emit)
        total_bytes += len(f.name) + 4
        if isinstance(f.data_type, BooleanType):
            b = kid.data.astype(jnp.int32)
            starts_v = jnp.where(b != 0, 0, 4).astype(jnp.int32)
            lens_v = jnp.where(b != 0, 4, 5).astype(jnp.int32)
            parts.append((bool_buf, starts_v, lens_v))
            part_emit.append(emit)
            seps.append(None)
            total_bytes += 5
        elif isinstance(f.data_type, IntegralType):
            W = 20
            v = kid.data.astype(jnp.int64)
            neg = v < 0
            # |v| via where (int64 min is unreachable for json ints we emit)
            av = jnp.where(neg, -v, v)
            nd = jnp.ones((cap,), jnp.int32)
            p = jnp.int64(10)
            for _ in range(18):
                nd = nd + (av >= p)
                p = p * 10
            cells = []
            for k in range(W):
                r = W - 1 - k  # digit significance from the right
                div = jnp.int64(10) ** r if r < 19 else jnp.int64(10**18) * 10
                digit = (av // div) % 10
                cells.append((digit + 48).astype(jnp.uint8))
            mat = jnp.stack(cells, axis=1)  # (cap, W) right-aligned digits
            start_in = (W - nd).astype(jnp.int32)
            # place '-' just before the first digit for negatives
            sign_pos = jnp.clip(start_in - 1, 0, W - 1)
            mat = jnp.where(
                (jnp.arange(W)[None, :] == sign_pos[:, None])
                & neg[:, None], jnp.uint8(ord("-")), mat)
            flat = mat.reshape(-1)
            starts_v = (jnp.arange(cap, dtype=jnp.int32) * W
                        + jnp.where(neg, start_in - 1, start_in))
            lens_v = nd + neg.astype(jnp.int32)
            parts.append((flat, starts_v, lens_v))
            part_emit.append(emit)
            seps.append(None)
            total_bytes += W
        else:  # string
            if kid.offsets is None:
                return None
            s0 = kid.offsets[:-1].astype(jnp.int32)
            sl = (kid.offsets[1:] - kid.offsets[:-1]).astype(jnp.int32)
            kdata = kid.data
            kbytes = int(kdata.shape[0])
            # rows whose value needs escaping (quote, backslash, control,
            # non-ASCII) fall to the host patch
            bad = ((kdata == np.uint8(ord('"')))
                   | (kdata == np.uint8(ord("\\")))
                   | (kdata < np.uint8(0x20)) | (kdata >= np.uint8(0x80)))
            bpref = jnp.concatenate([
                jnp.zeros((1,), jnp.int32),
                jnp.cumsum(bad.astype(jnp.int32))])
            nb = bpref[jnp.clip(s0 + sl, 0, kbytes)] \
                - bpref[jnp.clip(s0, 0, kbytes)]
            row_ok = row_ok & ((nb == 0) | ~emit)
            add_const(b'"', emit)
            parts.append((kdata, s0, sl))
            part_emit.append(emit)
            seps.append(None)
            add_const(b'"', emit)
            total_bytes += int(jnp.max(sl)) + 2 if n else 2
        prev_any = prev_any | emit
    add_const(b"}", struct_valid)
    out_cap = bucket_capacity(max(cap * total_bytes, 1))
    if out_cap > 1 << 26:  # pathological width: keep HBM bounded, go host
        return None
    rm = jnp.arange(cap) < n
    serve = (row_ok | ~struct_valid) & True
    serve = serve | ~rm
    out, offs = SK.concat_columns(parts, out_cap, part_emit=part_emit,
                                  seps=seps)
    serve_np = np.asarray(serve)
    validity = struct_valid & rm
    if bool(np.all(serve_np)):
        return _str_col(batch, out, offs, validity, col)
    # host patch for escape-needing rows
    import pyarrow as pa
    texts = col.to_arrow().to_pylist()
    patched = [None] * cap
    for i in np.nonzero(~serve_np)[0]:
        v = texts[int(i)]
        patched[int(i)] = None if v is None else _json.dumps(
            StructsToJson._to_jsonable(v, st), separators=(",", ":"))
    pcol = TpuColumnVector.from_arrow(pa.array(patched, pa.string()))
    serve_j = jnp.asarray(serve_np)
    pvalid = (pcol.validity if pcol.validity is not None
              else jnp.ones((int(pcol.offsets.shape[0]) - 1,), bool))
    pn = int(pcol.offsets.shape[0]) - 1
    p_starts = jnp.zeros((cap,), jnp.int32).at[:pn].set(
        pcol.offsets[:-1][:cap])
    p_lens = jnp.zeros((cap,), jnp.int32).at[:pn].set(
        (pcol.offsets[1:] - pcol.offsets[:-1])[:cap])
    pv = jnp.zeros((cap,), bool).at[:pn].set(pvalid[:cap])
    out2, offs2 = SK.concat_columns(
        [(out, offs[:-1], offs[1:] - offs[:-1]),
         (pcol.data, p_starts, p_lens)],
        bucket_capacity(max(out_cap + int(pcol.data.shape[0]), 1)),
        part_emit=[serve_j & validity, (~serve_j) & pv])
    final_valid = jnp.where(serve_j, validity, pv)
    return _str_col(batch, out2, offs2, final_valid, col)


class StructsToJson(UnaryExpression):
    """to_json(struct) (reference GpuStructsToJson.scala). Null fields omitted
    (Spark ignoreNullFields default)."""

    @property
    def dtype(self) -> DataType:
        return StringT

    @staticmethod
    def _to_jsonable(v: Any, dt: DataType) -> Any:
        if v is None:
            return None
        if isinstance(dt, StructType):
            return {f.name: StructsToJson._to_jsonable(v.get(f.name), f.data_type)
                    for f in dt.fields
                    if v.get(f.name) is not None}
        if isinstance(dt, ArrayType):
            return [StructsToJson._to_jsonable(x, dt.element_type) for x in v]
        if isinstance(dt, MapType):
            items = v.items() if isinstance(v, dict) else v
            return {str(k): StructsToJson._to_jsonable(x, dt.value_type)
                    for k, x in items}
        if isinstance(dt, DecimalType):
            return float(v)
        if isinstance(dt, (DateType, TimestampType)):
            return str(v)
        return v

    def _row_to_json(self, v: Any) -> Optional[str]:
        if v is None:
            return None
        return _json.dumps(self._to_jsonable(v, self.child.dtype),
                           separators=(",", ":"))

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        arr = self.child.eval_cpu(table, ctx)
        if not isinstance(arr, (pa.Array, pa.ChunkedArray)):
            return self._row_to_json(arr)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        return pa.array([self._row_to_json(v) for v in arr.to_pylist()],
                        type=pa.string())

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from ..columnar.vector import TpuScalar
        from .strings import _string_result_from_arrow
        import pyarrow as pa
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            return TpuScalar(StringT, self._row_to_json(c.value))
        out = device_structs_to_json(c, batch, self.child.dtype, ctx)
        if out is not None:
            return out
        out = pa.array([self._row_to_json(v) for v in c.to_arrow().to_pylist()],
                       type=pa.string())
        return _string_result_from_arrow(out, batch)

    def pretty(self) -> str:
        return f"to_json({self.child.pretty()})"


# ---------------------------------------------------------------------------
# json_tuple — a generator producing exactly one row of N string fields
# ---------------------------------------------------------------------------

class JsonTuple(Generator):
    """json_tuple(json, f1, ..., fn) (reference GpuJsonTuple.scala).
    Top-level field extraction only, results rendered like get_json_object."""

    def __init__(self, child: Expression, fields: List[str]):
        self.children = (child,)
        if not fields:
            raise ValueError("json_tuple requires at least one field name")
        self.fields = list(fields)

    @property
    def child(self) -> Expression:
        return self.children[0]

    def element_schema(self):
        return [(f"c{i}", StringT, True) for i in range(len(self.fields))]

    def render_field(self, doc: Optional[str], field: str) -> Optional[str]:
        """One field of one document, json_tuple rendering (floats/nested
        re-serialized canonically) — the host patch for the device scan."""
        if doc is None:
            return None
        try:
            parsed = _json.loads(doc)
            obj = parsed if isinstance(parsed, dict) else None
        except (ValueError, RecursionError):
            obj = None
        v = obj.get(field) if obj is not None else None
        if v is None:
            return None
        if isinstance(v, str):
            return v
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, (dict, list)):
            return _json.dumps(v, separators=(",", ":"))
        return _json.dumps(v)

    def extract_rows(self, docs: List[Optional[str]]) -> List[List[Optional[str]]]:
        """Per input doc, the extracted field values (one output row each)."""
        out = []
        for doc in docs:
            row: List[Optional[str]] = []
            obj = None
            if doc is not None:
                try:
                    parsed = _json.loads(doc)
                    obj = parsed if isinstance(parsed, dict) else None
                except (ValueError, RecursionError):
                    obj = None
            for f in self.fields:
                v = obj.get(f) if obj is not None else None
                if v is None:
                    row.append(None)
                elif isinstance(v, str):
                    row.append(v)
                elif isinstance(v, bool):
                    row.append("true" if v else "false")
                elif isinstance(v, (dict, list)):
                    row.append(_json.dumps(v, separators=(",", ":")))
                else:
                    row.append(_json.dumps(v))
            out.append(row)
        return out

    def pretty(self) -> str:
        return f"json_tuple({self.child.pretty()}, {', '.join(self.fields)})"
