"""JSON expressions: get_json_object, from_json, to_json, json_tuple.

Reference: GpuGetJsonObject.scala, GpuJsonToStructs.scala + GpuJsonReadCommon.scala,
GpuStructsToJson.scala, GpuJsonTuple.scala (backed by JNI JSONUtils + the cuDF
JSON reader). TPU strategy: JSON text has no device layout, so these are
host-assisted expressions — parse with Python's json (Spark parity caveats are
handled explicitly below), then rebuild an Arrow column; the tagging layer
prices them as host_assisted, the same way the reference prices JSON ops as
incompat/off-by-default (spark.rapids.sql.expression.GetJsonObject defaults
false, GpuOverrides.scala).

Spark-parity notes implemented here:
  * get_json_object path grammar: $, .field, ['field'], [index], [*]; invalid
    path or malformed document → NULL; string results are unquoted; object /
    array results re-serialized compactly.
  * from_json PERMISSIVE mode: malformed row → NULL struct; field type
    mismatches null out the single field (Spark's partial-result behavior).
  * to_json omits null fields (spark.sql.jsonGenerator.ignoreNullFields=true
    default).
"""

from __future__ import annotations

import json as _json
import re
from typing import Any, List, Optional, Tuple

import numpy as np

from ..types import (ArrayType, BooleanType, ByteType, DataType, DateType,
                     DecimalType, DoubleType, FloatType, IntegerType, IntegralType,
                     LongType, MapType, ShortType, StringT, StringType,
                     StructField, StructType, TimestampType)
from .base import Expression, UnaryExpression, _DEFAULT_CTX
from .generators import Generator


# ---------------------------------------------------------------------------
# JSONPath subset (Spark's JsonPathParser: root, named field, array index, *)
# ---------------------------------------------------------------------------

_PATH_TOKEN = re.compile(
    r"\.(?P<dot>[^.\[\]]+)"        # .field
    r"|\[\'(?P<quoted>[^']*)\'\]"  # ['field']
    r"|\[(?P<index>\d+)\]"         # [0]
    r"|\[\*\]"                     # [*]
    r"|(?P<star>\.\*)"             # .*
)


def parse_json_path(path: str) -> Optional[List[Any]]:
    """'$.a[0].b' → ['a', 0, 'b']; '[*]' → WILDCARD marker. None if invalid."""
    if not path or not path.startswith("$"):
        return None
    out: List[Any] = []
    pos = 1
    while pos < len(path):
        m = _PATH_TOKEN.match(path, pos)
        if m is None:
            return None
        if m.group("dot") is not None:
            name = m.group("dot")
            if name == "*":
                out.append(WILDCARD)
            else:
                out.append(name)
        elif m.group("quoted") is not None:
            out.append(m.group("quoted"))
        elif m.group("index") is not None:
            out.append(int(m.group("index")))
        else:  # [*] or .*
            out.append(WILDCARD)
        pos = m.end()
    return out


class _Wildcard:
    def __repr__(self):
        return "*"


WILDCARD = _Wildcard()


def _walk(value: Any, steps: List[Any], i: int = 0):
    """Evaluate path steps; returns list of matches (wildcards fan out)."""
    if i == len(steps):
        return [value]
    step = steps[i]
    if step is WILDCARD:
        if isinstance(value, list):
            out = []
            for v in value:
                out.extend(_walk(v, steps, i + 1))
            return out
        if isinstance(value, dict):
            out = []
            for v in value.values():
                out.extend(_walk(v, steps, i + 1))
            return out
        return []
    if isinstance(step, int):
        if isinstance(value, list) and 0 <= step < len(value):
            return _walk(value[step], steps, i + 1)
        return []
    # named field
    if isinstance(value, dict) and step in value:
        return _walk(value[step], steps, i + 1)
    # Spark: name step on an ARRAY maps over the elements (e.g. $.a.b where a
    # is an array of objects)
    if isinstance(value, list):
        out = []
        for v in value:
            if isinstance(v, dict) and step in v:
                out.extend(_walk(v[step], steps, i + 1))
        return out
    return []


def _render(matches: List[Any], had_wildcard: bool) -> Optional[str]:
    if not matches:
        return None
    if len(matches) == 1 and not had_wildcard:
        v = matches[0]
        if v is None:
            return None
        if isinstance(v, str):
            return v
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, (dict, list)):
            return _json.dumps(v, separators=(",", ":"))
        return _json.dumps(v)
    if len(matches) == 1:
        v = matches[0]
        if isinstance(v, (dict, list)):
            return _json.dumps(v, separators=(",", ":"))
        return _json.dumps(v) if not isinstance(v, str) else v
    return _json.dumps(matches, separators=(",", ":"))


def get_json_object_impl(doc: Optional[str], path_steps) -> Optional[str]:
    if doc is None or path_steps is None:
        return None
    try:
        value = _json.loads(doc)
    except (ValueError, RecursionError):
        return None
    had_wildcard = any(s is WILDCARD for s in path_steps)
    return _render(_walk(value, path_steps), had_wildcard)


def device_json_get(col, batch, steps, ctx=None):
    """Device JSON path extraction (kernels/json_scan.py) for single-name
    paths ('$.key'), or None when outside the device subset. Per-ROW hybrid:
    rows the validating scan cannot certify (escapes, float canonicalization,
    duplicate keys, deep nesting, top-level arrays) are re-run on the host
    engine and spliced back — one odd row no longer drags the batch to host.

    Reference: GpuGetJsonObject.scala via JNI JSONUtils (device kernel)."""
    import jax.numpy as jnp
    import numpy as np

    from ..kernels import strings as SK
    from ..kernels.json_scan import (K_PRIMITIVE, K_STRING, scan_key_spans)
    from ..columnar.vector import bucket_capacity
    from .strings import _dev_str, _str_col
    if (steps is None or len(steps) != 1
            or not isinstance(steps[0], str)):
        return None
    if not _dev_str(col):
        return None
    if not SK.is_ascii(col.data):
        return None  # multi-byte keys/content: host handles encoding corners
    data, offsets = col.data, col.offsets
    nbytes = int(data.shape[0])
    n = int(offsets.shape[0]) - 1
    if n == 0:
        return None
    cap_bytes = 4096
    if ctx is not None:
        from ..config import JSON_DEVICE_SCAN_MAX_ROW_BYTES
        cap_bytes = ctx.conf.get(JSON_DEVICE_SCAN_MAX_ROW_BYTES)
    lens = offsets[1:] - offsets[:-1]
    max_len = int(jnp.max(lens)) if n else 0
    if max_len > cap_bytes:
        return None
    spans = scan_key_spans(data, offsets, steps[0].encode(), max_len)
    # servable on device: certified rows whose value renders byte-identically
    # to the host (raw string without escapes; canonical int; true/false) —
    # or a null result (invalid doc / missing key / JSON null)
    is_null_out = (~spans.valid_doc | ~spans.found
                   | ((spans.kind == K_PRIMITIVE) & (spans.tok == 21)))
    raw_ok = ((spans.kind == K_STRING)
              | ((spans.kind == K_PRIMITIVE)
                 & ((spans.tok == 2) | (spans.tok == 3)
                    | (spans.tok == 12) | (spans.tok == 17))))
    serve = spans.confident & (is_null_out | raw_ok)
    serve_np = np.asarray(serve)
    row_valid = col.validity
    out_len = jnp.where(serve & ~is_null_out, spans.length, 0)
    out_start = jnp.where(serve & ~is_null_out, spans.start, 0)
    out, offs = SK.build_ranges(data, out_start.astype(jnp.int32),
                                out_len.astype(jnp.int32),
                                bucket_capacity(max(nbytes, 1)))
    validity = ~jnp.asarray(np.asarray(is_null_out))
    if row_valid is not None:
        nv = int(validity.shape[0])
        validity = validity & row_valid[:nv]
    if bool(np.all(serve_np)):
        v = jnp.zeros((batch.capacity,), bool).at[
            :validity.shape[0]].set(validity)
        return _str_col(batch, out, offs, v, col)
    # host patch for the unserved minority, spliced row-wise on device
    import pyarrow as pa

    from ..columnar.vector import TpuColumnVector
    arr = col.to_arrow()
    texts = arr.to_pylist()
    patched = [None] * n
    for i in np.nonzero(~serve_np)[0]:
        patched[int(i)] = get_json_object_impl(texts[int(i)], steps)
    patch_col = TpuColumnVector.from_arrow(pa.array(patched, pa.string()))
    serve_j = jnp.asarray(serve_np)
    dev_emit = serve_j & validity
    patch_valid = (patch_col.validity if patch_col.validity is not None
                   else jnp.ones((int(patch_col.offsets.shape[0]) - 1,),
                                 bool))
    patch_emit = (~serve_j) & patch_valid[:n]
    p_starts = patch_col.offsets[:-1][:n]
    p_lens = (patch_col.offsets[1:] - patch_col.offsets[:-1])[:n]
    out2, offs2 = SK.concat_columns(
        [(out, offs[:-1], offs[1:] - offs[:-1]),
         (patch_col.data, p_starts, p_lens)],
        bucket_capacity(max(nbytes + int(patch_col.data.shape[0]), 1)),
        part_emit=[dev_emit, patch_emit])
    final_valid = jnp.where(serve_j, validity, patch_valid[:n])
    v = jnp.zeros((batch.capacity,), bool).at[:n].set(final_valid)
    return _str_col(batch, out2, offs2, v, col)


class GetJsonObject(Expression):
    """get_json_object(json, path) → string (reference GpuGetJsonObject.scala,
    JNI JSONUtils.getJsonObject)."""

    def __init__(self, child: Expression, path: Expression):
        self.children = (child, path)

    @property
    def dtype(self) -> DataType:
        return StringT

    def _path_steps(self, ctx):
        from .base import Literal
        p = self.children[1]
        if not isinstance(p, Literal):
            raise ValueError("get_json_object path must be a literal")
        return parse_json_path(p.value) if p.value is not None else None

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        steps = self._path_steps(ctx)
        arr = self.children[0].eval_cpu(table, ctx)
        if not isinstance(arr, (pa.Array, pa.ChunkedArray)):
            return get_json_object_impl(arr, steps)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        return pa.array([get_json_object_impl(v, steps)
                         for v in arr.to_pylist()], type=pa.string())

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from ..columnar.vector import TpuScalar
        from .strings import _string_result_from_arrow
        import pyarrow as pa
        steps = self._path_steps(ctx)
        c = self.children[0].eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            return TpuScalar(StringT, get_json_object_impl(c.value, steps))
        out = device_json_get(c, batch, steps, ctx)
        if out is not None:
            return out
        out = pa.array([get_json_object_impl(v, steps)
                        for v in c.to_arrow().to_pylist()], type=pa.string())
        return _string_result_from_arrow(out, batch)

    def pretty(self) -> str:
        return f"get_json_object({self.children[0].pretty()}, {self.children[1].pretty()})"


# ---------------------------------------------------------------------------
# from_json
# ---------------------------------------------------------------------------

def _coerce_json_value(v: Any, dt: DataType) -> Any:
    """Spark JacksonParser-style coercion; mismatch → None (partial results)."""
    if v is None:
        return None
    try:
        if isinstance(dt, StringType):
            if isinstance(v, (dict, list)):
                return _json.dumps(v, separators=(",", ":"))
            if isinstance(v, bool):
                return "true" if v else "false"
            return v if isinstance(v, str) else _json.dumps(v)
        if isinstance(dt, BooleanType):
            return v if isinstance(v, bool) else None
        if isinstance(dt, IntegralType):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                if isinstance(v, str):
                    return None  # Spark: quoted numbers don't parse as ints
                return None
            if isinstance(v, float):
                return None  # Spark: JSON float tokens don't parse as ints
            iv = int(v)
            bits = {ByteType: 8, ShortType: 16, IntegerType: 32,
                    LongType: 64}[type(dt)]
            lo, hi = -(1 << (bits - 1)), (1 << (bits - 1)) - 1
            return iv if lo <= iv <= hi else None
        if isinstance(dt, (DoubleType, FloatType)):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return None
            return float(v)
        if isinstance(dt, DecimalType):
            import decimal
            if isinstance(v, bool) or not isinstance(v, (int, float, str)):
                return None
            d = decimal.Decimal(str(v)).quantize(
                decimal.Decimal(1).scaleb(-dt.scale),
                rounding=decimal.ROUND_HALF_UP)
            # overflow vs declared precision → null (PERMISSIVE)
            if len(d.as_tuple().digits) - max(0, -d.as_tuple().exponent) \
                    > dt.precision - dt.scale:
                return None
            return d
        if isinstance(dt, DateType):
            import datetime as _dt
            if not isinstance(v, str):
                return None
            return _dt.date.fromisoformat(v.strip()[:10])
        if isinstance(dt, TimestampType):
            import datetime as _dt
            if not isinstance(v, str):
                return None
            ts = _dt.datetime.fromisoformat(v.strip().replace("Z", "+00:00"))
            if ts.tzinfo is None:
                ts = ts.replace(tzinfo=_dt.timezone.utc)
            return ts
        if isinstance(dt, StructType):
            if not isinstance(v, dict):
                return None
            return {f.name: _coerce_json_value(v.get(f.name), f.data_type)
                    for f in dt.fields}
        if isinstance(dt, ArrayType):
            if not isinstance(v, list):
                return None
            return [_coerce_json_value(x, dt.element_type) for x in v]
        if isinstance(dt, MapType):
            if not isinstance(v, dict):
                return None
            return [( k, _coerce_json_value(x, dt.value_type))
                    for k, x in v.items()]
    except (ValueError, TypeError, OverflowError):
        return None
    return None


def from_json_impl(doc: Optional[str], schema: StructType) -> Optional[dict]:
    if doc is None:
        return None
    try:
        v = _json.loads(doc)
    except (ValueError, RecursionError):
        return None
    if not isinstance(v, dict):
        return None
    return _coerce_json_value(v, schema)


class JsonToStructs(UnaryExpression):
    """from_json(json, schema) (reference GpuJsonToStructs.scala; cuDF JSON
    reader per batch there, row-wise host parse here)."""

    def __init__(self, child: Expression, schema: StructType):
        super().__init__(child)
        if not isinstance(schema, StructType):
            raise TypeError("from_json schema must be a StructType")
        self.schema_type = schema

    @property
    def dtype(self) -> DataType:
        return self.schema_type

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        from ..types import to_arrow
        arr = self.child.eval_cpu(table, ctx)
        at = to_arrow(self.schema_type)
        if not isinstance(arr, (pa.Array, pa.ChunkedArray)):
            one = from_json_impl(arr, self.schema_type)
            return pa.array([one], type=at)[0]
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        rows = [from_json_impl(v, self.schema_type) for v in arr.to_pylist()]
        return pa.array(rows, type=at)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from ..columnar.vector import TpuColumnVector, TpuScalar
        import pyarrow as pa
        from ..types import to_arrow
        c = self.child.eval_tpu(batch, ctx)
        at = to_arrow(self.schema_type)
        if isinstance(c, TpuScalar):
            rows = [from_json_impl(c.value, self.schema_type)] * batch.num_rows
        else:
            rows = [from_json_impl(v, self.schema_type)
                    for v in c.to_arrow().to_pylist()]
        col = TpuColumnVector.from_arrow(pa.array(rows, type=at))
        if col.capacity < batch.capacity:
            from ..columnar.batch import _repad
            col = _repad(col, batch.capacity)
        return col

    def pretty(self) -> str:
        return f"from_json({self.child.pretty()})"


class StructsToJson(UnaryExpression):
    """to_json(struct) (reference GpuStructsToJson.scala). Null fields omitted
    (Spark ignoreNullFields default)."""

    @property
    def dtype(self) -> DataType:
        return StringT

    @staticmethod
    def _to_jsonable(v: Any, dt: DataType) -> Any:
        if v is None:
            return None
        if isinstance(dt, StructType):
            return {f.name: StructsToJson._to_jsonable(v.get(f.name), f.data_type)
                    for f in dt.fields
                    if v.get(f.name) is not None}
        if isinstance(dt, ArrayType):
            return [StructsToJson._to_jsonable(x, dt.element_type) for x in v]
        if isinstance(dt, MapType):
            items = v.items() if isinstance(v, dict) else v
            return {str(k): StructsToJson._to_jsonable(x, dt.value_type)
                    for k, x in items}
        if isinstance(dt, DecimalType):
            return float(v)
        if isinstance(dt, (DateType, TimestampType)):
            return str(v)
        return v

    def _row_to_json(self, v: Any) -> Optional[str]:
        if v is None:
            return None
        return _json.dumps(self._to_jsonable(v, self.child.dtype),
                           separators=(",", ":"))

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        arr = self.child.eval_cpu(table, ctx)
        if not isinstance(arr, (pa.Array, pa.ChunkedArray)):
            return self._row_to_json(arr)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        return pa.array([self._row_to_json(v) for v in arr.to_pylist()],
                        type=pa.string())

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from ..columnar.vector import TpuScalar
        from .strings import _string_result_from_arrow
        import pyarrow as pa
        c = self.child.eval_tpu(batch, ctx)
        if isinstance(c, TpuScalar):
            return TpuScalar(StringT, self._row_to_json(c.value))
        out = pa.array([self._row_to_json(v) for v in c.to_arrow().to_pylist()],
                       type=pa.string())
        return _string_result_from_arrow(out, batch)

    def pretty(self) -> str:
        return f"to_json({self.child.pretty()})"


# ---------------------------------------------------------------------------
# json_tuple — a generator producing exactly one row of N string fields
# ---------------------------------------------------------------------------

class JsonTuple(Generator):
    """json_tuple(json, f1, ..., fn) (reference GpuJsonTuple.scala).
    Top-level field extraction only, results rendered like get_json_object."""

    def __init__(self, child: Expression, fields: List[str]):
        self.children = (child,)
        if not fields:
            raise ValueError("json_tuple requires at least one field name")
        self.fields = list(fields)

    @property
    def child(self) -> Expression:
        return self.children[0]

    def element_schema(self):
        return [(f"c{i}", StringT, True) for i in range(len(self.fields))]

    def extract_rows(self, docs: List[Optional[str]]) -> List[List[Optional[str]]]:
        """Per input doc, the extracted field values (one output row each)."""
        out = []
        for doc in docs:
            row: List[Optional[str]] = []
            obj = None
            if doc is not None:
                try:
                    parsed = _json.loads(doc)
                    obj = parsed if isinstance(parsed, dict) else None
                except (ValueError, RecursionError):
                    obj = None
            for f in self.fields:
                v = obj.get(f) if obj is not None else None
                if v is None:
                    row.append(None)
                elif isinstance(v, str):
                    row.append(v)
                elif isinstance(v, bool):
                    row.append("true" if v else "false")
                elif isinstance(v, (dict, list)):
                    row.append(_json.dumps(v, separators=(",", ":")))
                else:
                    row.append(_json.dumps(v))
            out.append(row)
        return out

    def pretty(self) -> str:
        return f"json_tuple({self.child.pretty()}, {', '.join(self.fields)})"
