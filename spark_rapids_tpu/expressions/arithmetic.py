"""Arithmetic expressions with Spark semantics (overflow, div-by-zero, ANSI).

Reference: /root/reference/sql-plugin/src/main/scala/org/apache/spark/sql/rapids/
arithmetic.scala (1279 LoC) — overflow-checked add/sub/mul/div, java-style integer
division/remainder (truncate toward zero, remainder takes dividend's sign), ANSI
error raising, decimal scale rules. The TPU versions express the same semantics as
jax ops that XLA fuses into the surrounding projection.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..types import (ByteT, DataType, DecimalType, DoubleT, FloatT, FractionalType,
                     IntegerT, IntegralType, LongT, NumericType, ShortT)
from .base import (BinaryExpression, EvalContext, Expression, ExpressionError,
                   UnaryExpression, _DEFAULT_CTX)

_INT_INFO = {np.dtype(np.int8): (np.int8(-128), np.int8(127)),
             np.dtype(np.int16): (np.int16(-32768), np.int16(32767)),
             np.dtype(np.int32): (np.int32(-2**31), np.int32(2**31 - 1)),
             np.dtype(np.int64): (np.int64(-2**63), np.int64(2**63 - 1))}


def _ansi_check(flag, ctx: EvalContext, message: str) -> None:
    """ANSI overflow/invalid checks sync one bool to host (the reference raises from
    device-side checked kernels the same way, arithmetic.scala GpuAddBase)."""
    if ctx.ansi and bool(jnp.any(flag)):
        raise ExpressionError(message)


class BinaryArithmetic(BinaryExpression):
    symbol = "?"

    #: decimal128 limb kernel (kernels/decimal128), set on Add/Subtract/Multiply
    _dec128_op = None

    @property
    def dtype(self) -> DataType:
        return self.left.dtype

    def pretty(self) -> str:
        return f"({self.children[0].pretty()} {self.symbol} {self.children[1].pretty()})"

    def _is_dec128(self) -> bool:
        return (isinstance(self.dtype, DecimalType)
                and self.dtype.precision > DecimalType.MAX_DEVICE_PRECISION
                and type(self)._dec128_op is not None)

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        if self._is_dec128():
            return self._dec128_eval(batch, ctx)
        return super().eval_tpu(batch, ctx)

    def _dec128_eval(self, batch, ctx):
        """Two-limb 128-bit path (reference spark-rapids-jni DecimalUtils):
        overflow beyond the result precision → null (ANSI: error), Spark's
        decimal overflow semantics."""
        from .base import combine_validity, device_parts, make_column
        from ..columnar.vector import row_mask
        from ..kernels import decimal128 as D
        cap = batch.capacity
        l = self.left.eval_tpu(batch, ctx)
        r = self.right.eval_tpu(batch, ctx)
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)

        def limbs(d):
            if getattr(d, "ndim", 0) == 2:
                if d.shape[0] == 1:                  # scalar limb pair (1, 2)
                    return (jnp.full((cap,), d[0, 0], jnp.int64),
                            jnp.full((cap,), d[0, 1], jnp.int64))
                return d[:, 0], d[:, 1]              # (cap, 2) column
            # scaled-int64 (≤18) operand: sign-extend into limbs
            return D.from_int64(jnp.broadcast_to(d, (cap,)))

        lh, ll = limbs(ld)
        rh, rl = limbs(rd)
        h, lo, ovf = type(self)._dec128_op(lh, ll, rh, rl)
        ovf = ovf | D.precision_overflow(h, lo, self.dtype.precision)
        valid = combine_validity(cap, lv, rv, row_mask(batch.num_rows, cap))
        if ctx.ansi:
            bad = ovf if valid is None else (ovf & valid)
            _ansi_check(bad, ctx,
                        f"decimal overflow in {type(self).__name__.lower()}")
        valid = combine_validity(cap, valid, ~ovf)
        data = jnp.stack([h, lo], axis=1)
        return make_column(self.dtype, data, valid, batch.num_rows)

    def _py_op(self, a: int, b: int) -> int:
        raise NotImplementedError

    def _dec128_cpu(self, l, r, ctx):
        """Host oracle for decimal128: exact python ints with Spark's
        null-on-overflow (ANSI: error)."""
        import pyarrow as pa
        from ..kernels.decimal128 import scaled_decimal, unscaled_int
        from ..types import to_arrow as type_to_arrow
        scale = self.dtype.scale
        bound = 10 ** self.dtype.precision - 1

        def vals(x, n):
            if isinstance(x, (pa.Array, pa.ChunkedArray)):
                return [None if v is None else
                        unscaled_int(v, _scale_of(x.type))
                        for v in x.to_pylist()], len(x)
            return None, n

        la = l if isinstance(l, (pa.Array, pa.ChunkedArray)) else None
        ra = r if isinstance(r, (pa.Array, pa.ChunkedArray)) else None
        n = len(la) if la is not None else len(ra)
        lv, _ = vals(l, n)
        rv, _ = vals(r, n)
        if lv is None:
            lv = [None if l is None else unscaled_int(l, scale)] * n
        if rv is None:
            rv = [None if r is None else unscaled_int(r, scale)] * n
        out = []
        for a, b in zip(lv, rv):
            if a is None or b is None:
                out.append(None)
                continue
            v = self._py_op(a, b)
            if abs(v) > bound:
                if ctx.ansi:
                    raise ExpressionError(
                        f"decimal overflow in {type(self).__name__.lower()}")
                out.append(None)
            else:
                out.append(scaled_decimal(v, scale))
        return pa.array(out, type=type_to_arrow(self.dtype))

    def _arrow_fn(self, ctx: EvalContext):
        raise NotImplementedError

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        import pyarrow.compute as pc
        from ..types import to_arrow as type_to_arrow
        l = self.left.eval_cpu(table, ctx)
        r = self.right.eval_cpu(table, ctx)
        if self._is_dec128():
            return self._dec128_cpu(l, r, ctx)
        try:
            out = self._cpu_compute(l, r, ctx)
        except pa.ArrowInvalid as e:
            raise ExpressionError(str(e)) from e
        # arrow promotes array-op-pyscalar to the wider type; Spark (and the
        # device kernel) keep the operand type with two's-complement wrap
        if isinstance(self.dtype, IntegralType) \
                and isinstance(out, (pa.Array, pa.ChunkedArray)):
            at = type_to_arrow(self.dtype)
            if out.type != at:
                out = pc.cast(out, at, safe=False)
        return out


class Add(BinaryArithmetic):
    symbol = "+"

    def _py_op(self, a, b):
        return a + b

    def _compute(self, l, r, ctx, valid):
        out = l + r  # int overflow wraps (XLA two's-complement), matching Java
        if ctx.ansi and isinstance(self.dtype, IntegralType):
            overflow = ((l > 0) & (r > 0) & (out < 0)) | ((l < 0) & (r < 0) & (out >= 0))
            if valid is not None:
                overflow = overflow & valid
            _ansi_check(overflow, ctx, "integer overflow in add")
        return out

    def _cpu_compute(self, l, r, ctx):
        import pyarrow.compute as pc
        return pc.add_checked(l, r) if ctx.ansi else pc.add(l, r)


class Subtract(BinaryArithmetic):
    symbol = "-"

    def _py_op(self, a, b):
        return a - b

    def _compute(self, l, r, ctx, valid):
        out = l - r
        if ctx.ansi and isinstance(self.dtype, IntegralType):
            overflow = ((l >= 0) & (r < 0) & (out < 0)) | ((l < 0) & (r > 0) & (out >= 0))
            if valid is not None:
                overflow = overflow & valid
            _ansi_check(overflow, ctx, "integer overflow in subtract")
        return out

    def _cpu_compute(self, l, r, ctx):
        import pyarrow.compute as pc
        return pc.subtract_checked(l, r) if ctx.ansi else pc.subtract(l, r)


class Multiply(BinaryArithmetic):
    symbol = "*"

    def _py_op(self, a, b):
        return a * b

    def _compute(self, l, r, ctx, valid):
        out = l * r
        if ctx.ansi and isinstance(self.dtype, IntegralType):
            # overflow iff r != 0 and out / r != l (trunc division round-trips)
            bad = (r != 0) & (_trunc_div(out, r) != l)
            if valid is not None:
                bad = bad & valid
            _ansi_check(bad, ctx, "integer overflow in multiply")
        return out

    def _cpu_compute(self, l, r, ctx):
        import pyarrow.compute as pc
        return pc.multiply_checked(l, r) if ctx.ansi else pc.multiply(l, r)


def _trunc_div(a, b):
    """Java-style integer division: truncate toward zero (numpy/XLA // floors)."""
    if jnp.issubdtype(a.dtype, jnp.floating):
        return a / b
    safe_b = jnp.where(b == 0, jnp.ones((), b.dtype), b)
    q = a // safe_b
    r = a - q * safe_b
    fix = (r != 0) & ((a < 0) != (safe_b < 0))
    return q + fix.astype(q.dtype)


class Divide(BinaryArithmetic):
    """Spark `/`: fractional division; inputs coerced to double (or decimal).
    Zero divisor → null (non-ANSI) or error (ANSI) for ALL types — Spark's
    DivModLike semantics, not IEEE (reference GpuDivide)."""
    symbol = "/"

    @property
    def dtype(self) -> DataType:
        return self.left.dtype  # coercion made both sides double/decimal

    @property
    def nullable(self) -> bool:
        return True

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .base import (combine_validity, device_parts, make_column)
        from ..columnar.vector import row_mask
        l = self.left.eval_tpu(batch, ctx)
        r = self.right.eval_tpu(batch, ctx)
        cap = batch.capacity
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        mask = row_mask(batch.num_rows, cap)
        valid = combine_validity(cap, lv, rv, mask)
        zero = rd == 0
        if ctx.ansi:
            z = zero if valid is None else (zero & valid)
            _ansi_check(z, ctx, "division by zero")
        safe_r = jnp.where(zero, jnp.ones((), rd.dtype), rd)
        if jnp.issubdtype(rd.dtype, jnp.floating):
            data = ld / safe_r
        else:
            data = _trunc_div(ld, safe_r)
        newvalid = combine_validity(cap, valid, ~zero & mask)
        return make_column(self.dtype, data, newvalid, batch.num_rows)

    def _cpu_compute(self, l, r, ctx):
        import pyarrow as pa
        import pyarrow.compute as pc
        rz = pc.fill_null(pc.equal(r, pa.scalar(0, _atype(r))), False)
        if ctx.ansi and bool(pc.any(rz).as_py()):
            raise ExpressionError("division by zero")
        r_safe = pc.if_else(rz, pa.scalar(1, _atype(r)), r)
        out = pc.divide(l, r_safe)
        return pc.if_else(rz, pa.scalar(None, _atype(out)), out)


def _atype(x):
    import pyarrow as pa
    if isinstance(x, (pa.Array, pa.ChunkedArray, pa.Scalar)):
        return x.type
    return pa.scalar(x).type


def _as_array(x):
    import pyarrow as pa
    if isinstance(x, pa.ChunkedArray):
        return x.combine_chunks()
    return x


def _null_mask(x):
    import numpy as np
    import pyarrow as pa
    import pyarrow.compute as pc
    if isinstance(x, (pa.Array, pa.ChunkedArray)):
        return np.asarray(pc.is_null(x).to_numpy(zero_copy_only=False)).astype(bool)
    return np.zeros(1, dtype=bool) if x is not None else np.ones(1, dtype=bool)


class IntegralDivide(BinaryArithmetic):
    """Spark `div`: integral division returning long."""
    symbol = "div"

    @property
    def dtype(self) -> DataType:
        return LongT

    @property
    def nullable(self) -> bool:
        return True

    def _compute(self, l, r, ctx, valid):
        raise NotImplementedError  # handled in eval_tpu

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .base import combine_validity, device_parts, make_column
        from ..columnar.vector import row_mask
        l = self.left.eval_tpu(batch, ctx)
        r = self.right.eval_tpu(batch, ctx)
        cap = batch.capacity
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        mask = row_mask(batch.num_rows, cap)
        valid = combine_validity(cap, lv, rv, mask)
        zero = rd == 0
        if ctx.ansi:
            z = zero if valid is None else (zero & valid)
            _ansi_check(z, ctx, "division by zero")
        data = _trunc_div(ld.astype(jnp.int64),
                          jnp.where(zero, jnp.ones((), jnp.int64),
                                    rd.astype(jnp.int64)))
        newvalid = combine_validity(cap, valid, ~zero & mask)
        return make_column(LongT, data, newvalid, batch.num_rows)

    def _cpu_compute(self, l, r, ctx):
        import pyarrow as pa
        import pyarrow.compute as pc
        l64 = pc.cast(l, pa.int64())
        r64 = pc.cast(r, pa.int64())
        rz = pc.equal(r64, 0)
        if ctx.ansi and bool(pc.any(pc.fill_null(rz, False)).as_py()):
            raise ExpressionError("division by zero")
        r_safe = pc.if_else(rz, pa.scalar(1, pa.int64()), r64)
        # arrow divide on ints truncates toward zero (C semantics) == Spark div
        out = pc.divide(l64, r_safe)
        return pc.if_else(rz, pa.scalar(None, pa.int64()), out)


class Remainder(BinaryArithmetic):
    """Spark `%`: java semantics — result takes the dividend's sign."""
    symbol = "%"

    @property
    def nullable(self) -> bool:
        return True

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .base import combine_validity, device_parts, make_column
        from ..columnar.vector import row_mask
        l = self.left.eval_tpu(batch, ctx)
        r = self.right.eval_tpu(batch, ctx)
        cap = batch.capacity
        ld, lv = device_parts(l, cap)
        rd, rv = device_parts(r, cap)
        mask = row_mask(batch.num_rows, cap)
        valid = combine_validity(cap, lv, rv, mask)
        if jnp.issubdtype(ld.dtype, jnp.floating):
            data = jnp.fmod(ld, rd)  # C fmod: sign of dividend, matches Java %
            return make_column(self.dtype, data, valid, batch.num_rows)
        zero = rd == 0
        if ctx.ansi:
            z = zero if valid is None else (zero & valid)
            _ansi_check(z, ctx, "division by zero")
        safe_r = jnp.where(zero, jnp.ones((), rd.dtype), rd)
        q = _trunc_div(ld, safe_r)
        data = ld - q * safe_r
        newvalid = combine_validity(cap, valid, ~zero & mask)
        return make_column(self.dtype, data, newvalid, batch.num_rows)

    def _cpu_compute(self, l, r, ctx):
        import pyarrow as pa
        import pyarrow.compute as pc
        t = _atype(l)
        if pa.types.is_floating(t):
            import numpy as np
            ln = _as_array(l).to_numpy(zero_copy_only=False)
            rn = _as_array(r).to_numpy(zero_copy_only=False) if isinstance(r, (pa.Array, pa.ChunkedArray)) else r.as_py() if isinstance(r, pa.Scalar) else r
            with np.errstate(invalid="ignore"):
                out = np.fmod(np.asarray(ln, dtype=np.float64), rn)
            return pa.array(out, mask=_null_mask(l) | _null_mask(r) if isinstance(r, (pa.Array, pa.ChunkedArray)) else _null_mask(l))
        rz = pc.equal(r, 0)
        if ctx.ansi and bool(pc.any(pc.fill_null(rz, False)).as_py()):
            raise ExpressionError("division by zero")
        r_safe = pc.if_else(rz, pa.scalar(1, _atype(r)), r)
        # arrow int division truncates toward zero; remainder = l - trunc(l/r)*r
        q = pc.divide(l, r_safe)
        out = pc.subtract(l, pc.multiply(q, r_safe))
        return pc.if_else(rz, pa.scalar(None, _atype(out)), out)


class Pmod(BinaryArithmetic):
    """Positive modulus (reference GpuPmod)."""
    symbol = "pmod"

    @property
    def nullable(self) -> bool:
        return True

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        rem = Remainder(self.left, self.right).eval_tpu(batch, ctx)
        from .base import device_parts, make_column
        cap = batch.capacity
        rd, rv = device_parts(self.right.eval_tpu(batch, ctx), cap)
        d = rem.data
        fixed = jnp.where(d < 0, d + jnp.abs(rd).astype(d.dtype), d)
        return make_column(self.dtype, fixed, rem.validity, batch.num_rows)

    def _cpu_compute(self, l, r, ctx):
        import pyarrow as pa
        import pyarrow.compute as pc
        rem = Remainder(self.left, self.right)._cpu_compute(l, r, ctx)
        neg = pc.less(rem, 0)
        absr = pc.abs(r)
        return pc.if_else(neg, pc.add(rem, absr), rem)


class UnaryMinus(UnaryExpression):
    def _compute(self, d, ctx, valid):
        if ctx.ansi and jnp.issubdtype(d.dtype, jnp.signedinteger):
            lo, _ = _INT_INFO[np.dtype(d.dtype.name)]
            bad = d == lo
            if valid is not None:
                bad = bad & valid
            _ansi_check(bad, ctx, "integer overflow in negate")
        return -d

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        c = self.child.eval_cpu(table, ctx)
        return pc.negate_checked(c) if ctx.ansi else pc.negate(c)

    def pretty(self) -> str:
        return f"(- {self.child.pretty()})"


class UnaryPositive(UnaryExpression):
    def _compute(self, d, ctx, valid):
        return d

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        return self.child.eval_cpu(table, ctx)


class Abs(UnaryExpression):
    def _compute(self, d, ctx, valid):
        if ctx.ansi and jnp.issubdtype(d.dtype, jnp.signedinteger):
            lo, _ = _INT_INFO[np.dtype(d.dtype.name)]
            bad = d == lo
            if valid is not None:
                bad = bad & valid
            _ansi_check(bad, ctx, "integer overflow in abs")
        return jnp.abs(d)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow.compute as pc
        c = self.child.eval_cpu(table, ctx)
        return pc.abs_checked(c) if ctx.ansi else pc.abs(c)


def _scale_of(arrow_type) -> int:
    import pyarrow as pa
    return arrow_type.scale if pa.types.is_decimal(arrow_type) else 0


def _wire_dec128():
    from ..kernels import decimal128 as D
    Add._dec128_op = staticmethod(D.add128)
    Subtract._dec128_op = staticmethod(D.sub128)
    Multiply._dec128_op = staticmethod(D.mul128)


_wire_dec128()
