"""Deterministic scale-test data generator.

Reference: datagen/ (bigDataGen.scala, README.md:1-36) — seed-mapping design:
every cell is a pure function of (seed, table, column, row) so any slice of a
huge dataset regenerates identically without storing it; controllable
cardinality and skew. Used by the scale tests and the TPC-H-style benchmarks
(benchmarks/).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa


def _cell_rng(seed: int, table: str, column: str, part: int) -> np.random.Generator:
    # stable per (seed, table, column, partition) stream — the seed-mapping idea
    key = abs(hash((seed, table, column, part))) % (2**63)
    return np.random.default_rng(key)


class ColumnSpec:
    def __init__(self, name: str, kind: str, *, cardinality: Optional[int] = None,
                 skew: float = 0.0, min_val=None, max_val=None,
                 null_prob: float = 0.0, alphabet: str = "abcdefghij",
                 max_len: int = 12, values: Optional[Sequence[str]] = None,
                 sequential: bool = False, modulo: Optional[int] = None,
                 repeat: int = 1):
        self.name = name
        # int/long/double/string/date/bool/key/seq/choice
        self.kind = kind
        self.cardinality = cardinality
        self.skew = skew  # 0 = uniform; >0 zipf-ish concentration
        self.min_val = min_val
        self.max_val = max_val
        self.null_prob = null_prob
        self.alphabet = alphabet
        self.max_len = max_len
        self.values = list(values) if values is not None else None
        self.sequential = sequential  # choice: values[row % len] (dim tables)
        self.modulo = modulo          # seq: (row // repeat) % modulo
        self.repeat = repeat          # seq: each key value repeats this often

    def generate(self, rng: np.random.Generator, n: int,
                 offset: int = 0) -> pa.Array:
        if self.kind == "seq":
            # primary-key column: globally unique (offset carries across
            # partitions); with modulo/repeat it becomes a deterministic FK
            vals = (np.arange(offset, offset + n, dtype=np.int64)
                    // self.repeat)
            if self.modulo:
                vals = vals % self.modulo
            return pa.array(vals, pa.int64())
        if self.kind == "choice":
            vals = self.values
            if self.sequential:
                idx = (np.arange(offset, offset + n)) % len(vals)
            elif self.skew > 0:
                ranks = np.arange(1, len(vals) + 1, dtype=np.float64)
                w = ranks ** (-self.skew)
                w /= w.sum()
                idx = rng.choice(len(vals), size=n, p=w)
            else:
                idx = rng.integers(0, len(vals), n)
            arr = pa.array(np.asarray(vals, dtype=object)[idx].tolist(),
                           pa.string())
            return self._with_nulls(arr, rng, n)
        if self.kind in ("key", "int", "long"):
            if self.cardinality:
                if self.skew > 0:
                    # zipf-like: rank^-skew weights over the key domain
                    ranks = np.arange(1, self.cardinality + 1, dtype=np.float64)
                    w = ranks ** (-self.skew)
                    w /= w.sum()
                    vals = rng.choice(self.cardinality, size=n, p=w)
                else:
                    vals = rng.integers(0, self.cardinality, n)
            else:
                lo = self.min_val if self.min_val is not None else 0
                hi = self.max_val if self.max_val is not None else 2**31 - 1
                vals = rng.integers(lo, hi + 1, n, dtype=np.int64)
            t = pa.int64() if self.kind == "long" else pa.int32()
            arr = pa.array(vals.astype(np.int64 if self.kind == "long" else np.int32), t)
        elif self.kind == "double":
            lo = self.min_val if self.min_val is not None else 0.0
            hi = self.max_val if self.max_val is not None else 1.0
            arr = pa.array(rng.random(n) * (hi - lo) + lo, pa.float64())
        elif self.kind == "bool":
            arr = pa.array(rng.integers(0, 2, n).astype(bool))
        elif self.kind == "date":
            lo = self.min_val if self.min_val is not None else 8000
            hi = self.max_val if self.max_val is not None else 12000
            arr = pa.array(rng.integers(lo, hi, n).astype(np.int32), pa.date32())
        elif self.kind == "string":
            card = self.cardinality or 0
            if card:
                # dictionary of `card` distinct strings, zipf-weighted picks
                dict_rng = np.random.default_rng(card * 7919 + 13)
                lens = dict_rng.integers(1, self.max_len + 1, card)
                words = ["".join(self.alphabet[c] for c in
                                 dict_rng.integers(0, len(self.alphabet), l))
                         for l in lens]
                idx = rng.integers(0, card, n)
                arr = pa.array([words[i] for i in idx])
            else:
                lens = rng.integers(0, self.max_len + 1, n)
                chars = rng.integers(0, len(self.alphabet), int(lens.sum()))
                out, pos = [], 0
                for l in lens:
                    out.append("".join(self.alphabet[c]
                                       for c in chars[pos:pos + l]))
                    pos += l
                arr = pa.array(out)
        else:
            raise ValueError(f"unknown column kind {self.kind}")
        return self._with_nulls(arr, rng, n)

    def _with_nulls(self, arr: pa.Array, rng: np.random.Generator,
                    n: int) -> pa.Array:
        if self.null_prob > 0:
            mask = rng.random(n) < self.null_prob
            arr = pa.array([None if m else v
                            for v, m in zip(arr.to_pylist(), mask)],
                           type=arr.type)
        return arr


class TableSpec:
    def __init__(self, name: str, columns: Sequence[ColumnSpec]):
        self.name = name
        self.columns = list(columns)

    def generate_partition(self, seed: int, part: int, rows: int,
                           offset: int = 0) -> pa.Table:
        cols = {}
        for c in self.columns:
            rng = _cell_rng(seed, self.name, c.name, part)
            cols[c.name] = c.generate(rng, rows, offset=offset)
        return pa.table(cols)

    def generate(self, seed: int, rows: int, partitions: int = 1) -> pa.Table:
        per = rows // partitions
        tables, offset = [], 0
        for p in range(partitions):
            n = per + (1 if p < rows % partitions else 0)
            tables.append(self.generate_partition(seed, p, n, offset=offset))
            offset += n
        return pa.concat_tables(tables)


# --- TPC-H-style schema at a given scale (rows ~ SF * base) -----------------

_SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
_SHIPINSTRUCT = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
                 "TAKE BACK RETURN"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
            "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ",
            "JAPAN", "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU",
            "CHINA", "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA",
            "UNITED KINGDOM", "UNITED STATES"]
_COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
           "blanched", "blue", "blush", "brown", "burlywood", "burnished",
           "chartreuse", "chiffon", "chocolate", "coral", "cornflower",
           "cream", "cyan", "dark", "green", "forest", "frosted", "gainsboro",
           "ghost", "goldenrod", "honeydew", "hot", "indian", "ivory"]
_TYPES = [f"{a} {b} {c}"
          for a in ("STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY",
                    "PROMO")
          for b in ("ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED")
          for c in ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")]
_BRANDS = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
_CONTAINERS = [f"{a} {b}"
               for a in ("SM", "MED", "LG", "JUMBO", "WRAP")
               for b in ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN",
                         "DRUM")]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]

N_NATIONS = len(_NATIONS)
N_REGIONS = len(_REGIONS)


def tpch_lineitem(scale_rows: int) -> TableSpec:
    return TableSpec("lineitem", [
        ColumnSpec("l_orderkey", "key", cardinality=max(scale_rows // 4, 1)),
        ColumnSpec("l_partkey", "key", cardinality=max(scale_rows // 20, 1)),
        ColumnSpec("l_suppkey", "key", cardinality=max(scale_rows // 100, 1)),
        ColumnSpec("l_quantity", "int", min_val=1, max_val=50),
        ColumnSpec("l_extendedprice", "double", min_val=900.0, max_val=105000.0),
        ColumnSpec("l_discount", "double", min_val=0.0, max_val=0.1),
        ColumnSpec("l_tax", "double", min_val=0.0, max_val=0.08),
        ColumnSpec("l_returnflag", "string", cardinality=3, max_len=1,
                   alphabet="RAN"),
        ColumnSpec("l_linestatus", "string", cardinality=2, max_len=1,
                   alphabet="OF"),
        ColumnSpec("l_shipdate", "date", min_val=8035, max_val=10590),
        ColumnSpec("l_commitdate", "date", min_val=8035, max_val=10590),
        ColumnSpec("l_receiptdate", "date", min_val=8035, max_val=10590),
        ColumnSpec("l_shipmode", "choice", values=_SHIPMODES),
        ColumnSpec("l_shipinstruct", "choice", values=_SHIPINSTRUCT),
    ])


def tpch_orders(scale_rows: int) -> TableSpec:
    return TableSpec("orders", [
        ColumnSpec("o_orderkey", "seq"),
        ColumnSpec("o_custkey", "key", cardinality=max(scale_rows // 10, 1)),
        ColumnSpec("o_orderdate", "date", min_val=8035, max_val=10590),
        ColumnSpec("o_totalprice", "double", min_val=800.0, max_val=600000.0),
        ColumnSpec("o_orderpriority", "choice", values=_PRIORITIES),
        ColumnSpec("o_orderstatus", "choice", values=["O", "F", "P"]),
    ])


def tpch_customer(scale_rows: int) -> TableSpec:
    return TableSpec("customer", [
        ColumnSpec("c_custkey", "seq"),
        ColumnSpec("c_name", "string", max_len=18),
        ColumnSpec("c_mktsegment", "choice", values=_SEGMENTS),
        ColumnSpec("c_acctbal", "double", min_val=-1000.0, max_val=10000.0),
        ColumnSpec("c_nationkey", "seq", modulo=N_NATIONS),
        ColumnSpec("c_phone", "string", alphabet="0123456789-", max_len=15),
    ])


def tpch_supplier(scale_rows: int) -> TableSpec:
    return TableSpec("supplier", [
        ColumnSpec("s_suppkey", "seq"),
        ColumnSpec("s_name", "string", max_len=18),
        ColumnSpec("s_nationkey", "seq", modulo=N_NATIONS),
        ColumnSpec("s_acctbal", "double", min_val=-1000.0, max_val=10000.0),
    ])


def tpch_part(scale_rows: int) -> TableSpec:
    return TableSpec("part", [
        ColumnSpec("p_partkey", "seq"),
        ColumnSpec("p_name", "choice", values=[
            f"{a} {b}" for a in _COLORS for b in ("metal", "steel", "satin")]),
        ColumnSpec("p_type", "choice", values=_TYPES),
        ColumnSpec("p_brand", "choice", values=_BRANDS),
        ColumnSpec("p_container", "choice", values=_CONTAINERS),
        ColumnSpec("p_size", "int", min_val=1, max_val=50),
        ColumnSpec("p_retailprice", "double", min_val=900.0, max_val=2000.0),
    ])


def tpch_partsupp(n_parts: int, n_suppliers: int) -> TableSpec:
    # 4 suppliers per part: ps_partkey = (row // 4) % n_parts — the modulo
    # keeps the FK inside part's key domain for ANY generated row count
    return TableSpec("partsupp", [
        ColumnSpec("ps_partkey", "seq", repeat=4, modulo=max(n_parts, 1)),
        ColumnSpec("ps_suppkey", "key",
                   cardinality=max(n_suppliers, 1)),
        ColumnSpec("ps_availqty", "int", min_val=1, max_val=9999),
        ColumnSpec("ps_supplycost", "double", min_val=1.0, max_val=1000.0),
    ])


def tpch_nation() -> TableSpec:
    return TableSpec("nation", [
        ColumnSpec("n_nationkey", "seq"),
        ColumnSpec("n_name", "choice", values=_NATIONS, sequential=True),
        ColumnSpec("n_regionkey", "seq", modulo=N_REGIONS),
    ])


def tpch_region() -> TableSpec:
    return TableSpec("region", [
        ColumnSpec("r_regionkey", "seq"),
        ColumnSpec("r_name", "choice", values=_REGIONS, sequential=True),
    ])
