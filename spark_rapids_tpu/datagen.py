"""Deterministic scale-test data generator.

Reference: datagen/ (bigDataGen.scala, README.md:1-36) — seed-mapping design:
every cell is a pure function of (seed, table, column, row) so any slice of a
huge dataset regenerates identically without storing it; controllable
cardinality and skew. Used by the scale tests and the TPC-H-style benchmarks
(benchmarks/).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa


def _cell_rng(seed: int, table: str, column: str, part: int) -> np.random.Generator:
    # stable per (seed, table, column, partition) stream — the seed-mapping
    # idea. MUST be process-stable (python's hash() is salted per process,
    # which made every benchmark run generate different data), so derive the
    # stream key from a content hash.
    import zlib
    key = zlib.crc32(f"{seed}|{table}|{column}|{part}".encode())
    return np.random.default_rng((seed << 32) ^ key)


class ColumnSpec:
    def __init__(self, name: str, kind: str, *, cardinality: Optional[int] = None,
                 skew: float = 0.0, min_val=None, max_val=None,
                 null_prob: float = 0.0, alphabet: str = "abcdefghij",
                 max_len: int = 12, values: Optional[Sequence[str]] = None,
                 sequential: bool = False, modulo: Optional[int] = None,
                 repeat: int = 1, derive=None):
        self.name = name
        # int/long/double/string/date/bool/key/seq/choice
        self.kind = kind
        self.cardinality = cardinality
        self.skew = skew  # 0 = uniform; >0 zipf-ish concentration
        self.min_val = min_val
        self.max_val = max_val
        self.null_prob = null_prob
        self.alphabet = alphabet
        self.max_len = max_len
        self.values = list(values) if values is not None else None
        self.sequential = sequential  # choice: values[row % len] (dim tables)
        self.modulo = modulo          # seq: (row // repeat) % modulo
        self.repeat = repeat          # seq: each key value repeats this often
        # derive: fn(cols_so_far: dict[str, pa.Array], rng, n) -> pa.Array —
        # cross-column FK consistency (e.g. lineitem suppliers drawn from the
        # part's partsupp suppliers, as the real TPC-H generator does)
        self.derive = derive

    def generate(self, rng: np.random.Generator, n: int,
                 offset: int = 0) -> pa.Array:
        if self.kind == "seq":
            # primary-key column: globally unique (offset carries across
            # partitions); with modulo/repeat it becomes a deterministic FK
            vals = (np.arange(offset, offset + n, dtype=np.int64)
                    // self.repeat)
            if self.modulo:
                vals = vals % self.modulo
            return pa.array(vals, pa.int64())
        if self.kind == "choice":
            vals = self.values
            if self.sequential:
                idx = (np.arange(offset, offset + n)) % len(vals)
            elif self.skew > 0:
                ranks = np.arange(1, len(vals) + 1, dtype=np.float64)
                w = ranks ** (-self.skew)
                w /= w.sum()
                idx = rng.choice(len(vals), size=n, p=w)
            else:
                idx = rng.integers(0, len(vals), n)
            arr = pa.array(np.asarray(vals, dtype=object)[idx].tolist(),
                           pa.string())
            return self._with_nulls(arr, rng, n)
        if self.kind in ("key", "int", "long"):
            if self.cardinality:
                if self.skew > 0:
                    # zipf-like: rank^-skew weights over the key domain
                    ranks = np.arange(1, self.cardinality + 1, dtype=np.float64)
                    w = ranks ** (-self.skew)
                    w /= w.sum()
                    vals = rng.choice(self.cardinality, size=n, p=w)
                else:
                    vals = rng.integers(0, self.cardinality, n)
            else:
                lo = self.min_val if self.min_val is not None else 0
                hi = self.max_val if self.max_val is not None else 2**31 - 1
                vals = rng.integers(lo, hi + 1, n, dtype=np.int64)
            t = pa.int64() if self.kind == "long" else pa.int32()
            arr = pa.array(vals.astype(np.int64 if self.kind == "long" else np.int32), t)
        elif self.kind == "double":
            lo = self.min_val if self.min_val is not None else 0.0
            hi = self.max_val if self.max_val is not None else 1.0
            arr = pa.array(rng.random(n) * (hi - lo) + lo, pa.float64())
        elif self.kind == "bool":
            arr = pa.array(rng.integers(0, 2, n).astype(bool))
        elif self.kind == "date":
            lo = self.min_val if self.min_val is not None else 8000
            hi = self.max_val if self.max_val is not None else 12000
            arr = pa.array(rng.integers(lo, hi, n).astype(np.int32), pa.date32())
        elif self.kind == "string":
            card = self.cardinality or 0
            if card:
                # dictionary of `card` distinct strings, zipf-weighted picks
                dict_rng = np.random.default_rng(card * 7919 + 13)
                lens = dict_rng.integers(1, self.max_len + 1, card)
                words = ["".join(self.alphabet[c] for c in
                                 dict_rng.integers(0, len(self.alphabet), l))
                         for l in lens]
                idx = rng.integers(0, card, n)
                arr = pa.array([words[i] for i in idx])
            else:
                lens = rng.integers(0, self.max_len + 1, n)
                chars = rng.integers(0, len(self.alphabet), int(lens.sum()))
                out, pos = [], 0
                for l in lens:
                    out.append("".join(self.alphabet[c]
                                       for c in chars[pos:pos + l]))
                    pos += l
                arr = pa.array(out)
        else:
            raise ValueError(f"unknown column kind {self.kind}")
        return self._with_nulls(arr, rng, n)

    def _with_nulls(self, arr: pa.Array, rng: np.random.Generator,
                    n: int) -> pa.Array:
        if self.null_prob > 0:
            mask = rng.random(n) < self.null_prob
            arr = pa.array([None if m else v
                            for v, m in zip(arr.to_pylist(), mask)],
                           type=arr.type)
        return arr


class TableSpec:
    def __init__(self, name: str, columns: Sequence[ColumnSpec]):
        self.name = name
        self.columns = list(columns)

    def generate_partition(self, seed: int, part: int, rows: int,
                           offset: int = 0) -> pa.Table:
        cols = {}
        for c in self.columns:
            rng = _cell_rng(seed, self.name, c.name, part)
            if c.kind == "derive":
                cols[c.name] = c.derive(cols, rng, rows, offset)
            else:
                cols[c.name] = c.generate(rng, rows, offset=offset)
        return pa.table(cols)

    def generate(self, seed: int, rows: int, partitions: int = 1) -> pa.Table:
        per = rows // partitions
        tables, offset = [], 0
        for p in range(partitions):
            n = per + (1 if p < rows % partitions else 0)
            tables.append(self.generate_partition(seed, p, n, offset=offset))
            offset += n
        return pa.concat_tables(tables)


# --- TPC-H-style schema at a given scale (rows ~ SF * base) -----------------

_SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
_SHIPINSTRUCT = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
                 "TAKE BACK RETURN"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
            "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ",
            "JAPAN", "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU",
            "CHINA", "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA",
            "UNITED KINGDOM", "UNITED STATES"]
_COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
           "blanched", "blue", "blush", "brown", "burlywood", "burnished",
           "chartreuse", "chiffon", "chocolate", "coral", "cornflower",
           "cream", "cyan", "dark", "green", "forest", "frosted", "gainsboro",
           "ghost", "goldenrod", "honeydew", "hot", "indian", "ivory"]
_TYPES = [f"{a} {b} {c}"
          for a in ("STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY",
                    "PROMO")
          for b in ("ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED")
          for c in ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")]
_BRANDS = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
_CONTAINERS = [f"{a} {b}"
               for a in ("SM", "MED", "LG", "JUMBO", "WRAP")
               for b in ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN",
                         "DRUM")]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]

N_NATIONS = len(_NATIONS)
N_REGIONS = len(_REGIONS)


def tpch_lineitem(scale_rows: int) -> TableSpec:
    n_supp = max(scale_rows // 100, 1)

    def _li_suppkey(cols, rng, n, offset=0):
        # supplier drawn from the part's 4 partsupp suppliers (the real
        # dbgen invariant: lineitem (part,supp) pairs exist in partsupp) —
        # mirrors the affine layout in tpch_partsupp below
        pk = np.asarray(cols["l_partkey"].to_numpy(zero_copy_only=False),
                        np.int64)
        j = rng.integers(0, 4, n)
        return pa.array((31 * pk + 7 * j) % n_supp, pa.int64())

    return TableSpec("lineitem", [
        ColumnSpec("l_orderkey", "key", cardinality=max(scale_rows // 4, 1)),
        ColumnSpec("l_partkey", "key", cardinality=max(scale_rows // 20, 1)),
        ColumnSpec("l_suppkey", "derive", derive=_li_suppkey),
        ColumnSpec("l_quantity", "int", min_val=1, max_val=50),
        ColumnSpec("l_extendedprice", "double", min_val=900.0, max_val=105000.0),
        ColumnSpec("l_discount", "double", min_val=0.0, max_val=0.1),
        ColumnSpec("l_tax", "double", min_val=0.0, max_val=0.08),
        ColumnSpec("l_returnflag", "string", cardinality=3, max_len=1,
                   alphabet="RAN"),
        ColumnSpec("l_linestatus", "string", cardinality=2, max_len=1,
                   alphabet="OF"),
        ColumnSpec("l_shipdate", "date", min_val=8035, max_val=10590),
        ColumnSpec("l_commitdate", "date", min_val=8035, max_val=10590),
        ColumnSpec("l_receiptdate", "date", min_val=8035, max_val=10590),
        ColumnSpec("l_shipmode", "choice", values=_SHIPMODES),
        ColumnSpec("l_shipinstruct", "choice", values=_SHIPINSTRUCT),
    ])


def tpch_orders(scale_rows: int) -> TableSpec:
    return TableSpec("orders", [
        ColumnSpec("o_orderkey", "seq"),
        # 2/3 of the customer domain: like dbgen, a third of customers have
        # placed no orders (q13/q22 exercise exactly that population)
        ColumnSpec("o_custkey", "key",
                   cardinality=max(2 * scale_rows // 30, 1)),
        ColumnSpec("o_orderdate", "date", min_val=8035, max_val=10590),
        ColumnSpec("o_totalprice", "double", min_val=800.0, max_val=600000.0),
        ColumnSpec("o_orderpriority", "choice", values=_PRIORITIES),
        ColumnSpec("o_orderstatus", "choice", values=["O", "F", "P"]),
    ])


def tpch_customer(scale_rows: int) -> TableSpec:
    return TableSpec("customer", [
        ColumnSpec("c_custkey", "seq"),
        ColumnSpec("c_name", "string", max_len=18),
        ColumnSpec("c_mktsegment", "choice", values=_SEGMENTS),
        ColumnSpec("c_acctbal", "double", min_val=-1000.0, max_val=10000.0),
        ColumnSpec("c_nationkey", "seq", modulo=N_NATIONS),
        ColumnSpec("c_phone", "string", alphabet="0123456789-", max_len=15),
    ])


def tpch_supplier(scale_rows: int) -> TableSpec:
    return TableSpec("supplier", [
        ColumnSpec("s_suppkey", "seq"),
        ColumnSpec("s_name", "string", max_len=18),
        ColumnSpec("s_nationkey", "seq", modulo=N_NATIONS),
        ColumnSpec("s_acctbal", "double", min_val=-1000.0, max_val=10000.0),
        # a minority of comments carry the q16 exclusion phrase
        ColumnSpec("s_comment", "choice", values=[
            "quick deliveries", "ironic packages", "silent deposits",
            "Customer not Complaints noted", "regular accounts",
            "slyly final Customer Complaints", "bold requests"]),
    ])


def tpch_part(scale_rows: int) -> TableSpec:
    return TableSpec("part", [
        ColumnSpec("p_partkey", "seq"),
        ColumnSpec("p_name", "choice", values=[
            f"{a} {b}" for a in _COLORS for b in ("metal", "steel", "satin")]),
        ColumnSpec("p_mfgr", "choice", values=[
            f"Manufacturer#{i}" for i in range(1, 6)]),
        ColumnSpec("p_type", "choice", values=_TYPES),
        ColumnSpec("p_brand", "choice", values=_BRANDS),
        ColumnSpec("p_container", "choice", values=_CONTAINERS),
        ColumnSpec("p_size", "int", min_val=1, max_val=50),
        ColumnSpec("p_retailprice", "double", min_val=900.0, max_val=2000.0),
    ])


def tpch_partsupp(n_parts: int, n_suppliers: int) -> TableSpec:
    # 4 suppliers per part: ps_partkey = (row // 4) % n_parts — the modulo
    # keeps the FK inside part's key domain for ANY generated row count.
    # ps_suppkey is the affine layout lineitem's derive mirrors, so every
    # lineitem (part,supp) pair exists in partsupp (dbgen invariant).
    n_s = max(n_suppliers, 1)

    def _ps_suppkey(cols, rng, n, offset=0):
        pk = np.asarray(cols["ps_partkey"].to_numpy(zero_copy_only=False),
                        np.int64)
        j = (np.arange(offset, offset + n)) % 4
        return pa.array((31 * pk + 7 * j) % n_s, pa.int64())

    return TableSpec("partsupp", [
        ColumnSpec("ps_partkey", "seq", repeat=4, modulo=max(n_parts, 1)),
        ColumnSpec("ps_suppkey", "derive", derive=_ps_suppkey),
        ColumnSpec("ps_availqty", "int", min_val=1, max_val=9999),
        ColumnSpec("ps_supplycost", "double", min_val=1.0, max_val=1000.0),
    ])


def tpch_nation() -> TableSpec:
    return TableSpec("nation", [
        ColumnSpec("n_nationkey", "seq"),
        ColumnSpec("n_name", "choice", values=_NATIONS, sequential=True),
        ColumnSpec("n_regionkey", "seq", modulo=N_REGIONS),
    ])


def tpch_region() -> TableSpec:
    return TableSpec("region", [
        ColumnSpec("r_regionkey", "seq"),
        ColumnSpec("r_name", "choice", values=_REGIONS, sequential=True),
    ])


# --- TPC-DS-style schema (reference integration_tests tpcds suite; the
# dimensional model is the standard's, columns trimmed to what the query
# set touches; date_dim is a REAL calendar so derived columns stay
# consistent) ---------------------------------------------------------------

TPCDS_BASE_DATE = "1998-01-01"
TPCDS_DAYS = 2557  # 7 years, 1998-2004


def tpcds_date_dim(n_days: int = TPCDS_DAYS) -> pa.Table:
    """Deterministic calendar dimension: d_date_sk 0..n-1 maps to real dates
    from TPCDS_BASE_DATE, with year/month/day columns computed from the real
    calendar (consistent under any query)."""
    sk = np.arange(n_days, dtype=np.int64)
    dates = np.datetime64(TPCDS_BASE_DATE) + sk.astype("timedelta64[D]")
    d = dates.astype("datetime64[D]")
    years = d.astype("datetime64[Y]").astype(np.int64) + 1970
    months = d.astype("datetime64[M]").astype(np.int64) % 12 + 1
    dom = (d - d.astype("datetime64[M]")).astype(np.int64) + 1
    dow = (d.astype(np.int64) + 4) % 7  # 1970-01-01 was a Thursday
    day_names = np.array(["Sunday", "Monday", "Tuesday", "Wednesday",
                          "Thursday", "Friday", "Saturday"])
    week_seq = (d.astype(np.int64) + 4) // 7
    return pa.table({
        "d_date_sk": pa.array(sk, pa.int64()),
        "d_date": pa.array(d.astype("datetime64[D]").astype(np.int32)
                           if False else
                           (d - np.datetime64("1970-01-01")).astype(np.int32),
                           pa.date32()),
        "d_year": pa.array(years.astype(np.int32), pa.int32()),
        "d_moy": pa.array(months.astype(np.int32), pa.int32()),
        "d_dom": pa.array(dom.astype(np.int32), pa.int32()),
        "d_qoy": pa.array(((months - 1) // 3 + 1).astype(np.int32),
                          pa.int32()),
        "d_dow": pa.array(dow.astype(np.int32), pa.int32()),
        "d_day_name": pa.array(day_names[dow], pa.string()),
        "d_week_seq": pa.array(week_seq, pa.int64()),
        "d_month_seq": pa.array((years - 1970) * 12 + months - 1, pa.int64()),
    })


_DS_CATEGORIES = ["Books", "Home", "Electronics", "Jewelry", "Men", "Music",
                  "Shoes", "Sports", "Women", "Children"]
_DS_STATES = ["TN", "CA", "TX", "NY", "GA", "OH", "IL", "WA", "MI", "VA"]
_DS_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
                 "4 yr Degree", "Advanced Degree", "Unknown"]
_DS_MARITAL = ["M", "S", "D", "W", "U"]
_DS_BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                     "0-500", "Unknown"]
_DS_CREDIT = ["Low Risk", "Good", "High Risk", "Unknown"]


def tpcds_item(n: int) -> TableSpec:
    return TableSpec("item", [
        ColumnSpec("i_item_sk", "seq"),
        ColumnSpec("i_item_id", "string", cardinality=max(n // 2, 1),
                   alphabet="ABCDEFGHIJKLMNOP", max_len=16),
        ColumnSpec("i_category", "choice", values=_DS_CATEGORIES),
        ColumnSpec("i_class", "choice", values=[
            f"class{i:02d}" for i in range(20)]),
        ColumnSpec("i_brand", "choice", values=[
            f"brand{i:02d}" for i in range(50)]),
        ColumnSpec("i_brand_id", "int", min_val=1000, max_val=10000),
        ColumnSpec("i_manufact_id", "int", min_val=1, max_val=1000),
        ColumnSpec("i_manager_id", "int", min_val=1, max_val=100),
        ColumnSpec("i_current_price", "double", min_val=0.5, max_val=300.0),
        ColumnSpec("i_wholesale_cost", "double", min_val=0.2, max_val=90.0),
        ColumnSpec("i_color", "choice", values=_COLORS),
        ColumnSpec("i_size", "choice", values=[
            "small", "medium", "large", "extra large", "petite", "N/A"]),
    ])


def tpcds_store(n: int = 12) -> TableSpec:
    return TableSpec("store", [
        ColumnSpec("s_store_sk", "seq"),
        ColumnSpec("s_store_id", "string", cardinality=n, max_len=8,
                   alphabet="STORE0123456789"),
        ColumnSpec("s_store_name", "choice", values=[
            f"store_{i}" for i in range(n)], sequential=True),
        ColumnSpec("s_state", "choice", values=_DS_STATES),
        ColumnSpec("s_county", "choice", values=[
            f"county{i}" for i in range(8)]),
        ColumnSpec("s_city", "choice", values=[
            f"city{i}" for i in range(20)]),
        ColumnSpec("s_gmt_offset", "double", min_val=-8.0, max_val=-5.0),
        ColumnSpec("s_number_employees", "int", min_val=200, max_val=300),
    ])


def tpcds_customer(n: int, n_addr: int, n_cdemo: int, n_hdemo: int
                   ) -> TableSpec:
    return TableSpec("customer", [
        ColumnSpec("c_customer_sk", "seq"),
        ColumnSpec("c_customer_id", "string", cardinality=max(n, 1),
                   alphabet="CUSTID0123456789", max_len=16),
        ColumnSpec("c_current_addr_sk", "key", cardinality=max(n_addr, 1)),
        ColumnSpec("c_current_cdemo_sk", "key", cardinality=max(n_cdemo, 1)),
        ColumnSpec("c_current_hdemo_sk", "key", cardinality=max(n_hdemo, 1)),
        ColumnSpec("c_first_name", "string", cardinality=200, max_len=10,
                   alphabet="abcdefghijklmnop"),
        ColumnSpec("c_last_name", "string", cardinality=300, max_len=12,
                   alphabet="abcdefghijklmnop"),
        ColumnSpec("c_birth_year", "int", min_val=1930, max_val=2000),
        ColumnSpec("c_birth_country", "choice", values=_NATIONS),
    ])


def tpcds_customer_address(n: int) -> TableSpec:
    return TableSpec("customer_address", [
        ColumnSpec("ca_address_sk", "seq"),
        ColumnSpec("ca_state", "choice", values=_DS_STATES),
        ColumnSpec("ca_county", "choice", values=[
            f"county{i}" for i in range(8)]),
        ColumnSpec("ca_city", "choice", values=[
            f"city{i}" for i in range(20)]),
        ColumnSpec("ca_zip", "choice", values=[
            f"{z:05d}" for z in range(10000, 10080)]),
        ColumnSpec("ca_country", "choice", values=["United States"]),
        ColumnSpec("ca_gmt_offset", "double", min_val=-8.0, max_val=-5.0),
    ])


def tpcds_customer_demographics(n: int = 1000) -> TableSpec:
    return TableSpec("customer_demographics", [
        ColumnSpec("cd_demo_sk", "seq"),
        ColumnSpec("cd_gender", "choice", values=["M", "F"]),
        ColumnSpec("cd_marital_status", "choice", values=_DS_MARITAL),
        ColumnSpec("cd_education_status", "choice", values=_DS_EDUCATION),
        ColumnSpec("cd_purchase_estimate", "int", min_val=500, max_val=10000),
        ColumnSpec("cd_credit_rating", "choice", values=_DS_CREDIT),
        ColumnSpec("cd_dep_count", "int", min_val=0, max_val=6),
    ])


def tpcds_household_demographics(n: int = 720) -> TableSpec:
    return TableSpec("household_demographics", [
        ColumnSpec("hd_demo_sk", "seq"),
        ColumnSpec("hd_buy_potential", "choice", values=_DS_BUY_POTENTIAL),
        ColumnSpec("hd_dep_count", "int", min_val=0, max_val=9),
        ColumnSpec("hd_vehicle_count", "int", min_val=-1, max_val=4),
        ColumnSpec("hd_income_band_sk", "key", cardinality=20),
    ])


def tpcds_promotion(n: int = 30) -> TableSpec:
    return TableSpec("promotion", [
        ColumnSpec("p_promo_sk", "seq"),
        ColumnSpec("p_channel_email", "choice", values=["Y", "N"]),
        ColumnSpec("p_channel_event", "choice", values=["Y", "N"]),
        ColumnSpec("p_channel_tv", "choice", values=["Y", "N"]),
        ColumnSpec("p_channel_dmail", "choice", values=["Y", "N"]),
    ])


def tpcds_warehouse(n: int = 6) -> TableSpec:
    return TableSpec("warehouse", [
        ColumnSpec("w_warehouse_sk", "seq"),
        ColumnSpec("w_warehouse_name", "choice", values=[
            f"warehouse_{i}" for i in range(n)], sequential=True),
        ColumnSpec("w_state", "choice", values=_DS_STATES),
    ])


def tpcds_time_dim(n: int = 86400) -> TableSpec:
    return TableSpec("time_dim", [
        ColumnSpec("t_time_sk", "seq"),
        ColumnSpec("t_hour", "seq", repeat=3600, modulo=24),
        ColumnSpec("t_minute", "seq", repeat=60, modulo=60),
    ])


def tpcds_web_site(n: int = 8) -> TableSpec:
    return TableSpec("web_site", [
        ColumnSpec("web_site_sk", "seq"),
        ColumnSpec("web_name", "choice", values=[
            f"site_{i}" for i in range(n)], sequential=True),
    ])


def tpcds_ship_mode(n: int = 10) -> TableSpec:
    return TableSpec("ship_mode", [
        ColumnSpec("sm_ship_mode_sk", "seq"),
        ColumnSpec("sm_type", "choice", values=[
            "EXPRESS", "OVERNIGHT", "REGULAR", "TWO DAY", "LIBRARY"]),
        ColumnSpec("sm_carrier", "choice", values=[
            "UPS", "FEDEX", "AIRBORNE", "USPS", "DHL"]),
    ])


def tpcds_reason(n: int = 35) -> TableSpec:
    return TableSpec("reason", [
        ColumnSpec("r_reason_sk", "seq"),
        ColumnSpec("r_reason_desc", "choice",
                   values=[f"reason {i:02d}" for i in range(n)],
                   sequential=True),
    ])


def tpcds_call_center(n: int = 4) -> TableSpec:
    return TableSpec("call_center", [
        ColumnSpec("cc_call_center_sk", "seq"),
        ColumnSpec("cc_name", "choice",
                   values=[f"call_center_{i}" for i in range(n)],
                   sequential=True),
        ColumnSpec("cc_manager", "choice",
                   values=[f"manager_{i}" for i in range(8)]),
    ])


def tpcds_income_band(n: int = 20) -> TableSpec:
    def _lower(cols, rng, m, offset=0):
        return pa.array(np.arange(offset, offset + m, dtype=np.int64)
                        * 10000, pa.int64())

    def _upper(cols, rng, m, offset=0):
        return pa.array((np.arange(offset, offset + m, dtype=np.int64) + 1)
                        * 10000 - 1, pa.int64())

    return TableSpec("income_band", [
        ColumnSpec("ib_income_band_sk", "seq"),
        ColumnSpec("ib_lower_bound", "derive", derive=_lower),
        ColumnSpec("ib_upper_bound", "derive", derive=_upper),
    ])


def _sales_money_cols(prefix: str):
    p = prefix
    return [
        ColumnSpec(f"{p}_quantity", "int", min_val=1, max_val=100,
                   null_prob=0.02),
        ColumnSpec(f"{p}_wholesale_cost", "double", min_val=1.0,
                   max_val=100.0),
        ColumnSpec(f"{p}_list_price", "double", min_val=1.0, max_val=300.0),
        ColumnSpec(f"{p}_sales_price", "double", min_val=0.0, max_val=300.0,
                   null_prob=0.02),
        ColumnSpec(f"{p}_ext_discount_amt", "double", min_val=0.0,
                   max_val=1000.0),
        ColumnSpec(f"{p}_ext_sales_price", "double", min_val=0.0,
                   max_val=30000.0),
        ColumnSpec(f"{p}_ext_wholesale_cost", "double", min_val=1.0,
                   max_val=10000.0),
        ColumnSpec(f"{p}_ext_list_price", "double", min_val=1.0,
                   max_val=30000.0),
        ColumnSpec(f"{p}_ext_tax", "double", min_val=0.0, max_val=3000.0),
        ColumnSpec(f"{p}_coupon_amt", "double", min_val=0.0, max_val=500.0),
        ColumnSpec(f"{p}_net_paid", "double", min_val=0.0, max_val=30000.0),
        ColumnSpec(f"{p}_net_profit", "double", min_val=-5000.0,
                   max_val=10000.0),
    ]


def tpcds_store_sales(rows: int, n_items: int, n_cust: int, n_stores: int,
                      n_cdemo: int, n_hdemo: int, n_addr: int,
                      n_promo: int) -> TableSpec:
    """Item and customer are DETERMINISTIC functions of the row / ticket
    (item = (17·row+5) mod n_items, customer = 13·ticket mod n_cust), the
    dsdgen invariant that store_returns rows reference real sales — so
    sales⋈returns joins on (customer, item, ticket) actually match."""
    ni, nc = max(n_items, 1), max(n_cust, 1)

    def _ss_item(cols, rng, n, offset=0):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return pa.array((17 * idx + 5) % ni, pa.int64())

    def _ss_cust(cols, rng, n, offset=0):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return pa.array((13 * (idx // 4)) % nc, pa.int64())

    return TableSpec("store_sales", [
        ColumnSpec("ss_sold_date_sk", "key", cardinality=TPCDS_DAYS,
                   null_prob=0.01),
        ColumnSpec("ss_sold_time_sk", "key", cardinality=86400),
        ColumnSpec("ss_item_sk", "derive", derive=_ss_item),
        ColumnSpec("ss_customer_sk", "derive", derive=_ss_cust),
        ColumnSpec("ss_cdemo_sk", "key", cardinality=max(n_cdemo, 1)),
        ColumnSpec("ss_hdemo_sk", "key", cardinality=max(n_hdemo, 1)),
        ColumnSpec("ss_addr_sk", "key", cardinality=max(n_addr, 1)),
        ColumnSpec("ss_store_sk", "key", cardinality=max(n_stores, 1)),
        ColumnSpec("ss_promo_sk", "key", cardinality=max(n_promo, 1)),
        ColumnSpec("ss_ticket_number", "seq", repeat=4),
        *_sales_money_cols("ss"),
    ])


def tpcds_store_returns(rows: int, n_items: int, n_cust: int, n_stores: int,
                        n_tickets: int) -> TableSpec:
    """Each return references a real sale: ticket is random, and
    (item, customer) are re-derived from the ticket with the same affine
    layout store_sales uses."""
    ni, nc, nt = max(n_items, 1), max(n_cust, 1), max(n_tickets, 1)

    def _sr_item(cols, rng, n, offset=0):
        t = np.asarray(cols["sr_ticket_number"].to_numpy(
            zero_copy_only=False), np.int64)
        j = rng.integers(0, 4, n)
        return pa.array((17 * (4 * t + j) + 5) % ni, pa.int64())

    def _sr_cust(cols, rng, n, offset=0):
        t = np.asarray(cols["sr_ticket_number"].to_numpy(
            zero_copy_only=False), np.int64)
        return pa.array((13 * t) % nc, pa.int64())

    return TableSpec("store_returns", [
        ColumnSpec("sr_returned_date_sk", "key", cardinality=TPCDS_DAYS),
        ColumnSpec("sr_ticket_number", "key", cardinality=nt),
        ColumnSpec("sr_item_sk", "derive", derive=_sr_item),
        ColumnSpec("sr_customer_sk", "derive", derive=_sr_cust),
        ColumnSpec("sr_store_sk", "key", cardinality=max(n_stores, 1)),
        ColumnSpec("sr_reason_sk", "key", cardinality=35),
        ColumnSpec("sr_return_quantity", "int", min_val=1, max_val=40),
        ColumnSpec("sr_return_amt", "double", min_val=0.0, max_val=5000.0),
        ColumnSpec("sr_net_loss", "double", min_val=0.0, max_val=3000.0),
    ])


def tpcds_catalog_sales(rows: int, n_items: int, n_cust: int, n_cdemo: int,
                        n_hdemo: int, n_addr: int, n_promo: int,
                        n_wh: int) -> TableSpec:
    return TableSpec("catalog_sales", [
        ColumnSpec("cs_sold_date_sk", "key", cardinality=TPCDS_DAYS,
                   null_prob=0.01),
        ColumnSpec("cs_ship_date_sk", "key", cardinality=TPCDS_DAYS),
        ColumnSpec("cs_item_sk", "key", cardinality=max(n_items, 1)),
        ColumnSpec("cs_bill_customer_sk", "key", cardinality=max(n_cust, 1)),
        ColumnSpec("cs_bill_cdemo_sk", "key", cardinality=max(n_cdemo, 1)),
        ColumnSpec("cs_bill_hdemo_sk", "key", cardinality=max(n_hdemo, 1)),
        ColumnSpec("cs_bill_addr_sk", "key", cardinality=max(n_addr, 1)),
        ColumnSpec("cs_promo_sk", "key", cardinality=max(n_promo, 1)),
        ColumnSpec("cs_warehouse_sk", "key", cardinality=max(n_wh, 1)),
        ColumnSpec("cs_ship_mode_sk", "key", cardinality=10),
        ColumnSpec("cs_call_center_sk", "key", cardinality=4),
        ColumnSpec("cs_order_number", "seq", repeat=3),
        ColumnSpec("cs_sold_time_sk", "key", cardinality=86400),
        *_sales_money_cols("cs"),
    ])


def tpcds_catalog_returns(rows: int, n_items: int, n_orders: int,
                          n_cust: int = 100) -> TableSpec:
    return TableSpec("catalog_returns", [
        ColumnSpec("cr_returned_date_sk", "key", cardinality=TPCDS_DAYS),
        ColumnSpec("cr_item_sk", "key", cardinality=max(n_items, 1)),
        ColumnSpec("cr_order_number", "key", cardinality=max(n_orders, 1)),
        ColumnSpec("cr_return_quantity", "int", min_val=1, max_val=40),
        ColumnSpec("cr_return_amount", "double", min_val=0.0, max_val=5000.0),
        ColumnSpec("cr_net_loss", "double", min_val=0.0, max_val=3000.0),
        ColumnSpec("cr_returning_customer_sk", "key",
                   cardinality=max(n_cust, 1)),
        ColumnSpec("cr_call_center_sk", "key", cardinality=4),
    ])


def tpcds_web_sales(rows: int, n_items: int, n_cust: int, n_addr: int,
                    n_sites: int, n_promo: int, n_wh: int = 6) -> TableSpec:
    return TableSpec("web_sales", [
        ColumnSpec("ws_sold_date_sk", "key", cardinality=TPCDS_DAYS,
                   null_prob=0.01),
        ColumnSpec("ws_ship_date_sk", "key", cardinality=TPCDS_DAYS),
        ColumnSpec("ws_sold_time_sk", "key", cardinality=86400),
        ColumnSpec("ws_item_sk", "key", cardinality=max(n_items, 1)),
        ColumnSpec("ws_bill_customer_sk", "key", cardinality=max(n_cust, 1)),
        ColumnSpec("ws_bill_addr_sk", "key", cardinality=max(n_addr, 1)),
        ColumnSpec("ws_web_site_sk", "key", cardinality=max(n_sites, 1)),
        ColumnSpec("ws_ship_mode_sk", "key", cardinality=10),
        ColumnSpec("ws_promo_sk", "key", cardinality=max(n_promo, 1)),
        ColumnSpec("ws_order_number", "seq", repeat=3),
        ColumnSpec("ws_warehouse_sk", "key", cardinality=max(n_wh, 1)),
        *_sales_money_cols("ws"),
    ])


def tpcds_web_returns(rows: int, n_items: int, n_orders: int,
                      n_cust: int = 100) -> TableSpec:
    return TableSpec("web_returns", [
        ColumnSpec("wr_returned_date_sk", "key", cardinality=TPCDS_DAYS),
        ColumnSpec("wr_item_sk", "key", cardinality=max(n_items, 1)),
        ColumnSpec("wr_order_number", "key", cardinality=max(n_orders, 1)),
        ColumnSpec("wr_return_quantity", "int", min_val=1, max_val=40),
        ColumnSpec("wr_return_amt", "double", min_val=0.0, max_val=5000.0),
        ColumnSpec("wr_net_loss", "double", min_val=0.0, max_val=3000.0),
        ColumnSpec("wr_returning_customer_sk", "key",
                   cardinality=max(n_cust, 1)),
        ColumnSpec("wr_reason_sk", "key", cardinality=35),
    ])


def tpcds_inventory(rows: int, n_items: int, n_wh: int) -> TableSpec:
    return TableSpec("inventory", [
        ColumnSpec("inv_date_sk", "key", cardinality=TPCDS_DAYS),
        ColumnSpec("inv_item_sk", "key", cardinality=max(n_items, 1)),
        ColumnSpec("inv_warehouse_sk", "key", cardinality=max(n_wh, 1)),
        ColumnSpec("inv_quantity_on_hand", "int", min_val=0, max_val=1000,
                   null_prob=0.02),
    ])
