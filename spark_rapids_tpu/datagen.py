"""Deterministic scale-test data generator.

Reference: datagen/ (bigDataGen.scala, README.md:1-36) — seed-mapping design:
every cell is a pure function of (seed, table, column, row) so any slice of a
huge dataset regenerates identically without storing it; controllable
cardinality and skew. Used by the scale tests and the TPC-H-style benchmarks
(benchmarks/).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa


def _cell_rng(seed: int, table: str, column: str, part: int) -> np.random.Generator:
    # stable per (seed, table, column, partition) stream — the seed-mapping idea
    key = abs(hash((seed, table, column, part))) % (2**63)
    return np.random.default_rng(key)


class ColumnSpec:
    def __init__(self, name: str, kind: str, *, cardinality: Optional[int] = None,
                 skew: float = 0.0, min_val=None, max_val=None,
                 null_prob: float = 0.0, alphabet: str = "abcdefghij",
                 max_len: int = 12):
        self.name = name
        self.kind = kind  # int/long/double/string/date/bool/key
        self.cardinality = cardinality
        self.skew = skew  # 0 = uniform; >0 zipf-ish concentration
        self.min_val = min_val
        self.max_val = max_val
        self.null_prob = null_prob
        self.alphabet = alphabet
        self.max_len = max_len

    def generate(self, rng: np.random.Generator, n: int) -> pa.Array:
        if self.kind in ("key", "int", "long"):
            if self.cardinality:
                if self.skew > 0:
                    # zipf-like: rank^-skew weights over the key domain
                    ranks = np.arange(1, self.cardinality + 1, dtype=np.float64)
                    w = ranks ** (-self.skew)
                    w /= w.sum()
                    vals = rng.choice(self.cardinality, size=n, p=w)
                else:
                    vals = rng.integers(0, self.cardinality, n)
            else:
                lo = self.min_val if self.min_val is not None else 0
                hi = self.max_val if self.max_val is not None else 2**31 - 1
                vals = rng.integers(lo, hi + 1, n, dtype=np.int64)
            t = pa.int64() if self.kind == "long" else pa.int32()
            arr = pa.array(vals.astype(np.int64 if self.kind == "long" else np.int32), t)
        elif self.kind == "double":
            lo = self.min_val if self.min_val is not None else 0.0
            hi = self.max_val if self.max_val is not None else 1.0
            arr = pa.array(rng.random(n) * (hi - lo) + lo, pa.float64())
        elif self.kind == "bool":
            arr = pa.array(rng.integers(0, 2, n).astype(bool))
        elif self.kind == "date":
            lo = self.min_val if self.min_val is not None else 8000
            hi = self.max_val if self.max_val is not None else 12000
            arr = pa.array(rng.integers(lo, hi, n).astype(np.int32), pa.date32())
        elif self.kind == "string":
            card = self.cardinality or 0
            if card:
                # dictionary of `card` distinct strings, zipf-weighted picks
                dict_rng = np.random.default_rng(card * 7919 + 13)
                lens = dict_rng.integers(1, self.max_len + 1, card)
                words = ["".join(self.alphabet[c] for c in
                                 dict_rng.integers(0, len(self.alphabet), l))
                         for l in lens]
                idx = rng.integers(0, card, n)
                arr = pa.array([words[i] for i in idx])
            else:
                lens = rng.integers(0, self.max_len + 1, n)
                chars = rng.integers(0, len(self.alphabet), int(lens.sum()))
                out, pos = [], 0
                for l in lens:
                    out.append("".join(self.alphabet[c]
                                       for c in chars[pos:pos + l]))
                    pos += l
                arr = pa.array(out)
        else:
            raise ValueError(f"unknown column kind {self.kind}")
        if self.null_prob > 0:
            mask = rng.random(n) < self.null_prob
            arr = pa.array([None if m else v
                            for v, m in zip(arr.to_pylist(), mask)],
                           type=arr.type)
        return arr


class TableSpec:
    def __init__(self, name: str, columns: Sequence[ColumnSpec]):
        self.name = name
        self.columns = list(columns)

    def generate_partition(self, seed: int, part: int, rows: int) -> pa.Table:
        cols = {}
        for c in self.columns:
            rng = _cell_rng(seed, self.name, c.name, part)
            cols[c.name] = c.generate(rng, rows)
        return pa.table(cols)

    def generate(self, seed: int, rows: int, partitions: int = 1) -> pa.Table:
        per = rows // partitions
        tables = [self.generate_partition(seed, p,
                                          per + (1 if p < rows % partitions else 0))
                  for p in range(partitions)]
        return pa.concat_tables(tables)


# --- TPC-H-style schema at a given scale (rows ~ SF * base) -----------------

def tpch_lineitem(scale_rows: int) -> TableSpec:
    return TableSpec("lineitem", [
        ColumnSpec("l_orderkey", "key", cardinality=max(scale_rows // 4, 1)),
        ColumnSpec("l_partkey", "key", cardinality=max(scale_rows // 20, 1)),
        ColumnSpec("l_quantity", "int", min_val=1, max_val=50),
        ColumnSpec("l_extendedprice", "double", min_val=900.0, max_val=105000.0),
        ColumnSpec("l_discount", "double", min_val=0.0, max_val=0.1),
        ColumnSpec("l_tax", "double", min_val=0.0, max_val=0.08),
        ColumnSpec("l_returnflag", "string", cardinality=3, max_len=1,
                   alphabet="RAN"),
        ColumnSpec("l_linestatus", "string", cardinality=2, max_len=1,
                   alphabet="OF"),
        ColumnSpec("l_shipdate", "date", min_val=8035, max_val=10590),
    ])


def tpch_orders(scale_rows: int) -> TableSpec:
    return TableSpec("orders", [
        ColumnSpec("o_orderkey", "key", cardinality=max(scale_rows, 1)),
        ColumnSpec("o_custkey", "key", cardinality=max(scale_rows // 10, 1)),
        ColumnSpec("o_orderdate", "date", min_val=8035, max_val=10590),
        ColumnSpec("o_totalprice", "double", min_val=800.0, max_val=600000.0),
    ])


def tpch_customer(scale_rows: int) -> TableSpec:
    return TableSpec("customer", [
        ColumnSpec("c_custkey", "key", cardinality=max(scale_rows, 1)),
        ColumnSpec("c_mktsegment", "string", cardinality=5, max_len=1,
                   alphabet="ABCDE"),
        ColumnSpec("c_acctbal", "double", min_val=-1000.0, max_val=10000.0),
    ])
