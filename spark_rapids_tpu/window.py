"""Window specification API (pyspark.sql.Window-shaped).

Reference: window/GpuWindowExec.scala + GpuWindowExpression.scala. Frame model:
row-based frames with the Spark boundary constants; range frames currently
support only UNBOUNDED/CURRENT combinations (the common cases; full range
frames follow with the datetime work).
"""

from __future__ import annotations

import sys
from typing import List, Optional, Sequence

from .expressions.base import Expression, UnresolvedAttribute

UNBOUNDED_PRECEDING = -sys.maxsize
UNBOUNDED_FOLLOWING = sys.maxsize
CURRENT_ROW = 0


class WindowSpec:
    def __init__(self, partition_by: Sequence[Expression] = (),
                 order_by: Sequence = (),
                 frame: Optional[tuple] = None,
                 frame_type: str = "rows"):
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        self.frame = frame  # (start, end) in row offsets, None = default
        self.frame_type = frame_type

    def partitionBy(self, *cols) -> "WindowSpec":
        from .session import _expr
        exprs = [UnresolvedAttribute(c) if isinstance(c, str) else _expr(c)
                 for c in cols]
        return WindowSpec(exprs, self.order_by, self.frame, self.frame_type)

    def orderBy(self, *cols) -> "WindowSpec":
        from .plan.logical import SortOrder
        from .session import _expr
        orders = []
        for c in cols:
            if isinstance(c, SortOrder):
                orders.append(c)
            else:
                e = UnresolvedAttribute(c) if isinstance(c, str) else _expr(c)
                orders.append(SortOrder(e, True))
        return WindowSpec(self.partition_by, orders, self.frame, self.frame_type)

    def rowsBetween(self, start: int, end: int) -> "WindowSpec":
        return WindowSpec(self.partition_by, self.order_by, (start, end), "rows")

    def rangeBetween(self, start: int, end: int) -> "WindowSpec":
        if (start, end) not in ((UNBOUNDED_PRECEDING, CURRENT_ROW),
                                (UNBOUNDED_PRECEDING, UNBOUNDED_FOLLOWING),
                                (CURRENT_ROW, UNBOUNDED_FOLLOWING)):
            raise NotImplementedError(
                "general range frames not yet supported; use rowsBetween")
        return WindowSpec(self.partition_by, self.order_by, (start, end), "range")


class Window:
    unboundedPreceding = UNBOUNDED_PRECEDING
    unboundedFollowing = UNBOUNDED_FOLLOWING
    currentRow = CURRENT_ROW

    @staticmethod
    def partitionBy(*cols) -> WindowSpec:
        return WindowSpec().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols) -> WindowSpec:
        return WindowSpec().orderBy(*cols)


class WindowFunction(Expression):
    """Ranking/offset window functions (reference GpuWindowExpression rank/
    row_number/lead/lag)."""

    name = ""
    unevaluable = True  # driven by the window exec (reference Unevaluable)

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def pretty(self) -> str:
        return f"{self.name}({', '.join(c.pretty() for c in self.children)})"


class RowNumber(WindowFunction):
    name = "row_number"

    @property
    def dtype(self):
        from .types import IntegerT
        return IntegerT

    @property
    def nullable(self) -> bool:
        return False


class Rank(WindowFunction):
    name = "rank"

    @property
    def dtype(self):
        from .types import IntegerT
        return IntegerT

    @property
    def nullable(self) -> bool:
        return False


class DenseRank(WindowFunction):
    name = "dense_rank"

    @property
    def dtype(self):
        from .types import IntegerT
        return IntegerT

    @property
    def nullable(self) -> bool:
        return False


class NTile(WindowFunction):
    name = "ntile"

    def __init__(self, n: Expression):
        super().__init__(n)

    @property
    def dtype(self):
        from .types import IntegerT
        return IntegerT


class PercentRank(WindowFunction):
    """(rank - 1) / (partition size - 1); 0.0 for single-row partitions
    (reference GpuPercentRank)."""
    name = "percent_rank"

    @property
    def dtype(self):
        from .types import DoubleT
        return DoubleT

    @property
    def nullable(self) -> bool:
        return False


class CumeDist(WindowFunction):
    """Rows ordered at-or-before current (peers included) / partition size
    (reference GpuCumeDist)."""
    name = "cume_dist"

    @property
    def dtype(self):
        from .types import DoubleT
        return DoubleT

    @property
    def nullable(self) -> bool:
        return False


class Lead(WindowFunction):
    name = "lead"

    def __init__(self, child: Expression, offset: int = 1,
                 default: Optional[Expression] = None):
        super().__init__(child)
        self.offset = offset
        self.default = default

    @property
    def dtype(self):
        return self.children[0].dtype


class Lag(WindowFunction):
    name = "lag"

    def __init__(self, child: Expression, offset: int = 1,
                 default: Optional[Expression] = None):
        super().__init__(child)
        self.offset = offset
        self.default = default

    @property
    def dtype(self):
        return self.children[0].dtype


class WindowExpression(Expression):
    """fn OVER spec."""

    unevaluable = True  # driven by the window exec (reference Unevaluable)

    def __init__(self, function: Expression, spec: WindowSpec):
        self.children = (function,)
        self.spec = spec

    @property
    def function(self) -> Expression:
        return self.children[0]

    @property
    def dtype(self):
        return self.function.dtype

    @property
    def nullable(self) -> bool:
        return self.function.nullable

    def pretty(self) -> str:
        parts = []
        if self.spec.partition_by:
            parts.append("PARTITION BY " + ", ".join(
                p.pretty() for p in self.spec.partition_by))
        if self.spec.order_by:
            parts.append("ORDER BY " + ", ".join(
                o.pretty() for o in self.spec.order_by))
        return f"{self.function.pretty()} OVER ({' '.join(parts)})"
