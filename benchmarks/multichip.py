"""MULTICHIP bench: sharded multi-chip query execution over the mesh data
plane (ROADMAP item 2 done-bar).

Runs TPC-H q1/q3/q18 and a TPC-DS sample (q3) through the full framework
twice per query — mesh session (collective exchanges, grouped root
dispatch) vs single-device baseline — via
`spark_rapids_tpu.parallel.sharded.run_mesh_query`, asserting bit-identical
results and O(exchanges) collective launches, then prints ONE compact
parseable JSON summary line LAST (per-chip rows/s, collective-time
breakdown, scaling efficiency vs 1 chip).

Queries are written WITHOUT hand-pruning selects since ISSUE 17: the
logical optimizer's column-pruning pass (plan/optimizer.py, on by
default) narrows every exchange to the referenced columns the way the
hand-written `select`s used to — run() asserts per record that the
planner-pruned plans still run bit-identically with ZERO per-map
exchange fallbacks. String-carrying exchanges (q1's group keys,
q18's final c_name aggregation) ride the collective too since the
dictionary-encode pass landed (`spark.rapids.tpu.exchange.
dictionaryEncode.enabled`): the fabric moves int32 codes plus one
broadcast dictionary per exchange, and the summary records how many
exchanges used it (`string_collectives`, `dict_encode_ms`) — the
per-query `collective_launches` vs `exchanges` split stays the honest
coverage number, now expected to match.

Since the fused dataplane (ISSUE 16) the summary also carries
`compact_fused` (True when every exchange compacted INSIDE the one
cached collective dispatch — False is a regression), the staging-pool
`staging_reuse_hits` counter, and `overlap_segments` (non-zero only when
the opt-in segmented exchange/compute overlap ran; set
``MULTICHIP_OVERLAP=K`` to arm `spark.rapids.tpu.exchange.overlap.*`
with K segments for a round). tools/bench_diff.py gates the
compact/staging phase walls lower-is-better and treats the two new
counters as neutral.

Usage: python benchmarks/multichip.py [--devices N] [--rows N]
(on a machine without N real chips, run through
`__graft_entry__.dryrun_multichip`, which virtualizes an N-device CPU
platform first).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _tpch_tables(s, rows: int, parts: int):
    import benchmarks.tpch as tpch
    return tpch.load_tables(s, rows, parts=parts)


def _q1(rows: int, parts: int):
    def build(s):
        import benchmarks.tpch as tpch
        return tpch.q1(s, _tpch_tables(s, rows, parts))
    return build


def _q3(rows: int, parts: int):
    """TPC-H q3, unpruned: the optimizer's ColumnPruning pass narrows the
    scans and exchange payloads to keys/dates/doubles (what the hand-
    written selects did through r07), so the whole query rides the
    collective data plane."""
    def build(s):
        import spark_rapids_tpu.functions as F
        t = _tpch_tables(s, rows, parts)
        cust = t["customer"].filter(F.col("c_mktsegment") == "BUILDING")
        orders = t["orders"]
        li = t["lineitem"]
        return (cust.join(orders, on=cust["c_custkey"] == orders["o_custkey"])
                .join(li, on=orders["o_orderkey"] == li["l_orderkey"])
                .withColumn("revenue",
                            F.col("l_extendedprice")
                            * (1 - F.col("l_discount")))
                .groupBy("o_orderkey", "o_orderdate")
                .agg(F.sum(F.col("revenue")).alias("revenue"))
                .sort(F.col("revenue").desc(), "o_orderkey")
                .limit(10))
    return build


def _q18(rows: int, parts: int):
    """TPC-H q18, unpruned and FAITHFUL on the group keys: the final
    aggregation groups on c_name + c_custkey like the spec query — the
    c_name string payload rides the collective as dictionary codes (the
    r06 round had to substitute c_custkey to stay fixed-width). Column
    pruning is the optimizer's job now, including the lineitem relation
    referenced on BOTH join branches."""
    def build(s):
        import spark_rapids_tpu.functions as F
        t = _tpch_tables(s, rows, parts)
        li = t["lineitem"]
        orders = t["orders"]
        cust = t["customer"]
        big = (li.groupBy("l_orderkey")
               .agg(F.sum(F.col("l_quantity")).alias("total_qty"))
               .filter(F.col("total_qty") > 150))
        return (orders
                .join(big, on=orders["o_orderkey"] == big["l_orderkey"],
                      how="leftsemi")
                .join(cust, on=orders["o_custkey"] == cust["c_custkey"])
                .join(li, on=orders["o_orderkey"] == li["l_orderkey"])
                .groupBy("c_name", "c_custkey", "o_orderkey",
                         "o_orderdate", "o_totalprice")
                .agg(F.sum(F.col("l_quantity")).alias("sum_qty"))
                .sort(F.col("o_totalprice").desc(), "o_orderdate")
                .limit(100))
    return build


def _tpcds_q3(rows: int, parts: int):
    """TPC-DS q3 sample, unpruned: the optimizer narrows the exchange
    payloads to fixed width (the group keys use the brand ID, not the
    brand string; the name resolves from item downstream in a real
    report)."""
    def build(s):
        import benchmarks.tpcds as tpcds
        import spark_rapids_tpu.functions as F
        t = tpcds.load_tables(s, rows, parts=parts)
        ss = t["store_sales"]
        item = t["item"].filter(F.col("i_manufact_id").between(100, 250))
        nov = t["date_dim"].filter(F.col("d_moy") == 11)
        return (ss.join(nov, on=ss["ss_sold_date_sk"] == nov["d_date_sk"])
                .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
                .groupBy("d_year", "i_brand_id")
                .agg(F.sum(F.col("ss_ext_sales_price")).alias("sum_agg"))
                .sort("d_year", F.col("sum_agg").desc(), "i_brand_id")
                .limit(100))
    return build


def run(n_devices: int, rows: int) -> dict:
    """All four stages; a stage failure records itself and the remaining
    stages still run (same discipline as bench.py)."""
    from spark_rapids_tpu.parallel.sharded import run_mesh_query, summarize

    # identical batch segmentation in BOTH runs (one batch per reduce
    # partition): float partial-aggregation is only bit-reproducible under
    # identical segmentation — the collective emits ONE block per reduce
    # partition while the per-map path coalesces several, and a different
    # batch split changes the float accumulation order (same property as
    # the reference's GPU-vs-CPU aggregation). Pinning the batch size to
    # the input isolates what the bit-identity check is FOR: the data
    # plane moves every row to the right shard, unchanged.
    extra = {"spark.rapids.sql.batchSizeRows": str(max(rows, 1 << 16))}
    # opt-in overlap round (ISSUE 16): MULTICHIP_OVERLAP=K arms the
    # segmented exchange/compute overlap; bit-identity still asserts
    overlap_k = int(os.environ.get("MULTICHIP_OVERLAP", "0") or 0)
    if overlap_k > 1:
        extra.update({
            "spark.rapids.tpu.exchange.overlap.enabled": "true",
            "spark.rapids.tpu.exchange.overlap.segments": str(overlap_k),
        })
    # fact tables load with parts == mesh size so BOTH plans (mesh and
    # baseline) are structurally identical: the planner sizes exchanges by
    # min(shuffle.partitions, child partitions), so fewer input parts would
    # give the baseline narrower exchanges than the aligned mesh plan —
    # structurally different plans aggregate floats in different orders
    stages = [
        ("tpch_q1", _q1(rows, n_devices), rows),
        ("tpch_q3", _q3(rows, n_devices), rows),
        ("tpch_q18", _q18(rows, n_devices), rows),
        ("tpcds_q3", _tpcds_q3(rows, n_devices), rows),
    ]
    records, input_rows, errors, elapsed = [], {}, {}, {}
    for name, build, n_rows in stages:
        t0 = time.perf_counter()
        try:
            rec = run_mesh_query(name, build, n_devices=n_devices,
                                 extra_conf=extra)
            # ISSUE 17 gate: the hand-written pruning selects are gone —
            # the optimizer-pruned plans must STILL run bit-identically
            # over the collective plane with zero per-map fallbacks
            assert rec["bit_identical"], \
                f"{name}: optimizer-pruned plan not bit-identical"
            assert rec["collective_launches_O_exchanges"], \
                f"{name}: collective launches not O(exchanges)"
            assert not rec["per_map_reasons"], \
                (f"{name}: per-map exchange fallbacks after optimizer "
                 f"pruning: {rec['per_map_reasons']}")
            records.append(rec)
            input_rows[name] = n_rows
        except Exception as e:  # noqa: BLE001 — keep later stages alive
            errors[name] = f"{type(e).__name__}: {e}"[:300]
        elapsed[name] = round(time.perf_counter() - t0, 1)
    summary = summarize(records, n_devices, input_rows)
    summary["rows"] = rows
    summary["stage_elapsed_s"] = elapsed
    if errors:
        summary["errors"] = errors
    import jax
    summary["platform"] = jax.default_backend()
    summary["records"] = records
    return summary


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="mesh size (default: all visible devices)")
    ap.add_argument("--rows", type=int,
                    default=int(os.environ.get("MULTICHIP_ROWS",
                                               str(1 << 16))))
    args = ap.parse_args()
    import jax
    n = args.devices or len(jax.devices())
    summary = run(n, args.rows)
    records = summary.pop("records", [])
    # full detail first (humans), then the ONE compact machine-read line
    print(json.dumps({"detail": records}, indent=None), flush=True)
    print(json.dumps(summary, separators=(",", ":")), flush=True)


if __name__ == "__main__":
    main()
