"""TPC-DS-style benchmark queries through the full framework (reference:
integration_tests tpcds suite; BASELINE.md's 99-query north star).

32 queries over the simplified TPC-DS dimensional model from
spark_rapids_tpu.datagen (tpcds_*): the standard's join/aggregate shapes with
correlated subqueries hand-decorrelated the way Spark's optimizer lowers
them — grouped-agg joins, semi/anti joins, cross-joined scalar aggregates,
windowed ratios, rollups. Every query has a CPU-oracle equality test in
tests/test_tpcds.py.

Usage: python benchmarks/tpcds.py [--rows N] [--queries q3,q7,...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def make_session(tpu: bool):
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.sql.enabled": str(tpu).lower(),
                       "spark.rapids.shuffle.mode":
                           "ICI" if tpu else "MULTITHREADED",
                       "spark.sql.shuffle.partitions": "4"})


def load_tables(s, rows: int, parts: int = 4):
    """All tables at store_sales-row scale `rows` (other facts/dims scaled
    by TPC-DS-like ratios)."""
    from spark_rapids_tpu import datagen as dg

    n_items = max(rows // 50, 30)
    n_cust = max(rows // 40, 50)
    n_addr = max(n_cust // 2, 25)
    n_cdemo = 400
    n_hdemo = 144
    n_stores = 12
    n_promo = 30
    n_wh = 6
    n_sites = 8
    n_cs = max(rows // 2, 1)
    n_ws = max(rows // 4, 1)
    n_sr = max(rows // 10, 1)
    n_cr = max(n_cs // 10, 1)
    n_wr = max(n_ws // 10, 1)
    n_inv = max(rows // 4, 1)

    def df(spec, n, p=1):
        return s.createDataFrame(spec.generate(42, n, p), num_partitions=p)

    tables = {
        "date_dim": s.createDataFrame(dg.tpcds_date_dim()),
        "item": df(dg.tpcds_item(n_items), n_items),
        "store": df(dg.tpcds_store(), n_stores),
        "customer": df(dg.tpcds_customer(n_cust, n_addr, n_cdemo, n_hdemo),
                       n_cust),
        "customer_address": df(dg.tpcds_customer_address(n_addr), n_addr),
        "customer_demographics": df(dg.tpcds_customer_demographics(),
                                    n_cdemo),
        "household_demographics": df(dg.tpcds_household_demographics(),
                                     n_hdemo),
        "promotion": df(dg.tpcds_promotion(), n_promo),
        "warehouse": df(dg.tpcds_warehouse(), n_wh),
        "web_site": df(dg.tpcds_web_site(), n_sites),
        "ship_mode": df(dg.tpcds_ship_mode(), 10),
        "time_dim": df(dg.tpcds_time_dim(), 86400),
        "store_sales": df(dg.tpcds_store_sales(
            rows, n_items, n_cust, n_stores, n_cdemo, n_hdemo, n_addr,
            n_promo), rows, parts),
        "store_returns": df(dg.tpcds_store_returns(
            n_sr, n_items, n_cust, n_stores, max(rows // 4, 1)), n_sr,
            parts),
        "catalog_sales": df(dg.tpcds_catalog_sales(
            n_cs, n_items, n_cust, n_cdemo, n_hdemo, n_addr, n_promo,
            n_wh), n_cs, parts),
        "catalog_returns": df(dg.tpcds_catalog_returns(
            n_cr, n_items, max(n_cs // 3, 1)), n_cr, parts),
        "web_sales": df(dg.tpcds_web_sales(
            n_ws, n_items, n_cust, n_addr, n_sites, n_promo), n_ws, parts),
        "web_returns": df(dg.tpcds_web_returns(
            n_wr, n_items, max(n_ws // 3, 1)), n_wr, parts),
        "inventory": df(dg.tpcds_inventory(n_inv, n_items, n_wh), n_inv,
                        parts),
    }
    return tables


def _F():
    import spark_rapids_tpu.functions as F
    return F


# --- the queries ------------------------------------------------------------
# Each mirrors the standard's query shape on the simplified schema. Filter
# constants are chosen to select real data from the generator.


def q3(s, t):
    """Brand sales in a month (TPC-DS 3)."""
    F = _F()
    ss, dt, item = t["store_sales"], t["date_dim"], t["item"]
    sel_i = item.filter(F.col("i_manufact_id").between(100, 250))
    nov = dt.filter(F.col("d_moy") == 11)
    return (ss.join(nov, on=ss["ss_sold_date_sk"] == nov["d_date_sk"])
            .join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
            .groupBy("d_year", "i_brand_id", "i_brand")
            .agg(F.sum(F.col("ss_ext_sales_price")).alias("sum_agg"))
            .sort("d_year", F.col("sum_agg").desc(), "i_brand_id")
            .limit(100))


def q7(s, t):
    """Demographic averages (TPC-DS 7)."""
    F = _F()
    ss, cd, dt, item, promo = (t["store_sales"], t["customer_demographics"],
                               t["date_dim"], t["item"], t["promotion"])
    sel_cd = cd.filter((F.col("cd_gender") == "M")
                       & (F.col("cd_marital_status") == "S")
                       & (F.col("cd_education_status") == "College"))
    y = dt.filter(F.col("d_year") == 2000)
    sel_p = promo.filter((F.col("p_channel_email") == "N")
                         | (F.col("p_channel_event") == "N"))
    return (ss.join(sel_cd, on=ss["ss_cdemo_sk"] == sel_cd["cd_demo_sk"])
            .join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
            .join(sel_p, on=ss["ss_promo_sk"] == sel_p["p_promo_sk"])
            .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
            .groupBy("i_item_id")
            .agg(F.avg(F.col("ss_quantity")).alias("agg1"),
                 F.avg(F.col("ss_list_price")).alias("agg2"),
                 F.avg(F.col("ss_coupon_amt")).alias("agg3"),
                 F.avg(F.col("ss_sales_price")).alias("agg4"))
            .sort("i_item_id")
            .limit(100))


def q12(s, t):
    """Web revenue ratio by class over a window (TPC-DS 12)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ws, item, dt = t["web_sales"], t["item"], t["date_dim"]
    sel_i = item.filter(F.col("i_category").isin(
        "Sports", "Books", "Home"))
    days = dt.filter((F.col("d_date") >= F.lit(10371))
                     & (F.col("d_date") <= F.lit(10401)))
    j = (ws.join(sel_i, on=ws["ws_item_sk"] == sel_i["i_item_sk"])
         .join(days, on=ws["ws_sold_date_sk"] == days["d_date_sk"])
         .groupBy("i_item_id", "i_category", "i_class", "i_current_price")
         .agg(F.sum(F.col("ws_ext_sales_price")).alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (j.withColumn(
                "revenueratio",
                F.col("itemrevenue") * 100.0
                / F.sum(F.col("itemrevenue")).over(w))
            .select("i_item_id", "i_category", "i_class", "itemrevenue",
                    "revenueratio")
            .sort("i_category", "i_class", "i_item_id")
            .limit(100))


def q13(s, t):
    """Conditional averages over demographic brackets (TPC-DS 13)."""
    F = _F()
    ss, cd, hd, ca, dt, store = (t["store_sales"],
                                 t["customer_demographics"],
                                 t["household_demographics"],
                                 t["customer_address"], t["date_dim"],
                                 t["store"])
    y = dt.filter(F.col("d_year") == 2001)
    sel_cd = cd.filter(F.col("cd_marital_status").isin("M", "S", "W"))
    sel_hd = hd.filter(F.col("hd_dep_count").isin(1, 3))
    sel_ca = ca.filter(F.col("ca_state").isin("TX", "OH", "CA", "NY", "GA",
                                              "TN"))
    return (ss.join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
            .join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
            .join(sel_cd, on=ss["ss_cdemo_sk"] == sel_cd["cd_demo_sk"])
            .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
            .join(sel_ca, on=ss["ss_addr_sk"] == sel_ca["ca_address_sk"])
            .agg(F.avg(F.col("ss_quantity")).alias("avg_qty"),
                 F.avg(F.col("ss_ext_sales_price")).alias("avg_esp"),
                 F.avg(F.col("ss_ext_wholesale_cost")).alias("avg_ewc"),
                 F.sum(F.col("ss_ext_wholesale_cost")).alias("sum_ewc")))


def q15(s, t):
    """Catalog sales by zip cohort (TPC-DS 15)."""
    F = _F()
    cs, cust, ca, dt = (t["catalog_sales"], t["customer"],
                        t["customer_address"], t["date_dim"])
    q = dt.filter((F.col("d_qoy") == 1) & (F.col("d_year") == 2001))
    zips = [f"{z:05d}" for z in range(10000, 10010)]
    return (cs.join(cust, on=cs["cs_bill_customer_sk"]
                    == cust["c_customer_sk"])
            .join(ca, on=cust["c_current_addr_sk"] == ca["ca_address_sk"])
            .join(q, on=cs["cs_sold_date_sk"] == q["d_date_sk"])
            .filter(F.col("ca_zip").isin(*zips)
                    | F.col("ca_state").isin("CA", "WA", "GA")
                    | (F.col("cs_sales_price") > 250.0))
            .groupBy("ca_zip")
            .agg(F.sum(F.col("cs_sales_price")).alias("total"))
            .sort("ca_zip")
            .limit(100))


def q19(s, t):
    """Brand revenue, manager cohort (TPC-DS 19)."""
    F = _F()
    ss, dt, item, cust, ca, store = (t["store_sales"], t["date_dim"],
                                     t["item"], t["customer"],
                                     t["customer_address"], t["store"])
    sel_i = item.filter(F.col("i_manager_id").between(1, 20))
    m = dt.filter((F.col("d_moy") == 11) & (F.col("d_year") == 1998))
    return (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
            .join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
            .join(cust, on=ss["ss_customer_sk"] == cust["c_customer_sk"])
            .join(ca, on=cust["c_current_addr_sk"] == ca["ca_address_sk"])
            .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
            .filter(F.col("ca_city") != F.col("s_city"))
            .groupBy("i_brand_id", "i_brand", "i_manufact_id")
            .agg(F.sum(F.col("ss_ext_sales_price")).alias("ext_price"))
            .sort(F.col("ext_price").desc(), "i_brand_id")
            .limit(100))


def q20(s, t):
    """Catalog revenue ratio by class over a window (TPC-DS 20)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    cs, item, dt = t["catalog_sales"], t["item"], t["date_dim"]
    sel_i = item.filter(F.col("i_category").isin(
        "Sports", "Books", "Home"))
    days = dt.filter((F.col("d_date") >= F.lit(10371))
                     & (F.col("d_date") <= F.lit(10401)))
    j = (cs.join(sel_i, on=cs["cs_item_sk"] == sel_i["i_item_sk"])
         .join(days, on=cs["cs_sold_date_sk"] == days["d_date_sk"])
         .groupBy("i_item_id", "i_category", "i_class", "i_current_price")
         .agg(F.sum(F.col("cs_ext_sales_price")).alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (j.withColumn(
                "revenueratio",
                F.col("itemrevenue") * 100.0
                / F.sum(F.col("itemrevenue")).over(w))
            .select("i_item_id", "i_category", "i_class", "itemrevenue",
                    "revenueratio")
            .sort("i_category", "i_class", "i_item_id")
            .limit(100))


def q25(s, t):
    """Store sales/returns/catalog profit triple join (TPC-DS 25)."""
    F = _F()
    ss, sr, cs, dt, store, item = (t["store_sales"], t["store_returns"],
                                   t["catalog_sales"], t["date_dim"],
                                   t["store"], t["item"])
    d1 = dt.filter(F.col("d_year") == 2000) \
        .select(F.col("d_date_sk").alias("d1_sk"))
    d2 = dt.filter(F.col("d_year").between(2000, 2002)) \
        .select(F.col("d_date_sk").alias("d2_sk"))
    d3 = dt.filter(F.col("d_year").between(2000, 2002)) \
        .select(F.col("d_date_sk").alias("d3_sk"))
    j = (ss.join(sr, on=(ss["ss_customer_sk"] == sr["sr_customer_sk"])
                 & (ss["ss_item_sk"] == sr["sr_item_sk"])
                 & (ss["ss_ticket_number"] == sr["sr_ticket_number"]))
         .join(cs, on=(sr["sr_customer_sk"] == cs["cs_bill_customer_sk"])
               & (sr["sr_item_sk"] == cs["cs_item_sk"]))
         .join(d1, on=ss["ss_sold_date_sk"] == d1["d1_sk"])
         .join(d2, on=sr["sr_returned_date_sk"] == d2["d2_sk"])
         .join(d3, on=cs["cs_sold_date_sk"] == d3["d3_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(item, on=ss["ss_item_sk"] == item["i_item_sk"]))
    return (j.groupBy("i_item_id", "s_store_id", "s_store_name")
            .agg(F.sum(F.col("ss_net_profit")).alias("store_sales_profit"),
                 F.sum(F.col("sr_net_loss")).alias("store_returns_loss"),
                 F.sum(F.col("cs_net_profit")).alias("catalog_sales_profit"))
            .sort("i_item_id", "s_store_id")
            .limit(100))


def q26(s, t):
    """Catalog demographic averages (TPC-DS 26)."""
    F = _F()
    cs, cd, dt, item, promo = (t["catalog_sales"],
                               t["customer_demographics"], t["date_dim"],
                               t["item"], t["promotion"])
    sel_cd = cd.filter((F.col("cd_gender") == "M")
                       & (F.col("cd_marital_status") == "S")
                       & (F.col("cd_education_status") == "College"))
    y = dt.filter(F.col("d_year") == 2000)
    sel_p = promo.filter((F.col("p_channel_email") == "N")
                         | (F.col("p_channel_event") == "N"))
    return (cs.join(sel_cd, on=cs["cs_bill_cdemo_sk"] == sel_cd["cd_demo_sk"])
            .join(y, on=cs["cs_sold_date_sk"] == y["d_date_sk"])
            .join(sel_p, on=cs["cs_promo_sk"] == sel_p["p_promo_sk"])
            .join(item, on=cs["cs_item_sk"] == item["i_item_sk"])
            .groupBy("i_item_id")
            .agg(F.avg(F.col("cs_quantity")).alias("agg1"),
                 F.avg(F.col("cs_list_price")).alias("agg2"),
                 F.avg(F.col("cs_coupon_amt")).alias("agg3"),
                 F.avg(F.col("cs_sales_price")).alias("agg4"))
            .sort("i_item_id")
            .limit(100))


def q27(s, t):
    """State rollup of store demographics (TPC-DS 27: GROUP BY ROLLUP)."""
    F = _F()
    ss, cd, dt, store, item = (t["store_sales"],
                               t["customer_demographics"], t["date_dim"],
                               t["store"], t["item"])
    sel_cd = cd.filter((F.col("cd_gender") == "F")
                       & (F.col("cd_marital_status") == "M")
                       & (F.col("cd_education_status") == "College"))
    y = dt.filter(F.col("d_year") == 2002)
    sel_s = store.filter(F.col("s_state").isin("TN", "CA", "TX"))
    return (ss.join(sel_cd, on=ss["ss_cdemo_sk"] == sel_cd["cd_demo_sk"])
            .join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
            .join(sel_s, on=ss["ss_store_sk"] == sel_s["s_store_sk"])
            .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
            .rollup("i_item_id", "s_state")
            .agg(F.avg(F.col("ss_quantity")).alias("agg1"),
                 F.avg(F.col("ss_list_price")).alias("agg2"),
                 F.avg(F.col("ss_coupon_amt")).alias("agg3"),
                 F.avg(F.col("ss_sales_price")).alias("agg4"))
            .sort("i_item_id", "s_state")
            .limit(100))


def q29(s, t):
    """Quantity sold/returned/re-sold (TPC-DS 29)."""
    F = _F()
    ss, sr, cs, dt, store, item = (t["store_sales"], t["store_returns"],
                                   t["catalog_sales"], t["date_dim"],
                                   t["store"], t["item"])
    d1 = dt.filter(F.col("d_year") == 1999) \
        .select(F.col("d_date_sk").alias("d1_sk"))
    d2 = dt.filter(F.col("d_year").between(1999, 2001)) \
        .select(F.col("d_date_sk").alias("d2_sk"))
    d3 = dt.filter(F.col("d_year").between(1999, 2001)) \
        .select(F.col("d_date_sk").alias("d3_sk"))
    j = (ss.join(sr, on=(ss["ss_customer_sk"] == sr["sr_customer_sk"])
                 & (ss["ss_item_sk"] == sr["sr_item_sk"])
                 & (ss["ss_ticket_number"] == sr["sr_ticket_number"]))
         .join(cs, on=(sr["sr_customer_sk"] == cs["cs_bill_customer_sk"])
               & (sr["sr_item_sk"] == cs["cs_item_sk"]))
         .join(d1, on=ss["ss_sold_date_sk"] == d1["d1_sk"])
         .join(d2, on=sr["sr_returned_date_sk"] == d2["d2_sk"])
         .join(d3, on=cs["cs_sold_date_sk"] == d3["d3_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(item, on=ss["ss_item_sk"] == item["i_item_sk"]))
    return (j.groupBy("i_item_id", "s_store_id", "s_store_name")
            .agg(F.sum(F.col("ss_quantity")).alias("store_sales_quantity"),
                 F.sum(F.col("sr_return_quantity"))
                 .alias("store_returns_quantity"),
                 F.sum(F.col("cs_quantity")).alias("catalog_sales_quantity"))
            .sort("i_item_id", "s_store_id")
            .limit(100))


def q32(s, t):
    """Excess discount: 1.3 × per-item average (TPC-DS 32 decorrelated)."""
    F = _F()
    cs, item, dt = t["catalog_sales"], t["item"], t["date_dim"]
    sel_i = item.filter(F.col("i_manufact_id") == 977)
    days = dt.filter((F.col("d_date") >= F.lit(10900))
                     & (F.col("d_date") <= F.lit(10990)))
    base = (cs.join(days, on=cs["cs_sold_date_sk"] == days["d_date_sk"])
            .join(sel_i, on=cs["cs_item_sk"] == sel_i["i_item_sk"]))
    thresh = (base.groupBy("i_item_sk")
              .agg((F.avg(F.col("cs_ext_discount_amt")) * 1.3)
                   .alias("disc_thresh"))
              .select(F.col("i_item_sk").alias("th_item"),
                      F.col("disc_thresh")))
    return (base.join(thresh, on=base["i_item_sk"] == thresh["th_item"])
            .filter(F.col("cs_ext_discount_amt") > F.col("disc_thresh"))
            .agg(F.sum(F.col("cs_ext_discount_amt"))
                 .alias("excess_discount_amount")))


def q36(s, t):
    """Gross-margin rollup with rank inside hierarchy level (TPC-DS 36)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    from spark_rapids_tpu.expressions.generators import GroupingExpr
    ss, dt, item, store = (t["store_sales"], t["date_dim"], t["item"],
                           t["store"])
    y = dt.filter(F.col("d_year") == 2001)
    sel_s = store.filter(F.col("s_state").isin("TN", "CA"))
    g = (ss.join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
         .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
         .join(sel_s, on=ss["ss_store_sk"] == sel_s["s_store_sk"])
         .rollup("i_category", "i_class")
         .agg((F.sum(F.col("ss_net_profit"))
               / F.sum(F.col("ss_ext_sales_price"))).alias("gross_margin"),
              F.grouping("i_category").alias("g_cat"),
              F.grouping("i_class").alias("g_class")))
    g = g.withColumn("lochierarchy", F.col("g_cat") + F.col("g_class"))
    w = Window.partitionBy("lochierarchy").orderBy(
        F.col("gross_margin").asc())
    return (g.withColumn("rank_within_parent", F.rank().over(w))
            .select("gross_margin", "i_category", "i_class", "lochierarchy",
                    "rank_within_parent")
            .sort(F.col("lochierarchy").desc(), "i_category",
                  "rank_within_parent")
            .limit(100))


def q37(s, t):
    """Items with inventory in a window joined to catalog sales (TPC-DS 37)."""
    F = _F()
    item, inv, dt, cs = (t["item"], t["inventory"], t["date_dim"],
                         t["catalog_sales"])
    sel_i = item.filter((F.col("i_current_price") >= 20.0)
                        & (F.col("i_current_price") <= 150.0)
                        & F.col("i_manufact_id").between(500, 800))
    days = dt.filter((F.col("d_date") >= F.lit(10300))
                     & (F.col("d_date") <= F.lit(10660)))
    stocked = (inv.filter(F.col("inv_quantity_on_hand").between(100, 500))
               .join(days, on=inv["inv_date_sk"] == days["d_date_sk"])
               .join(sel_i, on=inv["inv_item_sk"] == sel_i["i_item_sk"],
                     how="leftsemi")
               .select(F.col("inv_item_sk").alias("st_item")).distinct())
    return (sel_i.join(stocked, on=sel_i["i_item_sk"] == stocked["st_item"],
                       how="leftsemi")
            .join(cs, on=sel_i["i_item_sk"] == cs["cs_item_sk"],
                  how="leftsemi")
            .select("i_item_id", "i_item_sk", "i_current_price")
            .sort("i_item_id")
            .limit(100))


def q42(s, t):
    """Category revenue in a month (TPC-DS 42)."""
    F = _F()
    ss, dt, item = t["store_sales"], t["date_dim"], t["item"]
    m = dt.filter((F.col("d_moy") == 11) & (F.col("d_year") == 2000))
    return (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
            .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
            .groupBy("d_year", "i_category")
            .agg(F.sum(F.col("ss_ext_sales_price")).alias("total"))
            .sort(F.col("total").desc(), "d_year", "i_category")
            .limit(100))


def q43(s, t):
    """Store sales pivoted by day of week (TPC-DS 43)."""
    F = _F()
    ss, dt, store = t["store_sales"], t["date_dim"], t["store"]
    y = dt.filter(F.col("d_year") == 2000)
    j = (ss.join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"]))
    aggs = []
    for i, day in enumerate(["Sunday", "Monday", "Tuesday", "Wednesday",
                             "Thursday", "Friday", "Saturday"]):
        aggs.append(F.sum(F.when(F.col("d_day_name") == day,
                                 F.col("ss_sales_price"))
                          .otherwise(F.lit(None)))
                    .alias(f"{day[:3].lower()}_sales"))
    return (j.groupBy("s_store_name", "s_store_id")
            .agg(*aggs)
            .sort("s_store_name", "s_store_id")
            .limit(100))


def q48(s, t):
    """Bracketed quantity sum over demographics/address (TPC-DS 48)."""
    F = _F()
    ss, cd, ca, dt, store = (t["store_sales"], t["customer_demographics"],
                             t["customer_address"], t["date_dim"],
                             t["store"])
    y = dt.filter(F.col("d_year") == 2000)
    j = (ss.join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
         .join(cd, on=ss["ss_cdemo_sk"] == cd["cd_demo_sk"])
         .join(ca, on=ss["ss_addr_sk"] == ca["ca_address_sk"]))
    b1 = ((F.col("cd_marital_status") == "M")
          & (F.col("cd_education_status") == "4 yr Degree")
          & F.col("ss_sales_price").between(100.0, 150.0))
    b2 = ((F.col("cd_marital_status") == "D")
          & (F.col("cd_education_status") == "2 yr Degree")
          & F.col("ss_sales_price").between(50.0, 100.0))
    b3 = ((F.col("cd_marital_status") == "S")
          & (F.col("cd_education_status") == "College")
          & F.col("ss_sales_price").between(150.0, 200.0))
    return (j.filter(b1 | b2 | b3)
            .agg(F.sum(F.col("ss_quantity")).alias("total_quantity")))


def q50(s, t):
    """Return latency day-buckets per store (TPC-DS 50)."""
    F = _F()
    ss, sr, dt, store = (t["store_sales"], t["store_returns"],
                         t["date_dim"], t["store"])
    d2 = dt.filter((F.col("d_year") == 2001) & (F.col("d_moy") == 8)) \
        .select(F.col("d_date_sk").alias("ret_sk"))
    j = (ss.join(sr, on=(ss["ss_ticket_number"] == sr["sr_ticket_number"])
                 & (ss["ss_item_sk"] == sr["sr_item_sk"])
                 & (ss["ss_customer_sk"] == sr["sr_customer_sk"]))
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(d2, on=sr["sr_returned_date_sk"] == d2["ret_sk"]))
    lag = F.col("sr_returned_date_sk") - F.col("ss_sold_date_sk")
    return (j.groupBy("s_store_name", "s_store_id")
            .agg(F.sum(F.when(lag <= 30, 1).otherwise(0)).alias("d30"),
                 F.sum(F.when((lag > 30) & (lag <= 60), 1).otherwise(0))
                 .alias("d31_60"),
                 F.sum(F.when((lag > 60) & (lag <= 90), 1).otherwise(0))
                 .alias("d61_90"),
                 F.sum(F.when((lag > 90) & (lag <= 120), 1).otherwise(0))
                 .alias("d91_120"),
                 F.sum(F.when(lag > 120, 1).otherwise(0)).alias("d_gt120"))
            .sort("s_store_name", "s_store_id")
            .limit(100))


def q52(s, t):
    """Brand extended price in a month (TPC-DS 52)."""
    F = _F()
    ss, dt, item = t["store_sales"], t["date_dim"], t["item"]
    m = dt.filter((F.col("d_moy") == 11) & (F.col("d_year") == 2000))
    return (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
            .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
            .groupBy("d_year", "i_brand_id", "i_brand")
            .agg(F.sum(F.col("ss_ext_sales_price")).alias("ext_price"))
            .sort("d_year", F.col("ext_price").desc(), "i_brand_id")
            .limit(100))


def q53(s, t):
    """Manufacturer quarterly sales vs average (TPC-DS 53)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ss, dt, item, store = (t["store_sales"], t["date_dim"], t["item"],
                           t["store"])
    months = dt.filter(F.col("d_month_seq").between(350, 361))
    sel_i = item.filter(F.col("i_class").isin(
        "class01", "class03", "class05", "class07"))
    g = (ss.join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
         .join(months, on=ss["ss_sold_date_sk"] == months["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .groupBy("i_manufact_id", "d_qoy")
         .agg(F.sum(F.col("ss_sales_price")).alias("sum_sales")))
    w = Window.partitionBy("i_manufact_id")
    g = g.withColumn("avg_quarterly_sales",
                     F.avg(F.col("sum_sales")).over(w))
    return (g.filter(
                F.when(F.col("avg_quarterly_sales") > 0.0,
                       F.abs(F.col("sum_sales")
                             - F.col("avg_quarterly_sales"))
                       / F.col("avg_quarterly_sales"))
                .otherwise(F.lit(None)) > 0.1)
            .select("i_manufact_id", "sum_sales", "avg_quarterly_sales")
            .sort("avg_quarterly_sales", F.col("sum_sales").desc(),
                  "i_manufact_id")
            .limit(100))


def q55(s, t):
    """Brand revenue for one manager month (TPC-DS 55)."""
    F = _F()
    ss, dt, item = t["store_sales"], t["date_dim"], t["item"]
    m = dt.filter((F.col("d_moy") == 11) & (F.col("d_year") == 1999))
    sel_i = item.filter(F.col("i_manager_id").between(20, 40))
    return (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
            .join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
            .groupBy("i_brand_id", "i_brand")
            .agg(F.sum(F.col("ss_ext_sales_price")).alias("ext_price"))
            .sort(F.col("ext_price").desc(), "i_brand_id")
            .limit(100))


def q61(s, t):
    """Promotional to total revenue ratio (TPC-DS 61)."""
    F = _F()
    ss, promo, dt, store, cust, ca, item = (
        t["store_sales"], t["promotion"], t["date_dim"], t["store"],
        t["customer"], t["customer_address"], t["item"])
    m = dt.filter((F.col("d_year") == 1998) & (F.col("d_moy") == 11))
    sel_i = item.filter(F.col("i_category") == "Jewelry")
    sel_ca = ca.filter(F.col("ca_gmt_offset") <= -6.0)
    base = (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
            .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
            .join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
            .join(cust, on=ss["ss_customer_sk"] == cust["c_customer_sk"])
            .join(sel_ca, on=cust["c_current_addr_sk"]
                  == sel_ca["ca_address_sk"]))
    promos = (base.join(promo, on=base["ss_promo_sk"] == promo["p_promo_sk"])
              .filter((F.col("p_channel_dmail") == "Y")
                      | (F.col("p_channel_email") == "Y")
                      | (F.col("p_channel_tv") == "Y"))
              .agg(F.sum(F.col("ss_ext_sales_price")).alias("promotions")))
    total = base.agg(F.sum(F.col("ss_ext_sales_price")).alias("total"))
    return (promos.crossJoin(total)
            .withColumn("ratio",
                        F.col("promotions") * 100.0 / F.col("total")))


def q62(s, t):
    """Web ship-latency day buckets (TPC-DS 62)."""
    F = _F()
    ws, dt, sm, site = (t["web_sales"], t["date_dim"], t["ship_mode"],
                        t["web_site"])
    months = dt.filter(F.col("d_month_seq").between(350, 361)) \
        .select(F.col("d_date_sk").alias("ship_sk"))
    j = (ws.join(months, on=ws["ws_ship_date_sk"] == months["ship_sk"])
         .join(sm, on=ws["ws_ship_mode_sk"] == sm["sm_ship_mode_sk"])
         .join(site, on=ws["ws_web_site_sk"] == site["web_site_sk"]))
    lag = F.col("ws_ship_date_sk") - F.col("ws_sold_date_sk")
    return (j.groupBy("sm_type", "web_name")
            .agg(F.sum(F.when(lag <= 30, 1).otherwise(0)).alias("d30"),
                 F.sum(F.when((lag > 30) & (lag <= 60), 1).otherwise(0))
                 .alias("d31_60"),
                 F.sum(F.when((lag > 60) & (lag <= 90), 1).otherwise(0))
                 .alias("d61_90"),
                 F.sum(F.when((lag > 90) & (lag <= 120), 1).otherwise(0))
                 .alias("d91_120"),
                 F.sum(F.when(lag > 120, 1).otherwise(0)).alias("d_gt120"))
            .sort("sm_type", "web_name")
            .limit(100))


def q63(s, t):
    """Manager monthly sales vs average (TPC-DS 63)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ss, dt, item, store = (t["store_sales"], t["date_dim"], t["item"],
                           t["store"])
    months = dt.filter(F.col("d_month_seq").between(350, 361))
    sel_i = item.filter(F.col("i_category").isin("Books", "Children",
                                                 "Electronics"))
    g = (ss.join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
         .join(months, on=ss["ss_sold_date_sk"] == months["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .groupBy("i_manager_id", "d_moy")
         .agg(F.sum(F.col("ss_sales_price")).alias("sum_sales")))
    w = Window.partitionBy("i_manager_id")
    g = g.withColumn("avg_monthly_sales",
                     F.avg(F.col("sum_sales")).over(w))
    return (g.filter(
                F.when(F.col("avg_monthly_sales") > 0.0,
                       F.abs(F.col("sum_sales")
                             - F.col("avg_monthly_sales"))
                       / F.col("avg_monthly_sales"))
                .otherwise(F.lit(None)) > 0.1)
            .select("i_manager_id", "sum_sales", "avg_monthly_sales")
            .sort("i_manager_id", F.col("avg_monthly_sales").desc(),
                  "sum_sales")
            .limit(100))


def q65(s, t):
    """Stores selling items at <=10% of average revenue (TPC-DS 65)."""
    F = _F()
    ss, dt, store, item = (t["store_sales"], t["date_dim"], t["store"],
                           t["item"])
    months = dt.filter(F.col("d_month_seq").between(350, 361))
    rev = (ss.join(months, on=ss["ss_sold_date_sk"] == months["d_date_sk"])
           .groupBy("ss_store_sk", "ss_item_sk")
           .agg(F.sum(F.col("ss_sales_price")).alias("revenue")))
    avg_rev = (rev.groupBy("ss_store_sk")
               .agg(F.avg(F.col("revenue")).alias("ave"))
               .select(F.col("ss_store_sk").alias("a_store"), F.col("ave")))
    return (rev.join(avg_rev, on=rev["ss_store_sk"] == avg_rev["a_store"])
            .filter(F.col("revenue") <= 0.1 * F.col("ave"))
            .join(store, on=rev["ss_store_sk"] == store["s_store_sk"])
            .join(item, on=rev["ss_item_sk"] == item["i_item_sk"])
            .select("s_store_name", "i_item_id", "revenue")
            .sort("s_store_name", "i_item_id")
            .limit(100))


def q68(s, t):
    """City customer purchase profile (TPC-DS 68)."""
    F = _F()
    ss, dt, store, hd, ca, cust = (t["store_sales"], t["date_dim"],
                                   t["store"], t["household_demographics"],
                                   t["customer_address"], t["customer"])
    days = dt.filter((F.col("d_dom").between(1, 2))
                     & F.col("d_year").isin(1999, 2000, 2001))
    sel_hd = hd.filter((F.col("hd_dep_count") == 4)
                       | (F.col("hd_vehicle_count") == 3))
    sel_ca = ca.select(F.col("ca_address_sk").alias("pos_addr"),
                       F.col("ca_city").alias("bought_city"))
    g = (ss.join(days, on=ss["ss_sold_date_sk"] == days["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
         .join(sel_ca, on=ss["ss_addr_sk"] == sel_ca["pos_addr"])
         .groupBy("ss_ticket_number", "ss_customer_sk", "bought_city")
         .agg(F.sum(F.col("ss_ext_sales_price")).alias("extended_price"),
              F.sum(F.col("ss_ext_list_price")).alias("list_price"),
              F.sum(F.col("ss_ext_tax")).alias("extended_tax")))
    j = (g.join(cust, on=g["ss_customer_sk"] == cust["c_customer_sk"])
         .join(t["customer_address"],
               on=cust["c_current_addr_sk"]
               == t["customer_address"]["ca_address_sk"])
         .filter(F.col("ca_city") != F.col("bought_city")))
    return (j.select("c_last_name", "c_first_name", "ca_city",
                     "bought_city", "ss_ticket_number", "extended_price",
                     "extended_tax", "list_price")
            .sort("c_last_name", "ss_ticket_number")
            .limit(100))


def q73(s, t):
    """Households buying 1-5 tickets (TPC-DS 73)."""
    F = _F()
    ss, dt, store, hd, cust = (t["store_sales"], t["date_dim"], t["store"],
                               t["household_demographics"], t["customer"])
    days = dt.filter(F.col("d_dom").between(1, 2)
                     & F.col("d_year").isin(1999, 2000, 2001))
    sel_hd = hd.filter(F.col("hd_buy_potential").isin(">10000", "Unknown")
                       & (F.col("hd_vehicle_count") > 0))
    g = (ss.join(days, on=ss["ss_sold_date_sk"] == days["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
         .groupBy("ss_ticket_number", "ss_customer_sk")
         .agg(F.count_star().alias("cnt"))
         .filter(F.col("cnt").between(1, 5)))
    return (g.join(cust, on=g["ss_customer_sk"] == cust["c_customer_sk"])
            .select("c_last_name", "c_first_name", "ss_ticket_number",
                    "cnt")
            .sort(F.col("cnt").desc(), "c_last_name")
            .limit(100))


def q79(s, t):
    """Customer city amounts/profit (TPC-DS 79)."""
    F = _F()
    ss, dt, store, hd, cust = (t["store_sales"], t["date_dim"], t["store"],
                               t["household_demographics"], t["customer"])
    days = dt.filter((F.col("d_dow") == 1)
                     & F.col("d_year").isin(1999, 2000, 2001))
    sel_s = store.filter(F.col("s_number_employees").between(200, 295))
    sel_hd = hd.filter((F.col("hd_dep_count") == 6)
                       | (F.col("hd_vehicle_count") > 2))
    g = (ss.join(days, on=ss["ss_sold_date_sk"] == days["d_date_sk"])
         .join(sel_s, on=ss["ss_store_sk"] == sel_s["s_store_sk"])
         .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
         .groupBy("ss_ticket_number", "ss_customer_sk", "s_city")
         .agg(F.sum(F.col("ss_coupon_amt")).alias("amt"),
              F.sum(F.col("ss_net_profit")).alias("profit")))
    return (g.join(cust, on=g["ss_customer_sk"] == cust["c_customer_sk"])
            .select("c_last_name", "c_first_name", "s_city", "amt",
                    "profit", "ss_ticket_number")
            .sort("c_last_name", "c_first_name", "ss_ticket_number")
            .limit(100))


def q82(s, t):
    """Store items with bounded inventory (TPC-DS 82)."""
    F = _F()
    item, inv, dt, ss = (t["item"], t["inventory"], t["date_dim"],
                         t["store_sales"])
    sel_i = item.filter((F.col("i_current_price").between(30.0, 150.0))
                        & F.col("i_manufact_id").between(300, 600))
    days = dt.filter((F.col("d_date") >= F.lit(10300))
                     & (F.col("d_date") <= F.lit(10660)))
    stocked = (inv.filter(F.col("inv_quantity_on_hand").between(100, 500))
               .join(days, on=inv["inv_date_sk"] == days["d_date_sk"])
               .select(F.col("inv_item_sk").alias("st_item")).distinct())
    return (sel_i.join(stocked, on=sel_i["i_item_sk"] == stocked["st_item"],
                       how="leftsemi")
            .join(ss, on=sel_i["i_item_sk"] == ss["ss_item_sk"],
                  how="leftsemi")
            .select("i_item_id", "i_item_sk", "i_current_price")
            .sort("i_item_id")
            .limit(100))


def q89(s, t):
    """Class monthly sales vs average (TPC-DS 89)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ss, dt, item, store = (t["store_sales"], t["date_dim"], t["item"],
                           t["store"])
    y = dt.filter(F.col("d_year") == 1999)
    a = item.filter(F.col("i_category").isin("Books", "Electronics",
                                             "Sports")
                    & F.col("i_class").isin("class01", "class05",
                                            "class09"))
    b = item.filter(F.col("i_category").isin("Men", "Jewelry", "Women")
                    & F.col("i_class").isin("class02", "class06",
                                            "class10"))
    sel_i = a.union(b)
    g = (ss.join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
         .join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .groupBy("i_category", "i_class", "i_brand", "s_store_name",
                  "s_store_id", "d_moy")
         .agg(F.sum(F.col("ss_sales_price")).alias("sum_sales")))
    w = Window.partitionBy("i_category", "i_brand", "s_store_name",
                           "s_store_id")
    g = g.withColumn("avg_monthly_sales",
                     F.avg(F.col("sum_sales")).over(w))
    return (g.filter(
                F.when(F.col("avg_monthly_sales") != 0.0,
                       F.abs(F.col("sum_sales")
                             - F.col("avg_monthly_sales"))
                       / F.col("avg_monthly_sales"))
                .otherwise(F.lit(None)) > 0.1)
            .select("i_category", "i_class", "i_brand", "s_store_name",
                    "d_moy", "sum_sales", "avg_monthly_sales")
            .sort(F.col("sum_sales") - F.col("avg_monthly_sales"),
                  "s_store_name")
            .limit(100))


def q90(s, t):
    """AM to PM web sales ratio (TPC-DS 90, bucketed in one pass)."""
    F = _F()
    ws, td = t["web_sales"], t["time_dim"]
    j = ws.join(td, on=ws["ws_sold_time_sk"] == td["t_time_sk"])
    am_c = F.sum(F.when(F.col("t_hour").between(8, 9), 1).otherwise(0))
    pm_c = F.sum(F.when(F.col("t_hour").between(19, 20), 1).otherwise(0))
    return j.agg(am_c.alias("amc"), pm_c.alias("pmc")).withColumn(
        "am_pm_ratio",
        F.when(F.col("pmc") > 0,
               F.col("amc").cast("double") / F.col("pmc").cast("double"))
        .otherwise(F.lit(None)))


def q92(s, t):
    """Web excess discount (TPC-DS 92 decorrelated)."""
    F = _F()
    ws, item, dt = t["web_sales"], t["item"], t["date_dim"]
    sel_i = item.filter(F.col("i_manufact_id") == 350)
    days = dt.filter((F.col("d_date") >= F.lit(10900))
                     & (F.col("d_date") <= F.lit(10990)))
    base = (ws.join(days, on=ws["ws_sold_date_sk"] == days["d_date_sk"])
            .join(sel_i, on=ws["ws_item_sk"] == sel_i["i_item_sk"]))
    thresh = (base.groupBy("i_item_sk")
              .agg((F.avg(F.col("ws_ext_discount_amt")) * 1.3)
                   .alias("disc_thresh"))
              .select(F.col("i_item_sk").alias("th_item"),
                      F.col("disc_thresh")))
    return (base.join(thresh, on=base["i_item_sk"] == thresh["th_item"])
            .filter(F.col("ws_ext_discount_amt") > F.col("disc_thresh"))
            .agg(F.sum(F.col("ws_ext_discount_amt"))
                 .alias("excess_discount_amount")))


def q96(s, t):
    """Store sales count in a time window (TPC-DS 96)."""
    F = _F()
    ss, td, hd, store = (t["store_sales"], t["time_dim"],
                         t["household_demographics"], t["store"])
    sel_t = td.filter((F.col("t_hour") == 20)
                      & (F.col("t_minute") >= 30))
    sel_hd = hd.filter(F.col("hd_dep_count") == 7)
    return (ss.join(sel_t, on=ss["ss_sold_time_sk"] == sel_t["t_time_sk"])
            .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
            .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
            .agg(F.count_star().alias("cnt")))


def q98(s, t):
    """Store revenue ratio by class over a window (TPC-DS 98)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ss, item, dt = t["store_sales"], t["item"], t["date_dim"]
    sel_i = item.filter(F.col("i_category").isin(
        "Sports", "Books", "Home"))
    days = dt.filter((F.col("d_date") >= F.lit(10371))
                     & (F.col("d_date") <= F.lit(10401)))
    j = (ss.join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
         .join(days, on=ss["ss_sold_date_sk"] == days["d_date_sk"])
         .groupBy("i_item_id", "i_category", "i_class", "i_current_price")
         .agg(F.sum(F.col("ss_ext_sales_price")).alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (j.withColumn(
                "revenueratio",
                F.col("itemrevenue") * 100.0
                / F.sum(F.col("itemrevenue")).over(w))
            .select("i_item_id", "i_category", "i_class", "itemrevenue",
                    "revenueratio")
            .sort("i_category", "i_class", "i_item_id")
            .limit(100))


def q99(s, t):
    """Catalog ship-latency day buckets (TPC-DS 99)."""
    F = _F()
    cs, dt, sm, wh = (t["catalog_sales"], t["date_dim"], t["ship_mode"],
                      t["warehouse"])
    months = dt.filter(F.col("d_month_seq").between(350, 361)) \
        .select(F.col("d_date_sk").alias("ship_sk"))
    j = (cs.join(months, on=cs["cs_ship_date_sk"] == months["ship_sk"])
         .join(sm, on=cs["cs_ship_mode_sk"] == sm["sm_ship_mode_sk"])
         .join(wh, on=cs["cs_warehouse_sk"] == wh["w_warehouse_sk"]))
    lag = F.col("cs_ship_date_sk") - F.col("cs_sold_date_sk")
    return (j.groupBy("w_warehouse_name", "sm_type")
            .agg(F.sum(F.when(lag <= 30, 1).otherwise(0)).alias("d30"),
                 F.sum(F.when((lag > 30) & (lag <= 60), 1).otherwise(0))
                 .alias("d31_60"),
                 F.sum(F.when((lag > 60) & (lag <= 90), 1).otherwise(0))
                 .alias("d61_90"),
                 F.sum(F.when((lag > 90) & (lag <= 120), 1).otherwise(0))
                 .alias("d91_120"),
                 F.sum(F.when(lag > 120, 1).otherwise(0)).alias("d_gt120"))
            .sort("w_warehouse_name", "sm_type")
            .limit(100))


def q5_simplified(s, t):
    """Channel profit roll-together (TPC-DS 5 shape: union of channels)."""
    F = _F()
    dt = t["date_dim"]
    days = dt.filter((F.col("d_date") >= F.lit(10585))
                     & (F.col("d_date") <= F.lit(10599)))
    ss = (t["store_sales"]
          .join(days, on=t["store_sales"]["ss_sold_date_sk"]
                == days["d_date_sk"])
          .select(F.col("ss_ext_sales_price").alias("sales"),
                  F.col("ss_net_profit").alias("profit"),
                  F.lit("store channel").alias("channel")))
    cs = (t["catalog_sales"]
          .join(days, on=t["catalog_sales"]["cs_sold_date_sk"]
                == days["d_date_sk"])
          .select(F.col("cs_ext_sales_price").alias("sales"),
                  F.col("cs_net_profit").alias("profit"),
                  F.lit("catalog channel").alias("channel")))
    ws = (t["web_sales"]
          .join(days, on=t["web_sales"]["ws_sold_date_sk"]
                == days["d_date_sk"])
          .select(F.col("ws_ext_sales_price").alias("sales"),
                  F.col("ws_net_profit").alias("profit"),
                  F.lit("web channel").alias("channel")))
    return (ss.union(cs).union(ws)
            .groupBy("channel")
            .agg(F.sum(F.col("sales")).alias("sales"),
                 F.sum(F.col("profit")).alias("profit"))
            .sort("channel"))


def q33_simplified(s, t):
    """Manufacturer revenue across all three channels (TPC-DS 33 shape)."""
    F = _F()
    dt, item = t["date_dim"], t["item"]
    m = dt.filter((F.col("d_year") == 1998) & (F.col("d_moy") == 3))
    sel_i = item.filter(F.col("i_category") == "Electronics")

    def chan(fact, date_col, item_col, price_col):
        f = t[fact]
        return (f.join(m, on=f[date_col] == m["d_date_sk"])
                .join(sel_i, on=f[item_col] == sel_i["i_item_sk"])
                .groupBy("i_manufact_id")
                .agg(F.sum(F.col(price_col)).alias("total_sales")))

    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price")))
    return (u.groupBy("i_manufact_id")
            .agg(F.sum(F.col("total_sales")).alias("total_sales"))
            .sort(F.col("total_sales").desc(), "i_manufact_id")
            .limit(100))


def q45(s, t):
    """Web customers in zip cohort or item cohort (TPC-DS 45)."""
    F = _F()
    ws, cust, ca, dt, item = (t["web_sales"], t["customer"],
                              t["customer_address"], t["date_dim"],
                              t["item"])
    q = dt.filter((F.col("d_qoy") == 2) & (F.col("d_year") == 2001))
    cohort_items = item.filter(F.col("i_item_sk").isin(
        2, 3, 5, 7, 11, 13, 17, 19, 23, 29)) \
        .select(F.col("i_item_id").alias("coh_id")).distinct()
    j = (ws.join(cust, on=ws["ws_bill_customer_sk"]
                 == cust["c_customer_sk"])
         .join(ca, on=cust["c_current_addr_sk"] == ca["ca_address_sk"])
         .join(q, on=ws["ws_sold_date_sk"] == q["d_date_sk"])
         .join(item, on=ws["ws_item_sk"] == item["i_item_sk"]))
    zips = ["10000", "10001", "10002", "10003", "10004"]
    cohort = j.join(cohort_items, on=j["i_item_id"]
                    == cohort_items["coh_id"], how="leftsemi") \
        .select("ca_zip", "ca_city", "ws_sales_price")
    zipped = j.filter(F.col("ca_zip").isin(*zips)) \
        .select("ca_zip", "ca_city", "ws_sales_price")
    return (zipped.union(cohort)
            .groupBy("ca_zip", "ca_city")
            .agg(F.sum(F.col("ws_sales_price")).alias("total"))
            .sort("ca_zip", "ca_city")
            .limit(100))


def q88_simplified(s, t):
    """Time-of-day sales histogram (TPC-DS 88 shape: one pass, 8 buckets)."""
    F = _F()
    ss, td, hd = (t["store_sales"], t["time_dim"],
                  t["household_demographics"])
    sel_hd = hd.filter(((F.col("hd_dep_count") == 4)
                        & (F.col("hd_vehicle_count") <= 6))
                       | ((F.col("hd_dep_count") == 2)
                          & (F.col("hd_vehicle_count") <= 4))
                       | ((F.col("hd_dep_count") == 0)
                          & (F.col("hd_vehicle_count") <= 2)))
    j = (ss.join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
         .join(td, on=ss["ss_sold_time_sk"] == td["t_time_sk"]))
    aggs = []
    for h1, m1, h2, m2, name in [
            (8, 30, 9, 0, "h8_30_to_9"), (9, 0, 9, 30, "h9_to_9_30"),
            (9, 30, 10, 0, "h9_30_to_10"), (10, 0, 10, 30, "h10_to_10_30"),
            (10, 30, 11, 0, "h10_30_to_11"), (11, 0, 11, 30, "h11_to_11_30"),
            (11, 30, 12, 0, "h11_30_to_12"), (12, 0, 12, 30, "h12_to_12_30")]:
        lo = h1 * 60 + m1
        hi = h2 * 60 + m2
        mins = F.col("t_hour") * 60 + F.col("t_minute")
        aggs.append(F.sum(F.when((mins >= lo) & (mins < hi), 1)
                          .otherwise(0)).alias(name))
    return j.agg(*aggs)


QUERIES = {
    "q3": q3, "q5": q5_simplified, "q7": q7, "q12": q12, "q13": q13,
    "q15": q15, "q19": q19, "q20": q20, "q25": q25, "q26": q26, "q27": q27,
    "q29": q29, "q32": q32, "q33": q33_simplified, "q36": q36, "q37": q37,
    "q42": q42, "q43": q43, "q45": q45, "q48": q48, "q50": q50, "q52": q52,
    "q53": q53, "q55": q55, "q61": q61, "q62": q62, "q63": q63, "q65": q65,
    "q68": q68, "q73": q73, "q79": q79, "q82": q82, "q88": q88_simplified,
    "q89": q89, "q90": q90, "q92": q92, "q96": q96, "q98": q98, "q99": q99,
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--queries", default=",".join(QUERIES))
    args = ap.parse_args()
    s = make_session(tpu=True)
    tables = load_tables(s, args.rows)
    results = {}
    for name in args.queries.split(","):
        fn = QUERIES[name.strip()]
        df = fn(s, tables)
        t0 = time.perf_counter()
        out = df.to_arrow()
        results[f"{name}_s"] = round(time.perf_counter() - t0, 4)
        results[f"{name}_rows"] = out.num_rows
    print(json.dumps({"metric": "tpcds_suite", "rows": args.rows,
                      **results}))


if __name__ == "__main__":
    main()
