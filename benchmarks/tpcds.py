"""TPC-DS-style benchmark queries through the full framework (reference:
integration_tests tpcds suite; BASELINE.md's 99-query north star).

32 queries over the simplified TPC-DS dimensional model from
spark_rapids_tpu.datagen (tpcds_*): the standard's join/aggregate shapes with
correlated subqueries hand-decorrelated the way Spark's optimizer lowers
them — grouped-agg joins, semi/anti joins, cross-joined scalar aggregates,
windowed ratios, rollups. Every query has a CPU-oracle equality test in
tests/test_tpcds.py.

Usage: python benchmarks/tpcds.py [--rows N] [--queries q3,q7,...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def make_session(tpu: bool):
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.sql.enabled": str(tpu).lower(),
                       "spark.rapids.shuffle.mode":
                           "ICI" if tpu else "MULTITHREADED",
                       "spark.sql.shuffle.partitions": "4"})


def load_tables(s, rows: int, parts: int = 4):
    """All tables at store_sales-row scale `rows` (other facts/dims scaled
    by TPC-DS-like ratios)."""
    from spark_rapids_tpu import datagen as dg

    n_items = max(rows // 50, 30)
    n_cust = max(rows // 40, 50)
    n_addr = max(n_cust // 2, 25)
    n_cdemo = 400
    n_hdemo = 144
    n_stores = 12
    n_promo = 30
    n_wh = 6
    n_sites = 8
    n_cs = max(rows // 2, 1)
    n_ws = max(rows // 4, 1)
    n_sr = max(rows // 10, 1)
    n_cr = max(n_cs // 10, 1)
    n_wr = max(n_ws // 10, 1)
    n_inv = max(rows // 4, 1)

    def df(spec, n, p=1):
        return s.createDataFrame(spec.generate(42, n, p), num_partitions=p)

    tables = {
        "date_dim": s.createDataFrame(dg.tpcds_date_dim()),
        "item": df(dg.tpcds_item(n_items), n_items),
        "store": df(dg.tpcds_store(), n_stores),
        "customer": df(dg.tpcds_customer(n_cust, n_addr, n_cdemo, n_hdemo),
                       n_cust),
        "customer_address": df(dg.tpcds_customer_address(n_addr), n_addr),
        "customer_demographics": df(dg.tpcds_customer_demographics(),
                                    n_cdemo),
        "household_demographics": df(dg.tpcds_household_demographics(),
                                     n_hdemo),
        "promotion": df(dg.tpcds_promotion(), n_promo),
        "warehouse": df(dg.tpcds_warehouse(), n_wh),
        "web_site": df(dg.tpcds_web_site(), n_sites),
        "ship_mode": df(dg.tpcds_ship_mode(), 10),
        "reason": df(dg.tpcds_reason(), 35),
        "call_center": df(dg.tpcds_call_center(), 4),
        "income_band": df(dg.tpcds_income_band(), 20),
        "time_dim": df(dg.tpcds_time_dim(), 86400),
        "store_sales": df(dg.tpcds_store_sales(
            rows, n_items, n_cust, n_stores, n_cdemo, n_hdemo, n_addr,
            n_promo), rows, parts),
        "store_returns": df(dg.tpcds_store_returns(
            n_sr, n_items, n_cust, n_stores, max(rows // 4, 1)), n_sr,
            parts),
        "catalog_sales": df(dg.tpcds_catalog_sales(
            n_cs, n_items, n_cust, n_cdemo, n_hdemo, n_addr, n_promo,
            n_wh), n_cs, parts),
        "catalog_returns": df(dg.tpcds_catalog_returns(
            n_cr, n_items, max(n_cs // 3, 1), n_cust), n_cr, parts),
        "web_sales": df(dg.tpcds_web_sales(
            n_ws, n_items, n_cust, n_addr, n_sites, n_promo, n_wh), n_ws,
            parts),
        "web_returns": df(dg.tpcds_web_returns(
            n_wr, n_items, max(n_ws // 3, 1), n_cust), n_wr, parts),
        "inventory": df(dg.tpcds_inventory(n_inv, n_items, n_wh), n_inv,
                        parts),
    }
    return tables


def _F():
    import spark_rapids_tpu.functions as F
    return F


# --- the queries ------------------------------------------------------------
# Each mirrors the standard's query shape on the simplified schema. Filter
# constants are chosen to select real data from the generator.


def q3(s, t):
    """Brand sales in a month (TPC-DS 3)."""
    F = _F()
    ss, dt, item = t["store_sales"], t["date_dim"], t["item"]
    sel_i = item.filter(F.col("i_manufact_id").between(100, 250))
    nov = dt.filter(F.col("d_moy") == 11)
    return (ss.join(nov, on=ss["ss_sold_date_sk"] == nov["d_date_sk"])
            .join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
            .groupBy("d_year", "i_brand_id", "i_brand")
            .agg(F.sum(F.col("ss_ext_sales_price")).alias("sum_agg"))
            .sort("d_year", F.col("sum_agg").desc(), "i_brand_id")
            .limit(100))


def q7(s, t):
    """Demographic averages (TPC-DS 7)."""
    F = _F()
    ss, cd, dt, item, promo = (t["store_sales"], t["customer_demographics"],
                               t["date_dim"], t["item"], t["promotion"])
    sel_cd = cd.filter((F.col("cd_gender") == "M")
                       & (F.col("cd_marital_status") == "S")
                       & (F.col("cd_education_status") == "College"))
    y = dt.filter(F.col("d_year") == 2000)
    sel_p = promo.filter((F.col("p_channel_email") == "N")
                         | (F.col("p_channel_event") == "N"))
    return (ss.join(sel_cd, on=ss["ss_cdemo_sk"] == sel_cd["cd_demo_sk"])
            .join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
            .join(sel_p, on=ss["ss_promo_sk"] == sel_p["p_promo_sk"])
            .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
            .groupBy("i_item_id")
            .agg(F.avg(F.col("ss_quantity")).alias("agg1"),
                 F.avg(F.col("ss_list_price")).alias("agg2"),
                 F.avg(F.col("ss_coupon_amt")).alias("agg3"),
                 F.avg(F.col("ss_sales_price")).alias("agg4"))
            .sort("i_item_id")
            .limit(100))


def q12(s, t):
    """Web revenue ratio by class over a window (TPC-DS 12)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ws, item, dt = t["web_sales"], t["item"], t["date_dim"]
    sel_i = item.filter(F.col("i_category").isin(
        "Sports", "Books", "Home"))
    days = dt.filter((F.col("d_date") >= F.lit(10371))
                     & (F.col("d_date") <= F.lit(10401)))
    j = (ws.join(sel_i, on=ws["ws_item_sk"] == sel_i["i_item_sk"])
         .join(days, on=ws["ws_sold_date_sk"] == days["d_date_sk"])
         .groupBy("i_item_id", "i_category", "i_class", "i_current_price")
         .agg(F.sum(F.col("ws_ext_sales_price")).alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (j.withColumn(
                "revenueratio",
                F.col("itemrevenue") * 100.0
                / F.sum(F.col("itemrevenue")).over(w))
            .select("i_item_id", "i_category", "i_class", "itemrevenue",
                    "revenueratio")
            .sort("i_category", "i_class", "i_item_id")
            .limit(100))


def q13(s, t):
    """Conditional averages over demographic brackets (TPC-DS 13)."""
    F = _F()
    ss, cd, hd, ca, dt, store = (t["store_sales"],
                                 t["customer_demographics"],
                                 t["household_demographics"],
                                 t["customer_address"], t["date_dim"],
                                 t["store"])
    y = dt.filter(F.col("d_year") == 2001)
    sel_cd = cd.filter(F.col("cd_marital_status").isin("M", "S", "W"))
    sel_hd = hd.filter(F.col("hd_dep_count").isin(1, 3))
    sel_ca = ca.filter(F.col("ca_state").isin("TX", "OH", "CA", "NY", "GA",
                                              "TN"))
    return (ss.join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
            .join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
            .join(sel_cd, on=ss["ss_cdemo_sk"] == sel_cd["cd_demo_sk"])
            .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
            .join(sel_ca, on=ss["ss_addr_sk"] == sel_ca["ca_address_sk"])
            .agg(F.avg(F.col("ss_quantity")).alias("avg_qty"),
                 F.avg(F.col("ss_ext_sales_price")).alias("avg_esp"),
                 F.avg(F.col("ss_ext_wholesale_cost")).alias("avg_ewc"),
                 F.sum(F.col("ss_ext_wholesale_cost")).alias("sum_ewc")))


def q15(s, t):
    """Catalog sales by zip cohort (TPC-DS 15)."""
    F = _F()
    cs, cust, ca, dt = (t["catalog_sales"], t["customer"],
                        t["customer_address"], t["date_dim"])
    q = dt.filter((F.col("d_qoy") == 1) & (F.col("d_year") == 2001))
    zips = [f"{z:05d}" for z in range(10000, 10010)]
    return (cs.join(cust, on=cs["cs_bill_customer_sk"]
                    == cust["c_customer_sk"])
            .join(ca, on=cust["c_current_addr_sk"] == ca["ca_address_sk"])
            .join(q, on=cs["cs_sold_date_sk"] == q["d_date_sk"])
            .filter(F.col("ca_zip").isin(*zips)
                    | F.col("ca_state").isin("CA", "WA", "GA")
                    | (F.col("cs_sales_price") > 250.0))
            .groupBy("ca_zip")
            .agg(F.sum(F.col("cs_sales_price")).alias("total"))
            .sort("ca_zip")
            .limit(100))


def q19(s, t):
    """Brand revenue, manager cohort (TPC-DS 19)."""
    F = _F()
    ss, dt, item, cust, ca, store = (t["store_sales"], t["date_dim"],
                                     t["item"], t["customer"],
                                     t["customer_address"], t["store"])
    sel_i = item.filter(F.col("i_manager_id").between(1, 20))
    m = dt.filter((F.col("d_moy") == 11) & (F.col("d_year") == 1998))
    return (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
            .join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
            .join(cust, on=ss["ss_customer_sk"] == cust["c_customer_sk"])
            .join(ca, on=cust["c_current_addr_sk"] == ca["ca_address_sk"])
            .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
            .filter(F.col("ca_city") != F.col("s_city"))
            .groupBy("i_brand_id", "i_brand", "i_manufact_id")
            .agg(F.sum(F.col("ss_ext_sales_price")).alias("ext_price"))
            .sort(F.col("ext_price").desc(), "i_brand_id")
            .limit(100))


def q20(s, t):
    """Catalog revenue ratio by class over a window (TPC-DS 20)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    cs, item, dt = t["catalog_sales"], t["item"], t["date_dim"]
    sel_i = item.filter(F.col("i_category").isin(
        "Sports", "Books", "Home"))
    days = dt.filter((F.col("d_date") >= F.lit(10371))
                     & (F.col("d_date") <= F.lit(10401)))
    j = (cs.join(sel_i, on=cs["cs_item_sk"] == sel_i["i_item_sk"])
         .join(days, on=cs["cs_sold_date_sk"] == days["d_date_sk"])
         .groupBy("i_item_id", "i_category", "i_class", "i_current_price")
         .agg(F.sum(F.col("cs_ext_sales_price")).alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (j.withColumn(
                "revenueratio",
                F.col("itemrevenue") * 100.0
                / F.sum(F.col("itemrevenue")).over(w))
            .select("i_item_id", "i_category", "i_class", "itemrevenue",
                    "revenueratio")
            .sort("i_category", "i_class", "i_item_id")
            .limit(100))


def q25(s, t):
    """Store sales/returns/catalog profit triple join (TPC-DS 25)."""
    F = _F()
    ss, sr, cs, dt, store, item = (t["store_sales"], t["store_returns"],
                                   t["catalog_sales"], t["date_dim"],
                                   t["store"], t["item"])
    d1 = dt.filter(F.col("d_year") == 2000) \
        .select(F.col("d_date_sk").alias("d1_sk"))
    d2 = dt.filter(F.col("d_year").between(2000, 2002)) \
        .select(F.col("d_date_sk").alias("d2_sk"))
    d3 = dt.filter(F.col("d_year").between(2000, 2002)) \
        .select(F.col("d_date_sk").alias("d3_sk"))
    j = (ss.join(sr, on=(ss["ss_customer_sk"] == sr["sr_customer_sk"])
                 & (ss["ss_item_sk"] == sr["sr_item_sk"])
                 & (ss["ss_ticket_number"] == sr["sr_ticket_number"]))
         .join(cs, on=(sr["sr_customer_sk"] == cs["cs_bill_customer_sk"])
               & (sr["sr_item_sk"] == cs["cs_item_sk"]))
         .join(d1, on=ss["ss_sold_date_sk"] == d1["d1_sk"])
         .join(d2, on=sr["sr_returned_date_sk"] == d2["d2_sk"])
         .join(d3, on=cs["cs_sold_date_sk"] == d3["d3_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(item, on=ss["ss_item_sk"] == item["i_item_sk"]))
    return (j.groupBy("i_item_id", "s_store_id", "s_store_name")
            .agg(F.sum(F.col("ss_net_profit")).alias("store_sales_profit"),
                 F.sum(F.col("sr_net_loss")).alias("store_returns_loss"),
                 F.sum(F.col("cs_net_profit")).alias("catalog_sales_profit"))
            .sort("i_item_id", "s_store_id")
            .limit(100))


def q26(s, t):
    """Catalog demographic averages (TPC-DS 26)."""
    F = _F()
    cs, cd, dt, item, promo = (t["catalog_sales"],
                               t["customer_demographics"], t["date_dim"],
                               t["item"], t["promotion"])
    sel_cd = cd.filter((F.col("cd_gender") == "M")
                       & (F.col("cd_marital_status") == "S")
                       & (F.col("cd_education_status") == "College"))
    y = dt.filter(F.col("d_year") == 2000)
    sel_p = promo.filter((F.col("p_channel_email") == "N")
                         | (F.col("p_channel_event") == "N"))
    return (cs.join(sel_cd, on=cs["cs_bill_cdemo_sk"] == sel_cd["cd_demo_sk"])
            .join(y, on=cs["cs_sold_date_sk"] == y["d_date_sk"])
            .join(sel_p, on=cs["cs_promo_sk"] == sel_p["p_promo_sk"])
            .join(item, on=cs["cs_item_sk"] == item["i_item_sk"])
            .groupBy("i_item_id")
            .agg(F.avg(F.col("cs_quantity")).alias("agg1"),
                 F.avg(F.col("cs_list_price")).alias("agg2"),
                 F.avg(F.col("cs_coupon_amt")).alias("agg3"),
                 F.avg(F.col("cs_sales_price")).alias("agg4"))
            .sort("i_item_id")
            .limit(100))


def q27(s, t):
    """State rollup of store demographics (TPC-DS 27: GROUP BY ROLLUP)."""
    F = _F()
    ss, cd, dt, store, item = (t["store_sales"],
                               t["customer_demographics"], t["date_dim"],
                               t["store"], t["item"])
    sel_cd = cd.filter((F.col("cd_gender") == "F")
                       & (F.col("cd_marital_status") == "M")
                       & (F.col("cd_education_status") == "College"))
    y = dt.filter(F.col("d_year") == 2002)
    sel_s = store.filter(F.col("s_state").isin("TN", "CA", "TX"))
    return (ss.join(sel_cd, on=ss["ss_cdemo_sk"] == sel_cd["cd_demo_sk"])
            .join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
            .join(sel_s, on=ss["ss_store_sk"] == sel_s["s_store_sk"])
            .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
            .rollup("i_item_id", "s_state")
            .agg(F.avg(F.col("ss_quantity")).alias("agg1"),
                 F.avg(F.col("ss_list_price")).alias("agg2"),
                 F.avg(F.col("ss_coupon_amt")).alias("agg3"),
                 F.avg(F.col("ss_sales_price")).alias("agg4"))
            .sort("i_item_id", "s_state")
            .limit(100))


def q29(s, t):
    """Quantity sold/returned/re-sold (TPC-DS 29)."""
    F = _F()
    ss, sr, cs, dt, store, item = (t["store_sales"], t["store_returns"],
                                   t["catalog_sales"], t["date_dim"],
                                   t["store"], t["item"])
    d1 = dt.filter(F.col("d_year") == 1999) \
        .select(F.col("d_date_sk").alias("d1_sk"))
    d2 = dt.filter(F.col("d_year").between(1999, 2001)) \
        .select(F.col("d_date_sk").alias("d2_sk"))
    d3 = dt.filter(F.col("d_year").between(1999, 2001)) \
        .select(F.col("d_date_sk").alias("d3_sk"))
    j = (ss.join(sr, on=(ss["ss_customer_sk"] == sr["sr_customer_sk"])
                 & (ss["ss_item_sk"] == sr["sr_item_sk"])
                 & (ss["ss_ticket_number"] == sr["sr_ticket_number"]))
         .join(cs, on=(sr["sr_customer_sk"] == cs["cs_bill_customer_sk"])
               & (sr["sr_item_sk"] == cs["cs_item_sk"]))
         .join(d1, on=ss["ss_sold_date_sk"] == d1["d1_sk"])
         .join(d2, on=sr["sr_returned_date_sk"] == d2["d2_sk"])
         .join(d3, on=cs["cs_sold_date_sk"] == d3["d3_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(item, on=ss["ss_item_sk"] == item["i_item_sk"]))
    return (j.groupBy("i_item_id", "s_store_id", "s_store_name")
            .agg(F.sum(F.col("ss_quantity")).alias("store_sales_quantity"),
                 F.sum(F.col("sr_return_quantity"))
                 .alias("store_returns_quantity"),
                 F.sum(F.col("cs_quantity")).alias("catalog_sales_quantity"))
            .sort("i_item_id", "s_store_id")
            .limit(100))


def q32(s, t):
    """Excess discount: 1.3 × per-item average (TPC-DS 32 decorrelated)."""
    F = _F()
    cs, item, dt = t["catalog_sales"], t["item"], t["date_dim"]
    sel_i = item.filter(F.col("i_manufact_id") == 977)
    days = dt.filter((F.col("d_date") >= F.lit(10900))
                     & (F.col("d_date") <= F.lit(10990)))
    base = (cs.join(days, on=cs["cs_sold_date_sk"] == days["d_date_sk"])
            .join(sel_i, on=cs["cs_item_sk"] == sel_i["i_item_sk"]))
    thresh = (base.groupBy("i_item_sk")
              .agg((F.avg(F.col("cs_ext_discount_amt")) * 1.3)
                   .alias("disc_thresh"))
              .select(F.col("i_item_sk").alias("th_item"),
                      F.col("disc_thresh")))
    return (base.join(thresh, on=base["i_item_sk"] == thresh["th_item"])
            .filter(F.col("cs_ext_discount_amt") > F.col("disc_thresh"))
            .agg(F.sum(F.col("cs_ext_discount_amt"))
                 .alias("excess_discount_amount")))


def q36(s, t):
    """Gross-margin rollup with rank inside hierarchy level (TPC-DS 36)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    from spark_rapids_tpu.expressions.generators import GroupingExpr
    ss, dt, item, store = (t["store_sales"], t["date_dim"], t["item"],
                           t["store"])
    y = dt.filter(F.col("d_year") == 2001)
    sel_s = store.filter(F.col("s_state").isin("TN", "CA"))
    g = (ss.join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
         .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
         .join(sel_s, on=ss["ss_store_sk"] == sel_s["s_store_sk"])
         .rollup("i_category", "i_class")
         .agg((F.sum(F.col("ss_net_profit"))
               / F.sum(F.col("ss_ext_sales_price"))).alias("gross_margin"),
              F.grouping("i_category").alias("g_cat"),
              F.grouping("i_class").alias("g_class")))
    g = g.withColumn("lochierarchy", F.col("g_cat") + F.col("g_class"))
    w = Window.partitionBy("lochierarchy").orderBy(
        F.col("gross_margin").asc())
    return (g.withColumn("rank_within_parent", F.rank().over(w))
            .select("gross_margin", "i_category", "i_class", "lochierarchy",
                    "rank_within_parent")
            .sort(F.col("lochierarchy").desc(), "i_category",
                  "rank_within_parent")
            .limit(100))


def q37(s, t):
    """Items with inventory in a window joined to catalog sales (TPC-DS 37)."""
    F = _F()
    item, inv, dt, cs = (t["item"], t["inventory"], t["date_dim"],
                         t["catalog_sales"])
    sel_i = item.filter((F.col("i_current_price") >= 20.0)
                        & (F.col("i_current_price") <= 150.0)
                        & F.col("i_manufact_id").between(500, 800))
    days = dt.filter((F.col("d_date") >= F.lit(10300))
                     & (F.col("d_date") <= F.lit(10660)))
    stocked = (inv.filter(F.col("inv_quantity_on_hand").between(100, 500))
               .join(days, on=inv["inv_date_sk"] == days["d_date_sk"])
               .join(sel_i, on=inv["inv_item_sk"] == sel_i["i_item_sk"],
                     how="leftsemi")
               .select(F.col("inv_item_sk").alias("st_item")).distinct())
    return (sel_i.join(stocked, on=sel_i["i_item_sk"] == stocked["st_item"],
                       how="leftsemi")
            .join(cs, on=sel_i["i_item_sk"] == cs["cs_item_sk"],
                  how="leftsemi")
            .select("i_item_id", "i_item_sk", "i_current_price")
            .sort("i_item_id")
            .limit(100))


def q42(s, t):
    """Category revenue in a month (TPC-DS 42)."""
    F = _F()
    ss, dt, item = t["store_sales"], t["date_dim"], t["item"]
    m = dt.filter((F.col("d_moy") == 11) & (F.col("d_year") == 2000))
    return (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
            .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
            .groupBy("d_year", "i_category")
            .agg(F.sum(F.col("ss_ext_sales_price")).alias("total"))
            .sort(F.col("total").desc(), "d_year", "i_category")
            .limit(100))


def q43(s, t):
    """Store sales pivoted by day of week (TPC-DS 43)."""
    F = _F()
    ss, dt, store = t["store_sales"], t["date_dim"], t["store"]
    y = dt.filter(F.col("d_year") == 2000)
    j = (ss.join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"]))
    aggs = []
    for i, day in enumerate(["Sunday", "Monday", "Tuesday", "Wednesday",
                             "Thursday", "Friday", "Saturday"]):
        aggs.append(F.sum(F.when(F.col("d_day_name") == day,
                                 F.col("ss_sales_price"))
                          .otherwise(F.lit(None)))
                    .alias(f"{day[:3].lower()}_sales"))
    return (j.groupBy("s_store_name", "s_store_id")
            .agg(*aggs)
            .sort("s_store_name", "s_store_id")
            .limit(100))


def q48(s, t):
    """Bracketed quantity sum over demographics/address (TPC-DS 48)."""
    F = _F()
    ss, cd, ca, dt, store = (t["store_sales"], t["customer_demographics"],
                             t["customer_address"], t["date_dim"],
                             t["store"])
    y = dt.filter(F.col("d_year") == 2000)
    j = (ss.join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
         .join(cd, on=ss["ss_cdemo_sk"] == cd["cd_demo_sk"])
         .join(ca, on=ss["ss_addr_sk"] == ca["ca_address_sk"]))
    b1 = ((F.col("cd_marital_status") == "M")
          & (F.col("cd_education_status") == "4 yr Degree")
          & F.col("ss_sales_price").between(100.0, 150.0))
    b2 = ((F.col("cd_marital_status") == "D")
          & (F.col("cd_education_status") == "2 yr Degree")
          & F.col("ss_sales_price").between(50.0, 100.0))
    b3 = ((F.col("cd_marital_status") == "S")
          & (F.col("cd_education_status") == "College")
          & F.col("ss_sales_price").between(150.0, 200.0))
    return (j.filter(b1 | b2 | b3)
            .agg(F.sum(F.col("ss_quantity")).alias("total_quantity")))


def q50(s, t):
    """Return latency day-buckets per store (TPC-DS 50)."""
    F = _F()
    ss, sr, dt, store = (t["store_sales"], t["store_returns"],
                         t["date_dim"], t["store"])
    d2 = dt.filter((F.col("d_year") == 2001) & (F.col("d_moy") == 8)) \
        .select(F.col("d_date_sk").alias("ret_sk"))
    j = (ss.join(sr, on=(ss["ss_ticket_number"] == sr["sr_ticket_number"])
                 & (ss["ss_item_sk"] == sr["sr_item_sk"])
                 & (ss["ss_customer_sk"] == sr["sr_customer_sk"]))
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(d2, on=sr["sr_returned_date_sk"] == d2["ret_sk"]))
    lag = F.col("sr_returned_date_sk") - F.col("ss_sold_date_sk")
    return (j.groupBy("s_store_name", "s_store_id")
            .agg(F.sum(F.when(lag <= 30, 1).otherwise(0)).alias("d30"),
                 F.sum(F.when((lag > 30) & (lag <= 60), 1).otherwise(0))
                 .alias("d31_60"),
                 F.sum(F.when((lag > 60) & (lag <= 90), 1).otherwise(0))
                 .alias("d61_90"),
                 F.sum(F.when((lag > 90) & (lag <= 120), 1).otherwise(0))
                 .alias("d91_120"),
                 F.sum(F.when(lag > 120, 1).otherwise(0)).alias("d_gt120"))
            .sort("s_store_name", "s_store_id")
            .limit(100))


def q52(s, t):
    """Brand extended price in a month (TPC-DS 52)."""
    F = _F()
    ss, dt, item = t["store_sales"], t["date_dim"], t["item"]
    m = dt.filter((F.col("d_moy") == 11) & (F.col("d_year") == 2000))
    return (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
            .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
            .groupBy("d_year", "i_brand_id", "i_brand")
            .agg(F.sum(F.col("ss_ext_sales_price")).alias("ext_price"))
            .sort("d_year", F.col("ext_price").desc(), "i_brand_id")
            .limit(100))


def q53(s, t):
    """Manufacturer quarterly sales vs average (TPC-DS 53)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ss, dt, item, store = (t["store_sales"], t["date_dim"], t["item"],
                           t["store"])
    months = dt.filter(F.col("d_month_seq").between(350, 361))
    sel_i = item.filter(F.col("i_class").isin(
        "class01", "class03", "class05", "class07"))
    g = (ss.join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
         .join(months, on=ss["ss_sold_date_sk"] == months["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .groupBy("i_manufact_id", "d_qoy")
         .agg(F.sum(F.col("ss_sales_price")).alias("sum_sales")))
    w = Window.partitionBy("i_manufact_id")
    g = g.withColumn("avg_quarterly_sales",
                     F.avg(F.col("sum_sales")).over(w))
    return (g.filter(
                F.when(F.col("avg_quarterly_sales") > 0.0,
                       F.abs(F.col("sum_sales")
                             - F.col("avg_quarterly_sales"))
                       / F.col("avg_quarterly_sales"))
                .otherwise(F.lit(None)) > 0.1)
            .select("i_manufact_id", "sum_sales", "avg_quarterly_sales")
            .sort("avg_quarterly_sales", F.col("sum_sales").desc(),
                  "i_manufact_id")
            .limit(100))


def q55(s, t):
    """Brand revenue for one manager month (TPC-DS 55)."""
    F = _F()
    ss, dt, item = t["store_sales"], t["date_dim"], t["item"]
    m = dt.filter((F.col("d_moy") == 11) & (F.col("d_year") == 1999))
    sel_i = item.filter(F.col("i_manager_id").between(20, 40))
    return (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
            .join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
            .groupBy("i_brand_id", "i_brand")
            .agg(F.sum(F.col("ss_ext_sales_price")).alias("ext_price"))
            .sort(F.col("ext_price").desc(), "i_brand_id")
            .limit(100))


def q61(s, t):
    """Promotional to total revenue ratio (TPC-DS 61)."""
    F = _F()
    ss, promo, dt, store, cust, ca, item = (
        t["store_sales"], t["promotion"], t["date_dim"], t["store"],
        t["customer"], t["customer_address"], t["item"])
    m = dt.filter((F.col("d_year") == 1998) & (F.col("d_moy") == 11))
    sel_i = item.filter(F.col("i_category") == "Jewelry")
    sel_ca = ca.filter(F.col("ca_gmt_offset") <= -6.0)
    base = (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
            .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
            .join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
            .join(cust, on=ss["ss_customer_sk"] == cust["c_customer_sk"])
            .join(sel_ca, on=cust["c_current_addr_sk"]
                  == sel_ca["ca_address_sk"]))
    promos = (base.join(promo, on=base["ss_promo_sk"] == promo["p_promo_sk"])
              .filter((F.col("p_channel_dmail") == "Y")
                      | (F.col("p_channel_email") == "Y")
                      | (F.col("p_channel_tv") == "Y"))
              .agg(F.sum(F.col("ss_ext_sales_price")).alias("promotions")))
    total = base.agg(F.sum(F.col("ss_ext_sales_price")).alias("total"))
    return (promos.crossJoin(total)
            .withColumn("ratio",
                        F.col("promotions") * 100.0 / F.col("total")))


def q62(s, t):
    """Web ship-latency day buckets (TPC-DS 62)."""
    F = _F()
    ws, dt, sm, site = (t["web_sales"], t["date_dim"], t["ship_mode"],
                        t["web_site"])
    months = dt.filter(F.col("d_month_seq").between(350, 361)) \
        .select(F.col("d_date_sk").alias("ship_sk"))
    j = (ws.join(months, on=ws["ws_ship_date_sk"] == months["ship_sk"])
         .join(sm, on=ws["ws_ship_mode_sk"] == sm["sm_ship_mode_sk"])
         .join(site, on=ws["ws_web_site_sk"] == site["web_site_sk"]))
    lag = F.col("ws_ship_date_sk") - F.col("ws_sold_date_sk")
    return (j.groupBy("sm_type", "web_name")
            .agg(F.sum(F.when(lag <= 30, 1).otherwise(0)).alias("d30"),
                 F.sum(F.when((lag > 30) & (lag <= 60), 1).otherwise(0))
                 .alias("d31_60"),
                 F.sum(F.when((lag > 60) & (lag <= 90), 1).otherwise(0))
                 .alias("d61_90"),
                 F.sum(F.when((lag > 90) & (lag <= 120), 1).otherwise(0))
                 .alias("d91_120"),
                 F.sum(F.when(lag > 120, 1).otherwise(0)).alias("d_gt120"))
            .sort("sm_type", "web_name")
            .limit(100))


def q63(s, t):
    """Manager monthly sales vs average (TPC-DS 63)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ss, dt, item, store = (t["store_sales"], t["date_dim"], t["item"],
                           t["store"])
    months = dt.filter(F.col("d_month_seq").between(350, 361))
    sel_i = item.filter(F.col("i_category").isin("Books", "Children",
                                                 "Electronics"))
    g = (ss.join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
         .join(months, on=ss["ss_sold_date_sk"] == months["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .groupBy("i_manager_id", "d_moy")
         .agg(F.sum(F.col("ss_sales_price")).alias("sum_sales")))
    w = Window.partitionBy("i_manager_id")
    g = g.withColumn("avg_monthly_sales",
                     F.avg(F.col("sum_sales")).over(w))
    return (g.filter(
                F.when(F.col("avg_monthly_sales") > 0.0,
                       F.abs(F.col("sum_sales")
                             - F.col("avg_monthly_sales"))
                       / F.col("avg_monthly_sales"))
                .otherwise(F.lit(None)) > 0.1)
            .select("i_manager_id", "sum_sales", "avg_monthly_sales")
            .sort("i_manager_id", F.col("avg_monthly_sales").desc(),
                  "sum_sales")
            .limit(100))


def q65(s, t):
    """Stores selling items at <=10% of average revenue (TPC-DS 65)."""
    F = _F()
    ss, dt, store, item = (t["store_sales"], t["date_dim"], t["store"],
                           t["item"])
    months = dt.filter(F.col("d_month_seq").between(350, 361))
    rev = (ss.join(months, on=ss["ss_sold_date_sk"] == months["d_date_sk"])
           .groupBy("ss_store_sk", "ss_item_sk")
           .agg(F.sum(F.col("ss_sales_price")).alias("revenue")))
    avg_rev = (rev.groupBy("ss_store_sk")
               .agg(F.avg(F.col("revenue")).alias("ave"))
               .select(F.col("ss_store_sk").alias("a_store"), F.col("ave")))
    return (rev.join(avg_rev, on=rev["ss_store_sk"] == avg_rev["a_store"])
            .filter(F.col("revenue") <= 0.1 * F.col("ave"))
            .join(store, on=rev["ss_store_sk"] == store["s_store_sk"])
            .join(item, on=rev["ss_item_sk"] == item["i_item_sk"])
            .select("s_store_name", "i_item_id", "revenue")
            .sort("s_store_name", "i_item_id")
            .limit(100))


def q68(s, t):
    """City customer purchase profile (TPC-DS 68)."""
    F = _F()
    ss, dt, store, hd, ca, cust = (t["store_sales"], t["date_dim"],
                                   t["store"], t["household_demographics"],
                                   t["customer_address"], t["customer"])
    days = dt.filter((F.col("d_dom").between(1, 2))
                     & F.col("d_year").isin(1999, 2000, 2001))
    sel_hd = hd.filter((F.col("hd_dep_count") == 4)
                       | (F.col("hd_vehicle_count") == 3))
    sel_ca = ca.select(F.col("ca_address_sk").alias("pos_addr"),
                       F.col("ca_city").alias("bought_city"))
    g = (ss.join(days, on=ss["ss_sold_date_sk"] == days["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
         .join(sel_ca, on=ss["ss_addr_sk"] == sel_ca["pos_addr"])
         .groupBy("ss_ticket_number", "ss_customer_sk", "bought_city")
         .agg(F.sum(F.col("ss_ext_sales_price")).alias("extended_price"),
              F.sum(F.col("ss_ext_list_price")).alias("list_price"),
              F.sum(F.col("ss_ext_tax")).alias("extended_tax")))
    j = (g.join(cust, on=g["ss_customer_sk"] == cust["c_customer_sk"])
         .join(t["customer_address"],
               on=cust["c_current_addr_sk"]
               == t["customer_address"]["ca_address_sk"])
         .filter(F.col("ca_city") != F.col("bought_city")))
    return (j.select("c_last_name", "c_first_name", "ca_city",
                     "bought_city", "ss_ticket_number", "extended_price",
                     "extended_tax", "list_price")
            .sort("c_last_name", "ss_ticket_number")
            .limit(100))


def q73(s, t):
    """Households buying 1-5 tickets (TPC-DS 73)."""
    F = _F()
    ss, dt, store, hd, cust = (t["store_sales"], t["date_dim"], t["store"],
                               t["household_demographics"], t["customer"])
    days = dt.filter(F.col("d_dom").between(1, 2)
                     & F.col("d_year").isin(1999, 2000, 2001))
    sel_hd = hd.filter(F.col("hd_buy_potential").isin(">10000", "Unknown")
                       & (F.col("hd_vehicle_count") > 0))
    g = (ss.join(days, on=ss["ss_sold_date_sk"] == days["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
         .groupBy("ss_ticket_number", "ss_customer_sk")
         .agg(F.count_star().alias("cnt"))
         .filter(F.col("cnt").between(1, 5)))
    return (g.join(cust, on=g["ss_customer_sk"] == cust["c_customer_sk"])
            .select("c_last_name", "c_first_name", "ss_ticket_number",
                    "cnt")
            .sort(F.col("cnt").desc(), "c_last_name")
            .limit(100))


def q79(s, t):
    """Customer city amounts/profit (TPC-DS 79)."""
    F = _F()
    ss, dt, store, hd, cust = (t["store_sales"], t["date_dim"], t["store"],
                               t["household_demographics"], t["customer"])
    days = dt.filter((F.col("d_dow") == 1)
                     & F.col("d_year").isin(1999, 2000, 2001))
    sel_s = store.filter(F.col("s_number_employees").between(200, 295))
    sel_hd = hd.filter((F.col("hd_dep_count") == 6)
                       | (F.col("hd_vehicle_count") > 2))
    g = (ss.join(days, on=ss["ss_sold_date_sk"] == days["d_date_sk"])
         .join(sel_s, on=ss["ss_store_sk"] == sel_s["s_store_sk"])
         .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
         .groupBy("ss_ticket_number", "ss_customer_sk", "s_city")
         .agg(F.sum(F.col("ss_coupon_amt")).alias("amt"),
              F.sum(F.col("ss_net_profit")).alias("profit")))
    return (g.join(cust, on=g["ss_customer_sk"] == cust["c_customer_sk"])
            .select("c_last_name", "c_first_name", "s_city", "amt",
                    "profit", "ss_ticket_number")
            .sort("c_last_name", "c_first_name", "ss_ticket_number")
            .limit(100))


def q82(s, t):
    """Store items with bounded inventory (TPC-DS 82)."""
    F = _F()
    item, inv, dt, ss = (t["item"], t["inventory"], t["date_dim"],
                         t["store_sales"])
    sel_i = item.filter((F.col("i_current_price").between(30.0, 150.0))
                        & F.col("i_manufact_id").between(300, 600))
    days = dt.filter((F.col("d_date") >= F.lit(10300))
                     & (F.col("d_date") <= F.lit(10660)))
    stocked = (inv.filter(F.col("inv_quantity_on_hand").between(100, 500))
               .join(days, on=inv["inv_date_sk"] == days["d_date_sk"])
               .select(F.col("inv_item_sk").alias("st_item")).distinct())
    return (sel_i.join(stocked, on=sel_i["i_item_sk"] == stocked["st_item"],
                       how="leftsemi")
            .join(ss, on=sel_i["i_item_sk"] == ss["ss_item_sk"],
                  how="leftsemi")
            .select("i_item_id", "i_item_sk", "i_current_price")
            .sort("i_item_id")
            .limit(100))


def q89(s, t):
    """Class monthly sales vs average (TPC-DS 89)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ss, dt, item, store = (t["store_sales"], t["date_dim"], t["item"],
                           t["store"])
    y = dt.filter(F.col("d_year") == 1999)
    a = item.filter(F.col("i_category").isin("Books", "Electronics",
                                             "Sports")
                    & F.col("i_class").isin("class01", "class05",
                                            "class09"))
    b = item.filter(F.col("i_category").isin("Men", "Jewelry", "Women")
                    & F.col("i_class").isin("class02", "class06",
                                            "class10"))
    sel_i = a.union(b)
    g = (ss.join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
         .join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .groupBy("i_category", "i_class", "i_brand", "s_store_name",
                  "s_store_id", "d_moy")
         .agg(F.sum(F.col("ss_sales_price")).alias("sum_sales")))
    w = Window.partitionBy("i_category", "i_brand", "s_store_name",
                           "s_store_id")
    g = g.withColumn("avg_monthly_sales",
                     F.avg(F.col("sum_sales")).over(w))
    return (g.filter(
                F.when(F.col("avg_monthly_sales") != 0.0,
                       F.abs(F.col("sum_sales")
                             - F.col("avg_monthly_sales"))
                       / F.col("avg_monthly_sales"))
                .otherwise(F.lit(None)) > 0.1)
            .select("i_category", "i_class", "i_brand", "s_store_name",
                    "d_moy", "sum_sales", "avg_monthly_sales")
            .sort(F.col("sum_sales") - F.col("avg_monthly_sales"),
                  "s_store_name")
            .limit(100))


def q90(s, t):
    """AM to PM web sales ratio (TPC-DS 90, bucketed in one pass)."""
    F = _F()
    ws, td = t["web_sales"], t["time_dim"]
    j = ws.join(td, on=ws["ws_sold_time_sk"] == td["t_time_sk"])
    am_c = F.sum(F.when(F.col("t_hour").between(8, 9), 1).otherwise(0))
    pm_c = F.sum(F.when(F.col("t_hour").between(19, 20), 1).otherwise(0))
    return j.agg(am_c.alias("amc"), pm_c.alias("pmc")).withColumn(
        "am_pm_ratio",
        F.when(F.col("pmc") > 0,
               F.col("amc").cast("double") / F.col("pmc").cast("double"))
        .otherwise(F.lit(None)))


def q92(s, t):
    """Web excess discount (TPC-DS 92 decorrelated)."""
    F = _F()
    ws, item, dt = t["web_sales"], t["item"], t["date_dim"]
    sel_i = item.filter(F.col("i_manufact_id") == 350)
    days = dt.filter((F.col("d_date") >= F.lit(10900))
                     & (F.col("d_date") <= F.lit(10990)))
    base = (ws.join(days, on=ws["ws_sold_date_sk"] == days["d_date_sk"])
            .join(sel_i, on=ws["ws_item_sk"] == sel_i["i_item_sk"]))
    thresh = (base.groupBy("i_item_sk")
              .agg((F.avg(F.col("ws_ext_discount_amt")) * 1.3)
                   .alias("disc_thresh"))
              .select(F.col("i_item_sk").alias("th_item"),
                      F.col("disc_thresh")))
    return (base.join(thresh, on=base["i_item_sk"] == thresh["th_item"])
            .filter(F.col("ws_ext_discount_amt") > F.col("disc_thresh"))
            .agg(F.sum(F.col("ws_ext_discount_amt"))
                 .alias("excess_discount_amount")))


def q96(s, t):
    """Store sales count in a time window (TPC-DS 96)."""
    F = _F()
    ss, td, hd, store = (t["store_sales"], t["time_dim"],
                         t["household_demographics"], t["store"])
    sel_t = td.filter((F.col("t_hour") == 20)
                      & (F.col("t_minute") >= 30))
    sel_hd = hd.filter(F.col("hd_dep_count") == 7)
    return (ss.join(sel_t, on=ss["ss_sold_time_sk"] == sel_t["t_time_sk"])
            .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
            .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
            .agg(F.count_star().alias("cnt")))


def q98(s, t):
    """Store revenue ratio by class over a window (TPC-DS 98)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ss, item, dt = t["store_sales"], t["item"], t["date_dim"]
    sel_i = item.filter(F.col("i_category").isin(
        "Sports", "Books", "Home"))
    days = dt.filter((F.col("d_date") >= F.lit(10371))
                     & (F.col("d_date") <= F.lit(10401)))
    j = (ss.join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"])
         .join(days, on=ss["ss_sold_date_sk"] == days["d_date_sk"])
         .groupBy("i_item_id", "i_category", "i_class", "i_current_price")
         .agg(F.sum(F.col("ss_ext_sales_price")).alias("itemrevenue")))
    w = Window.partitionBy("i_class")
    return (j.withColumn(
                "revenueratio",
                F.col("itemrevenue") * 100.0
                / F.sum(F.col("itemrevenue")).over(w))
            .select("i_item_id", "i_category", "i_class", "itemrevenue",
                    "revenueratio")
            .sort("i_category", "i_class", "i_item_id")
            .limit(100))


def q99(s, t):
    """Catalog ship-latency day buckets (TPC-DS 99)."""
    F = _F()
    cs, dt, sm, wh = (t["catalog_sales"], t["date_dim"], t["ship_mode"],
                      t["warehouse"])
    months = dt.filter(F.col("d_month_seq").between(350, 361)) \
        .select(F.col("d_date_sk").alias("ship_sk"))
    j = (cs.join(months, on=cs["cs_ship_date_sk"] == months["ship_sk"])
         .join(sm, on=cs["cs_ship_mode_sk"] == sm["sm_ship_mode_sk"])
         .join(wh, on=cs["cs_warehouse_sk"] == wh["w_warehouse_sk"]))
    lag = F.col("cs_ship_date_sk") - F.col("cs_sold_date_sk")
    return (j.groupBy("w_warehouse_name", "sm_type")
            .agg(F.sum(F.when(lag <= 30, 1).otherwise(0)).alias("d30"),
                 F.sum(F.when((lag > 30) & (lag <= 60), 1).otherwise(0))
                 .alias("d31_60"),
                 F.sum(F.when((lag > 60) & (lag <= 90), 1).otherwise(0))
                 .alias("d61_90"),
                 F.sum(F.when((lag > 90) & (lag <= 120), 1).otherwise(0))
                 .alias("d91_120"),
                 F.sum(F.when(lag > 120, 1).otherwise(0)).alias("d_gt120"))
            .sort("w_warehouse_name", "sm_type")
            .limit(100))



def q33_simplified(s, t):
    """Manufacturer revenue across all three channels (TPC-DS 33 shape)."""
    F = _F()
    dt, item = t["date_dim"], t["item"]
    m = dt.filter((F.col("d_year") == 1998) & (F.col("d_moy") == 3))
    sel_i = item.filter(F.col("i_category") == "Electronics")

    def chan(fact, date_col, item_col, price_col):
        f = t[fact]
        return (f.join(m, on=f[date_col] == m["d_date_sk"])
                .join(sel_i, on=f[item_col] == sel_i["i_item_sk"])
                .groupBy("i_manufact_id")
                .agg(F.sum(F.col(price_col)).alias("total_sales")))

    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price")))
    return (u.groupBy("i_manufact_id")
            .agg(F.sum(F.col("total_sales")).alias("total_sales"))
            .sort(F.col("total_sales").desc(), "i_manufact_id")
            .limit(100))


def q45(s, t):
    """Web customers in zip cohort or item cohort (TPC-DS 45)."""
    F = _F()
    ws, cust, ca, dt, item = (t["web_sales"], t["customer"],
                              t["customer_address"], t["date_dim"],
                              t["item"])
    q = dt.filter((F.col("d_qoy") == 2) & (F.col("d_year") == 2001))
    cohort_items = item.filter(F.col("i_item_sk").isin(
        2, 3, 5, 7, 11, 13, 17, 19, 23, 29)) \
        .select(F.col("i_item_id").alias("coh_id")).distinct()
    j = (ws.join(cust, on=ws["ws_bill_customer_sk"]
                 == cust["c_customer_sk"])
         .join(ca, on=cust["c_current_addr_sk"] == ca["ca_address_sk"])
         .join(q, on=ws["ws_sold_date_sk"] == q["d_date_sk"])
         .join(item, on=ws["ws_item_sk"] == item["i_item_sk"]))
    zips = ["10000", "10001", "10002", "10003", "10004"]
    cohort = j.join(cohort_items, on=j["i_item_id"]
                    == cohort_items["coh_id"], how="leftsemi") \
        .select("ca_zip", "ca_city", "ws_sales_price")
    zipped = j.filter(F.col("ca_zip").isin(*zips)) \
        .select("ca_zip", "ca_city", "ws_sales_price")
    return (zipped.union(cohort)
            .groupBy("ca_zip", "ca_city")
            .agg(F.sum(F.col("ws_sales_price")).alias("total"))
            .sort("ca_zip", "ca_city")
            .limit(100))


def q88_simplified(s, t):
    """Time-of-day sales histogram (TPC-DS 88 shape: one pass, 8 buckets)."""
    F = _F()
    ss, td, hd = (t["store_sales"], t["time_dim"],
                  t["household_demographics"])
    sel_hd = hd.filter(((F.col("hd_dep_count") == 4)
                        & (F.col("hd_vehicle_count") <= 6))
                       | ((F.col("hd_dep_count") == 2)
                          & (F.col("hd_vehicle_count") <= 4))
                       | ((F.col("hd_dep_count") == 0)
                          & (F.col("hd_vehicle_count") <= 2)))
    j = (ss.join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
         .join(td, on=ss["ss_sold_time_sk"] == td["t_time_sk"]))
    aggs = []
    for h1, m1, h2, m2, name in [
            (8, 30, 9, 0, "h8_30_to_9"), (9, 0, 9, 30, "h9_to_9_30"),
            (9, 30, 10, 0, "h9_30_to_10"), (10, 0, 10, 30, "h10_to_10_30"),
            (10, 30, 11, 0, "h10_30_to_11"), (11, 0, 11, 30, "h11_to_11_30"),
            (11, 30, 12, 0, "h11_30_to_12"), (12, 0, 12, 30, "h12_to_12_30")]:
        lo = h1 * 60 + m1
        hi = h2 * 60 + m2
        mins = F.col("t_hour") * 60 + F.col("t_minute")
        aggs.append(F.sum(F.when((mins >= lo) & (mins < hi), 1)
                          .otherwise(0)).alias(name))
    return j.agg(*aggs)


# --- round-5 additions: correlated-subquery, set-op, window-chain, and
# grouping-sets families (decorrelated the way Spark's optimizer lowers
# them; reference integration_tests tpcds suites) ---------------------------


def q1(s, t):
    """Customers returning > 1.2x the store average (TPC-DS 1)."""
    F = _F()
    sr, dt, store, cust = (t["store_returns"], t["date_dim"], t["store"],
                           t["customer"])
    y = dt.filter(F.col("d_year") == 2000)
    ctr = (sr.join(y, on=sr["sr_returned_date_sk"] == y["d_date_sk"])
           .groupBy("sr_customer_sk", "sr_store_sk")
           .agg(F.sum(F.col("sr_return_amt")).alias("ctr_total_return")))
    thresh = (ctr.groupBy("sr_store_sk")
              .agg((F.avg(F.col("ctr_total_return")) * 1.2)
                   .alias("ret_thresh"))
              .select(F.col("sr_store_sk").alias("th_store"),
                      F.col("ret_thresh")))
    sel_s = store.filter(F.col("s_state").isin("TN", "CA", "TX", "NY"))
    return (ctr.join(thresh, on=ctr["sr_store_sk"] == thresh["th_store"])
            .filter(F.col("ctr_total_return") > F.col("ret_thresh"))
            .join(sel_s, on=ctr["sr_store_sk"] == sel_s["s_store_sk"])
            .join(cust, on=ctr["sr_customer_sk"] == cust["c_customer_sk"])
            .select("c_customer_id")
            .sort("c_customer_id")
            .limit(100))


def q6(s, t):
    """States where customers buy items priced >1.2x category average
    (TPC-DS 6, decorrelated per-category average)."""
    F = _F()
    ca, cust, ss, dt, item = (t["customer_address"], t["customer"],
                              t["store_sales"], t["date_dim"], t["item"])
    m = dt.filter(F.col("d_year") == 2001)
    cat_avg = (item.groupBy("i_category")
               .agg((F.avg(F.col("i_current_price")) * 1.2)
                    .alias("p_thresh"))
               .select(F.col("i_category").alias("avg_cat"),
                       F.col("p_thresh")))
    pricey = (item.join(cat_avg, on=item["i_category"] == cat_avg["avg_cat"])
              .filter(F.col("i_current_price") > F.col("p_thresh")))
    j = (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
         .join(pricey, on=ss["ss_item_sk"] == pricey["i_item_sk"])
         .join(cust, on=ss["ss_customer_sk"] == cust["c_customer_sk"])
         .join(ca, on=cust["c_current_addr_sk"] == ca["ca_address_sk"]))
    return (j.groupBy("ca_state").agg(F.count_star().alias("cnt"))
            .filter(F.col("cnt") >= 10)
            .sort("cnt", "ca_state")
            .limit(100))


def q30(s, t):
    """Web customers returning >1.2x their state average (TPC-DS 30)."""
    F = _F()
    wr, dt, cust, ca = (t["web_returns"], t["date_dim"], t["customer"],
                        t["customer_address"])
    y = dt.filter(F.col("d_year") == 2002)
    base = (wr.join(y, on=wr["wr_returned_date_sk"] == y["d_date_sk"])
            .join(cust, on=wr["wr_returning_customer_sk"]
                  == cust["c_customer_sk"])
            .join(ca, on=cust["c_current_addr_sk"] == ca["ca_address_sk"]))
    ctr = (base.groupBy("wr_returning_customer_sk", "ca_state")
           .agg(F.sum(F.col("wr_return_amt")).alias("ctr_total_return")))
    thresh = (ctr.groupBy("ca_state")
              .agg((F.avg(F.col("ctr_total_return")) * 1.2)
                   .alias("ret_thresh"))
              .select(F.col("ca_state").alias("th_state"),
                      F.col("ret_thresh")))
    return (ctr.join(thresh, on=ctr["ca_state"] == thresh["th_state"])
            .filter(F.col("ctr_total_return") > F.col("ret_thresh"))
            .join(cust, on=ctr["wr_returning_customer_sk"]
                  == cust["c_customer_sk"])
            .select("c_customer_id", "c_first_name", "c_last_name",
                    "ca_state", "ctr_total_return")
            .sort("c_customer_id", "ca_state")
            .limit(100))


def q81(s, t):
    """Catalog customers returning >1.2x their state average (TPC-DS 81)."""
    F = _F()
    cr, dt, cust, ca = (t["catalog_returns"], t["date_dim"], t["customer"],
                        t["customer_address"])
    y = dt.filter(F.col("d_year") == 2000)
    base = (cr.join(y, on=cr["cr_returned_date_sk"] == y["d_date_sk"])
            .join(cust, on=cr["cr_returning_customer_sk"]
                  == cust["c_customer_sk"])
            .join(ca, on=cust["c_current_addr_sk"] == ca["ca_address_sk"]))
    ctr = (base.groupBy("cr_returning_customer_sk", "ca_state")
           .agg(F.sum(F.col("cr_return_amount")).alias("ctr_total_return")))
    thresh = (ctr.groupBy("ca_state")
              .agg((F.avg(F.col("ctr_total_return")) * 1.2)
                   .alias("ret_thresh"))
              .select(F.col("ca_state").alias("th_state"),
                      F.col("ret_thresh")))
    return (ctr.join(thresh, on=ctr["ca_state"] == thresh["th_state"])
            .filter(F.col("ctr_total_return") > F.col("ret_thresh"))
            .join(cust, on=ctr["cr_returning_customer_sk"]
                  == cust["c_customer_sk"])
            .select("c_customer_id", "c_first_name", "c_last_name",
                    "ca_state", "ctr_total_return")
            .sort("c_customer_id", "ca_state")
            .limit(100))


def q8(s, t):
    """Store profit for zips in both a fixed list and the frequent-customer
    zip set (TPC-DS 8: INTERSECT)."""
    F = _F()
    ss, dt, store, ca, cust = (t["store_sales"], t["date_dim"], t["store"],
                               t["customer_address"], t["customer"])
    zips = [f"{z:05d}" for z in range(10000, 10040)]
    zips1 = (ca.filter(F.col("ca_zip").isin(*zips))
             .select("ca_zip").distinct())
    zips2 = (ca.join(cust, on=ca["ca_address_sk"]
                     == cust["c_current_addr_sk"])
             .groupBy("ca_zip").agg(F.count_star().alias("cnt"))
             .filter(F.col("cnt") > 5).select("ca_zip"))
    sel_zips = zips1.intersect(zips2) \
        .select(F.col("ca_zip").alias("sel_zip"))
    y = dt.filter((F.col("d_qoy") == 2) & (F.col("d_year") == 1998))
    buyer = ca.select(F.col("ca_address_sk").alias("b_addr"),
                      F.col("ca_zip").alias("b_zip"))
    j = (ss.join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(buyer, on=ss["ss_addr_sk"] == buyer["b_addr"])
         .join(sel_zips, on=F.col("b_zip") == sel_zips["sel_zip"],
               how="leftsemi"))
    return (j.groupBy("s_store_name")
            .agg(F.sum(F.col("ss_net_profit")).alias("profit"))
            .sort("s_store_name")
            .limit(100))


def q38(s, t):
    """Customers active in ALL three channels in a period (TPC-DS 38:
    three-way INTERSECT of distinct (name, date) tuples)."""
    F = _F()
    dt, cust = t["date_dim"], t["customer"]
    period = dt.filter(F.col("d_month_seq").between(350, 361))

    def chan(fact, date_col, cust_col):
        f = t[fact]
        return (f.join(period, on=f[date_col] == period["d_date_sk"])
                .join(cust, on=f[cust_col] == cust["c_customer_sk"])
                .select("c_last_name", "c_first_name", "d_date")
                .distinct())

    hot = (chan("store_sales", "ss_sold_date_sk", "ss_customer_sk")
           .intersect(chan("catalog_sales", "cs_sold_date_sk",
                           "cs_bill_customer_sk"))
           .intersect(chan("web_sales", "ws_sold_date_sk",
                           "ws_bill_customer_sk")))
    return hot.agg(F.count_star().alias("cnt"))


def q87(s, t):
    """Store-only customers in a period (TPC-DS 87: EXCEPT chain)."""
    F = _F()
    dt, cust = t["date_dim"], t["customer"]
    period = dt.filter(F.col("d_month_seq").between(350, 361))

    def chan(fact, date_col, cust_col):
        f = t[fact]
        return (f.join(period, on=f[date_col] == period["d_date_sk"])
                .join(cust, on=f[cust_col] == cust["c_customer_sk"])
                .select("c_last_name", "c_first_name", "d_date")
                .distinct())

    cool = (chan("store_sales", "ss_sold_date_sk", "ss_customer_sk")
            .subtract(chan("catalog_sales", "cs_sold_date_sk",
                           "cs_bill_customer_sk"))
            .subtract(chan("web_sales", "ws_sold_date_sk",
                           "ws_bill_customer_sk")))
    return cool.agg(F.count_star().alias("cnt"))


def q47(s, t):
    """Store brand monthly deviation with prior/next month context
    (TPC-DS 47: window chain — partition avg + lag + lead)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ss, dt, item, store = (t["store_sales"], t["date_dim"], t["item"],
                           t["store"])
    yrs = dt.filter(F.col("d_year").isin(1999, 2000, 2001))
    v1 = (ss.join(yrs, on=ss["ss_sold_date_sk"] == yrs["d_date_sk"])
          .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
          .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
          .groupBy("i_category", "i_brand", "s_store_name", "d_year",
                   "d_moy")
          .agg(F.sum(F.col("ss_sales_price")).alias("sum_sales")))
    w_avg = Window.partitionBy("i_category", "i_brand", "s_store_name",
                               "d_year")
    w_seq = Window.partitionBy("i_category", "i_brand", "s_store_name") \
        .orderBy("d_year", "d_moy")
    v2 = (v1.withColumn("avg_monthly_sales",
                        F.avg(F.col("sum_sales")).over(w_avg))
          .withColumn("psum", F.lag(F.col("sum_sales")).over(w_seq))
          .withColumn("nsum", F.lead(F.col("sum_sales")).over(w_seq)))
    return (v2.filter((F.col("d_year") == 2000)
                      & (F.col("avg_monthly_sales") > 0)
                      & (F.abs(F.col("sum_sales")
                               - F.col("avg_monthly_sales"))
                         / F.col("avg_monthly_sales") > 0.1))
            .select("i_category", "i_brand", "s_store_name", "d_year",
                    "d_moy", "sum_sales", "avg_monthly_sales", "psum",
                    "nsum")
            .sort(F.col("sum_sales") - F.col("avg_monthly_sales"),
                  "s_store_name", "d_moy")
            .limit(100))


def q57(s, t):
    """Catalog brand monthly deviation with prior/next month context
    (TPC-DS 57: q47's window chain on the catalog channel)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    cs, dt, item, cc = (t["catalog_sales"], t["date_dim"], t["item"],
                        t["call_center"])
    yrs = dt.filter(F.col("d_year").isin(1999, 2000, 2001))
    v1 = (cs.join(yrs, on=cs["cs_sold_date_sk"] == yrs["d_date_sk"])
          .join(item, on=cs["cs_item_sk"] == item["i_item_sk"])
          .join(cc, on=cs["cs_call_center_sk"] == cc["cc_call_center_sk"])
          .groupBy("i_category", "i_brand", "cc_name", "d_year", "d_moy")
          .agg(F.sum(F.col("cs_sales_price")).alias("sum_sales")))
    w_avg = Window.partitionBy("i_category", "i_brand", "cc_name", "d_year")
    w_seq = Window.partitionBy("i_category", "i_brand", "cc_name") \
        .orderBy("d_year", "d_moy")
    v2 = (v1.withColumn("avg_monthly_sales",
                        F.avg(F.col("sum_sales")).over(w_avg))
          .withColumn("psum", F.lag(F.col("sum_sales")).over(w_seq))
          .withColumn("nsum", F.lead(F.col("sum_sales")).over(w_seq)))
    return (v2.filter((F.col("d_year") == 2000)
                      & (F.col("avg_monthly_sales") > 0)
                      & (F.abs(F.col("sum_sales")
                               - F.col("avg_monthly_sales"))
                         / F.col("avg_monthly_sales") > 0.1))
            .select("i_category", "i_brand", "cc_name", "d_year", "d_moy",
                    "sum_sales", "avg_monthly_sales", "psum", "nsum")
            .sort(F.col("sum_sales") - F.col("avg_monthly_sales"),
                  "cc_name", "d_moy")
            .limit(100))


def q51(s, t):
    """Cumulative web vs store revenue per item (TPC-DS 51: running-sum
    windows + FULL OUTER join)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    dt = t["date_dim"]
    period = dt.filter(F.col("d_month_seq").between(350, 355))

    def cume(fact, date_col, item_col, price_col, prefix):
        f = t[fact]
        g = (f.join(period, on=f[date_col] == period["d_date_sk"])
             .groupBy(item_col, "d_date")
             .agg(F.sum(F.col(price_col)).alias("day_sales")))
        w = Window.partitionBy(item_col).orderBy("d_date") \
            .rowsBetween(Window.unboundedPreceding, Window.currentRow)
        return (g.withColumn("cume_sales",
                             F.sum(F.col("day_sales")).over(w))
                .select(F.col(item_col).alias(f"{prefix}_item"),
                        F.col("d_date").alias(f"{prefix}_date"),
                        F.col("cume_sales").alias(f"{prefix}_cume")))

    web = cume("web_sales", "ws_sold_date_sk", "ws_item_sk",
               "ws_sales_price", "w")
    st = cume("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_sales_price", "s")
    j = web.join(st, on=(web["w_item"] == st["s_item"])
                 & (web["w_date"] == st["s_date"]), how="full")
    return (j.withColumn("item_sk", F.coalesce(F.col("w_item"),
                                               F.col("s_item")))
            .withColumn("d_date", F.coalesce(F.col("w_date"),
                                             F.col("s_date")))
            .filter(F.coalesce(F.col("w_cume"), F.lit(0.0))
                    > F.coalesce(F.col("s_cume"), F.lit(0.0)))
            .select("item_sk", "d_date", "w_cume", "s_cume")
            .sort("item_sk", "d_date")
            .limit(100))


def _web_returns_with_site(t, days):
    """Web returns carry no site key — recover ws_web_site_sk by joining
    back to the originating sale on (order, item), the way the standard's
    q5/q77 resolve the web return's site/page."""
    F = _F()
    wr, ws = t["web_returns"], t["web_sales"]
    sale = ws.select(F.col("ws_order_number").alias("o_order"),
                     F.col("ws_item_sk").alias("o_item"),
                     F.col("ws_web_site_sk")).distinct()
    return (wr.join(days, on=wr["wr_returned_date_sk"] == days["d_date_sk"])
            .join(sale, on=(wr["wr_order_number"] == sale["o_order"])
                  & (wr["wr_item_sk"] == sale["o_item"])))


def q5_rollup(s, t):
    """Channel sales/returns/profit ROLLUP (TPC-DS 5: union of sales and
    returns rows per channel, rollup(channel, id))."""
    F = _F()
    dt = t["date_dim"]
    days = dt.filter((F.col("d_date") >= F.lit(10585))
                     & (F.col("d_date") <= F.lit(10599)))

    def part(fact, date_col, id_col, sales_col, profit_col, channel):
        f = t[fact]
        return (f.join(days, on=f[date_col] == days["d_date_sk"])
                .select(F.lit(channel).alias("channel"),
                        F.col(id_col).alias("id"),
                        F.col(sales_col).alias("sales"),
                        F.lit(0.0).alias("returns_amt"),
                        F.col(profit_col).alias("profit")))

    def rpart(fact, date_col, id_col, ret_col, loss_col, channel):
        f = t[fact]
        return (f.join(days, on=f[date_col] == days["d_date_sk"])
                .select(F.lit(channel).alias("channel"),
                        F.col(id_col).alias("id"),
                        F.lit(0.0).alias("sales"),
                        F.col(ret_col).alias("returns_amt"),
                        (F.lit(0.0) - F.col(loss_col)).alias("profit")))

    u = (part("store_sales", "ss_sold_date_sk", "ss_store_sk",
              "ss_ext_sales_price", "ss_net_profit", "store channel")
         .union(rpart("store_returns", "sr_returned_date_sk", "sr_store_sk",
                      "sr_return_amt", "sr_net_loss", "store channel"))
         .union(part("catalog_sales", "cs_sold_date_sk",
                     "cs_call_center_sk", "cs_ext_sales_price",
                     "cs_net_profit", "catalog channel"))
         .union(rpart("catalog_returns", "cr_returned_date_sk",
                      "cr_call_center_sk", "cr_return_amount",
                      "cr_net_loss", "catalog channel"))
         .union(part("web_sales", "ws_sold_date_sk", "ws_web_site_sk",
                     "ws_ext_sales_price", "ws_net_profit", "web channel"))
         .union(_web_returns_with_site(t, days).select(
             F.lit("web channel").alias("channel"),
             F.col("ws_web_site_sk").alias("id"),
             F.lit(0.0).alias("sales"),
             F.col("wr_return_amt").alias("returns_amt"),
             (F.lit(0.0) - F.col("wr_net_loss")).alias("profit"))))
    return (u.rollup("channel", "id")
            .agg(F.sum(F.col("sales")).alias("sales"),
                 F.sum(F.col("returns_amt")).alias("returns_amt"),
                 F.sum(F.col("profit")).alias("profit"))
            .sort("channel", "id")
            .limit(100))


def q14_simplified(s, t):
    """Cross-channel items ROLLUP (TPC-DS 14 shape: INTERSECT of item
    attributes across channels feeding a rollup aggregate)."""
    F = _F()
    dt, item = t["date_dim"], t["item"]
    yrs = dt.filter(F.col("d_year").isin(1999, 2000, 2001))

    def chan_items(fact, date_col, item_col):
        f = t[fact]
        return (f.join(yrs, on=f[date_col] == yrs["d_date_sk"])
                .join(item, on=f[item_col] == item["i_item_sk"])
                .select("i_brand", "i_class", "i_category").distinct())

    cross = (chan_items("store_sales", "ss_sold_date_sk", "ss_item_sk")
             .intersect(chan_items("catalog_sales", "cs_sold_date_sk",
                                   "cs_item_sk"))
             .intersect(chan_items("web_sales", "ws_sold_date_sk",
                                   "ws_item_sk"))
             .select(F.col("i_brand").alias("x_brand"),
                     F.col("i_class").alias("x_class"),
                     F.col("i_category").alias("x_cat")))
    ss = t["store_sales"]
    y2000 = dt.filter(F.col("d_year") == 2000)
    base = (ss.join(y2000, on=ss["ss_sold_date_sk"] == y2000["d_date_sk"])
            .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
            .join(cross, on=(item["i_brand"] == cross["x_brand"])
                  & (item["i_class"] == cross["x_class"])
                  & (item["i_category"] == cross["x_cat"]),
                  how="leftsemi"))
    return (base.rollup("i_category", "i_class", "i_brand")
            .agg(F.sum(F.col("ss_quantity") * F.col("ss_list_price"))
                 .alias("sales"),
                 F.count_star().alias("number_sales"))
            .sort("i_category", "i_class", "i_brand")
            .limit(100))


def q18(s, t):
    """Catalog averages over a geography ROLLUP (TPC-DS 18)."""
    F = _F()
    cs, cd, cust, ca, dt, item = (
        t["catalog_sales"], t["customer_demographics"], t["customer"],
        t["customer_address"], t["date_dim"], t["item"])
    y = dt.filter(F.col("d_year") == 1998)
    sel_cd = cd.filter((F.col("cd_gender") == "F")
                       & (F.col("cd_education_status") == "Unknown"))
    j = (cs.join(y, on=cs["cs_sold_date_sk"] == y["d_date_sk"])
         .join(item, on=cs["cs_item_sk"] == item["i_item_sk"])
         .join(sel_cd, on=cs["cs_bill_cdemo_sk"] == sel_cd["cd_demo_sk"])
         .join(cust, on=cs["cs_bill_customer_sk"] == cust["c_customer_sk"])
         .join(ca, on=cust["c_current_addr_sk"] == ca["ca_address_sk"]))
    return (j.rollup("ca_country", "ca_state", "ca_county", "i_item_id")
            .agg(F.avg(F.col("cs_quantity")).alias("agg1"),
                 F.avg(F.col("cs_list_price")).alias("agg2"),
                 F.avg(F.col("cs_coupon_amt")).alias("agg3"),
                 F.avg(F.col("cs_sales_price")).alias("agg4"))
            .sort("ca_country", "ca_state", "ca_county", "i_item_id")
            .limit(100))


def q22(s, t):
    """Inventory quantity-on-hand over the item hierarchy ROLLUP
    (TPC-DS 22)."""
    F = _F()
    inv, dt, item = t["inventory"], t["date_dim"], t["item"]
    period = dt.filter(F.col("d_month_seq").between(350, 361))
    j = (inv.join(period, on=inv["inv_date_sk"] == period["d_date_sk"])
         .join(item, on=inv["inv_item_sk"] == item["i_item_sk"]))
    return (j.rollup("i_category", "i_class", "i_brand", "i_item_id")
            .agg(F.avg(F.col("inv_quantity_on_hand")).alias("qoh"))
            .sort("qoh", "i_category", "i_class", "i_brand", "i_item_id")
            .limit(100))


def q67(s, t):
    """Top items per category over a store/time ROLLUP with a rank window
    (TPC-DS 67)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ss, dt, store, item = (t["store_sales"], t["date_dim"], t["store"],
                           t["item"])
    period = dt.filter(F.col("d_month_seq").between(350, 361))
    g = (ss.join(period, on=ss["ss_sold_date_sk"] == period["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
         .rollup("i_category", "i_class", "i_brand", "d_year", "d_qoy",
                 "d_moy", "s_store_id")
         .agg(F.sum(F.coalesce(F.col("ss_sales_price")
                               * F.col("ss_quantity"), F.lit(0.0)))
              .alias("sumsales")))
    w = Window.partitionBy("i_category").orderBy(F.col("sumsales").desc())
    return (g.withColumn("rk", F.rank().over(w))
            .filter(F.col("rk") <= 10)
            .select("i_category", "i_class", "i_brand", "d_year", "d_qoy",
                    "d_moy", "s_store_id", "sumsales", "rk")
            .sort("i_category", F.col("sumsales").desc(), "rk")
            .limit(100))


def q77(s, t):
    """Per-channel sales vs returns ROLLUP (TPC-DS 77)."""
    F = _F()
    dt = t["date_dim"]
    days = dt.filter((F.col("d_date") >= F.lit(10585))
                     & (F.col("d_date") <= F.lit(10615)))

    def sales_by(fact, date_col, id_col, sales_col, profit_col):
        f = t[fact]
        return (f.join(days, on=f[date_col] == days["d_date_sk"])
                .groupBy(id_col)
                .agg(F.sum(F.col(sales_col)).alias("sales"),
                     F.sum(F.col(profit_col)).alias("profit"))
                .select(F.col(id_col).alias("sid"), F.col("sales"),
                        F.col("profit")))

    def returns_by(fact, date_col, id_col, ret_col, loss_col):
        f = t[fact]
        return (f.join(days, on=f[date_col] == days["d_date_sk"])
                .groupBy(id_col)
                .agg(F.sum(F.col(ret_col)).alias("returns_amt"),
                     F.sum(F.col(loss_col)).alias("profit_loss"))
                .select(F.col(id_col).alias("rid"), F.col("returns_amt"),
                        F.col("profit_loss")))

    def channel(sales, rets, name):
        j = sales.join(rets, on=sales["sid"] == rets["rid"], how="left")
        return j.select(
            F.lit(name).alias("channel"), F.col("sid").alias("id"),
            F.col("sales"),
            F.coalesce(F.col("returns_amt"), F.lit(0.0))
            .alias("returns_amt"),
            (F.col("profit")
             - F.coalesce(F.col("profit_loss"), F.lit(0.0)))
            .alias("profit"))

    u = (channel(sales_by("store_sales", "ss_sold_date_sk", "ss_store_sk",
                          "ss_ext_sales_price", "ss_net_profit"),
                 returns_by("store_returns", "sr_returned_date_sk",
                            "sr_store_sk", "sr_return_amt", "sr_net_loss"),
                 "store channel")
         .union(channel(
             sales_by("catalog_sales", "cs_sold_date_sk",
                      "cs_call_center_sk", "cs_ext_sales_price",
                      "cs_net_profit"),
             returns_by("catalog_returns", "cr_returned_date_sk",
                        "cr_call_center_sk", "cr_return_amount",
                        "cr_net_loss"),
             "catalog channel"))
         .union(channel(
             sales_by("web_sales", "ws_sold_date_sk", "ws_web_site_sk",
                      "ws_ext_sales_price", "ws_net_profit"),
             _web_returns_with_site(t, days)
             .groupBy("ws_web_site_sk")
             .agg(F.sum(F.col("wr_return_amt")).alias("returns_amt"),
                  F.sum(F.col("wr_net_loss")).alias("profit_loss"))
             .select(F.col("ws_web_site_sk").alias("rid"),
                     F.col("returns_amt"), F.col("profit_loss")),
             "web channel")))
    return (u.rollup("channel", "id")
            .agg(F.sum(F.col("sales")).alias("sales"),
                 F.sum(F.col("returns_amt")).alias("returns_amt"),
                 F.sum(F.col("profit")).alias("profit"))
            .sort("channel", "id")
            .limit(100))


def q80(s, t):
    """Channel sales net of returns ROLLUP with promo filter (TPC-DS 80:
    sales LEFT OUTER JOIN returns per channel, union, rollup(channel,id))."""
    F = _F()
    dt, item, promo = t["date_dim"], t["item"], t["promotion"]
    days = dt.filter((F.col("d_date") >= F.lit(10585))
                     & (F.col("d_date") <= F.lit(10615)))
    sel_i = item.filter(F.col("i_current_price") > 50.0)
    sel_p = promo.filter(F.col("p_channel_tv") == "N")

    def channel(fact, ret, date_col, id_col, item_col, order_col, promo_col,
                price_col, profit_col, r_item, r_order, ret_amt, ret_loss,
                name):
        f, r = t[fact], t[ret]
        rsel = r.select(F.col(r_item).alias("r_item"),
                        F.col(r_order).alias("r_order"),
                        F.col(ret_amt).alias("r_amt"),
                        F.col(ret_loss).alias("r_loss"))
        j = (f.join(days, on=f[date_col] == days["d_date_sk"])
             .join(sel_i, on=f[item_col] == sel_i["i_item_sk"])
             .join(sel_p, on=f[promo_col] == sel_p["p_promo_sk"])
             .join(rsel, on=(f[item_col] == rsel["r_item"])
                   & (f[order_col] == rsel["r_order"]), how="left"))
        return (j.groupBy(id_col)
                .agg(F.sum(F.col(price_col)).alias("sales"),
                     F.sum(F.coalesce(F.col("r_amt"), F.lit(0.0)))
                     .alias("returns_amt"),
                     F.sum(F.col(profit_col)
                           - F.coalesce(F.col("r_loss"), F.lit(0.0)))
                     .alias("profit"))
                .select(F.lit(name).alias("channel"),
                        F.col(id_col).alias("id"), F.col("sales"),
                        F.col("returns_amt"), F.col("profit")))

    u = (channel("store_sales", "store_returns", "ss_sold_date_sk",
                 "ss_store_sk", "ss_item_sk", "ss_ticket_number",
                 "ss_promo_sk", "ss_ext_sales_price", "ss_net_profit",
                 "sr_item_sk", "sr_ticket_number", "sr_return_amt",
                 "sr_net_loss", "store channel")
         .union(channel("catalog_sales", "catalog_returns",
                        "cs_sold_date_sk", "cs_call_center_sk",
                        "cs_item_sk", "cs_order_number", "cs_promo_sk",
                        "cs_ext_sales_price", "cs_net_profit", "cr_item_sk",
                        "cr_order_number", "cr_return_amount", "cr_net_loss",
                        "catalog channel"))
         .union(channel("web_sales", "web_returns", "ws_sold_date_sk",
                        "ws_web_site_sk", "ws_item_sk", "ws_order_number",
                        "ws_promo_sk", "ws_ext_sales_price", "ws_net_profit",
                        "wr_item_sk", "wr_order_number", "wr_return_amt",
                        "wr_net_loss", "web channel")))
    return (u.rollup("channel", "id")
            .agg(F.sum(F.col("sales")).alias("sales"),
                 F.sum(F.col("returns_amt")).alias("returns_amt"),
                 F.sum(F.col("profit")).alias("profit"))
            .sort("channel", "id")
            .limit(100))


def q2(s, t):
    """Week-over-year catalog+web sales ratio by day of week (TPC-DS 2)."""
    F = _F()
    dt, ws, cs = t["date_dim"], t["web_sales"], t["catalog_sales"]
    sales = (ws.select(F.col("ws_sold_date_sk").alias("sold_date_sk"),
                       F.col("ws_ext_sales_price").alias("sales_price"))
             .union(cs.select(
                 F.col("cs_sold_date_sk").alias("sold_date_sk"),
                 F.col("cs_ext_sales_price").alias("sales_price"))))
    j = sales.join(dt, on=sales["sold_date_sk"] == dt["d_date_sk"])
    days = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
            "Friday", "Saturday"]
    aggs = [F.sum(F.when(F.col("d_day_name") == day, F.col("sales_price"))
                  .otherwise(F.lit(None))).alias(f"{day[:3].lower()}_sales")
            for day in days]
    wk = j.groupBy("d_week_seq").agg(*aggs)
    wk1998 = dt.filter(F.col("d_year") == 1998) \
        .select("d_week_seq").distinct()
    wk1999 = dt.filter(F.col("d_year") == 1999) \
        .select("d_week_seq").distinct()
    y = wk.join(wk1998, on=wk["d_week_seq"] == wk1998["d_week_seq"],
                how="leftsemi")
    z = wk.join(wk1999, on=wk["d_week_seq"] == wk1999["d_week_seq"],
                how="leftsemi") \
        .select((F.col("d_week_seq") - 53).alias("wk2"),
                *[F.col(f"{d[:3].lower()}_sales").alias(
                    f"{d[:3].lower()}_sales2") for d in days])
    jj = y.join(z, on=y["d_week_seq"] == z["wk2"])
    ratios = [F.round(F.col(f"{d[:3].lower()}_sales")
                      / F.col(f"{d[:3].lower()}_sales2"), 2)
              .alias(f"r_{d[:3].lower()}") for d in days]
    return jj.select(F.col("d_week_seq"), *ratios).sort("d_week_seq")


def _year_total(t, fact, date_col, cust_col, amount, year):
    """Per-customer yearly total for the q4/q11/q74 growth family."""
    F = _F()
    f, dt = t[fact], t["date_dim"]
    y = dt.filter(F.col("d_year") == year)
    return (f.join(y, on=f[date_col] == y["d_date_sk"])
            .groupBy(cust_col)
            .agg(F.sum(amount).alias("year_total"))
            .filter(F.col("year_total") > 0))


def q4(s, t):
    """Customers whose catalog AND web growth beat store growth
    (TPC-DS 4: six per-channel year totals joined per customer)."""
    F = _F()
    cust = t["customer"]
    ss_amt = (F.col("ss_ext_list_price") - F.col("ss_ext_wholesale_cost")
              - F.col("ss_ext_discount_amt")
              + F.col("ss_ext_sales_price")) / 2
    cs_amt = (F.col("cs_ext_list_price") - F.col("cs_ext_wholesale_cost")
              - F.col("cs_ext_discount_amt")
              + F.col("cs_ext_sales_price")) / 2
    ws_amt = (F.col("ws_ext_list_price") - F.col("ws_ext_wholesale_cost")
              - F.col("ws_ext_discount_amt")
              + F.col("ws_ext_sales_price")) / 2

    def yt(fact, date_col, cust_col, amt, year, name):
        return _year_total(t, fact, date_col, cust_col, amt, year) \
            .select(F.col(cust_col).alias(f"{name}_cust"),
                    F.col("year_total").alias(name))

    ss1 = yt("store_sales", "ss_sold_date_sk", "ss_customer_sk", ss_amt,
             1999, "ss1")
    ss2 = yt("store_sales", "ss_sold_date_sk", "ss_customer_sk", ss_amt,
             2000, "ss2")
    cs1 = yt("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk",
             cs_amt, 1999, "cs1")
    cs2 = yt("catalog_sales", "cs_sold_date_sk", "cs_bill_customer_sk",
             cs_amt, 2000, "cs2")
    ws1 = yt("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk", ws_amt,
             1999, "ws1")
    ws2 = yt("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk", ws_amt,
             2000, "ws2")
    j = (ss1.join(ss2, on=ss1["ss1_cust"] == ss2["ss2_cust"])
         .join(cs1, on=ss1["ss1_cust"] == cs1["cs1_cust"])
         .join(cs2, on=ss1["ss1_cust"] == cs2["cs2_cust"])
         .join(ws1, on=ss1["ss1_cust"] == ws1["ws1_cust"])
         .join(ws2, on=ss1["ss1_cust"] == ws2["ws2_cust"]))
    j = j.filter((F.col("cs2") / F.col("cs1") > F.col("ss2") / F.col("ss1"))
                 & (F.col("cs2") / F.col("cs1")
                    > F.col("ws2") / F.col("ws1")))
    return (j.join(cust, on=j["ss1_cust"] == cust["c_customer_sk"])
            .select("c_customer_id", "c_first_name", "c_last_name")
            .sort("c_customer_id")
            .limit(100))


def q9(s, t):
    """Quantity-bucketed conditional averages off a one-row reason probe
    (TPC-DS 9: CASE over cross-joined scalar aggregates)."""
    F = _F()
    ss, reason = t["store_sales"], t["reason"]
    buckets = [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)]
    aggs = []
    for i, (lo, hi) in enumerate(buckets, 1):
        inb = F.col("ss_quantity").between(lo, hi)
        aggs += [
            F.sum(F.when(inb, 1).otherwise(0)).alias(f"cnt{i}"),
            F.avg(F.when(inb, F.col("ss_ext_discount_amt"))
                  .otherwise(F.lit(None))).alias(f"avg_disc{i}"),
            F.avg(F.when(inb, F.col("ss_net_paid"))
                  .otherwise(F.lit(None))).alias(f"avg_paid{i}"),
        ]
    stats = ss.agg(*aggs)
    probe = reason.filter(F.col("r_reason_sk") == 1).select("r_reason_sk")
    out = probe.crossJoin(stats)
    cases = [F.when(F.col(f"cnt{i}") > 100 * i,
                    F.col(f"avg_disc{i}"))
             .otherwise(F.col(f"avg_paid{i}")).alias(f"bucket{i}")
             for i in range(1, 6)]
    return out.select(*cases)


def q10(s, t):
    """Demographic counts for county customers active in store AND
    (web OR catalog) channels (TPC-DS 10: EXISTS lowered to semi joins)."""
    F = _F()
    cust, ca, cd, dt = (t["customer"], t["customer_address"],
                        t["customer_demographics"], t["date_dim"])
    period = dt.filter((F.col("d_year") == 2000)
                       & F.col("d_moy").between(1, 4))
    ss_cust = (t["store_sales"]
               .join(period, on=t["store_sales"]["ss_sold_date_sk"]
                     == period["d_date_sk"])
               .select(F.col("ss_customer_sk").alias("a_cust")).distinct())
    ws_cust = (t["web_sales"]
               .join(period, on=t["web_sales"]["ws_sold_date_sk"]
                     == period["d_date_sk"])
               .select(F.col("ws_bill_customer_sk").alias("a_cust")))
    cs_cust = (t["catalog_sales"]
               .join(period, on=t["catalog_sales"]["cs_sold_date_sk"]
                     == period["d_date_sk"])
               .select(F.col("cs_bill_customer_sk").alias("a_cust")))
    other = ws_cust.union(cs_cust).distinct()
    sel_ca = ca.filter(F.col("ca_county").isin("county0", "county1",
                                               "county2", "county3",
                                               "county4"))
    j = (cust.join(ss_cust, on=cust["c_customer_sk"] == ss_cust["a_cust"],
                   how="leftsemi")
         .join(other, on=cust["c_customer_sk"] == other["a_cust"],
               how="leftsemi")
         .join(sel_ca, on=cust["c_current_addr_sk"]
               == sel_ca["ca_address_sk"])
         .join(cd, on=cust["c_current_cdemo_sk"] == cd["cd_demo_sk"]))
    return (j.groupBy("cd_gender", "cd_marital_status",
                      "cd_education_status")
            .agg(F.count_star().alias("cnt"),
                 F.min(F.col("cd_purchase_estimate")).alias("min_est"),
                 F.max(F.col("cd_purchase_estimate")).alias("max_est"),
                 F.avg(F.col("cd_purchase_estimate")).alias("avg_est"))
            .sort("cd_gender", "cd_marital_status", "cd_education_status")
            .limit(100))


def q11(s, t):
    """Customers whose web growth beats store growth (TPC-DS 11)."""
    F = _F()
    cust = t["customer"]
    ss_amt = F.col("ss_ext_list_price") - F.col("ss_ext_discount_amt")
    ws_amt = F.col("ws_ext_list_price") - F.col("ws_ext_discount_amt")

    def yt(fact, date_col, cust_col, amt, year, name):
        return _year_total(t, fact, date_col, cust_col, amt, year) \
            .select(F.col(cust_col).alias(f"{name}_cust"),
                    F.col("year_total").alias(name))

    ss1 = yt("store_sales", "ss_sold_date_sk", "ss_customer_sk", ss_amt,
             1999, "ss1")
    ss2 = yt("store_sales", "ss_sold_date_sk", "ss_customer_sk", ss_amt,
             2000, "ss2")
    ws1 = yt("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk", ws_amt,
             1999, "ws1")
    ws2 = yt("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk", ws_amt,
             2000, "ws2")
    j = (ss1.join(ss2, on=ss1["ss1_cust"] == ss2["ss2_cust"])
         .join(ws1, on=ss1["ss1_cust"] == ws1["ws1_cust"])
         .join(ws2, on=ss1["ss1_cust"] == ws2["ws2_cust"])
         .filter(F.col("ws2") / F.col("ws1")
                 > F.col("ss2") / F.col("ss1")))
    return (j.join(cust, on=j["ss1_cust"] == cust["c_customer_sk"])
            .select("c_customer_id", "c_first_name", "c_last_name")
            .sort("c_customer_id")
            .limit(100))


def q16(s, t):
    """Multi-warehouse catalog orders never returned (TPC-DS 16:
    EXISTS/NOT EXISTS + COUNT DISTINCT via two-phase dedup)."""
    F = _F()
    cs, cr, dt, cc = (t["catalog_sales"], t["catalog_returns"],
                      t["date_dim"], t["call_center"])
    days = dt.filter((F.col("d_date") >= F.lit(10585))
                     & (F.col("d_date") <= F.lit(10645)))
    multi_wh = (t["catalog_sales"]
                .select("cs_order_number", "cs_warehouse_sk").distinct()
                .groupBy("cs_order_number")
                .agg(F.count_star().alias("n_wh"))
                .filter(F.col("n_wh") > 1)
                .select(F.col("cs_order_number").alias("mw_order")))
    base = (cs.join(days, on=cs["cs_ship_date_sk"] == days["d_date_sk"])
            .join(cc, on=cs["cs_call_center_sk"] == cc["cc_call_center_sk"])
            .join(multi_wh, on=cs["cs_order_number"] == multi_wh["mw_order"],
                  how="leftsemi")
            .join(cr.select(F.col("cr_order_number").alias("r_order")),
                  on=cs["cs_order_number"] == F.col("r_order"),
                  how="leftanti"))
    orders = (base.select("cs_order_number").distinct()
              .agg(F.count_star().alias("order_count")))
    money = base.agg(F.sum(F.col("cs_ext_tax")).alias("total_tax"),
                     F.sum(F.col("cs_net_profit")).alias("total_profit"))
    return orders.crossJoin(money)


def q17(s, t):
    """Quantity statistics across the sale→return→repurchase chain
    (TPC-DS 17: three date roles, avg/stddev per item and state)."""
    F = _F()
    ss, sr, cs, dt, store, item = (
        t["store_sales"], t["store_returns"], t["catalog_sales"],
        t["date_dim"], t["store"], t["item"])
    # year-wide date roles: the standard's quarter windows select almost
    # nothing at the suite's toy scale (the repurchase join is already the
    # selective step)
    d1 = dt.filter(F.col("d_year") == 2000) \
        .select(F.col("d_date_sk").alias("d1_sk"))
    d2 = dt.filter(F.col("d_year").between(1998, 2004)) \
        .select(F.col("d_date_sk").alias("d2_sk"))
    d3 = dt.filter(F.col("d_year").between(1998, 2004)) \
        .select(F.col("d_date_sk").alias("d3_sk"))
    j = (ss.join(sr, on=(ss["ss_ticket_number"] == sr["sr_ticket_number"])
                 & (ss["ss_item_sk"] == sr["sr_item_sk"])
                 & (ss["ss_customer_sk"] == sr["sr_customer_sk"]))
         .join(cs, on=(sr["sr_customer_sk"] == cs["cs_bill_customer_sk"])
               & (sr["sr_item_sk"] == cs["cs_item_sk"]))
         .join(d1, on=ss["ss_sold_date_sk"] == F.col("d1_sk"))
         .join(d2, on=sr["sr_returned_date_sk"] == F.col("d2_sk"))
         .join(d3, on=cs["cs_sold_date_sk"] == F.col("d3_sk"))
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(item, on=ss["ss_item_sk"] == item["i_item_sk"]))
    return (j.groupBy("i_item_id", "s_state")
            .agg(F.count(F.col("ss_quantity")).alias("store_sales_cnt"),
                 F.avg(F.col("ss_quantity")).alias("store_sales_avg"),
                 F.stddev(F.col("ss_quantity")).alias("store_sales_stdev"),
                 F.count(F.col("sr_return_quantity"))
                 .alias("store_ret_cnt"),
                 F.avg(F.col("sr_return_quantity")).alias("store_ret_avg"),
                 F.count(F.col("cs_quantity")).alias("catalog_cnt"),
                 F.avg(F.col("cs_quantity")).alias("catalog_avg"))
            .sort("i_item_id", "s_state")
            .limit(100))


def q21(s, t):
    """Inventory shift around a pivot date per warehouse/item
    (TPC-DS 21)."""
    F = _F()
    inv, wh, item, dt = (t["inventory"], t["warehouse"], t["item"],
                         t["date_dim"])
    # wider window + looser ratio than the standard: inventory is sparse
    # per (warehouse,item) at suite scale, the shape is what's exercised
    pivot = 10600
    days = dt.filter((F.col("d_date") >= F.lit(pivot - 120))
                     & (F.col("d_date") <= F.lit(pivot + 120)))
    sel_i = item.filter(F.col("i_current_price").between(0.99, 150.0))
    j = (inv.join(days, on=inv["inv_date_sk"] == days["d_date_sk"])
         .join(sel_i, on=inv["inv_item_sk"] == sel_i["i_item_sk"])
         .join(wh, on=inv["inv_warehouse_sk"] == wh["w_warehouse_sk"]))
    g = (j.groupBy("w_warehouse_name", "i_item_id")
         .agg(F.sum(F.when(F.col("d_date") < pivot,
                           F.col("inv_quantity_on_hand")).otherwise(0))
              .alias("inv_before"),
              F.sum(F.when(F.col("d_date") >= pivot,
                           F.col("inv_quantity_on_hand")).otherwise(0))
              .alias("inv_after")))
    return (g.filter((F.col("inv_before") > 0)
                     & (F.col("inv_after") / F.col("inv_before") >= 1.0 / 3)
                     & (F.col("inv_after") / F.col("inv_before") <= 3.0))
            .select("w_warehouse_name", "i_item_id", "inv_before",
                    "inv_after")
            .sort("w_warehouse_name", "i_item_id")
            .limit(100))


def q23_simplified(s, t):
    """Catalog+web sales to best customers on frequent items (TPC-DS 23
    shape: two derived cohorts feeding semi joins)."""
    F = _F()
    dt, ss = t["date_dim"], t["store_sales"]
    yrs = dt.filter(F.col("d_year").isin(1999, 2000))
    frequent = (ss.join(yrs, on=ss["ss_sold_date_sk"] == yrs["d_date_sk"])
                .groupBy("ss_item_sk")
                .agg(F.count_star().alias("cnt"))
                .filter(F.col("cnt") > 4)
                .select(F.col("ss_item_sk").alias("f_item")))
    spend = (ss.groupBy("ss_customer_sk")
             .agg(F.sum(F.col("ss_quantity") * F.col("ss_sales_price"))
                  .alias("csales")))
    tpcds_max = spend.agg(F.max(F.col("csales")).alias("tpcds_cmax"))
    best = (spend.crossJoin(tpcds_max)
            .filter(F.col("csales") > 0.5 * F.col("tpcds_cmax"))
            .select(F.col("ss_customer_sk").alias("b_cust")))
    month = dt.filter((F.col("d_year") == 2000) & (F.col("d_moy") == 3))
    cs, ws = t["catalog_sales"], t["web_sales"]
    cs_part = (cs.join(month, on=cs["cs_sold_date_sk"] == month["d_date_sk"])
               .join(frequent, on=cs["cs_item_sk"] == frequent["f_item"],
                     how="leftsemi")
               .join(best, on=cs["cs_bill_customer_sk"] == best["b_cust"],
                     how="leftsemi")
               .select((F.col("cs_quantity") * F.col("cs_list_price"))
                       .alias("sales")))
    ws_part = (ws.join(month, on=ws["ws_sold_date_sk"] == month["d_date_sk"])
               .join(frequent, on=ws["ws_item_sk"] == frequent["f_item"],
                     how="leftsemi")
               .join(best, on=ws["ws_bill_customer_sk"] == best["b_cust"],
                     how="leftsemi")
               .select((F.col("ws_quantity") * F.col("ws_list_price"))
                       .alias("sales")))
    return cs_part.union(ws_part).agg(F.sum(F.col("sales")).alias("sales"))


def q24_simplified(s, t):
    """Returned-sale net paid per customer and item color vs a global
    threshold (TPC-DS 24 shape)."""
    F = _F()
    ss, sr, store, item, cust = (t["store_sales"], t["store_returns"],
                                 t["store"], t["item"], t["customer"])
    j = (ss.join(sr, on=(ss["ss_ticket_number"] == sr["sr_ticket_number"])
                 & (ss["ss_item_sk"] == sr["sr_item_sk"]))
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(item, on=ss["ss_item_sk"] == item["i_item_sk"])
         .join(cust, on=ss["ss_customer_sk"] == cust["c_customer_sk"]))
    g = (j.groupBy("c_last_name", "c_first_name", "s_store_name",
                   "i_color")
         .agg(F.sum(F.col("ss_net_paid")).alias("netpaid")))
    thresh = g.agg((F.avg(F.col("netpaid")) * 0.05).alias("paid_thresh"))
    return (g.crossJoin(thresh)
            .filter(F.col("netpaid") > F.col("paid_thresh"))
            .select("c_last_name", "c_first_name", "s_store_name",
                    "netpaid")
            .sort("c_last_name", "c_first_name", "s_store_name")
            .limit(100))


def q28(s, t):
    """Six list-price bucket profiles with distinct counts (TPC-DS 28:
    cross-joined scalar aggregates, COUNT DISTINCT two-phase)."""
    F = _F()
    ss = t["store_sales"]
    buckets = [(0, 5, 8.0, 108.0), (6, 10, 90.0, 190.0),
               (11, 15, 142.0, 242.0), (16, 20, 135.0, 235.0),
               (21, 25, 122.0, 222.0), (26, 30, 154.0, 254.0)]
    out = None
    for i, (qlo, qhi, plo, phi) in enumerate(buckets, 1):
        f = ss.filter(F.col("ss_quantity").between(qlo, qhi)
                      & (F.col("ss_list_price").between(plo, phi)
                         | F.col("ss_coupon_amt").between(plo, phi + 800)
                         | F.col("ss_wholesale_cost").between(plo - 60,
                                                              phi - 30)))
        stats = f.agg(F.avg(F.col("ss_list_price")).alias(f"b{i}_lp"),
                      F.count(F.col("ss_list_price")).alias(f"b{i}_cnt"))
        dcnt = (f.select("ss_list_price").distinct()
                .agg(F.count_star().alias(f"b{i}_cntd")))
        piece = stats.crossJoin(dcnt)
        out = piece if out is None else out.crossJoin(piece)
    return out


def q31(s, t):
    """County store-vs-web quarterly growth comparison (TPC-DS 31)."""
    F = _F()
    dt, ca = t["date_dim"], t["customer_address"]

    def qsum(fact, date_col, addr_col, price_col, qoy, name):
        f = t[fact]
        d = dt.filter((F.col("d_qoy") == qoy) & (F.col("d_year") == 2000))
        return (f.join(d, on=f[date_col] == d["d_date_sk"])
                .join(ca, on=f[addr_col] == ca["ca_address_sk"])
                .groupBy("ca_county")
                .agg(F.sum(F.col(price_col)).alias(name))
                .select(F.col("ca_county").alias(f"{name}_cty"),
                        F.col(name)))

    ss1 = qsum("store_sales", "ss_sold_date_sk", "ss_addr_sk",
               "ss_ext_sales_price", 1, "ss1")
    ss2 = qsum("store_sales", "ss_sold_date_sk", "ss_addr_sk",
               "ss_ext_sales_price", 2, "ss2")
    ss3 = qsum("store_sales", "ss_sold_date_sk", "ss_addr_sk",
               "ss_ext_sales_price", 3, "ss3")
    ws1 = qsum("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
               "ws_ext_sales_price", 1, "ws1")
    ws2 = qsum("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
               "ws_ext_sales_price", 2, "ws2")
    ws3 = qsum("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
               "ws_ext_sales_price", 3, "ws3")
    j = (ss1.join(ss2, on=ss1["ss1_cty"] == ss2["ss2_cty"])
         .join(ss3, on=ss1["ss1_cty"] == ss3["ss3_cty"])
         .join(ws1, on=ss1["ss1_cty"] == ws1["ws1_cty"])
         .join(ws2, on=ss1["ss1_cty"] == ws2["ws2_cty"])
         .join(ws3, on=ss1["ss1_cty"] == ws3["ws3_cty"]))
    return (j.filter((F.col("ws2") / F.col("ws1")
                      > F.col("ss2") / F.col("ss1"))
                     & (F.col("ws3") / F.col("ws2")
                        > F.col("ss3") / F.col("ss2")))
            .select(F.col("ss1_cty").alias("ca_county"),
                    (F.col("ws2") / F.col("ws1")).alias("web_q1_q2"),
                    (F.col("ss2") / F.col("ss1")).alias("store_q1_q2"))
            .sort("ca_county"))


def q34(s, t):
    """Households buying 2-4 tickets in the dom windows (TPC-DS 34)."""
    F = _F()
    ss, dt, store, hd, cust = (t["store_sales"], t["date_dim"], t["store"],
                               t["household_demographics"], t["customer"])
    days = dt.filter((F.col("d_dom").between(1, 3)
                      | F.col("d_dom").between(25, 28))
                     & F.col("d_year").isin(1999, 2000, 2001))
    sel_hd = hd.filter(F.col("hd_buy_potential").isin(">10000", "Unknown")
                       & (F.col("hd_vehicle_count") > 0))
    g = (ss.join(days, on=ss["ss_sold_date_sk"] == days["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
         .groupBy("ss_ticket_number", "ss_customer_sk")
         .agg(F.count_star().alias("cnt"))
         .filter(F.col("cnt").between(2, 4)))
    return (g.join(cust, on=g["ss_customer_sk"] == cust["c_customer_sk"])
            .select("c_last_name", "c_first_name", "ss_ticket_number",
                    "cnt")
            .sort(F.col("cnt").desc(), "c_last_name")
            .limit(100))


def q35(s, t):
    """Demographics of multi-channel buyers (TPC-DS 35)."""
    F = _F()
    cust, ca, cd, dt = (t["customer"], t["customer_address"],
                        t["customer_demographics"], t["date_dim"])
    period = dt.filter((F.col("d_year") == 2000)
                       & (F.col("d_qoy") < 4))
    ss_cust = (t["store_sales"]
               .join(period, on=t["store_sales"]["ss_sold_date_sk"]
                     == period["d_date_sk"])
               .select(F.col("ss_customer_sk").alias("a_cust")).distinct())
    ws_cust = (t["web_sales"]
               .join(period, on=t["web_sales"]["ws_sold_date_sk"]
                     == period["d_date_sk"])
               .select(F.col("ws_bill_customer_sk").alias("a_cust")))
    cs_cust = (t["catalog_sales"]
               .join(period, on=t["catalog_sales"]["cs_sold_date_sk"]
                     == period["d_date_sk"])
               .select(F.col("cs_bill_customer_sk").alias("a_cust")))
    other = ws_cust.union(cs_cust).distinct()
    j = (cust.join(ss_cust, on=cust["c_customer_sk"] == ss_cust["a_cust"],
                   how="leftsemi")
         .join(other, on=cust["c_customer_sk"] == other["a_cust"],
               how="leftsemi")
         .join(ca, on=cust["c_current_addr_sk"] == ca["ca_address_sk"])
         .join(cd, on=cust["c_current_cdemo_sk"] == cd["cd_demo_sk"]))
    return (j.groupBy("ca_state", "cd_gender", "cd_marital_status")
            .agg(F.count_star().alias("cnt"),
                 F.min(F.col("cd_dep_count")).alias("min_dep"),
                 F.max(F.col("cd_dep_count")).alias("max_dep"),
                 F.avg(F.col("cd_dep_count")).alias("avg_dep"))
            .sort("ca_state", "cd_gender", "cd_marital_status")
            .limit(100))


def q39(s, t):
    """Inventory variability month-over-month (TPC-DS 39: stdev/mean
    coefficient joined across adjacent months)."""
    F = _F()
    inv, dt, item, wh = (t["inventory"], t["date_dim"], t["item"],
                         t["warehouse"])
    y = dt.filter(F.col("d_year") == 2000)
    # warehouse/month grain (the standard's per-item grain has singleton
    # groups at suite scale, so sample stddev would be null everywhere);
    # uniform qoh gives cov≈0.58, so the standard's cov>1 would select
    # nothing — 0.5 keeps the same shape with live rows
    g = (inv.join(y, on=inv["inv_date_sk"] == y["d_date_sk"])
         .join(item, on=inv["inv_item_sk"] == item["i_item_sk"])
         .join(wh, on=inv["inv_warehouse_sk"] == wh["w_warehouse_sk"])
         .groupBy("w_warehouse_sk", "d_moy")
         .agg(F.stddev(F.col("inv_quantity_on_hand")).alias("stdev"),
              F.avg(F.col("inv_quantity_on_hand")).alias("mean")))
    g = (g.filter((F.col("mean") > 0)
                  & (F.col("stdev") / F.col("mean") > 0.5))
         .withColumn("cov", F.col("stdev") / F.col("mean")))
    m1 = g.filter(F.col("d_moy") == 1).select(
        F.col("w_warehouse_sk").alias("w1"), F.col("cov").alias("cov1"))
    m2 = g.filter(F.col("d_moy") == 2).select(
        F.col("w_warehouse_sk").alias("w2"), F.col("cov").alias("cov2"))
    return (m1.join(m2, on=m1["w1"] == m2["w2"])
            .select("w1", "cov1", "cov2")
            .sort("w1"))


def q40(s, t):
    """Catalog sales net of returns around a pivot date per warehouse state
    (TPC-DS 40)."""
    F = _F()
    cs, cr, wh, item, dt = (t["catalog_sales"], t["catalog_returns"],
                            t["warehouse"], t["item"], t["date_dim"])
    pivot = 10600
    days = dt.filter((F.col("d_date") >= F.lit(pivot - 30))
                     & (F.col("d_date") <= F.lit(pivot + 30)))
    sel_i = item.filter(F.col("i_current_price").between(0.99, 150.0))
    rsel = cr.select(F.col("cr_item_sk").alias("r_item"),
                     F.col("cr_order_number").alias("r_order"),
                     F.col("cr_return_amount").alias("r_amt"))
    j = (cs.join(days, on=cs["cs_sold_date_sk"] == days["d_date_sk"])
         .join(sel_i, on=cs["cs_item_sk"] == sel_i["i_item_sk"])
         .join(wh, on=cs["cs_warehouse_sk"] == wh["w_warehouse_sk"])
         .join(rsel, on=(cs["cs_item_sk"] == rsel["r_item"])
               & (cs["cs_order_number"] == rsel["r_order"]), how="left"))
    net = F.col("cs_sales_price") - F.coalesce(F.col("r_amt"), F.lit(0.0))
    return (j.groupBy("w_state", "i_item_id")
            .agg(F.sum(F.when(F.col("d_date") < pivot, net).otherwise(0.0))
                 .alias("sales_before"),
                 F.sum(F.when(F.col("d_date") >= pivot, net).otherwise(0.0))
                 .alias("sales_after"))
            .sort("w_state", "i_item_id")
            .limit(100))


def q41(s, t):
    """Distinct items of manufacturers with qualifying variants
    (TPC-DS 41: EXISTS over the item dimension itself)."""
    F = _F()
    item = t["item"]
    variants = (item.filter(
        F.col("i_color").isin("almond", "antique", "aquamarine", "azure",
                              "beige", "blue", "blush", "brown")
        & F.col("i_size").isin("small", "medium", "large"))
        .select(F.col("i_manufact_id").alias("v_manufact")).distinct())
    sel = item.filter(F.col("i_manufact_id").between(1, 500))
    return (sel.join(variants, on=sel["i_manufact_id"]
                     == variants["v_manufact"], how="leftsemi")
            .select("i_item_id").distinct()
            .sort("i_item_id")
            .limit(100))


def q44(s, t):
    """Best and worst items by store profit rank (TPC-DS 44: dual rank
    windows joined on rank)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ss, item = t["store_sales"], t["item"]
    base = (ss.filter(F.col("ss_store_sk") == 4)
            .groupBy("ss_item_sk")
            .agg(F.avg(F.col("ss_net_profit")).alias("rank_col")))
    asc = (base.withColumn(
        "rnk", F.rank().over(Window.orderBy(F.col("rank_col").asc())))
        .filter(F.col("rnk") <= 10)
        .select(F.col("rnk").alias("a_rnk"),
                F.col("ss_item_sk").alias("best_sk")))
    desc = (base.withColumn(
        "rnk", F.rank().over(Window.orderBy(F.col("rank_col").desc())))
        .filter(F.col("rnk") <= 10)
        .select(F.col("rnk").alias("d_rnk"),
                F.col("ss_item_sk").alias("worst_sk")))
    i1 = item.select(F.col("i_item_sk").alias("i1_sk"),
                     F.col("i_item_id").alias("best_performing"))
    i2 = item.select(F.col("i_item_sk").alias("i2_sk"),
                     F.col("i_item_id").alias("worst_performing"))
    return (asc.join(desc, on=asc["a_rnk"] == desc["d_rnk"])
            .join(i1, on=F.col("best_sk") == i1["i1_sk"])
            .join(i2, on=F.col("worst_sk") == i2["i2_sk"])
            .select(F.col("a_rnk").alias("rnk"), "best_performing",
                    "worst_performing")
            .sort("rnk"))


def q46(s, t):
    """Weekend city purchases by mobile households (TPC-DS 46)."""
    F = _F()
    ss, dt, store, hd, ca, cust = (t["store_sales"], t["date_dim"],
                                   t["store"], t["household_demographics"],
                                   t["customer_address"], t["customer"])
    days = dt.filter(F.col("d_dow").isin(6, 0)
                     & F.col("d_year").isin(1999, 2000, 2001))
    sel_hd = hd.filter((F.col("hd_dep_count") == 4)
                       | (F.col("hd_vehicle_count") == 3))
    sel_ca = ca.select(F.col("ca_address_sk").alias("pos_addr"),
                       F.col("ca_city").alias("bought_city"))
    g = (ss.join(days, on=ss["ss_sold_date_sk"] == days["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(sel_hd, on=ss["ss_hdemo_sk"] == sel_hd["hd_demo_sk"])
         .join(sel_ca, on=ss["ss_addr_sk"] == sel_ca["pos_addr"])
         .groupBy("ss_ticket_number", "ss_customer_sk", "bought_city")
         .agg(F.sum(F.col("ss_coupon_amt")).alias("amt"),
              F.sum(F.col("ss_net_profit")).alias("profit")))
    j = (g.join(cust, on=g["ss_customer_sk"] == cust["c_customer_sk"])
         .join(t["customer_address"],
               on=cust["c_current_addr_sk"]
               == t["customer_address"]["ca_address_sk"])
         .filter(F.col("ca_city") != F.col("bought_city")))
    return (j.select("c_last_name", "c_first_name", "ca_city",
                     "bought_city", "ss_ticket_number", "amt", "profit")
            .sort("c_last_name", "c_first_name", "ss_ticket_number")
            .limit(100))


def q49(s, t):
    """Worst return ratios per channel (TPC-DS 49: dual rank windows per
    channel, union)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    dt = t["date_dim"]
    period = dt.filter((F.col("d_year") == 2000) & (F.col("d_moy") == 12))

    def chan(fact, ret, date_col, item_col, order_col, qty_col, price_col,
             r_item, r_order, r_qty, r_amt, name):
        f, r = t[fact], t[ret]
        rsel = r.select(F.col(r_item).alias("r_item"),
                        F.col(r_order).alias("r_order"),
                        F.col(r_qty).alias("r_qty"),
                        F.col(r_amt).alias("r_amt"))
        j = (f.join(period, on=f[date_col] == period["d_date_sk"])
             .filter((F.col(qty_col) > 0) & (F.col(price_col) > 0))
             .join(rsel, on=(f[item_col] == rsel["r_item"])
                   & (f[order_col] == rsel["r_order"]), how="left"))
        g = (j.groupBy(item_col)
             .agg(F.sum(F.coalesce(F.col("r_qty"), F.lit(0)))
                  .alias("ret_qty"),
                  F.sum(F.col(qty_col)).alias("sold_qty"),
                  F.sum(F.coalesce(F.col("r_amt"), F.lit(0.0)))
                  .alias("ret_amt"),
                  F.sum(F.col(price_col) * F.col(qty_col))
                  .alias("sold_amt")))
        g = (g.withColumn("return_ratio",
                          F.col("ret_qty") / F.col("sold_qty"))
             .withColumn("currency_ratio",
                         F.col("ret_amt") / F.col("sold_amt")))
        g = (g.withColumn("return_rank", F.rank().over(
                Window.orderBy(F.col("return_ratio").asc())))
             .withColumn("currency_rank", F.rank().over(
                 Window.orderBy(F.col("currency_ratio").asc()))))
        return (g.filter((F.col("return_rank") <= 10)
                         | (F.col("currency_rank") <= 10))
                .select(F.lit(name).alias("channel"),
                        F.col(item_col).cast("long").alias("item"),
                        F.col("return_ratio"), F.col("return_rank"),
                        F.col("currency_rank")))

    u = (chan("web_sales", "web_returns", "ws_sold_date_sk", "ws_item_sk",
              "ws_order_number", "ws_quantity", "ws_sales_price",
              "wr_item_sk", "wr_order_number", "wr_return_quantity",
              "wr_return_amt", "web")
         .union(chan("catalog_sales", "catalog_returns", "cs_sold_date_sk",
                     "cs_item_sk", "cs_order_number", "cs_quantity",
                     "cs_sales_price", "cr_item_sk", "cr_order_number",
                     "cr_return_quantity", "cr_return_amount", "catalog"))
         .union(chan("store_sales", "store_returns", "ss_sold_date_sk",
                     "ss_item_sk", "ss_ticket_number", "ss_quantity",
                     "ss_sales_price", "sr_item_sk", "sr_ticket_number",
                     "sr_return_quantity", "sr_return_amt", "store")))
    return (u.sort("channel", "return_rank", "item")
            .limit(100))


def q54(s, t):
    """Revenue segments of a month's cross-channel Electronics cohort
    (TPC-DS 54)."""
    F = _F()
    dt, item, cust, ss = (t["date_dim"], t["item"], t["customer"],
                          t["store_sales"])
    month = dt.filter((F.col("d_year") == 2000) & (F.col("d_moy") == 3))
    sel_i = item.filter(F.col("i_category") == "Electronics")
    cs, ws = t["catalog_sales"], t["web_sales"]
    sales = (cs.select(F.col("cs_sold_date_sk").alias("sold_date_sk"),
                       F.col("cs_bill_customer_sk").alias("cust_sk"),
                       F.col("cs_item_sk").alias("item_sk"))
             .union(ws.select(
                 F.col("ws_sold_date_sk").alias("sold_date_sk"),
                 F.col("ws_bill_customer_sk").alias("cust_sk"),
                 F.col("ws_item_sk").alias("item_sk"))))
    cohort = (sales.join(month, on=sales["sold_date_sk"]
                         == month["d_date_sk"])
              .join(sel_i, on=sales["item_sk"] == sel_i["i_item_sk"])
              .select("cust_sk").distinct())
    following = dt.filter((F.col("d_year") == 2000)
                          & F.col("d_moy").between(4, 6))
    rev = (ss.join(cohort, on=ss["ss_customer_sk"] == cohort["cust_sk"],
                   how="leftsemi")
           .join(following, on=ss["ss_sold_date_sk"]
                 == following["d_date_sk"])
           .groupBy("ss_customer_sk")
           .agg(F.sum(F.col("ss_ext_sales_price")).alias("revenue")))
    seg = rev.withColumn("segment",
                         F.floor(F.col("revenue") / 50).cast("int"))
    return (seg.groupBy("segment")
            .agg(F.count_star().alias("num_customers"))
            .withColumn("segment_base", F.col("segment") * 50)
            .sort("segment", "num_customers")
            .limit(100))


def q56(s, t):
    """Colored-item revenue across all three channels (TPC-DS 56)."""
    F = _F()
    dt, item = t["date_dim"], t["item"]
    m = dt.filter((F.col("d_year") == 2000) & (F.col("d_moy") == 2))
    sel_i = item.filter(F.col("i_color").isin("almond", "azure", "blue",
                                              "brown", "beige"))

    def chan(fact, date_col, item_col, price_col):
        f = t[fact]
        return (f.join(m, on=f[date_col] == m["d_date_sk"])
                .join(sel_i, on=f[item_col] == sel_i["i_item_sk"])
                .groupBy("i_item_id")
                .agg(F.sum(F.col(price_col)).alias("total_sales")))

    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price")))
    return (u.groupBy("i_item_id")
            .agg(F.sum(F.col("total_sales")).alias("total_sales"))
            .sort(F.col("total_sales").desc(), "i_item_id")
            .limit(100))


def q58(s, t):
    """Items with balanced revenue across the three channels (TPC-DS 58:
    each channel within 90-110% of the three-channel average)."""
    F = _F()
    dt, item = t["date_dim"], t["item"]
    period = dt.filter((F.col("d_year") == 2000) & (F.col("d_moy") == 6))

    def chan(fact, date_col, item_col, price_col, name):
        f = t[fact]
        return (f.join(period, on=f[date_col] == period["d_date_sk"])
                .join(item, on=f[item_col] == item["i_item_sk"])
                .groupBy("i_item_id")
                .agg(F.sum(F.col(price_col)).alias(name))
                .select(F.col("i_item_id").alias(f"{name}_id"),
                        F.col(name)))

    ss = chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price", "ss_rev")
    cs = chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
              "cs_ext_sales_price", "cs_rev")
    ws = chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
              "ws_ext_sales_price", "ws_rev")
    j = (ss.join(cs, on=ss["ss_rev_id"] == cs["cs_rev_id"])
         .join(ws, on=ss["ss_rev_id"] == ws["ws_rev_id"]))
    # ±50% band (the standard's ±10% selects ~nothing from the high-variance
    # toy-scale channel sums; the three-way balance shape is what matters)
    avg3 = (F.col("ss_rev") + F.col("cs_rev") + F.col("ws_rev")) / 3
    ok = ((F.col("ss_rev").between(0.5 * avg3, 1.5 * avg3))
          & (F.col("cs_rev").between(0.5 * avg3, 1.5 * avg3))
          & (F.col("ws_rev").between(0.5 * avg3, 1.5 * avg3)))
    return (j.filter(ok)
            .select(F.col("ss_rev_id").alias("item_id"), "ss_rev",
                    "cs_rev", "ws_rev")
            .sort("item_id")
            .limit(100))


def q59(s, t):
    """Store weekly sales year-over-year by day of week (TPC-DS 59)."""
    F = _F()
    ss, dt, store = t["store_sales"], t["date_dim"], t["store"]
    days = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
            "Friday", "Saturday"]
    j = ss.join(dt, on=ss["ss_sold_date_sk"] == dt["d_date_sk"])
    aggs = [F.sum(F.when(F.col("d_day_name") == day,
                         F.col("ss_sales_price"))
                  .otherwise(F.lit(None)))
            .alias(f"{day[:3].lower()}_sales") for day in days]
    wss = j.groupBy("d_week_seq", "ss_store_sk").agg(*aggs)
    wk1 = dt.filter(F.col("d_month_seq").between(336, 347)) \
        .select("d_week_seq").distinct()
    wk2 = dt.filter(F.col("d_month_seq").between(348, 359)) \
        .select("d_week_seq").distinct()
    y = (wss.join(wk1, on=wss["d_week_seq"] == wk1["d_week_seq"],
                  how="leftsemi")
         .join(store, on=wss["ss_store_sk"] == store["s_store_sk"])
         .select(F.col("s_store_id").alias("s_id1"),
                 F.col("d_week_seq").alias("wk1"),
                 F.col("s_store_name"),
                 *[F.col(f"{d[:3].lower()}_sales") for d in days]))
    z = (wss.join(wk2, on=wss["d_week_seq"] == wk2["d_week_seq"],
                  how="leftsemi")
         .join(store, on=wss["ss_store_sk"] == store["s_store_sk"])
         .select(F.col("s_store_id").alias("s_id2"),
                 (F.col("d_week_seq") - 52).alias("wk2"),
                 *[F.col(f"{d[:3].lower()}_sales")
                   .alias(f"{d[:3].lower()}_sales2") for d in days]))
    jj = y.join(z, on=(y["s_id1"] == z["s_id2"]) & (y["wk1"] == z["wk2"]))
    ratios = [(F.col(f"{d[:3].lower()}_sales")
               / F.col(f"{d[:3].lower()}_sales2"))
              .alias(f"r_{d[:3].lower()}") for d in days]
    return (jj.select("s_store_name", F.col("s_id1"), F.col("wk1"),
                      *ratios)
            .sort("s_store_name", "s_id1", "wk1")
            .limit(100))


def q60(s, t):
    """Music-category revenue across all three channels (TPC-DS 60)."""
    F = _F()
    dt, item = t["date_dim"], t["item"]
    m = dt.filter((F.col("d_year") == 1999) & (F.col("d_moy") == 9))
    sel_i = item.filter(F.col("i_category") == "Music")

    def chan(fact, date_col, item_col, price_col):
        f = t[fact]
        return (f.join(m, on=f[date_col] == m["d_date_sk"])
                .join(sel_i, on=f[item_col] == sel_i["i_item_sk"])
                .groupBy("i_item_id")
                .agg(F.sum(F.col(price_col)).alias("total_sales")))

    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price")))
    return (u.groupBy("i_item_id")
            .agg(F.sum(F.col("total_sales")).alias("total_sales"))
            .sort("i_item_id", F.col("total_sales").desc())
            .limit(100))


def q64_simplified(s, t):
    """Returned-item sale stats joined across two years (TPC-DS 64
    shape: the cross_sales self-join on item)."""
    F = _F()
    ss, sr, dt, item = (t["store_sales"], t["store_returns"],
                        t["date_dim"], t["item"])
    sel_i = item.filter(F.col("i_color").isin("almond", "azure", "blue",
                                              "brown", "beige", "cyan"))

    def cross_sales(year, name):
        y = dt.filter(F.col("d_year") == year)
        j = (ss.join(sr, on=(ss["ss_ticket_number"]
                             == sr["sr_ticket_number"])
                     & (ss["ss_item_sk"] == sr["sr_item_sk"]))
             .join(y, on=ss["ss_sold_date_sk"] == y["d_date_sk"])
             .join(sel_i, on=ss["ss_item_sk"] == sel_i["i_item_sk"]))
        return (j.groupBy("i_item_id")
                .agg(F.count_star().alias(f"{name}_cnt"),
                     F.sum(F.col("ss_wholesale_cost")).alias(f"{name}_wc"),
                     F.sum(F.col("ss_list_price")).alias(f"{name}_lp"))
                .select(F.col("i_item_id").alias(f"{name}_id"),
                        F.col(f"{name}_cnt"), F.col(f"{name}_wc"),
                        F.col(f"{name}_lp")))

    cs1 = cross_sales(2000, "y1")
    cs2 = cross_sales(2001, "y2")
    return (cs1.join(cs2, on=cs1["y1_id"] == cs2["y2_id"])
            .filter(F.col("y2_cnt") <= F.col("y1_cnt"))
            .select(F.col("y1_id").alias("item_id"), "y1_cnt", "y1_wc",
                    "y1_lp", "y2_cnt", "y2_wc", "y2_lp")
            .sort("item_id")
            .limit(100))


def q66(s, t):
    """Warehouse monthly revenue by channel (TPC-DS 66: 12 pivoted month
    columns over a web+catalog union)."""
    F = _F()
    dt, wh, sm = t["date_dim"], t["warehouse"], t["ship_mode"]
    y = dt.filter(F.col("d_year") == 2000)
    sel_sm = sm.filter(F.col("sm_carrier").isin("UPS", "FEDEX"))
    ws, cs = t["web_sales"], t["catalog_sales"]
    web = (ws.join(y, on=ws["ws_sold_date_sk"] == y["d_date_sk"])
           .join(sel_sm, on=ws["ws_ship_mode_sk"]
                 == sel_sm["sm_ship_mode_sk"])
           .join(wh, on=ws["ws_warehouse_sk"] == wh["w_warehouse_sk"])
           .select(F.col("w_warehouse_name"), F.col("d_moy"),
                   (F.col("ws_ext_sales_price") * F.col("ws_quantity"))
                   .alias("amt")))
    cat = (cs.join(y, on=cs["cs_sold_date_sk"] == y["d_date_sk"])
           .join(sel_sm, on=cs["cs_ship_mode_sk"]
                 == sel_sm["sm_ship_mode_sk"])
           .join(wh, on=cs["cs_warehouse_sk"] == wh["w_warehouse_sk"])
           .select(F.col("w_warehouse_name"), F.col("d_moy"),
                   (F.col("cs_ext_sales_price") * F.col("cs_quantity"))
                   .alias("amt")))
    u = web.union(cat)
    months = ["jan", "feb", "mar", "apr", "may", "jun", "jul", "aug",
              "sep", "oct", "nov", "dec"]
    aggs = [F.sum(F.when(F.col("d_moy") == i + 1, F.col("amt"))
                  .otherwise(0.0)).alias(f"{m}_sales")
            for i, m in enumerate(months)]
    return (u.groupBy("w_warehouse_name").agg(*aggs)
            .sort("w_warehouse_name")
            .limit(100))


def q69(s, t):
    """Demographics of store-only customers (TPC-DS 69: EXISTS +
    NOT EXISTS lowered to semi/anti joins)."""
    F = _F()
    cust, ca, cd, dt = (t["customer"], t["customer_address"],
                        t["customer_demographics"], t["date_dim"])
    period = dt.filter((F.col("d_year") == 2000)
                       & F.col("d_moy").between(1, 3))
    ss_cust = (t["store_sales"]
               .join(period, on=t["store_sales"]["ss_sold_date_sk"]
                     == period["d_date_sk"])
               .select(F.col("ss_customer_sk").alias("a_cust")).distinct())
    ws_cust = (t["web_sales"]
               .join(period, on=t["web_sales"]["ws_sold_date_sk"]
                     == period["d_date_sk"])
               .select(F.col("ws_bill_customer_sk").alias("a_cust")))
    cs_cust = (t["catalog_sales"]
               .join(period, on=t["catalog_sales"]["cs_sold_date_sk"]
                     == period["d_date_sk"])
               .select(F.col("cs_bill_customer_sk").alias("a_cust")))
    sel_ca = ca.filter(F.col("ca_state").isin("TN", "CA", "TX"))
    j = (cust.join(ss_cust, on=cust["c_customer_sk"] == ss_cust["a_cust"],
                   how="leftsemi")
         .join(ws_cust, on=cust["c_customer_sk"] == ws_cust["a_cust"],
               how="leftanti")
         .join(cs_cust, on=cust["c_customer_sk"] == cs_cust["a_cust"],
               how="leftanti")
         .join(sel_ca, on=cust["c_current_addr_sk"]
               == sel_ca["ca_address_sk"])
         .join(cd, on=cust["c_current_cdemo_sk"] == cd["cd_demo_sk"]))
    return (j.groupBy("cd_gender", "cd_marital_status",
                      "cd_education_status")
            .agg(F.count_star().alias("cnt"),
                 F.min(F.col("cd_purchase_estimate")).alias("min_est"),
                 F.max(F.col("cd_purchase_estimate")).alias("max_est"))
            .sort("cd_gender", "cd_marital_status", "cd_education_status")
            .limit(100))


def q70(s, t):
    """State/county profit ROLLUP restricted to top-5 states with ranking
    inside each hierarchy level (TPC-DS 70)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    from spark_rapids_tpu.expressions.generators import GroupingExpr  # noqa: F401
    ss, dt, store = t["store_sales"], t["date_dim"], t["store"]
    period = dt.filter(F.col("d_month_seq").between(350, 361))
    by_state = (ss.join(period, on=ss["ss_sold_date_sk"]
                        == period["d_date_sk"])
                .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
                .groupBy("s_state")
                .agg(F.sum(F.col("ss_net_profit")).alias("state_profit")))
    top5 = (by_state.withColumn(
        "rnk", F.rank().over(Window.orderBy(
            F.col("state_profit").desc())))
        .filter(F.col("rnk") <= 5)
        .select(F.col("s_state").alias("top_state")))
    g = (ss.join(period, on=ss["ss_sold_date_sk"] == period["d_date_sk"])
         .join(store, on=ss["ss_store_sk"] == store["s_store_sk"])
         .join(top5, on=store["s_state"] == top5["top_state"],
               how="leftsemi")
         .rollup("s_state", "s_county")
         .agg(F.sum(F.col("ss_net_profit")).alias("total_sum"),
              F.grouping("s_state").alias("g_state"),
              F.grouping("s_county").alias("g_county")))
    g = g.withColumn("lochierarchy", F.col("g_state") + F.col("g_county"))
    w = Window.partitionBy("lochierarchy").orderBy(
        F.col("total_sum").desc())
    return (g.withColumn("rank_within_parent", F.rank().over(w))
            .select("total_sum", "s_state", "s_county", "lochierarchy",
                    "rank_within_parent")
            .sort(F.col("lochierarchy").desc(), "s_state",
                  "rank_within_parent")
            .limit(100))


def q71(s, t):
    """Brand revenue in breakfast and dinner hours across channels
    (TPC-DS 71)."""
    F = _F()
    dt, item, td = t["date_dim"], t["item"], t["time_dim"]
    m = dt.filter((F.col("d_moy") == 11) & (F.col("d_year") == 2000))
    sel_i = item.filter(F.col("i_manager_id") <= 10)
    meal = td.filter(F.col("t_hour").isin(8, 9, 19, 20))
    ws, ss = t["web_sales"], t["store_sales"]
    web = (ws.join(m, on=ws["ws_sold_date_sk"] == m["d_date_sk"])
           .select(F.col("ws_ext_sales_price").alias("price"),
                   F.col("ws_item_sk").cast("long").alias("item_sk"),
                   F.col("ws_sold_time_sk").alias("time_sk")))
    st = (ss.join(m, on=ss["ss_sold_date_sk"] == m["d_date_sk"])
          .select(F.col("ss_ext_sales_price").alias("price"),
                  F.col("ss_item_sk").cast("long").alias("item_sk"),
                  F.col("ss_sold_time_sk").alias("time_sk")))
    u = web.union(st)
    j = (u.join(sel_i, on=u["item_sk"] == sel_i["i_item_sk"])
         .join(meal, on=u["time_sk"] == meal["t_time_sk"]))
    return (j.groupBy("i_brand_id", "i_brand", "t_hour")
            .agg(F.sum(F.col("price")).alias("ext_price"))
            .sort(F.col("ext_price").desc(), "i_brand_id", "t_hour")
            .limit(100))


def q72(s, t):
    """Catalog demand exceeding inventory on hand (TPC-DS 72: non-equi
    residual join against inventory)."""
    F = _F()
    cs, inv, dt, item, wh, hd = (t["catalog_sales"], t["inventory"],
                                 t["date_dim"], t["item"], t["warehouse"],
                                 t["household_demographics"])
    y = dt.filter(F.col("d_year") == 2000)
    sel_hd = hd.filter(F.col("hd_buy_potential") == ">10000")
    j = (cs.join(y, on=cs["cs_sold_date_sk"] == y["d_date_sk"])
         .join(sel_hd, on=cs["cs_bill_hdemo_sk"] == sel_hd["hd_demo_sk"])
         .join(inv, on=(cs["cs_item_sk"] == inv["inv_item_sk"])
               & (inv["inv_quantity_on_hand"] < cs["cs_quantity"]))
         .join(item, on=cs["cs_item_sk"] == item["i_item_sk"])
         .join(wh, on=inv["inv_warehouse_sk"] == wh["w_warehouse_sk"]))
    return (j.groupBy("i_item_id", "w_warehouse_name", "d_week_seq")
            .agg(F.count_star().alias("no_promo"))
            .sort(F.col("no_promo").desc(), "i_item_id",
                  "w_warehouse_name", "d_week_seq")
            .limit(100))


def q74(s, t):
    """Customers whose web net-paid growth beats store growth
    (TPC-DS 74: q11's skeleton on ss_net_paid)."""
    F = _F()
    cust = t["customer"]

    def yt(fact, date_col, cust_col, amt_col, year, name):
        return _year_total(t, fact, date_col, cust_col, F.col(amt_col),
                           year) \
            .select(F.col(cust_col).alias(f"{name}_cust"),
                    F.col("year_total").alias(name))

    ss1 = yt("store_sales", "ss_sold_date_sk", "ss_customer_sk",
             "ss_net_paid", 1999, "ss1")
    ss2 = yt("store_sales", "ss_sold_date_sk", "ss_customer_sk",
             "ss_net_paid", 2000, "ss2")
    ws1 = yt("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
             "ws_net_paid", 1999, "ws1")
    ws2 = yt("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk",
             "ws_net_paid", 2000, "ws2")
    j = (ss1.join(ss2, on=ss1["ss1_cust"] == ss2["ss2_cust"])
         .join(ws1, on=ss1["ss1_cust"] == ws1["ws1_cust"])
         .join(ws2, on=ss1["ss1_cust"] == ws2["ws2_cust"])
         .filter(F.col("ws2") / F.col("ws1")
                 > F.col("ss2") / F.col("ss1")))
    return (j.join(cust, on=j["ss1_cust"] == cust["c_customer_sk"])
            .select("c_customer_id", "c_first_name", "c_last_name")
            .sort("c_customer_id")
            .limit(100))


def q75(s, t):
    """Brands losing volume year over year (TPC-DS 75: sales net of
    returns unioned across channels, self-joined on prior year)."""
    F = _F()
    dt, item = t["date_dim"], t["item"]
    sel_i = item.filter(F.col("i_category") == "Books")

    def chan(fact, ret, date_col, item_col, order_col, qty_col, price_col,
             r_item, r_order, r_qty, r_amt):
        f, r = t[fact], t[ret]
        rsel = r.select(F.col(r_item).alias("r_item"),
                        F.col(r_order).alias("r_order"),
                        F.col(r_qty).alias("r_qty"),
                        F.col(r_amt).alias("r_amt"))
        j = (f.join(dt, on=f[date_col] == dt["d_date_sk"])
             .join(sel_i, on=f[item_col] == sel_i["i_item_sk"])
             .join(rsel, on=(f[item_col] == rsel["r_item"])
                   & (f[order_col] == rsel["r_order"]), how="left"))
        return j.select(
            F.col("d_year"), F.col("i_brand"),
            (F.col(qty_col) - F.coalesce(F.col("r_qty"), F.lit(0)))
            .alias("sales_cnt"),
            (F.col(price_col) - F.coalesce(F.col("r_amt"), F.lit(0.0)))
            .alias("sales_amt"))

    u = (chan("store_sales", "store_returns", "ss_sold_date_sk",
              "ss_item_sk", "ss_ticket_number", "ss_quantity",
              "ss_ext_sales_price", "sr_item_sk", "sr_ticket_number",
              "sr_return_quantity", "sr_return_amt")
         .union(chan("catalog_sales", "catalog_returns", "cs_sold_date_sk",
                     "cs_item_sk", "cs_order_number", "cs_quantity",
                     "cs_ext_sales_price", "cr_item_sk", "cr_order_number",
                     "cr_return_quantity", "cr_return_amount"))
         .union(chan("web_sales", "web_returns", "ws_sold_date_sk",
                     "ws_item_sk", "ws_order_number", "ws_quantity",
                     "ws_ext_sales_price", "wr_item_sk", "wr_order_number",
                     "wr_return_quantity", "wr_return_amt")))
    g = (u.groupBy("d_year", "i_brand")
         .agg(F.sum(F.col("sales_cnt")).alias("sales_cnt"),
              F.sum(F.col("sales_amt")).alias("sales_amt")))
    curr = g.filter(F.col("d_year") == 2000).select(
        F.col("i_brand").alias("c_brand"),
        F.col("sales_cnt").alias("c_cnt"),
        F.col("sales_amt").alias("c_amt"))
    prev = g.filter(F.col("d_year") == 1999).select(
        F.col("i_brand").alias("p_brand"),
        F.col("sales_cnt").alias("p_cnt"),
        F.col("sales_amt").alias("p_amt"))
    return (curr.join(prev, on=curr["c_brand"] == prev["p_brand"])
            .filter((F.col("p_cnt") > 0)
                    & (F.col("c_cnt").cast("double") / F.col("p_cnt")
                       < 0.9))
            .select(F.col("c_brand").alias("i_brand"), "p_cnt", "c_cnt",
                    (F.col("c_cnt") - F.col("p_cnt")).alias("cnt_diff"),
                    (F.col("c_amt") - F.col("p_amt")).alias("amt_diff"))
            .sort("cnt_diff", "i_brand")
            .limit(100))


def q76(s, t):
    """Sales rows with a NULL measure per channel (TPC-DS 76)."""
    F = _F()
    dt, item = t["date_dim"], t["item"]

    def chan(fact, date_col, item_col, null_col, price_col, name):
        f = t[fact]
        return (f.filter(F.isnull(F.col(null_col)))
                .join(dt, on=f[date_col] == dt["d_date_sk"])
                .join(item, on=f[item_col] == item["i_item_sk"])
                .select(F.lit(name).alias("channel"),
                        F.lit(null_col).alias("col_name"),
                        F.col("d_year"), F.col("d_qoy"),
                        F.col("i_category"),
                        F.col(price_col).alias("ext_sales_price")))

    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_quantity", "ss_ext_sales_price", "store")
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_quantity", "ws_ext_sales_price", "web"))
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_quantity", "cs_ext_sales_price", "catalog")))
    return (u.groupBy("channel", "col_name", "d_year", "d_qoy",
                      "i_category")
            .agg(F.count_star().alias("sales_cnt"),
                 F.sum(F.col("ext_sales_price")).alias("sales_amt"))
            .sort("channel", "col_name", "d_year", "d_qoy", "i_category")
            .limit(100))


def q78(s, t):
    """Non-returned sales per customer/item/year across channels
    (TPC-DS 78: LEFT JOIN returns, keep the never-returned rows)."""
    F = _F()
    dt = t["date_dim"]

    def chan(fact, ret, date_col, item_col, order_col, cust_col, qty_col,
             price_col, r_item, r_order, name):
        f, r = t[fact], t[ret]
        rsel = r.select(F.col(r_item).alias("r_item"),
                        F.col(r_order).alias("r_order"))
        j = (f.join(rsel, on=(f[item_col] == rsel["r_item"])
                    & (f[order_col] == rsel["r_order"]), how="leftanti")
             .join(dt, on=f[date_col] == dt["d_date_sk"]))
        return (j.groupBy("d_year", item_col, cust_col)
                .agg(F.sum(F.col(qty_col)).alias(f"{name}_qty"),
                     F.sum(F.col(price_col)).alias(f"{name}_amt"))
                .select(F.col("d_year").alias(f"{name}_year"),
                        F.col(item_col).alias(f"{name}_item"),
                        F.col(cust_col).alias(f"{name}_cust"),
                        F.col(f"{name}_qty"), F.col(f"{name}_amt")))

    ss = chan("store_sales", "store_returns", "ss_sold_date_sk",
              "ss_item_sk", "ss_ticket_number", "ss_customer_sk",
              "ss_quantity", "ss_ext_sales_price", "sr_item_sk",
              "sr_ticket_number", "ss")
    ws = chan("web_sales", "web_returns", "ws_sold_date_sk", "ws_item_sk",
              "ws_order_number", "ws_bill_customer_sk", "ws_quantity",
              "ws_ext_sales_price", "wr_item_sk", "wr_order_number", "ws")
    j = ss.join(ws, on=(ss["ss_year"] == ws["ws_year"])
                & (ss["ss_item"] == ws["ws_item"])
                & (ss["ss_cust"] == ws["ws_cust"]))
    return (j.filter(F.col("ws_qty") > 0)
            .select(F.col("ss_year").alias("year"),
                    F.col("ss_item").alias("item"),
                    F.col("ss_cust").alias("customer"),
                    F.round(F.col("ss_qty").cast("double")
                            / F.col("ws_qty"), 2).alias("ratio"),
                    "ss_qty", "ss_amt", "ws_qty", "ws_amt")
            .sort("year", "item", "customer")
            .limit(100))


def q83(s, t):
    """Return quantities per item across the three return channels
    (TPC-DS 83)."""
    F = _F()
    dt, item = t["date_dim"], t["item"]
    period = dt.filter(F.col("d_month_seq").between(350, 353))

    def chan(ret, date_col, item_col, qty_col, name):
        r = t[ret]
        return (r.join(period, on=r[date_col] == period["d_date_sk"])
                .join(item, on=r[item_col] == item["i_item_sk"])
                .groupBy("i_item_id")
                .agg(F.sum(F.col(qty_col)).alias(name))
                .select(F.col("i_item_id").alias(f"{name}_id"),
                        F.col(name)))

    sr = chan("store_returns", "sr_returned_date_sk", "sr_item_sk",
              "sr_return_quantity", "sr_qty")
    cr = chan("catalog_returns", "cr_returned_date_sk", "cr_item_sk",
              "cr_return_quantity", "cr_qty")
    wr = chan("web_returns", "wr_returned_date_sk", "wr_item_sk",
              "wr_return_quantity", "wr_qty")
    j = (sr.join(cr, on=sr["sr_qty_id"] == cr["cr_qty_id"])
         .join(wr, on=sr["sr_qty_id"] == wr["wr_qty_id"]))
    total = (F.col("sr_qty") + F.col("cr_qty") + F.col("wr_qty"))
    return (j.select(F.col("sr_qty_id").alias("item_id"), "sr_qty",
                     "cr_qty", "wr_qty",
                     F.round(F.col("sr_qty") / total * 100.0, 2)
                     .alias("sr_dev"),
                     F.round(F.col("cr_qty") / total * 100.0, 2)
                     .alias("cr_dev"),
                     F.round(F.col("wr_qty") / total * 100.0, 2)
                     .alias("wr_dev"))
            .sort("item_id")
            .limit(100))


def q84(s, t):
    """Returning customers in an income band and city (TPC-DS 84)."""
    F = _F()
    cust, ca, hd, ib, sr = (t["customer"], t["customer_address"],
                            t["household_demographics"], t["income_band"],
                            t["store_returns"])
    sel_ca = ca.filter(F.col("ca_city").isin("city0", "city1", "city2",
                                             "city3", "city4"))
    sel_ib = ib.filter((F.col("ib_lower_bound") >= 0)
                       & (F.col("ib_upper_bound") <= 100000 - 1))
    returned = sr.select(F.col("sr_customer_sk").alias("r_cust")).distinct()
    j = (cust.join(sel_ca, on=cust["c_current_addr_sk"]
                   == sel_ca["ca_address_sk"])
         .join(hd, on=cust["c_current_hdemo_sk"] == hd["hd_demo_sk"])
         .join(sel_ib, on=hd["hd_income_band_sk"]
               == sel_ib["ib_income_band_sk"])
         .join(returned, on=cust["c_customer_sk"] == returned["r_cust"],
               how="leftsemi"))
    return (j.select(F.col("c_customer_id").alias("customer_id"),
                     F.concat(F.col("c_last_name"), F.lit(", "),
                              F.col("c_first_name"))
                     .alias("customername"),
                     "ca_city")
            .sort("customer_id")
            .limit(100))


def q85(s, t):
    """Web return reasons with demographic brackets (TPC-DS 85)."""
    F = _F()
    wr, reason, cust, cd, dt = (t["web_returns"], t["reason"],
                                t["customer"],
                                t["customer_demographics"], t["date_dim"])
    y = dt.filter(F.col("d_year") == 2000)
    b1 = ((F.col("cd_marital_status") == "M")
          & (F.col("cd_education_status") == "4 yr Degree"))
    b2 = ((F.col("cd_marital_status") == "S")
          & (F.col("cd_education_status") == "College"))
    b3 = ((F.col("cd_marital_status") == "W")
          & (F.col("cd_education_status") == "2 yr Degree"))
    j = (wr.join(y, on=wr["wr_returned_date_sk"] == y["d_date_sk"])
         .join(reason, on=wr["wr_reason_sk"] == reason["r_reason_sk"])
         .join(cust, on=wr["wr_returning_customer_sk"]
               == cust["c_customer_sk"])
         .join(cd, on=cust["c_current_cdemo_sk"] == cd["cd_demo_sk"])
         .filter(b1 | b2 | b3))
    return (j.groupBy("r_reason_desc")
            .agg(F.avg(F.col("wr_return_quantity")).alias("avg_qty"),
                 F.avg(F.col("wr_return_amt")).alias("avg_amt"),
                 F.avg(F.col("wr_net_loss")).alias("avg_loss"))
            .sort("r_reason_desc")
            .limit(100))


def q86(s, t):
    """Web net-paid rollup with rank inside hierarchy level (TPC-DS 86:
    q36's shape on the web channel)."""
    F = _F()
    from spark_rapids_tpu.window import Window
    ws, dt, item = t["web_sales"], t["date_dim"], t["item"]
    period = dt.filter(F.col("d_month_seq").between(350, 361))
    g = (ws.join(period, on=ws["ws_sold_date_sk"] == period["d_date_sk"])
         .join(item, on=ws["ws_item_sk"] == item["i_item_sk"])
         .rollup("i_category", "i_class")
         .agg(F.sum(F.col("ws_net_paid")).alias("total_sum"),
              F.grouping("i_category").alias("g_cat"),
              F.grouping("i_class").alias("g_class")))
    g = g.withColumn("lochierarchy", F.col("g_cat") + F.col("g_class"))
    w = Window.partitionBy("lochierarchy").orderBy(
        F.col("total_sum").desc())
    return (g.withColumn("rank_within_parent", F.rank().over(w))
            .select("total_sum", "i_category", "i_class", "lochierarchy",
                    "rank_within_parent")
            .sort(F.col("lochierarchy").desc(), "i_category",
                  "rank_within_parent")
            .limit(100))


def q91(s, t):
    """Call-center catalog return losses by demographic (TPC-DS 91)."""
    F = _F()
    cr, cc, dt, cust, cd, hd = (t["catalog_returns"], t["call_center"],
                                t["date_dim"], t["customer"],
                                t["customer_demographics"],
                                t["household_demographics"])
    m = dt.filter(F.col("d_year") == 1998)
    sel_cd = cd.filter(F.col("cd_marital_status").isin("M", "W"))
    sel_hd = hd.filter(F.col("hd_buy_potential").isin(
        ">10000", "5001-10000", "Unknown"))
    j = (cr.join(m, on=cr["cr_returned_date_sk"] == m["d_date_sk"])
         .join(cc, on=cr["cr_call_center_sk"] == cc["cc_call_center_sk"])
         .join(cust, on=cr["cr_returning_customer_sk"]
               == cust["c_customer_sk"])
         .join(sel_cd, on=cust["c_current_cdemo_sk"]
               == sel_cd["cd_demo_sk"])
         .join(sel_hd, on=cust["c_current_hdemo_sk"]
               == sel_hd["hd_demo_sk"]))
    return (j.groupBy("cc_name", "cc_manager", "cd_marital_status",
                      "cd_education_status")
            .agg(F.sum(F.col("cr_net_loss")).alias("returns_loss"))
            .sort(F.col("returns_loss").desc(), "cc_name", "cc_manager")
            .limit(100))


def q93(s, t):
    """Actual sales after reason-coded returns (TPC-DS 93)."""
    F = _F()
    ss, sr, reason = t["store_sales"], t["store_returns"], t["reason"]
    sel_r = reason.filter(F.col("r_reason_desc").isin(
        "reason 01", "reason 02", "reason 03"))
    rsel = (sr.join(sel_r, on=sr["sr_reason_sk"] == sel_r["r_reason_sk"],
                    how="leftsemi")
            .select(F.col("sr_ticket_number").alias("r_ticket"),
                    F.col("sr_item_sk").alias("r_item"),
                    F.col("sr_return_quantity").alias("r_qty")))
    j = ss.join(rsel, on=(ss["ss_ticket_number"] == rsel["r_ticket"])
                & (ss["ss_item_sk"] == rsel["r_item"]), how="left")
    act = F.when(F.isnull(F.col("r_qty")),
                 F.col("ss_quantity") * F.col("ss_sales_price")) \
        .otherwise((F.col("ss_quantity") - F.col("r_qty"))
                   * F.col("ss_sales_price"))
    return (j.withColumn("act_sales", act)
            .groupBy("ss_customer_sk")
            .agg(F.sum(F.col("act_sales")).alias("sumsales"))
            .sort("sumsales", "ss_customer_sk")
            .limit(100))


def q94(s, t):
    """Multi-warehouse web orders never returned (TPC-DS 94)."""
    F = _F()
    ws, wr, dt, site = (t["web_sales"], t["web_returns"], t["date_dim"],
                        t["web_site"])
    days = dt.filter((F.col("d_date") >= F.lit(10585))
                     & (F.col("d_date") <= F.lit(10645)))
    multi_wh = (t["web_sales"]
                .select("ws_order_number", "ws_warehouse_sk").distinct()
                .groupBy("ws_order_number")
                .agg(F.count_star().alias("n_wh"))
                .filter(F.col("n_wh") > 1)
                .select(F.col("ws_order_number").alias("mw_order")))
    base = (ws.join(days, on=ws["ws_ship_date_sk"] == days["d_date_sk"])
            .join(site, on=ws["ws_web_site_sk"] == site["web_site_sk"])
            .join(multi_wh, on=ws["ws_order_number"] == multi_wh["mw_order"],
                  how="leftsemi")
            .join(wr.select(F.col("wr_order_number").alias("r_order")),
                  on=ws["ws_order_number"] == F.col("r_order"),
                  how="leftanti"))
    orders = (base.select("ws_order_number").distinct()
              .agg(F.count_star().alias("order_count")))
    money = base.agg(F.sum(F.col("ws_ext_tax")).alias("total_tax"),
                     F.sum(F.col("ws_net_profit")).alias("total_profit"))
    return orders.crossJoin(money)


def q95(s, t):
    """Multi-warehouse web orders WITH returns (TPC-DS 95: q94's shape
    with EXISTS instead of NOT EXISTS)."""
    F = _F()
    ws, wr, dt, site = (t["web_sales"], t["web_returns"], t["date_dim"],
                        t["web_site"])
    days = dt.filter((F.col("d_date") >= F.lit(10585))
                     & (F.col("d_date") <= F.lit(10645)))
    multi_wh = (t["web_sales"]
                .select("ws_order_number", "ws_warehouse_sk").distinct()
                .groupBy("ws_order_number")
                .agg(F.count_star().alias("n_wh"))
                .filter(F.col("n_wh") > 1)
                .select(F.col("ws_order_number").alias("mw_order")))
    base = (ws.join(days, on=ws["ws_ship_date_sk"] == days["d_date_sk"])
            .join(site, on=ws["ws_web_site_sk"] == site["web_site_sk"])
            .join(multi_wh, on=ws["ws_order_number"] == multi_wh["mw_order"],
                  how="leftsemi")
            .join(wr.select(F.col("wr_order_number").alias("r_order")),
                  on=ws["ws_order_number"] == F.col("r_order"),
                  how="leftsemi"))
    orders = (base.select("ws_order_number").distinct()
              .agg(F.count_star().alias("order_count")))
    money = base.agg(F.sum(F.col("ws_ext_tax")).alias("total_tax"),
                     F.sum(F.col("ws_net_profit")).alias("total_profit"))
    return orders.crossJoin(money)


def q97(s, t):
    """Store/catalog customer-item overlap (TPC-DS 97: FULL OUTER join of
    the two distinct purchase sets)."""
    F = _F()
    dt = t["date_dim"]
    period = dt.filter(F.col("d_month_seq").between(350, 361))
    ss, cs = t["store_sales"], t["catalog_sales"]
    ssci = (ss.join(period, on=ss["ss_sold_date_sk"] == period["d_date_sk"])
            .select(F.col("ss_customer_sk").alias("s_cust"),
                    F.col("ss_item_sk").alias("s_item")).distinct())
    csci = (cs.join(period, on=cs["cs_sold_date_sk"] == period["d_date_sk"])
            .select(F.col("cs_bill_customer_sk").alias("c_cust"),
                    F.col("cs_item_sk").alias("c_item")).distinct())
    j = ssci.join(csci, on=(ssci["s_cust"] == csci["c_cust"])
                  & (ssci["s_item"] == csci["c_item"]), how="full")
    return j.agg(
        F.sum(F.when(F.isnull(F.col("c_cust"))
                     & ~F.isnull(F.col("s_cust")), 1).otherwise(0))
        .alias("store_only"),
        F.sum(F.when(~F.isnull(F.col("s_cust"))
                     & ~F.isnull(F.col("c_cust")), 1).otherwise(0))
        .alias("store_and_catalog"),
        F.sum(F.when(F.isnull(F.col("s_cust"))
                     & ~F.isnull(F.col("c_cust")), 1).otherwise(0))
        .alias("catalog_only"))


QUERIES = {
    "q1": q1, "q2": q2, "q3": q3, "q4": q4, "q5": q5_rollup, "q6": q6,
    "q7": q7, "q8": q8, "q9": q9, "q10": q10, "q11": q11, "q12": q12,
    "q13": q13, "q14": q14_simplified, "q15": q15, "q16": q16, "q17": q17,
    "q18": q18, "q19": q19, "q20": q20, "q21": q21, "q22": q22,
    "q23": q23_simplified, "q24": q24_simplified, "q25": q25, "q26": q26,
    "q27": q27, "q28": q28, "q29": q29, "q30": q30, "q31": q31, "q32": q32,
    "q33": q33_simplified, "q34": q34, "q35": q35, "q36": q36, "q37": q37,
    "q38": q38, "q39": q39, "q40": q40, "q41": q41, "q42": q42, "q43": q43,
    "q44": q44, "q45": q45, "q46": q46, "q47": q47, "q48": q48, "q49": q49,
    "q50": q50, "q51": q51, "q52": q52, "q53": q53, "q54": q54, "q55": q55,
    "q56": q56, "q57": q57, "q58": q58, "q59": q59, "q60": q60,
    "q64": q64_simplified, "q61": q61, "q62": q62, "q63": q63, "q65": q65,
    "q66": q66, "q67": q67, "q68": q68, "q69": q69, "q70": q70, "q71": q71,
    "q72": q72, "q73": q73, "q74": q74, "q75": q75, "q76": q76, "q77": q77,
    "q78": q78, "q79": q79, "q80": q80, "q81": q81, "q82": q82, "q83": q83,
    "q84": q84, "q85": q85, "q86": q86, "q87": q87, "q88": q88_simplified,
    "q89": q89, "q90": q90, "q91": q91, "q92": q92, "q93": q93, "q94": q94,
    "q95": q95, "q96": q96, "q97": q97, "q98": q98, "q99": q99,
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--queries", default=",".join(QUERIES))
    args = ap.parse_args()
    s = make_session(tpu=True)
    tables = load_tables(s, args.rows)
    results = {}
    for name in args.queries.split(","):
        fn = QUERIES[name.strip()]
        df = fn(s, tables)
        t0 = time.perf_counter()
        out = df.to_arrow()
        results[f"{name}_s"] = round(time.perf_counter() - t0, 4)
        results[f"{name}_rows"] = out.num_rows
    print(json.dumps({"metric": "tpcds_suite", "rows": args.rows,
                      **results}))


if __name__ == "__main__":
    main()
