"""TPC-H-style benchmark queries running through the full framework
(reference: integration_tests mortgage Benchmarks.scala + ScaleTest harness).

Usage: python benchmarks/tpch.py [--rows N] [--queries q1,q3,q6] [--cpu]
Prints per-query wall-clock for the TPU plan and (optionally) the CPU plan.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def make_session(tpu: bool):
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.sql.enabled": str(tpu).lower(),
                       "spark.sql.shuffle.partitions": "8"})


def load_tables(s, rows: int, parts: int = 4):
    from spark_rapids_tpu.datagen import (tpch_customer, tpch_lineitem,
                                          tpch_orders)
    li = s.createDataFrame(tpch_lineitem(rows).generate(42, rows, parts),
                          num_partitions=parts)
    orders = s.createDataFrame(
        tpch_orders(rows // 4).generate(42, rows // 4, parts),
        num_partitions=parts)
    cust = s.createDataFrame(
        tpch_customer(rows // 40).generate(42, rows // 40, 1))
    return li, orders, cust


def q1(s, li, orders, cust):
    import spark_rapids_tpu.functions as F
    return (li.filter(F.col("l_shipdate") <= 10471)
            .withColumn("disc_price",
                        F.col("l_extendedprice") * (1 - F.col("l_discount")))
            .withColumn("charge",
                        F.col("l_extendedprice") * (1 - F.col("l_discount"))
                        * (1 + F.col("l_tax")))
            .groupBy("l_returnflag", "l_linestatus")
            .agg(F.sum(F.col("l_quantity")).alias("sum_qty"),
                 F.sum(F.col("l_extendedprice")).alias("sum_base_price"),
                 F.sum(F.col("disc_price")).alias("sum_disc_price"),
                 F.sum(F.col("charge")).alias("sum_charge"),
                 F.avg(F.col("l_quantity")).alias("avg_qty"),
                 F.avg(F.col("l_extendedprice")).alias("avg_price"),
                 F.avg(F.col("l_discount")).alias("avg_disc"),
                 F.count(F.col("l_quantity")).alias("count_order"))
            .sort("l_returnflag", "l_linestatus"))


def q3(s, li, orders, cust):
    import spark_rapids_tpu.functions as F
    return (cust.filter(F.col("c_mktsegment") == "A")
            .join(orders, on=cust["c_custkey"] == orders["o_custkey"])
            .join(li, on=orders["o_orderkey"] == li["l_orderkey"])
            .withColumn("revenue",
                        F.col("l_extendedprice") * (1 - F.col("l_discount")))
            .groupBy("o_orderkey", "o_orderdate")
            .agg(F.sum(F.col("revenue")).alias("revenue"))
            .sort(F.col("revenue").desc())
            .limit(10))


def q6(s, li, orders, cust):
    import spark_rapids_tpu.functions as F
    return (li.filter((F.col("l_shipdate") >= 8766)
                      & (F.col("l_shipdate") < 9131)
                      & (F.col("l_discount") >= 0.05)
                      & (F.col("l_discount") <= 0.07)
                      & (F.col("l_quantity") < 24))
            .agg(F.sum(F.col("l_extendedprice") * F.col("l_discount"))
                 .alias("revenue")))


QUERIES = {"q1": q1, "q3": q3, "q6": q6}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--queries", default="q1,q3,q6")
    ap.add_argument("--cpu", action="store_true",
                    help="also time the CPU (fallback) plan")
    args = ap.parse_args()

    results = {}
    for mode in (["tpu", "cpu"] if args.cpu else ["tpu"]):
        s = make_session(tpu=(mode == "tpu"))
        li, orders, cust = load_tables(s, args.rows)
        for name in args.queries.split(","):
            fn = QUERIES[name.strip()]
            df = fn(s, li, orders, cust)
            t0 = time.perf_counter()
            out = df.to_arrow()
            dt = time.perf_counter() - t0
            results[f"{name}_{mode}_s"] = round(dt, 4)
            results[f"{name}_rows"] = out.num_rows
    print(json.dumps({"metric": "tpch_suite", "rows": args.rows, **results}))


if __name__ == "__main__":
    main()
