"""TPC-H-style benchmark queries running through the full framework
(reference: integration_tests mortgage Benchmarks.scala + ScaleTest harness).

12 queries (q1 q3 q4 q5 q6 q9 q10 q12 q13 q14 q18 q19) over the full
simplified-TPC-H schema from spark_rapids_tpu.datagen; every query runs
end-to-end through session -> override engine -> exec chain, and each has a
CPU-oracle equality test in tests/test_tpch_queries.py.

Usage: python benchmarks/tpch.py [--rows N] [--queries q1,q3,...] [--cpu]
Prints per-query wall-clock for the TPU plan and (optionally) the CPU plan.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def make_session(tpu: bool):
    from spark_rapids_tpu.session import TpuSession
    # device-resident shuffle (reference UCX/CACHE_ONLY mode): blocks stay
    # in HBM as spillable batches — the file mode's Arrow round trip costs
    # thousands of ~100ms tunnel transfers per query
    return TpuSession({"spark.rapids.sql.enabled": str(tpu).lower(),
                       "spark.rapids.shuffle.mode":
                           "ICI" if tpu else "MULTITHREADED",
                       "spark.sql.shuffle.partitions": "8"})


def load_tables(s, rows: int, parts: int = 4):
    """All eight TPC-H tables at lineitem-row scale `rows` (other tables
    scaled by the usual TPC-H ratios)."""
    from spark_rapids_tpu import datagen as dg

    def df(spec, n, p=1):
        return s.createDataFrame(spec.generate(42, n, p), num_partitions=p)

    n_orders = max(rows // 4, 1)
    n_cust = max(rows // 40, 1)
    n_supp = max(rows // 100, 1)
    n_part = max(rows // 20, 1)
    return {
        "lineitem": df(dg.tpch_lineitem(rows), rows, parts),
        "orders": df(dg.tpch_orders(n_orders), n_orders, parts),
        "customer": df(dg.tpch_customer(n_cust), n_cust),
        "supplier": df(dg.tpch_supplier(n_supp), n_supp),
        "part": df(dg.tpch_part(n_part), n_part),
        "partsupp": df(dg.tpch_partsupp(n_part, n_supp), n_part * 4),
        "nation": df(dg.tpch_nation(), dg.N_NATIONS),
        "region": df(dg.tpch_region(), dg.N_REGIONS),
    }


def q1(s, t):
    import spark_rapids_tpu.functions as F
    li = t["lineitem"]
    return (li.filter(F.col("l_shipdate") <= 10471)
            .withColumn("disc_price",
                        F.col("l_extendedprice") * (1 - F.col("l_discount")))
            .withColumn("charge",
                        F.col("l_extendedprice") * (1 - F.col("l_discount"))
                        * (1 + F.col("l_tax")))
            .groupBy("l_returnflag", "l_linestatus")
            .agg(F.sum(F.col("l_quantity")).alias("sum_qty"),
                 F.sum(F.col("l_extendedprice")).alias("sum_base_price"),
                 F.sum(F.col("disc_price")).alias("sum_disc_price"),
                 F.sum(F.col("charge")).alias("sum_charge"),
                 F.avg(F.col("l_quantity")).alias("avg_qty"),
                 F.avg(F.col("l_extendedprice")).alias("avg_price"),
                 F.avg(F.col("l_discount")).alias("avg_disc"),
                 F.count(F.col("l_quantity")).alias("count_order"))
            .sort("l_returnflag", "l_linestatus"))


def q3(s, t):
    import spark_rapids_tpu.functions as F
    li, orders, cust = t["lineitem"], t["orders"], t["customer"]
    return (cust.filter(F.col("c_mktsegment") == "BUILDING")
            .join(orders, on=cust["c_custkey"] == orders["o_custkey"])
            .join(li, on=orders["o_orderkey"] == li["l_orderkey"])
            .withColumn("revenue",
                        F.col("l_extendedprice") * (1 - F.col("l_discount")))
            .groupBy("o_orderkey", "o_orderdate")
            .agg(F.sum(F.col("revenue")).alias("revenue"))
            .sort(F.col("revenue").desc())
            .limit(10))


def q4(s, t):
    """Order-priority checking: semi join on late lineitems."""
    import spark_rapids_tpu.functions as F
    li, orders = t["lineitem"], t["orders"]
    late = li.filter(F.col("l_commitdate") < F.col("l_receiptdate"))
    return (orders.filter((F.col("o_orderdate") >= 8582)
                          & (F.col("o_orderdate") < 8674))
            .join(late, on=orders["o_orderkey"] == late["l_orderkey"],
                  how="leftsemi")
            .groupBy("o_orderpriority")
            .agg(F.count_star().alias("order_count"))
            .sort("o_orderpriority"))


def q5(s, t):
    """Local supplier volume: five-way join down the region axis."""
    import spark_rapids_tpu.functions as F
    li, orders, cust = t["lineitem"], t["orders"], t["customer"]
    supp, nation, region = t["supplier"], t["nation"], t["region"]
    asia = region.filter(F.col("r_name") == "ASIA")
    return (cust
            .join(orders, on=cust["c_custkey"] == orders["o_custkey"])
            .join(li, on=orders["o_orderkey"] == li["l_orderkey"])
            .join(supp, on=(li["l_suppkey"] == supp["s_suppkey"])
                  & (cust["c_nationkey"] == supp["s_nationkey"]))
            .join(nation, on=supp["s_nationkey"] == nation["n_nationkey"])
            .join(asia, on=nation["n_regionkey"] == asia["r_regionkey"])
            .filter((F.col("o_orderdate") >= 8766)
                    & (F.col("o_orderdate") < 9131))
            .withColumn("revenue",
                        F.col("l_extendedprice") * (1 - F.col("l_discount")))
            .groupBy("n_name")
            .agg(F.sum(F.col("revenue")).alias("revenue"))
            .sort(F.col("revenue").desc()))


def q6(s, t):
    import spark_rapids_tpu.functions as F
    li = t["lineitem"]
    return (li.filter((F.col("l_shipdate") >= 8766)
                      & (F.col("l_shipdate") < 9131)
                      & (F.col("l_discount") >= 0.05)
                      & (F.col("l_discount") <= 0.07)
                      & (F.col("l_quantity") < 24))
            .agg(F.sum(F.col("l_extendedprice") * F.col("l_discount"))
                 .alias("revenue")))


def q9(s, t):
    """Product-type profit: part/supplier/partsupp/orders joins + like."""
    import spark_rapids_tpu.functions as F
    li, orders = t["lineitem"], t["orders"]
    supp, nation, part, ps = (t["supplier"], t["nation"], t["part"],
                              t["partsupp"])
    green = part.filter(F.col("p_name").like("%green%"))
    return (li
            .join(green, on=li["l_partkey"] == green["p_partkey"])
            .join(supp, on=li["l_suppkey"] == supp["s_suppkey"])
            .join(ps, on=(li["l_suppkey"] == ps["ps_suppkey"])
                  & (li["l_partkey"] == ps["ps_partkey"]))
            .join(orders, on=li["l_orderkey"] == orders["o_orderkey"])
            .join(nation, on=supp["s_nationkey"] == nation["n_nationkey"])
            .withColumn("amount",
                        F.col("l_extendedprice") * (1 - F.col("l_discount"))
                        - F.col("ps_supplycost") * F.col("l_quantity"))
            .withColumn("o_year",
                        (F.col("o_orderdate").cast("int") / 365).cast("int"))
            .groupBy("n_name", "o_year")
            .agg(F.sum(F.col("amount")).alias("sum_profit"))
            .sort("n_name", F.col("o_year").desc()))


def q10(s, t):
    """Returned-item reporting: revenue lost to returns per customer."""
    import spark_rapids_tpu.functions as F
    li, orders, cust, nation = (t["lineitem"], t["orders"], t["customer"],
                                t["nation"])
    returned = li.filter(F.col("l_returnflag") == "R")
    return (cust
            .join(orders, on=cust["c_custkey"] == orders["o_custkey"])
            .join(returned, on=orders["o_orderkey"] == returned["l_orderkey"])
            .join(nation, on=cust["c_nationkey"] == nation["n_nationkey"])
            .filter((F.col("o_orderdate") >= 8674)
                    & (F.col("o_orderdate") < 8766))
            .withColumn("revenue",
                        F.col("l_extendedprice") * (1 - F.col("l_discount")))
            .groupBy("c_custkey", "c_name", "c_acctbal", "c_phone", "n_name")
            .agg(F.sum(F.col("revenue")).alias("revenue"))
            .sort(F.col("revenue").desc())
            .limit(20))


def q12(s, t):
    """Shipping modes and order priority: conditional aggregation."""
    import spark_rapids_tpu.functions as F
    li, orders = t["lineitem"], t["orders"]
    sel = li.filter(((F.col("l_shipmode") == "MAIL")
                     | (F.col("l_shipmode") == "SHIP"))
                    & (F.col("l_commitdate") < F.col("l_receiptdate"))
                    & (F.col("l_shipdate") < F.col("l_commitdate"))
                    & (F.col("l_receiptdate") >= 8766)
                    & (F.col("l_receiptdate") < 9131))
    high = ((F.col("o_orderpriority") == "1-URGENT")
            | (F.col("o_orderpriority") == "2-HIGH"))
    return (orders.join(sel, on=orders["o_orderkey"] == sel["l_orderkey"])
            .groupBy("l_shipmode")
            .agg(F.sum(F.when(high, 1).otherwise(0)).alias("high_line_count"),
                 F.sum(F.when(~high, 1).otherwise(0)).alias("low_line_count"))
            .sort("l_shipmode"))


def q13(s, t):
    """Customer order-count distribution: left join + two-level agg."""
    import spark_rapids_tpu.functions as F
    orders, cust = t["orders"], t["customer"]
    sel = orders.filter(~F.col("o_orderpriority").like("%NOT%"))
    per_cust = (cust.join(sel, on=cust["c_custkey"] == sel["o_custkey"],
                          how="left")
                .groupBy("c_custkey")
                .agg(F.count(F.col("o_orderkey")).alias("c_count")))
    return (per_cust.groupBy("c_count")
            .agg(F.count_star().alias("custdist"))
            .sort(F.col("custdist").desc(), F.col("c_count").desc()))


def q14(s, t):
    """Promotion effect: conditional revenue ratio."""
    import spark_rapids_tpu.functions as F
    li, part = t["lineitem"], t["part"]
    sel = li.filter((F.col("l_shipdate") >= 9374)
                    & (F.col("l_shipdate") < 9404))
    joined = sel.join(part, on=sel["l_partkey"] == part["p_partkey"])
    rev = F.col("l_extendedprice") * (1 - F.col("l_discount"))
    promo = F.col("p_type").like("PROMO%")
    return joined.agg(
        (F.sum(F.when(promo, rev).otherwise(F.lit(0.0))) * 100.0
         / F.sum(rev)).alias("promo_revenue"))


def q18(s, t):
    """Large-volume customers: grouped having via filter on aggregate."""
    import spark_rapids_tpu.functions as F
    li, orders, cust = t["lineitem"], t["orders"], t["customer"]
    big = (li.groupBy("l_orderkey")
           .agg(F.sum(F.col("l_quantity")).alias("total_qty"))
           .filter(F.col("total_qty") > 150))
    return (orders
            .join(big, on=orders["o_orderkey"] == big["l_orderkey"],
                  how="leftsemi")
            .join(cust, on=orders["o_custkey"] == cust["c_custkey"])
            .join(li, on=orders["o_orderkey"] == li["l_orderkey"])
            .groupBy("c_name", "c_custkey", "o_orderkey", "o_orderdate",
                     "o_totalprice")
            .agg(F.sum(F.col("l_quantity")).alias("sum_qty"))
            .sort(F.col("o_totalprice").desc(), "o_orderdate")
            .limit(100))


def q19(s, t):
    """Discounted revenue: disjunctive bracketed predicates."""
    import spark_rapids_tpu.functions as F
    li, part = t["lineitem"], t["part"]
    j = li.join(part, on=li["l_partkey"] == part["p_partkey"])
    qty, size = F.col("l_quantity"), F.col("p_size")
    common = (((F.col("l_shipmode") == "AIR")
               | (F.col("l_shipmode") == "REG AIR"))
              & (F.col("l_shipinstruct") == "DELIVER IN PERSON"))
    b1 = ((F.col("p_brand") == "Brand#12")
          & F.col("p_container").like("SM%")
          & (qty >= 1) & (qty <= 11) & (size >= 1) & (size <= 5))
    b2 = ((F.col("p_brand") == "Brand#23")
          & F.col("p_container").like("MED%")
          & (qty >= 10) & (qty <= 20) & (size >= 1) & (size <= 10))
    b3 = ((F.col("p_brand") == "Brand#34")
          & F.col("p_container").like("LG%")
          & (qty >= 20) & (qty <= 30) & (size >= 1) & (size <= 15))
    return (j.filter(common & (b1 | b2 | b3))
            .agg(F.sum(F.col("l_extendedprice") * (1 - F.col("l_discount")))
                 .alias("revenue")))


QUERIES = {"q1": q1, "q3": q3, "q4": q4, "q5": q5, "q6": q6, "q9": q9,
           "q10": q10, "q12": q12, "q13": q13, "q14": q14, "q18": q18,
           "q19": q19}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--queries", default=",".join(QUERIES))
    ap.add_argument("--cpu", action="store_true",
                    help="also time the CPU (fallback) plan")
    args = ap.parse_args()

    results = {}
    for mode in (["tpu", "cpu"] if args.cpu else ["tpu"]):
        s = make_session(tpu=(mode == "tpu"))
        tables = load_tables(s, args.rows)
        for name in args.queries.split(","):
            fn = QUERIES[name.strip()]
            df = fn(s, tables)
            t0 = time.perf_counter()
            out = df.to_arrow()
            dt = time.perf_counter() - t0
            results[f"{name}_{mode}_s"] = round(dt, 4)
            results[f"{name}_rows"] = out.num_rows
    print(json.dumps({"metric": "tpch_suite", "rows": args.rows, **results}))


if __name__ == "__main__":
    main()
